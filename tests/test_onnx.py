"""ONNX export/import tests (reference strategy:
tests/python-pytest/onnx/ — round-trip through serialized ModelProto)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
import mxnet_tpu.symbol as sym
from mxnet_tpu.contrib import onnx as onnx_mxnet
from mxnet_tpu.contrib.onnx import _proto as P


def test_proto_codec_roundtrip():
    model = {
        "ir_version": 8,
        "producer_name": "mxnet_tpu",
        "opset_import": [{"domain": "", "version": 13}],
        "graph": {
            "name": "g",
            "node": [{"op_type": "Relu", "input": ["x"], "output": ["y"],
                      "name": "relu0",
                      "attribute": [{"name": "axis", "type": P.A_INT,
                                     "i": -1},
                                    {"name": "perm", "type": P.A_INTS,
                                     "ints": [1, 0]},
                                    {"name": "eps", "type": P.A_FLOAT,
                                     "f": 0.5}]}],
            "initializer": [P.tensor_from_numpy(
                "w", np.arange(6, dtype=np.float32).reshape(2, 3))],
            "input": [{"name": "x", "type": {"tensor_type": {
                "elem_type": P.FLOAT,
                "shape": {"dim": [{"dim_value": 2}, {"dim_value": 3}]}}}}],
            "output": [{"name": "y", "type": {"tensor_type": {
                "elem_type": P.FLOAT, "shape": {"dim": []}}}}],
        },
    }
    blob = P.encode("ModelProto", model)
    back = P.decode("ModelProto", blob)
    assert back["ir_version"] == 8
    assert back["graph"]["node"][0]["op_type"] == "Relu"
    at = {a["name"]: a for a in back["graph"]["node"][0]["attribute"]}
    assert at["axis"]["i"] == -1
    assert at["perm"]["ints"] == [1, 0]
    assert at["eps"]["f"] == pytest.approx(0.5)
    w = P.tensor_to_numpy(back["graph"]["initializer"][0])
    assert np.allclose(w, np.arange(6).reshape(2, 3))
    dims = back["graph"]["input"][0]["type"]["tensor_type"]["shape"]["dim"]
    assert [d["dim_value"] for d in dims] == [2, 3]


def _mlp():
    data = sym.var("data")
    w1, b1 = sym.var("fc1_weight"), sym.var("fc1_bias")
    h = sym.FullyConnected(data, w1, b1, num_hidden=16, name="fc1")
    h = sym.Activation(h, act_type="relu", name="relu1")
    w2, b2 = sym.var("fc2_weight"), sym.var("fc2_bias")
    out = sym.FullyConnected(h, w2, b2, num_hidden=4, name="fc2")
    out = sym.softmax(out, axis=-1, name="prob")
    rng = np.random.RandomState(0)
    params = {"fc1_weight": nd.array(rng.randn(16, 8) * 0.3),
              "fc1_bias": nd.array(rng.randn(16) * 0.1),
              "fc2_weight": nd.array(rng.randn(4, 16) * 0.3),
              "fc2_bias": nd.array(rng.randn(4) * 0.1)}
    return out, params


def test_onnx_roundtrip_mlp(tmp_path):
    out, params = _mlp()
    rng = np.random.RandomState(1)
    x = nd.array(rng.randn(8, 8).astype(np.float32))
    ref = out.eval(data=x, **params)[0].asnumpy()

    path = str(tmp_path / "mlp.onnx")
    onnx_mxnet.export_model(out, params, input_shapes=[(8, 8)],
                            onnx_file_path=path)
    sym2, args2, aux2 = onnx_mxnet.import_model(path)
    assert not aux2
    got = sym2.eval(data=x, **args2)[0].asnumpy()
    assert got.shape == ref.shape
    assert np.allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_onnx_roundtrip_cnn(tmp_path):
    data = sym.var("data")
    w = sym.var("conv_weight")
    b = sym.var("conv_bias")
    h = sym.Convolution(data, w, b, kernel=(3, 3), pad=(1, 1), stride=(1, 1),
                        num_filter=6, name="conv0")
    h = sym.Activation(h, act_type="relu", name="act0")
    h = sym.Pooling(h, kernel=(2, 2), stride=(2, 2), pool_type="max",
                    name="pool0")
    h = sym.Flatten(h, name="flat0")
    wf, bf = sym.var("fc_weight"), sym.var("fc_bias")
    out = sym.FullyConnected(h, wf, bf, num_hidden=3, name="fc0")

    rng = np.random.RandomState(2)
    params = {"conv_weight": nd.array(rng.randn(6, 3, 3, 3) * 0.2),
              "conv_bias": nd.array(rng.randn(6) * 0.1),
              "fc_weight": nd.array(rng.randn(3, 6 * 4 * 4) * 0.1),
              "fc_bias": nd.array(rng.randn(3) * 0.1)}
    x = nd.array(rng.randn(2, 3, 8, 8).astype(np.float32))
    ref = out.eval(data=x, **params)[0].asnumpy()

    path = str(tmp_path / "cnn.onnx")
    onnx_mxnet.export_model(out, params, input_shapes=[(2, 3, 8, 8)],
                            onnx_file_path=path)
    sym2, args2, _ = onnx_mxnet.import_model(path)
    got = sym2.eval(data=x, **args2)[0].asnumpy()
    assert np.allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_onnx_roundtrip_elemwise_scalar(tmp_path):
    data = sym.var("data")
    out = sym.transpose((data * 2.0 + 1.0), axes=(1, 0), name="t0")
    x = nd.array(np.arange(6).reshape(2, 3).astype(np.float32))
    ref = out.eval(data=x)[0].asnumpy()
    path = str(tmp_path / "ew.onnx")
    onnx_mxnet.export_model(out, {}, input_shapes=[(2, 3)],
                            onnx_file_path=path)
    sym2, args2, _ = onnx_mxnet.import_model(path)
    got = sym2.eval(data=x, **args2)[0].asnumpy()
    assert np.allclose(got, ref)


def test_onnx_export_unsupported_op_raises(tmp_path):
    data = sym.var("data")
    out = sym.Correlation(data, data)
    with pytest.raises(mx.MXNetError, match="no converter"):
        onnx_mxnet.export_model(out, {}, input_shapes=[(1, 1, 8, 8)],
                                onnx_file_path=str(tmp_path / "x.onnx"))


def test_onnx_batchnorm_aux_split(tmp_path):
    data = sym.var("data")
    g, b = sym.var("bn_gamma"), sym.var("bn_beta")
    mm, mv = sym.var("bn_moving_mean"), sym.var("bn_moving_var")
    out = sym.BatchNorm(data, g, b, mm, mv, fix_gamma=False,
                        use_global_stats=True, name="bn0")
    rng = np.random.RandomState(3)
    params = {"bn_gamma": nd.array(rng.rand(4) + 0.5),
              "bn_beta": nd.array(rng.randn(4)),
              "bn_moving_mean": nd.array(rng.randn(4) * 0.1),
              "bn_moving_var": nd.array(rng.rand(4) + 0.5)}
    x = nd.array(rng.randn(2, 4, 3, 3).astype(np.float32))
    ref = out.eval(data=x, **params)[0].asnumpy()
    path = str(tmp_path / "bn.onnx")
    onnx_mxnet.export_model(out, params, input_shapes=[(2, 4, 3, 3)],
                            onnx_file_path=path)
    sym2, args2, aux2 = onnx_mxnet.import_model(path)
    assert set(aux2) == {"bn_moving_mean", "bn_moving_var"}
    got = sym2.eval(data=x, **args2, **aux2)[0].asnumpy()
    assert np.allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_onnx_avgpool_count_include_pad_roundtrip(tmp_path):
    data = sym.var("data")
    out = sym.Pooling(data, kernel=(2, 2), stride=(2, 2), pad=(1, 1),
                      pool_type="avg", count_include_pad=False, name="p0")
    rng = np.random.RandomState(0)
    x = nd.array(rng.rand(1, 2, 6, 6).astype(np.float32))
    ref = out.eval(data=x)[0].asnumpy()
    path = str(tmp_path / "ap.onnx")
    onnx_mxnet.export_model(out, {}, input_shapes=[(1, 2, 6, 6)],
                            onnx_file_path=path)
    sym2, args2, _ = onnx_mxnet.import_model(path)
    got = sym2.eval(data=x, **args2)[0].asnumpy()
    assert np.allclose(got, ref, rtol=1e-5), np.abs(got - ref).max()


def test_onnx_batchnorm_fix_gamma_exports_ones(tmp_path):
    data = sym.var("data")
    g, b = sym.var("g"), sym.var("b")
    mm, mv = sym.var("mm"), sym.var("mv")
    out = sym.BatchNorm(data, g, b, mm, mv, use_global_stats=True,
                        name="bn0")                   # fix_gamma default True
    rng = np.random.RandomState(1)
    params = {"g": nd.array(rng.rand(3) + 2.0),       # non-unit gamma
              "b": nd.array(rng.randn(3)),
              "mm": nd.array(rng.randn(3) * 0.1),
              "mv": nd.array(rng.rand(3) + 0.5)}
    x = nd.array(rng.randn(2, 3, 4, 4).astype(np.float32))
    ref = out.eval(data=x, **params)[0].asnumpy()
    path = str(tmp_path / "bnfg.onnx")
    onnx_mxnet.export_model(out, params, input_shapes=[(2, 3, 4, 4)],
                            onnx_file_path=path)
    sym2, args2, aux2 = onnx_mxnet.import_model(path)
    got = sym2.eval(data=x, **args2, **aux2)[0].asnumpy()
    assert np.allclose(got, ref, rtol=1e-4, atol=1e-5), \
        np.abs(got - ref).max()


def test_onnx_pooling_full_convention_raises(tmp_path):
    data = sym.var("data")
    out = sym.Pooling(data, kernel=(3, 3), stride=(2, 2),
                      pooling_convention="full")
    with pytest.raises(mx.MXNetError, match="pooling_convention"):
        onnx_mxnet.export_model(out, {}, input_shapes=[(1, 1, 6, 6)],
                                onnx_file_path=str(tmp_path / "x.onnx"))


def test_onnx_gemm_alpha_beta_import(tmp_path):
    # hand-build a Gemm with non-default scaling, as an external exporter
    # would, and check the importer honors alpha/beta
    rng = np.random.RandomState(2)
    w = rng.randn(4, 8).astype(np.float32)
    c = rng.randn(4).astype(np.float32)
    model = {
        "ir_version": 8, "producer_name": "external",
        "opset_import": [{"domain": "", "version": 13}],
        "graph": {
            "name": "g",
            "node": [{"op_type": "Gemm", "input": ["x", "w", "c"],
                      "output": ["y"], "name": "gemm0",
                      "attribute": [
                          {"name": "alpha", "type": P.A_FLOAT, "f": 0.5},
                          {"name": "beta", "type": P.A_FLOAT, "f": 2.0},
                          {"name": "transB", "type": P.A_INT, "i": 1}]}],
            "initializer": [P.tensor_from_numpy("w", w),
                            P.tensor_from_numpy("c", c)],
            "input": [{"name": "x", "type": {"tensor_type": {
                "elem_type": P.FLOAT,
                "shape": {"dim": [{"dim_value": 2}, {"dim_value": 8}]}}}}],
            "output": [{"name": "y", "type": {"tensor_type": {
                "elem_type": P.FLOAT, "shape": {"dim": []}}}}],
        },
    }
    path = str(tmp_path / "gemm.onnx")
    with open(path, "wb") as f:
        f.write(P.encode("ModelProto", model))
    sym2, args2, _ = onnx_mxnet.import_model(path)
    x = rng.randn(2, 8).astype(np.float32)
    got = sym2.eval(x=nd.array(x), **args2)[0].asnumpy()
    want = 0.5 * (x @ w.T) + 2.0 * c
    assert np.allclose(got, want, rtol=1e-5, atol=1e-6)
