"""Interprocedural mxlint (ISSUE-4): call graph, dataflow summaries,
helper-hop upgrades of jit-retrace/host-sync, and the two new passes
(collective-soundness, resource-leak).

Pure-AST fixtures — no jax import, milliseconds per test (tier-1 budget
discipline, ROADMAP.md).  The acceptance shapes pinned here:

- jit-retrace / host-sync catch a violation routed through >= 1 helper
  hop (same-file, two-hop, and cross-file via import);
- collective-soundness flags a wrong axis name and a non-total
  ppermute perm;
- the repo tree (incl. tools/) stays clean under every pass.
"""
import json
import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.mxlint import Project, lint_paths, lint_sources  # noqa: E402
from tools.mxlint.core import SourceFile                    # noqa: E402
from tools.mxlint.callgraph import CallGraph                # noqa: E402
from tools.mxlint.dataflow import build_summaries           # noqa: E402


def run(src, path="mxnet_tpu/parallel/fixture.py", select=None):
    return lint_sources({path: textwrap.dedent(src)}, select=select)


def run_many(srcs, select=None):
    return lint_sources({p: textwrap.dedent(s) for p, s in srcs.items()},
                        select=select)


def ids(issues):
    return [i.pass_id for i in issues]


def graph_of(srcs):
    files = [SourceFile(p, textwrap.dedent(s))
             for p, s in sorted(srcs.items())]
    return CallGraph(files)


# ------------------------------------------------------------- call graph
def test_callgraph_resolves_nested_module_and_imported():
    g = graph_of({
        "pkg/a.py": """
            def helper(x):
                return x

            def caller(x):
                def inner(y):
                    return y
                inner(x)
                return helper(x)
        """,
        "pkg/b.py": """
            from pkg.a import helper

            def cross(x):
                return helper(x)
        """,
    })
    callees = {s.callee.qname for s in g.calls["pkg.a.caller"]}
    assert callees == {"pkg.a.caller.inner", "pkg.a.helper"}
    assert {s.callee.qname for s in g.calls["pkg.b.cross"]} == \
        {"pkg.a.helper"}


def test_callgraph_method_resolution_via_class_attribute():
    g = graph_of({
        "pkg/m.py": """
            class Worker:
                def run(self, x):
                    return x

            class Pool:
                def __init__(self):
                    self._w = Worker()

                def go(self, x):
                    return self._w.run(x)

                def go_local(self, x):
                    w = Worker()
                    return w.run(x)
        """,
    })
    assert {s.callee.qname for s in g.calls["pkg.m.Pool.go"]} == \
        {"pkg.m.Worker.run"}
    assert "pkg.m.Worker.run" in {
        s.callee.qname for s in g.calls["pkg.m.Pool.go_local"]}


def test_callgraph_arg_map_accounts_for_bound_receiver():
    g = graph_of({
        "pkg/m.py": """
            class C:
                def m(self, a, b):
                    return a

            def f(c, x, y):
                return c.m(x, b=y)
        """,
    })
    # unresolvable receiver type -> no edge; bind explicitly instead
    g2 = graph_of({
        "pkg/m.py": """
            class C:
                def m(self, a, b):
                    return a

            def f(x, y):
                c = C()
                return c.m(x, b=y)
        """,
    })
    (site,) = [s for s in g2.calls["pkg.m.f"]
               if s.callee.qname == "pkg.m.C.m"]
    # param 0 = the bound receiver (c), param 1 = a (positional after
    # self), param 2 = b (keyword)
    assert sorted(site.arg_map) == [0, 1, 2]
    assert site.arg_map[0].id == "c"


def test_callgraph_arg_map_classmethod_via_class_name_is_bound():
    # C.helper(x) on a @classmethod binds cls via the descriptor: x
    # maps to param 1 (a), not the cls slot — an unbound-style shift
    # would silently drop the traced arg from every summary match
    g = graph_of({
        "pkg/m.py": """
            class C:
                @classmethod
                def helper(cls, a):
                    return float(a)

            def f(x):
                return C.helper(x)
        """,
    })
    (site,) = [s for s in g.calls["pkg.m.f"]
               if s.callee.qname == "pkg.m.C.helper"]
    # x lands on param 1 (a); the cls slot maps the receiver expression
    assert 1 in site.arg_map and site.arg_map[1].id == "x"
    # plain self-methods called through the class stay unbound
    g2 = graph_of({
        "pkg/m.py": """
            class C:
                def m(self, a):
                    return a

            def f(obj, x):
                return C.m(obj, x)
        """,
    })
    (site,) = [s for s in g2.calls["pkg.m.f"]
               if s.callee.qname == "pkg.m.C.m"]
    assert sorted(site.arg_map) == [0, 1]


# -------------------------------------------------------------- summaries
def test_summary_fixpoint_on_mutual_recursion():
    g = graph_of({
        "pkg/m.py": """
            def ping(x, n):
                if n:
                    return pong(x, n - 1)
                return x

            def pong(x, n):
                float(x)
                return ping(x, n)
        """,
    })
    s = build_summaries(g)
    # both sides of the cycle agree: param 0 reaches the scalarization
    assert 0 in s["pkg.m.ping"].sync_params
    assert 0 in s["pkg.m.pong"].sync_params
    assert 0 in s["pkg.m.ping"].returns_params


def test_summary_witness_names_the_chain():
    g = graph_of({
        "pkg/m.py": """
            def leaf(v):
                return v.asnumpy()

            def mid(a):
                return leaf(a)

            def top(x):
                return mid(x)
        """,
    })
    s = build_summaries(g)
    w = s["pkg.m.top"].sync_params[0][0].describe()
    assert "mid" in w and "leaf" in w and "asnumpy" in w


def test_summary_static_metadata_does_not_taint():
    g = graph_of({
        "pkg/m.py": """
            def f(x):
                n = x.shape[0]
                m = len(x)
                return int(n) + int(m)
        """,
    })
    s = build_summaries(g)
    assert s["pkg.m.f"].sync_params == {}


# ------------------------------------------- jit-retrace through helpers
def test_jit_retrace_one_helper_hop():
    issues = run("""
        import jax

        def scalarize(v):
            return float(v)

        @jax.jit
        def f(x):
            return x * scalarize(x)
    """, select=["jit-retrace"])
    assert ids(issues) == ["jit-retrace"]
    assert issues[0].line == 9          # the call site inside the jit
    assert "scalarize" in issues[0].message


def test_jit_retrace_two_hops_and_assignment_tracking():
    issues = run("""
        import jax

        def leaf(v):
            return v.asnumpy()

        def mid(a):
            return leaf(a)

        @jax.jit
        def f(x):
            y = x + 1
            return mid(y)
    """, select=["jit-retrace"])
    assert ids(issues) == ["jit-retrace"]
    assert "mid" in issues[0].message and "leaf" in issues[0].message


def test_jit_retrace_cross_file_helper():
    issues = run_many({
        "mxnet_tpu/helpers.py": """
            def to_host(v):
                return v.asnumpy()
        """,
        "mxnet_tpu/model.py": """
            import jax
            from mxnet_tpu.helpers import to_host

            @jax.jit
            def f(x):
                return to_host(x)
        """,
    }, select=["jit-retrace"])
    assert [(i.pass_id, i.path) for i in issues] == \
        [("jit-retrace", "mxnet_tpu/model.py")]


def test_jit_retrace_hybrid_forward_helper_hop():
    issues = run("""
        def peek(v):
            return v.item()

        class Net:
            def hybrid_forward(self, F, x):
                return peek(x)
    """, select=["jit-retrace"])
    assert ids(issues) == ["jit-retrace"]


def test_jit_retrace_helper_on_host_value_is_quiet():
    issues = run("""
        import jax

        def scalarize(v):
            return float(v)

        @jax.jit
        def f(x):
            n = x.shape[0]
            return x * scalarize(n)

        def host(y):
            return scalarize(y)
    """, select=["jit-retrace"])
    assert issues == []


def test_jit_retrace_helper_hop_suppression():
    issues = run("""
        import jax

        def scalarize(v):
            return float(v)

        @jax.jit
        def f(x):
            # mxlint: disable=jit-retrace (static under vmap contract)
            return x * scalarize(x)
    """, select=["jit-retrace"])
    assert issues == []


# --------------------------------------------- host-sync through helpers
def test_host_sync_helper_hop_in_ops():
    issues = run_many({
        "mxnet_tpu/util.py": """
            def fetch(v):
                return v.asnumpy()
        """,
        "mxnet_tpu/ops/nn.py": """
            from mxnet_tpu.util import fetch

            def relu_impl(x):
                return fetch(x)
        """,
    }, select=["host-sync"])
    assert [(i.pass_id, i.path) for i in issues] == \
        [("host-sync", "mxnet_tpu/ops/nn.py")]
    assert "fetch" in issues[0].message
    assert "asnumpy" in issues[0].message


def test_host_sync_helper_hop_in_batcher_dispatch():
    issues = run_many({
        "mxnet_tpu/serving/util.py": """
            import jax

            def drain(arrays):
                jax.block_until_ready(arrays)
                return arrays
        """,
        "mxnet_tpu/serving/batcher.py": """
            from .util import drain

            class MyBatcher:
                def run_batch(self, outs):
                    return drain(outs)
        """,
    }, select=["host-sync"])
    assert [(i.pass_id, i.path) for i in issues] == \
        [("host-sync", "mxnet_tpu/serving/batcher.py")]


def test_host_sync_engine_sync_outputs_is_sanctioned():
    issues = run_many({
        "mxnet_tpu/engine.py": """
            import jax

            def sync_outputs(arrays, site="serving"):
                jax.block_until_ready(arrays)
                return arrays
        """,
        "mxnet_tpu/serving/batcher.py": """
            from mxnet_tpu.engine import sync_outputs

            class MyBatcher:
                def run_batch(self, outs):
                    return sync_outputs(outs, site="batch")
        """,
    }, select=["host-sync"])
    assert issues == []


def test_host_sync_helper_inside_scope_not_double_flagged():
    # the helper lives in ops/ itself: its direct line is the finding,
    # the call site is not repeated
    issues = run("""
        def fetch(v):
            return v.asnumpy()

        def relu_impl(x):
            return fetch(x)
    """, path="mxnet_tpu/ops/nn.py", select=["host-sync"])
    assert len(issues) == 1
    assert issues[0].line == 3          # fetch's own .asnumpy()


def test_host_sync_nested_helper_in_batcher_not_double_flagged():
    # a def nested inside a *Batcher method is itself a checked surface
    # (same scope rule as the direct check): its own .asnumpy() line is
    # the finding, the call into it must not add a second one
    issues = run("""
        class DynamicBatcher:
            def run_batch(self, y):
                def conv(x):
                    return x.asnumpy()
                return conv(y)
    """, path="mxnet_tpu/serving/batcher.py", select=["host-sync"])
    assert len(issues) == 1
    assert issues[0].line == 5          # conv's own .asnumpy()


def test_host_sync_chain_ending_in_checked_surface_not_double_flagged():
    # hot serving site -> plain helper -> ops/ sink: the sink's own line
    # carries the finding; the chained finding at the serving call site
    # must not fire a second one
    issues = run_many({
        "mxnet_tpu/ops/math.py": """
            def fetch(v):
                return v.asnumpy()
        """,
        "mxnet_tpu/util.py": """
            from mxnet_tpu.ops.math import fetch

            def mid(v):
                return fetch(v)
        """,
        "mxnet_tpu/serving/batcher.py": """
            from mxnet_tpu.util import mid

            class MyBatcher:
                def run_batch(self, outs):
                    return mid(outs)
        """,
    }, select=["host-sync"])
    assert [(i.pass_id, i.path) for i in issues] == \
        [("host-sync", "mxnet_tpu/ops/math.py")]


def test_jit_retrace_sink_in_traced_helper_not_double_flagged():
    # jit f -> plain mid -> jit deep with the .asnumpy(): deep's direct
    # finding owns the bug; no chained finding at f's call into mid
    issues = run("""
        import jax

        @jax.jit
        def deep(v):
            return v.asnumpy()

        def mid(v):
            return deep(v)

        @jax.jit
        def f(x):
            return mid(x)
    """, select=["jit-retrace"])
    assert len(issues) == 1
    assert issues[0].line == 6          # deep's own .asnumpy()


def test_host_sync_second_unchecked_sink_not_masked():
    # the helper's FIRST sink lives in ops/ (directly checked there),
    # but its own .asnumpy() is in an unchecked plain module — the hot
    # call site must still report that second sink
    issues = run_many({
        "mxnet_tpu/ops/math.py": """
            def fetch(v):
                return v.asnumpy()
        """,
        "mxnet_tpu/util.py": """
            from mxnet_tpu.ops.math import fetch

            def mid(v):
                fetch(v)
                return v.asnumpy()
        """,
        "mxnet_tpu/serving/batcher.py": """
            from mxnet_tpu.util import mid

            class MyBatcher:
                def run_batch(self, outs):
                    return mid(outs)
        """,
    }, select=["host-sync"])
    paths = sorted(i.path for i in issues)
    assert paths == ["mxnet_tpu/ops/math.py",
                     "mxnet_tpu/serving/batcher.py"]
    chained = [i for i in issues if "batcher" in i.path][0]
    assert "util.py:6" in chained.message    # mid's own .asnumpy()


def test_jit_retrace_second_unchecked_sink_not_masked():
    # helper first routes through a jit-decorated sink (owned there),
    # then does its own float(v) — the jit call site still reports
    issues = run("""
        import jax

        @jax.jit
        def deep(v):
            return v.asnumpy()

        def mid(v):
            deep(v)
            return float(v)

        @jax.jit
        def f(x):
            return mid(x)
    """, select=["jit-retrace"])
    assert len(issues) == 2
    assert issues[0].line == 6          # deep's own .asnumpy()
    assert "float" in issues[1].message # chained finding at f's call


def test_jit_retrace_taint_through_project_class():
    # a traced value stored in a project object and read back through a
    # method must stay tainted: resolving the class cannot make the
    # analysis blinder than an opaque external class would be
    issues = run("""
        import jax

        class Accum:
            def __init__(self, v):
                self._v = v

            def total(self):
                return self._v

        @jax.jit
        def f(x):
            acc = Accum(x)
            return float(acc.total())
    """, select=["jit-retrace"])
    assert ids(issues) == ["jit-retrace"]
    assert "float" in issues[0].message


def test_jit_retrace_clean_helper_return_not_flagged():
    # the helper's summary proves its return does not derive from the
    # traced argument — float() on that result is host math, not a
    # tracer escape
    issues = run("""
        import jax

        def scale_const(x):
            return 2.0

        @jax.jit
        def f(x):
            s = float(scale_const(x))
            return x * s
    """, select=["jit-retrace"])
    assert issues == []


# ---------------------------------------------------- collective-soundness
def test_collective_wrong_axis_name_flagged():
    issues = run("""
        from jax import lax
        from jax.sharding import Mesh
        from jax.experimental.shard_map import shard_map

        def f(x, devices):
            mesh = Mesh(devices, axis_names=("dp", "tp"))

            def body(x):
                return lax.psum(x, "dpp")

            return shard_map(body, mesh=mesh, in_specs=None,
                             out_specs=None)(x)
    """, select=["collective-soundness"])
    assert ids(issues) == ["collective-soundness"]
    assert "'dpp'" in issues[0].message
    assert "['dp', 'tp']" in issues[0].message


def test_collective_axis_default_param_resolved_and_quiet():
    issues = run("""
        from jax import lax
        from jax.sharding import Mesh
        from jax.experimental.shard_map import shard_map

        def f(x, devices, axis_name="tp"):
            mesh = Mesh(devices, axis_names=("dp", "tp"))

            def body(x):
                return lax.psum(x, axis_name)

            return shard_map(body, mesh=mesh, in_specs=None,
                             out_specs=None)(x)
    """, select=["collective-soundness"])
    assert issues == []


def test_collective_axis_local_assignment_const_propagated():
    # the axis variable is a straight-line local string assignment in
    # the body scope — const-prop must resolve it (and flag the typo)
    issues = run("""
        from jax import lax
        from jax.sharding import Mesh
        from jax.experimental.shard_map import shard_map

        def f(x, devices):
            mesh = Mesh(devices, axis_names=("dp", "tp"))

            def body(x):
                axis = "dpp"
                return lax.psum(x, axis)

            return shard_map(body, mesh=mesh, in_specs=None,
                             out_specs=None)(x)
    """, select=["collective-soundness"])
    assert ids(issues) == ["collective-soundness"]
    assert "'dpp'" in issues[0].message


def test_collective_partial_bound_constant_param_is_uniform():
    # shard_map(partial(body, True), ...): the pre-bound literal is the
    # same on every device — branching on it is not divergence
    issues = run("""
        from functools import partial
        from jax import lax
        from jax.sharding import Mesh
        from jax.experimental.shard_map import shard_map

        def body(use_sum, x):
            if use_sum:
                return lax.psum(x, "dp")
            return x

        def run(x, devices):
            mesh = Mesh(devices, axis_names=("dp",))
            return shard_map(partial(body, True), mesh=mesh,
                             in_specs=None, out_specs=None)(x)
    """, select=["collective-soundness"])
    assert issues == []


def test_collective_nested_def_under_tainted_if_not_flagged():
    # defining a function under the if executes no collective
    issues = run("""
        from jax import lax
        from jax.sharding import Mesh
        from jax.experimental.shard_map import shard_map

        def run(x, devices):
            mesh = Mesh(devices, axis_names=("dp",))

            def body(x):
                if x.sum() > 0:
                    def g(v):
                        return lax.psum(v, "dp")
                return x

            return shard_map(body, mesh=mesh, in_specs=None,
                             out_specs=None)(x)
    """, select=["collective-soundness"])
    assert issues == []


def test_collective_module_scope_shard_map_site_checked():
    # `apply = shard_map(body, mesh, ...)` at module level is a common
    # JAX idiom — the body must be checked against that site's mesh
    issues = run("""
        from jax import lax
        from jax.sharding import Mesh
        from jax.experimental.shard_map import shard_map

        def body(x):
            return lax.psum(x, "WRONG_AXIS")

        mesh = Mesh(None, axis_names=("dp",))
        apply = shard_map(body, mesh, in_specs=None, out_specs=None)
    """, select=["collective-soundness"])
    assert ids(issues) == ["collective-soundness"]
    assert "'WRONG_AXIS'" in issues[0].message
    assert "['dp']" in issues[0].message


def test_collective_mesh_param_not_bound_to_sibling_local():
    # the shard_map site's `mesh` is a runtime PARAMETER; the same name
    # assigned in a sibling nested def must not bind — the pass falls
    # back to the project axis universe and "dp" is in it, so: quiet
    issues = run("""
        from jax import lax
        from jax.sharding import Mesh
        from jax.experimental.shard_map import shard_map

        def outer(x, devices, mesh):
            def unrelated():
                mesh = Mesh(devices, axis_names=("tp",))
                return mesh

            def body(x):
                return lax.psum(x, "dp")

            return shard_map(body, mesh, in_specs=None,
                             out_specs=None)(x)

        def elsewhere(devices):
            return Mesh(devices, axis_names=("dp",))
    """, select=["collective-soundness"])
    assert issues == []


def test_collective_cond_keyword_branches_checked():
    # lax.cond with true_fun=/false_fun= keywords is the same deadlock
    # shape as the positional form
    issues = run("""
        from jax import lax
        from jax.sharding import Mesh
        from jax.experimental.shard_map import shard_map

        def do_psum(v):
            return lax.psum(v, "dp")

        def run(x, devices):
            mesh = Mesh(devices, axis_names=("dp",))

            def body(x):
                return lax.cond(x[0] > 0, true_fun=do_psum,
                                false_fun=do_psum, operand=x)

            return shard_map(body, mesh=mesh, in_specs=None,
                             out_specs=None)(x)
    """, select=["collective-soundness"])
    assert ids(issues) == ["collective-soundness"]
    assert "deadlock" in issues[0].message


def test_collective_outside_shard_map_not_checked():
    issues = run("""
        from jax import lax

        def host_helper(x):
            return lax.psum(x, "totally_bogus_axis")
    """, select=["collective-soundness"])
    assert issues == []


def test_ppermute_non_total_literal_and_comprehension():
    lit = run("""
        from jax import lax
        from jax.sharding import Mesh
        from jax.experimental.shard_map import shard_map

        def f(x, devices):
            mesh = Mesh(devices, axis_names=("pp",))

            def body(x):
                return lax.ppermute(x, "pp", [(0, 1), (1, 0), (2, 0)])

            return shard_map(body, mesh=mesh, in_specs=None,
                             out_specs=None)(x)
    """, select=["collective-soundness"])
    assert ids(lit) == ["collective-soundness"]
    assert "total permutation" in lit[0].message
    comp = run("""
        from jax import lax
        from jax.sharding import Mesh
        from jax.experimental.shard_map import shard_map

        def f(x, devices, n):
            mesh = Mesh(devices, axis_names=("pp",))

            def body(x):
                return lax.ppermute(
                    x, "pp", perm=[(i, i + 1) for i in range(n - 1)])

            return shard_map(body, mesh=mesh, in_specs=None,
                             out_specs=None)(x)
    """, select=["collective-soundness"])
    assert ids(comp) == ["collective-soundness"]


def test_ppermute_total_ring_and_literal_are_quiet():
    issues = run("""
        from jax import lax
        from jax.sharding import Mesh
        from jax.experimental.shard_map import shard_map

        def f(x, devices):
            mesh = Mesh(devices, axis_names=("sp",))

            def body(x):
                size = lax.psum(1, "sp")
                ring = [(j, (j + 1) % size) for j in range(size)]
                x = lax.ppermute(x, "sp", ring)
                return lax.ppermute(x, "sp", [(0, 1), (1, 0)])

            return shard_map(body, mesh=mesh, in_specs=None,
                             out_specs=None)(x)
    """, select=["collective-soundness"])
    assert issues == []


def test_collective_under_per_device_if_flagged_through_helper():
    issues = run("""
        from jax import lax
        from jax.sharding import Mesh
        from jax.experimental.shard_map import shard_map

        def reduce_it(v, ax):
            return lax.psum(v, ax)

        def f(x, devices):
            mesh = Mesh(devices, axis_names=("dp",))

            def body(x):
                if x.sum() > 0:
                    return reduce_it(x, "dp")
                return x

            return shard_map(body, mesh=mesh, in_specs=None,
                             out_specs=None)(x)
    """, select=["collective-soundness"])
    assert ids(issues) == ["collective-soundness"]
    assert "per-device" in issues[0].message


def test_collective_under_cond_lambda_flagged_uniform_pred_quiet():
    pos = run("""
        from jax import lax
        from jax.sharding import Mesh
        from jax.experimental.shard_map import shard_map

        def f(x, devices):
            mesh = Mesh(devices, axis_names=("dp",))

            def body(x):
                return lax.cond(x.sum() > 0,
                                lambda v: lax.psum(v, "dp"),
                                lambda v: v, x)

            return shard_map(body, mesh=mesh, in_specs=None,
                             out_specs=None)(x)
    """, select=["collective-soundness"])
    assert ids(pos) == ["collective-soundness"]
    neg = run("""
        from jax import lax
        from jax.sharding import Mesh
        from jax.experimental.shard_map import shard_map

        def f(x, n_steps, devices):
            mesh = Mesh(devices, axis_names=("dp",))

            def body(x):
                # uniform predicate: collective result, not a shard
                total = lax.psum(x.sum(), "dp")
                return lax.cond(total > 0,
                                lambda v: lax.psum(v, "dp"),
                                lambda v: v, x)

            return shard_map(body, mesh=mesh, in_specs=None,
                             out_specs=None)(x)
    """, select=["collective-soundness"])
    assert neg == []


def test_collective_soundness_suppression():
    issues = run("""
        from jax import lax
        from jax.sharding import Mesh
        from jax.experimental.shard_map import shard_map

        def f(x, devices):
            mesh = Mesh(devices, axis_names=("pp",))

            def body(x):
                # mxlint: disable=collective-soundness (fill-drain)
                return lax.ppermute(x, "pp", [(0, 1)])

            return shard_map(body, mesh=mesh, in_specs=None,
                             out_specs=None)(x)
    """, select=["collective-soundness"])
    assert issues == []


# ----------------------------------------------------------- resource-leak
def test_resource_leak_never_closed_and_early_return():
    issues = run("""
        def leak(p):
            f = open(p)
            return f.read()

        def early(p, flag):
            f = open(p)
            if flag:
                return None
            f.close()
    """, path="mxnet_tpu/io/fixture.py", select=["resource-leak"])
    assert ids(issues) == ["resource-leak"] * 2
    assert "never closed" in issues[0].message
    assert "exits first" in issues[1].message


def test_resource_leak_inline_consumption():
    issues = run("""
        import json

        def load(p):
            return json.load(open(p))
    """, path="mxnet_tpu/io/fixture.py", select=["resource-leak"])
    assert ids(issues) == ["resource-leak"]
    assert "inline" in issues[0].message


def test_resource_leak_negatives():
    issues = run("""
        def ok_with(p):
            with open(p) as f:
                return f.read()

        def ok_finally(p):
            f = open(p)
            try:
                return f.read()
            finally:
                f.close()

        def ok_straightline(p):
            f = open(p)
            data = f.read()
            f.close()
            return data

        def ok_transfer(p):
            f = open(p)
            return f

        class Holder:
            def __init__(self, p):
                self._fh = open(p)

            def close(self):
                self._fh.close()
    """, path="mxnet_tpu/io/fixture.py", select=["resource-leak"])
    assert issues == []


def test_resource_leak_tuple_and_walrus_bindings_owned():
    # tuple-bound and walrus-bound handles are named acquires, not
    # inline consumption; properly closed ones stay quiet
    issues = run("""
        def ok_tuple(a, b):
            f1, f2 = open(a), open(b)
            try:
                return f1.read() + f2.read()
            finally:
                f1.close()
                f2.close()

        def ok_walrus(p):
            if (fh := open(p)):
                data = fh.read()
            fh.close()
            return data
    """, path="mxnet_tpu/io/fixture.py", select=["resource-leak"])
    assert issues == []
    # ...and an unclosed tuple-bound handle is still a finding
    leak = run("""
        def bad_tuple(a, b):
            f1, f2 = open(a), open(b)
            f1.close()
            return f2
    """, path="mxnet_tpu/io/fixture.py", select=["resource-leak"])
    assert ids(leak) == []          # f2 escapes via return — exempt
    leak = run("""
        def bad_tuple(a, b):
            f1, f2 = open(a), open(b)
            f1.close()
            return f1.name
    """, path="mxnet_tpu/io/fixture.py", select=["resource-leak"])
    assert ids(leak) == ["resource-leak"]
    assert "'f2'" in leak[0].message


def test_resource_leak_transfer_nested_in_return():
    # return Reader(f) / return [f] hand the handle to a new owner —
    # the documented RecordIO-style factory shape must stay quiet
    issues = run("""
        def factory(p):
            f = open(p)
            return Reader(f)

        def pair(p, q):
            f = open(p)
            g = open(q)
            return [f, g]
    """, path="mxnet_tpu/io/fixture.py", select=["resource-leak"])
    assert issues == []


def test_resource_leak_lock_acquire_without_finally():
    pos = run("""
        def grab(lock):
            lock.acquire()
            do_work()
            lock.release()
    """, path="mxnet_tpu/serving/fixture.py", select=["resource-leak"])
    assert ids(pos) == ["resource-leak"]
    assert "finally" in pos[0].message
    neg = run("""
        def grab(lock):
            lock.acquire()
            try:
                do_work()
            finally:
                lock.release()

        class SanLock:
            def acquire(self, blocking=True):
                self._lock.acquire(blocking)

            def __enter__(self):
                self.acquire()
                return self
    """, path="mxnet_tpu/serving/fixture.py", select=["resource-leak"])
    assert neg == []


def test_resource_leak_suppression():
    issues = run("""
        def leak(p):
            f = open(p)  # mxlint: disable=resource-leak (daemon-owned)
            return f.read()
    """, path="mxnet_tpu/io/fixture.py", select=["resource-leak"])
    assert issues == []


# --------------------------------------------- review-found regressions
def test_two_shard_map_sites_in_one_function_both_checked():
    """Probe-node id reuse must not alias two shard_map bodies (the
    resolve cache only keys real tree nodes)."""
    issues = run("""
        from jax import lax
        from jax.sharding import Mesh
        from jax.experimental.shard_map import shard_map

        def f(x, devices):
            mesh_a = Mesh(devices, axis_names=("dp",))
            mesh_b = Mesh(devices, axis_names=("tp",))

            def body_a(x):
                return lax.psum(x, "zz")

            def body_b(x):
                return lax.psum(x, "dp")

            y = shard_map(body_a, mesh=mesh_a, in_specs=None,
                          out_specs=None)(x)
            return shard_map(body_b, mesh=mesh_b, in_specs=None,
                             out_specs=None)(y)
    """, select=["collective-soundness"])
    msgs = [i.message for i in issues]
    assert len(issues) == 2, msgs
    assert any("'zz'" in m for m in msgs)       # body_a vs mesh_a
    assert any("'dp'" in m for m in msgs)       # body_b vs tp-only mesh


def test_jit_retrace_constructor_arg_mapping():
    """Class(...) calls bind __init__'s implicit self: positional arg 0
    must map to the first real parameter."""
    issues = run("""
        import jax

        class Sink:
            def __init__(self, cfg):
                self.v = cfg.asnumpy()

        @jax.jit
        def f(x):
            return Sink(x)
    """, select=["jit-retrace"])
    assert [(i.pass_id, i.line) for i in issues] == [("jit-retrace", 10)]


def test_collective_under_while_loop_on_per_device_carry():
    issues = run("""
        from jax import lax
        from jax.sharding import Mesh
        from jax.experimental.shard_map import shard_map

        def keep_going(c):
            return c[0] > 0

        def step(c):
            return lax.psum(c, "dp")

        def f(x, devices):
            mesh = Mesh(devices, axis_names=("dp",))

            def body(x):
                return lax.while_loop(keep_going, step, x)

            return shard_map(body, mesh=mesh, in_specs=None,
                             out_specs=None)(x)
    """, select=["collective-soundness"])
    assert ids(issues) == ["collective-soundness"]
    assert "while_loop" in issues[0].message


def test_collective_while_loop_uniform_init_quiet():
    issues = run("""
        from jax import lax
        from jax.sharding import Mesh
        from jax.experimental.shard_map import shard_map

        def keep_going(c):
            return c < 8

        def step(c):
            return lax.psum(c, "dp")

        def f(x, devices):
            mesh = Mesh(devices, axis_names=("dp",))

            def body(x):
                total = lax.psum(x.sum(), "dp")     # uniform carry
                return lax.while_loop(keep_going, step, total)

            return shard_map(body, mesh=mesh, in_specs=None,
                             out_specs=None)(x)
    """, select=["collective-soundness"])
    assert issues == []


def test_jit_retrace_lambda_param_shadows_traced_name():
    issues = run("""
        import jax

        @jax.jit
        def f(x, ks):
            order = sorted(ks, key=lambda x: float(x))
            return x
    """, select=["jit-retrace"])
    # the lambda's own x shadows the traced param: no finding
    assert [i for i in issues if "float" in i.message] == []


def test_comprehension_target_keeps_iter_taint():
    g = graph_of({
        "pkg/m.py": """
            def drain(outs):
                return [o.asnumpy() for o in outs]
        """,
    })
    s = build_summaries(g)
    assert 0 in s["pkg.m.drain"].sync_params


def test_subscript_index_and_enumerate_counter_do_not_taint():
    """The FasterRCNN anchor-generator shape: host tables indexed by a
    loop counter feeding np.array must not be blamed on the traced
    input (indexing a host tuple by a tracer raises regardless)."""
    issues = run("""
        import jax
        import numpy as np

        class Anchors:
            def level(self, lvl, H, W):
                size = self.sizes[lvl]
                return np.array([size * r for r in self.ratios])

        class Net:
            def _flat(self, levels):
                return np.concatenate(
                    [self.anchors.level(i, f.shape[2], f.shape[3])
                     for i, f in enumerate(levels)])

            def hybrid_forward(self, F, x):
                levels = self.features(x)
                return self._flat(levels)
    """, select=["jit-retrace"])
    assert issues == []


def test_kwonly_param_summary_and_mapping():
    issues = run("""
        import jax

        def send(*, arr):
            return arr.asnumpy()

        @jax.jit
        def f(x):
            return send(arr=x)
    """, select=["jit-retrace"])
    assert [(i.pass_id, i.line) for i in issues] == [("jit-retrace", 9)]


def test_shape_based_predicate_is_uniform_not_divergent():
    issues = run("""
        from jax import lax
        from jax.sharding import Mesh
        from jax.experimental.shard_map import shard_map

        def f(x, devices):
            mesh = Mesh(devices, axis_names=("dp",))

            def body(x):
                n = x.shape[0]
                if n > 1:
                    return lax.psum(x, "dp")
                return x

            return shard_map(body, mesh=mesh, in_specs=None,
                             out_specs=None)(x)
    """, select=["collective-soundness"])
    assert issues == []


def test_mixed_collective_expression_keeps_shard_taint():
    """`lax.psum(x, a) + x` still carries the raw shard: divergence
    through it must flag (only an exact collective call is uniform)."""
    issues = run("""
        from jax import lax
        from jax.sharding import Mesh
        from jax.experimental.shard_map import shard_map

        def f(x, devices):
            mesh = Mesh(devices, axis_names=("dp",))

            def body(x):
                y = lax.psum(x, "dp") + x
                flag = y.sum() > 0
                if flag:
                    return lax.psum(x, "dp")
                return y

            return shard_map(body, mesh=mesh, in_specs=None,
                             out_specs=None)(x)
    """, select=["collective-soundness"])
    assert ids(issues) == ["collective-soundness"]


def test_divergence_anchor_not_swallowed_by_inner_suppression():
    """A suppression for a DIFFERENT finding inside the if-body must not
    swallow the divergence finding (anchored to the collective, and the
    suppressed line is another statement)."""
    issues = run("""
        from jax import lax
        from jax.sharding import Mesh
        from jax.experimental.shard_map import shard_map

        def f(x, devices):
            mesh = Mesh(devices, axis_names=("dp",))

            def body(x):
                if x.sum() > 0:
                    # mxlint: disable=collective-soundness (fill-drain)
                    x = lax.ppermute(x, "dp", [(0, 1)])
                    return lax.psum(x, "dp")
                return x

            return shard_map(body, mesh=mesh, in_specs=None,
                             out_specs=None)(x)
    """, select=["collective-soundness"])
    # the perm finding is suppressed; the divergence finding survives
    assert len(issues) >= 1
    assert all("per-device" in i.message for i in issues)


def test_resource_leak_bare_name_with_statement_is_release():
    issues = run("""
        def g(p):
            f = open(p)
            with f:
                return f.read()
    """, path="mxnet_tpu/io/fixture.py", select=["resource-leak"])
    assert issues == []


def test_unbound_method_call_arg_mapping():
    """Batcher.run(b, x) is unbound: arg 0 is the receiver, arg 1 the
    payload — the escape must blame x, not b."""
    issues = run("""
        import jax

        class Batcher:
            def run(self, xs, tag):
                return xs.asnumpy()

        @jax.jit
        def f(b, x, t):
            return Batcher.run(b, x, t)
    """, select=["jit-retrace"])
    assert len(issues) == 1
    assert "'x'" in issues[0].message


def test_relative_import_in_package_init_resolves():
    """A helper re-exported through ``pkg/__init__.py`` (relative
    import) still resolves from a cross-package call site — the helper
    itself is NOT a serving dispatch surface, so the only possible
    finding is the interprocedural one at the ops call site."""
    issues = run_many({
        "mxnet_tpu/serving/convert.py": """
            def collect_outs(outs):
                return [o.asnumpy() for o in outs]
        """,
        "mxnet_tpu/serving/__init__.py": """
            from .convert import collect_outs
        """,
        "mxnet_tpu/ops/impl.py": """
            from mxnet_tpu.serving import collect_outs

            def op_impl(x):
                return collect_outs(x)
        """,
    }, select=["host-sync"])
    assert [(i.pass_id, i.path) for i in issues] == \
        [("host-sync", "mxnet_tpu/ops/impl.py")]


def test_dotted_import_module_call_resolves():
    g = graph_of({
        "pkg/helpers.py": """
            def f(x):
                return x
        """,
        "pkg/user.py": """
            import pkg.helpers

            def g(x):
                return pkg.helpers.f(x)
        """,
    })
    assert {s.callee.qname for s in g.calls["pkg.user.g"]} == \
        {"pkg.helpers.f"}


def test_switch_with_branch_list_divergence():
    issues = run("""
        from jax import lax
        from jax.sharding import Mesh
        from jax.experimental.shard_map import shard_map

        def reduce_it(v):
            return lax.psum(v, "dp")

        def keep(v):
            return v

        def f(x, devices):
            mesh = Mesh(devices, axis_names=("dp",))

            def body(x):
                return lax.switch(lax.axis_index("dp"),
                                  [reduce_it, keep], x)

            return shard_map(body, mesh=mesh, in_specs=None,
                             out_specs=None)(x)
    """, select=["collective-soundness"])
    assert ids(issues) == ["collective-soundness"]
    assert "switch" in issues[0].message


def test_nested_tainted_ifs_report_once():
    issues = run("""
        from jax import lax
        from jax.sharding import Mesh
        from jax.experimental.shard_map import shard_map

        def f(x, devices):
            mesh = Mesh(devices, axis_names=("dp",))

            def body(x):
                if x.sum() > 0:
                    if x.sum() > 1:
                        return lax.psum(x, "dp")
                return x

            return shard_map(body, mesh=mesh, in_specs=None,
                             out_specs=None)(x)
    """, select=["collective-soundness"])
    assert len(issues) == 1


def test_unbound_dotted_method_call_arg_mapping():
    """m.Batcher.run(b, x) through an import alias is unbound too — the
    dotted receiver must not shift the arg map and drop x."""
    issues = run_many({
        "pkg/helper.py": """
            class Batcher:
                def run(self, xs, tag):
                    return xs.asnumpy()
        """,
        "pkg/main.py": """
            import jax
            from pkg import helper as m

            @jax.jit
            def f(b, x, t):
                return m.Batcher.run(b, x, t)
        """,
    }, select=["jit-retrace"])
    assert len(issues) == 1
    assert "'x'" in issues[0].message


def test_param_rebound_to_collective_result_is_uniform():
    """x = lax.psum(x, axis) rebinds the shard param to the uniform
    reduction — branching on it afterwards is not a divergence."""
    issues = run("""
        from jax import lax
        from jax.sharding import Mesh
        from jax.experimental.shard_map import shard_map

        def f(x, y, devices):
            mesh = Mesh(devices, axis_names=("dp",))

            def body(x, y):
                x = lax.psum(x, "dp")
                if x[0] > 0:
                    return lax.psum(y, "dp")
                return y

            return shard_map(body, mesh=mesh, in_specs=None,
                             out_specs=None)(x, y)
    """, select=["collective-soundness"])
    assert issues == []


def test_wash_is_line_bounded_not_retroactive():
    """A straight-line uniform rebind AFTER a divergent `if` must not
    retroactively un-taint the predicate — the `if` read the raw
    shard, and the collective under it is a real deadlock."""
    issues = run("""
        from jax import lax
        from jax.sharding import Mesh
        from jax.experimental.shard_map import shard_map

        def f(x, devices):
            mesh = Mesh(devices, axis_names=("dp",))

            def body(x):
                if x.sum() > 0:
                    k = lax.pmax(x, "dp")
                x = lax.psum(x, "dp")
                return x

            return shard_map(body, mesh=mesh, in_specs=None,
                             out_specs=None)(x)
    """, select=["collective-soundness"])
    assert len(issues) == 1
    assert "per-device" in issues[0].message


def test_axis_index_under_divergence_is_not_a_deadlock():
    """lax.axis_index exchanges nothing — calling it under a per-device
    branch cannot deadlock (its axis name is still validated)."""
    issues = run("""
        from jax import lax
        from jax.sharding import Mesh
        from jax.experimental.shard_map import shard_map

        def f(x, devices):
            mesh = Mesh(devices, axis_names=("dp",))

            def body(x):
                if x.sum() > 0:
                    return x * lax.axis_index("dp")
                return x

            return shard_map(body, mesh=mesh, in_specs=None,
                             out_specs=None)(x)
    """, select=["collective-soundness"])
    assert issues == []


def test_axis_index_wrong_axis_name_still_flagged():
    issues = run("""
        from jax import lax
        from jax.sharding import Mesh
        from jax.experimental.shard_map import shard_map

        def f(x, devices):
            mesh = Mesh(devices, axis_names=("dp",))

            def body(x):
                return x * lax.axis_index("mp")

            return shard_map(body, mesh=mesh, in_specs=None,
                             out_specs=None)(x)
    """, select=["collective-soundness"])
    assert len(issues) == 1
    assert "mp" in issues[0].message


def test_param_and_local_shadowing_block_module_resolution():
    """A name bound as a parameter or local must not resolve to a
    same-named module-level function — calls through it stay opaque."""
    issues = run("""
        import jax
        import numpy as np

        def materialize(v):
            return np.asarray(v)

        @jax.jit
        def f(x, materialize):
            return materialize(x)

        @jax.jit
        def g(x):
            materialize = lambda v: v * 2
            return materialize(x)
    """, select=["jit-retrace"])
    assert issues == []


def test_switch_operands_are_not_branches():
    """lax.switch data operands (args[2:]) must not be scanned as
    branch callables: an operand whose name collides with a collective-
    calling module function is not a divergence."""
    issues = run("""
        from jax import lax
        from jax.sharding import Mesh
        from jax.experimental.shard_map import shard_map

        def helper(v):
            return lax.psum(v, "dp")

        def f(x, devices):
            mesh = Mesh(devices, axis_names=("dp",))

            def body(x):
                return lax.switch(x[0].astype(int),
                                  [lambda o: o, lambda o: -o],
                                  helper)

            return shard_map(body, mesh=mesh, in_specs=None,
                             out_specs=None)(x)
    """, select=["collective-soundness"])
    assert issues == []


def test_external_import_is_opaque_not_unique_name_matched():
    """`from external_lib import convert` binds convert to an external
    module — the call must stay opaque, not resolve to an unrelated
    same-named project function."""
    issues = run_many({
        "mxnet_tpu/utils.py": """
            def convert(y):
                return y.asnumpy()
        """,
        "mxnet_tpu/ops/impl.py": """
            import jax
            from external_lib import convert

            @jax.jit
            def op_impl(x):
                return convert(x)
        """,
    }, select=["jit-retrace", "host-sync"])
    assert issues == []


def test_bare_project_function_named_item_is_not_a_sync():
    """A project helper named `item` called bare is not `.item()` — the
    method-style sinks need a receiver."""
    issues = run_many({
        "mxnet_tpu/util/fmt.py": """
            def item(n):
                return {"name": n}

            def fmt(n):
                return item(n)
        """,
        "mxnet_tpu/ops/impl.py": """
            from mxnet_tpu.util.fmt import fmt

            def op_impl(n):
                return fmt(n)
        """,
    }, select=["host-sync"])
    assert issues == []


def test_constant_arg_param_is_uniform_not_divergent():
    """helper(x, True): a literal config flag is identical on every
    device — branching on it around a collective is not a divergence."""
    issues = run("""
        from jax import lax
        from jax.sharding import Mesh
        from jax.experimental.shard_map import shard_map

        def helper(x, reduce_it):
            if reduce_it:
                return lax.psum(x, "dp")
            return x

        def f(x, devices):
            mesh = Mesh(devices, axis_names=("dp",))

            def body(x):
                return helper(x, True)

            return shard_map(body, mesh=mesh, in_specs=None,
                             out_specs=None)(x)
    """, select=["collective-soundness"])
    assert issues == []


def test_host_uniform_closure_scalar_arg_is_not_divergent():
    """helper(x, n) where n is a host config int in the enclosing
    scope: the predicate `n > 1` is identical on every device."""
    issues = run("""
        from jax import lax
        from jax.sharding import Mesh
        from jax.experimental.shard_map import shard_map

        def helper(x, n):
            if n > 1:
                return lax.psum(x, "dp")
            return x

        def f(x, n_stages, devices):
            mesh = Mesh(devices, axis_names=("dp",))

            def body(x):
                return helper(x, n_stages)

            return shard_map(body, mesh=mesh, in_specs=None,
                             out_specs=None)(x)
    """, select=["collective-soundness"])
    assert issues == []


def test_shard_arg_to_helper_still_divergent():
    """Negative control for the uniform-arg exemption: the shard itself
    forwarded into the helper keeps the divergence finding."""
    issues = run("""
        from jax import lax
        from jax.sharding import Mesh
        from jax.experimental.shard_map import shard_map

        def helper(x, g):
            if g.sum() > 0:
                return lax.psum(x, "dp")
            return x

        def f(x, devices):
            mesh = Mesh(devices, axis_names=("dp",))

            def body(x):
                return helper(x, x)

            return shard_map(body, mesh=mesh, in_specs=None,
                             out_specs=None)(x)
    """, select=["collective-soundness"])
    assert len(issues) == 1
    assert "per-device" in issues[0].message


def test_raise_with_handler_close_is_not_an_early_exit():
    """A raise inside a try whose except handler closes the handle
    reaches the close on that path — no leak."""
    issues = run("""
        def g(p):
            f = open(p)
            try:
                data = f.read()
                if not data:
                    raise ValueError("empty")
            except ValueError:
                f.close()
                raise
            f.close()
            return data
    """, path="mxnet_tpu/io/fixture.py", select=["resource-leak"])
    assert issues == []


def test_resource_leak_ifexp_opener_closed_in_finally_is_quiet():
    issues = run("""
        def g(p, cond):
            f = open(p) if cond else None
            try:
                return f.read() if f else ""
            finally:
                if f:
                    f.close()
    """, path="mxnet_tpu/io/fixture.py", select=["resource-leak"])
    assert issues == []


# ------------------------------------------------------------ repo gates
def test_tools_tree_is_clean():
    """tools/ (the linter itself included) is clean under every pass —
    the mxnet_tpu/ gate lives in test_mxlint.py; together they pin the
    ISSUE-4 acceptance `python -m tools.mxlint mxnet_tpu/ tools/` == 0."""
    issues = lint_paths([os.path.join(REPO, "tools")])
    assert issues == [], "\n".join(str(i) for i in issues)


def test_cli_json_format_and_bad_path():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.mxlint", "--format", "json",
         "tools/mxlint/callgraph.py"],
        cwd=REPO, capture_output=True, text=True)
    assert proc.returncode == 0
    assert "mxlint: clean" not in proc.stdout      # machine-pure output
    # a bad path mixed with a good one is still a hard error
    proc = subprocess.run(
        [sys.executable, "-m", "tools.mxlint", "--format", "json",
         "tools/mxlint/callgraph.py", "definitely_not_here/"],
        cwd=REPO, capture_output=True, text=True)
    assert proc.returncode == 2
    assert "not found" in proc.stderr


def test_cli_json_findings_parse(tmp_path):
    bad = tmp_path / "ops" / "x.py"
    bad.parent.mkdir()
    bad.write_text("import jax\n\n"
                   "def op_impl(x):\n"
                   "    return jax.block_until_ready(x)\n")
    proc = subprocess.run(
        [sys.executable, "-m", "tools.mxlint", "--format", "json",
         str(tmp_path)],
        cwd=REPO, capture_output=True, text=True)
    assert proc.returncode == 1
    objs = [json.loads(line) for line in proc.stdout.splitlines()]
    assert len(objs) == 1
    assert objs[0]["pass"] == "host-sync"
    assert objs[0]["line"] == 4
    assert set(objs[0]) == {"pass", "file", "line", "col", "message"}


def test_shuffling_collective_result_stays_per_device():
    """A ppermute result differs on every device — a predicate derived
    from it must keep the divergence check armed (only psum-family /
    all_gather reductions are axis-uniform and wash the taint)."""
    issues = run("""
        from jax import lax
        from jax.sharding import Mesh
        from jax.experimental.shard_map import shard_map

        def f(x, devices):
            mesh = Mesh(devices, axis_names=("dp",))

            def body(x):
                r = lax.ppermute(
                    x, "dp", perm=[(j, (j + 1) % 4) for j in range(4)])
                if r.sum() > 0:
                    return lax.psum(x, "dp")
                return r

            return shard_map(body, mesh=mesh, in_specs=None,
                             out_specs=None)(x)
    """, select=["collective-soundness"])
    assert ids(issues) == ["collective-soundness"]


def test_function_local_import_does_not_leak_to_module_scope():
    """A `from x import f` inside one function must not shadow the
    module-level import of the same name for every other function in
    the file — and must still resolve inside its own function."""
    g = graph_of({
        "pkg/__init__.py": "",
        "pkg/utils.py": """
            def convert(v):
                return v
        """,
        "pkg/other.py": """
            def convert(v):
                return v.asnumpy()
        """,
        "pkg/m.py": """
            from pkg.utils import convert

            def local_user(v):
                from pkg.other import convert
                return convert(v)

            def module_user(v):
                return convert(v)
        """,
    })
    (site,) = g.calls["pkg.m.local_user"]
    assert site.callee.qname == "pkg.other.convert"
    (site,) = g.calls["pkg.m.module_user"]
    assert site.callee.qname == "pkg.utils.convert"


def test_match_statement_arms_are_analyzed():
    """Sinks inside match-case arms must be visible to the dataflow
    walk (jit-retrace) and to resource-leak's statement scan."""
    issues = run("""
        import jax

        @jax.jit
        def f(x, mode):
            match mode:
                case "a":
                    return x.asnumpy()
                case _:
                    return x
    """, select=["jit-retrace"])
    assert [(i.pass_id, i.line) for i in issues] == [("jit-retrace", 8)]
    issues = run("""
        def g(p, mode):
            match mode:
                case "a":
                    f = open(p)
                    return f.read()
                case _:
                    return None
    """, select=["resource-leak"])
    assert ids(issues) == ["resource-leak"]


def test_bare_project_helper_named_like_collective_not_misreported():
    """A plain project function that happens to be NAMED psum is not a
    lax collective: calling it under a per-device `if` must not yield a
    divergence finding (its summary speaks for what it reaches)."""
    issues = run("""
        from jax import lax
        from jax.sharding import Mesh
        from jax.experimental.shard_map import shard_map

        def psum(a):
            return a + 1

        def f(x, devices):
            mesh = Mesh(devices, axis_names=("dp",))

            def body(x):
                if x[0] > 0:
                    return psum(x)
                return x

            return shard_map(body, mesh=mesh, in_specs=None,
                             out_specs=None)(x)
    """, select=["collective-soundness"])
    assert issues == []


def test_while_loop_keyword_init_val_flagged():
    """`lax.while_loop(cond, step, init_val=x)` with a shard-derived
    init is the same deadlock shape as the positional form."""
    issues = run("""
        from jax import lax
        from jax.sharding import Mesh
        from jax.experimental.shard_map import shard_map

        def f(x, devices):
            mesh = Mesh(devices, axis_names=("dp",))

            def body(x):
                def cond(c):
                    return c.sum() > 0

                def step(c):
                    return lax.psum(c, "dp")

                return lax.while_loop(cond, step, init_val=x)

            return shard_map(body, mesh=mesh, in_specs=None,
                             out_specs=None)(x)
    """, select=["collective-soundness"])
    assert ids(issues) == ["collective-soundness"]


def test_bare_helper_named_psum_does_not_wash_divergence_taint():
    """`x = psum(x, "dp")` calling a bare project helper named psum is
    NOT a uniform reduction — the per-device taint survives and the
    following divergent collective is still flagged."""
    issues = run("""
        from jax import lax
        from jax.sharding import Mesh
        from jax.experimental.shard_map import shard_map

        def psum(a, axis):
            return a + 1

        def f(x, devices):
            mesh = Mesh(devices, axis_names=("dp",))

            def body(x):
                x = psum(x, "dp")
                if x.sum() > 0:
                    return lax.psum(x, "dp")
                return x

            return shard_map(body, mesh=mesh, in_specs=None,
                             out_specs=None)(x)
    """, select=["collective-soundness"])
    assert ids(issues) == ["collective-soundness"]


def test_host_sync_direct_item_flagged_like_helper_routed():
    """Inlining a flagged `.item()` helper must not silence the
    finding: the direct method call is the same untracked sync."""
    direct = run_many({"mxnet_tpu/ops/x.py": """
        def op_impl(arr):
            return arr.item()
    """}, select=["host-sync"])
    assert ids(direct) == ["host-sync"]
    routed = run_many({"mxnet_tpu/ops/x.py": """
        def _get(arr):
            return arr.item()
    """, "mxnet_tpu/serving/batcher.py": """
        from mxnet_tpu.ops.x import _get

        class DynamicBatcher:
            def _next_batch(self, arr):
                return _get(arr)
    """}, select=["host-sync"])
    assert "host-sync" in ids(routed)
