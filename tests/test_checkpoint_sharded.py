"""parallel.checkpoint: sharded async Orbax checkpoints round-trip on the
virtual mesh and resumed training matches uninterrupted training."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import nd, gluon, parallel
from mxnet_tpu.gluon import nn


def _setup(seed):
    mx.random.seed(seed)
    # explicit prefixes: the auto-name counter is process-global, and
    # checkpoint trees are keyed by parameter name
    net = nn.HybridSequential(prefix="ck_net_")
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu", in_units=8,
                         prefix="fc1_"),
                nn.Dense(4, in_units=16, prefix="fc2_"))
    net.initialize(mx.init.Xavier())
    mesh = parallel.make_mesh(dp=2, tp=2, sp=1,
                              devices=jax.devices()[:4])

    def loss_fn(out, y):
        return ((out - y) ** 2).mean()

    rng = np.random.RandomState(seed)
    x = nd.array(rng.randn(8, 8).astype(np.float32))
    y = nd.array(rng.randn(8, 4).astype(np.float32))
    tr = parallel.ShardedTrainer(
        net, loss_fn, mesh, optimizer="adamw",
        optimizer_params={"learning_rate": 1e-2},
        example_inputs=(x,), n_labels=1)
    return tr, x, y


def test_roundtrip_and_resume(tmp_path):
    tr, x, y = _setup(0)
    losses = [float(jax.device_get(tr.step(x, y))) for _ in range(3)]

    with parallel.CheckpointManager(tmp_path / "ckpt",
                                    async_write=False) as mngr:
        mngr.save(3, tr)
    # continue training: the uninterrupted trajectory
    ref = [float(jax.device_get(tr.step(x, y))) for _ in range(3)]

    # fresh trainer restores and must reproduce the same trajectory
    tr2, x2, y2 = _setup(0)
    step = parallel.load_checkpoint(tmp_path / "ckpt", tr2)
    assert step == 3
    got = [float(jax.device_get(tr2.step(x2, y2))) for _ in range(3)]
    np.testing.assert_allclose(got, ref, rtol=1e-5)
    assert losses[0] > got[-1]            # sanity: training progressed


def test_restored_arrays_keep_shardings(tmp_path):
    tr, x, y = _setup(1)
    tr.step(x, y)
    parallel.save_checkpoint(tmp_path / "c2", tr, step=1)
    tr2, _, _ = _setup(1)
    parallel.load_checkpoint(tmp_path / "c2", tr2)
    for name, arr in tr2.params.items():
        expect = tr.params[name].sharding
        assert arr.sharding == expect, name


def test_rolling_retention(tmp_path):
    tr, x, y = _setup(2)
    with parallel.CheckpointManager(tmp_path / "c3", max_to_keep=2,
                                    async_write=False) as mngr:
        for s in (1, 2, 3, 4):
            tr.step(x, y)
            mngr.save(s, tr)
        mngr.wait()
        assert mngr.latest_step() == 4
        assert mngr.all_steps() == [3, 4]


def test_restore_missing_raises(tmp_path):
    tr, _, _ = _setup(3)
    with pytest.raises(mx.MXNetError):
        parallel.load_checkpoint(tmp_path / "nope", tr)
