"""Multi-replica serving: placement, health-checked routing, failover,
and rolling recovery (docs/serving.md §10).

Everything runs on numpy fakes / function entries — ZERO XLA compiles —
with millisecond heartbeats, so the full kill -> detect -> reroute ->
recover -> rejoin ladder is tested at step granularity.  CI re-runs
this file under MXNET_ENGINE_SANITIZE=1 (the router, heartbeat threads,
and request workers cross the set condition from three thread
families).
"""
import threading
import time

import numpy as np
import pytest

from mxnet_tpu import faults, runtime_metrics as rm, serving
from mxnet_tpu.base import MXNetError
from mxnet_tpu.parallel.placement import replica_groups, replica_mesh
from mxnet_tpu.serving.batcher import bucket_set
from mxnet_tpu.serving.decode import DecodeEngine
from mxnet_tpu.serving.replica import (DRAINING, HEALTHY, STOPPED,
                                       UNHEALTHY, ReplicaSet)
from mxnet_tpu.serving.resilience import (CircuitBreaker, Deadline,
                                          ServerOverloadedError)


@pytest.fixture(autouse=True)
def _clean_slate():
    faults.clear()
    rm.reset()
    rm.enable()
    yield
    faults.clear()
    rm.disable()
    rm.reset()


SIG = [{"shape": [None, 2], "dtype": "float32"}]


def _fn(a):
    return a * 2.0 + 1.0


def _cfg(**kw):
    kw.setdefault("replicas", 3)
    kw.setdefault("max_batch_size", 4)
    kw.setdefault("max_latency_us", 1)
    kw.setdefault("retry_backoff_ms", 0)
    kw.setdefault("replica_heartbeat_ms", 10)
    kw.setdefault("replica_heartbeat_window_ms", 80)
    kw.setdefault("circuit_cooldown_ms", 30)
    return serving.ServingConfig(**kw)


def _entry(fn=_fn, name="m"):
    repo = serving.ModelRepository()
    repo.add_function(name, fn, SIG)
    return repo.get(name)


def _rset(fn=_fn, **cfg_kw):
    return ReplicaSet(_entry(fn), _cfg(**cfg_kw))


def _wait_state(rset, rid, state, timeout=10.0):
    deadline = time.monotonic() + timeout
    while rset.replicas()[rid] != state:
        assert time.monotonic() < deadline, \
            (rid, state, rset.debug_state())
        time.sleep(0.005)


X = {n: np.arange(2 * n, dtype=np.float32).reshape(n, 2)
     for n in (1, 2, 3)}


# ------------------------------------------------------------- placement
class TestPlacement:
    def test_disjoint_groups(self):
        devs = [f"d{i}" for i in range(8)]
        groups = replica_groups(4, devices=devs, tp=2)
        assert groups == [("d0", "d1"), ("d2", "d3"), ("d4", "d5"),
                          ("d6", "d7")]
        flat = [d for g in groups for d in g]
        assert len(set(flat)) == len(flat)          # strictly disjoint

    def test_subset_when_devices_exceed_need(self):
        groups = replica_groups(2, devices=list("abcdef"), tp=2)
        assert groups == [("a", "b"), ("c", "d")]

    def test_single_device_oversubscribes_by_default(self):
        groups = replica_groups(3, devices=["cpu0"])
        assert groups == [("cpu0",)] * 3

    def test_multi_device_shortfall_raises_by_default(self):
        with pytest.raises(MXNetError, match="fault isolation"):
            replica_groups(4, devices=["a", "b"])

    def test_explicit_oversubscribe_round_robins(self):
        groups = replica_groups(4, devices=["a", "b"],
                                oversubscribe=True)
        assert groups == [("a",), ("b",), ("a",), ("b",)]

    @pytest.mark.parametrize("bad", [dict(n_replicas=0),
                                     dict(n_replicas=1, tp=0)])
    def test_validation(self, bad):
        with pytest.raises(MXNetError):
            replica_groups(devices=["a"], **bad)

    def test_replica_mesh_axes(self):
        import jax
        mesh = replica_mesh(jax.devices()[:1])
        assert mesh.axis_names == ("dp", "tp")
        assert mesh.shape["dp"] == 1 and mesh.shape["tp"] == 1
        with pytest.raises(MXNetError):
            replica_mesh([])

    def test_replica_mesh_shape_tracks_group_size(self):
        # a tp=4 group yields a (1, 4) device array: dp is always the
        # degenerate leading axis, tp spans the whole group in order
        mesh = replica_mesh(["a", "b", "c", "d"])
        assert mesh.devices.shape == (1, 4)
        assert list(mesh.devices[0]) == ["a", "b", "c", "d"]
        assert mesh.shape["dp"] == 1 and mesh.shape["tp"] == 4

    def test_replica_mesh_custom_axis_name(self):
        mesh = replica_mesh(["a", "b"], axis_name="mp")
        assert mesh.axis_names == ("dp", "mp")
        assert mesh.shape["mp"] == 2
        assert "tp" not in mesh.shape

    def test_replica_meshes_from_groups_are_disjoint(self):
        devs = [f"d{i}" for i in range(8)]
        meshes = [replica_mesh(g)
                  for g in replica_groups(4, devices=devs, tp=2)]
        seen = [d for m in meshes for d in m.devices.ravel()]
        assert len(seen) == len(set(seen))      # no device in two meshes
        assert all(m.axis_names == ("dp", "tp") for m in meshes)


# ------------------------------------------- breaker consecutive fast trip
class TestConsecutiveTrip:
    def test_trips_before_window_fills(self):
        br = CircuitBreaker(20, 0.5, 1000, consecutive=3)
        br.record(True)
        for _ in range(3):
            br.record(False)
        assert br.state == "open"

    def test_success_resets_the_run(self):
        # threshold high enough that the 2/3 windowed error rate never
        # trips — only the consecutive rule is in play here
        br = CircuitBreaker(20, 0.95, 1000, consecutive=3)
        for _ in range(10):
            br.record(False)
            br.record(False)
            br.record(True)             # never 3 in a row
        assert br.state == "closed"

    def test_zero_keeps_windowed_semantics(self):
        br = CircuitBreaker(20, 0.5, 1000, consecutive=0)
        for _ in range(5):
            br.record(False)
        assert br.state == "closed"     # window not full yet

    def test_probe_success_clears_run(self):
        br = CircuitBreaker(20, 0.5, 1, consecutive=2)
        br.record(False)
        br.record(False)
        assert br.state == "open"
        time.sleep(0.005)
        assert br.admit() is True       # the half-open probe
        br.record(True)
        assert br.state == "closed"
        assert br.debug_state()["consec_failures"] == 0


# ------------------------------------------------------- predict replicas
class TestReplicaSetPredict:
    def test_prewarm_gates_routability(self):
        with _rset() as rset:
            assert set(rset.replicas().values()) == {HEALTHY}
            st = rset.stats()
            bound = len(bucket_set(4))
            for rid, info in st["replicas"].items():
                assert info["prewarms"] == 1
                assert rset.replica(rid).batcher.programs() == bound

    def test_outputs_and_load_balance(self):
        with _rset() as rset:
            for i in range(30):
                n = (i % 3) + 1
                (out,) = rset.run_batch([(X[n],)])
                np.testing.assert_array_equal(out[0], _fn(X[n]))
            reqs = [v["requests"]
                    for v in rset.stats()["replicas"].values()]
            assert all(r > 0 for r in reqs), reqs
            assert sum(reqs) == 30

    def test_transient_failure_fails_over_byte_identical(self):
        with _rset() as rset:
            (ref,) = rset.run_batch([(X[2],)])
            with faults.plan("replica.*.execute=fail,times=1"):
                (out,) = rset.run_batch([(X[2],)])
            np.testing.assert_array_equal(out[0], ref[0])
            st = rset.stats()
            assert st["failovers"] == 1
            assert rm.SERVING_REPLICA_FAILOVERS.value(model="m") == 1

    def test_deterministic_failure_raises_without_failover(self):
        def picky(a):
            if np.any(a == 99.0):       # value-poisoned, prewarm-safe
                raise ValueError("poisoned")
            return _fn(a)

        poison = np.full((2, 2), 99.0, np.float32)
        with ReplicaSet(_entry(picky), _cfg()) as rset:
            with pytest.raises(ValueError):
                rset.run_batch([(poison,)])
            assert rset.stats()["failovers"] == 0

    def test_build_wait_deadline_never_counts_against_replica(self):
        """Review fix (ISSUE-15): a deadline that expires waiting on
        another thread's bucket build says nothing about the replica's
        health — with threshold=1 a single recorded failure would mark
        it UNHEALTHY, so the expiry must skip the replica breaker
        (mirroring the model-level breaker's exclusion)."""
        from mxnet_tpu.serving.resilience import DeadlineExceededError
        rset = _rset(replicas=1, replica_failure_threshold=1)
        try:
            entry = rset.entry
            in_build, release = threading.Event(), threading.Event()
            real = entry.make_program

            def blocking_make_program(rows):
                in_build.set()
                assert release.wait(30)
                return real(rows)
            # prewarm already built every bucket: evict so the next
            # dispatch rebuilds through the wedged builder
            rset.replica("r0").batcher.evict(entry)
            entry.make_program = blocking_make_program
            x = np.ones((1, 2), np.float32)
            done = []
            builder = threading.Thread(
                target=lambda: done.append(rset.run_batch([(x,)])))
            builder.start()
            try:
                assert in_build.wait(10)
                with pytest.raises(DeadlineExceededError):
                    rset.run_batch([(x,)], deadline=Deadline.start(0.2))
                # no outcome recorded: the replica stays routable
                assert rset.replicas()["r0"] == HEALTHY
                assert rset.stats()["failovers"] == 0
            finally:
                release.set()
                builder.join(30)
            assert len(done) == 1
            entry.make_program = real
            # and the replica still serves
            np.testing.assert_allclose(
                rset.run_batch([(x,)])[0][0], _fn(x))
            assert rset.replicas()["r0"] == HEALTHY
        finally:
            rset.stop()

    def test_consecutive_failures_trip_then_probe_recovers(self):
        rset = _rset(replica_failure_threshold=2)
        try:
            rep = rset.replica("r0")
            rset._record_outcome(rep, False)
            rset._record_outcome(rep, False)
            assert rset.replicas()["r0"] == UNHEALTHY
            assert rep.unhealthy_reason == "failures"
            # routing avoids it while the breaker cools down
            picked = {rset._select().rid for _ in range(10)}
            assert "r0" not in picked
            # after the cooldown the router offers it the half-open
            # probe FIRST; a success re-heals the state machine
            time.sleep(0.05)
            probe = rset._select()
            assert probe.rid == "r0"
            rset._record_outcome(rep, True)
            assert rset.replicas()["r0"] == HEALTHY
        finally:
            rset.stop()

    def test_all_dark_sheds_typed(self):
        with _rset(replica_failure_threshold=1,
                   circuit_cooldown_ms=60000) as rset:
            for rid in list(rset.replicas()):
                rset._record_outcome(rset.replica(rid), False)
            assert set(rset.replicas().values()) == {UNHEALTHY}
            with pytest.raises(ServerOverloadedError, match="no healthy"):
                rset.run_batch([(X[1],)])
            assert rset.stats()["no_healthy_rejects"] == 1

    def test_expired_deadline_stops_failover(self):
        with _rset() as rset:
            dead = Deadline(time.monotonic() - 1.0, 0.001)
            with faults.plan("replica.*.execute=fail"):
                with pytest.raises(faults.InjectedFault):
                    rset.run_batch([(X[1],)], deadline=dead)
            assert rset.stats()["failovers"] == 0


# ---------------------------------------------------- heartbeats + rejoin
class TestHeartbeats:
    def test_stall_detect_dark_serve_prewarm_rejoin(self):
        with _rset() as rset:
            p0 = rset.replica("r1").prewarms
            with faults.plan("replica.r1.heartbeat=stall,ms=400,times=1"):
                _wait_state(rset, "r1", UNHEALTHY, timeout=5)
                assert rset.replica("r1").unhealthy_reason.startswith(
                    "heartbeat")
                # the dark window serves byte-identically via siblings
                for _ in range(5):
                    (out,) = rset.run_batch([(X[1],)])
                    np.testing.assert_array_equal(out[0], _fn(X[1]))
            # beats resume -> rejoin gated on a FRESH prewarm pass
            _wait_state(rset, "r1", HEALTHY, timeout=10)
            assert rset.replica("r1").prewarms == p0 + 1
            st = rset.stats()
            assert st["rejoins"] >= 1 and st["unhealthy_marks"] >= 1

    def test_detection_needs_no_traffic(self):
        # the sweep rides sibling heartbeats, not requests
        with _rset() as rset:
            with faults.plan("replica.r2.heartbeat=stall,ms=400,times=1"):
                _wait_state(rset, "r2", UNHEALTHY, timeout=5)
            _wait_state(rset, "r2", HEALTHY, timeout=10)

    def test_heartbeat_age_gauge_published(self):
        with _rset() as rset:
            time.sleep(0.05)
            age = rm.SERVING_REPLICA_HEARTBEAT_AGE.value(
                model="m", replica="r0")
            assert age is not None and age < 5.0


# -------------------------------------------------------------- rolling ops
class TestRollingOps:
    def test_add_replica_prewarms_before_routable(self):
        with _rset(replicas=2) as rset:
            rid = rset.add_replica()
            assert rset.replicas()[rid] == HEALTHY
            rep = rset.replica(rid)
            assert rep.prewarms == 1
            assert rep.batcher.programs() == len(bucket_set(4))
            # and it takes traffic
            for _ in range(12):
                rset.run_batch([(X[1],)])
            assert rset.replica(rid).requests > 0

    def test_remove_replica_drains(self):
        gate = threading.Event()
        entered = threading.Event()

        def gated(a):
            entered.set()
            assert gate.wait(30)
            return _fn(a)

        gate.set()                          # prewarm passes through
        with ReplicaSet(_entry(gated), _cfg(replicas=2)) as rset:
            gate.clear()
            entered.clear()
            done = []
            t = threading.Thread(
                target=lambda: done.append(
                    rset.run_batch([(X[1],)])))
            t.start()
            assert entered.wait(30)
            victim = next(rid for rid, rep in rset._replicas.items()
                          if rep.inflight > 0)
            remover = threading.Thread(
                target=rset.remove_replica, args=(victim,),
                kwargs=dict(timeout=30))
            remover.start()
            deadline = time.monotonic() + 5
            while rset.replicas().get(victim) != DRAINING:
                assert time.monotonic() < deadline
                time.sleep(0.005)
            gate.set()                      # in-flight finishes
            remover.join(30)
            t.join(30)
            assert done and victim not in rset.replicas()
            assert rset.stats()["drained"] == 1

    def test_remove_last_replica_refused(self):
        with _rset(replicas=1) as rset:
            with pytest.raises(MXNetError, match="last replica"):
                rset.remove_replica("r0")

    def test_restart_fresh_state_through_prewarm(self):
        with _rset(replicas=2) as rset:
            rep = rset.replica("r0")
            rset._record_outcome(rep, False)
            assert rep.failures == 1
            rset.restart("r0", timeout=10)
            fresh = rset.replica("r0")
            assert fresh is not rep
            assert fresh.failures == 0 and fresh.prewarms == 1
            assert rset.replicas()["r0"] == HEALTHY
            (out,) = rset.run_batch([(X[1],)])
            np.testing.assert_array_equal(out[0], _fn(X[1]))


# --------------------------------------------------------- decode replicas
class FakeLM:
    """Decode-model protocol in plain numpy: next token = (last + 1)
    mod vocab; prefill proposes the prompt's last token."""

    vocab_size = 16
    max_context = 32

    def prefill(self, tokens, length, block_table):
        logits = np.zeros((self.vocab_size,), np.float32)
        logits[int(tokens[0, int(length) - 1]) % self.vocab_size] = 1.0
        return logits

    def decode_step(self, tokens, positions, block_tables):
        logits = np.zeros((tokens.shape[0], self.vocab_size),
                          np.float32)
        logits[np.arange(tokens.shape[0]),
               (tokens + 1) % self.vocab_size] = 1.0
        return logits


def _decode_entry(model_factory=FakeLM, name="lm"):
    repo = serving.ModelRepository()
    repo.add_decoder(name, model_factory(),
                     model_factory=model_factory)
    return repo.get(name)


def _decode_cfg(**kw):
    kw.setdefault("replicas", 2)
    kw.setdefault("decode_page_size", 4)
    kw.setdefault("decode_pool_pages", 17)
    kw.setdefault("decode_max_batch", 4)
    kw.setdefault("decode_max_new_tokens", 8)
    kw.setdefault("retry_backoff_ms", 0)
    kw.setdefault("retry_max", 2)
    kw.setdefault("replica_heartbeat_ms", 10)
    kw.setdefault("replica_heartbeat_window_ms", 80)
    kw.setdefault("circuit_cooldown_ms", 30)
    return serving.ServingConfig(**kw)


class TestReplicaSetDecode:
    def test_generate_parity_and_leak_free(self):
        with ReplicaSet(_decode_entry(), _decode_cfg()) as rset:
            out = rset.generate([3], max_new_tokens=4, timeout=30)
            assert out.tolist() == [3, 4, 5, 6]
            rset.check_leaks()

    def test_kill_mid_generate_quarantines_then_fails_over(self):
        """ISSUE-13 chaos criterion: a replica dying mid-generate()
        quarantines the sequence leak-free and the request is
        re-admitted fresh on a sibling — byte-identical tokens."""
        with ReplicaSet(_decode_entry(), _decode_cfg()) as rset:
            ref = rset.generate([3], max_new_tokens=4, timeout=30)
            # 3 fail firings: the serving replica burns its 2 retries
            # and quarantines; the sibling runs clean
            with faults.plan("replica.*.decode.step=fail,times=3"):
                out = rset.generate([3], max_new_tokens=4, timeout=30)
            assert out.tolist() == ref.tolist()
            st = rset.stats()
            assert st["failovers"] == 1
            quarantined = sum(s["quarantined"]
                              for s in rset.decode_stats().values())
            assert quarantined == 1
            rset.check_leaks()          # quarantine released every page
            used = sum(s["used_pages"]
                       for s in rset.decode_stats().values())
            assert used == 0

    def test_failover_budget_exhausts_typed(self):
        with ReplicaSet(_decode_entry(),
                        _decode_cfg(retry_max=1)) as rset:
            with faults.plan("replica.*.decode.step=fail"):
                with pytest.raises(MXNetError):
                    rset.generate([3], max_new_tokens=4, timeout=30)
            rset.check_leaks()

    def test_non_adapter_model_without_factory_rejected(self):
        repo = serving.ModelRepository()
        repo.add_decoder("lm", FakeLM())            # no factory
        with pytest.raises(MXNetError, match="model_factory"):
            ReplicaSet(repo.get("lm"), _decode_cfg(replicas=2))

    def test_single_replica_set_owns_the_model(self):
        repo = serving.ModelRepository()
        repo.add_decoder("lm", FakeLM())
        with ReplicaSet(repo.get("lm"),
                        _decode_cfg(replicas=1)) as rset:
            out = rset.generate([3], max_new_tokens=2, timeout=30)
            assert out.tolist() == [3, 4]


# ------------------------------------------------- scoped decode fault sites
class TestDecodeFaultScope:
    def _engine(self, scope):
        eng = DecodeEngine(FakeLM(), _decode_cfg(replicas=1),
                           model_name="fake", fault_scope=scope)
        eng._started = True             # manual stepping
        return eng

    def _run(self, eng):
        seq = eng.submit([3], max_new_tokens=2)
        n = 0
        while not seq.event.is_set():
            eng.step()
            n += 1
            assert n < 32
        return seq

    def test_scoped_engine_ignores_plain_decode_sites(self):
        eng = self._engine("replica.r7.decode")
        with faults.plan("decode.step=fail"):
            seq = self._run(eng)
        assert seq.finish_reason == "length"
        assert seq.tokens == [3, 4]

    def test_scoped_engine_honors_its_own_sites(self):
        eng = self._engine("replica.r7.decode")
        with faults.plan("replica.r7.decode.step=fail"):
            seq = self._run(eng)
        assert seq.finish_reason == "quarantined"

    def test_default_scope_unchanged(self):
        eng = self._engine("decode")
        with faults.plan("decode.step=fail"):
            seq = self._run(eng)
        assert seq.finish_reason == "quarantined"


# ------------------------------------------------------ server integration
class TestServerIntegration:
    def _server(self, fn=_fn, **cfg_kw):
        repo = serving.ModelRepository()
        repo.add_function("m", fn, SIG)
        return repo, serving.ModelServer(repo, _cfg(**cfg_kw))

    def test_predict_parity_with_single_replica(self):
        _, single = self._server(replicas=1)
        _, multi = self._server(replicas=3)
        with single, multi:
            for n in (1, 2, 3):
                a = single.predict("m", X[n], timeout=30)
                b = multi.predict("m", X[n], timeout=30)
                np.testing.assert_array_equal(a, b)
            st = multi.stats()
            assert "replica_sets" in st
            assert sum(v["requests"] for v in
                       st["replica_sets"]["m"]["replicas"].values()) \
                == 3

    def test_failover_under_threaded_load(self):
        repo, srv = self._server(replicas=3)
        errors, outs = [], []

        def worker(tid):
            for i in range(8):
                n = (tid + i) % 3 + 1
                try:
                    outs.append(
                        (n, srv.predict("m", X[n], timeout=30)))
                except Exception as e:          # noqa: BLE001
                    errors.append(e)

        with srv:
            with faults.plan("replica.r1.execute=fail,times=6,seed=2"):
                pool = [threading.Thread(target=worker, args=(t,))
                        for t in range(6)]
                for t in pool:
                    t.start()
                for t in pool:
                    t.join(60)
            assert not errors, errors[:3]       # failover absorbed all
            for n, out in outs:
                np.testing.assert_array_equal(out, _fn(X[n]))
            assert len(outs) == 48

    def test_generate_through_server_with_failover(self):
        repo = serving.ModelRepository()
        repo.add_decoder("lm", FakeLM(), model_factory=FakeLM)
        with serving.ModelServer(repo, _decode_cfg()) as srv:
            ref = srv.generate("lm", [3], max_new_tokens=4, timeout=30)
            with faults.plan("replica.*.decode.step=fail,times=3"):
                out = srv.generate("lm", [3], max_new_tokens=4,
                                   timeout=30)
            assert out.tolist() == ref.tolist() == [3, 4, 5, 6]
            stats = srv.decode_stats("lm")
            assert set(stats) == {"r0", "r1"}
            entry = repo.get("lm")
            srv._replica_sets[entry.uid].check_leaks()

    def test_prewarm_builds_all_replicas_before_traffic(self):
        repo, srv = self._server(replicas=2)
        with srv:
            summary = srv.prewarm("m")
            assert set(summary["replicas"].values()) == {HEALTHY}
            rs = summary["stats"]["replicas"]
            assert all(v["prewarms"] == 1 for v in rs.values())
            assert all(v["requests"] == 0 for v in rs.values())

    def test_unload_stops_replica_set(self):
        repo, srv = self._server(replicas=2)
        with srv:
            srv.predict("m", X[1], timeout=30)
            entry = repo.get("m")
            rset = srv._replica_sets[entry.uid]
            repo.unload("m")
            assert entry.uid not in srv._replica_sets
            assert set(rset.replicas().values()) == {STOPPED}

    def test_debug_state_serializable(self):
        import json
        repo, srv = self._server(replicas=2)
        with srv:
            srv.predict("m", X[1], timeout=30)
            state = srv.debug_state()
            assert state["replica_sets"]
            (rset_state,) = state["replica_sets"].values()
            assert set(rset_state["replicas"]) == {"r0", "r1"}
            json.dumps(state)           # flight-recorder contract

    def test_server_stop_stops_replicas(self):
        repo, srv = self._server(replicas=2)
        srv.predict("m", X[1], timeout=30)
        entry = repo.get("m")
        rset = srv._replica_sets[entry.uid]
        assert srv.stop(timeout=30)
        assert set(rset.replicas().values()) == {STOPPED}

    def test_replica_traffic_tagged_in_traces(self):
        from mxnet_tpu import tracing
        tracing.enable(sample=1.0)
        try:
            repo, srv = self._server(replicas=2)
            with srv:
                with faults.plan("replica.*.execute=fail,times=1"):
                    srv.predict("m", X[1], timeout=30)
                fo = srv.stats()["replica_sets"]["m"]["failovers"]
                assert fo == 1
                tagged = [
                    s for tr in tracing.TRACER.traces()
                    for s in tr["spans"]
                    if (s.get("tags") or {}).get("failover_from")]
                assert tagged, "no failover_from trace tag recorded"
                assert all((s["tags"] or {}).get("replica")
                           for s in tagged)
        finally:
            tracing.disable()
            tracing.reset()


# -------------------------------------------- sanitizer-mode router stress
class TestRouterStress:
    def test_threaded_routing_with_chaos_consistent_counters(self):
        """8 client threads x 10 requests against 3 replicas while a
        seeded plan kills one replica's executes AND stalls its
        heartbeat: every request resolves (typed or served), counters
        reconcile, and — under MXNET_ENGINE_SANITIZE=1 in CI — no
        lock-order inversion fires across the router / heartbeat /
        worker lock families."""
        with _rset() as rset:
            errors, served = [], []

            def worker(tid):
                for i in range(10):
                    n = (tid + i) % 3 + 1
                    try:
                        (out,) = rset.run_batch(
                            [(X[n],)],
                            deadline=Deadline.start(30))
                        np.testing.assert_array_equal(
                            out[0], _fn(X[n]))
                        served.append(n)
                    except MXNetError as e:
                        errors.append(e)

            plan = ("replica.r0.execute=fail,times=10,seed=5;"
                    "replica.r0.heartbeat=stall,ms=200,times=1")
            with faults.plan(plan):
                pool = [threading.Thread(target=worker, args=(t,))
                        for t in range(8)]
                for t in pool:
                    t.start()
                for t in pool:
                    t.join(60)
            assert len(served) + len(errors) == 80
            assert not errors, errors[:3]
            st = rset.stats()
            assert sum(v["requests"]
                       for v in st["replicas"].values()) \
                == st["dispatched"]
            assert all(v["inflight"] == 0
                       for v in st["replicas"].values())


# ----------------------------------------- one AOT miss, N warm replicas
class TestReplicaCompileSharing:
    def test_sibling_replicas_deserialize_the_first_miss(
            self, tmp_path, monkeypatch):
        """The §10 compile contract: per-replica program caches go
        through the persistent compile cache, so replica count never
        multiplies cold compiles — replica r0's misses store
        executables that r1 deserializes (disk hits), bucket for
        bucket."""
        import mxnet_tpu as mx
        from mxnet_tpu import compile_cache as cc
        from mxnet_tpu import nd
        from mxnet_tpu.gluon import nn

        monkeypatch.setenv("MXNET_COMPILE_CACHE_DIR",
                           str(tmp_path / "cache"))
        mx.random.seed(7)
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(4, in_units=8))
        net.initialize(mx.init.Xavier())
        net.hybridize()
        x = nd.random.uniform(shape=(1, 8))
        art = net.export_stablehlo(x, path=str(tmp_path / "m"),
                                   dynamic_batch=True)

        repo = serving.ModelRepository()
        repo.load_artifact("m", art)
        cache = cc.get_default()
        h0, m0 = cache.hits, cache.misses
        with ReplicaSet(repo.get("m"),
                        _cfg(replicas=2, max_batch_size=2)) as rset:
            buckets = len(bucket_set(2))
            progs = {rid: info["programs"] for rid, info
                     in rset.debug_state()["replicas"].items()}
            assert progs == {"r0": buckets, "r1": buckets}
            # cold compiles happened ONCE per bucket; the sibling
            # replica loaded executables, not the compiler
            assert cache.misses - m0 == buckets, \
                (cache.misses - m0, buckets)
            assert cache.hits - h0 >= buckets, (cache.hits - h0)
            # and both replicas serve byte-identically
            xb = np.arange(8, dtype=np.float32).reshape(1, 8)
            outs = [rset.run_batch([(xb,)])[0][0] for _ in range(4)]
            for out in outs[1:]:
                np.testing.assert_array_equal(out, outs[0])


# ----------------------------------------------- review-hardening fixes
class TestReviewHardening:
    def test_failed_rejoin_prewarm_retries_after_cooldown(self):
        """Review fix: one transient prewarm failure during a
        heartbeat rejoin must not strand the replica dark forever —
        the beat loop retries the bring-up after the breaker
        cooldown."""
        poison = {"on": False}

        def flaky(a):
            if poison["on"]:
                raise RuntimeError("transient backend outage")
            return _fn(a)

        with ReplicaSet(_entry(flaky),
                        _cfg(replicas=2,
                             circuit_cooldown_ms=30)) as rset:
            with faults.plan(
                    "replica.r0.heartbeat=stall,ms=300,times=1"):
                poison["on"] = True     # the rejoin prewarm will fail
                _wait_state(rset, "r0", UNHEALTHY, timeout=5)
            # beats are back; the first rejoin attempt fails and the
            # reason becomes "prewarm failed: ..."
            deadline = time.monotonic() + 5
            while not (rset.replica("r0").unhealthy_reason or "") \
                    .startswith("prewarm failed"):
                assert time.monotonic() < deadline, \
                    rset.debug_state()["replicas"]["r0"]
                time.sleep(0.005)
            poison["on"] = False        # outage clears
            _wait_state(rset, "r0", HEALTHY, timeout=10)
            assert rset.replica("r0").prewarms >= 1

    def test_initial_prewarm_failure_self_heals(self):
        """Review fix: a replica whose FIRST prewarm fails still gets
        a beat thread, so it recovers on its own once the failure
        clears — no operator restart() required."""
        poison = {"left": 100}

        def flaky(a):
            if poison["left"] > 0:
                poison["left"] -= 1
                raise RuntimeError("cold backend")
            return _fn(a)

        rset = ReplicaSet(_entry(flaky),
                          _cfg(replicas=1, circuit_cooldown_ms=20))
        try:
            assert rset.replicas()["r0"] == UNHEALTHY
            poison["left"] = 0
            _wait_state(rset, "r0", HEALTHY, timeout=10)
            (out,) = rset.run_batch([(X[1],)])
            np.testing.assert_array_equal(out[0], _fn(X[1]))
        finally:
            rset.stop()

    def test_window_zero_keeps_consecutive_fast_trip(self):
        """Review fix: disabling the windowed breaker
        (circuit_window=0) must NOT disable the replica layer's
        consecutive-failure dead-replica detector."""
        br = CircuitBreaker(0, 0.5, 20, consecutive=2)
        br.record(False)
        assert br.record(False) == "open"
        with pytest.raises(ServerOverloadedError):
            br.admit()
        time.sleep(0.03)
        assert br.admit() is True       # half-open probe still works
        br.record(True)
        assert br.state == "closed"
        # and fully-off stays fully-off
        off = CircuitBreaker(0, 0.5, 20, consecutive=0)
        for _ in range(10):
            assert off.record(False) == "closed"
        assert off.admit() is False

    def test_window_zero_replica_set_still_marks_unhealthy(self):
        with _rset(circuit_window=0,
                   replica_failure_threshold=2) as rset:
            rep = rset.replica("r0")
            rset._record_outcome(rep, False)
            rset._record_outcome(rep, False)
            assert rset.replicas()["r0"] == UNHEALTHY
            assert rep.unhealthy_reason == "failures"

    def test_stats_disambiguates_two_live_versions(self):
        repo = serving.ModelRepository()
        repo.add_function("m", _fn, SIG)                 # v1, active
        repo.add_function("m", lambda a: a * 5.0, SIG,
                          version=2, activate=False)     # staged
        with serving.ModelServer(repo, _cfg(replicas=2)) as srv:
            srv.predict("m", X[1], timeout=30)           # builds v1 set
            srv.prewarm("m", version=2)                  # builds v2 set
            keys = set(srv.stats()["replica_sets"])
            assert keys == {"m", "m@v2"}, keys
