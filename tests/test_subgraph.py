"""Subgraph backend registry + optimize_for (Symbol and HybridBlock)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, gluon
from mxnet_tpu.subgraph import (SubgraphProperty, register_backend,
                                list_backends, rewrite_nodes)


def test_inference_pass_strips_dropout():
    data = mx.sym.Variable("data")
    w = mx.sym.Variable("w")
    h = mx.sym.dot(data, w)
    h = mx.sym.Dropout(h, p=0.5)
    out = mx.sym.relu(h)

    opt = out.optimize_for("inference")
    names_before = [n.op.name for n in out._topo() if n.op is not None]
    names_after = [n.op.name for n in opt._topo() if n.op is not None]
    assert "Dropout" in names_before
    assert "Dropout" not in names_after

    x = nd.array(np.random.RandomState(0).randn(4, 3).astype(np.float32))
    wv = nd.array(np.random.RandomState(1).randn(3, 5).astype(np.float32))
    ref = np.maximum(np.dot(x.asnumpy(), wv.asnumpy()), 0)
    got = opt.eval(data=x, w=wv)
    got = got[0] if isinstance(got, (list, tuple)) else got
    np.testing.assert_allclose(got.asnumpy(), ref, rtol=1e-5)


def test_unknown_backend_raises():
    data = mx.sym.Variable("data")
    with pytest.raises(mx.MXNetError):
        (data + 1).optimize_for("no_such_backend")
    assert "inference" in list_backends()


def test_custom_backend_rewrite():
    # swap relu -> sigmoid via a registered property
    @register_backend("swap_relu_test")
    class SwapRelu(SubgraphProperty):
        def apply(self, sym, **kwargs):
            from mxnet_tpu.symbol.symbol import _SymNode
            from mxnet_tpu.ops.registry import get_op

            def node_fn(node, new_inputs):
                if node.op is not None and node.op.name == "relu":
                    return _SymNode(get_op("sigmoid"), new_inputs, {},
                                    node.name + "_sig")
                return None

            return rewrite_nodes(sym, node_fn)

    data = mx.sym.Variable("data")
    out = mx.sym.relu(data)
    opt = out.optimize_for("swap_relu_test")
    x = nd.array(np.array([-1.0, 0.0, 2.0], np.float32))
    got = opt.eval(data=x)
    got = got[0] if isinstance(got, (list, tuple)) else got
    np.testing.assert_allclose(got.asnumpy(),
                               1.0 / (1.0 + np.exp(-x.asnumpy())),
                               rtol=1e-5)


def test_hybrid_block_optimize_for():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8, activation="relu"),
            gluon.nn.Dropout(0.5),
            gluon.nn.Dense(3))
    net.initialize(mx.init.Xavier())
    x = nd.array(np.random.RandomState(0).randn(2, 4).astype(np.float32))
    y_ref = net(x)            # inference mode: Dropout is identity

    blk = net.optimize_for(x, backend="inference")
    y_opt = blk(x)
    np.testing.assert_allclose(y_opt.asnumpy(), y_ref.asnumpy(),
                               rtol=1e-5)

    # the rewritten graph really lost its Dropout node
    names = [n.op.name for n in blk._out_sym._topo() if n.op is not None]
    assert "Dropout" not in names


def test_hybrid_block_optimize_for_multi_input():
    class TwoIn(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.fc = gluon.nn.Dense(4)

        def hybrid_forward(self, F, a, b):
            return self.fc(a) + self.fc(b)

    net = TwoIn()
    net.initialize(mx.init.Xavier())
    a = nd.array(np.random.RandomState(0).randn(2, 3).astype(np.float32))
    b = nd.array(np.random.RandomState(1).randn(2, 3).astype(np.float32))
    ref = net(a, b)
    blk = net.optimize_for(a, b, backend="inference")
    np.testing.assert_allclose(blk(a, b).asnumpy(), ref.asnumpy(),
                               rtol=1e-5)


def test_optimize_for_requires_backend():
    net = gluon.nn.Dense(2)
    net.initialize()
    x = nd.ones((1, 3))
    net(x)
    with pytest.raises(mx.MXNetError):
        net.optimize_for(x)
