"""opperf harness test (reference: benchmark/opperf self-test)."""
import sys
import os

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from benchmark.opperf import run_performance_test


def test_opperf_runs_and_reports():
    rows = run_performance_test(ops={"relu", "dot", "adam_update"},
                                warmup=1, runs=2)
    assert len(rows) == 3
    for row in rows:
        assert "error" not in row, row
        assert row["avg_ms"] > 0
        assert row["compile_ms"] > 0
        assert row["shape"]


def test_opperf_category_filter():
    rows = run_performance_test(categories={"gemm"}, warmup=0, runs=1)
    assert {r["op"] for r in rows} == {"dot", "batch_dot"}
