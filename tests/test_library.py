"""mx.library.load: dynamic custom-op library loading (MXLoadLib
equivalent).  Compiles a real plugin .so with g++ in a session-scoped
fixture, then exercises eager forward, autograd backward, and the
hybridize()/jit path."""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd, gluon

PLUGIN_SRC = r"""
// Test plugin: mxnet_tpu op-library ABI v1.
//   my_scale2(x)  -> 2*x          (with backward: dx = 2*g)
//   my_addsub(a,b)-> a+b          (no backward exported)
#include <cstdint>
#include <cstring>

namespace {
int64_t numel(const int64_t* shape, int ndim) {
  int64_t n = 1;
  for (int i = 0; i < ndim; ++i) n *= shape[i];
  return n;
}
}

extern "C" {
int mxlib_abi_version() { return 1; }
int mxlib_num_ops() { return 2; }
const char* mxlib_op_name(int op) {
  return op == 0 ? "my_scale2" : "my_addsub";
}
int mxlib_op_num_inputs(int op) { return op == 0 ? 1 : 2; }
int mxlib_op_has_backward(int op) { return op == 0 ? 1 : 0; }

int mxlib_op_infer_shape(int op, int n_in, const int64_t* shapes,
                         const int* ndims, int64_t* out_shape) {
  // output takes input 0's shape for both ops
  for (int i = 0; i < ndims[0]; ++i) out_shape[i] = shapes[i];
  return ndims[0];
}

int mxlib_op_forward(int op, int n_in, const float** ins,
                     const int64_t* shapes, const int* ndims,
                     float* out, const int64_t* out_shape, int out_ndim) {
  int64_t n = numel(out_shape, out_ndim);
  if (op == 0) {
    for (int64_t i = 0; i < n; ++i) out[i] = 2.0f * ins[0][i];
  } else {
    for (int64_t i = 0; i < n; ++i) out[i] = ins[0][i] + ins[1][i];
  }
  return 0;
}

int mxlib_op_backward(int op, int n_in, const float* out_grad,
                      const float** ins, const int64_t* shapes,
                      const int* ndims, float** in_grads) {
  if (op != 0) return 1;
  int64_t n = numel(shapes, ndims[0]);
  for (int64_t i = 0; i < n; ++i) in_grads[0][i] = 2.0f * out_grad[i];
  return 0;
}
}  // extern "C"
"""


@pytest.fixture(scope="module")
def plugin_so(tmp_path_factory):
    d = tmp_path_factory.mktemp("oplib")
    src = d / "testplugin.cc"
    so = d / "libtestplugin.so"
    src.write_text(PLUGIN_SRC)
    subprocess.run(["g++", "-O2", "-shared", "-fPIC", str(src),
                    "-o", str(so)], check=True)
    names = mx.library.load(str(so))
    assert sorted(names) == ["my_addsub", "my_scale2"]
    return str(so)


def test_load_missing_file_raises():
    with pytest.raises(mx.MXNetError):
        mx.library.load("/nonexistent/libnope.so")


def test_load_non_plugin_raises(plugin_so):
    # the framework's own native IO lib lacks the plugin ABI
    from mxnet_tpu.lib import nativelib
    path = os.path.join(os.path.dirname(nativelib.__file__),
                        "libmxnet_tpu_native.so")
    if not os.path.exists(path):
        pytest.skip("native lib not built")
    with pytest.raises(mx.MXNetError):
        mx.library.load(path)


def test_collision_keeps_builtin(tmp_path):
    # a plugin op named like a built-in must NOT replace it
    src = tmp_path / "collide.cc"
    so = tmp_path / "libcollide.so"
    src.write_text(PLUGIN_SRC.replace('"my_scale2"', '"dot"')
                   .replace('"my_addsub"', '"my_addsub_c"'))
    subprocess.run(["g++", "-O2", "-shared", "-fPIC", str(src),
                    "-o", str(so)], check=True)
    builtin_dot = nd.dot
    mx.library.load(str(so))
    assert nd.dot is builtin_dot          # untouched
    # still reachable through the Custom dispatcher
    x = nd.array(np.array([1.0, -2.0], np.float32))
    np.testing.assert_allclose(
        nd.Custom(x, op_type="dot").asnumpy(), 2 * x.asnumpy())


def test_eager_forward(plugin_so):
    x = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    y = nd.my_scale2(x)
    np.testing.assert_allclose(y.asnumpy(), 2 * x.asnumpy())
    a = nd.array(np.ones((4,), np.float32))
    b = nd.array(np.full((4,), 3.0, np.float32))
    np.testing.assert_allclose(nd.my_addsub(a, b).asnumpy(),
                               np.full((4,), 4.0, np.float32))


def test_idempotent_reload(plugin_so):
    # loading the same path twice is a no-op returning the same ops
    names = mx.library.load(plugin_so)
    assert sorted(names) == ["my_addsub", "my_scale2"]
    assert plugin_so in mx.library.loaded_libraries()


def test_autograd_backward(plugin_so):
    x = nd.array(np.array([1.0, -2.0, 3.0], np.float32))
    x.attach_grad()
    with autograd.record():
        y = nd.my_scale2(x)
        loss = (y * y).sum()
    loss.backward()
    # d/dx (2x)^2 = 8x
    np.testing.assert_allclose(x.grad.asnumpy(), 8 * x.asnumpy(),
                               rtol=1e-5)


def test_no_backward_exported_raises(plugin_so):
    a = nd.array(np.ones((3,), np.float32))
    b = nd.array(np.ones((3,), np.float32))
    a.attach_grad()
    with autograd.record():
        y = nd.my_addsub(a, b)
    with pytest.raises(mx.MXNetError):
        y.backward()


def test_hybridized_block_uses_plugin(plugin_so):
    class Net(gluon.HybridBlock):
        def hybrid_forward(self, F, x):
            return F.my_scale2(x) + 1.0

    net = Net()
    net.initialize()
    net.hybridize()
    x = nd.array(np.arange(4, dtype=np.float32))
    out1 = net(x)
    out2 = net(x)  # cached-trace replay
    np.testing.assert_allclose(out1.asnumpy(), 2 * x.asnumpy() + 1.0)
    np.testing.assert_allclose(out2.asnumpy(), out1.asnumpy())


def test_symbol_path(plugin_so):
    data = mx.sym.Variable("data")
    y = mx.sym.my_scale2(data)
    out = y.eval(data=nd.array(np.array([1.5, 2.5], np.float32)))
    res = out[0] if isinstance(out, (list, tuple)) else out
    np.testing.assert_allclose(res.asnumpy(), [3.0, 5.0])
