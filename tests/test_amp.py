"""AMP tests (reference strategy: tests/python/.../test_amp.py).

bf16 training must reach the same loss as fp32 within tolerance, the op
namespace patching must route MXU ops to bf16 / sensitive ops to fp32, and
dynamic loss scaling must skip overflowed steps.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd, gluon
from mxnet_tpu.contrib import amp


@pytest.fixture
def amp_on():
    amp.init(target_dtype="bfloat16")
    yield
    amp.amp._deinit()


def _toy(n=256, seed=3):
    rng = np.random.RandomState(seed)
    w = rng.randn(16, 1).astype(np.float32)
    x = rng.randn(n, 16).astype(np.float32)
    y = x @ w + 0.1 * rng.randn(n, 1).astype(np.float32)
    return x, y


def _train_mlp(x, y, use_amp, epochs=60, mp=False):
    mx.random.seed(0)
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(32, activation="relu"))
    net.add(gluon.nn.Dense(1))
    net.initialize(mx.init.Xavier())
    opt_params = {"learning_rate": 0.05}
    if mp:
        opt_params["multi_precision"] = True
    trainer = gluon.Trainer(net.collect_params(), "sgd", opt_params)
    if use_amp:
        amp.init_trainer(trainer)
    loss_fn = gluon.loss.L2Loss()
    xs, ys = nd.array(x), nd.array(y)
    final = None
    for _ in range(epochs):
        with autograd.record():
            loss = loss_fn(net(xs), ys)
        if use_amp:
            with amp.scale_loss(loss, trainer) as scaled:
                scaled.backward()
        else:
            loss.backward()
        trainer.step(x.shape[0])
        final = float(loss.mean().asscalar())
    return final


class TestAmpInit:
    def test_bf16_ops_patched(self, amp_on):
        x = nd.array(np.random.rand(4, 8).astype(np.float32))
        w = nd.array(np.random.rand(3, 8).astype(np.float32))
        b = nd.zeros((3,))
        out = nd.FullyConnected(x, w, b, num_hidden=3)
        assert str(out.dtype) == "bfloat16"      # MXU op ran in bf16
        sm = nd.softmax(out)
        assert str(sm.dtype) == "float32"        # sensitive op forced fp32

    def test_symbolic_path_patched(self, amp_on):
        from mxnet_tpu import sym
        data = sym.var("data")
        out = sym.FullyConnected(data, sym.var("w"), sym.var("b"),
                                 num_hidden=4)
        # the rewrite inserted amp_cast nodes into the graph
        assert "amp_cast" in out.tojson()

    def test_double_init_consistent(self, amp_on):
        amp.init(target_dtype="bfloat16")  # idempotent
        with pytest.raises(mx.MXNetError):
            amp.init(target_dtype="float16")

    def test_widest_cast(self, amp_on):
        a = nd.array(np.ones((2, 2), np.float32)).astype("bfloat16")
        b = nd.array(np.ones((2, 2), np.float32))
        out = nd.broadcast_add(a, b)
        assert str(out.dtype) == "float32"


class TestAmpTraining:
    def test_bf16_matches_fp32_loss(self, amp_on):
        x, y = _toy()
        loss_amp = _train_mlp(x, y, use_amp=True)
        amp.amp._deinit()
        loss_fp32 = _train_mlp(x, y, use_amp=False)
        # converged losses agree within tolerance
        assert abs(loss_amp - loss_fp32) < 0.02, (loss_amp, loss_fp32)
        assert loss_amp < 0.15  # converged well below the init loss (~0.5)

    def test_multi_precision_master_weights(self):
        """bf16 params + multi_precision: fp32 master copy drives updates."""
        mx.random.seed(0)
        net = gluon.nn.Dense(4, in_units=8)
        net.initialize(mx.init.Xavier())
        net.cast("bfloat16")
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1,
                                 "multi_precision": True})
        x = nd.array(np.random.rand(16, 8).astype(np.float32)) \
            .astype("bfloat16")
        with autograd.record():
            loss = net(x).sum()
        loss.backward()
        trainer.step(16)
        w = net.weight.data()
        assert str(w.dtype) == "bfloat16"
        # master weights exist in the updater state as fp32
        updater = trainer._dev_updaters[0]
        state = updater.states[0]
        assert isinstance(state, tuple)
        assert str(state[0].dtype) == "float32"


class TestLossScaler:
    def test_overflow_skips_step_and_halves_scale(self):
        net = gluon.nn.Dense(2, in_units=4)
        net.initialize()
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1})
        amp.init_trainer(trainer)
        scaler = trainer._amp_loss_scaler
        s0 = scaler.loss_scale
        w_before = net.weight.data().asnumpy().copy()
        x = nd.array(np.random.rand(4, 4).astype(np.float32))
        with autograd.record():
            loss = net(x).sum()
        loss.backward()
        # poison the gradient with inf
        g = net.weight.grad()
        g._set_data(g._data.at[0, 0].set(np.inf))
        trainer.step(4)
        np.testing.assert_array_equal(net.weight.data().asnumpy(),
                                      w_before)          # step skipped
        assert scaler.loss_scale == s0 / 2

    def test_scale_grows_after_window(self):
        scaler = amp.LossScaler(init_scale=4.0, scale_window=3)
        for _ in range(3):
            scaler.update_scale(False)
        assert scaler.loss_scale == 8.0

    def test_scale_loss_divides_grads(self):
        net = gluon.nn.Dense(1, in_units=2, use_bias=False)
        net.initialize(mx.init.One())
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.0})
        amp.init_trainer(trainer)
        scale = trainer._amp_loss_scaler.loss_scale
        x = nd.array(np.ones((1, 2), np.float32))
        with autograd.record():
            loss = net(x).sum()
        with amp.scale_loss(loss, trainer) as scaled:
            scaled.backward()
        raw = net.weight.grad().asnumpy()
        np.testing.assert_allclose(raw, scale * np.ones((1, 2)))
        amp.unscale(trainer)
        np.testing.assert_allclose(net.weight.grad().asnumpy(),
                                   np.ones((1, 2)))
