"""Request tracing + flight recorder (mxnet_tpu.tracing — ISSUE-8).

Covers: tracer core (ids/links/tags/no-op path/sampling/ring), span
concurrency across the batcher worker pool and the decode-engine step
loop (also under MXNET_ENGINE_SANITIZE), histogram exemplars + the
label-cardinality guard, the traced serving round trip (predict +
generate span chains, exemplar link, zero-new-programs criterion), and
the flight recorder (debug_state, incident dumps, exporters).

All serving models here are numpy fakes or tiny jit programs — the
suite must stay cheap under the tier-1 budget.
"""
import json
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import engine, runtime_metrics as rm, serving
from mxnet_tpu import tracing as tr
from mxnet_tpu.base import MXNetError
from mxnet_tpu.serving.decode import DecodeEngine


@pytest.fixture(autouse=True)
def _tracing_on():
    """Enable + zero the tracer per test, restore the off default."""
    tr.reset()
    tr.enable(sample=1.0)
    yield
    tr.disable()
    tr.reset()
    tr.TRACER.set_sample(1.0)


@pytest.fixture
def metrics():
    rm.reset()
    rm.enable()
    yield rm
    rm.disable()
    rm.reset()


def _span_index(trace):
    return {s["name"]: s for s in trace["spans"]}


def _assert_links(trace):
    """Every span belongs to the trace and parents resolve inside it
    (the root's parent is None)."""
    ids = {s["span_id"] for s in trace["spans"]}
    for s in trace["spans"]:
        assert s["trace_id"] == trace["trace_id"], s
        assert s["parent_id"] is None or s["parent_id"] in ids, s


class FakeLM:
    """Decode-model protocol in pure numpy (zero compiles): prefill
    emits one-hot of (length % vocab), decode emits (token+1) % vocab."""

    vocab_size = 8
    max_context = 16

    def prefill(self, tokens, length, block_table):
        return np.eye(self.vocab_size,
                      dtype=np.float32)[int(length) % self.vocab_size]

    def decode_step(self, tokens, positions, block_tables):
        out = np.zeros((tokens.shape[0], self.vocab_size), np.float32)
        out[np.arange(tokens.shape[0]),
            (tokens + 1) % self.vocab_size] = 1.0
        return out


def _decode_cfg(**kw):
    base = dict(decode_page_size=4, decode_pool_pages=16,
                decode_max_batch=2, decode_max_new_tokens=4)
    base.update(kw)
    return serving.ServingConfig(**base)


# ---------------------------------------------------------------------------
# tracer core
# ---------------------------------------------------------------------------
class TestTracerCore:
    def test_root_child_links_and_tags(self):
        root = tr.trace("req", model="m")
        assert root.sampled
        with root:
            with tr.span("child", rows=3) as c:
                c.set_tag("extra", "x")
                # thread-local nesting: grandchild parents to child
                with tr.span("grandchild"):
                    pass
        t = tr.TRACER.last(root="req")
        assert t is not None
        _assert_links(t)
        idx = _span_index(t)
        assert idx["req"]["parent_id"] is None
        assert idx["child"]["parent_id"] == idx["req"]["span_id"]
        assert idx["grandchild"]["parent_id"] == idx["child"]["span_id"]
        assert idx["child"]["tags"] == {"rows": 3, "extra": "x"}
        assert t["duration"] >= 0

    def test_disabled_path_is_noop(self):
        """Mirror of the metrics-disabled test: with the switch off,
        every entry point returns the shared no-op singleton and
        records nothing."""
        tr.disable()
        assert tr.trace("x") is tr._NOOP
        assert tr.span("x") is tr._NOOP
        assert tr.record_span("x", None, 0.0, 1.0) is None
        assert tr.current_span() is None
        assert tr.current_context() is None
        tr.tag("k", "v")                       # no current span: no-op
        with tr.trace("x") as s:
            assert s is tr._NOOP
            s.set_tag("a", 1)
            s.end()
        st = tr.TRACER.stats()
        assert st["traces_started"] == 0
        assert st["spans"] == 0
        assert not st["enabled"]

    def test_noop_overhead_is_flat(self):
        """The off path must not allocate per call — same object every
        time, and a tight loop stays in the same cost class as the
        metrics-disabled path (no growth assertions on wall time; CI
        machines throttle)."""
        tr.disable()
        spans = {id(tr.span("x")) for _ in range(1000)}
        assert spans == {id(tr._NOOP)}

    def test_span_without_parent_is_noop(self):
        """span() never roots a trace — only trace() does, so helper
        code deep in the stack cannot create orphan traces."""
        assert tr.span("orphan") is tr._NOOP
        assert tr.TRACER.stats()["traces_started"] == 0

    def test_cross_thread_start_end(self):
        root = tr.trace("req")
        ctx = root.context
        q = tr.span("queue_wait", parent=ctx)

        def worker():
            e = tr.span("execute", parent=ctx)
            q.end(slot=0)
            e.end()

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        root.end()
        trace = tr.TRACER.last(root="req")
        idx = _span_index(trace)
        assert set(idx) == {"req", "queue_wait", "execute"}
        assert idx["queue_wait"]["tags"] == {"slot": 0}
        # the span remembers the thread it was STARTED on
        assert idx["queue_wait"]["thread"] != idx["execute"]["thread"]
        _assert_links(trace)

    def test_end_is_idempotent(self):
        root = tr.trace("req")
        c = tr.span("c", parent=root.context)
        c.end()
        t1 = c.t1
        c.end(late="tag")
        assert c.t1 == t1
        root.end()
        spans = tr.TRACER.last(root="req")["spans"]
        assert [s["name"] for s in spans].count("c") == 1
        # the second end() returned before tagging: "late" never lands
        c_dict = [s for s in spans if s["name"] == "c"][0]
        assert "late" not in c_dict["tags"]

    def test_late_span_after_completion_dropped(self):
        root = tr.trace("req")
        ctx = root.context
        root.end()                              # trace completes
        tr.span("straggler", parent=ctx).end()
        t = tr.TRACER.last(root="req")
        assert [s["name"] for s in t["spans"]] == ["req"]
        assert tr.TRACER.stats()["spans_dropped"] == 1

    def test_record_span_explicit_interval(self):
        root = tr.trace("req")
        tr.record_span("step", root.context, 10.0, 10.5,
                       {"step": 1})
        root.end()
        idx = _span_index(tr.TRACER.last(root="req"))
        assert idx["step"]["t0"] == 10.0 and idx["step"]["t1"] == 10.5

    def test_error_tag_on_exception(self):
        with pytest.raises(ValueError):
            with tr.trace("req"):
                with tr.span("inner"):
                    raise ValueError("boom")
        idx = _span_index(tr.TRACER.last(root="req"))
        assert idx["inner"]["tags"]["error"] == "ValueError"
        assert idx["req"]["tags"]["error"] == "ValueError"

    def test_sampling_stride_deterministic(self):
        tr.TRACER.set_sample(0.25)
        kept = [tr.trace("t").sampled for _ in range(16)]
        assert sum(kept) == 4
        st = tr.TRACER.stats()
        assert st["traces_unsampled"] == 12
        # unsampled roots are the no-op span: no context to propagate
        tr.TRACER.set_sample(0.0)
        s = tr.trace("never")
        assert s is tr._NOOP and s.context is None

    def test_sample_rate_validated(self):
        with pytest.raises(MXNetError):
            tr.TRACER.set_sample(1.5)

    def test_ring_eviction_order(self):
        t2 = tr.Tracer(ring=3, sample=1.0)
        for i in range(5):
            t2.start_trace(f"r{i}").end()
        assert [x["root"] for x in t2.traces()] == ["r2", "r3", "r4"]
        st = t2.stats()
        assert st["traces_evicted"] == 2
        assert st["traces_completed"] == 5
        assert t2.find("nope") is None

    def test_span_cap_per_trace(self, monkeypatch):
        monkeypatch.setattr(tr, "_MAX_SPANS_PER_TRACE", 4)
        root = tr.trace("req")
        for i in range(10):
            tr.span(f"s{i}", parent=root.context).end()
        root.end()
        t = tr.TRACER.last(root="req")
        # 4 kept (incl. root's own slot usage: 4 children, root dropped
        # past the cap but still completes the trace)
        assert len(t["spans"]) == 4
        assert t["dropped_spans"] == 7

    def test_active_trace_bound(self, monkeypatch):
        monkeypatch.setattr(tr, "_MAX_ACTIVE_TRACES", 3)
        roots = [tr.trace(f"r{i}") for i in range(5)]
        st = tr.TRACER.stats()
        assert st["active"] == 3
        assert st["traces_aborted"] == 2
        # the aborted (oldest) roots end into the void, not a crash
        for r in roots:
            r.end()
        assert tr.TRACER.stats()["completed"] == 3

    def test_concurrent_span_stress(self):
        """Many threads opening/closing spans on a shared trace: every
        finished span lands exactly once, counters stay consistent."""
        root = tr.trace("req")
        ctx = root.context
        n_threads, n_spans = 8, 50

        def worker(k):
            for i in range(n_spans):
                s = tr.span(f"w{k}.{i}", parent=ctx)
                s.end()

        threads = [threading.Thread(target=worker, args=(k,))
                   for k in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        root.end()
        t = tr.TRACER.last(root="req")
        assert len(t["spans"]) == n_threads * n_spans + 1
        _assert_links(t)


class TestExporters:
    def _one_trace(self):
        root = tr.trace("req", model="m")
        with root:
            with tr.span("child", rows=2):
                pass
        return tr.TRACER.last(root="req")

    def test_chrome_trace_valid(self, tmp_path):
        t = self._one_trace()
        ct = tr.to_chrome_trace(t)
        json.dumps(ct)                          # serializable
        events = [e for e in ct["traceEvents"] if e.get("ph") == "X"]
        assert len(events) == 2
        for e in events:
            assert e["dur"] >= 0 and "trace_id" in e["args"]
        path = tr.dump_chrome_trace(str(tmp_path / "t.json"), t)
        assert json.load(open(path))["traceEvents"]

    def test_jsonl(self, tmp_path):
        t = self._one_trace()
        text = tr.dump_jsonl(str(tmp_path / "t.jsonl"), t)
        lines = [json.loads(l) for l in text.splitlines()]
        assert {l["name"] for l in lines} == {"req", "child"}
        assert all(l["root"] == "req" for l in lines)
        assert open(str(tmp_path / "t.jsonl")).read() == text


# ---------------------------------------------------------------------------
# exemplars + cardinality guard (runtime_metrics)
# ---------------------------------------------------------------------------
class TestExemplars:
    def test_exemplar_per_bucket_latest_wins(self, metrics):
        h = rm.histogram("t.tr.ex", labelnames=("m",),
                         buckets=(0.1, 1.0))
        h.observe(0.05, exemplar="a", m="x")
        h.observe(0.06, exemplar="b", m="x")   # same bucket: b wins
        h.observe(0.5, exemplar="c", m="x")
        h.observe(5.0, exemplar="d", m="x")
        ex = h.exemplars(m="x")
        assert ex[0] == ("b", 0.06)
        assert ex[1] == ("c", 0.5)
        assert ex[2] == ("d", 5.0)

    def test_exemplar_for_quantile_nearest(self, metrics):
        h = rm.histogram("t.tr.q", buckets=(0.1, 1.0))
        for _ in range(99):
            h.observe(0.05, exemplar="fast")
        h.observe(5.0, exemplar="slow")
        assert h.exemplar_for_quantile(0.99, ) in ("fast", "slow")
        assert h.exemplar_for_quantile(1.0) == "slow"
        assert h.exemplar_for_quantile(0.5) == "fast"
        # no data -> None; exemplar-less observations -> nearest search
        h2 = rm.histogram("t.tr.q2", buckets=(0.1,))
        assert h2.exemplar_for_quantile(0.99) is None
        h2.observe(0.05)
        assert h2.exemplar_for_quantile(0.99) is None
        with pytest.raises(MXNetError):
            h.exemplar_for_quantile(1.5)

    def test_exemplar_disabled_noop(self, metrics):
        rm.disable()
        h = rm.histogram("t.tr.exoff", buckets=(1.0,))
        h.observe(0.5, exemplar="a")
        assert h.count() == 0
        assert h.exemplar_for_quantile(0.99) is None

    def test_prometheus_renders_exemplar(self, metrics):
        h = rm.histogram("t.tr.prom", buckets=(1.0,))
        h.observe(0.5, exemplar="tid123")
        txt = rm.dump_prometheus()
        line = [l for l in txt.splitlines()
                if l.startswith("t_tr_prom_bucket")][0]
        assert '# {trace_id="tid123"} 0.5' in line


class TestCardinalityGuard:
    def test_counter_clamps_and_warns_once(self, metrics, caplog):
        c = rm.counter("t.tr.card", labelnames=("who",))
        c.max_label_sets = 4
        import logging
        with caplog.at_level(logging.WARNING, logger="mxnet_tpu"):
            for i in range(12):
                c.inc(who=f"u{i}")
        warns = [r for r in caplog.records
                 if "t.tr.card" in r.getMessage()]
        assert len(warns) == 1                  # warn once
        snap = c._snapshot()
        assert len(snap) == 5                   # bound + overflow
        assert snap[(rm._OVERFLOW_LABEL,)] == 8
        assert c.total() == 12                  # aggregate intact

    def test_existing_series_keep_updating_past_bound(self, metrics):
        c = rm.counter("t.tr.card2", labelnames=("who",))
        c.max_label_sets = 2
        c.inc(who="a")
        c.inc(who="b")
        c.inc(who="c")                          # clamped
        c.inc(who="a")                          # existing: not clamped
        assert c.value(who="a") == 2
        assert c.value(who="c") == 0            # folded into overflow

    def test_gauge_and_histogram_guard(self, metrics):
        g = rm.gauge("t.tr.cardg", labelnames=("w",))
        g.max_label_sets = 2
        for i in range(5):
            g.set(i, w=f"u{i}")
            g.set_max(i, w=f"u{i}")
            g.inc(w=f"u{i}")
        assert len(g._snapshot()) == 3
        h = rm.histogram("t.tr.cardh", labelnames=("w",),
                         buckets=(1.0,))
        h.max_label_sets = 2
        for i in range(5):
            h.observe(0.5, w=f"u{i}")
        assert len(h._snapshot()) == 3

    def test_unlabeled_metrics_unbounded_by_guard(self, metrics):
        c = rm.counter("t.tr.nolabel")
        c.max_label_sets = 0
        c.inc()
        assert c.value() == 1


# ---------------------------------------------------------------------------
# serving integration
# ---------------------------------------------------------------------------
def _function_server(**cfg_kw):
    repo = serving.ModelRepository()
    repo.add_function("echo", lambda x: x * 2.0,
                      [{"shape": [None, 3], "dtype": "float32"}])
    cfg = serving.ServingConfig(**cfg_kw) if cfg_kw \
        else serving.ServingConfig()
    return serving.ModelServer(repo, cfg), repo


class TestServingTracing:
    def test_predict_span_chain_and_exemplar(self, metrics):
        srv, repo = _function_server()
        try:
            out = srv.predict("echo", np.ones((2, 3), np.float32),
                              timeout=60)
            np.testing.assert_allclose(out, 2.0)
        finally:
            srv.stop()
        t = tr.TRACER.last(root="serving.predict")
        assert t is not None
        _assert_links(t)
        idx = _span_index(t)
        assert {"serving.predict", "serving.admit",
                "serving.queue_wait", "serving.batch",
                "serving.execute"} <= set(idx)
        b = idx["serving.batch"]
        assert b["tags"]["bucket_outcome"] in ("miss", "mem_hit",
                                               "disk_hit")
        assert b["tags"]["bucket"] == 2 and b["tags"]["rows"] == 2
        assert idx["serving.execute"]["parent_id"] == b["span_id"]
        # exemplar: the p99 resolves to this trace
        ex = rm.SERVING_REQUEST_SECONDS.exemplar_for_quantile(
            0.99, model="echo")
        assert ex == t["trace_id"]

    def test_coalesced_requests_share_batch_span(self, metrics):
        """Two coalesced requests: each trace gets the batch-assembly
        span (one live, one copied with shared_with), both with the
        same interval."""
        srv, repo = _function_server(max_batch_size=8,
                                     max_latency_us=200000,
                                     num_workers=1)
        try:
            results = [None, None]

            def call(i):
                results[i] = srv.predict(
                    "echo", np.ones((1, 3), np.float32), timeout=60)

            threads = [threading.Thread(target=call, args=(i,))
                       for i in range(2)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        finally:
            srv.stop()
        traces = [t for t in tr.TRACER.traces()
                  if t["root"] == "serving.predict"]
        assert len(traces) == 2
        batch_spans = []
        for t in traces:
            _assert_links(t)
            idx = _span_index(t)
            assert "serving.batch" in idx
            batch_spans.append(idx["serving.batch"])
        # coalesced into ONE dispatch: the shared copy names its home
        if any(b["tags"].get("requests") == 2 for b in batch_spans):
            shared = [b for b in batch_spans
                      if "shared_with" in b["tags"]]
            live = [b for b in batch_spans
                    if "shared_with" not in b["tags"]]
            assert len(shared) == 1 and len(live) == 1
            assert shared[0]["tags"]["shared_with"] \
                == live[0]["trace_id"]
            assert shared[0]["t0"] == live[0]["t0"]

    def test_shed_incident_dump(self, metrics, tmp_path, monkeypatch):
        """Load shedding writes ONE debounced flight-recorder dump with
        the server's debug state inside."""
        # isolate incident bookkeeping for this test
        monkeypatch.setitem(tr._INCIDENTS, "last", 0.0)
        monkeypatch.setitem(tr._INCIDENTS, "count", 0)
        monkeypatch.setattr(
            tr, "_INCIDENTS",
            dict(tr._INCIDENTS, paths=type(tr._INCIDENTS["paths"])()))
        gate = threading.Event()
        entered = threading.Event()

        def gated(a):
            entered.set()
            assert gate.wait(60)
            return a

        repo = serving.ModelRepository()
        repo.add_function("gated", gated,
                          [{"shape": [None, 1], "dtype": "float32"}])
        cfg = serving.ServingConfig(max_batch_size=1, max_latency_us=1,
                                    queue_depth=2, shed_watermark=1,
                                    num_workers=1)
        srv = serving.ModelServer(repo, cfg)
        payload = np.ones((1, 1), np.float32)
        threads = [threading.Thread(
            target=lambda: srv.predict("gated", payload, timeout=60))]
        threads[0].start()
        assert entered.wait(60)
        deadline = time.monotonic() + 60
        while srv.stats()["queue_depth"] > 0:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        threads.append(threading.Thread(
            target=lambda: srv.predict("gated", payload, timeout=60)))
        threads[1].start()
        while srv.stats()["queue_depth"] < 1:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        sheds = 0
        for _ in range(3):
            with pytest.raises(serving.ServerOverloadedError):
                srv.predict("gated", payload, timeout=60)
            sheds += 1
        gate.set()
        for t in threads:
            t.join(60)
        srv.stop()
        paths = tr.incident_paths()
        assert len(paths) == 1, paths           # 3 sheds, 1 dump
        rec = json.load(open(paths[0]))
        assert rec["reason"] == "serving.shed"
        assert rec["state"]["stats"]["shed"] >= 1
        assert rec["state"]["queues"], rec["state"]
        import os
        os.unlink(paths[0])

    def test_debug_state_shape(self, metrics):
        srv, repo = _function_server()
        repo.add_decoder("lm", FakeLM())
        try:
            srv.predict("echo", np.ones((1, 3), np.float32), timeout=60)
            srv.generate("lm", [1, 2], max_new_tokens=2, timeout=60)
            state = srv.debug_state()
        finally:
            srv.stop()
        json.dumps(state, default=str)          # serializable
        assert state["server"] == srv.name
        assert state["stats"]["completed"] >= 1
        assert state["repository"]["echo"]["current"] == 1
        assert state["repository"]["lm"]["versions"][0]["kind"] \
            == "decoder"
        (eng_state,) = state["decoders"].values()
        assert eng_state["model"] == "lm"
        assert eng_state["free_slots"] == eng_state["max_batch"]
        assert "allocator" in eng_state
        assert state["tracer"]["enabled"]

    def test_untraced_run_records_nothing(self, metrics):
        tr.disable()
        srv, repo = _function_server()
        repo.add_decoder("lm", FakeLM())
        try:
            srv.predict("echo", np.ones((1, 3), np.float32), timeout=60)
            srv.generate("lm", [1], max_new_tokens=2, timeout=60)
        finally:
            srv.stop()
        st = tr.TRACER.stats()
        assert st["traces_started"] == 0 and st["spans"] == 0

    def test_traced_request_compiles_nothing_new(self, metrics):
        """ISSUE-8 acceptance: tracing on/off does not change the jit
        program count — one tiny compiled program serves traced and
        untraced requests alike."""
        import jax
        f = jax.jit(lambda x: x * 2.0)
        repo = serving.ModelRepository()
        repo.add_function("jit", lambda x: f(x),
                          [{"shape": [None, 3], "dtype": "float32"}])
        srv = serving.ModelServer(repo)
        try:
            srv.predict("jit", np.ones((2, 3), np.float32), timeout=60)
            baseline = f._cache_size()
            assert baseline >= 1
            srv.predict("jit", np.ones((2, 3), np.float32), timeout=60)
            tr.disable()
            srv.predict("jit", np.ones((2, 3), np.float32), timeout=60)
            assert f._cache_size() == baseline
        finally:
            srv.stop()


class TestDecodeTracing:
    def test_generate_span_chain(self, metrics):
        srv, repo = _function_server()
        repo.add_decoder("lm", FakeLM())
        try:
            toks = srv.generate("lm", [1, 2, 3], max_new_tokens=3,
                                timeout=60)
            assert len(toks) == 3
        finally:
            srv.stop()
        t = tr.TRACER.last(root="serving.generate")
        assert t is not None
        _assert_links(t)
        idx = _span_index(t)
        need = {"serving.generate", "decode.admission",
                "decode.queue_wait", "decode.prefill", "decode.step",
                "decode.evict"}
        assert need <= set(idx), sorted(idx)
        assert idx["decode.admission"]["tags"]["prompt_tokens"] == 3
        assert idx["decode.queue_wait"]["tags"]["slot"] is not None
        assert idx["decode.prefill"]["tags"]["kv_pages"] >= 1
        assert idx["decode.step"]["tags"]["context_len"] >= 3
        ev = idx["decode.evict"]["tags"]
        assert ev["reason"] == "length"
        assert ev["pages_released"] >= 1
        assert ev["generated_tokens"] == 3
        # exemplar on TTFT
        ex = rm.SERVING_DECODE_TTFT_SECONDS.exemplar_for_quantile(
            0.99, model="lm")
        assert ex == t["trace_id"]

    def test_sampled_out_generate_stays_off_path(self, metrics):
        """Review regression: a sampled-out ModelServer.generate() must
        NOT re-enter head sampling in DecodeEngine.submit and root a
        fragment decode.request trace — one request, one decision."""
        tr.TRACER.set_sample(0.0)
        srv, repo = _function_server()
        repo.add_decoder("lm", FakeLM())
        try:
            srv.generate("lm", [1, 2], max_new_tokens=2, timeout=60)
        finally:
            srv.stop()
        st = tr.TRACER.stats()
        assert st["traces_started"] == 0, st
        assert st["spans"] == 0, st
        # exactly ONE sampling decision was consumed for the request
        assert st["traces_unsampled"] == 1, st

    def test_shed_trace_keeps_admission_span(self):
        """Review regression: on an engine-rooted shed the admission
        span (carrying the shed tag) must land BEFORE the root
        completes the trace — not be dropped as a straggler."""
        eng = DecodeEngine(FakeLM(), _decode_cfg(queue_depth=1),
                           model_name="d", autostart=False)
        eng._started = True
        eng.submit([1], max_new_tokens=2)       # fills the line
        from mxnet_tpu.serving.server import ServerOverloadedError
        with pytest.raises(ServerOverloadedError):
            eng.submit([2], max_new_tokens=2)
        t = tr.TRACER.last(root="decode.request")
        assert t is not None
        idx = _span_index(t)
        assert idx["decode.request"]["tags"]["error"] \
            == "ServerOverloadedError"
        assert idx["decode.admission"]["tags"]["shed"] is True
        assert tr.TRACER.stats()["spans_dropped"] == 0

    def test_failed_batch_trace_keeps_error_batch_span(self, metrics):
        """Review regression: a failing batch still lands its
        error-tagged serving.batch span in the request trace."""
        repo = serving.ModelRepository()

        def broken(x):
            raise RuntimeError("kaboom")

        repo.add_function("broken", broken,
                          [{"shape": [None, 1], "dtype": "float32"}])
        srv = serving.ModelServer(repo)
        try:
            with pytest.raises(RuntimeError, match="kaboom"):
                srv.predict("broken", np.ones((1, 1), np.float32),
                            timeout=60)
        finally:
            srv.stop()
        t = tr.TRACER.last(root="serving.predict")
        idx = _span_index(t)
        assert idx["serving.batch"]["tags"]["error"] == "RuntimeError"
        assert idx["serving.predict"]["tags"]["error"] == "RuntimeError"

    def test_direct_engine_roots_its_own_trace(self):
        """A DecodeEngine driven without a ModelServer still yields a
        complete trace (engine-owned root, closed at eviction)."""
        eng = DecodeEngine(FakeLM(), _decode_cfg(), model_name="d")
        eng.start()
        try:
            out = eng.generate([1, 2], max_new_tokens=2, timeout=60)
            assert len(out) == 2
        finally:
            assert eng.stop(timeout=60)
        t = tr.TRACER.last(root="decode.request")
        assert t is not None
        _assert_links(t)
        names = set(_span_index(t))
        assert {"decode.request", "decode.admission",
                "decode.queue_wait", "decode.prefill",
                "decode.step", "decode.evict"} <= names

    def test_step_span_stride(self, metrics):
        """decode.step spans record the first step then every Nth."""
        eng = DecodeEngine(FakeLM(), _decode_cfg(decode_page_size=2,
                                                 decode_pool_pages=16),
                           model_name="d")
        eng.start()
        try:
            eng.generate([1], max_new_tokens=12, timeout=60)
        finally:
            assert eng.stop(timeout=60)
        t = tr.TRACER.last(root="decode.request")
        steps = [s["tags"]["step"] for s in t["spans"]
                 if s["name"] == "decode.step"]
        from mxnet_tpu.serving import decode as _dec
        expect = [n for n in range(1, 12)
                  if n == 1 or n % _dec._STEP_SPAN_EVERY == 0]
        assert steps == expect, steps

    def test_spans_across_engine_thread_under_sanitizer(self,
                                                        monkeypatch):
        """Tracer + serving locks under MXNET_ENGINE_SANITIZE: spans
        opened in the submitter thread and closed in the step loop must
        not create a lock-order inversion."""
        monkeypatch.setattr(engine, "_SANITIZE", True)
        engine._LOCK_ORDERS.reset()
        try:
            # fresh sanitized tracer so Tracer._lock participates in
            # the order graph alongside the engine's _SanCondition
            monkeypatch.setattr(tr, "TRACER",
                                tr.Tracer(ring=16, sample=1.0))
            eng = DecodeEngine(FakeLM(), _decode_cfg(), model_name="d")
            eng.start()
            try:
                outs = []
                threads = [threading.Thread(
                    target=lambda: outs.append(eng.generate(
                        [1, 2], max_new_tokens=3, timeout=60)))
                    for _ in range(4)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(60)
                assert len(outs) == 4
            finally:
                assert eng.stop(timeout=60)
            t = tr.TRACER.last(root="decode.request")
            assert t is not None
            _assert_links(t)
        finally:
            engine._LOCK_ORDERS.reset()

    def test_cancelled_before_admission_evicts_with_trace(self):
        """A request cancelled while WAITING still completes its trace
        (queue-wait error-tagged, evict span with zero pages)."""
        eng = DecodeEngine(FakeLM(), _decode_cfg(), model_name="d",
                           autostart=False)
        eng._started = True                     # accept submits
        seq = eng.submit([1, 2], max_new_tokens=2)
        seq.cancelled = True
        eng._admit()
        with pytest.raises(MXNetError, match="cancelled"):
            eng.result(seq, timeout=5)
        t = tr.TRACER.last(root="decode.request")
        assert t is not None
        idx = _span_index(t)
        assert idx["decode.evict"]["tags"]["reason"] == "cancelled"
        assert idx["decode.evict"]["tags"]["pages_released"] == 0
        assert idx["decode.queue_wait"]["tags"]["error"] == "cancelled"


class TestFlightRecorder:
    def test_flight_record_shape(self):
        root = tr.trace("req")
        root.end()
        rec = tr.flight_record(state={"k": 1})
        assert rec["tracer"]["completed"] == 1
        assert rec["traces"][0]["root"] == "req"
        assert rec["state"] == {"k": 1}

    def test_record_incident_debounce_and_callable_state(self,
                                                         tmp_path,
                                                         monkeypatch):
        monkeypatch.setattr(
            tr, "_INCIDENTS",
            {"last": 0.0, "count": 0,
             "paths": type(tr._INCIDENTS["paths"])()})
        calls = []

        def state():
            calls.append(1)
            return {"depth": 3}

        p1 = tr.record_incident("test", state,
                                path=str(tmp_path / "f1.json"))
        assert p1 is not None
        assert tr.record_incident("test", state,
                                  path=str(tmp_path / "f2.json")) \
            is None                             # debounced
        p3 = tr.record_incident("test", state,
                                path=str(tmp_path / "f3.json"),
                                min_interval=0.0)
        assert p3 is not None
        assert len(calls) == 2                  # debounce skips state()
        rec = json.load(open(p1))
        assert rec["reason"] == "test" and rec["state"] == {"depth": 3}
        assert tr.incident_paths() == [p1, p3]

    def test_record_incident_disabled_noop(self, tmp_path):
        tr.disable()
        assert tr.record_incident("x", {},
                                  path=str(tmp_path / "x.json")) is None

    def test_incident_survives_failing_state_fn(self, tmp_path,
                                                monkeypatch):
        monkeypatch.setattr(
            tr, "_INCIDENTS",
            {"last": 0.0, "count": 0,
             "paths": type(tr._INCIDENTS["paths"])()})

        def bad_state():
            raise RuntimeError("broken")

        p = tr.record_incident("x", bad_state,
                               path=str(tmp_path / "x.json"))
        rec = json.load(open(p))
        assert "debug_state failed" in rec["state"]["error"]
