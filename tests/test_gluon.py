"""Gluon Block/HybridBlock/Parameter tests.

Modeled on the reference suite tests/python/unittest/test_gluon.py
(hybridize-vs-imperative equivalence, deferred init, save/load round trips
— SURVEY.md §4).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn


def _mlp():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32, activation="relu"))
        net.add(nn.Dense(8))
    return net


def test_parameter_basic():
    p = gluon.Parameter("weight", shape=(4, 3))
    p.initialize(ctx=mx.cpu(0))
    assert p.data().shape == (4, 3)
    assert p.grad().shape == (4, 3)
    assert p.list_ctx() == [mx.cpu(0)]
    p.set_data(nd.ones((4, 3)))
    assert p.data().asnumpy().sum() == 12


def test_parameter_deferred_init():
    net = _mlp()
    net.initialize()
    # shape unknown until first forward
    with pytest.raises(Exception):
        net[0].weight.data()
    x = nd.ones((2, 5))
    net(x)
    assert net[0].weight.shape == (32, 5)


def test_parameter_sharing():
    d1 = nn.Dense(8, in_units=8)
    d2 = nn.Dense(8, in_units=8, params=d1.collect_params())
    d1.initialize()
    x = nd.random.uniform(shape=(4, 8))
    assert np.allclose(d1(x).asnumpy(), d2(x).asnumpy())


def test_hybrid_vs_imperative():
    net = _mlp()
    net.initialize()
    x = nd.random.uniform(shape=(4, 10))
    y_imp = net(x).asnumpy()
    net.hybridize()
    y_hyb = net(x).asnumpy()
    assert np.allclose(y_imp, y_hyb, atol=1e-5)


def test_hybrid_gradients_match():
    x_np = np.random.randn(4, 10).astype(np.float32)

    def run(hybridize):
        mx.random.seed(7)
        np.random.seed(7)
        net = _mlp()
        net.initialize()
        if hybridize:
            net.hybridize()
        x = nd.array(x_np)
        x.attach_grad()
        with autograd.record():
            y = net(x)
            loss = (y * y).sum()
        loss.backward()
        grads = {name[len(net.prefix):]: p.grad().asnumpy()
                 for name, p in net.collect_params().items()}
        return x.grad.asnumpy(), grads

    xg_i, g_i = run(False)
    xg_h, g_h = run(True)
    assert np.allclose(xg_i, xg_h, atol=1e-4), np.abs(xg_i - xg_h).max()
    for name in g_i:
        assert np.allclose(g_i[name], g_h[name], atol=1e-4), name


def test_cached_op_reuse():
    from mxnet_tpu.gluon.block import nb_cached_programs
    net = _mlp()
    net.initialize()
    net.hybridize()
    x = nd.ones((2, 6))
    before = nb_cached_programs()
    net(x)
    net(x)
    net(x)
    after_same = nb_cached_programs()
    assert after_same == before + 1  # one signature -> one compile
    net(nd.ones((4, 6)))  # new batch size -> new program
    assert nb_cached_programs() == after_same + 1


def test_conv_pool_shapes():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(8, 3, padding=1), nn.MaxPool2D(2, 2),
                nn.Conv2D(16, 3, padding=1), nn.GlobalAvgPool2D(),
                nn.Flatten(), nn.Dense(10))
    net.initialize()
    out = net(nd.ones((2, 3, 16, 16)))
    assert out.shape == (2, 10)


def test_conv_transpose_shape():
    net = nn.Conv2DTranspose(4, 3, strides=2, padding=1, output_padding=1,
                             in_channels=8)
    net.initialize()
    out = net(nd.ones((2, 8, 7, 7)))
    assert out.shape == (2, 4, 14, 14)


def test_batchnorm_train_vs_eval():
    bn = nn.BatchNorm(in_channels=4)
    bn.initialize()
    x = nd.random.uniform(shape=(8, 4, 3, 3))
    with autograd.record():
        y_train = bn(x)
    y_eval = bn(x)
    # training output is normalized by batch stats: near zero mean
    m = y_train.asnumpy().mean(axis=(0, 2, 3))
    assert np.abs(m).max() < 1e-4
    # eval uses running stats (just updated once): different output
    assert not np.allclose(y_train.asnumpy(), y_eval.asnumpy())


def test_embedding_layer():
    emb = nn.Embedding(10, 6)
    emb.initialize()
    idx = nd.array(np.array([[1, 2], [3, 4]]), dtype="int32")
    out = emb(idx)
    assert out.shape == (2, 2, 6)


def test_layernorm_groupnorm():
    ln = nn.LayerNorm(in_channels=6)
    ln.initialize()
    y = ln(nd.random.uniform(shape=(3, 6)))
    m = y.asnumpy().mean(axis=-1)
    assert np.abs(m).max() < 1e-4
    gn = nn.GroupNorm(num_groups=2, in_channels=4)
    gn.initialize()
    z = gn(nd.random.uniform(shape=(2, 4, 5, 5)))
    assert z.shape == (2, 4, 5, 5)


def test_save_load_parameters(tmp_path):
    net = _mlp()
    net.initialize()
    x = nd.random.uniform(shape=(2, 12))
    y0 = net(x).asnumpy()
    fname = str(tmp_path / "mlp.params")
    net.save_parameters(fname)
    net2 = _mlp()
    net2.load_parameters(fname)
    assert np.allclose(y0, net2(x).asnumpy(), atol=1e-6)


def test_sequential_getitem_len():
    net = _mlp()
    assert len(net) == 2
    assert isinstance(net[0], nn.Dense)
    assert isinstance(net[0:1], nn.HybridSequential)


def test_activations():
    x = nd.array(np.linspace(-3, 3, 13, dtype=np.float32))
    for blk, ref in [
        (nn.Activation("relu"), lambda v: np.maximum(v, 0)),
        (nn.LeakyReLU(0.1), lambda v: np.where(v > 0, v, 0.1 * v)),
        (nn.ELU(1.0), lambda v: np.where(v > 0, v, np.expm1(v))),
        (nn.Swish(), lambda v: v / (1 + np.exp(-v))),
    ]:
        out = blk(x).asnumpy()
        assert np.allclose(out, ref(x.asnumpy()), atol=1e-5), type(blk)


def test_custom_hybrid_block():
    class Net(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.fc = nn.Dense(6, in_units=4)
                self.scale = self.params.get("scale", shape=(1,),
                                             init="ones")

        def hybrid_forward(self, F, x, scale):
            return self.fc(x) * scale

    net = Net()
    net.initialize()
    x = nd.ones((2, 4))
    y1 = net(x).asnumpy()
    net.hybridize()
    y2 = net(x).asnumpy()
    assert np.allclose(y1, y2, atol=1e-6)
    # grads flow to child + own param under hybrid
    with autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    assert float(np.abs(net.scale.grad().asnumpy()).sum()) > 0
    assert float(np.abs(net.fc.weight.grad().asnumpy()).sum()) > 0


def test_block_summary_runs(capsys):
    net = _mlp()
    net.initialize()
    net.summary(nd.ones((1, 5)))
    assert "Total params" in capsys.readouterr().out


def test_hybrid_dropout_varies_across_calls():
    """CachedOp must feed a fresh PRNG key per call (review finding:
    baked-constant keys repeat the same dropout mask every step)."""
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dropout(0.5))
    net.initialize()
    net.hybridize()
    x = nd.ones((4, 64))
    with autograd.record():
        m1 = net(x).asnumpy()
    with autograd.record():
        m2 = net(x).asnumpy()
    assert (m1 != m2).any(), "identical dropout masks across calls"
    # eval mode: dropout off
    assert np.allclose(net(x).asnumpy(), 1.0)


def test_multi_precision_adam():
    """multi_precision with non-SGD optimizers (review finding)."""
    import mxnet_tpu.optimizer as opt
    w = nd.array(np.ones((4,), np.float16), dtype="float16")
    g = nd.array(np.full((4,), 0.5, np.float16), dtype="float16")
    o = opt.Adam(learning_rate=0.1, multi_precision=True)
    state = o.create_state_multi_precision(0, w)
    assert isinstance(state, tuple) and str(state[0].dtype) == "float32"
    o.update_multi_precision(0, w, g, state)
    assert str(w.dtype) == "float16"
    assert (w.asnumpy() < 1.0).all()


def test_trainer_multi_device_state_not_double_stepped():
    """Per-device updaters (review finding: shared state double-steps)."""
    p = gluon.Parameter("w", shape=(2,))
    p.initialize(ctx=[mx.cpu(0)])
    # simulate 2 device copies
    import mxnet_tpu.context as ctx_mod
    trainer = gluon.Trainer([p], "adam", {"learning_rate": 0.1})
    with autograd.record():
        loss = (p.data() * p.data()).sum()
    loss.backward()
    trainer.step(1)
    t = trainer._updater.optimizer._index_update_count[0]
    assert t == 1, t


def test_hybrid_second_backward_raises_clear_error():
    net = nn.Dense(3, in_units=4)
    net.initialize()
    net.hybridize()
    x = nd.random.uniform(shape=(2, 4))
    x.attach_grad()
    with autograd.record():
        h = net(x)
        y1 = h.sum()
        y2 = (h * 2).sum()
    y1.backward(retain_graph=True)
    g1 = x.grad.asnumpy().copy()
    y2.backward()                       # retained residuals: fine
    assert np.allclose(x.grad.asnumpy(), 2 * g1, rtol=1e-5)
    # fresh pass WITHOUT retain: second replay must raise clearly
    with autograd.record():
        h = net(x)
        y1 = h.sum()
        y2 = (h * 2).sum()
    y1.backward()
    with pytest.raises(mx.MXNetError, match="retain_graph"):
        y2.backward()


def test_cached_op_cache_bounded_lru():
    """Gluon-layer compile-cache growth control (VERDICT r2 weak #6):
    the per-CachedOp program cache is LRU-bounded and warns on churn."""
    import warnings
    net = nn.Dense(4, in_units=8, prefix="lru_dense_")
    net.initialize()
    net.hybridize(cache_size=2)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        for B in (1, 2, 3, 4):
            net(nd.zeros((B, 8)))
        cop = net._cached_op
        assert len(cop._cache) == 2
        assert cop._n_evictions == 2
        assert any("eviction" in str(x.message) for x in w)
    # LRU order: hitting a cached sig keeps it resident
    net(nd.zeros((4, 8)))     # hit, moves (4,8) to MRU
    net(nd.zeros((5, 8)))     # evicts (3,8), not (4,8)
    sigs = [s[0][0][0] for s in cop._cache]
    assert (4, 8) in sigs and (5, 8) in sigs


def test_cached_op_bucket_shapes():
    """hybridize(bucket_shapes=...) pads ragged axes onto a fixed bucket
    set: one program per bucket, padding-safe outputs."""
    net = nn.Dense(4, flatten=False, in_units=8, prefix="bkt_dense_")
    net.initialize()
    net.hybridize(bucket_shapes={1: [4, 8]})
    from mxnet_tpu.gluon.block import nb_cached_programs
    n0 = nb_cached_programs()
    out3 = net(nd.ones((2, 3, 8)))
    assert out3.shape == (2, 4, 4)          # padded up to bucket 4
    net(nd.ones((2, 4, 8)))                  # exact bucket: same program
    net(nd.ones((2, 6, 8)))                  # bucket 8
    net(nd.ones((2, 7, 8)))                  # bucket 8 again: same program
    assert nb_cached_programs() - n0 == 2
    # zero-padding on the bucketed axis: padded rows produce bias-only
    # outputs, real rows match the unpadded compute
    ref = net(nd.ones((2, 4, 8))).asnumpy()
    np.testing.assert_allclose(out3.asnumpy()[:, :3], ref[:, :3], rtol=1e-5)
    with pytest.raises(mx.base.MXNetError, match="larger than the largest"):
        net(nd.ones((2, 9, 8)))


def test_cached_op_bucket_pad_keeps_input_grads():
    """Bucket padding must tape through the dispatcher: d(loss)/d(input)
    flows across the pad (vjp of pad = slice)."""
    net = nn.Dense(4, flatten=False, in_units=8, prefix="bktg_dense_")
    net.initialize()
    net.hybridize(bucket_shapes={1: [4, 8]})
    x = nd.random.uniform(shape=(2, 3, 8))
    x.attach_grad()
    from mxnet_tpu import autograd
    with autograd.record():
        y = net(x)
    y.backward()
    assert np.abs(x.grad.asnumpy()).sum() > 0
    # reference: same net un-bucketed gives identical input grads
    net2 = nn.Dense(4, flatten=False, in_units=8, prefix="bktg2_dense_")
    net2.initialize()
    for (n1, p1), (n2, p2) in zip(net.collect_params().items(),
                                  net2.collect_params().items()):
        p2.set_data(p1.data())
    x2 = nd.array(x.asnumpy())
    x2.attach_grad()
    with autograd.record():
        y2 = net2(x2)
    y2.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), x2.grad.asnumpy(),
                               rtol=1e-5, atol=1e-6)
