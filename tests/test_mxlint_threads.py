"""mxthread tests: the thread-role × lockset engine and the race trio
it powers (shared-state-race, atomicity, condition-discipline), plus
the ISSUE-20 satellites (SARIF output round trip, scope single-source).

Pure-AST + stdlib: no jax import, so the whole file costs a few
seconds (tier-1 budget discipline — ROADMAP.md).
"""
import json
import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.mxlint import PASSES, lint_sources        # noqa: E402
from tools.mxlint.core import Project, SourceFile    # noqa: E402

RACE_PASSES = ["shared-state-race", "atomicity", "condition-discipline"]

HDR = """
    import threading
"""


def run(src, select=None, path="mxnet_tpu/fixture.py", extra=None,
        report=None):
    sources = {path: textwrap.dedent(HDR) + textwrap.dedent(src)}
    for p, s in (extra or {}).items():
        sources[p] = textwrap.dedent(HDR) + textwrap.dedent(s)
    return lint_sources(sources, select=select, report=report)


def model_of(src, path="mxnet_tpu/fixture.py", extra=None):
    sources = {path: textwrap.dedent(HDR) + textwrap.dedent(src)}
    for p, s in (extra or {}).items():
        sources[p] = textwrap.dedent(HDR) + textwrap.dedent(s)
    proj = Project()
    proj.harvest([SourceFile(p, s) for p, s in sources.items()])
    return proj.threadmodel()


def ids(issues):
    return [i.pass_id for i in issues]


def test_catalogue_has_the_race_trio():
    assert len(PASSES) == 22
    for pid in RACE_PASSES:
        assert pid in PASSES


# ================================================== the engine's facts
RACY_BOX = """
    class Box:
        def __init__(self):
            self.n = 0
            self._t = threading.Thread(target=self._loop)
            self._t.start()

        def _loop(self):
            self.n += 1

        def bump(self):
            self.n += 1
"""


def test_thread_root_becomes_a_role():
    tm = model_of(RACY_BOX)
    role_ids = set(tm.roles)
    assert "main" in role_ids
    assert any(r.startswith("thread:") and "_loop" in r
               for r in role_ids)


def test_loop_spawn_is_a_pool_role():
    tm = model_of("""
        class Pool:
            def __init__(self, k):
                self.done = 0
                for _ in range(k):
                    threading.Thread(target=self._work).start()

            def _work(self):
                self.done += 1
    """)
    pool = [r for rid, r in tm.roles.items() if "_work" in rid]
    assert len(pool) == 1 and pool[0].multi


def test_shared_keys_need_two_roles():
    tm = model_of(RACY_BOX)
    assert "Box.n" in tm.shared_keys()
    # single-threaded twin: same writes, no thread — nothing escapes
    tm2 = model_of("""
        class Solo:
            def __init__(self):
                self.n = 0

            def bump(self):
                self.n += 1
    """)
    assert "Solo.n" not in tm2.shared_keys()


def test_entry_lockset_is_inherited_from_all_callers():
    tm = model_of("""
        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0
                threading.Thread(target=self._loop).start()

            def _loop(self):
                with self._lock:
                    self._bump_locked()

            def bump(self):
                with self._lock:
                    self._bump_locked()

            def _bump_locked(self):
                self.n += 1
    """)
    accs = [a for a in tm.accesses["Box.n"]
            if a.fn.qname.endswith("_bump_locked")]
    assert accs and all("Box._lock" in tm.locks_of(a) for a in accs)
    # the witness names the inheritance, not just the lock
    assert "via" in tm.lock_witness(accs[0])


# ============================================ pass 20: shared-state-race
def test_two_role_unlocked_compound_write_fires():
    issues = run(RACY_BOX, select=["shared-state-race"])
    assert ids(issues) == ["shared-state-race"]
    msg = issues[0].message
    # both sites, both roles, both locksets — in one finding
    assert "Box.n" in msg and "no lock" in msg
    assert "_loop" in msg and "main" in msg
    assert "mxnet_tpu/fixture.py" in msg      # the partner site


def test_shared_lock_on_both_sides_is_quiet():
    issues = run("""
        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0
                threading.Thread(target=self._loop).start()

            def _loop(self):
                with self._lock:
                    self.n += 1

            def bump(self):
                with self._lock:
                    self.n += 1
    """, select=["shared-state-race"])
    assert issues == []


def test_inherited_lock_silences_the_pair():
    issues = run("""
        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0
                threading.Thread(target=self._loop).start()

            def _loop(self):
                with self._lock:
                    self._bump_locked()

            def bump(self):
                with self._lock:
                    self._bump_locked()

            def _bump_locked(self):
                self.n += 1
    """, select=["shared-state-race"])
    assert issues == []


def test_non_compound_writes_are_gil_atomic_and_quiet():
    issues = run("""
        class Box:
            def __init__(self):
                self.flag = False
                threading.Thread(target=self._loop).start()

            def _loop(self):
                self.flag = True

            def clear(self):
                self.flag = False
    """, select=["shared-state-race"])
    assert issues == []


def test_locked_compound_write_vs_lockfree_read_is_quiet():
    # the read is one atomic load under the GIL; the locked writer
    # cannot tear it
    issues = run("""
        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0
                threading.Thread(target=self._loop).start()

            def _loop(self):
                with self._lock:
                    self.n += 1

            def peek(self):
                return self.n
    """, select=["shared-state-race"])
    assert issues == []


def test_suppression_on_either_site_silences_the_pair():
    issues = run("""
        class Box:
            def __init__(self):
                self.n = 0
                threading.Thread(target=self._loop).start()

            def _loop(self):
                self.n += 1  # mxlint: disable=shared-state-race (test contract)

            def bump(self):
                self.n += 1
    """, select=["shared-state-race"])
    assert issues == []


# ===================================================== pass 21: atomicity
def test_rmw_on_shared_state_fires():
    issues = run(RACY_BOX, select=["atomicity"])
    assert ids(issues) == ["atomicity", "atomicity"]  # both sites
    assert "read-modify-write" in issues[0].message


def test_rmw_under_lock_is_quiet():
    issues = run("""
        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0
                threading.Thread(target=self._loop).start()

            def _loop(self):
                with self._lock:
                    self.n += 1

            def bump(self):
                with self._lock:
                    self.n += 1
    """, select=["atomicity"])
    assert issues == []


def test_check_then_act_fires():
    issues = run("""
        class Box:
            def __init__(self):
                self._d = {}
                threading.Thread(target=self._loop).start()

            def _loop(self):
                self._d["k"] = 1

            def take(self):
                if "k" in self._d:
                    return self._d.pop("k")
    """, select=["atomicity"])
    assert ids(issues) == ["atomicity"]
    assert "check-then-act" in issues[0].message


def test_check_then_act_under_lock_is_quiet():
    issues = run("""
        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._d = {}
                threading.Thread(target=self._loop).start()

            def _loop(self):
                with self._lock:
                    self._d["k"] = 1

            def take(self):
                with self._lock:
                    if "k" in self._d:
                        return self._d.pop("k")
    """, select=["atomicity"])
    assert issues == []


def test_single_role_state_never_flags_atomicity():
    issues = run("""
        class Solo:
            def __init__(self):
                self.n = 0
                self._d = {}

            def bump(self):
                self.n += 1
                if "k" in self._d:
                    self._d.pop("k")
    """, select=["atomicity"])
    assert issues == []


# ========================================= pass 22: condition-discipline
def test_wait_under_if_fires_and_while_is_quiet():
    issues = run("""
        class Box:
            def __init__(self):
                self._cond = threading.Condition()
                self.ready = False

            def park_if(self):
                with self._cond:
                    if not self.ready:
                        self._cond.wait()

            def park_while(self):
                with self._cond:
                    while not self.ready:
                        self._cond.wait()

            def wake(self):
                with self._cond:
                    self.ready = True
                    self._cond.notify_all()
    """, select=["condition-discipline"])
    assert ids(issues) == ["condition-discipline"]
    assert "while" in issues[0].message
    assert issues[0].line < 15      # anchored at the if-guarded wait


def test_notify_without_the_lock_fires():
    issues = run("""
        class Box:
            def __init__(self):
                self._cond = threading.Condition()
                self.ready = False

            def park(self):
                with self._cond:
                    while not self.ready:
                        self._cond.wait()

            def wake(self):
                self.ready = True
                self._cond.notify_all()
    """, select=["condition-discipline"])
    assert ids(issues) == ["condition-discipline"]
    assert "notify" in issues[0].message


def test_wait_nothing_notifies_fires_cross_file():
    issues = run("""
        class Box:
            def __init__(self):
                self._cond = threading.Condition()
                self.ready = False

            def park(self):
                with self._cond:
                    while not self.ready:
                        self._cond.wait()
    """, select=["condition-discipline"])
    assert ids(issues) == ["condition-discipline"]
    assert "notif" in issues[0].message


def test_timeout_wait_is_polling_and_quiet():
    issues = run("""
        class Box:
            def __init__(self):
                self._cond = threading.Condition()
                self.ready = False

            def park(self):
                with self._cond:
                    while not self.ready:
                        self._cond.wait(0.1)
    """, select=["condition-discipline"])
    assert issues == []


# ================================================= --changed soundness
CROSS_A = """
    class Box:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1

        def _work(self):
            self.n += 1
"""
CROSS_B = """
    from mxnet_tpu.fa import Box

    def start():
        b = Box()
        t = threading.Thread(target=b._work)
        t.start()
        return b
"""


def test_changed_report_keeps_cross_file_roles_sound():
    # the thread role comes from fb.py; the racing writes live in
    # fa.py.  A --changed run reporting only fa.py must still see the
    # role (whole-project harvest) and report the finding there.
    issues = run(CROSS_A, path="mxnet_tpu/fa.py",
                 extra={"mxnet_tpu/fb.py": CROSS_B},
                 select=["shared-state-race"],
                 report=["mxnet_tpu/fa.py"])
    assert ids(issues) == ["shared-state-race"]
    assert issues[0].path == "mxnet_tpu/fa.py"
    # reporting only the (finding-free) spawner file stays empty
    issues = run(CROSS_A, path="mxnet_tpu/fa.py",
                 extra={"mxnet_tpu/fb.py": CROSS_B},
                 select=["shared-state-race"],
                 report=["mxnet_tpu/fb.py"])
    assert issues == []


# ============================================ satellite: SARIF round trip
def test_sarif_cli_round_trip(tmp_path):
    bad = tmp_path / "serving" / "x.py"
    bad.parent.mkdir()
    bad.write_text(textwrap.dedent("""
        import threading

        class Box:
            def __init__(self):
                self.n = 0
                threading.Thread(target=self._loop).start()

            def _loop(self):
                self.n += 1

            def bump(self):
                self.n += 1
    """))
    sarif = subprocess.run(
        [sys.executable, "-m", "tools.mxlint", "--no-cache",
         "--format", "sarif", str(tmp_path)],
        cwd=REPO, capture_output=True, text=True)
    assert sarif.returncode == 1
    doc = json.loads(sarif.stdout)
    assert doc["version"] == "2.1.0"
    runobj = doc["runs"][0]
    rule_ids = [r["id"] for r in runobj["tool"]["driver"]["rules"]]
    assert sorted(rule_ids) == sorted(PASSES)
    results = runobj["results"]
    assert results, "expected findings on the seeded race"
    # identical finding set to --format json (same suppression /
    # baseline semantics — only the serialization differs)
    plain = subprocess.run(
        [sys.executable, "-m", "tools.mxlint", "--no-cache",
         "--format", "json", str(tmp_path)],
        cwd=REPO, capture_output=True, text=True)
    want = {(j["pass"], j["line"]) for j in
            (json.loads(l) for l in plain.stdout.splitlines() if l)}
    got = set()
    for r in results:
        assert r["ruleId"] in rule_ids
        loc = r["locations"][0]["physicalLocation"]
        line = loc["region"]["startLine"]
        # SARIF columns are 1-based; mxlint's are 0-based
        assert loc["region"]["startColumn"] >= 1
        got.add((r["ruleId"], line))
    assert got == want


def test_sarif_clean_tree_is_an_empty_results_array(tmp_path):
    good = tmp_path / "ok.py"
    good.write_text("def f():\n    return 1\n")
    proc = subprocess.run(
        [sys.executable, "-m", "tools.mxlint", "--no-cache",
         "--format", "sarif", str(tmp_path)],
        cwd=REPO, capture_output=True, text=True)
    assert proc.returncode == 0
    doc = json.loads(proc.stdout)
    assert doc["runs"][0]["results"] == []


# ======================================= satellite: scope single-source
def test_scope_tables_single_source_drives_passes_and_docs():
    from tools.mxlint.scopes import SCOPES
    ld = SCOPES["lock-discipline"]
    assert ld.matches("mxnet_tpu/serving/server.py")
    assert not ld.matches("mxnet_tpu/gluon/block.py")
    hs = SCOPES["host-sync"]
    assert hs.match_key("mxnet_tpu/ops/gemm.py") == "ops"
    assert hs.match_key("mxnet_tpu/serving/batcher.py") == "serving"
    assert hs.match_key("mxnet_tpu/engine.py") is None
    # the committed docs table is in sync with the declarations
    proc = subprocess.run(
        [sys.executable, "tools/gen_lint_docs.py", "--check"],
        cwd=REPO, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
