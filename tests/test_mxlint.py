"""mxlint fixture tests: each pass fires on its positive snippet, stays
quiet on the negative, and honors the suppression comment — plus the
acceptance gate that the real tree is clean (ISSUE-3).

Pure-AST: no jax import, so this file costs milliseconds (tier-1 budget
discipline — ROADMAP.md).
"""
import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.mxlint import PASSES, Project, lint_paths, lint_sources  # noqa: E402


def run(src, path="mxnet_tpu/serving/fixture.py", select=None, **proj):
    project = Project(**proj) if proj else None
    return lint_sources({path: textwrap.dedent(src)}, select=select,
                        project=project)


def ids(issues):
    return [i.pass_id for i in issues]


def test_pass_catalogue_complete():
    assert set(PASSES) == {"jit-retrace", "host-sync", "lock-discipline",
                           "metrics-misuse", "env-registry",
                           "collective-soundness", "resource-leak",
                           "shape-soundness", "dtype-promotion",
                           "recompile-churn", "fault-site-soundness",
                           "deadline-soundness", "telemetry-drift",
                           "determinism-soundness", "thread-lifecycle",
                           "blocking-in-loop", "sharding-soundness",
                           "replication-soundness",
                           "donation-soundness", "shared-state-race",
                           "atomicity", "condition-discipline"}


# ---------------------------------------------------------------- jit-retrace
def test_jit_retrace_fires_on_scalarized_traced_arg():
    issues = run("""
        import jax

        @jax.jit
        def f(x):
            s = float(x)
            return x * s
    """, select=["jit-retrace"])
    assert ids(issues) == ["jit-retrace"]
    assert "float()" in issues[0].message


def test_jit_retrace_fires_on_asnumpy_and_np_asarray():
    issues = run("""
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            return np.asarray(x) + x.asnumpy()

        class Net:
            def hybrid_forward(self, F, x):
                return x.asnumpy()
    """, select=["jit-retrace"])
    assert ids(issues) == ["jit-retrace"] * 3


def test_jit_retrace_partial_decorator_and_nested_fn_params():
    issues = run("""
        from functools import partial
        import jax

        @partial(jax.jit, static_argnums=(1,))
        def f(x, n):
            def inner(d):
                return int(d)
            return inner(x)
    """, select=["jit-retrace"])
    assert ids(issues) == ["jit-retrace"]


def test_jit_retrace_nested_param_name_does_not_leak_to_outer_body():
    issues = run("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            n = x.shape[0]

            def body(n):
                return n

            return jnp.zeros(int(n)) + body(x)
    """, select=["jit-retrace"])
    assert issues == []


def test_jit_retrace_negative_static_shape_and_unjitted():
    issues = run("""
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            return x.reshape(int(x.shape[0]), -1)

        def host_fn(x):
            return float(x) + np.asarray(x).sum()
    """, select=["jit-retrace"])
    assert issues == []


def test_jit_retrace_suppression():
    issues = run("""
        import jax

        @jax.jit
        def f(x):
            s = float(x)  # mxlint: disable=jit-retrace
            return x * s
    """, select=["jit-retrace"])
    assert issues == []


# ------------------------------------------------------------------ host-sync
def test_host_sync_fires_in_ops():
    issues = run("""
        import jax

        def relu_impl(x):
            jax.block_until_ready(x)
            return x
    """, path="mxnet_tpu/ops/fixture.py", select=["host-sync"])
    assert ids(issues) == ["host-sync"]
    assert "engine.sync_outputs" in issues[0].message


def test_host_sync_fires_in_batcher_dispatch():
    issues = run("""
        class MyBatcher:
            def run_batch(self, entry, reqs):
                outs = entry.prog(*reqs)
                return [o.asnumpy() for o in outs]
    """, select=["host-sync"])
    assert ids(issues) == ["host-sync"]


def test_host_sync_quiet_on_admission_path_and_unscoped_files():
    issues = run("""
        class Server:
            def predict(self, model, x):
                return x.asnumpy()
    """, select=["host-sync"])
    assert issues == []
    issues = run("""
        import jax

        def helper(x):
            return jax.block_until_ready(x)
    """, path="mxnet_tpu/gluon/fixture.py", select=["host-sync"])
    assert issues == []


def test_host_sync_suppression():
    issues = run("""
        def _worker_loop(self):
            # mxlint: disable=host-sync (measured: cheaper than a queue)
            self.out.asnumpy()
    """, select=["host-sync"])
    assert issues == []


# ------------------------------------------------------------ lock-discipline
def test_lock_module_state_fires_and_lock_silences():
    pos = run("""
        _CACHE = {}

        def put(k, v):
            _CACHE[k] = v
    """, select=["lock-discipline"])
    assert ids(pos) == ["lock-discipline"]
    neg = run("""
        import threading
        _CACHE = {}
        _LOCK = threading.Lock()

        def put(k, v):
            with _LOCK:
                _CACHE[k] = v
    """, select=["lock-discipline"])
    assert neg == []


def test_lock_instance_state_fires_outside_lock():
    issues = run("""
        import threading

        class Pool:
            def __init__(self):
                self._lock = threading.Lock()
                self._jobs = []

            def add(self, j):
                self._jobs.append(j)

            def rebind(self, js):
                self._jobs = list(js)
    """, select=["lock-discipline"])
    assert ids(issues) == ["lock-discipline"] * 2


def test_lock_instance_state_quiet_under_lock_and_unlocked_class():
    issues = run("""
        import threading

        class Pool:
            def __init__(self):
                self._lock = threading.Lock()
                self._jobs = []

            def add(self, j):
                with self._lock:
                    self._jobs.append(j)

        class PlainBag:
            def __init__(self):
                self._items = []

            def add(self, x):
                self._items.append(x)
    """, select=["lock-discipline"])
    assert issues == []


def test_lock_order_inversion_detected_across_functions():
    issues = run("""
        import threading
        A_LOCK = threading.Lock()
        B_LOCK = threading.Lock()

        def forward():
            with A_LOCK:
                with B_LOCK:
                    pass

        def backward():
            with B_LOCK:
                with A_LOCK:
                    pass
    """, select=["lock-discipline"])
    assert ids(issues) == ["lock-discipline"] * 2
    assert "inversion" in issues[0].message


def test_blocking_call_under_lock():
    issues = run("""
        import threading, time
        _LOCK = threading.Lock()

        def poll():
            with _LOCK:
                time.sleep(0.5)
    """, select=["lock-discipline"])
    assert ids(issues) == ["lock-discipline"]
    assert "blocking" in issues[0].message


def test_lock_suppression_directive_above_statement():
    issues = run("""
        import threading

        class Pool:
            def __init__(self):
                self._lock = threading.Lock()
                self._depth = 0

            def _set_depth(self, d):
                # mxlint: disable=lock-discipline (callers hold lock)
                self._depth = d
    """, select=["lock-discipline"])
    assert issues == []


# ------------------------------------------------------------- metrics-misuse
def test_counter_negative_inc_fires_gauge_quiet():
    issues = run("""
        from runtime_metrics import counter, gauge
        REQS = counter("reqs")
        DEPTH = gauge("depth")

        def shed():
            REQS.inc(-1)
            DEPTH.inc(-1)
            REQS.inc(2)
    """, select=["metrics-misuse"])
    assert ids(issues) == ["metrics-misuse"]
    assert "monotonic" in issues[0].message


def test_histogram_bucket_conflict_across_files():
    srcs = {
        "mxnet_tpu/a.py": "from m import histogram\n"
                          "H1 = histogram('lat', buckets=(0.1, 1.0))\n",
        "mxnet_tpu/b.py": "from m import histogram\n"
                          "H2 = histogram('lat', buckets=(0.1, 2.0))\n",
    }
    issues = lint_sources(srcs, select=["metrics-misuse"])
    assert ids(issues) == ["metrics-misuse"] * 2
    same = dict(srcs)
    same["mxnet_tpu/b.py"] = same["mxnet_tpu/a.py"].replace("H1", "H2")
    assert lint_sources(same, select=["metrics-misuse"]) == []


def test_histogram_suppressed_site_does_not_hide_conflict_elsewhere():
    srcs = {
        "mxnet_tpu/a.py": "from m import histogram\n"
                          "H1 = histogram('lat', buckets=(0.1, 1.0))"
                          "  # mxlint: disable=metrics-misuse\n",
        "mxnet_tpu/b.py": "from m import histogram\n"
                          "H2 = histogram('lat', buckets=(0.1, 2.0))\n",
    }
    issues = lint_sources(srcs, select=["metrics-misuse"])
    assert [(i.pass_id, i.path) for i in issues] == \
        [("metrics-misuse", "mxnet_tpu/b.py")]


def test_metrics_suppression():
    issues = run("""
        from runtime_metrics import counter
        N = counter("n")

        def f():
            N.inc(-1)  # mxlint: disable=metrics-misuse
    """, select=["metrics-misuse"])
    assert issues == []


# --------------------------------------------------------------- env-registry
def test_env_registry_fires_on_undeclared_read():
    issues = run("""
        import os

        def f():
            a = os.environ.get("MXNET_TOTALLY_UNDECLARED_KNOB")
            b = os.environ["MXNET_ANOTHER_UNDECLARED_KNOB"]
            return a, b
    """, select=["env-registry"])
    assert ids(issues) == ["env-registry"] * 2


def test_env_registry_declared_or_documented_is_quiet():
    src = """
        import os
        from base import declare_env, get_env
        declare_env("MXNET_FIXTURE_KNOB", "0", "doc")

        def f():
            return (get_env("MXNET_FIXTURE_KNOB"),
                    os.environ.get("MXNET_FIXTURE_DOC_ONLY"))
    """
    issues = run(src, select=["env-registry"],
                 env_documented={"MXNET_FIXTURE_DOC_ONLY"})
    assert issues == []


def test_env_registry_suppression():
    issues = run("""
        import os

        def f():
            # mxlint: disable=env-registry (third-party launcher knob)
            return os.environ.get("MXNET_FIXTURE_PRIVATE")
    """, select=["env-registry"])
    assert issues == []


# ------------------------------------------------------------------ framework
def test_disable_file_directive():
    issues = run("""
        # mxlint: disable-file=lock-discipline
        _CACHE = {}

        def put(k, v):
            _CACHE[k] = v
    """, select=["lock-discipline"])
    assert issues == []


def test_parse_error_reported_not_crashing():
    issues = lint_sources({"mxnet_tpu/bad.py": "def broken(:\n"})
    assert [i.pass_id for i in issues] == ["parse-error"]


def test_repo_tree_is_clean():
    """The ISSUE-3 acceptance gate: mxlint over mxnet_tpu/ exits 0."""
    issues = lint_paths([os.path.join(REPO, "mxnet_tpu")])
    assert issues == [], "\n".join(str(i) for i in issues)


def test_cli_end_to_end(tmp_path):
    bad = tmp_path / "serving" / "x.py"
    bad.parent.mkdir()
    bad.write_text("_STATE = {}\n\ndef f():\n    _STATE['k'] = 1\n")
    proc = subprocess.run(
        [sys.executable, "-m", "tools.mxlint", str(tmp_path)],
        cwd=REPO, capture_output=True, text=True)
    assert proc.returncode == 1
    assert "lock-discipline" in proc.stdout
    proc = subprocess.run(
        [sys.executable, "-m", "tools.mxlint", "--list-passes"],
        cwd=REPO, capture_output=True, text=True)
    assert proc.returncode == 0
    assert "env-registry" in proc.stdout


def test_cli_nonexistent_path_is_an_error_not_clean():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.mxlint", "mxnte_tpu_typo/"],
        cwd=REPO, capture_output=True, text=True)
    assert proc.returncode == 2
    assert "not found" in proc.stderr
    assert "clean" not in proc.stdout
