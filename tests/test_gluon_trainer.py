"""Trainer/optimizer integration tests (reference:
tests/python/unittest/test_gluon_trainer.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn


def _net():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu", in_units=8))
        net.add(nn.Dense(4, in_units=16))
    return net


def _step(net, trainer, x, y):
    loss_fn = gluon.loss.L2Loss()
    with autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()
    trainer.step(x.shape[0])
    return float(loss.mean().asscalar())


@pytest.mark.parametrize("opt,kw", [
    ("sgd", {"learning_rate": 0.1}),
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}),
    ("adam", {"learning_rate": 1e-2}),
    ("adamw", {"learning_rate": 1e-2}),
    ("nag", {"learning_rate": 0.05, "momentum": 0.9}),
    ("rmsprop", {"learning_rate": 1e-2}),
    ("lamb", {"learning_rate": 1e-2}),
])
def test_trainer_decreases_loss(opt, kw):
    net = _net()
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), opt, kw)
    x = nd.random.uniform(shape=(16, 8))
    y = nd.random.uniform(shape=(16, 4))
    first = _step(net, trainer, x, y)
    for _ in range(10):
        last = _step(net, trainer, x, y)
    assert last < first, f"{opt}: {first} -> {last}"


def test_trainer_lr_scheduler():
    from mxnet_tpu.lr_scheduler import FactorScheduler
    net = _net()
    net.initialize()
    sched = FactorScheduler(step=2, factor=0.5, base_lr=0.1)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "lr_scheduler": sched})
    x = nd.random.uniform(shape=(4, 8))
    y = nd.random.uniform(shape=(4, 4))
    for _ in range(6):
        _step(net, trainer, x, y)
    assert trainer.learning_rate < 0.1


def test_trainer_save_load_states(tmp_path):
    net = _net()
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    x = nd.random.uniform(shape=(4, 8))
    y = nd.random.uniform(shape=(4, 4))
    _step(net, trainer, x, y)
    fname = str(tmp_path / "trainer.states")
    trainer.save_states(fname)
    trainer2 = gluon.Trainer(net.collect_params(), "sgd",
                             {"learning_rate": 0.1, "momentum": 0.9})
    trainer2.load_states(fname)
    # momentum state carried over
    s1 = trainer._updater.states
    s2 = trainer2._updater.states
    for k in s1:
        if s1[k] is None:
            continue
        a = s1[k] if not isinstance(s1[k], tuple) else s1[k][0]
        b = s2[k] if not isinstance(s2[k], tuple) else s2[k][0]
        assert np.allclose(a.asnumpy(), b.asnumpy())


def test_zero_grad():
    net = _net()
    net.initialize()
    x = nd.random.uniform(shape=(4, 8))
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    params = net.collect_params()
    params.zero_grad()
    for _, p in params.items():
        assert np.abs(p.grad().asnumpy()).sum() == 0


def test_gradient_accumulation():
    net = _net()
    net.initialize()
    for _, p in net.collect_params().items():
        p.grad_req = "add"
    x = nd.random.uniform(shape=(4, 8))
    with autograd.record():
        net(x).sum().backward()
    g1 = net[0].weight.grad().asnumpy().copy()
    with autograd.record():
        net(x).sum().backward()
    g2 = net[0].weight.grad().asnumpy()
    assert np.allclose(g2, 2 * g1, rtol=1e-4, atol=1e-5)


def test_clip_global_norm():
    arrays = [nd.ones((2, 2)) * 3, nd.ones((3,)) * 4]
    total = gluon.utils.clip_global_norm(arrays, 1.0)
    new_total = sum(float(a.norm().asscalar()) ** 2
                    for a in arrays) ** 0.5
    assert new_total < 1.01
    assert total > 1.0


def test_split_and_load():
    data = nd.arange(12).reshape((6, 2))
    ctxs = [mx.cpu(0), mx.cpu(0)]
    parts = gluon.utils.split_and_load(data, ctxs)
    assert len(parts) == 2
    assert parts[0].shape == (3, 2)
    got = np.concatenate([p.asnumpy() for p in parts])
    assert np.allclose(got, data.asnumpy())


def test_clip_global_norm_async():
    arrays = [nd.ones((2, 2)) * 3, nd.ones((3,)) * 4]
    total = gluon.utils.clip_global_norm(arrays, 1.0, check_isfinite=False)
    assert isinstance(total, nd.NDArray)
    assert float(total.asscalar()) > 1.0
    new_total = sum(float(a.norm().asscalar()) ** 2
                    for a in arrays) ** 0.5
    assert new_total < 1.01
    # below the threshold: arrays unchanged
    small = [nd.ones((2,)) * 0.1]
    gluon.utils.clip_global_norm(small, 10.0)
    assert np.allclose(small[0].asnumpy(), 0.1)


@pytest.mark.parametrize("optname,kw", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-3}),
    ("adam", {"learning_rate": 0.01, "wd": 1e-3}),
    ("adamw", {"learning_rate": 0.01, "wd": 0.01}),
])
def test_fused_update_matches_unfused(optname, kw):
    def build():
        net = nn.Sequential()
        net.add(nn.Dense(16, in_units=8), nn.Dense(4, in_units=16))
        net.initialize(mx.init.Xavier(rnd_type="gaussian"))
        return net

    mx.random.seed(42)
    net_a = build()
    mx.random.seed(42)
    net_b = build()
    tr_a = gluon.Trainer(net_a.collect_params(), optname, dict(kw))
    tr_b = gluon.Trainer(net_b.collect_params(), optname, dict(kw))
    tr_b._optimizer.fused = False          # force per-param reference path
    assert tr_a._fused_eligible()

    x = nd.random.uniform(shape=(8, 8))
    y = nd.random.uniform(shape=(8, 4))
    for step in range(4):
        for net, tr in ((net_a, tr_a), (net_b, tr_b)):
            with autograd.record():
                l = ((net(x) - y) ** 2).mean()
            l.backward()
            tr.step(1)
    # zip in insertion order: global name-prefix counters (dense9_ vs
    # dense10_) sort differently lexically, so sorted() can misalign
    for (na, pa), (nb, pb) in zip(
            net_a.collect_params().items(),
            net_b.collect_params().items()):
        assert np.allclose(pa.data().asnumpy(), pb.data().asnumpy(),
                           rtol=1e-5, atol=1e-6), (optname, na)
    # one compiled program, reused across the 4 steps
    assert len(tr_a._fused_progs) == 1


def test_fused_update_multi_precision_bf16():
    net = nn.Sequential()
    net.add(nn.Dense(16, in_units=8), nn.Dense(4, in_units=16))
    net.initialize()
    net.cast("bfloat16")
    tr = gluon.Trainer(net.collect_params(), "adamw",
                       {"learning_rate": 0.05, "multi_precision": True})
    assert tr._fused_eligible()
    x = nd.random.uniform(shape=(8, 8)).astype("bfloat16")
    y = nd.ones((8, 4)).astype("bfloat16")
    losses = []
    for _ in range(20):
        with autograd.record():
            l = ((net(x) - y) ** 2).mean()
        l.backward()
        tr.step(1)
        losses.append(float(l.asscalar()))
    assert losses[-1] < losses[0] * 0.5
    # fp32 master weights survive in the updater state
    st = tr._updater.states[0]
    assert isinstance(st, tuple) and str(st[0].dtype) == "float32"


def test_fused_update_ineligible_falls_back():
    net = nn.Sequential()
    net.add(nn.Dense(4, in_units=8))
    net.initialize()
    for p in net.collect_params().values():
        p.grad_req = "add"
    tr = gluon.Trainer(net.collect_params(), "adam", {"learning_rate": 0.1})
    assert not tr._fused_eligible()
    x = nd.random.uniform(shape=(2, 8))
    with autograd.record():
        net(x).sum().backward()
    tr.step(1)          # per-param path still works


def test_clip_global_norm_one_program_across_thresholds():
    """Regression for the ISSUE-5 recompile-churn sweep finding:
    max_norm used to ride in static_argnums, so a clipping *schedule*
    (a new threshold every step) compiled a new XLA program per value.
    It is traced now — distinct thresholds must share one program."""
    def clip(max_norm):
        arrays = [nd.ones((2, 2)) * 3, nd.ones((3,)) * 4]
        gluon.utils.clip_global_norm(arrays, max_norm)

    clip(1.0)       # may genuinely compile (first time for these shapes)
    baseline = gluon.utils._clip_global_norm_jit._cache_size()
    clip(2.0)
    clip(3.5)
    after = gluon.utils._clip_global_norm_jit._cache_size()
    assert after == baseline, (
        f"{after - baseline} extra program(s) compiled for "
        f"threshold-only changes")


def test_clip_global_norm_nan_preserves_arrays():
    a = nd.array([1.0, np.nan])
    b = nd.array([2.0, 3.0])
    with pytest.warns(UserWarning):
        total = gluon.utils.clip_global_norm([a, b], 1.0)
    assert not (total < float("inf"))
    got = a.asnumpy()
    assert got[0] == 1.0 and np.isnan(got[1])      # untouched, not poisoned
    assert np.allclose(b.asnumpy(), [2.0, 3.0])


class TestFusedHybridStep:
    """The deferred backward+optimizer fusion (VERDICT r2 item 3): the
    three-call recipe compiles to one program in Trainer.step, with
    semantics identical to the eager path."""

    def _build(self, seed):
        from mxnet_tpu.gluon import nn
        mx.random.seed(seed)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu", in_units=4))
        net.add(nn.BatchNorm(in_channels=16))
        net.add(nn.Dense(1, in_units=16))
        net.initialize(mx.init.Xavier())

        class LossBlock(gluon.HybridBlock):
            def __init__(self, inner, **kw):
                super().__init__(**kw)
                with self.name_scope():
                    self.inner = inner

            def hybrid_forward(self, F, x, y):
                return ((self.inner(x) - y) ** 2).mean()

        blk = LossBlock(net)
        blk.hybridize(static_alloc=True)
        return net, blk

    def test_matches_eager_path(self, monkeypatch):
        # deterministic inputs: fixed RandomState AND fixed global seeds
        # (the autouse conftest seed can be overridden via
        # MXNET_TEST_SEED; this test's numbers must not depend on it)
        np.random.seed(0)
        mx.random.seed(0)
        rng = np.random.RandomState(0)
        X, Y = rng.randn(8, 4).astype(np.float32), \
            rng.randn(8, 1).astype(np.float32)
        out = {}
        for knob in ("0", "1"):
            monkeypatch.setenv("MXNET_FUSED_HYBRID_STEP", knob)
            net, blk = self._build(21)
            tr = gluon.Trainer(net.collect_params(), "adam",
                               {"learning_rate": 1e-2})
            losses = []
            for _ in range(5):
                x, y = nd.array(X), nd.array(Y)
                with autograd.record():
                    l = blk(x, y)
                l.backward()
                tr.step(8)
                losses.append(float(l.asnumpy()))
            out[knob] = (losses,
                         [p.data().asnumpy().copy()
                          for p in net.collect_params().values()],
                         [p.grad().asnumpy().copy()
                          for p in net.collect_params().values()
                          if p.grad_req != "null"])
        # float32-appropriate bounds: the fused and eager paths run
        # differently-ordered XLA reductions (BatchNorm statistics,
        # adam moment updates), so per-element drift accumulates to
        # ~1e-4 relative over 5 steps — well above the old 1e-5/1e-6
        # bounds that made this flake, far below anything that would
        # indicate a semantic divergence.
        np.testing.assert_allclose(out["0"][0], out["1"][0],
                                   rtol=1e-4, atol=1e-5)
        for a, b in zip(out["0"][1], out["1"][1]):
            np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-4)
        for a, b in zip(out["0"][2], out["1"][2]):
            np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-4)

    def test_grad_read_flushes_pending(self):
        rng = np.random.RandomState(1)
        net, blk = self._build(22)
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 1e-2})
        x = nd.array(rng.randn(8, 4).astype(np.float32))
        y = nd.array(rng.randn(8, 1).astype(np.float32))
        with autograd.record():
            l = blk(x, y)
        l.backward()
        assert autograd.peek_pending() is not None
        p = next(iter(net.collect_params().values()))
        g = p.grad().asnumpy()              # read flushes
        assert autograd.peek_pending() is None
        assert np.isfinite(g).all()
        tr.step(8)                          # eager fallback still works

    def test_input_grads_via_fused_step(self):
        rng = np.random.RandomState(2)
        net, blk = self._build(23)
        tr = gluon.Trainer(net.collect_params(), "adam",
                           {"learning_rate": 1e-2})
        x = nd.array(rng.randn(8, 4).astype(np.float32))
        y = nd.array(rng.randn(8, 1).astype(np.float32))
        x.attach_grad()
        with autograd.record():
            l = blk(x, y)
        l.backward()
        tr.step(8)
        assert np.abs(x.grad.asnumpy()).sum() > 0

    def test_waitall_flushes(self):
        rng = np.random.RandomState(3)
        net, blk = self._build(24)
        gluon.Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 1e-2})
        x = nd.array(rng.randn(8, 4).astype(np.float32))
        y = nd.array(rng.randn(8, 1).astype(np.float32))
        with autograd.record():
            l = blk(x, y)
        l.backward()
        assert autograd.peek_pending() is not None
        mx.waitall()
        assert autograd.peek_pending() is None

    def test_deferred_forward_compiles_one_program(self):
        """From the second recorded call on, record/backward/step runs
        as ONE fwd+bwd+opt program: the 'full' entry appears in the
        step-program cache and the loss is only materialized by step."""
        rng = np.random.RandomState(9)
        net, blk = self._build(31)
        tr = gluon.Trainer(net.collect_params(), "adam",
                           {"learning_rate": 1e-2})
        x = nd.array(rng.randn(8, 4).astype(np.float32))
        y = nd.array(rng.randn(8, 1).astype(np.float32))
        losses = []
        for it in range(3):
            with autograd.record():
                l = blk(x, y)
            if it > 0:
                # deferred: the loss is an unmaterialized lazy array
                assert l._lazy_cb is not None
            l.backward()
            tr.step(8)
            # step materialized it (full fusion) or flushed (fallback)
            assert l._lazy_cb is None
            losses.append(float(l.asnumpy()))
        assert any(isinstance(k, tuple) and k and k[0] == "full"
                   for k in tr._fused_step_progs), \
            "full fwd+bwd+opt fusion never engaged"
        assert losses[0] > losses[-1]     # it's really training
        # grads were written (contract: .grad stays observable)
        for p in net.collect_params().values():
            if p.grad_req != "null":
                assert np.isfinite(p.grad().asnumpy()).all()

    def test_deferred_forward_read_before_step_materializes(self):
        """Reading the loss between backward() and step() falls back to
        the standalone forward with identical numbers."""
        rng = np.random.RandomState(10)
        X = rng.randn(8, 4).astype(np.float32)
        Y = rng.randn(8, 1).astype(np.float32)
        out = {}
        for read_early in (False, True):
            net, blk = self._build(32)
            tr = gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 1e-2})
            vals = []
            for _ in range(3):
                x, y = nd.array(X), nd.array(Y)
                with autograd.record():
                    l = blk(x, y)
                l.backward()
                if read_early:
                    vals.append(float(l.asscalar()))   # materializes
                tr.step(8)
                if not read_early:
                    vals.append(float(l.asscalar()))
            out[read_early] = (vals, [p.data().asnumpy().copy()
                                      for p in
                                      net.collect_params().values()])
        np.testing.assert_allclose(out[True][0], out[False][0],
                                   rtol=1e-5, atol=1e-6)
        for a, b in zip(out[True][1], out[False][1]):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)

    def test_hoisted_grad_alias_sees_fresh_grads(self):
        """Grad-buffer aliases hoisted out of the loop (``grads =
        [p.grad() for p in params]``) must observe THIS step's gradients
        when read between backward() and step() — the deferred tape
        flushes on wait_to_read/asnumpy of a pending grad destination."""
        rng = np.random.RandomState(5)
        net, blk = self._build(27)
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 1e-2})
        params = [p for p in net.collect_params().values()
                  if p.grad_req != "null"]
        x = nd.array(rng.randn(8, 4).astype(np.float32))
        y = nd.array(rng.randn(8, 1).astype(np.float32))
        with autograd.record():
            l = blk(x, y)
        l.backward()
        tr.step(8)
        grads = [p.grad() for p in params]          # hoisted aliases
        stale = [g.asnumpy().copy() for g in grads]
        x2 = nd.array(3 * rng.randn(8, 4).astype(np.float32))
        y2 = nd.array(3 * rng.randn(8, 1).astype(np.float32))
        with autograd.record():
            l = blk(x2, y2)
        l.backward()
        assert autograd.peek_pending() is not None
        fresh = [g.asnumpy() for g in grads]        # must flush first
        assert autograd.peek_pending() is None
        assert any(not np.allclose(a, b)
                   for a, b in zip(stale, fresh))
        tr.step(8)                                  # eager fallback works

    def test_hoisted_grad_alias_as_op_input_flushes(self):
        """Consuming a pending grad buffer as an op INPUT (the
        clip_global_norm pattern) flushes the deferred backward too."""
        rng = np.random.RandomState(6)
        net, blk = self._build(28)
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 1e-2})
        params = [p for p in net.collect_params().values()
                  if p.grad_req != "null"]
        x = nd.array(rng.randn(8, 4).astype(np.float32))
        y = nd.array(rng.randn(8, 1).astype(np.float32))
        with autograd.record():
            l = blk(x, y)
        l.backward()
        tr.step(8)
        grads = [p.grad() for p in params]
        stale0 = grads[0].asnumpy().copy()
        x2 = nd.array(3 * rng.randn(8, 4).astype(np.float32))
        y2 = nd.array(3 * rng.randn(8, 1).astype(np.float32))
        with autograd.record():
            l = blk(x2, y2)
        l.backward()
        assert autograd.peek_pending() is not None
        scaled = grads[0] * 1.0                     # op input → flush
        assert autograd.peek_pending() is None
        assert not np.allclose(scaled.asnumpy(), stale0)
        tr.step(8)

    def test_failed_fused_step_restores_num_update(self):
        """A failed fused step rolls back num_update alongside the
        per-index counts — lr_scheduler/_get_lr must not run one step
        ahead after a failure (ADVICE r3)."""
        import jax
        import mxnet_tpu.base as base
        rng = np.random.RandomState(7)
        net, blk = self._build(29)
        tr = gluon.Trainer(net.collect_params(), "adam",
                           {"learning_rate": 1e-2})
        x = nd.array(rng.randn(8, 4).astype(np.float32))
        y = nd.array(rng.randn(8, 1).astype(np.float32))
        with autograd.record():
            l = blk(x, y)
        l.backward()
        tr.step(8)              # first call: eager fwd + bwd-only entry
        with autograd.record():
            l = blk(x, y)
        l.backward()
        tr.step(8)              # deferred fwd: builds the FULL entry
        o = tr._optimizer
        counts_before = dict(o._index_update_count)
        num_update_before = o.num_update

        entry = next(e for k, e in tr._fused_step_progs.items()
                     if isinstance(k, tuple) and k and k[0] == "full")

        def failing_prog(*args):
            # signature-agnostic: works for both the two-program entry
            # (res, cots, weights, ...) and the one-program full-fusion
            # entry (key, nonparams, cots, weights, states, ...)
            for a in jax.tree_util.tree_leaves(args):
                if hasattr(a, "delete"):
                    a.delete()                      # donated + consumed
            raise RuntimeError("synthetic post-dispatch failure")

        real_prog = entry["prog"]
        entry["prog"] = failing_prog
        with autograd.record():
            l = blk(x, y)
        l.backward()
        with pytest.raises(base.MXNetError, match="donated"):
            tr.step(8)
        assert dict(o._index_update_count) == counts_before
        assert o.num_update == num_update_before
        entry["prog"] = real_prog

    def test_broken_fusion_no_double_count_advance(self):
        """A negative-cached (broken) fused signature must not
        double-advance optimizer update counts: the early return happens
        before bookkeeping, the eager path advances once."""
        rng = np.random.RandomState(4)
        net, blk = self._build(25)
        tr = gluon.Trainer(net.collect_params(), "adam",
                           {"learning_rate": 1e-2})
        x = nd.array(rng.randn(8, 4).astype(np.float32))
        y = nd.array(rng.randn(8, 1).astype(np.float32))
        with autograd.record():
            l = blk(x, y)
        l.backward()
        tr.step(8)                                  # builds fused entry
        o = tr._optimizer
        counts1 = dict(o._index_update_count)
        cache = tr._fused_step_progs
        for entry in cache.values():
            entry["broken"] = True                  # simulate neg-cache
        with autograd.record():
            l = blk(x, y)
        l.backward()
        tr.step(8)                                  # eager fallback
        counts2 = dict(o._index_update_count)
        assert all(counts2[k] == counts1[k] + 1 for k in counts1), \
            (counts1, counts2)

    def test_lr_change_and_frozen_param_through_fusion(self):
        """set_learning_rate mid-training reaches the fused program, and
        frozen (grad_req='null') params pass through untouched."""
        from mxnet_tpu.gluon import nn
        rng = np.random.RandomState(6)
        mx.random.seed(26)
        net = nn.HybridSequential()
        net.add(nn.Dense(8, activation="relu", in_units=4))
        net.add(nn.Dense(1, in_units=8))
        net.initialize(mx.init.Xavier())
        first = net[0] if hasattr(net, "__getitem__") else None
        frozen_p = next(iter(net.collect_params().values()))
        frozen_p.grad_req = "null"
        w0 = frozen_p.data().asnumpy().copy()

        class LB(gluon.HybridBlock):
            def __init__(self, inner, **kw):
                super().__init__(**kw)
                with self.name_scope():
                    self.inner = inner

            def hybrid_forward(self, F, x, y):
                return ((self.inner(x) - y) ** 2).mean()

        blk = LB(net)
        blk.hybridize(static_alloc=True)
        tr = gluon.Trainer(
            [p for p in net.collect_params().values()
             if p.grad_req != "null"], "sgd", {"learning_rate": 0.1})
        x = nd.array(rng.randn(8, 4).astype(np.float32))
        y = nd.array(rng.randn(8, 1).astype(np.float32))

        def step():
            with autograd.record():
                l = blk(x, y)
            l.backward()
            tr.step(8)
            return float(l.asnumpy())

        step()
        tuned = next(p for p in net.collect_params().values()
                     if p.grad_req != "null")
        before = tuned.data().asnumpy().copy()
        tr.set_learning_rate(0.0)       # zero LR: params must FREEZE
        step()
        np.testing.assert_allclose(tuned.data().asnumpy(), before,
                                   rtol=1e-6)
        tr.set_learning_rate(0.1)
        step()
        assert np.abs(tuned.data().asnumpy() - before).max() > 0
        np.testing.assert_allclose(frozen_p.data().asnumpy(), w0)
