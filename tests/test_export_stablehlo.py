"""Deployment-artifact proof (VERDICT r2 missing #3 / docs/frontends.md
§2): an exported StableHLO artifact must execute OUTSIDE the framework —
a subprocess that imports only jax+numpy reproduces the block's outputs.

Exports are slow (jit lowering + serialization), so the static and
dynamic artifacts are built ONCE per module and shared by the tests.
"""
import json
import os
import shutil
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, deploy
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn


def _build_net():
    mx.random.seed(7)
    net = nn.HybridSequential(prefix="shlo_net_")
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu", in_units=8))
        net.add(nn.BatchNorm(in_channels=16))
        net.add(nn.Dense(4, in_units=16))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    return net


@pytest.fixture(scope="module")
def static_art(tmp_path_factory):
    """One static export shared module-wide: (net, x, path-prefix)."""
    net = _build_net()
    x = nd.random.uniform(shape=(5, 8))
    path = str(tmp_path_factory.mktemp("shlo_static") / "model")
    deploy.export_stablehlo(net, x, path=path, emit_text=True)
    return net, x, path


@pytest.fixture(scope="module")
def dynamic_art(tmp_path_factory):
    """One dynamic-batch export shared module-wide: (net, path-prefix)."""
    net = _build_net()
    x = nd.random.uniform(shape=(5, 8))
    path = str(tmp_path_factory.mktemp("shlo_dyn") / "dyn")
    deploy.export_stablehlo(net, x, path=path, dynamic_batch=True,
                            version=3)
    return net, path


def test_artifact_runs_without_framework(static_art, tmp_path):
    net, x, path = static_art
    artifact = path + ".shlo"
    ref = net(x).asnumpy()                      # inference outputs
    assert os.path.exists(artifact)
    assert os.path.exists(path + ".json")
    # the MLIR text is genuine StableHLO
    text = open(path + ".stablehlo.txt").read()
    assert "stablehlo" in text and "func.func public @main" in text
    manifest = json.load(open(path + ".json"))
    assert manifest["inputs"][0]["shape"] == [5, 8]

    np.save(str(tmp_path / "x.npy"), x.asnumpy())
    np.save(str(tmp_path / "ref.npy"), ref)

    # serving-side consumer: ONLY jax + numpy.  A poisoned meta-importer
    # makes any mxnet_tpu import a hard failure, proving independence.
    runner = textwrap.dedent("""
        import sys
        class _Block:
            def find_module(self, name, path=None):
                if name.split('.')[0] == 'mxnet_tpu':
                    raise ImportError('framework import attempted at '
                                      'serving time: ' + name)
                return None
        sys.meta_path.insert(0, _Block())
        import numpy as np
        from jax import export
        blob = bytearray(open(sys.argv[1], 'rb').read())
        fn = export.deserialize(blob)
        x = np.load(sys.argv[2])
        out = np.asarray(fn.call(x))
        np.save(sys.argv[3], out)
        print('served', out.shape)
    """)
    out_path = str(tmp_path / "out.npy")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PYTHONPATH", None)                 # no repo on the path
    proc = subprocess.run(
        [sys.executable, "-c", runner, artifact,
         str(tmp_path / "x.npy"), out_path],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=str(tmp_path))                      # not the repo root
    assert proc.returncode == 0, proc.stderr[-2000:]
    served = np.load(out_path)
    np.testing.assert_allclose(served, ref, rtol=1e-5, atol=1e-5)


def test_load_stablehlo_roundtrip(static_art, tmp_path):
    net, x, path = static_art
    fn = deploy.load_stablehlo(path + ".shlo")
    np.testing.assert_allclose(np.asarray(fn.call(x.asnumpy())),
                               net(x).asnumpy(), rtol=1e-5, atol=1e-5)
    with pytest.raises(MXNetError, match="no artifact"):
        deploy.load_stablehlo(str(tmp_path / "missing.shlo"))


def test_manifest_validation_roundtrip(static_art, tmp_path):
    """load_stablehlo validates calls against the .json manifest: a
    shape/dtype mistake raises a clear MXNetError naming the manifest,
    not an opaque PJRT failure; matching inputs still round-trip."""
    net, x, path = static_art
    fn = deploy.load_stablehlo(path + ".shlo")
    assert fn.manifest["inputs"] == [{"shape": [5, 8],
                                     "dtype": "float32"}]
    assert fn.manifest["outputs"][0]["shape"] == [5, 4]
    assert not fn.dynamic_batch

    # the good path still round-trips (NDArray or numpy)
    np.testing.assert_allclose(np.asarray(fn.call(x)),
                               net(x).asnumpy(), rtol=1e-5, atol=1e-5)
    with pytest.raises(MXNetError, match="dtype mismatch"):
        fn.call(x.asnumpy().astype(np.float64))
    with pytest.raises(MXNetError, match="rank mismatch"):
        fn.call(x.asnumpy()[0])
    with pytest.raises(MXNetError, match="shape mismatch at axis 0"):
        fn.call(np.ones((3, 8), np.float32))
    with pytest.raises(MXNetError, match="expected 1 input"):
        fn.call(x.asnumpy(), x.asnumpy())
    # the error names the manifest file, so it is actionable
    with pytest.raises(MXNetError, match="model.json"):
        fn.call(np.ones((5, 9), np.float32))

    # an artifact without a manifest (pre-manifest export) stays
    # loadable — copy the .shlo away from its .json
    bare = str(tmp_path / "bare.shlo")
    shutil.copyfile(path + ".shlo", bare)
    fn2 = deploy.load_stablehlo(bare)
    assert fn2.manifest is None
    np.testing.assert_allclose(np.asarray(fn2.call(x.asnumpy())),
                               net(x).asnumpy(), rtol=1e-5, atol=1e-5)


def test_validate_manifest_structural_checks():
    """The ISSUE-5 static half: a manifest is soundness-checked at
    export AND load time, so a malformed or batch-collapsing signature
    fails with an actionable error instead of a mid-request failure."""
    good = {"inputs": [{"shape": [None, 8], "dtype": "float32"}],
            "outputs": [{"shape": [None, 4], "dtype": "float32"}],
            "version": 3, "dynamic_batch": True}
    assert deploy.validate_manifest(dict(good)) == good

    with pytest.raises(MXNetError, match="missing 'inputs'"):
        deploy.validate_manifest({"outputs": []})
    bad = dict(good, inputs=[{"shape": [None, -2], "dtype": "float32"}])
    with pytest.raises(MXNetError, match="nonnegative ints or null"):
        deploy.validate_manifest(bad)
    bad = dict(good, inputs=[{"shape": [None, 8], "dtype": "float99"}])
    with pytest.raises(MXNetError, match="unknown dtype"):
        deploy.validate_manifest(bad)
    bad = dict(good, version="three")
    with pytest.raises(MXNetError, match="version must be an int"):
        deploy.validate_manifest(bad)
    bad = dict(good, inputs=[{"shape": "nope", "dtype": "float32"}])
    with pytest.raises(MXNetError, match="signature entry"):
        deploy.validate_manifest(bad)


def test_validate_manifest_dynamic_batch_inference_checks():
    """With dynamic_batch, every input AND output must be batch-major
    with a symbolic (null) leading dim — a concrete leading dim means
    the block collapsed the batch axis and serving could not un-pad."""
    m = {"inputs": [{"shape": [4, 8], "dtype": "float32"}],
         "outputs": [{"shape": [None, 4], "dtype": "float32"}],
         "dynamic_batch": True}
    with pytest.raises(MXNetError, match="symbolic batch dim"):
        deploy.validate_manifest(m)
    m = {"inputs": [{"shape": [None, 8], "dtype": "float32"}],
         "outputs": [{"shape": [4], "dtype": "float32"}],
         "dynamic_batch": True}
    with pytest.raises(MXNetError, match="not .*batch-major|batch-major"):
        deploy.validate_manifest(m)
    # a global reduce to a scalar output is the canonical collapse
    m = {"inputs": [{"shape": [None, 8], "dtype": "float32"}],
         "outputs": [{"shape": [], "dtype": "float32"}],
         "dynamic_batch": True}
    with pytest.raises(MXNetError, match="batch"):
        deploy.validate_manifest(m)
    # static manifests are free to have concrete leading dims
    m = {"inputs": [{"shape": [4, 8], "dtype": "float32"}],
         "outputs": [{"shape": [4], "dtype": "float32"}]}
    deploy.validate_manifest(m)


def test_validate_signature_guards_add_function():
    """A hand-written serving signature gets the same structural check
    an exported manifest does, at registration time."""
    from mxnet_tpu.serving import ModelRepository

    deploy.validate_signature([{"shape": [None, 8], "dtype": "float32"}])
    with pytest.raises(MXNetError, match="list of .*entries"):
        deploy.validate_signature({"shape": [8]})
    with pytest.raises(MXNetError, match="unknown dtype"):
        deploy.validate_signature([{"shape": [8], "dtype": "floatx"}])

    repo = ModelRepository()
    with pytest.raises(MXNetError, match="add_function\\('bad'\\)"):
        repo.add_function("bad", lambda x: x,
                          [{"shape": [None, "eight"], "dtype": "float32"}])
    assert repo.models() == [] or "bad" not in repo.models()
    # dynamic_batch (the default) demands a symbolic leading dim at
    # registration — a concrete one would mis-split rows at un-pad time
    with pytest.raises(MXNetError, match="concrete leading dimension"):
        repo.add_function("batchy", lambda x: x,
                          [{"shape": [4, 8], "dtype": "float32"}])
    repo.add_function("batchy", lambda x: x,
                      [{"shape": [4, 8], "dtype": "float32"}],
                      dynamic_batch=False)        # static entries may


def test_rejected_export_leaves_no_orphan_artifact(tmp_path):
    """A dynamic_batch export whose block collapses the batch axis must
    fail *before* anything is written: an orphan .shlo without its
    manifest would later load with zero validation."""
    from mxnet_tpu import gluon

    class Collapse(gluon.HybridBlock):
        def hybrid_forward(self, F, x):
            return x.sum()

    block = Collapse()
    block.initialize()
    x = nd.random.uniform(shape=(3, 8))
    block(x)
    path = str(tmp_path / "collapse")
    with pytest.raises(MXNetError, match="batch"):
        deploy.export_stablehlo(block, x, path=path, dynamic_batch=True)
    assert not os.path.exists(path + ".shlo")
    assert not os.path.exists(path + ".json")


def test_dynamic_batch_export_serves_any_batch(dynamic_art):
    """dynamic_batch=True leaves the batch dimension symbolic: one
    artifact answers every batch size (the serving subsystem's shape
    buckets build on this), and the manifest records the dynamic axis
    as null."""
    net, path = dynamic_art
    fn = deploy.load_stablehlo(path + ".shlo")
    assert fn.dynamic_batch
    assert fn.manifest["version"] == 3
    assert fn.manifest["inputs"] == [{"shape": [None, 8],
                                      "dtype": "float32"}]
    assert fn.manifest["outputs"][0]["shape"] == [None, 4]
    for n in (1, 3, 8):
        xs = nd.random.uniform(shape=(n, 8))
        np.testing.assert_allclose(np.asarray(fn.call(xs.asnumpy())),
                                   net(xs).asnumpy(),
                                   rtol=1e-5, atol=1e-5)
    # the batch axis is free, every other dimension still validates
    with pytest.raises(MXNetError, match="axis 1"):
        fn.call(np.ones((4, 9), np.float32))


def test_bfloat16_artifact_validates_not_crashes(tmp_path):
    """Extension dtypes (bfloat16, the TPU-native default) must flow
    through manifest validation: a mismatch raises MXNetError, and the
    matching-dtype call serves — not a numpy TypeError on
    np.dtype('bfloat16')."""
    mx.random.seed(11)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(4, in_units=8))
    net.initialize(mx.init.Xavier())
    net.cast("bfloat16")
    net.hybridize()
    x = nd.random.uniform(shape=(3, 8)).astype("bfloat16")
    path = str(tmp_path / "bf16")
    deploy.export_stablehlo(net, x, path=path)
    fn = deploy.load_stablehlo(path + ".shlo")
    assert fn.manifest["inputs"][0]["dtype"] == "bfloat16"
    with pytest.raises(MXNetError, match="dtype mismatch"):
        fn.call(np.ones((3, 8), np.float32))
    got = np.asarray(fn.call(x.asnumpy())).astype(np.float32)
    want = net(x).asnumpy().astype(np.float32)
    np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)


# ------------------------------------------------------------------------
# Quantized artifacts (ISSUE-10): export_stablehlo(quantize=) -> manifest
# v4 quantization block -> digest-validated load -> serving admission.
# One quantized export shared module-wide (exports are slow).
# ------------------------------------------------------------------------
@pytest.fixture(scope="module")
def quant_art(tmp_path_factory):
    """One int8 dynamic-batch export: (net, x, path-prefix)."""
    net = _build_net()
    x = nd.random.uniform(shape=(4, 8))
    net(x)
    path = str(tmp_path_factory.mktemp("shlo_quant") / "net_int8")
    deploy.export_stablehlo(net, x, path=path, dynamic_batch=True,
                            version=1, quantize="int8")
    return net, x, path


def test_quantized_export_manifest_v4(quant_art):
    _net, _x, path = quant_art
    manifest = json.load(open(path + ".json"))
    assert manifest["manifest_version"] == 4
    qb = manifest["quantization"]
    assert qb["mode"] == "int8"
    # only >=2d float tensors quantize (Dense kernels; BatchNorm
    # vectors and biases stay f32)
    names = {w["name"] for w in qb["weights"]}
    assert len(names) == 2 and all("weight" in n for n in names)
    for w in qb["weights"]:
        assert w["dtype"] == "int8" and w["scale"] > 0 and w["elems"] > 0
    calib = qb["calibration"]
    assert calib["examples"] == 4
    assert 0 <= calib["max_rel_err"] < 0.1
    assert isinstance(qb["digest"], str) and len(qb["digest"]) == 64
    # inputs/outputs stay f32 — quantization is a weights-storage
    # property, not a signature change
    assert manifest["inputs"][0]["dtype"] == "float32"


def test_quantized_artifact_roundtrip_within_calibration(quant_art):
    net, x, path = quant_art
    model = deploy.load_stablehlo(path + ".shlo")
    calib = model.quantization["calibration"]
    ref = net(x).asnumpy()
    got = np.asarray(model.call(x.asnumpy()))
    assert np.abs(got - ref).max() <= calib["max_abs_err"] + 1e-6
    # and a batch size the calibration never saw
    x2 = nd.random.uniform(shape=(7, 8))
    got2 = np.asarray(model.call(x2.asnumpy()))
    ref2 = net(x2).asnumpy()
    assert np.abs(got2 - ref2).max() < 10 * calib["max_abs_err"] + 1e-3


def test_quantized_artifact_smaller_than_f32(tmp_path):
    # needs weights big enough that the MLIR container overhead does
    # not drown the 4x constant shrink (the shared fixture net is tiny)
    mx.random.seed(9)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(256, in_units=64, activation="relu"))
        net.add(nn.Dense(16, in_units=256))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    x = nd.random.uniform(shape=(2, 64))
    net(x)
    f32 = str(tmp_path / "f32")
    i8 = str(tmp_path / "i8")
    deploy.export_stablehlo(net, x, path=f32)
    deploy.export_stablehlo(net, x, path=i8, quantize="int8")
    assert os.path.getsize(f32 + ".shlo") \
        > 2.5 * os.path.getsize(i8 + ".shlo")


def test_tampered_scale_rejected_at_load(quant_art, tmp_path):
    _net, _x, path = quant_art
    prefix = str(tmp_path / "tampered")
    shutil.copyfile(path + ".shlo", prefix + ".shlo")
    manifest = json.load(open(path + ".json"))
    manifest["quantization"]["weights"][0]["scale"] *= 2.0
    json.dump(manifest, open(prefix + ".json", "w"))
    with pytest.raises(MXNetError, match="digest mismatch"):
        deploy.load_stablehlo(prefix + ".shlo")


def test_corrupt_scale_values_rejected(quant_art, tmp_path):
    _net, _x, path = quant_art
    manifest = json.load(open(path + ".json"))
    for bad in (-1.0, 0.0, float("nan"), "x"):
        m = json.loads(json.dumps(manifest))
        m["quantization"]["weights"][0]["scale"] = bad
        with pytest.raises(MXNetError):
            deploy.validate_manifest(m)
    # quantization block on a pre-v4 manifest is malformed
    m = json.loads(json.dumps(manifest))
    m["manifest_version"] = 3
    with pytest.raises(MXNetError, match="manifest_version >= 4"):
        deploy.validate_manifest(m)
    # nulling the digest must NOT bypass verification: a present key
    # verifies whatever its value is
    m = json.loads(json.dumps(manifest))
    m["quantization"]["digest"] = None
    with pytest.raises(MXNetError, match="digest mismatch"):
        deploy.validate_manifest(m)
    # mode/dtype disagreement
    m = json.loads(json.dumps(manifest))
    m["quantization"]["weights"][0]["dtype"] = "float8_e4m3fn"
    with pytest.raises(MXNetError, match="disagrees with mode"):
        deploy.validate_manifest(m)


def test_quantized_serving_admission_knobs(quant_art, tmp_path,
                                           monkeypatch):
    from mxnet_tpu import serving
    _net, _x, path = quant_art
    # stripped digest: admitted by validate_manifest (digest optional
    # structurally) but rejected at serving admission by default
    prefix = str(tmp_path / "nodigest")
    shutil.copyfile(path + ".shlo", prefix + ".shlo")
    manifest = json.load(open(path + ".json"))
    del manifest["quantization"]["digest"]
    json.dump(manifest, open(prefix + ".json", "w"))
    repo = serving.ModelRepository()
    with pytest.raises(MXNetError, match="no scale digest"):
        repo.load_artifact("m", prefix + ".shlo")
    monkeypatch.setenv("MXNET_SERVING_QUANT_REQUIRE_DIGEST", "0")
    repo.load_artifact("m", prefix + ".shlo")       # dev-mode admits
    # calibration-error admission bound
    monkeypatch.delenv("MXNET_SERVING_QUANT_REQUIRE_DIGEST")
    monkeypatch.setenv("MXNET_SERVING_QUANT_MAX_REL_ERR", "1e-9")
    with pytest.raises(MXNetError, match="exceeds the admission bound"):
        repo.load_artifact("m2", path + ".shlo")
    monkeypatch.setenv("MXNET_SERVING_QUANT_MAX_REL_ERR", "0.5")
    entry = repo.load_artifact("m2", path + ".shlo")
    assert entry.quantization["mode"] == "int8"


def test_quantized_and_f32_versions_coexist_in_serving(quant_art,
                                                       tmp_path):
    """The tentpole serving criterion: f32 and int8 artifacts of ONE
    model serve side by side through the same bucket machinery, each
    within the per-version program bound, swap switching between
    them."""
    from mxnet_tpu import serving
    net, x, path = quant_art
    f32 = str(tmp_path / "f32v")
    deploy.export_stablehlo(net, x, path=f32, dynamic_batch=True,
                            version=1)
    repo = serving.ModelRepository()
    repo.load_artifact("net", f32 + ".shlo")                 # v1 f32
    repo.load_artifact("net", path + ".shlo", version=2,
                       activate=False)                       # v2 int8
    cfg = serving.ServingConfig(max_batch_size=4, max_latency_us=0)
    srv = serving.ModelServer(repo, cfg)
    try:
        payload = x.asnumpy()
        ref = net(x).asnumpy()
        f32_out = srv.predict("net", payload, timeout=120)
        np.testing.assert_allclose(f32_out, ref, rtol=1e-5, atol=1e-5)
        repo.swap("net", 2)
        q_out = srv.predict("net", payload, timeout=120)
        calib = repo.get("net").quantization["calibration"]
        assert np.abs(q_out - ref).max() <= calib["max_abs_err"] + 1e-6
        # distinct programs per version (uids differ), both bounded
        batcher = srv.batcher
        assert batcher.programs(repo._resolve("net", 1)) >= 1
        assert batcher.programs(repo._resolve("net", 2)) >= 1
        import math
        bound = int(math.ceil(math.log2(cfg.max_batch_size))) + 1
        assert batcher.programs(repo._resolve("net", 2)) <= bound
    finally:
        srv.stop()


def test_fp8_export_roundtrip(tmp_path):
    net = _build_net()
    x = nd.random.uniform(shape=(3, 8))
    net(x)
    path = str(tmp_path / "net_fp8")
    deploy.export_stablehlo(net, x, path=path, dynamic_batch=True,
                            quantize="fp8")
    model = deploy.load_stablehlo(path + ".shlo")
    qb = model.quantization
    assert qb["mode"] == "fp8"
    assert all(w["dtype"] == "float8_e4m3fn" for w in qb["weights"])
    ref = net(x).asnumpy()
    got = np.asarray(model.call(x.asnumpy()))
    assert np.abs(got - ref).max() <= qb["calibration"]["max_abs_err"] \
        + 1e-6


def test_quantize_arg_validated(tmp_path):
    net = _build_net()
    x = nd.random.uniform(shape=(3, 8))
    net(x)
    with pytest.raises(MXNetError, match="'int8' or 'fp8'"):
        deploy.export_stablehlo(net, x, path=str(tmp_path / "bad"),
                                quantize="int4")
