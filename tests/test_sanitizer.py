"""Concurrency-sanitizer tests (MXNET_ENGINE_SANITIZE — ISSUE-3).

The sanitizer is a load-time env knob; these tests flip the module flag
directly so they exercise both modes regardless of how the suite was
launched (CI's sanity_lint job re-runs this file plus the serving tests
with the env var actually set, so the import-time path is covered
there).
"""
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import engine
from mxnet_tpu.base import MXNetError
from mxnet_tpu.serving import ModelRepository, ModelServer, ServingConfig


@pytest.fixture
def sanitize(monkeypatch):
    monkeypatch.setattr(engine, "_SANITIZE", True)
    engine._LOCK_ORDERS.reset()
    yield
    engine._LOCK_ORDERS.reset()


def test_factories_return_plain_primitives_when_off(monkeypatch):
    monkeypatch.setattr(engine, "_SANITIZE", False)
    assert isinstance(engine.make_lock("x"), type(threading.Lock()))
    assert not isinstance(engine.make_condition("x"),
                          engine._SanCondition)


def test_factories_return_sanitized_wrappers_when_on(sanitize):
    lk = engine.make_lock("test.lock")
    assert isinstance(lk, engine._SanLock)
    with lk:
        assert lk.locked()
    assert not lk.locked()
    cond = engine.make_condition("test.cond")
    with cond:
        assert cond.wait(timeout=0.01) is False
        cond.notify_all()


def test_lock_order_inversion_raises_instead_of_deadlocking(sanitize):
    a = engine.make_lock("inv.A")
    b = engine.make_lock("inv.B")
    with a:
        with b:
            pass
    errs = []

    def reversed_order():
        try:
            with b:
                with a:
                    pass
        except MXNetError as e:
            errs.append(str(e))

    t = threading.Thread(target=reversed_order)
    t.start()
    t.join(10)
    assert errs and "lock-order inversion" in errs[0]


def test_consistent_order_is_quiet_across_threads(sanitize):
    a = engine.make_lock("ok.A")
    b = engine.make_lock("ok.B")
    errs = []

    def same_order():
        try:
            for _ in range(50):
                with a:
                    with b:
                        pass
        except MXNetError as e:       # pragma: no cover
            errs.append(e)

    ts = [threading.Thread(target=same_order) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(10)
    assert errs == []


def test_first_time_concurrent_abba_raises_instead_of_deadlocking(
        sanitize):
    """Edges are recorded BEFORE blocking: with a fresh graph, a thread
    blocked in A->B must already have published A->B, so the opposing
    B->A acquirer raises instead of completing the deadlock."""
    a = engine.make_lock("abba.A")
    b = engine.make_lock("abba.B")
    t1_blocked = threading.Event()
    outcome = {}

    def t1():
        with a:
            t1_blocked.set()
            with b:             # blocks: main holds B; edge A->B is
                pass            # already recorded at this point
        outcome["t1"] = "done"

    b.acquire()                 # main takes B first
    t = threading.Thread(target=t1)
    t.start()
    t1_blocked.wait(10)
    import time
    time.sleep(0.1)             # let t1 publish A->B and block on B
    try:
        with pytest.raises(MXNetError, match="lock-order inversion"):
            a.acquire()         # the reverse order: must raise, not hang
    finally:
        b.release()             # unblocks t1
    t.join(10)
    assert outcome.get("t1") == "done"


def test_trylock_does_not_constrain_blocking_acquirers(sanitize):
    """A non-blocking acquire can never deadlock, so holding A and
    trylocking B must not make a blocking B->A order elsewhere raise."""
    a = engine.make_lock("try.A")
    b = engine.make_lock("try.B")
    with a:
        assert b.acquire(blocking=False)
        b.release()
    errs = []

    def blocking_reverse():
        try:
            with b:
                with a:
                    pass
        except MXNetError as e:         # pragma: no cover
            errs.append(e)

    t = threading.Thread(target=blocking_reverse)
    t.start()
    t.join(10)
    assert errs == []


def test_condition_wait_does_not_record_false_edges(sanitize):
    cond = engine.make_condition("wait.cond")
    other = engine.make_lock("wait.other")
    done = []

    def waiter():
        with cond:
            cond.wait(timeout=5)
        # post-wakeup: cond released; taking `other` then cond again
        # must not conflict with the notifier's other->notify path
        with other:
            with cond:
                done.append("waiter")

    t = threading.Thread(target=waiter)
    t.start()
    import time
    time.sleep(0.05)
    with other:
        with cond:
            cond.notify_all()
    t.join(10)
    assert done == ["waiter"]


def test_tracked_array_write_passes_untracked_raises(sanitize):
    arr = mx.nd.ones((2, 2))
    arr += 1                            # normal in-place write: fine
    arr.wait_to_read()
    eng = engine.engine()
    with eng._lock:
        eng._live.pop(id(arr), None)    # simulate an untracked husk
    with pytest.raises(MXNetError, match="not tracking"):
        arr._set_data(arr._data)


def test_serving_roundtrip_under_sanitizer(sanitize):
    """The ISSUE-3 regression: DynamicBatcher/ModelServer shared-state
    discipline holds under concurrent load with lock-order recording and
    tracked-array assertions active."""
    repo = ModelRepository()
    repo.add_function(
        "echo", lambda x: x * 2.0,
        [{"shape": [None, 3], "dtype": "float32"}])
    cfg = ServingConfig(num_workers=2, max_batch_size=8, queue_depth=64)
    outs, errs = [], []

    def client(rows):
        try:
            out = srv.predict("echo", np.ones((rows, 3), np.float32),
                              timeout=30)
            outs.append(out)
        except Exception as e:          # noqa: BLE001
            errs.append(e)

    with ModelServer(repo, cfg) as srv:
        ts = [threading.Thread(target=client, args=(1 + i % 3,))
              for i in range(12)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
        stats = srv.stats()
    assert errs == []
    assert len(outs) == 12 and all((o == 2.0).all() for o in outs)
    assert stats["completed"] == 12
    # hot-swap + unload exercise the repository/batcher lock interplay
    assert srv.stop()


def test_sanitizer_active_reports_module_flag(monkeypatch):
    monkeypatch.setattr(engine, "_SANITIZE", True)
    assert engine.sanitizer_active()
    monkeypatch.setattr(engine, "_SANITIZE", False)
    assert not engine.sanitizer_active()


# ===================================================== thread sanitizer
@pytest.fixture
def thread_sanitize(monkeypatch):
    monkeypatch.setattr(engine, "_SANITIZE", True)
    engine._THREADS.reset()
    yield
    engine._THREADS.reset()


def test_leaked_thread_raises_with_owner_and_site(thread_sanitize):
    release = threading.Event()
    t = engine.make_thread(release.wait, name="leaky",
                           owner="TestOwner")
    t.start()
    try:
        with pytest.raises(MXNetError) as exc:
            engine.check_thread_leaks(grace_s=0.05)
        msg = str(exc.value)
        assert "thread leak" in msg
        assert "leaky" in msg and "TestOwner" in msg
        assert "test_sanitizer.py" in msg       # creation site witness
    finally:
        release.set()
        t.join(5)


def test_joined_thread_is_clean(thread_sanitize):
    t = engine.make_thread(lambda: None, name="quick", owner="TestOwner")
    t.start()
    t.join(5)
    engine.check_thread_leaks(grace_s=0.05)     # no raise


def test_forgotten_thread_is_exempt(thread_sanitize):
    release = threading.Event()
    t = engine.make_thread(release.wait, name="abandoned",
                           owner="TestOwner")
    t.start()
    engine.forget_thread(t, "deliberately abandoned (test)")
    try:
        engine.check_thread_leaks(grace_s=0.05)  # no raise
        rows = engine.thread_registry()
        assert any(r["name"] == "abandoned" and r["abandoned"]
                   for r in rows)
    finally:
        release.set()
        t.join(5)


def test_grace_covers_a_stopping_thread(thread_sanitize):
    evt = threading.Event()
    t = engine.make_thread(lambda: evt.wait(0.1), name="stopping",
                           owner="TestOwner")
    t.start()
    # still alive at call time; exits within the grace window
    engine.check_thread_leaks(grace_s=5.0)
    assert not t.is_alive()


def test_make_thread_off_path_is_plain_and_unregistered(monkeypatch):
    monkeypatch.setattr(engine, "_SANITIZE", False)
    engine._THREADS.reset()
    t = engine.make_thread(lambda: None, name="plain", owner="X")
    assert isinstance(t, threading.Thread) and t.daemon
    t.start()
    t.join(5)
    assert engine.thread_registry() == []
    engine.check_thread_leaks()                  # no-op, no raise


def test_thread_registry_rows_shape(thread_sanitize):
    release = threading.Event()
    t = engine.make_thread(release.wait, name="rowed", owner="Owner(x)")
    t.start()
    try:
        rows = engine.thread_registry()
        (row,) = [r for r in rows if r["name"] == "rowed"]
        assert row["owner"] == "Owner(x)"
        assert row["daemon"] is True
        assert row["age_s"] >= 0.0
        assert "tests/test_sanitizer.py" in row["site"] \
            or "test_sanitizer.py" in row["site"]
    finally:
        release.set()
        t.join(5)


# ---------------------------------------------------------------------------
# Eraser-style lockset race sanitizer (engine.watch_races — ISSUE-20)
# ---------------------------------------------------------------------------

class _Counter:
    """Two-thread shared counter the race tests seed; fresh subclass
    per test so the once-per-class __setattr__ wrap never leaks state
    between tests."""

    def __init__(self, lock=None):
        self.n = 0
        self.flag = False
        self._lock = lock


def _second_thread_write(obj, lock=None, field="n"):
    """One unlocked (or locked) += from a second thread; returns any
    MXNetError it raised.  The Eraser state machine flags on the second
    thread's FIRST write, so no schedule luck is involved."""
    errs = []

    def work():
        try:
            if lock is not None:
                with lock:
                    setattr(obj, field, getattr(obj, field) + 1)
            else:
                setattr(obj, field, getattr(obj, field) + 1)
        except MXNetError as e:
            errs.append(e)

    t = threading.Thread(target=work, name="race-writer")
    t.start()
    t.join(10)
    return errs


def test_race_sanitizer_catches_unlocked_two_thread_write(sanitize):
    class C(_Counter):
        pass

    obj = engine.watch_races(C())
    obj.n += 1                          # main thread owns the field
    errs = _second_thread_write(obj)
    assert len(errs) == 1
    msg = str(errs[0])
    assert "data race on C.n" in msg
    assert "race-writer" in msg         # the second writer, by name
    assert "MainThread" in msg          # the first writer, by name
    assert msg.count("test_sanitizer.py") >= 2   # both write stacks
    assert "shared-state-race" in msg   # points at the static twin


def test_race_sanitizer_silent_on_locked_twin(sanitize):
    lk = engine.make_lock("race.Counter._lock")

    class C(_Counter):
        pass

    obj = engine.watch_races(C(lock=lk))
    with lk:
        obj.n += 1
    assert _second_thread_write(obj, lock=lk) == []
    assert obj.n == 2                   # both updates landed


def test_race_sanitizer_lockset_is_running_intersection(sanitize):
    # writer A holds {L1, L2}, writer B holds {L2}: fine (L2 shared);
    # a third write holding only {L1} empties the intersection and is
    # the one that raises
    l1 = engine.make_lock("race.L1")
    l2 = engine.make_lock("race.L2")

    class C(_Counter):
        pass

    obj = engine.watch_races(C())
    with l1, l2:
        obj.n += 1
    assert _second_thread_write(obj, lock=l2) == []
    with pytest.raises(MXNetError, match="data race on C.n"):
        with l1:
            obj.n += 1


def test_race_sanitizer_exempt_field_is_untracked(sanitize):
    class C(_Counter):
        pass

    obj = engine.watch_races(C(), exempt=("flag",))
    obj.flag = True
    assert _second_thread_write(obj, field="flag") == []


def test_race_sanitizer_single_thread_never_flags(sanitize):
    class C(_Counter):
        pass

    obj = engine.watch_races(C())
    for _ in range(100):
        obj.n += 1                      # exclusive owner, no locks: ok
    assert obj.n == 100


def test_watch_races_off_path_is_zero_cost(monkeypatch):
    monkeypatch.setattr(engine, "_SANITIZE", False)

    class C(_Counter):
        pass

    obj = engine.watch_races(C())
    assert "_mx_race_fields_" not in obj.__dict__
    assert C not in engine._RACE_WATCHED_CLASSES
    errs = _second_thread_write(obj)
    assert errs == [] and obj.n == 1


def test_serving_classes_auto_arm_under_sanitizer(sanitize):
    from mxnet_tpu.serving.kv_cache import PageAllocator, PageGeometry
    geo = PageGeometry(page_size=4, pool_pages=8, max_context=16,
                      num_layers=1, num_heads=1, head_dim=4)
    alloc = PageAllocator(geo)
    assert "_mx_race_fields_" in alloc.__dict__
    # the allocator's own lock discipline satisfies its sanitizer:
    # peak_used is written under PageAllocator._lock from any thread
    assert alloc.allocate("s", 2)
    errs = []

    def other():
        try:
            alloc.allocate("t", 1)
            alloc.release("t")
        except MXNetError as e:         # pragma: no cover
            errs.append(e)

    t = threading.Thread(target=other)
    t.start()
    t.join(10)
    assert errs == []
    alloc.release("s")
    assert alloc.check_leaks() == 0
