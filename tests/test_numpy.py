"""mx.np / mx.npx tests (reference strategy: tests/python/unittest/
test_numpy_op.py / test_numpy_ndarray.py — numpy-semantics parity checks
against real numpy)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import np as mnp
from mxnet_tpu import npx
from mxnet_tpu.ndarray import NDArray


class TestNpCreation:
    def test_array_zeros_ones(self):
        a = mnp.array([[1, 2], [3, 4]])
        assert isinstance(a, NDArray)
        assert a.shape == (2, 2)
        onp.testing.assert_array_equal(mnp.zeros((2, 3)).asnumpy(),
                                       onp.zeros((2, 3)))
        onp.testing.assert_array_equal(
            mnp.ones((2,), dtype=mnp.int32).asnumpy(),
            onp.ones((2,), onp.int32))

    def test_zero_dim_and_zero_size(self):
        """np-shape semantics: 0-d and 0-size arrays are first-class."""
        s = mnp.array(3.5)
        assert s.shape == ()
        assert float(s.asnumpy()) == 3.5
        z = mnp.zeros((0, 4))
        assert z.shape == (0, 4)
        assert mnp.concatenate([z, z]).shape == (0, 4)

    def test_arange_linspace(self):
        onp.testing.assert_allclose(mnp.arange(5).asnumpy(), onp.arange(5))
        onp.testing.assert_allclose(mnp.linspace(0, 1, 5).asnumpy(),
                                    onp.linspace(0, 1, 5))


class TestNpBroadcastSemantics:
    def test_true_numpy_broadcasting(self):
        a = mnp.ones((3, 1, 4))
        b = mnp.arange(2).reshape((2, 1)).astype("float32")
        out = mnp.add(a, b)
        ref = onp.ones((3, 1, 4)) + onp.arange(2).reshape(2, 1)
        assert out.shape == ref.shape == (3, 2, 4)
        onp.testing.assert_allclose(out.asnumpy(), ref)

    def test_where_and_comparison(self):
        x = mnp.array([1.0, -2.0, 3.0])
        out = mnp.where(mnp.greater(x, 0), x, mnp.zeros_like(x))
        onp.testing.assert_allclose(out.asnumpy(), [1.0, 0.0, 3.0])

    def test_reductions_match_numpy(self):
        rng = onp.random.RandomState(0)
        x = rng.randn(3, 4, 5).astype(onp.float32)
        m = mnp.array(x)
        for red in ("sum", "mean", "max", "min", "var", "std", "prod"):
            got = getattr(mnp, red)(m, axis=1).asnumpy()
            want = getattr(onp, red)(x, axis=1)
            onp.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-5)

    def test_einsum_matmul(self):
        rng = onp.random.RandomState(1)
        a = rng.randn(2, 3).astype(onp.float32)
        b = rng.randn(3, 4).astype(onp.float32)
        onp.testing.assert_allclose(
            mnp.einsum("ij,jk->ik", mnp.array(a), mnp.array(b)).asnumpy(),
            a @ b, rtol=1e-5)
        onp.testing.assert_allclose(
            mnp.matmul(mnp.array(a), mnp.array(b)).asnumpy(), a @ b,
            rtol=1e-5)

    def test_split_returns_ndarrays(self):
        parts = mnp.split(mnp.arange(12).reshape((3, 4)), 2, axis=1)
        assert len(parts) == 2
        assert all(isinstance(p, NDArray) for p in parts)
        assert parts[0].shape == (3, 2)


class TestNpSubmodules:
    def test_linalg(self):
        a = onp.array([[4.0, 0.0], [0.0, 9.0]], onp.float32)
        onp.testing.assert_allclose(
            mnp.linalg.norm(mnp.array(a)).asnumpy(),
            onp.linalg.norm(a), rtol=1e-6)
        inv = mnp.linalg.inv(mnp.array(a)).asnumpy()
        onp.testing.assert_allclose(inv, onp.linalg.inv(a), rtol=1e-5)

    def test_fft_roundtrip(self):
        x = onp.random.RandomState(0).randn(8).astype(onp.float32)
        back = mnp.fft.ifft(mnp.fft.fft(mnp.array(x)))
        onp.testing.assert_allclose(back.asnumpy().real, x, atol=1e-5)

    def test_random_seeded(self):
        mnp.random.seed(42)
        a = mnp.random.uniform(size=(4,)).asnumpy()
        mnp.random.seed(42)
        b = mnp.random.uniform(size=(4,)).asnumpy()
        onp.testing.assert_array_equal(a, b)
        assert mnp.random.randint(0, 10, size=(100,)).asnumpy().max() < 10
        n = mnp.random.normal(2.0, 0.5, size=(2000,)).asnumpy()
        assert abs(n.mean() - 2.0) < 0.1

    def test_error_wraps_mxnet_error(self):
        with pytest.raises(mx.MXNetError):
            mnp.reshape(mnp.zeros((4,)), (3,))


class TestNpx:
    def test_set_np_flags(self):
        npx.set_np()
        assert npx.is_np_array() and npx.is_np_shape()
        npx.reset_np()
        assert not npx.is_np_array()

    def test_nn_extension_ops(self):
        x = mnp.random.normal(size=(2, 8))
        w = mnp.random.normal(size=(4, 8))
        b = mnp.zeros((4,))
        out = npx.fully_connected(x, w, b, num_hidden=4)
        assert out.shape == (2, 4)
        onp.testing.assert_allclose(
            out.asnumpy(), x.asnumpy() @ w.asnumpy().T + b.asnumpy(),
            rtol=2e-5, atol=2e-5)
        sm = npx.softmax(out)
        onp.testing.assert_allclose(sm.asnumpy().sum(-1), 1.0, rtol=1e-5)
        assert npx.relu(mnp.array([-1.0, 2.0])).asnumpy().tolist() \
            == [0.0, 2.0]

    def test_one_hot_pick(self):
        idx = mnp.array([0, 2]).astype("int32")
        oh = npx.one_hot(idx, 3)
        onp.testing.assert_array_equal(
            oh.asnumpy(), [[1, 0, 0], [0, 0, 1]])

    def test_save_load(self, tmp_path):
        path = str(tmp_path / "arrs")
        npx.save(path, {"w": mnp.ones((2, 2))})
        back = npx.load(path)
        onp.testing.assert_array_equal(back["w"].asnumpy(),
                                       onp.ones((2, 2)))


class TestNpAutograd:
    """mx.np is autograd-recordable (VERDICT r2 weak #5): np calls under
    record() tape through ops.registry.invoke like mx.nd ops."""

    def test_grad_through_np_ops(self):
        from mxnet_tpu import autograd
        x = mnp.array([[1.0, 2.0], [3.0, 4.0]])
        x.attach_grad()
        with autograd.record():
            y = mnp.sum(mnp.square(x) * 3.0)
        y.backward()
        onp.testing.assert_allclose(x.grad.asnumpy(), 6 * x.asnumpy())

    def test_multi_output_and_mixed_tape(self):
        from mxnet_tpu import autograd, nd
        a = mnp.array([1.0, 2.0, 3.0, 4.0])
        a.attach_grad()
        with autograd.record():
            p0, p1 = mnp.split(a, 2)
            l = nd.sum(p0 * 2.0) + mnp.sum(p1 * 3.0)
        l.backward()
        onp.testing.assert_allclose(a.grad.asnumpy(), [2, 2, 3, 3])

    def test_train_tiny_model_in_np(self):
        """A linear-regression model written entirely in mx.np trains to
        convergence — the VERDICT 'done' criterion."""
        from mxnet_tpu import autograd
        rng = onp.random.RandomState(0)
        Xh = rng.randn(64, 4).astype(onp.float32)
        true_w = onp.array([[1.0], [-2.0], [0.5], [3.0]], onp.float32)
        Yh = Xh @ true_w
        X, Y = mnp.array(Xh), mnp.array(Yh)
        w = mnp.zeros((4, 1))
        b = mnp.zeros((1,))
        w.attach_grad()
        b.attach_grad()
        losses = []
        for _ in range(60):
            with autograd.record():
                pred = mnp.matmul(X, w) + b
                loss = mnp.mean(mnp.square(pred - Y))
            loss.backward()
            for p in (w, b):
                p -= 0.1 * p.grad
                p.grad[:] = 0
            losses.append(float(loss.asnumpy()))
        assert losses[-1] < 1e-3 < losses[0]
        onp.testing.assert_allclose(w.asnumpy(), true_w, atol=0.05)

    def test_metadata_fns_stay_tape_free(self):
        from mxnet_tpu import autograd
        x = mnp.ones((2, 3))
        x.attach_grad()
        with autograd.record():
            assert mnp.shape(x) == (2, 3)
            assert mnp.ndim(x) == 2
            assert mnp.size(x) == 6
            y = mnp.sum(x)
        y.backward()
        onp.testing.assert_allclose(x.grad.asnumpy(), onp.ones((2, 3)))

    def test_namedtuple_results_eager_and_taped(self):
        """jnp.linalg returns NamedTuple result types (EighResult):
        wrapping must rebuild them, eager and under record()."""
        from mxnet_tpu import autograd
        x = mnp.array([[2.0, 1.0], [1.0, 3.0]])
        r = mnp.linalg.eigh(x)
        assert hasattr(r, "eigenvalues") and hasattr(r, "eigenvectors")
        x.attach_grad()
        with autograd.record():
            vals, _vecs = mnp.linalg.eigh(x)
            l = mnp.sum(vals)
        l.backward()
        # d(sum of eigenvalues)/dX = I for symmetric X
        onp.testing.assert_allclose(x.grad.asnumpy(), onp.eye(2),
                                    atol=1e-5)

    def test_baked_constants_not_shared_across_bulk_cache(self):
        """Two taped np calls differing only in a baked scalar must not
        share a compiled backward (bulk-replay cache identity)."""
        from mxnet_tpu import autograd

        def grad_of(c):
            x = mnp.array([1.0, 2.0])
            x.attach_grad()
            with autograd.record():
                y = mnp.sum(mnp.multiply(mnp.square(x), c))
            y.backward()
            return x.grad.asnumpy()

        g3 = grad_of(3.0)
        g5 = grad_of(5.0)
        onp.testing.assert_allclose(g3, 6 * onp.array([1.0, 2.0]))
        onp.testing.assert_allclose(g5, 10 * onp.array([1.0, 2.0]))
