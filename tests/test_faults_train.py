"""Training-plane resilience ladder (docs/training_resilience.md):
fault sites in trainer/io/kvstore/checkpoint, the step watchdog,
checkpoint integrity + corrupt-payload fallback, iterator-cursor and
RNG checkpointing, and TrainingSupervisor's bounded-restart bit-exact
resume — all on numpy fakes; the one real ShardedTrainer test reuses a
single tiny compile.
"""
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import faults, io, runtime_metrics as rm
from mxnet_tpu.base import MXNetError
from mxnet_tpu.parallel import (CheckpointManager, CrashLoopError,
                                StepWatchdog, TrainingSupervisor,
                                TrainStepTimeoutError, run_with_deadline)


@pytest.fixture(autouse=True)
def _no_leftover_faults():
    faults.clear()
    yield
    faults.clear()


class NumpyTrainer:
    """Deterministic toy trainer on numpy (zero compiles): momentum
    SGD on least squares plus one eager-RNG draw per step, so resume
    is bit-exact only if params, opt state, data cursor AND the RNG
    stream are all restored."""

    def __init__(self, n_features=4, lr=0.05):
        rs = np.random.RandomState(0)
        self.params = {"w": rs.randn(n_features).astype(np.float32)}
        self.opt_state = {"m": np.zeros(n_features, np.float32)}
        self.lr = lr
        self.batches_seen = []          # (global caller tag, checksum)

    def step(self, data, label):
        faults.inject("train.step")     # same site ShardedTrainer has
        w = np.asarray(self.params["w"])
        m = np.asarray(self.opt_state["m"])
        x = np.asarray(getattr(data, "asnumpy", lambda: data)())
        y = np.asarray(getattr(label, "asnumpy", lambda: label)())
        noise = mx.random.uniform(shape=w.shape).asnumpy() * 1e-3
        pred = x @ w
        grad = 2 * x.T @ (pred - y) / len(y) + noise
        m = 0.9 * m + grad
        w = w - self.lr * m
        self.params = {"w": w.astype(np.float32)}
        self.opt_state = {"m": m.astype(np.float32)}
        return float(np.mean((pred - y) ** 2))


def _dataset(n=30, n_features=4):
    rs = np.random.RandomState(1)
    x = rs.randn(n, n_features).astype(np.float32)
    y = (x @ np.arange(1, n_features + 1).astype(np.float32)) \
        .astype(np.float32)
    return x, y


def _supervised_run(ckpt_dir, spec=None, num_steps=12, save_every=3,
                    batch_size=6, record=None, **sup_kw):
    """One supervised training run; returns (losses, supervisor,
    fired-fault counters)."""
    mx.random.seed(7)
    x, y = _dataset()
    it = io.NDArrayIter(x, y, batch_size=batch_size, shuffle=True,
                        seed=11)
    trainer = NumpyTrainer()
    manager = CheckpointManager(ckpt_dir, max_to_keep=4,
                                async_write=False)

    def step_fn(tr, batch):
        if record is not None:
            record.append((supervisor._step,
                           float(batch.data[0].asnumpy().sum())))
        return tr.step(batch.data[0], batch.label[0])

    supervisor = TrainingSupervisor(
        trainer, manager, it, step_fn=step_fn, save_every=save_every,
        backoff_ms=sup_kw.pop("backoff_ms", 1),
        backoff_max_ms=sup_kw.pop("backoff_max_ms", 2), **sup_kw)
    if spec:
        faults.install(spec)
    try:
        losses = supervisor.run(num_steps)
    finally:
        plan = faults.active()
        faults.clear()
        manager.close()
    return losses, supervisor, plan.counters() if plan else {}


# ---------------------------------------------------------------------------
# fault sites
# ---------------------------------------------------------------------------
class TestTrainingFaultSites:
    def test_data_next_site(self):
        x, y = _dataset(12)
        it = io.NDArrayIter(x, y, batch_size=4)
        with faults.plan("train.data.next=fail,times=1"):
            with pytest.raises(faults.InjectedFault) as err:
                it.next()
            assert err.value.site == "train.data.next"
            assert err.value.transient
            # the failed call did not consume the batch
            assert it.next().data[0].shape[0] == 4

    def test_kvstore_push_pull_sites(self):
        kv = mx.kv.create("local")
        kv.init("w", mx.nd.zeros((2,)))
        out = mx.nd.zeros((2,))
        with faults.plan("kvstore.push=fail,times=1;"
                         "kvstore.pull=fail,times=1"):
            with pytest.raises(faults.InjectedFault):
                kv.push("w", mx.nd.ones((2,)))
            with pytest.raises(faults.InjectedFault):
                kv.pull("w", out=out)
        kv.push("w", mx.nd.ones((2,)))
        kv.pull("w", out=out)
        np.testing.assert_allclose(out.asnumpy(), 1.0)

    def test_fake_trainer_step_site(self):
        tr = NumpyTrainer()
        x, y = _dataset(6)
        with faults.plan("train.step=fail,times=1"):
            with pytest.raises(faults.InjectedFault):
                tr.step(x, y)
            assert tr.step(x, y) > 0

    def test_train_glob_matches_all_training_sites(self):
        plan = faults.FaultPlan.parse("train.*=fail")
        assert plan.rules[0].matches("train.step")
        assert plan.rules[0].matches("train.data.next")
        assert not plan.rules[0].matches("kvstore.push")


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------
class TestStepWatchdog:
    def test_wedged_step_typed_timeout_no_leaked_thread(self):
        release = threading.Event()
        before = {t.name for t in threading.enumerate()}
        t0 = time.monotonic()
        with pytest.raises(TrainStepTimeoutError) as err:
            run_with_deadline(lambda: release.wait(30), 150,
                              site="train.step")
        elapsed = time.monotonic() - t0
        assert elapsed < 5, elapsed          # deadline, not the wedge
        assert err.value.transient
        assert "150" in str(err.value)
        # unwedge the fake collective: the abandoned worker must exit
        release.set()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            leaked = {t.name for t in threading.enumerate()} - before
            if not any(n.startswith("mxnet-watchdog") for n in leaked):
                break
            time.sleep(0.01)
        leaked = {t.name for t in threading.enumerate()} - before
        assert not any(n.startswith("mxnet-watchdog") for n in leaked)

    def test_zero_timeout_runs_in_caller_thread(self):
        seen = []
        run_with_deadline(lambda: seen.append(
            threading.current_thread().name), 0)
        assert seen == [threading.current_thread().name]

    def test_result_and_exception_propagate(self):
        assert run_with_deadline(lambda: 41 + 1, 1000) == 42
        with pytest.raises(ZeroDivisionError):
            run_with_deadline(lambda: 1 // 0, 1000)

    def test_straggler_detection(self):
        wd = StepWatchdog(timeout_ms=0, slow_factor=3.0)
        assert wd.active
        for _ in range(6):
            wd.watch(lambda: time.sleep(0.002))
        assert wd.slow_steps == 0
        wd.watch(lambda: time.sleep(0.05))
        assert wd.slow_steps == 1
        state = wd.debug_state()
        assert state["slow_steps"] == 1 and state["observed"] == 7

    def test_inactive_by_default(self, monkeypatch):
        monkeypatch.delenv("MXNET_TRAIN_STEP_TIMEOUT_MS",
                           raising=False)
        monkeypatch.delenv("MXNET_TRAIN_SLOW_STEP_FACTOR",
                           raising=False)
        assert not StepWatchdog().active

    def test_stall_fault_is_bounded_by_the_deadline(self):
        """train.step ``stall`` (the wedged-collective chaos shape)
        fires INSIDE the watched call, so the deadline bounds it
        instead of the sleep hanging the train-loop thread."""
        wd = StepWatchdog(timeout_ms=150, slow_factor=0)

        def body():
            faults.inject("train.step")
            return 1.0

        with faults.plan("train.step=stall,ms=60000,times=1"):
            t0 = time.monotonic()
            with pytest.raises(TrainStepTimeoutError):
                wd.watch(body)
            assert time.monotonic() - t0 < 5

    def test_abandoned_worker_cannot_clobber_restored_state(self):
        """After a timeout the worker's eventual result is discarded:
        a late-finishing wedged step must never overwrite trainer
        state the supervisor has since restored (run_with_deadline
        returns via the caller, and only the caller commits)."""
        release = threading.Event()
        finished = threading.Event()

        def wedged():
            release.wait(30)
            finished.set()
            return "poisoned result"

        with pytest.raises(TrainStepTimeoutError):
            run_with_deadline(wedged, 100)
        release.set()
        assert finished.wait(5)
        # the poisoned result was dropped on the floor — nothing to
        # assert beyond "no exception, no value escaped": the caller
        # got the typed timeout, not "poisoned result"

    def test_sharded_trainer_wedged_step(self):
        """The real step() wiring: a wedged compiled step raises the
        typed timeout within the deadline instead of hanging."""
        import jax
        from mxnet_tpu import nd, parallel
        from mxnet_tpu.gluon import nn
        net = nn.Dense(4, in_units=4, prefix="wdg_")
        net.initialize()
        mesh = parallel.make_mesh(dp=1, tp=1, sp=1,
                                  devices=jax.devices()[:1])
        x = nd.array(np.ones((2, 4), np.float32))
        y = nd.array(np.ones((2, 4), np.float32))
        trainer = parallel.ShardedTrainer(
            net, lambda out, lab: ((out - lab) ** 2).mean(), mesh,
            optimizer="sgd", example_inputs=(x,), n_labels=1,
            step_timeout_ms=300)
        assert float(jax.device_get(trainer.step(x, y))) >= 0
        release = threading.Event()
        wedged = lambda *a, **k: (release.wait(30), None)  # noqa: E731
        trainer._step = wedged
        t0 = time.monotonic()
        with pytest.raises(TrainStepTimeoutError):
            trainer.step(x, y)
        assert time.monotonic() - t0 < 5
        release.set()


# ---------------------------------------------------------------------------
# iterator cursor + RNG state
# ---------------------------------------------------------------------------
class TestCheckpointableIterator:
    @pytest.mark.parametrize("handle", ["pad", "discard", "roll_over"])
    def test_cursor_roundtrip_mid_epochs(self, handle):
        x, y = _dataset(20)
        make = lambda: io.NDArrayIter(  # noqa: E731
            x, y, batch_size=3, shuffle=True,
            last_batch_handle=handle, seed=5)

        def drive(it, n):
            out = []
            for _ in range(n):
                try:
                    b = it.next()
                except StopIteration:
                    it.reset()
                    b = it.next()
                out.append(b.data[0].asnumpy().copy())
            return out

        it = make()
        drive(it, 9)                    # into the second epoch
        cursor = it.get_cursor()
        want = drive(it, 8)
        it2 = make()
        it2.set_cursor(cursor)
        got = drive(it2, 8)
        for a, b in zip(want, got):
            np.testing.assert_array_equal(a, b)

    def test_unseeded_shuffle_not_checkpointable(self):
        x, y = _dataset(9)
        it = io.NDArrayIter(x, y, batch_size=3, shuffle=True)
        with pytest.raises(MXNetError, match="seed"):
            it.get_cursor()
        # unshuffled iterators are checkpointable without a seed
        it = io.NDArrayIter(x, y, batch_size=3)
        assert it.get_cursor()["epoch"] == 0

    def test_cursor_config_mismatch_refused(self):
        x, y = _dataset(12)
        it = io.NDArrayIter(x, y, batch_size=3, seed=1)
        cursor = it.get_cursor()
        other = io.NDArrayIter(x, y, batch_size=4, seed=1)
        with pytest.raises(MXNetError, match="batch_size"):
            other.set_cursor(cursor)
        other = io.NDArrayIter(x[:9], y[:9], batch_size=3, seed=1)
        with pytest.raises(MXNetError, match="num_data"):
            other.set_cursor(cursor)
        # a different shuffle setting yields different batches from
        # identical (seed, epoch, position) — must be refused too
        shuffled = io.NDArrayIter(x, y, batch_size=3, shuffle=True,
                                  seed=1)
        with pytest.raises(MXNetError, match="shuffle"):
            shuffled.set_cursor(cursor)

    def test_seeded_epochs_are_reproducible(self):
        x, y = _dataset(12)
        orders = []
        for _ in range(2):
            it = io.NDArrayIter(x, y, batch_size=4, shuffle=True,
                                seed=9)
            epoch = [it.next().data[0].asnumpy().copy()
                     for _ in range(3)]
            orders.append(np.concatenate(epoch))
        np.testing.assert_array_equal(orders[0], orders[1])


class TestRNGStateCheckpoint:
    def test_roundtrip_bit_exact(self):
        mx.random.seed(3)
        mx.random.uniform(shape=(4,)).asnumpy()     # advance stream
        state = mx.random.get_state()
        want = [mx.random.uniform(shape=(3,)).asnumpy()
                for _ in range(3)]
        mx.random.set_state(state)
        got = [mx.random.uniform(shape=(3,)).asnumpy()
               for _ in range(3)]
        for a, b in zip(want, got):
            np.testing.assert_array_equal(a, b)

    def test_state_is_json_serializable(self):
        import json
        state = mx.random.get_state()
        assert json.loads(json.dumps(state)) == state


# ---------------------------------------------------------------------------
# checkpoint integrity + fallback
# ---------------------------------------------------------------------------
class _TinyState:
    def __init__(self, value=0.0):
        self.params = {"w": np.full(4, value, np.float32)}
        self.opt_state = {"m": np.zeros(4, np.float32)}


class TestCorruptPayloadFallback:
    def _manager_with_steps(self, tmp_path, steps=(1, 2)):
        mngr = CheckpointManager(tmp_path / "ckpt", max_to_keep=4,
                                 async_write=False)
        holder = _TinyState()
        for step in steps:
            holder.params["w"] = np.full(4, float(step), np.float32)
            mngr.save(step, holder, extra={"step": step})
            mngr.wait()
        return mngr

    def test_bit_flipped_blob_falls_back_with_warning(self, tmp_path,
                                                      caplog):
        from mxnet_tpu.parallel.checkpoint import _flip_payload_byte
        mngr = self._manager_with_steps(tmp_path)
        assert mngr.latest_verified_step() == 2
        flipped = _flip_payload_byte(mngr._step_dir(2))
        assert flipped is not None
        target = _TinyState()
        with caplog.at_level("WARNING", logger="mxnet_tpu"):
            step = mngr.restore(target)
        assert step == 1
        np.testing.assert_allclose(np.asarray(target.params["w"]), 1.0)
        assert any("falling back" in r.message for r in caplog.records)
        mngr.close()

    def test_explicit_step_still_raises_on_corruption(self, tmp_path):
        from mxnet_tpu.parallel.checkpoint import _flip_payload_byte
        mngr = self._manager_with_steps(tmp_path)
        _flip_payload_byte(mngr._step_dir(2))
        with pytest.raises(Exception):
            mngr.restore(_TinyState(), step=2)
        mngr.close()

    def test_injected_save_corruption_detected(self, tmp_path):
        mngr = CheckpointManager(tmp_path / "c", async_write=False)
        holder = _TinyState()
        holder.params["w"] = np.full(4, 1.0, np.float32)
        mngr.save(1, holder)
        mngr.wait()
        with faults.plan("checkpoint.save=corrupt,times=1"):
            holder.params["w"] = np.full(4, 2.0, np.float32)
            mngr.save(2, holder)
            mngr.wait()                 # barrier fires the bit flip
        target = _TinyState()
        assert mngr.restore(target) == 1
        np.testing.assert_allclose(np.asarray(target.params["w"]), 1.0)
        mngr.close()

    def test_restore_fail_site_raises_typed(self, tmp_path):
        mngr = self._manager_with_steps(tmp_path, steps=(1,))
        with faults.plan("checkpoint.restore=fail,times=1"):
            with pytest.raises(faults.InjectedFault):
                mngr.restore(_TinyState())
        assert mngr.restore(_TinyState()) == 1
        mngr.close()

    def test_residuals_ride_the_checkpoint_tree(self, tmp_path):
        """Quantized-collective error-feedback residuals are step
        state: they round-trip next to params/opt_state so a
        compressed-sync resume stays on the uninterrupted
        trajectory."""
        holder = _TinyState()
        holder.residuals = {"w": np.full(4, 0.25, np.float32)}
        mngr = CheckpointManager(tmp_path / "c", async_write=False)
        mngr.save(1, holder)
        mngr.wait()
        target = _TinyState()
        target.residuals = {"w": np.zeros(4, np.float32)}
        assert mngr.restore(target) == 1
        np.testing.assert_allclose(np.asarray(target.residuals["w"]),
                                   0.25)
        mngr.close()

    def test_unbarriered_newer_step_never_auto_restored(self,
                                                        tmp_path):
        """A step saved but killed before its barrier (no manifest,
        NEWER than the marker) is torn by definition: when the marker
        step rots, fallback must go OLDER — restoring the unverified
        step would also skip its extra payload (RNG/cursor) and break
        bit-exact resume."""
        from mxnet_tpu.parallel.checkpoint import _flip_payload_byte
        mngr = self._manager_with_steps(tmp_path, steps=(1, 2))
        holder = _TinyState(3.0)
        mngr.save(3, holder)            # kill before wait(): no
        mngr._pending = []              # manifest, marker stays at 2
        assert mngr.latest_verified_step() == 2
        _flip_payload_byte(mngr._step_dir(2))
        target = _TinyState()
        assert mngr.restore(target) == 1
        np.testing.assert_allclose(np.asarray(target.params["w"]), 1.0)
        mngr.close()

    def test_extra_payload_roundtrip_and_gc(self, tmp_path):
        mngr = CheckpointManager(tmp_path / "c", max_to_keep=2,
                                 async_write=False)
        holder = _TinyState()
        for step in (1, 2, 3, 4):
            mngr.save(step, holder, extra={"losses": [0.1] * step})
            mngr.wait()
        assert mngr.load_extra(4) == {"losses": [0.1] * 4}
        # retention GC'd steps 1/2: their sidecars must be gone too
        assert mngr.load_extra(1) is None
        assert not (tmp_path / "c" / "VERIFY-1.json").exists()
        mngr.close()


# ---------------------------------------------------------------------------
# supervisor
# ---------------------------------------------------------------------------
class TestTrainingSupervisor:
    def test_bit_exact_resume_after_midstep_kill(self, tmp_path):
        ref, _sup, _ = _supervised_run(tmp_path / "ref")
        chaos, sup, fired = _supervised_run(
            tmp_path / "chaos",
            spec="train.step=fail,after=5,times=1")
        assert fired["train.step:fail"] == 1
        assert sup.restarts == 1
        assert chaos == ref             # bit-exact trajectory
        assert sup.debug_state()["latest_verified_step"] == 12

    def test_resume_sees_exactly_batch_k_plus_1(self, tmp_path):
        ref_batches, chaos_batches = [], []
        _supervised_run(tmp_path / "r", record=ref_batches)
        _supervised_run(tmp_path / "c", record=chaos_batches,
                        spec="train.step=fail,after=7,times=1")
        ref_by_step = dict(ref_batches)
        for step, checksum in chaos_batches:
            assert checksum == ref_by_step[step], step
        # the killed step (and the steps replayed from the restore
        # point) were re-attempted — always with the SAME batch, so
        # every unique step saw exactly one batch and none was skipped
        steps = [s for s, _ in chaos_batches]
        assert sorted(set(steps)) == list(range(12))
        assert len(steps) > 12          # the kill forced replays

    def test_kill_during_checkpoint_save(self, tmp_path):
        ref, _s, _ = _supervised_run(tmp_path / "ref")
        chaos, sup, fired = _supervised_run(
            tmp_path / "chaos",
            spec="checkpoint.save=fail,after=1,times=1")
        assert fired["checkpoint.save:fail"] == 1
        assert sup.restarts == 1
        assert chaos == ref

    def test_corrupt_marker_checkpoint_plus_kill(self, tmp_path):
        """The acceptance ladder: corrupt the newest verified payload,
        then kill — restore falls back one checkpoint further and the
        trajectory still matches the twin."""
        ref, _s, _ = _supervised_run(tmp_path / "ref")
        chaos, sup, fired = _supervised_run(
            tmp_path / "chaos",
            spec="train.step=fail,after=7,times=1;"
                 "checkpoint.save=corrupt,after=2,times=1")
        assert fired == {"train.step:fail": 1,
                         "checkpoint.save:corrupt": 1}
        assert sup.restarts == 1
        assert chaos == ref

    def test_transient_restore_failure_stays_supervised(self,
                                                        tmp_path):
        """A transient blip DURING recovery (the checkpoint.restore
        fault site) re-enters the restart policy — bounded by the
        breaker — instead of escaping run()."""
        ref, _s, _ = _supervised_run(tmp_path / "ref")
        chaos, sup, fired = _supervised_run(
            tmp_path / "chaos",
            spec="train.step=fail,after=5,times=1;"
                 "checkpoint.restore=fail,times=1")
        assert fired == {"train.step:fail": 1,
                         "checkpoint.restore:fail": 1}
        assert sup.restarts == 2        # the kill + the restore blip
        assert chaos == ref

    def test_transient_restore_failures_trip_the_breaker(self,
                                                         tmp_path):
        with pytest.raises(CrashLoopError):
            _supervised_run(tmp_path / "c",
                            spec="train.step=fail,after=5,times=1;"
                                 "checkpoint.restore=fail",
                            max_restarts=3)

    def test_unseeded_shuffle_iter_degrades_to_warning(self, tmp_path,
                                                       caplog):
        """An uncheckpointable iterator (shuffle without seed=) must
        not fail the save — the supervisor warns once and runs
        without the bit-exact cursor."""
        x, y = _dataset(18)
        it = io.NDArrayIter(x, y, batch_size=6, shuffle=True)
        mngr = CheckpointManager(tmp_path / "c", async_write=False)
        sup = TrainingSupervisor(
            NumpyTrainer(), mngr, it, save_every=2, backoff_ms=1,
            step_fn=lambda t, b: t.step(b.data[0], b.label[0]))
        with caplog.at_level("WARNING", logger="mxnet_tpu"):
            losses = sup.run(4)
        assert len(losses) == 4
        assert sum("cursor unavailable" in r.message
                   for r in caplog.records) == 1
        assert mngr.load_extra(4)["cursor"] is None
        mngr.close()

    def test_explicit_step_corrupt_injection_applies(self, tmp_path):
        """checkpoint.restore=corrupt on an explicit step= really
        flips the payload (the fired counter must match an observed
        effect, not a no-op)."""
        mngr = CheckpointManager(tmp_path / "c", async_write=False)
        holder = _TinyState(1.0)
        mngr.save(1, holder)
        mngr.wait()
        assert mngr._verify_step(1) == (True, "verified")
        with faults.plan("checkpoint.restore=corrupt,times=1") as plan:
            try:
                mngr.restore(_TinyState(), step=1)
            except Exception:   # noqa: BLE001 — backend may reject rot
                pass
            assert plan.counters()["checkpoint.restore:corrupt"] == 1
        # the fired counter corresponds to a REAL on-disk effect
        ok, why = mngr._verify_step(1)
        assert not ok and "mismatch" in why
        mngr.close()

    def test_deterministic_failure_reraises(self, tmp_path):
        boom = ValueError("shape mismatch")

        def bad_step(_trainer, _batch):
            raise boom

        x, y = _dataset(12)
        it = io.NDArrayIter(x, y, batch_size=4, seed=1)
        mngr = CheckpointManager(tmp_path / "c", async_write=False)
        sup = TrainingSupervisor(NumpyTrainer(), mngr, it,
                                 step_fn=bad_step, backoff_ms=1)
        with pytest.raises(ValueError):
            sup.run(4)
        assert sup.restarts == 0
        mngr.close()

    def test_crash_loop_breaker_trips(self, tmp_path):
        with pytest.raises(CrashLoopError) as err:
            _supervised_run(tmp_path / "c", spec="train.step=fail",
                            max_restarts=2)
        assert err.value.restarts == 2
        assert isinstance(err.value.last_error, faults.InjectedFault)

    def test_backoff_is_jittered_exponential_and_bounded(self,
                                                         tmp_path,
                                                         monkeypatch):
        sleeps = []
        import mxnet_tpu.parallel.supervisor as sup_mod
        monkeypatch.setattr(sup_mod.time, "sleep",
                            lambda s: sleeps.append(s))
        _losses, sup, _ = _supervised_run(
            tmp_path / "c", spec="train.step=fail,after=2,times=3",
            backoff_ms=8, backoff_max_ms=20)
        assert sup.restarts == 3
        lo, hi = 8 / 1e3, 20 / 1e3
        assert len(sleeps) == 3
        assert lo * 0.5 <= sleeps[0] <= lo          # 8ms * U[.5,1)
        assert lo <= sleeps[1] <= 2 * lo            # 16ms * U[.5,1)
        assert hi * 0.5 <= sleeps[2] <= hi          # capped at 20ms

    def test_progress_resets_the_breaker(self, tmp_path):
        """Two kills spread across the run with max_restarts=2: each
        restart makes progress before the next kill, so consecutive
        failures reset and the breaker never trips."""
        chaos, sup, fired = _supervised_run(
            tmp_path / "c",
            spec="train.step=fail,after=3,times=1;"
                 "train.step=fail,after=8,times=1",
            max_restarts=2, num_steps=10)
        assert sup.restarts == 2
        assert fired["train.step:fail"] == 2    # aggregated rules
        assert len(chaos) == 10
        assert sup.debug_state()["consecutive_failures"] == 0

    def test_step_timeout_is_supervised(self, tmp_path):
        """A wedged step -> typed timeout -> supervised restore ->
        completion; the wedge releases at teardown."""
        release = threading.Event()
        wedge = {"armed": True}
        watchdog = StepWatchdog(timeout_ms=200, slow_factor=0)

        def step_fn(trainer, batch):
            def body():
                if wedge.pop("armed", None):
                    release.wait(30)    # the wedged collective
                return trainer.step(batch.data[0], batch.label[0])
            return watchdog.watch(body)

        try:
            x, y = _dataset()
            it = io.NDArrayIter(x, y, batch_size=6, seed=1)
            mngr = CheckpointManager(tmp_path / "c", async_write=False)
            sup = TrainingSupervisor(NumpyTrainer(), mngr, it,
                                     step_fn=step_fn, save_every=3,
                                     backoff_ms=1, backoff_max_ms=2)
            losses = sup.run(6)
            assert len(losses) == 6
            assert sup.restarts == 1
            assert watchdog.timeouts == 1
            mngr.close()
        finally:
            release.set()

    def test_cross_process_resume_from_anchor(self, tmp_path):
        """A NEW supervisor over the same checkpoint dir auto-resumes:
        same losses as one uninterrupted run (the preemption story)."""
        ref, _s, _ = _supervised_run(tmp_path / "ref", num_steps=12)
        first, _s2, _ = _supervised_run(tmp_path / "c", num_steps=6)
        resumed, sup, _ = _supervised_run(tmp_path / "c", num_steps=12)
        assert resumed == ref
        assert first == ref[:6]

    def test_restart_metrics_published(self, tmp_path):
        rm.enable()
        rm.reset()
        try:
            _losses, sup, _ = _supervised_run(
                tmp_path / "c", spec="train.step=fail,after=4,times=1")
            assert rm.TRAIN_RESTARTS.value() == 1
            snap = rm.snapshot()
            recovery = snap["train.recovery.seconds"]["values"][""]
            assert recovery["count"] == 1
        finally:
            rm.disable()
            rm.reset()

    def test_debug_state_shape(self, tmp_path):
        _losses, sup, _ = _supervised_run(tmp_path / "c")
        state = sup.debug_state()
        assert state["step"] == 12
        assert state["restarts"] == 0
        assert state["crash_loop_tripped"] is False
        assert state["latest_verified_step"] == 12
