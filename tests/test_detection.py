"""SSD MultiBox detection ops: prior generation, target matching/encoding,
decode + NMS (reference: tests/python/unittest/test_operator.py multibox
cases)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_multibox_prior_shapes_and_values():
    data = nd.zeros((1, 3, 2, 2))
    anchors = nd.MultiBoxPrior(data, sizes=(0.5, 0.25), ratios=(1.0, 2.0))
    # K = num_sizes + num_ratios - 1 = 3 boxes per cell, 2x2 cells
    assert anchors.shape == (1, 2 * 2 * 3, 4)
    a = anchors.asnumpy()[0]
    # first cell center is ((0+.5)/2, (0+.5)/2) = (0.25, 0.25); first box
    # is sizes[0]=0.5 at ratio 1: corners (0.25±0.25)
    np.testing.assert_allclose(a[0], [0.0, 0.0, 0.5, 0.5], atol=1e-6)
    # second box: size 0.25 -> (0.25±0.125)
    np.testing.assert_allclose(a[1], [0.125, 0.125, 0.375, 0.375],
                               atol=1e-6)
    # third box: size 0.5 at ratio 2 -> w=0.5*sqrt2/2, h=0.5/sqrt2/2
    w, h = 0.5 * np.sqrt(2) / 2, 0.5 / np.sqrt(2) / 2
    np.testing.assert_allclose(a[2], [0.25 - w, 0.25 - h, 0.25 + w,
                                      0.25 + h], atol=1e-6)


def test_multibox_prior_nonsquare_aspect():
    # reference: w carries the H/W factor so ratio-1 boxes are square in
    # image space (multibox_prior.cc w = size * in_h / in_w / 2)
    data = nd.zeros((1, 3, 2, 4))          # H=2, W=4
    a = nd.MultiBoxPrior(data, sizes=(0.5,)).asnumpy()[0]
    w = a[0, 2] - a[0, 0]
    h = a[0, 3] - a[0, 1]
    np.testing.assert_allclose(w, 0.5 * (2 / 4), atol=1e-6)
    np.testing.assert_allclose(h, 0.5, atol=1e-6)


def test_multibox_prior_clip():
    data = nd.zeros((1, 3, 1, 1))
    anchors = nd.MultiBoxPrior(data, sizes=(1.5,), clip=True)
    a = anchors.asnumpy()
    assert a.min() >= 0.0 and a.max() <= 1.0


def test_multibox_target_matching_and_encoding():
    # two anchors; one gt overlapping anchor 0 exactly
    anchors = nd.array(np.array(
        [[[0.0, 0.0, 0.5, 0.5], [0.5, 0.5, 1.0, 1.0]]], np.float32))
    label = nd.array(np.array(
        [[[1.0, 0.0, 0.0, 0.5, 0.5],
          [-1.0, 0.0, 0.0, 0.0, 0.0]]], np.float32))    # one gt, one pad
    cls_pred = nd.zeros((1, 3, 2))
    box_t, box_m, cls_t = nd.MultiBoxTarget(anchors, label, cls_pred)
    ct = cls_t.asnumpy()[0]
    assert ct[0] == 2.0      # class 1 -> target 1+1 = 2
    assert ct[1] == 0.0      # background
    bm = box_m.asnumpy()[0].reshape(2, 4)
    np.testing.assert_allclose(bm[0], 1.0)
    np.testing.assert_allclose(bm[1], 0.0)
    # perfect match: offsets are all zero
    bt = box_t.asnumpy()[0].reshape(2, 4)
    np.testing.assert_allclose(bt[0], 0.0, atol=1e-5)


def test_multibox_target_offset_encoding_roundtrip():
    # encode with MultiBoxTarget, decode with MultiBoxDetection: the
    # decoded box must reproduce the ground truth
    rng = np.random.RandomState(0)
    anchors_np = np.array([[[0.1, 0.1, 0.6, 0.7]]], np.float32)
    gt = np.array([[[0.0, 0.15, 0.05, 0.7, 0.8]]], np.float32)
    anchors = nd.array(anchors_np)
    label = nd.array(gt)
    cls_pred = nd.zeros((1, 2, 1))
    box_t, box_m, cls_t = nd.MultiBoxTarget(anchors, label, cls_pred)
    assert cls_t.asnumpy()[0, 0] == 1.0

    # feed the encoded offsets back through the decoder
    cls_prob = nd.array(np.array([[[0.1], [0.9]]], np.float32))
    out = nd.MultiBoxDetection(cls_prob, box_t, anchors,
                               threshold=0.5, clip=False)
    row = out.asnumpy()[0, 0]
    assert row[0] == 0.0                 # class id (background excluded)
    np.testing.assert_allclose(row[2:], gt[0, 0, 1:], atol=1e-5)


def test_multibox_detection_nms():
    # three anchors: two heavily overlapping (same class), one separate
    anchors = nd.array(np.array(
        [[[0.1, 0.1, 0.4, 0.4],
          [0.12, 0.1, 0.42, 0.4],
          [0.6, 0.6, 0.9, 0.9]]], np.float32))
    # zero offsets: boxes decode to the anchors themselves
    loc = nd.zeros((1, 12))
    cls_prob = nd.array(np.array(
        [[[0.1, 0.2, 0.1],          # background
          [0.9, 0.8, 0.85]]], np.float32))
    out = nd.MultiBoxDetection(cls_prob, loc, anchors,
                               nms_threshold=0.5).asnumpy()[0]
    kept = out[out[:, 0] >= 0]
    # the weaker of the overlapping pair is suppressed
    assert kept.shape[0] == 2
    np.testing.assert_allclose(sorted(kept[:, 1]), [0.85, 0.9], atol=1e-6)


def test_multibox_detection_threshold():
    anchors = nd.array(np.array([[[0.1, 0.1, 0.4, 0.4]]], np.float32))
    loc = nd.zeros((1, 4))
    cls_prob = nd.array(np.array([[[0.99], [0.005]]], np.float32))
    out = nd.MultiBoxDetection(cls_prob, loc, anchors,
                               threshold=0.01).asnumpy()[0]
    assert (out[:, 0] == -1).all()       # below threshold: all suppressed


def test_multibox_target_negative_mining():
    # 4 anchors, 1 matched; mining ratio 1 keeps only 1 hard negative
    anchors = nd.array(np.array(
        [[[0.0, 0.0, 0.5, 0.5], [0.5, 0.0, 1.0, 0.5],
          [0.0, 0.5, 0.5, 1.0], [0.5, 0.5, 1.0, 1.0]]], np.float32))
    label = nd.array(np.array(
        [[[0.0, 0.0, 0.0, 0.5, 0.5]]], np.float32))
    # background scores: anchor 1 is the "hardest" negative (lowest bg)
    cls_pred = nd.array(np.array(
        [[[0.9, 0.1, 0.8, 0.7], [0.1, 0.9, 0.2, 0.3]]], np.float32))
    _, _, cls_t = nd.MultiBoxTarget(anchors, label, cls_pred,
                                    negative_mining_ratio=1.0,
                                    ignore_label=-1.0)
    ct = cls_t.asnumpy()[0]
    assert ct[0] == 1.0                  # the positive
    assert (ct == 0.0).sum() == 1        # exactly one kept negative
    assert ct[1] == 0.0                  # ...the hardest one
    assert (ct == -1.0).sum() == 2       # the rest ignored


def test_multibox_under_jit():
    # the whole pipeline must compile (static shapes, no python branches)
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.detection import (MultiBoxPrior, MultiBoxTarget,
                                         MultiBoxDetection)

    @jax.jit
    def pipeline(feat, label, cls_pred, cls_prob, loc):
        anchors = MultiBoxPrior(feat, sizes=(0.4, 0.2), ratios=(1.0, 2.0))
        bt, bm, ct = MultiBoxTarget(anchors, label, cls_pred)
        det = MultiBoxDetection(cls_prob, loc, anchors)
        return bt, bm, ct, det

    rng = np.random.RandomState(1)
    feat = jnp.zeros((2, 8, 4, 4))
    N = 4 * 4 * 3
    label = jnp.asarray(rng.rand(2, 3, 5).astype(np.float32))
    label = label.at[:, :, 0].set(0.0)
    cls_pred = jnp.asarray(rng.rand(2, 3, N).astype(np.float32))
    cls_prob = jnp.asarray(rng.rand(2, 3, N).astype(np.float32))
    loc = jnp.asarray(rng.randn(2, N * 4).astype(np.float32) * 0.1)
    bt, bm, ct, det = pipeline(feat, label, cls_pred, cls_prob, loc)
    assert bt.shape == (2, N * 4) and ct.shape == (2, N)
    assert det.shape == (2, N, 6)
    assert np.isfinite(np.asarray(det)).all()
