"""gluon.contrib nn/rnn tests (reference:
tests/python/unittest/test_gluon_contrib.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn, rnn
from mxnet_tpu.gluon.contrib import nn as cnn
from mxnet_tpu.gluon.contrib import rnn as crnn


def test_concurrent_and_identity():
    con = cnn.HybridConcurrent(axis=1)
    con.add(nn.Dense(3), cnn.Identity(), nn.Dense(2))
    con.initialize()
    x = nd.random.uniform(shape=(2, 4))
    out = con(x)
    assert out.shape == (2, 3 + 4 + 2)
    con.hybridize()
    assert np.allclose(con(x).asnumpy(), out.asnumpy(), rtol=1e-5)


def test_pixelshuffle2d():
    x = nd.arange(2 * 8 * 3 * 3).reshape((2, 8, 3, 3))
    ps = cnn.PixelShuffle2D(2)
    y = ps(x)
    assert y.shape == (2, 2, 6, 6)
    # depth-to-space invariant: every input value appears exactly once
    assert np.allclose(np.sort(y.asnumpy().ravel()),
                       np.sort(x.asnumpy().ravel()))
    ps_rect = cnn.PixelShuffle2D((1, 2))
    y2 = ps_rect(x)
    assert y2.shape == (2, 4, 3, 6)


def test_sync_batchnorm_matches_batchnorm():
    mx.random.seed(0)
    x = nd.random.uniform(shape=(4, 3, 5, 5))
    a = cnn.SyncBatchNorm(num_devices=8)
    b = nn.BatchNorm()
    a.initialize()
    b.initialize()
    with autograd.record():
        ya = a(x)
    with autograd.record():
        yb = b(x)
    assert np.allclose(ya.asnumpy(), yb.asnumpy(), rtol=1e-5)


def test_sparse_embedding_trains_only_touched_rows():
    mx.random.seed(0)
    se = cnn.SparseEmbedding(20, 4)
    se.initialize(mx.init.Normal(0.1))
    tr = gluon.Trainer(se.collect_params(), "sgd", {"learning_rate": 1.0})
    x = nd.array([2, 7, 7], dtype="int32")
    with autograd.record():
        se(x).sum().backward()
    before = se.weight.data().asnumpy().copy()
    tr.step(1)
    after = se.weight.data().asnumpy()
    changed = np.abs(after - before).sum(axis=1) > 0
    assert changed[2] and changed[7]
    assert not changed[0] and not changed[19]


def test_variational_dropout_same_mask_across_steps():
    mx.random.seed(3)
    base = rnn.LSTMCell(4, input_size=6)
    vd = crnn.VariationalDropoutCell(base, drop_inputs=0.5)
    vd.initialize()
    vd.reset()
    x = nd.ones((2, 6))
    with autograd.record():
        _, s = vd(x, vd.begin_state(batch_size=2))
        mask1 = vd._mask_in.asnumpy().copy()
        vd(x, s)
        mask2 = vd._mask_in.asnumpy()
    assert np.allclose(mask1, mask2)          # same mask within sequence
    vd.reset()
    with autograd.record():
        vd(x, vd.begin_state(batch_size=2))
    assert not np.allclose(vd._mask_in.asnumpy(), mask1)  # new sequence


def test_conv2d_lstm_cell_unroll():
    mx.random.seed(0)
    cell = crnn.Conv2DLSTMCell((3, 6, 6), 4, 3, 3, i2h_pad=1)
    cell.initialize()
    xs = [nd.random.uniform(shape=(2, 3, 6, 6)) for _ in range(3)]
    outs, states = cell.unroll(3, xs, layout="TNC", merge_outputs=False)
    assert len(outs) == 3
    assert outs[-1].shape == (2, 4, 6, 6)
    assert states[0].shape == (2, 4, 6, 6)
    # gradients flow end to end
    for p in cell.collect_params().values():
        p.grad_req = "write"
    with autograd.record():
        outs, _ = cell.unroll(3, xs, layout="TNC", merge_outputs=False)
        outs[-1].sum().backward()
    g = cell.i2h_weight.grad().asnumpy()
    assert np.abs(g).sum() > 0


def test_conv2d_lstm_default_pad_geometry():
    cell = crnn.Conv2DLSTMCell((3, 6, 6), 4, 3, 3)      # i2h_pad=0
    cell.initialize()
    out, st = cell(nd.random.uniform(shape=(2, 3, 6, 6)),
                   cell.begin_state(batch_size=2))
    assert out.shape == (2, 4, 4, 4)                    # conv output size


def test_variational_dropout_hybridize_raises():
    base = rnn.LSTMCell(4, input_size=6)
    vd = crnn.VariationalDropoutCell(base, drop_inputs=0.5)
    vd.initialize()
    vd.hybridize()
    vd.reset()
    x = nd.ones((2, 6))
    with pytest.raises(mx.MXNetError, match="hybridiz"):
        with autograd.record():
            vd(x, vd.begin_state(batch_size=2))
