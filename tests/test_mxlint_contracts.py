"""ISSUE-15 contract-soundness passes: fault-site-soundness,
deadline-soundness, telemetry-drift — pos/neg/suppression fixtures,
witness chains, registry round-trips, doc-regen check, the
repo-tree-clean gate, and the --changed acceptance (a reintroduced
typo'd fault site and an undeadlined sleep fire through unchanged
helpers).

Pure-AST plus one imported-registry round trip: no jax, milliseconds
(tier-1 budget discipline — the file name sorts into the executed
window).
"""
import json
import logging
import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.mxlint import PASSES, Project, lint_paths, lint_sources  # noqa: E402
from tools.mxlint.passes.fault_site import globs_intersect          # noqa: E402

SITES = {"serving.execute": None,
         "decode.step": ("fail", "delay", "corrupt", "stall"),
         "kv_cache.allocate": ("fail",),
         "replica.<rid>.heartbeat": None,
         "replica.<rid>.decode.step": None}


def run(src, path="mxnet_tpu/serving/fixture.py", select=None,
        sites=SITES, **proj):
    proj.setdefault("fault_sites", sites)
    proj.setdefault("ci_shell_texts", {})
    return lint_sources({path: textwrap.dedent(src)}, select=select,
                        project=Project(**proj))


def ids(issues):
    return [i.pass_id for i in issues]


# ========================================================= glob matching
def test_glob_intersection():
    assert globs_intersect("serving.*", "serving.execute")
    assert globs_intersect("replica.r1.*", "replica.*.decode.step")
    assert globs_intersect("*", "anything.at.all")
    assert globs_intersect("a.b", "a.b")
    assert not globs_intersect("serving.exeucte", "serving.execute")
    assert not globs_intersect("train.*", "serving.execute")
    assert globs_intersect("a.?", "a.b")
    assert not globs_intersect("a.?", "a.bc")


# ==================================================== fault-site-soundness
def test_fault_site_fires_on_typo_literal():
    issues = run("""
        from mxnet_tpu import faults as _faults
        def f():
            _faults.inject("serving.exeucte")
    """, select=["fault-site-soundness"])
    assert ids(issues) == ["fault-site-soundness"]
    assert "serving.exeucte" in issues[0].message
    assert "can never fire" in issues[0].message


def test_fault_site_quiet_on_declared_and_template():
    issues = run("""
        from mxnet_tpu import faults as _faults
        def f(rid):
            _faults.inject("serving.execute")
            _faults.check("kv_cache.allocate")
            _faults.inject(f"replica.{rid}.heartbeat")
    """, select=["fault-site-soundness"])
    assert issues == []


def test_fault_site_dynamic_scope_concat():
    issues = run("""
        from mxnet_tpu import faults as _faults
        class Engine:
            def go(self):
                _faults.inject(self.fault_scope + ".step")
                _faults.inject(self.fault_scope + ".stepp")
    """, select=["fault-site-soundness"])
    assert ids(issues) == ["fault-site-soundness"]
    assert "*.stepp" in issues[0].message


def test_fault_site_helper_routed_with_witness():
    issues = run("""
        from mxnet_tpu import faults as _faults
        def _inject(site, modes):
            raise _faults.InjectedFault(site)
        def wrapper(site):
            _inject(site, modes=("fail",))
        def g():
            wrapper("checkpoint.sav")
    """, select=["fault-site-soundness"])
    assert ids(issues) == ["fault-site-soundness"]
    assert "checkpoint.sav" in issues[0].message
    assert "via wrapper" in issues[0].message
    assert issues[0].line == 8      # at the literal's call site


def test_fault_site_spec_pattern_matches_nothing():
    issues = run("""
        from mxnet_tpu import faults
        def t(monkeypatch):
            with faults.plan("servig.*=fail"):
                pass
            monkeypatch.setenv("MXNET_FAULTS", "decode.step=fail")
            monkeypatch.setenv("MXNET_FAULTS", "decode.stepp=fail")
    """, select=["fault-site-soundness"])
    assert ids(issues) == ["fault-site-soundness"] * 2
    assert "servig.*" in issues[0].message
    assert "decode.stepp" in issues[1].message


def test_fault_site_spec_dead_mode():
    issues = run("""
        from mxnet_tpu import faults
        def t():
            faults.install("kv_cache.allocate=corrupt")
            faults.install("kv_cache.allocate=fail")
    """, select=["fault-site-soundness"])
    assert ids(issues) == ["fault-site-soundness"]
    assert "honors mode" in issues[0].message


def test_fault_site_fstring_spec_glob_ok():
    issues = run("""
        from mxnet_tpu import faults
        def t(victim):
            with faults.plan(f"replica.{victim}.heartbeat=stall"):
                pass
    """, select=["fault-site-soundness"])
    assert issues == []


def test_fault_site_suppression_honored():
    issues = run("""
        from mxnet_tpu import faults as _faults
        def f():
            _faults.inject("x.y")  # mxlint: disable=fault-site-soundness
    """, select=["fault-site-soundness"])
    assert issues == []


def test_fault_site_env_assignment_checked():
    issues = run("""
        import os
        def t():
            os.environ["MXNET_FAULTS"] = "no.such.site=fail"
    """, select=["fault-site-soundness"])
    assert ids(issues) == ["fault-site-soundness"]


def test_fault_site_ci_shell_specs_checked():
    issues = run("""
        def nothing():
            pass
    """, select=["fault-site-soundness"],
        ci_shell_texts={"ci/job.sh": "export MXNET_FAULTS='oops.x=fail'\n"})
    assert ids(issues) == ["fault-site-soundness"]
    assert issues[0].path == "ci/job.sh" and issues[0].line == 1


def test_fault_site_ci_shell_dead_mode_checked():
    """Review fix: the ci/*.sh scan validates modes like the Python
    spec check — `kv_cache.allocate=corrupt` can never fire."""
    issues = run("""
        def nothing():
            pass
    """, select=["fault-site-soundness"],
        ci_shell_texts={
            "ci/job.sh": "MXNET_FAULTS='kv_cache.allocate=corrupt'\n",
            "ci/ok.sh": "MXNET_FAULTS='kv_cache.allocate=fail'\n"})
    assert ids(issues) == ["fault-site-soundness"]
    assert issues[0].path == "ci/job.sh"
    assert "honors mode" in issues[0].message


def test_fault_site_ci_shell_quoted_spec_with_spaces():
    """Review fix: a quoted multi-rule spec may carry whitespace
    between clauses (legal at runtime — FaultPlan.parse strips each
    clause), so the scan must read to the closing quote, not the
    first space — otherwise the typo'd second clause escapes."""
    issues = run("""
        def nothing():
            pass
    """, select=["fault-site-soundness"],
        ci_shell_texts={"ci/job.sh": 'export MXNET_FAULTS='
                        '"serving.execute=fail; decode.stepp=stall"\n'})
    assert ids(issues) == ["fault-site-soundness"]
    assert "decode.stepp" in issues[0].message


def test_fault_site_template_literal_pattern_is_dead():
    """Review fix: a spec pattern that copy-pastes a template name
    from the docs ('replica.<rid>.heartbeat') is dead — '<rid>' is
    literal to fnmatch, so glob intersection against the template must
    not wave it through.  The glob spelling is the live form."""
    issues = run("""
        import os
        def f():
            os.environ["MXNET_FAULTS"] = "replica.<rid>.heartbeat=stall"
    """, select=["fault-site-soundness"])
    assert ids(issues) == ["fault-site-soundness"]
    issues = run("""
        import os
        def f():
            os.environ["MXNET_FAULTS"] = "replica.*.heartbeat=stall"
    """, select=["fault-site-soundness"])
    assert issues == []


def test_fault_site_harvests_declarations_from_scanned_files():
    # a file declaring its own site makes that site valid project-wide
    issues = lint_sources({
        "mxnet_tpu/plugin.py": textwrap.dedent("""
            from mxnet_tpu.faults import declare_fault_site
            declare_fault_site("plugin.flush", modes=("fail",))
        """),
        "mxnet_tpu/user.py": textwrap.dedent("""
            from mxnet_tpu import faults as _faults
            def f():
                _faults.inject("plugin.flush")
        """)}, select=["fault-site-soundness"],
        project=Project(ci_shell_texts={}))
    assert issues == []


def test_fault_site_repo_registry_fallback():
    """Linting a tests/-style file with NO declare_fault_site in the
    scanned set falls back to parsing the repo's faults.py — the CI
    run over tests/ and benchmark/ validates against the real
    catalogue."""
    issues = lint_sources({"tests/t.py": textwrap.dedent("""
        from mxnet_tpu import faults
        def t():
            with faults.plan("serving.execute=fail,times=1"):
                pass
            with faults.plan("serving.exeucte=fail"):
                pass
    """)}, select=["fault-site-soundness"],
        project=Project(ci_shell_texts={}))
    assert ids(issues) == ["fault-site-soundness"]
    assert "serving.exeucte" in issues[0].message


# ============================================= fault registry (runtime)
def test_registry_round_trip_and_parse_warning(caplog):
    from mxnet_tpu import faults
    sites = faults.declared_sites()
    # the catalogue covers every in-tree injection family
    for must in ("serving.execute", "serving.compile", "deploy.execute",
                 "compile_cache.load", "repository.load_artifact",
                 "decode.prefill", "decode.step", "decode.verify",
                 "decode.prefix_lookup", "kv_cache.allocate",
                 "replica.<rid>.execute", "replica.<rid>.heartbeat",
                 "train.step", "train.data.next", "kvstore.push",
                 "kvstore.pull", "kvstore.pushpull", "checkpoint.save",
                 "checkpoint.restore"):
        assert must in sites, must
    assert sites["kv_cache.allocate"].modes == ("fail",)
    assert faults.pattern_matches_declared("replica.r7.decode.step")
    assert not faults.pattern_matches_declared("replica.r7.decode.stepp")
    # review fix: a copy-pasted TEMPLATE name is dead — the literal
    # "<rid>" never fnmatches a runtime site, and glob intersection
    # against the template must not wave it through
    assert not faults.pattern_matches_declared("replica.<rid>.heartbeat")
    assert faults.pattern_matches_declared("replica.*.heartbeat")
    with caplog.at_level(logging.WARNING, logger="mxnet_tpu"):
        # mxlint: disable=fault-site-soundness (deliberately dead
        # pattern: this asserts the runtime warning fires)
        faults.FaultPlan.parse("decode.stepp=fail")
    assert any("can never fire" in r.message for r in caplog.records)
    caplog.clear()
    with caplog.at_level(logging.WARNING, logger="mxnet_tpu"):
        faults.FaultPlan.parse("decode.step=fail,times=1")
    assert not any("can never fire" in r.message
                   for r in caplog.records)


def test_diagnose_reports_mode_dead_rule(capsys):
    """Review fix: diagnose's DEAD RULE report validates the MODE like
    FaultPlan.parse does — kv_cache.allocate is fail-only, so a
    corrupt rule must print as dead, not as a live plan entry."""
    from mxnet_tpu import faults
    import tools.diagnose as dg
    # mxlint: disable=fault-site-soundness (deliberately mode-dead:
    # this asserts the operator-facing DEAD RULE line fires)
    with faults.plan("kv_cache.allocate=corrupt"):
        dg.diagnose()
    out = capsys.readouterr().out
    assert "DEAD RULE" in out and "honors mode" in out


def test_declare_fault_site_validates():
    import pytest
    from mxnet_tpu import faults
    from mxnet_tpu.base import MXNetError
    with pytest.raises(MXNetError, match="dotted lowercase"):
        faults.declare_fault_site("Bad Site")
    with pytest.raises(MXNetError, match="unknown mode"):
        faults.declare_fault_site("ok.site", modes=("explode",))
    assert "ok.site" not in faults.declared_sites()


# ======================================================= deadline-soundness
def test_deadline_fires_on_sleep_in_entry():
    issues = run("""
        import time
        class ModelServer:
            def predict(self, x):
                time.sleep(0.5)
                return x
    """, select=["deadline-soundness"])
    assert ids(issues) == ["deadline-soundness"]
    assert "ModelServer.predict" in issues[0].message


def test_deadline_fires_through_helpers_with_chain():
    issues = run("""
        import time
        def _pace():
            time.sleep(0.01)
        def helper(x):
            _pace()
            return x
        class ModelServer:
            def _worker_loop(self):
                helper(1)
    """, select=["deadline-soundness"])
    assert ids(issues) == ["deadline-soundness"]
    msg = issues[0].message
    assert "ModelServer._worker_loop" in msg
    assert "via helper" in msg and "_pace" in msg
    assert issues[0].line == 4      # anchored at the sleep


def test_deadline_quiet_when_deadline_consumed():
    issues = run("""
        import time
        class ModelServer:
            def predict(self, x, deadline):
                while not deadline.expired():
                    time.sleep(0.01)
            def generate(self, req):
                req.event.wait(req.deadline.remaining())
            def _worker_loop(self):
                retry_call(lambda: 1, retries=2, backoff_ms=1,
                           deadline=self._dl)
    """, select=["deadline-soundness"])
    assert issues == []


def test_deadline_wait_and_queue_get_sinks():
    issues = run("""
        class DecodeEngine:
            def _loop(self):
                self._cond.wait()
            def step(self):
                self._queue.get()
    """, select=["deadline-soundness"])
    assert ids(issues) == ["deadline-soundness"] * 2
    assert "wait" in issues[0].message
    assert "queue pop" in issues[1].message


def test_deadline_bounded_wait_and_get_quiet():
    issues = run("""
        class DecodeEngine:
            def _loop(self):
                self._cond.wait(0.25)
            def step(self):
                self._queue.get(timeout=1.0)
    """, select=["deadline-soundness"])
    assert issues == []


def test_deadline_retry_call_without_deadline():
    issues = run("""
        from mxnet_tpu.serving.resilience import retry_call
        class ModelServer:
            def predict(self, x):
                return retry_call(lambda: x, retries=3, backoff_ms=5)
    """, select=["deadline-soundness"])
    assert ids(issues) == ["deadline-soundness"]
    assert "retry_call" in issues[0].message


def test_deadline_unreachable_code_is_quiet():
    issues = run("""
        import time
        def offline_tool():
            time.sleep(5)       # not reachable from any entry point
    """, select=["deadline-soundness"])
    assert issues == []


def test_deadline_suppression_carries_contract():
    issues = run("""
        class ModelServer:
            def _worker_loop(self):
                # mxlint: disable=deadline-soundness (contract: idle
                # park; every enqueue notifies)
                self._cond.wait()
    """, select=["deadline-soundness"])
    assert issues == []


# ========================================================= telemetry-drift
DOC_METRICS = {"serving.requests": 10, "serving.ghost.metric": 11}
DOC_SPANS = {"serving.predict": 20, "fault.fail": 21, "fault.stall": 22,
             "decode.ghost": 23}


def test_telemetry_undocumented_metric_and_span():
    issues = run("""
        from mxnet_tpu import tracing as _tr
        from mxnet_tpu.runtime_metrics import counter
        REQS = counter("serving.requests", "ok")
        NEW = counter("serving.brand.new", "undocumented")
        def f():
            with _tr.span("serving.predict"):
                pass
            with _tr.span("serving.mystery"):
                pass
    """, path="mxnet_tpu/runtime_metrics.py",
        select=["telemetry-drift"],
        doc_metrics=DOC_METRICS, doc_spans=DOC_SPANS)
    msgs = [i.message for i in issues]
    assert any("serving.brand.new" in m and "undocumented" in m
               for m in msgs)
    assert any("serving.mystery" in m for m in msgs)
    assert not any("serving.requests'" in m and "undocumented" in m
                   for m in msgs)


def test_telemetry_documented_but_dead_rows():
    issues = run("""
        from mxnet_tpu import tracing as _tr
        from mxnet_tpu.runtime_metrics import counter
        REQS = counter("serving.requests", "ok")
        def f():
            with _tr.trace("serving.predict"):
                pass
    """, path="mxnet_tpu/runtime_metrics.py",
        select=["telemetry-drift"],
        doc_metrics=DOC_METRICS, doc_spans=DOC_SPANS)
    # spans authority (tracing.py) not scanned -> span dead rows quiet;
    # metrics authority scanned -> the ghost metric row flags at its
    # doc line
    dead = [i for i in issues if "emitted nowhere" in i.message]
    assert len(dead) == 1
    assert "serving.ghost.metric" in dead[0].message
    assert dead[0].path.endswith("observability.md")
    assert dead[0].line == 11


def test_telemetry_span_glob_covers_documented_family():
    issues = run("""
        from mxnet_tpu import tracing as _tr
        def observe(mode, ctx, now):
            _tr.record_span(f"fault.{mode}", ctx, now, now)
    """, path="mxnet_tpu/tracing.py", select=["telemetry-drift"],
        doc_metrics={}, doc_spans={"fault.fail": 21, "fault.stall": 22})
    assert issues == []     # the glob covers both documented rows


def test_telemetry_span_glob_matching_nothing_flags():
    issues = run("""
        from mxnet_tpu import tracing as _tr
        def observe(kind, ctx, now):
            _tr.record_span(f"ghost.{kind}", ctx, now, now)
    """, path="mxnet_tpu/tracing.py", select=["telemetry-drift"],
        doc_metrics={}, doc_spans={"fault.fail": 21})
    msgs = [i.message for i in issues]
    assert any("ghost.*" in m for m in msgs)


def test_telemetry_suppression_honored():
    issues = run("""
        from mxnet_tpu.runtime_metrics import counter
        X = counter("sneaky.metric", "x")  # mxlint: disable=telemetry-drift
    """, path="mxnet_tpu/runtime_metrics.py",
        select=["telemetry-drift"], doc_metrics={"a.b": 1}, doc_spans={})
    assert [i for i in issues if i.path.endswith("fixture.py")
            or "sneaky" in i.message] == []


def test_telemetry_partial_injection_falls_back_per_side():
    """Review fix: Project(doc_metrics=...) with doc_spans left None
    parses the repo doc for the SPANS side (the core.Project per-side
    fallback contract) instead of treating every span as undocumented.
    `serving.batch` is documented in the real docs/observability.md."""
    issues = run("""
        from mxnet_tpu import tracing as _tr
        def f():
            with _tr.span("serving.batch"):
                pass
    """, select=["telemetry-drift"], doc_metrics={"x.y": 1})
    assert issues == [], "\n".join(str(i) for i in issues)


def test_telemetry_partial_run_never_reports_dead_rows():
    # no authority module in the scanned set -> both dead-row
    # directions stay quiet even though nothing is emitted
    issues = run("""
        def f():
            pass
    """, select=["telemetry-drift"],
        doc_metrics=DOC_METRICS, doc_spans=DOC_SPANS)
    assert issues == []


def test_telemetry_doc_parser_reads_repo_doc():
    from tools.mxlint.passes.telemetry_drift import _doc_tables
    with open(os.path.join(REPO, "docs", "observability.md")) as fh:
        metrics, spans, relative = _doc_tables(fh.read())
    assert "serving.requests" in metrics
    assert "kvstore.push.bytes" in metrics      # normalized, not '.push.bytes'
    assert "serving.predict" in spans and "decode.request" in spans
    assert relative == []       # relative tokens are themselves findings


# ===================================================== repo acceptance gates
def test_repo_tree_clean_under_contract_passes():
    """ISSUE-15 acceptance: the three new passes are clean over
    mxnet_tpu/ + tools/ (sweep findings fixed or contract-noted)."""
    issues = lint_paths(
        [os.path.join(REPO, "mxnet_tpu"), os.path.join(REPO, "tools")],
        select=["fault-site-soundness", "deadline-soundness",
                "telemetry-drift"])
    assert issues == [], "\n".join(str(i) for i in issues)


def test_tests_and_benchmarks_fault_specs_clean():
    """The CI line: chaos specs in tests/ and benchmark/ validate
    against the registry (synthetic machinery sites carry their
    file-level suppression)."""
    issues = lint_paths(
        [os.path.join(REPO, "tests"), os.path.join(REPO, "benchmark")],
        select=["fault-site-soundness"])
    assert issues == [], "\n".join(str(i) for i in issues)


def test_pass_catalogue_is_16():
    assert len(PASSES) == 22


def test_fault_doc_tables_fresh():
    """Doc-regen gate (same discipline as env_vars.md): the generated
    fault-site tables match the committed docs."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "gen_fault_docs.py"), "--check"],
        capture_output=True, text=True, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr


def test_fault_doc_missing_end_marker_is_diagnosed(tmp_path, monkeypatch):
    """Review fix: a doc edit that drops the END marker while keeping
    BEGIN gets the same clean 'missing marker' diagnostic as a missing
    BEGIN — not an unpacking traceback."""
    import tools.gen_fault_docs as gfd
    doc = tmp_path / "serving.md"
    doc.write_text("intro\n" + gfd.BEGIN + "\n| old |\n")   # no END
    monkeypatch.setattr(gfd, "DOCS", {"serving": str(doc)})
    assert gfd.main(check=True) == 2


# ============================================== --changed acceptance (git)
def _git(cwd, *argv):
    proc = subprocess.run(
        ["git"] + list(argv), cwd=cwd, capture_output=True, text=True,
        env=dict(os.environ, GIT_AUTHOR_NAME="t", GIT_AUTHOR_EMAIL="t@t",
                 GIT_COMMITTER_NAME="t", GIT_COMMITTER_EMAIL="t@t",
                 HOME=str(cwd)))
    assert proc.returncode == 0, proc.stderr
    return proc.stdout


def mxlint(*argv, cwd=REPO):
    env = dict(os.environ, PYTHONPATH=REPO)
    return subprocess.run(
        [sys.executable, "-m", "tools.mxlint"] + list(argv),
        cwd=cwd, capture_output=True, text=True, env=env)


HELPER = """\
import time

def pace(ms):
    time.sleep(ms / 1e3)

def fire(faults, site):
    faults.inject(site)
"""

CALLER_V1 = """\
def untouched():
    pass
"""

CALLER_V2 = """\
from .helper import fire, pace
from mxnet_tpu import faults as _faults

class ModelServer:
    def predict(self, x):
        pace(5)                         # undeadlined sleep, 1 hop down
        fire(_faults, "decode.prefil")  # typo'd site through a helper
        return x
"""


def test_changed_mode_catches_reintroduced_contract_bugs(tmp_path):
    """The ISSUE-15 acceptance: a reintroduced typo'd fault site AND an
    undeadlined time.sleep on the predict path are caught by full lint
    AND by --changed when only the caller changed — the interprocedural
    findings fire through the unchanged helper."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "helper.py").write_text(HELPER)
    (pkg / "caller.py").write_text(CALLER_V1)
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "add", ".")
    _git(tmp_path, "commit", "-qm", "seed")
    sel = "fault-site-soundness,deadline-soundness"
    proc = mxlint("pkg", "--select", sel, cwd=tmp_path)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # reintroduce both bug shapes in caller.py only
    (pkg / "caller.py").write_text(CALLER_V2)
    full = mxlint("pkg", "--select", sel, "--format", "json",
                  cwd=tmp_path)
    assert full.returncode == 1, full.stderr
    findings = [json.loads(l) for l in full.stdout.splitlines()]
    by_pass = {f["pass"] for f in findings}
    assert by_pass == {"fault-site-soundness", "deadline-soundness"}
    fault = next(f for f in findings
                 if f["pass"] == "fault-site-soundness")
    assert "decode.prefil" in fault["message"]
    assert "via fire" in fault["message"]
    assert fault["file"] == os.path.join("pkg", "caller.py")
    dl = next(f for f in findings if f["pass"] == "deadline-soundness")
    assert "ModelServer.predict" in dl["message"]
    assert "via pace" in dl["message"]
    # the sleep anchors in the UNCHANGED helper: --changed must still
    # surface the typo'd-site finding at the changed call site, and
    # the full run remains the net for helper-anchored findings
    changed = mxlint("pkg", "--select", sel, "--format", "json",
                     "--changed", cwd=tmp_path)
    assert changed.returncode == 1, changed.stderr
    cfind = [json.loads(l) for l in changed.stdout.splitlines()]
    assert all(f["file"] == os.path.join("pkg", "caller.py")
               for f in cfind)
    assert any(f["pass"] == "fault-site-soundness"
               and "decode.prefil" in f["message"] for f in cfind)
