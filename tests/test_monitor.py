"""Monitor tests: install/tic/toc on a Gluon net, regex filtering,
interval gating, Module integration (reference strategy:
tests/python/unittest/test_monitor.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.monitor import Monitor, default_stat


def _small_net():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(4, activation="relu"), gluon.nn.Dense(2))
    net.initialize()
    return net


class TestMonitorGluon:
    def test_install_tic_toc_collects_outputs_weights_grads(self):
        net = _small_net()
        mon = Monitor(interval=1).install(net)
        mon.tic()
        x = nd.ones((3, 5))
        with autograd.record():
            out = net(x).sum()
        out.backward()
        res = mon.toc()
        names = [n for _step, n, _v in res]
        assert any(n.endswith("_output") for n in names)
        assert any("weight" in n and not n.endswith("_grad")
                   for n in names)
        assert any(n.endswith("weight_grad") for n in names)
        # default stat is finite on a healthy net
        for _step, n, v in res:
            if isinstance(v, float):
                assert np.isfinite(v), (n, v)
        # deactivated after toc: nothing collected until the next tic
        assert mon.toc() == []

    def test_pattern_filters_stats(self):
        net = _small_net()
        mon = Monitor(interval=1, pattern=".*weight.*").install(net)
        mon.tic()
        net(nd.ones((2, 5)))
        res = mon.toc()
        assert res
        assert all("weight" in n for _s, n, _v in res)

    def test_interval_gates_collection(self):
        net = _small_net()
        mon = Monitor(interval=2).install(net)
        mon.tic()                       # step 0: active
        net(nd.ones((2, 5)))
        assert mon.toc()
        mon.tic()                       # step 1: inactive
        net(nd.ones((2, 5)))
        assert mon.toc() == []
        mon.tic()                       # step 2: active again
        net(nd.ones((2, 5)))
        assert mon.toc()

    def test_custom_stat_func_detects_nan(self):
        net = _small_net()
        mon = Monitor(interval=1,
                      stat_func=lambda a: float(
                          np.isnan(a.asnumpy()).any())).install(net)
        mon.tic()
        x = nd.array(np.full((2, 5), np.nan, np.float32))
        net(x)
        res = mon.toc()
        nan_hits = [n for _s, n, v in res
                    if n.endswith("_output") and v == 1.0]
        assert nan_hits                 # NaN propagated and was flagged

    def test_sort_orders_by_name(self):
        net = _small_net()
        mon = Monitor(interval=1, sort=True).install(net)
        mon.tic()
        net(nd.ones((2, 5)))
        res = mon.toc()
        names = [n for _s, n, _v in res]
        assert names == sorted(names)

    def test_toc_print_logs_and_returns(self, caplog):
        import logging
        net = _small_net()
        mon = Monitor(interval=1, pattern=".*bias.*").install(net)
        mon.tic()
        net(nd.ones((2, 5)))
        with caplog.at_level(logging.INFO, logger="mxnet_tpu"):
            res = mon.toc_print()
        assert res
        assert any("bias" in r.message for r in caplog.records)

    def test_hybridized_block_safe(self):
        """Hooks fire with tracer-backed outputs during the CachedOp
        trace; they must be skipped, not poison the engine vars."""
        net = _small_net()
        net.hybridize(static_alloc=True)
        mon = Monitor(interval=1).install(net)
        for _ in range(3):              # trace pass + compiled passes
            mon.tic()
            with autograd.record():
                loss = net(nd.ones((2, 5))).sum()
            loss.backward()
            res = mon.toc()
            # weights/grads still statted at toc even when outputs are
            # unavailable on the compiled path
            assert any("weight" in n for _s, n, _v in res)
            assert not any(str(v).startswith("<error")
                           for _s, _n, v in res)

    def test_install_is_idempotent(self):
        net = _small_net()
        mon = Monitor(interval=1)
        mon.install(net)
        mon.install(net)                # Module.fit re-installs per call
        mon.tic()
        net(nd.ones((2, 5)))
        res = mon.toc()
        names = [n for _s, n, _v in res]
        assert len(names) == len(set(names))    # no duplicated stats

    def test_uninstall_removes_hooks(self):
        """A per-run Monitor must not leave stale hook closures on a
        long-lived net (Module.fit builds one Monitor per fit call)."""
        net = _small_net()
        n_hooks_before = sum(len(b._forward_hooks)
                             for b in net._iter_blocks())
        mon = Monitor(interval=1)
        mon.install(net)
        assert sum(len(b._forward_hooks)
                   for b in net._iter_blocks()) > n_hooks_before
        mon.uninstall()
        assert sum(len(b._forward_hooks)
                   for b in net._iter_blocks()) == n_hooks_before
        # uninstalled monitor collects nothing, and reinstall works
        mon.tic()
        net(nd.ones((2, 5)))
        assert mon.toc() == []
        mon.install(net)
        mon.tic()
        net(nd.ones((2, 5)))
        assert mon.toc()

    def test_default_stat(self):
        v = default_stat(nd.array(np.ones((4,), np.float32) * 3.0))
        assert v == pytest.approx(3.0)

    def test_install_rejects_unknown_target(self):
        with pytest.raises(mx.MXNetError):
            Monitor().install(42)


def _softmax_symbol():
    from mxnet_tpu import sym
    data = sym.var("data")
    label = sym.var("softmax_label")
    out = sym.FullyConnected(data, sym.var("fc_weight"),
                             sym.var("fc_bias"), num_hidden=3, name="fc")
    return sym.SoftmaxOutput(out, label, name="softmax")


class TestMonitorModule:
    def test_module_toc_stats_args_and_outputs(self):
        from mxnet_tpu import sym
        x = sym.var("data")
        y = sym.FullyConnected(x, sym.var("fc_weight"),
                               sym.var("fc_bias"), num_hidden=3, name="fc")
        mod = mx.module.Module(y, data_names=("data",), label_names=None)
        mod.bind(data_shapes=[("data", (2, 6))])
        mod.init_params()
        mon = Monitor(interval=1).install(mod)
        mon.tic()
        batch = mx.io.DataBatch(data=[nd.ones((2, 6))])
        mod.forward(batch, is_train=True)
        mod.backward()
        res = mon.toc()
        names = [n for _s, n, _v in res]
        assert "fc_weight" in names
        assert "fc_weight_grad" in names
        assert any(n.startswith("output") for n in names)

    def test_fit_with_monitor_smoke(self):
        """BaseModule.fit(monitor=...) wires install/tic/toc_print."""
        mon = Monitor(interval=1, pattern=".*weight$")
        data = np.random.rand(8, 6).astype(np.float32)
        labels = np.zeros(8, np.float32)
        it = mx.io.NDArrayIter(data, labels, batch_size=4,
                               label_name="softmax_label")
        mod = mx.module.Module(_softmax_symbol(), context=mx.cpu())
        mod.fit(it, num_epoch=1, monitor=mon,
                optimizer_params=(("learning_rate", 0.01),))
        assert mon.step >= 2            # ticked once per batch
