"""Native C++ IO library tests: build, byte-compat with the python
RecordIO implementation, CSV parser, and the io-tier wiring."""
import os
import struct

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio
from mxnet_tpu.lib import nativelib

pytestmark = pytest.mark.skipif(
    not nativelib.available(),
    reason="native toolchain unavailable (python fallback covers behavior)")

_MAGIC = struct.pack("<I", 0xCED7230A)


class TestNativeRecordIO:
    def test_roundtrip_including_multipart(self, tmp_path):
        path = str(tmp_path / "t.rec")
        payloads = [b"hello", b"x" * 1000, _MAGIC + b"lead",
                    b"a" + _MAGIC + b"b" + _MAGIC + b"c", b""]
        w = nativelib.NativeRecordWriter(path)
        for p in payloads:
            w.write(p)
        w.close()
        r = nativelib.NativeRecordReader(path)
        offs = r.index()
        assert len(offs) == len(payloads)
        assert [r.read_at(o) for o in offs] == payloads

    def test_native_write_python_read(self, tmp_path):
        path = str(tmp_path / "t.rec")
        payloads = [b"one", _MAGIC * 3, b"two" + _MAGIC]
        w = nativelib.NativeRecordWriter(path)
        for p in payloads:
            w.write(p)
        w.close()
        rd = recordio.MXRecordIO(path, "r")
        got = []
        while True:
            s = rd.read()
            if s is None:
                break
            got.append(s)
        assert got == payloads

    def test_python_write_native_read(self, tmp_path):
        path = str(tmp_path / "t.rec")
        payloads = [b"alpha", b"beta" + _MAGIC + b"gamma"]
        wr = recordio.MXRecordIO(path, "w")
        for p in payloads:
            wr.write(p)
        wr.close()
        r = nativelib.NativeRecordReader(path)
        assert [r.read_at(o) for o in r.index()] == payloads

    def test_corrupt_file_detected(self, tmp_path):
        path = str(tmp_path / "bad.rec")
        with open(path, "wb") as f:
            f.write(b"\x00" * 64)
        r = nativelib.NativeRecordReader(path)
        with pytest.raises(IOError):
            r.index()


class TestNativeCSV:
    def test_parse_matches_numpy(self, tmp_path):
        path = str(tmp_path / "d.csv")
        rng = np.random.RandomState(0)
        ref = rng.randn(20, 7).astype(np.float32)
        np.savetxt(path, ref, delimiter=",", fmt="%.6g")
        out = nativelib.csv_load(path)
        ref2 = np.loadtxt(path, delimiter=",", dtype=np.float32, ndmin=2)
        np.testing.assert_array_equal(out, ref2)

    def test_csviter_uses_native(self, tmp_path):
        from mxnet_tpu.io import CSVIter
        path = str(tmp_path / "d.csv")
        lpath = str(tmp_path / "l.csv")
        data = np.arange(24, dtype=np.float32).reshape(6, 4)
        np.savetxt(path, data, delimiter=",", fmt="%g")
        np.savetxt(lpath, np.arange(6, dtype=np.float32), delimiter=",",
                   fmt="%g")
        it = CSVIter(path, (4,), label_csv=lpath, batch_size=3)
        batch = next(it)
        np.testing.assert_array_equal(batch.data[0].asnumpy(), data[:3])

    def test_header_csv_raises(self, tmp_path):
        path = str(tmp_path / "h.csv")
        with open(path, "w") as f:
            f.write("x,y,z\n1,2,3\n")
        with pytest.raises(ValueError):
            nativelib.csv_load(path)

    def test_runtime_reports_native_io(self):
        feats = mx.runtime.Features()
        assert feats.is_enabled("NATIVE_IO")


class TestImageRecordIterNativeScan:
    def test_no_idx_scan_uses_native(self, tmp_path):
        import cv2
        from mxnet_tpu.io import ImageRecordIter
        rec_path = str(tmp_path / "imgs.rec")
        w = recordio.MXRecordIO(rec_path, "w")
        rng = np.random.RandomState(0)
        for i in range(10):
            img = rng.randint(0, 255, (20, 20, 3)).astype(np.uint8)
            header = recordio.IRHeader(0, float(i % 3), i, 0)
            w.write(recordio.pack_img(header, img, img_fmt=".png"))
        w.close()
        it = ImageRecordIter(rec_path, (3, 16, 16), batch_size=5)
        assert it._native is not None          # C++ scanner active
        batch = it.next()
        assert batch.data[0].shape == (5, 3, 16, 16)
        labels = batch.label[0].asnumpy()
        assert set(labels) <= {0.0, 1.0, 2.0}
