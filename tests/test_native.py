"""Native C++ IO library tests: build, byte-compat with the python
RecordIO implementation, CSV parser, and the io-tier wiring."""
import os
import struct

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio
from mxnet_tpu.lib import nativelib

pytestmark = pytest.mark.skipif(
    not nativelib.available(),
    reason="native toolchain unavailable (python fallback covers behavior)")

_MAGIC = struct.pack("<I", 0xCED7230A)


class TestNativeRecordIO:
    def test_roundtrip_including_multipart(self, tmp_path):
        path = str(tmp_path / "t.rec")
        payloads = [b"hello", b"x" * 1000, _MAGIC + b"lead",
                    b"a" + _MAGIC + b"b" + _MAGIC + b"c", b""]
        w = nativelib.NativeRecordWriter(path)
        for p in payloads:
            w.write(p)
        w.close()
        r = nativelib.NativeRecordReader(path)
        offs = r.index()
        assert len(offs) == len(payloads)
        assert [r.read_at(o) for o in offs] == payloads

    def test_native_write_python_read(self, tmp_path):
        path = str(tmp_path / "t.rec")
        payloads = [b"one", _MAGIC * 3, b"two" + _MAGIC]
        w = nativelib.NativeRecordWriter(path)
        for p in payloads:
            w.write(p)
        w.close()
        rd = recordio.MXRecordIO(path, "r")
        got = []
        while True:
            s = rd.read()
            if s is None:
                break
            got.append(s)
        assert got == payloads

    def test_python_write_native_read(self, tmp_path):
        path = str(tmp_path / "t.rec")
        payloads = [b"alpha", b"beta" + _MAGIC + b"gamma"]
        wr = recordio.MXRecordIO(path, "w")
        for p in payloads:
            wr.write(p)
        wr.close()
        r = nativelib.NativeRecordReader(path)
        assert [r.read_at(o) for o in r.index()] == payloads

    def test_corrupt_file_detected(self, tmp_path):
        path = str(tmp_path / "bad.rec")
        with open(path, "wb") as f:
            f.write(b"\x00" * 64)
        r = nativelib.NativeRecordReader(path)
        with pytest.raises(IOError):
            r.index()


class TestNativeCSV:
    def test_parse_matches_numpy(self, tmp_path):
        path = str(tmp_path / "d.csv")
        rng = np.random.RandomState(0)
        ref = rng.randn(20, 7).astype(np.float32)
        np.savetxt(path, ref, delimiter=",", fmt="%.6g")
        out = nativelib.csv_load(path)
        ref2 = np.loadtxt(path, delimiter=",", dtype=np.float32, ndmin=2)
        np.testing.assert_array_equal(out, ref2)

    def test_csviter_uses_native(self, tmp_path):
        from mxnet_tpu.io import CSVIter
        path = str(tmp_path / "d.csv")
        lpath = str(tmp_path / "l.csv")
        data = np.arange(24, dtype=np.float32).reshape(6, 4)
        np.savetxt(path, data, delimiter=",", fmt="%g")
        np.savetxt(lpath, np.arange(6, dtype=np.float32), delimiter=",",
                   fmt="%g")
        it = CSVIter(path, (4,), label_csv=lpath, batch_size=3)
        batch = next(it)
        np.testing.assert_array_equal(batch.data[0].asnumpy(), data[:3])

    def test_header_csv_raises(self, tmp_path):
        path = str(tmp_path / "h.csv")
        with open(path, "w") as f:
            f.write("x,y,z\n1,2,3\n")
        with pytest.raises(ValueError):
            nativelib.csv_load(path)

    def test_runtime_reports_native_io(self):
        feats = mx.runtime.Features()
        assert feats.is_enabled("NATIVE_IO")


class TestImageRecordIterNativeScan:
    def test_no_idx_scan_uses_native(self, tmp_path):
        import cv2
        from mxnet_tpu.io import ImageRecordIter
        rec_path = str(tmp_path / "imgs.rec")
        w = recordio.MXRecordIO(rec_path, "w")
        rng = np.random.RandomState(0)
        for i in range(10):
            img = rng.randint(0, 255, (20, 20, 3)).astype(np.uint8)
            header = recordio.IRHeader(0, float(i % 3), i, 0)
            w.write(recordio.pack_img(header, img, img_fmt=".png"))
        w.close()
        it = ImageRecordIter(rec_path, (3, 16, 16), batch_size=5)
        assert it._native is not None          # C++ scanner active
        batch = it.next()
        assert batch.data[0].shape == (5, 3, 16, 16)
        labels = batch.label[0].asnumpy()
        assert set(labels) <= {0.0, 1.0, 2.0}


class TestNativeJpegDecodeTier:
    """The threaded C++ JPEG batch decoder (nativelib.cc mxjpeg_*)."""

    def _jpeg(self, rng, hw=(300, 400), quality=92):
        import cv2
        img = rng.randint(0, 255, hw + (3,), dtype=np.uint8)
        return img, cv2.imencode(
            ".jpg", img[:, :, ::-1],
            [cv2.IMWRITE_JPEG_QUALITY, quality])[1].tobytes()

    def test_decode_batch_matches_cv2_reference(self):
        import cv2
        from mxnet_tpu.lib import nativelib
        if not nativelib.jpeg_available():
            pytest.skip("no libjpeg on this host")
        rng = np.random.RandomState(0)
        imgs, bufs = zip(*[self._jpeg(rng) for _ in range(4)])
        cy = np.full(4, -1.0, np.float32)      # center-crop sentinel
        mir = np.zeros(4, np.uint8)
        out, status = nativelib.decode_jpeg_batch(
            list(bufs), 256, 224, 224, cy, cy, mir, 2)
        assert status.tolist() == [0, 0, 0, 0]
        assert out.shape == (4, 3, 224, 224) and out.dtype == np.uint8
        for i, buf in enumerate(bufs):
            ref = cv2.imdecode(np.frombuffer(buf, np.uint8),
                               cv2.IMREAD_COLOR)[:, :, ::-1]
            h, w = ref.shape[:2]
            s = 256.0 / min(h, w)
            r = cv2.resize(ref, (int(w * s + 0.5), int(h * s + 0.5)))
            y0 = (r.shape[0] - 224) // 2
            x0 = (r.shape[1] - 224) // 2
            want = r[y0:y0 + 224, x0:x0 + 224].transpose(2, 0, 1)
            diff = np.abs(out[i].astype(int) - want.astype(int)).mean()
            # DCT-reduced decode + independent bilinear: small pixel
            # noise vs the full-decode cv2 reference is expected
            assert diff < 6.0, (i, diff)

    def test_mirror_and_integer_crop(self):
        from mxnet_tpu.lib import nativelib
        if not nativelib.jpeg_available():
            pytest.skip("no libjpeg on this host")
        rng = np.random.RandomState(1)
        _img, buf = self._jpeg(rng, hw=(256, 256))
        cy = np.full(1, -1.0, np.float32)
        plain, s1 = nativelib.decode_jpeg_batch(
            [buf], 0, 224, 224, cy, cy, np.zeros(1, np.uint8), 1)
        flipped, s2 = nativelib.decode_jpeg_batch(
            [buf], 0, 224, 224, cy, cy, np.ones(1, np.uint8), 1)
        assert s1[0] == 0 and s2[0] == 0
        np.testing.assert_array_equal(plain[0], flipped[0][:, :, ::-1])

    def test_bad_payload_reports_status_not_crash(self):
        from mxnet_tpu.lib import nativelib
        if not nativelib.jpeg_available():
            pytest.skip("no libjpeg on this host")
        rng = np.random.RandomState(2)
        _img, good = self._jpeg(rng)
        bad = b"\xff\xd8 not really a jpeg"
        cy = np.full(2, -1.0, np.float32)
        out, status = nativelib.decode_jpeg_batch(
            [bad, good], 256, 64, 64, cy, cy, np.zeros(2, np.uint8), 2)
        assert status[0] == 1 and status[1] == 0

    def test_iterator_mixed_shard_falls_back_per_image(self, tmp_path):
        from mxnet_tpu.io import ImageRecordIter
        from mxnet_tpu.lib import nativelib
        if not nativelib.jpeg_available():
            pytest.skip("no libjpeg on this host")
        rec_path = str(tmp_path / "mix.rec")
        w = recordio.MXIndexedRecordIO(rec_path + ".idx", rec_path, "w")
        rng = np.random.RandomState(3)
        for i in range(12):
            img = rng.randint(0, 255, (300, 400, 3), np.uint8)
            fmt = ".jpg" if i % 3 else ".png"     # every 3rd is PNG
            w.write_idx(i, recordio.pack_img(
                recordio.IRHeader(0, float(i), i, 0), img, quality=90,
                img_fmt=fmt))
        w.close()
        it = ImageRecordIter(rec_path, (3, 224, 224), batch_size=6,
                             shuffle=False, resize=256)
        labels = []
        while True:
            try:
                b = it.next()
            except StopIteration:
                break
            d = b.data[0].asnumpy()
            assert d.shape == (6, 3, 224, 224)
            assert np.isfinite(d).all() and d.max() > 10
            labels += list(b.label[0].asnumpy())
        assert it._native_jpeg                    # probe stayed on
        assert labels == [float(i) for i in range(12)]

    def test_iterator_png_shard_disables_probe(self, tmp_path):
        from mxnet_tpu.io import ImageRecordIter
        from mxnet_tpu.lib import nativelib
        if not nativelib.jpeg_available():
            pytest.skip("no libjpeg on this host")
        rec_path = str(tmp_path / "png.rec")
        w = recordio.MXIndexedRecordIO(rec_path + ".idx", rec_path, "w")
        rng = np.random.RandomState(4)
        for i in range(6):
            img = rng.randint(0, 255, (64, 64, 3), np.uint8)
            w.write_idx(i, recordio.pack_img(
                recordio.IRHeader(0, float(i), i, 0), img,
                img_fmt=".png"))
        w.close()
        it = ImageRecordIter(rec_path, (3, 32, 32), batch_size=6,
                             shuffle=False)
        b = it.next()
        assert b.data[0].shape == (6, 3, 32, 32)
        assert not it._native_jpeg                # probe disabled
