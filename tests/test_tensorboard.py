"""contrib.tensorboard: event-file writer round-trips through the reader
(which verifies TFRecord masked-CRC framing byte-for-byte)."""
import glob
import os

import numpy as np
import pytest

from mxnet_tpu import nd
from mxnet_tpu.contrib.tensorboard import (SummaryWriter, read_events,
                                           _crc32c)


def _events_file(logdir):
    files = glob.glob(os.path.join(str(logdir), "events.out.tfevents.*"))
    assert len(files) == 1
    return files[0]


def test_crc32c_known_vectors():
    # RFC 3720 / kernel test vectors for CRC32C (Castagnoli)
    assert _crc32c(b"") == 0
    assert _crc32c(b"123456789") == 0xE3069283
    assert _crc32c(b"\x00" * 32) == 0x8A9136AA


def test_scalar_roundtrip(tmp_path):
    with SummaryWriter(logdir=tmp_path) as sw:
        for step in range(5):
            sw.add_scalar("train/loss", 1.0 / (step + 1), global_step=step)
    events = read_events(_events_file(tmp_path))
    assert events[0]["file_version"] == "brain.Event:2"
    scalars = [(e["step"], e["values"]["train/loss"])
               for e in events if "train/loss" in e["values"]]
    assert len(scalars) == 5
    for step, value in scalars:
        assert value == pytest.approx(1.0 / (step + 1), rel=1e-6)


def test_scalar_accepts_ndarray(tmp_path):
    with SummaryWriter(logdir=tmp_path) as sw:
        sw.add_scalar("x", nd.array([3.5]).reshape(()), global_step=0)
    events = read_events(_events_file(tmp_path))
    assert events[-1]["values"]["x"] == pytest.approx(3.5)


def test_histogram_roundtrip(tmp_path):
    rng = np.random.RandomState(0)
    data = rng.randn(1000)
    with SummaryWriter(logdir=tmp_path) as sw:
        sw.add_histogram("w", data, global_step=7, bins=20)
    ev = read_events(_events_file(tmp_path))[-1]
    histo = ev["values"]["w"]["histo"]
    assert ev["step"] == 7
    assert histo["num"] == pytest.approx(1000)
    assert histo["min"] == pytest.approx(data.min())
    assert histo["max"] == pytest.approx(data.max())
    assert histo["sum"] == pytest.approx(data.sum(), rel=1e-6)
    assert sum(histo["bucket"]) == pytest.approx(1000)
    assert len(histo["bucket_limit"]) == len(histo["bucket"]) == 20


def test_image_roundtrip(tmp_path):
    from mxnet_tpu.image.image import imdecode
    img = (np.arange(8 * 6 * 3) % 256).reshape(8, 6, 3).astype(np.uint8)
    with SummaryWriter(logdir=tmp_path) as sw:
        sw.add_image("pic", img, global_step=1)
    ev = read_events(_events_file(tmp_path))[-1]
    h, w, c, png = ev["values"]["pic"]["image"]
    assert (h, w, c) == (8, 6, 3)
    decoded = imdecode(png)  # default to_rgb=True: PNG payload is RGB
    np.testing.assert_array_equal(np.asarray(decoded.asnumpy()), img)


def test_image_constant_float_clamps(tmp_path):
    from mxnet_tpu.image.image import imdecode
    # constant out-of-range float image must clamp, not wrap modulo 256
    img = np.full((4, 4), 2.0, np.float64)
    with SummaryWriter(logdir=tmp_path) as sw:
        sw.add_image("c", img)
    ev = read_events(_events_file(tmp_path))[-1]
    h, w, c, png = ev["values"]["c"]["image"]
    decoded = np.asarray(imdecode(png, flag=0).asnumpy())
    assert decoded.min() == decoded.max() == 255


def test_text_roundtrip(tmp_path):
    with SummaryWriter(logdir=tmp_path) as sw:
        sw.add_text("note", "hello tpu", global_step=2)
    ev = read_events(_events_file(tmp_path))[-1]
    assert ev["values"]["note"]["text"] == "hello tpu"


def test_log_metrics_callback(tmp_path):
    from types import SimpleNamespace
    from mxnet_tpu import metric
    from mxnet_tpu.contrib.tensorboard import LogMetricsCallback

    m = metric.Accuracy()
    m.update([nd.array([0, 1])], [nd.array([[0.9, 0.1], [0.2, 0.8]])])
    cb = LogMetricsCallback(str(tmp_path), prefix="train")
    cb(SimpleNamespace(eval_metric=m))
    cb.summary_writer.close()
    ev = read_events(_events_file(tmp_path))[-1]
    assert ev["values"]["train-accuracy"] == pytest.approx(1.0)
