"""INT8 quantization tests (reference test strategy:
tests/python/quantization/test_quantization.py — SURVEY.md 4)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, gluon
from mxnet_tpu.contrib import quantization as qt
from mxnet_tpu.gluon import nn


def test_quantize_dequantize_roundtrip():
    x = nd.random.uniform(-3, 3, shape=(16, 32))
    q, mn, mxr = nd.quantize_v2(x)
    assert str(q.dtype) == "int8"
    back = nd.dequantize(q, mn, mxr)
    scale = 3.0 / 127
    assert np.abs(back.asnumpy() - x.asnumpy()).max() < scale * 1.01


def test_quantize_with_calib_range_clips():
    x = nd.array([[-10.0, -1.0, 0.0, 1.0, 10.0]])
    q, mn, mxr = nd.quantize_v2(x, min_calib_range=-2.0, max_calib_range=2.0)
    qa = q.asnumpy()
    assert qa.min() == -127 and qa.max() == 127
    assert float(mxr.asscalar()) == pytest.approx(2.0)


def test_requantize_int32_to_int8():
    x = nd.random.uniform(-1, 1, shape=(8, 8))
    w = nd.random.uniform(-1, 1, shape=(4, 8))
    qx, xmn, xmx = nd.quantize_v2(x)
    qw, wmn, wmx = nd.quantize_v2(w)
    out32, omn, omx = nd.quantized_fully_connected(
        qx, qw, None, xmn, xmx, wmn, wmx, None, None,
        num_hidden=4, no_bias=True)
    q8, rmn, rmx = nd.requantize(out32, omn, omx)
    assert str(q8.dtype) == "int8"
    got = nd.dequantize(q8, rmn, rmx).asnumpy()
    ref = x.asnumpy() @ w.asnumpy().T
    assert np.abs(got - ref).max() < 0.05


def test_quantized_conv_matches_fp32():
    rng = np.random.RandomState(3)
    x = nd.array(rng.uniform(-1, 1, (2, 3, 8, 8)).astype(np.float32))
    w = nd.array(rng.uniform(-1, 1, (4, 3, 3, 3)).astype(np.float32))
    b = nd.array(rng.uniform(-1, 1, (4,)).astype(np.float32))
    qx, xmn, xmx = nd.quantize_v2(x)
    qw, wmn, wmx = nd.quantize_v2(w)
    qb, bmn, bmx = nd.quantize_v2(b)
    out32, omn, omx = nd.quantized_conv(
        qx, qw, qb, xmn, xmx, wmn, wmx, bmn, bmx,
        kernel=(3, 3), pad=(1, 1), num_filter=4)
    got = nd.dequantize(out32, omn, omx).asnumpy()
    ref = nd.Convolution(x, w, b, kernel=(3, 3), pad=(1, 1),
                         num_filter=4).asnumpy()
    assert np.abs(got - ref).max() < 0.2
    assert np.corrcoef(got.ravel(), ref.ravel())[0, 1] > 0.999


def _make_net():
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, 3, padding=1, activation="relu"),
            nn.MaxPool2D(2),
            nn.Dense(32, activation="relu"),
            nn.Dense(10))
    net.initialize()
    return net


@pytest.mark.parametrize("calib_mode", ["none", "naive", "entropy"])
def test_quantize_net_close_to_fp32(calib_mode):
    mx.random.seed(0)
    net = _make_net()
    x = nd.random.uniform(-1, 1, shape=(4, 3, 16, 16))
    ref = net(x).asnumpy()
    calib = [x] if calib_mode != "none" else None
    qnet = qt.quantize_net(net, calib_mode=calib_mode, calib_data=calib)
    out = qnet(x).asnumpy()
    assert out.shape == ref.shape
    # int8 keeps ranking/structure: high correlation, modest abs error
    assert np.corrcoef(out.ravel(), ref.ravel())[0, 1] > 0.99
    assert np.abs(out - ref).max() < 0.25 * max(1.0, np.abs(ref).max())


def test_quantize_net_excludes_and_hybridize():
    net = _make_net()
    x = nd.random.uniform(shape=(2, 3, 16, 16))
    ref = net(x).asnumpy()
    # exclude every Dense layer by match -> only the conv quantizes
    qnet = qt.quantize_net(net, exclude_layers_match=["dense"])
    from mxnet_tpu.gluon.nn import Dense
    denses = [b for b in qnet._children.values() if isinstance(b, Dense)]
    assert len(denses) == 2
    qnet.hybridize()
    out = qnet(x).asnumpy()
    assert np.corrcoef(out.ravel(), ref.ravel())[0, 1] > 0.99
    out2 = qnet(x).asnumpy()          # cached-op path reuse
    assert np.allclose(out, out2)


def test_entropy_threshold_ignores_outlier():
    rng = np.random.RandomState(0)
    data = np.concatenate([rng.normal(0, 1, 100000),
                           [1000.0]]).astype(np.float32)
    c = qt.CalibrationCollector(mode="entropy")
    c.collect("t", data)
    (mn, mxr), = c.ranges().values()
    # KL calibration clips the single huge outlier; naive would keep 1000
    assert mxr < 100.0
    assert mn == -mxr


def test_quantize_model_symbolic():
    import mxnet_tpu.symbol as sym
    data = sym.var("data")
    w1 = sym.var("fc1_weight")
    b1 = sym.var("fc1_bias")
    fc1 = sym.FullyConnected(data, w1, b1, num_hidden=16, name="fc1")
    act = sym.Activation(fc1, act_type="relu")
    w2 = sym.var("fc2_weight")
    b2 = sym.var("fc2_bias")
    out = sym.FullyConnected(act, w2, b2, num_hidden=4, name="fc2")

    rng = np.random.RandomState(0)
    args = {"fc1_weight": nd.array(rng.randn(16, 8) * 0.3),
            "fc1_bias": nd.array(rng.randn(16) * 0.1),
            "fc2_weight": nd.array(rng.randn(4, 16) * 0.3),
            "fc2_bias": nd.array(rng.randn(4) * 0.1)}
    x = nd.array(rng.randn(8, 8).astype(np.float32))
    ref = out.eval(data=x, **args)[0].asnumpy()

    qsym, qargs, _ = qt.quantize_model(out, args, calib_mode="naive",
                                       calib_data=[x])
    qnames = qsym.list_arguments()
    assert "fc1_weight_quantize" in qnames
    assert str(qargs["fc1_weight_quantize"].dtype) == "int8"
    got = qsym.eval(data=x, **qargs)[0].asnumpy()
    assert np.corrcoef(got.ravel(), ref.ravel())[0, 1] > 0.99
    assert np.abs(got - ref).max() < 0.25 * max(1.0, np.abs(ref).max())


def test_quantize_model_excluded_layer_stays_fp32():
    import mxnet_tpu.symbol as sym
    data = sym.var("data")
    w1 = sym.var("w1")
    fc1 = sym.FullyConnected(data, w1, num_hidden=8, no_bias=True,
                             name="fc1")
    qsym, _ = qt.quantize_graph(fc1, excluded_sym_names=["fc1"])
    assert "w1_quantize" not in qsym.list_arguments()
    assert "w1" in qsym.list_arguments()


def test_zero_range_all_zero_batch_keeps_bias():
    # dead-ReLU batch: all-zero input must not NaN/zero-poison the layer
    dense = nn.Dense(4, in_units=3)
    net = nn.HybridSequential()
    net.add(dense)
    net.initialize()
    dense.bias.set_data(nd.array([1.0, -2.0, 3.0, 0.5]))
    x = nd.zeros((2, 3))
    ref = net(x).asnumpy()
    qnet = qt.quantize_net(net)
    out = qnet(x).asnumpy()
    assert np.isfinite(out).all()
    assert np.abs(out - ref).max() < 0.05, (out, ref)
