"""CheckpointManager crash safety: the atomic last-step marker and the
SIGTERM/preemption save hook (parallel/checkpoint.py).

A fake orbax backend (plain JSON files + an explicit "durable" switch)
drives the torn-save scenarios deterministically: a kill mid-async-save
must never leave the latest-pointer at a checkpoint that was not yet
durable, and the signal hook must produce one synchronous save + marker
commit before chaining to the previous handler.
"""
import json
import os
import signal

import numpy as np
import pytest

import mxnet_tpu.parallel.checkpoint as cp
from mxnet_tpu.base import MXNetError


class FakeManager:
    """Mimics orbax.checkpoint.CheckpointManager closely enough for the
    marker/signal logic: save() records the step IMMEDIATELY (the torn
    window — the directory exists before the data is durable);
    wait_until_finished() makes pending saves durable."""

    def __init__(self, directory, options=None):
        self.dir = str(directory)
        os.makedirs(self.dir, exist_ok=True)
        self.pending = []               # saved, not yet durable
        self.waits = 0
        self.closed = False

    def _steps_file(self):
        return os.path.join(self.dir, "steps.json")

    def _durable_steps(self):
        try:
            with open(self._steps_file()) as f:
                return {int(k): v for k, v in json.load(f).items()}
        except OSError:
            return {}

    def save(self, step, args=None):
        self.pending.append((int(step), args.state))

    def wait_until_finished(self):
        self.waits += 1
        steps = self._durable_steps()
        for step, state in self.pending:
            steps[step] = state
        self.pending = []
        with open(self._steps_file(), "w") as f:
            json.dump({str(k): v for k, v in steps.items()}, f)

    def latest_step(self):
        steps = set(self._durable_steps())
        # orbax's directory listing ALSO sees in-flight (torn) steps —
        # exactly the hazard the marker exists to close
        steps |= {s for s, _ in self.pending}
        return max(steps) if steps else None

    def all_steps(self):
        return sorted(self._durable_steps())

    def restore(self, step, args=None):
        steps = self._durable_steps()
        if step not in steps:
            raise AssertionError(
                f"restore({step}): torn/unknown step (durable: "
                f"{sorted(steps)})")
        return steps[step]

    def close(self):
        self.closed = True


class FakeArgs:
    def __init__(self, state):
        self.state = state


class FakeOcp:
    CheckpointManager = FakeManager

    class CheckpointManagerOptions:
        def __init__(self, **kw):
            self.kw = kw

    class args:                          # noqa: N801 — orbax shape
        StandardSave = FakeArgs
        StandardRestore = FakeArgs


class FakeTrainer:
    def __init__(self, val=1.0):
        self.params = {"w": val}
        self.opt_state = {"m": 0.0}


@pytest.fixture
def fake_ocp(monkeypatch):
    monkeypatch.setattr(cp, "_ocp", lambda: FakeOcp)
    # the fake state is a plain dict, not an array pytree
    monkeypatch.setattr(cp, "_abstract_like", lambda tree: tree)
    monkeypatch.setattr(
        cp, "_trainer_state",
        lambda t: {"params": dict(t.params),
                   "opt_state": dict(t.opt_state)})
    return FakeOcp


class TestMarker:
    def test_marker_advances_only_at_the_barrier(self, fake_ocp,
                                                 tmp_path):
        m = cp.CheckpointManager(tmp_path)
        m.save(1, FakeTrainer())
        # async save in flight: backend lists step 1, marker does not
        assert m._mngr.latest_step() == 1
        assert m.latest_verified_step() is None
        m.wait()
        assert m.latest_verified_step() == 1
        assert m.latest_step() == 1

    def test_kill_mid_save_restores_last_verified(self, fake_ocp,
                                                  tmp_path):
        """The regression: a kill between save(2) and its durability
        barrier must leave restore() on step 1 — the backend's listing
        says 2 (torn), the marker says 1 (verified)."""
        m = cp.CheckpointManager(tmp_path)
        m.save(1, FakeTrainer(1.0))
        m.wait()
        m.save(2, FakeTrainer(2.0))     # ... killed here: no wait()

        # a fresh process opens the same directory
        m2 = cp.CheckpointManager(tmp_path)
        assert m2._mngr.latest_step() == 1      # fake: torn 2 vanished
        t = FakeTrainer(0.0)
        step = m2.restore(t)
        assert step == 1
        assert t.params["w"] == 1.0

    def test_marker_beats_backend_listing(self, fake_ocp, tmp_path):
        """Even when the torn step SURVIVES in the directory listing
        (the real orbax hazard), the marker pins restore to the
        verified step."""
        m = cp.CheckpointManager(tmp_path)
        m.save(1, FakeTrainer(1.0))
        m.wait()
        m.save(2, FakeTrainer(2.0))
        # torn: the backend still lists step 2 via pending
        assert m._mngr.latest_step() == 2
        assert m.latest_step() == 1     # marker wins
        t = FakeTrainer(0.0)
        assert m.restore(t) == 1 and t.params["w"] == 1.0

    def test_marker_write_is_atomic(self, fake_ocp, tmp_path):
        m = cp.CheckpointManager(tmp_path)
        m.save(3, FakeTrainer())
        m.wait()
        # no tmp leftovers; content is exactly the step
        assert not os.path.exists(m._marker_path + ".tmp")
        with open(m._marker_path) as f:
            assert f.read().strip() == "3"
        # a corrupted marker degrades to the backend listing
        with open(m._marker_path, "w") as f:
            f.write("garbage")
        assert m.latest_verified_step() is None
        assert m.latest_step() == 3


class TestSaveOnSignal:
    def test_sigterm_saves_then_chains(self, fake_ocp, tmp_path):
        chained = []
        prev = signal.signal(signal.SIGTERM,
                             lambda s, f: chained.append(s))
        try:
            m = cp.CheckpointManager(tmp_path)
            trainer = FakeTrainer(7.0)
            m.save_on_signal(trainer, step_fn=lambda: 42)
            signal.raise_signal(signal.SIGTERM)
            # one synchronous save + barrier + marker, then the chain
            assert m.latest_verified_step() == 42
            t = FakeTrainer(0.0)
            assert m.restore(t) == 42 and t.params["w"] == 7.0
            assert chained == [signal.SIGTERM]
            # uninstall restores the previous handler
            m.remove_signal_handlers()
            signal.raise_signal(signal.SIGTERM)
            assert chained == [signal.SIGTERM, signal.SIGTERM]
        finally:
            signal.signal(signal.SIGTERM, prev)

    def test_step_fn_evaluated_at_signal_time(self, fake_ocp, tmp_path):
        prev = signal.signal(signal.SIGTERM, lambda s, f: None)
        try:
            m = cp.CheckpointManager(tmp_path)
            box = {"step": 0}
            m.save_on_signal(FakeTrainer(), step_fn=lambda: box["step"])
            box["step"] = 9
            signal.raise_signal(signal.SIGTERM)
            assert m.latest_verified_step() == 9
            m.remove_signal_handlers()
        finally:
            signal.signal(signal.SIGTERM, prev)

    def test_failed_signal_save_still_chains(self, fake_ocp, tmp_path):
        chained = []
        prev = signal.signal(signal.SIGTERM,
                             lambda s, f: chained.append(s))
        try:
            m = cp.CheckpointManager(tmp_path)
            m.save(1, FakeTrainer(1.0))
            m.wait()

            def bad_step():
                raise RuntimeError("no step available")

            m.save_on_signal(FakeTrainer(), step_fn=bad_step)
            signal.raise_signal(signal.SIGTERM)
            # marker untouched, previous handler still ran
            assert m.latest_verified_step() == 1
            assert chained == [signal.SIGTERM]
            m.remove_signal_handlers()
        finally:
            signal.signal(signal.SIGTERM, prev)

    def test_step_fn_must_be_callable(self, fake_ocp, tmp_path):
        m = cp.CheckpointManager(tmp_path)
        with pytest.raises(MXNetError, match="zero-arg callable"):
            m.save_on_signal(FakeTrainer(), step_fn=5)

    def test_context_exit_removes_handlers(self, fake_ocp, tmp_path):
        prev = signal.getsignal(signal.SIGTERM)
        with cp.CheckpointManager(tmp_path) as m:
            m.save_on_signal(FakeTrainer(), step_fn=lambda: 1)
            assert signal.getsignal(signal.SIGTERM) is not prev
        assert signal.getsignal(signal.SIGTERM) is prev


class TestRealBackendMarker:
    """One thin end-to-end pass over the REAL orbax backend (skipped
    when orbax is absent): the marker rides an actual async save."""

    def test_roundtrip_marker(self, tmp_path):
        pytest.importorskip("orbax.checkpoint")
        import jax
        import jax.numpy as jnp

        class T:
            params = {"w": jnp.ones((2,))}
            opt_state = {"m": jnp.zeros((2,))}

        t = T()
        with cp.CheckpointManager(tmp_path, async_write=False) as m:
            m.save(5, t)
            m.wait()
            assert m.latest_verified_step() == 5
            t.params = {"w": jnp.zeros((2,))}
            assert m.restore(t) == 5
            np.testing.assert_array_equal(
                np.asarray(t.params["w"]), np.ones((2,)))
        del jax


class TestReviewHardening:
    def test_gc_collected_marker_falls_back_to_backend(self, fake_ocp,
                                                       tmp_path):
        """Review fix: max_to_keep retention may delete the marker's
        step after later saves landed without a barrier — restore must
        fall back to the backend's newest listed step, not wedge on
        the vanished one."""
        m = cp.CheckpointManager(tmp_path)
        m.save(5, FakeTrainer(5.0))
        m.wait()                        # marker = 5
        m.save(6, FakeTrainer(6.0))
        m.wait()                        # durable 5, 6; marker = 5? no: 6
        assert m.latest_verified_step() == 6
        # simulate retention GC of step 6 leaving only 5... instead:
        # marker at 6, backend loses 6 and gains 7 (saved elsewhere)
        steps = m._mngr._durable_steps()
        state7 = steps[6]
        del steps[6]
        steps[7] = state7
        with open(m._mngr._steps_file(), "w") as f:
            import json as _json
            _json.dump({str(k): v for k, v in steps.items()}, f)
        # marker says 6, backend has {5, 7}: fall back to the listing
        assert m.latest_step() == 7
        t = FakeTrainer(0.0)
        assert m.restore(t) == 7

    def test_none_previous_disposition_still_terminates(self, fake_ocp,
                                                        tmp_path,
                                                        monkeypatch):
        """Review fix: signal.signal() returns None for a C-installed
        handler; the chain must re-raise with the default action (the
        process terminates), never swallow the signal."""
        actions = []
        prev = signal.signal(signal.SIGTERM, lambda s, f: None)
        try:
            m = cp.CheckpointManager(tmp_path)
            m.save_on_signal(FakeTrainer(3.0), step_fn=lambda: 7)
            handler = signal.getsignal(signal.SIGTERM)
            m._signal_prev[signal.SIGTERM] = None   # C-level unknown
            monkeypatch.setattr(
                cp._signal, "signal",
                lambda s, h: actions.append(("reset", h)))
            monkeypatch.setattr(
                cp._signal, "raise_signal",
                lambda s: actions.append(("raise", s)))
            handler(signal.SIGTERM, None)
            assert m.latest_verified_step() == 7    # save still ran
            assert ("reset", signal.SIG_DFL) in actions
            assert ("raise", signal.SIGTERM) in actions
            m._signal_prev[signal.SIGTERM] = prev
            m.remove_signal_handlers()
        finally:
            signal.signal(signal.SIGTERM, prev)

    def test_sig_ign_previous_disposition_is_respected(self, fake_ocp,
                                                       tmp_path):
        prev = signal.signal(signal.SIGTERM, signal.SIG_IGN)
        try:
            m = cp.CheckpointManager(tmp_path)
            m.save_on_signal(FakeTrainer(), step_fn=lambda: 1)
            signal.raise_signal(signal.SIGTERM)     # must NOT kill us
            assert m.latest_verified_step() == 1
            m.remove_signal_handlers()
        finally:
            signal.signal(signal.SIGTERM, prev)
