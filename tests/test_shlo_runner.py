"""The C++ PJRT loader consumes exported StableHLO artifacts with NO
framework and NO Python — the frontends/deployment claim proven
language-neutral (docs/frontends.md §2; VERDICT r3 stretch item).

Opt-in: needs a PJRT plugin .so and possibly the accelerator it talks
to, so it only runs when MXNET_TEST_PJRT_PLUGIN is set (the
`native_build` CI job does this where a plugin is available).  On this
image the available plugin is the axon TPU tunnel — the run happens on
the real chip, which also means it must not race a live bench.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, gluon, deploy
from mxnet_tpu.gluon import nn

pytestmark = pytest.mark.skipif(
    not os.environ.get("MXNET_TEST_PJRT_PLUGIN"),
    reason="set MXNET_TEST_PJRT_PLUGIN=/path/plugin.so to run the "
           "framework-free PJRT loader end-to-end")


def test_cpp_loader_matches_python(tmp_path):
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import shlo_run

    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, 3, activation="relu"), nn.MaxPool2D(),
            nn.Flatten(), nn.Dense(10))
    net.initialize(mx.init.Xavier())
    x = nd.array(np.random.RandomState(0).randn(2, 1, 28, 28)
                 .astype(np.float32))
    net(x)
    prefix = str(tmp_path / "lenet")
    deploy.export_stablehlo(net, x, path=prefix, emit_text=True)
    ref = net(x).asnumpy()
    xbin = str(tmp_path / "x.bin")
    x.asnumpy().tofile(xbin)

    proc = shlo_run.run(prefix + ".stablehlo.txt",
                        str(tmp_path / "out"),
                        [f"f32@2x1x28x28@{xbin}"], check=False)
    assert proc.returncode == 0, proc.stderr
    meta = open(str(tmp_path / "out.0.meta")).read().split()
    assert meta[0] == "f32" and meta[1:] == ["2", "10"], meta
    out = np.fromfile(str(tmp_path / "out.0.bin"),
                      np.float32).reshape(2, 10)
    np.testing.assert_allclose(out, ref, rtol=2e-2, atol=2e-2)
