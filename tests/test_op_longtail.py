"""Long-tail reference ops added in r3 (cumsum/cumprod, split_v2, Crop,
im2col/col2im, SpatialTransformer, ROIPooling, ...)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


def test_cumsum_cumprod():
    x = nd.array(np.arange(6.0).reshape(2, 3))
    np.testing.assert_allclose(nd.cumsum(x, axis=1).asnumpy(),
                               np.cumsum(x.asnumpy(), axis=1))
    np.testing.assert_allclose(nd.cumsum(x).asnumpy(),
                               np.cumsum(x.asnumpy()))
    np.testing.assert_allclose(nd.cumprod(x, axis=0).asnumpy(),
                               np.cumprod(x.asnumpy(), axis=0))
    # differentiable
    x.attach_grad()
    with autograd.record():
        y = nd.sum(nd.cumsum(x, axis=1))
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [[3, 2, 1], [3, 2, 1]])


def test_digamma_unravel():
    np.testing.assert_allclose(
        nd.digamma(nd.array(np.array([1.0]))).asnumpy(), [-0.5772157],
        rtol=1e-5)
    u = nd.unravel_index(nd.array(np.array([5, 7]), dtype="int32"),
                         shape=(3, 4))
    assert u.asnumpy().tolist() == [[1, 1], [1, 3]]


def test_split_v2():
    a, b = nd.split_v2(nd.array(np.arange(8.0)),
                       indices_or_sections=(3,))
    assert a.shape == (3,) and b.shape == (5,)
    parts = nd.split_v2(nd.array(np.arange(8.0).reshape(2, 4)),
                        indices_or_sections=2, axis=0, squeeze_axis=True)
    assert parts[0].shape == (4,)


def test_crop():
    img = nd.array(np.arange(2 * 3 * 6 * 6, dtype=np.float32)
                   .reshape(2, 3, 6, 6))
    c = nd.Crop(img, offset=(1, 2), h_w=(3, 3))
    np.testing.assert_allclose(c.asnumpy(),
                               img.asnumpy()[:, :, 1:4, 2:5])
    like = nd.zeros((1, 1, 4, 4))
    c2 = nd.Crop(img, like, center_crop=True, num_args=2)
    np.testing.assert_allclose(c2.asnumpy(),
                               img.asnumpy()[:, :, 1:5, 1:5])


def test_im2col_col2im_adjoint():
    rng = np.random.RandomState(0)
    img = nd.array(rng.randn(2, 3, 8, 8).astype(np.float32))
    kw = dict(kernel=(3, 3), stride=(2, 2), pad=(1, 1))
    cols = nd.im2col(img, **kw)
    assert cols.shape == (2, 27, 16)
    y = nd.array(rng.randn(*cols.shape).astype(np.float32))
    lhs = float((cols * y).sum().asnumpy())
    rhs = float((img * nd.col2im(y, output_size=(8, 8), **kw))
                .sum().asnumpy())
    assert abs(lhs - rhs) < 1e-2 * max(1.0, abs(lhs))


def test_hard_sigmoid():
    x = nd.array(np.linspace(-5, 5, 11))
    hs = nd.hard_sigmoid(x).asnumpy()
    np.testing.assert_allclose(
        hs, np.clip(0.2 * x.asnumpy() + 0.5, 0, 1), rtol=1e-6)


def test_spatial_transformer_identity():
    rng = np.random.RandomState(1)
    img = nd.array(rng.randn(2, 3, 5, 5).astype(np.float32))
    ident = nd.array(np.tile(
        np.array([1, 0, 0, 0, 1, 0], np.float32), (2, 1)))
    out = nd.SpatialTransformer(img, ident, target_shape=(5, 5))
    np.testing.assert_allclose(out.asnumpy(), img.asnumpy(), atol=1e-5)


def test_roi_pooling():
    data = nd.array(np.arange(64, dtype=np.float32).reshape(1, 1, 8, 8))
    rois = nd.array(np.array([[0, 0, 0, 7, 7]], np.float32))
    out = nd.ROIPooling(data, rois, pooled_size=(2, 2),
                        spatial_scale=1.0)
    assert out.shape == (1, 1, 2, 2)
    # max of each quadrant-ish bin: bottom-right bin holds the max value
    assert float(out.asnumpy()[0, 0, 1, 1]) == 63.0
    assert float(out.asnumpy().min()) >= 0.0


def test_roi_pooling_covers_all_pixels():
    """Wide bins must not skip pixels: a lone max in a corner survives."""
    arr = np.zeros((1, 1, 8, 8), np.float32)
    arr[0, 0, 0, 0] = 100.0
    out = nd.ROIPooling(nd.array(arr),
                        nd.array(np.array([[0, 0, 0, 7, 7]], np.float32)),
                        pooled_size=(2, 2), spatial_scale=1.0)
    assert float(out.asnumpy()[0, 0, 0, 0]) == 100.0


def test_crop_out_of_bounds_raises():
    img = nd.zeros((1, 1, 4, 4))
    with pytest.raises(mx.base.MXNetError, match="exceeds"):
        nd.Crop(img, h_w=(6, 6))
    with pytest.raises(mx.base.MXNetError, match="exceeds"):
        nd.Crop(img, nd.zeros((1, 1, 6, 6)), center_crop=True, num_args=2)
