"""Autograd tape tests (reference: tests/python/unittest/test_autograd.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd
from mxnet_tpu.test_utils import assert_almost_equal, check_numeric_gradient


def test_simple_grad():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    assert_almost_equal(x.grad.asnumpy(), 2 * x.asnumpy())


def test_chain_rule():
    x = nd.array([[1.0, 2.0], [3.0, 4.0]])
    x.attach_grad()
    with autograd.record():
        y = nd.exp(x)
        z = (y * 2).sum()
    z.backward()
    assert_almost_equal(x.grad.asnumpy(), 2 * np.exp(x.asnumpy()), rtol=1e-5)


def test_two_inputs():
    a = nd.array([1.0, 2.0])
    b = nd.array([3.0, 4.0])
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        c = (a * b).sum()
    c.backward()
    assert_almost_equal(a.grad.asnumpy(), b.asnumpy())
    assert_almost_equal(b.grad.asnumpy(), a.asnumpy())


def test_reused_input():
    """x used twice -> grads accumulate across uses."""
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x + x  # dy/dx = 2x + 1
    y.backward()
    assert_almost_equal(x.grad.asnumpy(), [5.0])


def test_dot_grad():
    a = nd.array(np.random.rand(3, 4).astype(np.float32))
    b = nd.array(np.random.rand(4, 2).astype(np.float32))
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        c = nd.dot(a, b).sum()
    c.backward()
    assert_almost_equal(a.grad.asnumpy(),
                        np.ones((3, 2)) @ b.asnumpy().T, rtol=1e-5)
    assert_almost_equal(b.grad.asnumpy(),
                        a.asnumpy().T @ np.ones((3, 2)), rtol=1e-5)


def test_head_gradient():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 3
    y.backward(nd.array([10.0, 20.0]))
    assert_almost_equal(x.grad.asnumpy(), [30.0, 60.0])


def test_pause_scope():
    x = nd.array([1.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        with autograd.pause():
            z = x * 100  # not recorded
        w = y + z.detach()
    w.backward()
    assert_almost_equal(x.grad.asnumpy(), [2.0])
    assert autograd.is_recording() is False


def test_train_predict_mode():
    assert not autograd.is_training()
    with autograd.record(train_mode=True):
        assert autograd.is_training()
        with autograd.predict_mode():
            assert not autograd.is_training()
    with autograd.record(train_mode=False):
        assert not autograd.is_training()


def test_grad_req_add():
    x = nd.array([1.0, 2.0])
    x.attach_grad(grad_req="add")
    for _ in range(2):
        with autograd.record():
            y = (x * x).sum()
        y.backward()
    assert_almost_equal(x.grad.asnumpy(), 4 * x.asnumpy())
    x.zero_grad()
    assert_almost_equal(x.grad.asnumpy(), [0, 0])


def test_autograd_grad_function():
    x = nd.array([2.0, 3.0])
    with autograd.record():
        y = (x * x).sum()
    # x has no attached grad; use autograd.grad
    gx = autograd.grad(y, [x], create_graph=False)[0]
    assert_almost_equal(gx.asnumpy(), 2 * x.asnumpy())


def test_detach_cuts_graph():
    x = nd.array([1.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        z = y.detach() * 3
        w = y + z
    w.backward()
    assert_almost_equal(x.grad.asnumpy(), [2.0])


def test_multi_output_op_grad():
    x = nd.array(np.random.rand(4, 6).astype(np.float32))
    x.attach_grad()
    with autograd.record():
        parts = nd.split(x, num_outputs=2, axis=1)
        loss = (parts[0] * 2).sum() + (parts[1] * 3).sum()
    loss.backward()
    expected = np.concatenate([2 * np.ones((4, 3)), 3 * np.ones((4, 3))],
                              axis=1)
    assert_almost_equal(x.grad.asnumpy(), expected)


def test_nondifferentiable_cuts_tape():
    x = nd.array([1.0, 5.0, 3.0])
    x.attach_grad()
    with autograd.record():
        idx = nd.argmax(x)          # not differentiable
        y = (x * 2).sum() + idx     # idx contributes no grad
    y.backward()
    assert_almost_equal(x.grad.asnumpy(), [2.0, 2.0, 2.0])


def test_softmax_output_loss_grad():
    data = nd.array(np.random.rand(4, 10).astype(np.float32))
    label = nd.array([1, 2, 3, 4])
    data.attach_grad()
    with autograd.record():
        out = nd.SoftmaxOutput(data, label)
    out.backward()
    p = np.exp(data.asnumpy()) / np.exp(data.asnumpy()).sum(1, keepdims=True)
    oh = np.eye(10)[label.asnumpy().astype(int)]
    assert_almost_equal(data.grad.asnumpy(), p - oh, rtol=1e-4, atol=1e-5)


def test_custom_function():
    class MyClip(autograd.Function):
        def forward(self, x):
            self.save_for_backward(x)
            return nd.clip(x, a_min=-1.0, a_max=1.0)

        def backward(self, dy):
            x, = self.saved_tensors
            mask = (x.asnumpy() > -1) & (x.asnumpy() < 1)
            return dy * nd.array(mask.astype(np.float32))

    f = MyClip()
    x = nd.array([-2.0, 0.5, 2.0])
    x.attach_grad()
    with autograd.record():
        y = f(x)
        loss = y.sum()
    loss.backward()
    assert_almost_equal(x.grad.asnumpy(), [0.0, 1.0, 0.0])


def test_numeric_gradient_harness():
    check_numeric_gradient(lambda x: nd.tanh(x),
                           [np.random.rand(3, 3) * 0.5])
    check_numeric_gradient(lambda a, b: nd.dot(a, b),
                           [np.random.rand(2, 3), np.random.rand(3, 2)])
    check_numeric_gradient(
        lambda x: nd.Activation(x, act_type="sigmoid"),
        [np.random.rand(4, 4)])


def test_rnn_op_grad_flows():
    T, N, I, H = 3, 2, 4, 5
    x = nd.array(np.random.rand(T, N, I).astype(np.float32) * 0.1)
    nparams = 4 * H * (I + H) + 8 * H
    params = nd.array(np.random.rand(nparams).astype(np.float32) * 0.1)
    h0 = nd.zeros((1, N, H))
    c0 = nd.zeros((1, N, H))
    params.attach_grad()
    with autograd.record():
        out = nd.RNN(x, params, h0, c0, state_size=H, num_layers=1,
                     mode="lstm")
        loss = out.sum()
    loss.backward()
    g = params.grad.asnumpy()
    assert np.abs(g).sum() > 0


def test_grad_create_graph_second_order():
    # d/dx x^3 = 3x^2 ; d2/dx2 = 6x
    x = nd.array([1.0, 2.0, -3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x * x).sum()
        gx = autograd.grad(y, [x], create_graph=True)[0]
        gsum = gx.sum()
    gsum.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 6 * x.asnumpy(),
                               rtol=1e-5)


def test_grad_create_graph_third_order():
    # f = x^4: f' = 4x^3, f'' = 12x^2, f''' = 24x
    x = nd.array([0.5, 2.0])
    x.attach_grad()
    with autograd.record():
        y = (x ** 4).sum()
        g1 = autograd.grad(y, [x], create_graph=True)[0]
        g2 = autograd.grad(g1.sum(), [x], create_graph=True)[0]
        g3sum = g2.sum()
    g3sum.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 24 * x.asnumpy(),
                               rtol=1e-5)


def test_grad_create_graph_sin():
    # d2/dx2 sin(x) = -sin(x)
    x = nd.array([0.3, 1.2, -0.7])
    x.attach_grad()
    with autograd.record():
        y = nd.sin(x).sum()
        gx = autograd.grad(y, [x], create_graph=True)[0]
        gsum = gx.sum()
    gsum.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), -np.sin(x.asnumpy()),
                               rtol=1e-5, atol=1e-6)


def test_grad_create_graph_gradient_penalty():
    # WGAN-GP-style: loss = f(x) + |df/dx|^2 trains through the penalty
    x = nd.array([[1.0, 2.0], [3.0, 4.0]])
    w = nd.array([[0.5], [0.25]])
    w.attach_grad()
    x.attach_grad()
    with autograd.record():
        y = nd.dot(x, w).sum()
        gx = autograd.grad(y, [x], create_graph=True)[0]
        penalty = (gx * gx).sum()
        loss = y + penalty
    loss.backward()
    # dy/dx = w broadcast over rows; penalty = 2 * (w0^2 + w1^2)
    # dloss/dw = x.sum(0) + 4*w
    expect = x.asnumpy().sum(0)[:, None] + 4 * w.asnumpy()
    np.testing.assert_allclose(w.grad.asnumpy(), expect, rtol=1e-5)


def test_grad_create_graph_mixed_partials():
    # f = x^2 * y ; d/dy (df/dx) = 2x
    x = nd.array([1.5, -2.0])
    y = nd.array([2.0, 3.0])
    x.attach_grad()
    y.attach_grad()
    with autograd.record():
        f = (x * x * y).sum()
        gx = autograd.grad(f, [x], create_graph=True)[0]
        gsum = gx.sum()
    gsum.backward()
    np.testing.assert_allclose(y.grad.asnumpy(), 2 * x.asnumpy(),
                               rtol=1e-5)


def test_grad_create_graph_leaf_head():
    # head that IS a leaf variable: d head / d head = ones
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    g = autograd.grad(x, [x], create_graph=True)
    np.testing.assert_allclose(g[0].asnumpy(), [1.0, 1.0])


def test_grad_create_graph_dropout_train_mode():
    # mode-dependent ops must re-linearize their recorded (train) branch,
    # matching what backward() computes — not the identity predict branch
    mx.random.seed(7)
    x = nd.ones((64,))
    x.attach_grad()
    with autograd.record():
        y = nd.Dropout(x, p=0.5).sum()
    gx = autograd.grad(y, [x], create_graph=True)[0]
    y.backward()
    np.testing.assert_allclose(gx.asnumpy(), x.grad.asnumpy())
    # the train branch scales kept units by 1/(1-p)=2: grads are {0, 2}
    vals = set(np.unique(gx.asnumpy()))
    assert vals <= {0.0, 2.0} and 2.0 in vals


def test_grad_create_graph_duplicate_variables():
    # both occurrences of a duplicated variable get the full gradient,
    # matching the create_graph=False path
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    g = autograd.grad(y, [x, x], create_graph=True)
    np.testing.assert_allclose(g[0].asnumpy(), [2.0, 4.0])
    np.testing.assert_allclose(g[1].asnumpy(), [2.0, 4.0])


def test_grad_create_graph_leaf_head_no_attach():
    # leaf head without attach_grad works, same as create_graph=False
    x = nd.array([1.0, 2.0])
    g = autograd.grad(x, [x], create_graph=True)
    np.testing.assert_allclose(g[0].asnumpy(), [1.0, 1.0])


def test_grad_create_graph_recorded_head_grads_raise():
    # recorded head_grads would silently become constants: raise loudly
    x = nd.array([1.0, 2.0])
    w = nd.array([3.0, 4.0])
    x.attach_grad()
    w.attach_grad()
    with autograd.record():
        y = x * w
        hg = w * 2
    with pytest.raises(mx.MXNetError):
        autograd.grad(y, [x], head_grads=hg, create_graph=True)


def test_grad_create_graph_nonleaf_variable_raises():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        z = x * 2
        y = (z * z).sum()
    with pytest.raises(mx.MXNetError):
        autograd.grad(y, [z], create_graph=True)


def test_grad_create_graph_custom_function_raises():
    class Square(autograd.Function):
        def forward(self, x):
            self.save_for_backward(x)
            return x * x

        def backward(self, dy):
            (x,) = self.saved_tensors
            return 2 * x * dy

    sq = Square()
    x = nd.array([1.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = sq(x).sum()
    with pytest.raises(mx.MXNetError):
        autograd.grad(y, [x], create_graph=True)


def test_retain_graph_second_backward_not_accumulated():
    # retain_graph replay must NOT re-add the first pass's cotangents
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward(retain_graph=True)
    g1 = x.grad.asnumpy().copy()
    y.backward()                       # second replay over the kept tape
    g2 = x.grad.asnumpy()
    assert np.allclose(g1, [2.0, 4.0, 6.0])
    assert np.allclose(g2, g1)         # grad_req=write: same value again


def test_retain_graph_hybrid_block_second_backward():
    from mxnet_tpu.gluon import nn
    net = nn.Dense(3, in_units=4)
    net.initialize()
    net.hybridize(static_alloc=True)
    x = nd.random.uniform(shape=(2, 4))
    x.attach_grad()
    with autograd.record():
        y = net(x).sum()
    y.backward(retain_graph=True)
    g1 = x.grad.asnumpy().copy()
    y.backward()                       # must not hit donated residuals
    assert np.allclose(x.grad.asnumpy(), g1, rtol=1e-5)


def test_bulk_backward_matches_per_node():
    from mxnet_tpu import engine as eng
    from mxnet_tpu.autograd import _BULK_BWD_CACHE
    mx.random.seed(3)
    x = nd.random.uniform(shape=(4, 6))
    w = nd.random.uniform(shape=(6, 3))
    x.attach_grad()
    w.attach_grad()

    def step():
        with autograd.record():
            h = nd.relu(nd.dot(x, w) - 0.1)
            l = (h * h).sum()
        l.backward()
        return x.grad.asnumpy().copy(), w.grad.asnumpy().copy()

    before = len(_BULK_BWD_CACHE)
    gx_b, gw_b = step()
    assert len(_BULK_BWD_CACHE) > before          # bulk path engaged
    gx_b2, _ = step()                             # cache hit, same result
    assert np.allclose(gx_b, gx_b2)
    old = eng.set_bulk_size(1)                    # force per-node replay
    try:
        gx_p, gw_p = step()
    finally:
        eng.set_bulk_size(old)
    assert np.allclose(gx_b, gx_p, rtol=1e-5, atol=1e-6)
    assert np.allclose(gw_b, gw_p, rtol=1e-5, atol=1e-6)


def test_eager_dropout_backward_mask_matches_forward():
    mx.random.seed(0)
    x = nd.ones((4000,))
    x.attach_grad()
    with autograd.record():
        y = nd.Dropout(x, p=0.5, mode="always")
    y.backward()
    yv, g = y.asnumpy(), x.grad.asnumpy()
    assert ((yv != 0) == (g != 0)).all()          # same mask both ways
    assert np.allclose(g[g != 0], 2.0)            # 1/(1-p) scaling


def test_bulk_backward_with_dropout_engages_and_varies():
    from mxnet_tpu.autograd import _BULK_BWD_CACHE
    mx.random.seed(5)
    x = nd.ones((512,))
    x.attach_grad()
    before = len(_BULK_BWD_CACHE)
    grads = []
    for _ in range(2):
        with autograd.record():
            y = nd.Dropout(x * 2.0, p=0.5, mode="always") + 0.0
            (y * y).sum().backward()
        grads.append(x.grad.asnumpy().copy())
    assert len(_BULK_BWD_CACHE) > before          # rng node didn't block bulk
    # per-step keys are program inputs: masks (hence grads) differ
    assert not np.allclose(grads[0], grads[1])
    # grad consistent with its own forward mask: kept entries give
    # dl/dx = 2y * dy/dx = (2*4x)*(2/(1-p)) = 32 at x=1, dropped give 0
    vals = np.unique(np.round(grads[1], 4))
    assert set(vals).issubset({0.0, 32.0}), vals
