"""Pipeline parallelism (GPipe schedule over the pp axis): outputs and
gradients must match the sequential stage composition exactly."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu import parallel


def _stage_fn(w, x):
    return jnp.tanh(x @ w)


def _sequential(ws, micro_inputs):
    """Oracle: apply all stages to every microbatch in order."""
    outs = []
    for m in range(micro_inputs.shape[0]):
        h = micro_inputs[m]
        for s in range(ws.shape[0]):
            h = _stage_fn(ws[s], h)
        outs.append(h)
    return jnp.stack(outs)


@pytest.mark.parametrize("n_stages,n_micro", [(4, 6), (2, 3), (4, 2),
                                              (1, 3)])
def test_pipeline_matches_sequential(n_stages, n_micro):
    rng = np.random.RandomState(0)
    D, B = 8, 4
    ws = jnp.asarray(rng.randn(n_stages, D, D).astype(np.float32) * 0.5)
    xs = jnp.asarray(rng.randn(n_micro, B, D).astype(np.float32))
    mesh = parallel.make_pipeline_mesh(n_stages)
    out = parallel.pipeline_apply(_stage_fn, ws, xs, mesh)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_sequential(ws, xs)),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_gradients_match_sequential():
    rng = np.random.RandomState(1)
    n_stages, n_micro, D, B = 4, 5, 6, 3
    ws = jnp.asarray(rng.randn(n_stages, D, D).astype(np.float32) * 0.5)
    xs = jnp.asarray(rng.randn(n_micro, B, D).astype(np.float32))
    mesh = parallel.make_pipeline_mesh(n_stages)

    def loss_pp(ws):
        return (parallel.pipeline_apply(_stage_fn, ws, xs, mesh) ** 2) \
            .sum()

    def loss_seq(ws):
        return (_sequential(ws, xs) ** 2).sum()

    g_pp = jax.grad(loss_pp)(ws)
    g_seq = jax.grad(loss_seq)(ws)
    np.testing.assert_allclose(np.asarray(g_pp), np.asarray(g_seq),
                               rtol=1e-4, atol=1e-5)


def test_pipeline_under_jit_trains():
    # a compiled training loop over the pipeline converges
    rng = np.random.RandomState(2)
    n_stages, n_micro, D, B = 2, 4, 4, 8
    ws = jnp.asarray(rng.randn(n_stages, D, D).astype(np.float32) * 0.3)
    xs = jnp.asarray(rng.randn(n_micro, B, D).astype(np.float32))
    mesh = parallel.make_pipeline_mesh(n_stages)
    # teacher-student: the targets ARE a pipeline output, so the loss
    # can actually approach zero
    w_teacher = jnp.asarray(rng.randn(n_stages, D, D)
                            .astype(np.float32) * 0.3)
    ys = parallel.pipeline_apply(_stage_fn, w_teacher, xs, mesh)

    @jax.jit
    def step(ws):
        def loss(ws):
            out = parallel.pipeline_apply(_stage_fn, ws, xs, mesh)
            return ((out - ys) ** 2).mean()

        l, g = jax.value_and_grad(loss)(ws)
        return ws - 0.5 * g, l

    first = None
    for i in range(120):
        ws, l = step(ws)
        if i == 0:
            first = float(l)
    assert float(l) < 0.1 * first, (first, float(l))


def test_pipeline_params_pytree():
    # stage params as a pytree (dict of arrays), not a single array
    rng = np.random.RandomState(3)
    n_stages, D = 2, 4
    params = {"w": jnp.asarray(rng.randn(n_stages, D, D)
                               .astype(np.float32) * 0.5),
              "b": jnp.asarray(rng.randn(n_stages, D)
                               .astype(np.float32))}
    xs = jnp.asarray(rng.randn(3, 2, D).astype(np.float32))
    mesh = parallel.make_pipeline_mesh(n_stages)

    def fn(p, x):
        return jnp.tanh(x @ p["w"] + p["b"])

    out = parallel.pipeline_apply(fn, params, xs, mesh)
    # oracle
    h = xs
    res = []
    for m in range(3):
        v = xs[m]
        for s in range(n_stages):
            v = np.tanh(np.asarray(v) @ np.asarray(params["w"][s]) +
                        np.asarray(params["b"][s]))
        res.append(v)
    np.testing.assert_allclose(np.asarray(out), np.stack(res),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_too_few_devices_raises():
    import mxnet_tpu as mx
    with pytest.raises(mx.MXNetError):
        parallel.make_pipeline_mesh(100)
