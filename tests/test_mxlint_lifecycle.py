"""ISSUE-18 determinism & thread-lifecycle passes: determinism-soundness,
thread-lifecycle, blocking-in-loop — pos/neg/suppression fixtures,
witness chains, registry round-trip, the repo-tree-clean gate, and the
.mxlint_cache result cache (hit/miss/invalidation/--changed filter).

Pure-AST: no jax, milliseconds per fixture; the one full-tree gate run
shares a single lint invocation across all three passes.
"""
import os
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.mxlint import PASSES, Project, lint_paths, lint_sources  # noqa: E402
from tools.mxlint import cache as mxcache                           # noqa: E402
from tools.mxlint.core import iter_py_files                         # noqa: E402

SURFACES = {"mxnet_tpu.serving.fixture.make_trace": "trace replay",
            "mxnet_tpu.serving.fixture.Ckpt": "checkpoint payload"}


def run(src, path="mxnet_tpu/serving/fixture.py", select=None,
        surfaces=SURFACES, **proj):
    proj.setdefault("det_surfaces", surfaces)
    proj.setdefault("fault_sites", {})
    proj.setdefault("ci_shell_texts", {})
    return lint_sources({path: textwrap.dedent(src)}, select=select,
                        project=Project(**proj))


def ids(issues):
    return [i.pass_id for i in issues]


# ==================================================== determinism-soundness
def test_unseeded_np_random_in_surface_fires():
    issues = run("""
        import numpy as np
        def make_trace(cfg):
            return [np.random.uniform() for _ in range(3)]
    """, select=["determinism-soundness"])
    assert ids(issues) == ["determinism-soundness"]
    assert "np.random.uniform" in issues[0].message
    assert "make_trace" in issues[0].message


def test_witness_chain_through_helper():
    issues = run("""
        import random
        def _gap():
            return random.random()
        def _helper():
            return _gap()
        def make_trace(cfg):
            return _helper()
    """, select=["determinism-soundness"])
    assert ids(issues) == ["determinism-soundness"]
    # the chain names each hop with file:line witnesses
    assert "via" in issues[0].message
    assert "_helper" in issues[0].message
    assert "_gap" in issues[0].message
    assert "mxnet_tpu/serving/fixture.py:" in issues[0].message


def test_seeded_rng_is_clean():
    issues = run("""
        import numpy as np
        def make_trace(cfg):
            rng = np.random.RandomState(cfg.seed)
            return rng.uniform()
    """, select=["determinism-soundness"])
    assert issues == []


def test_entropy_rng_helper_is_sanctioned():
    issues = run("""
        from mxnet_tpu.base import entropy_rng
        def make_trace(cfg):
            rng = entropy_rng()
            return rng.random()
    """, select=["determinism-soundness"])
    assert issues == []


def test_clock_seeded_ctor_fires():
    issues = run("""
        import time
        import numpy as np
        def make_trace(cfg):
            rng = np.random.RandomState(int(time.time()))
            return rng.uniform()
    """, select=["determinism-soundness"])
    assert ids(issues) == ["determinism-soundness"]


def test_uuid4_and_urandom_fire():
    issues = run("""
        import os
        import uuid
        def make_trace(cfg):
            return uuid.uuid4().hex, os.urandom(8)
    """, select=["determinism-soundness"])
    assert ids(issues) == ["determinism-soundness"] * 2


def test_string_hash_and_set_iteration_fire():
    issues = run("""
        def make_trace(cfg):
            order = hash("model-a")
            out = []
            for name in {"a", "b", "c"}:
                out.append(name)
            return order, out
    """, select=["determinism-soundness"])
    assert len(issues) == 2
    assert all(i.pass_id == "determinism-soundness" for i in issues)


def test_class_surface_covers_methods():
    issues = run("""
        import random
        class Ckpt:
            def save(self):
                return random.random()
    """, select=["determinism-soundness"])
    assert ids(issues) == ["determinism-soundness"]


def test_unreachable_entropy_is_clean():
    issues = run("""
        import random
        def unrelated():
            return random.random()
        def make_trace(cfg):
            return 7
    """, select=["determinism-soundness"])
    assert issues == []


def test_determinism_suppression():
    issues = run("""
        import random
        def make_trace(cfg):
            # mxlint: disable=determinism-soundness
            return random.random()
    """, select=["determinism-soundness"])
    assert issues == []


def test_registry_round_trip_from_sources():
    # declare_deterministic literals in the scanned tree feed the
    # registry when no explicit registry is injected
    issues = run("""
        from mxnet_tpu.base import declare_deterministic
        import random
        declare_deterministic("mxnet_tpu.serving.fixture.gen",
                              "fixture surface")
        def gen():
            return random.random()
    """, select=["determinism-soundness"], surfaces=None)
    det = [i for i in issues if i.pass_id == "determinism-soundness"]
    assert len(det) == 1 and "gen" in det[0].message


# ======================================================== thread-lifecycle
def test_nondaemon_never_joined_fires():
    issues = run("""
        import threading
        class Pump:
            def start(self):
                self._t = threading.Thread(target=self._loop)
                self._t.start()
            def _loop(self):
                pass
            def stop(self):
                pass
    """, select=["thread-lifecycle"])
    assert ids(issues) == ["thread-lifecycle"]
    assert "never joined" in issues[0].message


def test_daemon_thread_is_exempt_from_join():
    issues = run("""
        import threading
        class Pump:
            def start(self):
                self._t = threading.Thread(target=self._loop, daemon=True)
                self._t.start()
            def _loop(self):
                pass
    """, select=["thread-lifecycle"])
    assert issues == []


def test_joined_with_timeout_on_stop_path_is_clean():
    issues = run("""
        import threading
        class Pump:
            def start(self):
                self._t = threading.Thread(target=self._loop)
                self._t.start()
            def _loop(self):
                pass
            def stop(self):
                self._halt()
            def _halt(self):
                self._t.join(timeout=5)
    """, select=["thread-lifecycle"])
    assert issues == []


def test_untimed_join_fires():
    issues = run("""
        import threading
        class Pump:
            def start(self):
                self._t = threading.Thread(target=self._loop)
                self._t.start()
            def _loop(self):
                pass
            def stop(self):
                self._t.join()
    """, select=["thread-lifecycle"])
    assert ids(issues) == ["thread-lifecycle"]
    assert "without a timeout" in issues[0].message


def test_local_thread_joined_inline_is_clean():
    issues = run("""
        import threading
        def fan_out(work):
            t = threading.Thread(target=work)
            t.start()
            t.join(30)
    """, select=["thread-lifecycle"])
    assert issues == []


def test_executor_without_shutdown_fires():
    issues = run("""
        from concurrent.futures import ThreadPoolExecutor
        class Loader:
            def __init__(self):
                self._pool = ThreadPoolExecutor(4)
    """, select=["thread-lifecycle"])
    assert ids(issues) == ["thread-lifecycle"]
    assert "shut down" in issues[0].message


def test_executor_with_shutdown_or_with_is_clean():
    issues = run("""
        from concurrent.futures import ThreadPoolExecutor
        class Loader:
            def __init__(self):
                self._pool = ThreadPoolExecutor(4)
            def close(self):
                self._pool.shutdown(wait=True)
        def batch(fn, items):
            with ThreadPoolExecutor(2) as pool:
                return list(pool.map(fn, items))
    """, select=["thread-lifecycle"])
    assert issues == []


def test_make_thread_defaults_are_clean():
    issues = run("""
        from mxnet_tpu.engine import make_thread
        class Pump:
            def start(self):
                self._t = make_thread(self._loop, name="pump",
                                      owner="Pump")
                self._t.start()
            def _loop(self):
                pass
    """, select=["thread-lifecycle"])
    assert issues == []


def test_orphan_loop_fires():
    issues = run("""
        import threading
        class Pump:
            def start(self):
                self._t = threading.Thread(target=self._loop,
                                           daemon=True)
                self._t.start()
            def _loop(self):
                while True:
                    self._work()
            def _work(self):
                pass
            def stop(self):
                self._stopping = True
    """, select=["thread-lifecycle"])
    assert ids(issues) == ["thread-lifecycle"]
    assert "orphan loop" in issues[0].message
    assert "Pump._loop" in issues[0].message


def test_loop_observing_stop_flag_is_clean():
    issues = run("""
        import threading
        class Pump:
            def start(self):
                self._t = threading.Thread(target=self._loop,
                                           daemon=True)
                self._t.start()
            def _loop(self):
                while True:
                    if self._stopping:
                        return
                    self._work()
            def _work(self):
                pass
            def stop(self):
                self._stopping = True
                self._t.join(timeout=5)
    """, select=["thread-lifecycle"])
    assert issues == []


def test_thread_lifecycle_suppression():
    issues = run("""
        import threading
        class Pump:
            def start(self):
                # deliberate fire-and-forget: forget_thread at runtime
                # mxlint: disable=thread-lifecycle
                self._t = threading.Thread(target=self._loop)
                self._t.start()
            def _loop(self):
                pass
            def stop(self):
                pass
    """, select=["thread-lifecycle"])
    assert issues == []


# ======================================================== blocking-in-loop
def test_sleep_in_unbreakable_loop_fires():
    issues = run("""
        import time
        class Pump:
            def _loop(self):
                while True:
                    self._work()
                    time.sleep(1.0)
            def _work(self):
                pass
    """, select=["blocking-in-loop"])
    assert ids(issues) == ["blocking-in-loop"]
    assert "time.sleep" in issues[0].message


def test_sleep_with_stop_check_is_clean():
    issues = run("""
        import time
        class Pump:
            def _loop(self):
                while True:
                    if self._stopping:
                        return
                    time.sleep(1.0)
    """, select=["blocking-in-loop"])
    assert issues == []


def test_timed_event_wait_is_clean():
    issues = run("""
        class Pump:
            def _loop(self):
                while True:
                    if self._evt.wait(0.5):
                        break
    """, select=["blocking-in-loop"])
    assert issues == []


def test_bare_condition_wait_fires():
    issues = run("""
        class Pump:
            def _loop(self):
                while True:
                    with self._cond:
                        self._cond.wait()
    """, select=["blocking-in-loop"])
    assert ids(issues) == ["blocking-in-loop"]


def test_blocking_suppression():
    issues = run("""
        import time
        def burn():
            while True:
                # mxlint: disable=blocking-in-loop
                time.sleep(60)
    """, select=["blocking-in-loop"])
    assert issues == []


# ====================================================== tree-clean gate
def test_repo_tree_is_clean_for_new_passes():
    files = iter_py_files([os.path.join(REPO, "mxnet_tpu"),
                           os.path.join(REPO, "tools")])
    issues = lint_paths(files, select=["determinism-soundness",
                                       "thread-lifecycle",
                                       "blocking-in-loop"])
    assert issues == [], [str(i) for i in issues]


def test_new_passes_registered():
    for pid in ("determinism-soundness", "thread-lifecycle",
                "blocking-in-loop"):
        assert pid in PASSES
    assert len(PASSES) == 22


# ========================================================== result cache
def _write(root, rel, text):
    p = os.path.join(root, rel)
    os.makedirs(os.path.dirname(p), exist_ok=True)
    with open(p, "w") as fh:
        fh.write(text)
    return p


def test_cache_round_trip_and_invalidation(tmp_path):
    root = str(tmp_path)
    f = _write(root, "pkg/mod.py", "x = 1\n")
    key = mxcache.cache_key([f], None, None, root=root)
    assert mxcache.load(key, root=root) is None          # cold miss
    from tools.mxlint.core import Issue
    issues = [Issue("thread-lifecycle", "pkg/mod.py", 3, 0, "msg")]
    mxcache.store(key, issues, root=root)
    got = mxcache.load(key, root=root)                   # warm hit
    assert [str(i) for i in got] == [str(i) for i in issues]
    _write(root, "pkg/mod.py", "x = 2\n")                # edit → new key
    assert mxcache.cache_key([f], None, None, root=root) != key


def test_cache_key_varies_with_select_and_report(tmp_path):
    root = str(tmp_path)
    f = _write(root, "pkg/mod.py", "x = 1\n")
    base = mxcache.cache_key([f], None, None, root=root)
    sel = mxcache.cache_key([f], ["thread-lifecycle"], None, root=root)
    rep = mxcache.cache_key([f], None, {"pkg/mod.py"}, root=root)
    assert len({base, sel, rep}) == 3


def test_cache_key_varies_with_side_inputs(tmp_path):
    root = str(tmp_path)
    f = _write(root, "pkg/mod.py", "x = 1\n")
    before = mxcache.cache_key([f], None, None, root=root)
    _write(root, "docs/env_vars.md", "MXNET_NEW_KNOB\n")
    assert mxcache.cache_key([f], None, None, root=root) != before
