"""CustomOp trampoline + DLPack + AttrScope tests (reference:
tests/python/unittest/test_operator.py::test_custom_op,
test_ndarray.py dlpack cases, test_attr.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd


class _Sigmoid(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        y = 1.0 / (1.0 + nd.exp(-in_data[0]))
        self.assign(out_data[0], req[0], y)

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        y = out_data[0]
        self.assign(in_grad[0], req[0], out_grad[0] * y * (1 - y))


@mx.operator.register("t_sigmoid")
class _SigmoidProp(mx.operator.CustomOpProp):
    def create_operator(self, ctx, in_shapes, in_dtypes):
        return _Sigmoid()


@mx.operator.register("t_twoout")
class _TwoOutProp(mx.operator.CustomOpProp):
    def list_outputs(self):
        return ["sum", "diff"]

    def create_operator(self, ctx, in_shapes, in_dtypes):
        class TwoOut(mx.operator.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                self.assign(out_data[0], req[0], in_data[0] + in_data[1])
                self.assign(out_data[1], req[1], in_data[0] - in_data[1])
        return TwoOut()

    def list_arguments(self):
        return ["a", "b"]


def test_custom_op_eager_forward_backward():
    x = nd.array([[-1.0, 0.0, 2.0]])
    x.attach_grad()
    with autograd.record():
        y = nd.Custom(x, op_type="t_sigmoid")
        y.sum().backward()
    ref = 1 / (1 + np.exp(-x.asnumpy()))
    assert np.allclose(y.asnumpy(), ref, rtol=1e-5)
    assert np.allclose(x.grad.asnumpy(), ref * (1 - ref), rtol=1e-5)


def test_custom_op_symbolic_and_multi_output():
    import mxnet_tpu.symbol as sym
    s = sym.Custom(sym.var("a"), sym.var("b"), op_type="t_twoout")
    a, b = nd.array([3.0, 1.0]), nd.array([1.0, 4.0])
    outs = s.eval(a=a, b=b)
    assert np.allclose(outs[0].asnumpy(), [4.0, 5.0])
    assert np.allclose(outs[1].asnumpy(), [2.0, -3.0])


def test_custom_op_hybridized():
    class Blk(gluon.HybridBlock):
        def hybrid_forward(self, F, x):
            return F.Custom(x, op_type="t_sigmoid") * 2.0

    b = Blk()
    b.hybridize()
    x = nd.array([[0.5, -0.5]])
    x.attach_grad()
    with autograd.record():
        out = b(x)
        out.sum().backward()
    r = 1 / (1 + np.exp(-x.asnumpy()))
    assert np.allclose(out.asnumpy(), 2 * r, rtol=1e-5)
    assert np.allclose(x.grad.asnumpy(), 2 * r * (1 - r), rtol=1e-5)


def test_custom_op_unknown_type_raises():
    with pytest.raises(mx.MXNetError, match="not registered"):
        nd.Custom(nd.ones((2,)), op_type="nope_never_registered")


def test_dlpack_torch_roundtrip():
    torch = pytest.importorskip("torch")
    x = nd.array([[1.0, 2.0], [3.0, 4.0]])
    t = torch.utils.dlpack.from_dlpack(x)        # NDArray __dlpack__
    assert t.shape == (2, 2)
    assert np.allclose(t.numpy(), x.asnumpy())
    back = nd.from_dlpack(torch.arange(4.0) + 1)
    assert isinstance(back, nd.NDArray)
    assert np.allclose(back.asnumpy(), [1, 2, 3, 4])
    cap = x.to_dlpack_for_read()
    t2 = torch.utils.dlpack.from_dlpack(cap)
    assert np.allclose(t2.numpy(), x.asnumpy())


def test_attr_scope_ctx_group():
    import mxnet_tpu.symbol as sym
    with mx.AttrScope(ctx_group="dev1", custom="yes"):
        a = sym.var("a")
        with mx.AttrScope(ctx_group="dev2"):
            b = sym.var("b")
        c = sym.relu(a)
    d = sym.relu(c)
    assert a.attr("ctx_group") == "dev1"
    assert a.attr("custom") == "yes"
    assert b.attr("ctx_group") == "dev2"      # inner scope overrides
    assert b.attr("custom") == "yes"          # outer attrs inherited
    assert c.attr("ctx_group") == "dev1"
    assert d.attr("ctx_group") is None        # outside any scope

    # group2ctx accepted by bind (placement is GSPMD's job; API parity)
    out = sym.FullyConnected(b, sym.var("w"), num_hidden=4, no_bias=True)
    exe = out.bind(mx.cpu(), {"b": nd.ones((2, 3)),
                              "w": nd.ones((4, 3))},
                   group2ctx={"dev1": mx.cpu(0), "dev2": mx.cpu(0)})
    got = exe.forward()
    assert got[0].shape == (2, 4)


def test_attr_scope_survives_json_roundtrip():
    import mxnet_tpu.symbol as sym
    with mx.AttrScope(ctx_group="dev1"):
        r = sym.relu(sym.var("a"))
    back = sym.load_json(r.tojson())
    assert back.attr("ctx_group") == "dev1"
    assert back.get_internals()["a_output"].attr("ctx_group") == "dev1"


def test_custom_op_out_kwarg_multi_output():
    a, b = nd.array([3.0, 1.0]), nd.array([1.0, 4.0])
    o1, o2 = nd.zeros((2,)), nd.zeros((2,))
    nd.Custom(a, b, op_type="t_twoout", out=[o1, o2])
    assert np.allclose(o1.asnumpy(), [4.0, 5.0])
    assert np.allclose(o2.asnumpy(), [2.0, -3.0])
    # list-then-positional input spelling keeps ALL inputs
    outs = nd.Custom([a], b, op_type="t_twoout")
    assert np.allclose(outs[0].asnumpy(), [4.0, 5.0])


def test_nd_load_accepts_file_object(tmp_path):
    p = tmp_path / "x.npz"
    nd.save(str(p), {"w": nd.ones((2, 2))})
    with open(p, "rb") as f:
        back = nd.load(f)
    assert np.allclose(back["w"].asnumpy(), 1.0)
