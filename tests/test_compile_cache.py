"""Persistent AOT compile cache (mxnet_tpu/compile_cache.py,
docs/serving.md §5): content-addressed keys, atomic corruption-tolerant
storage, LRU bound, executable round-trip, manifest-v3 precompiled
artifacts, and the zero-compile warm restart.

Byte-level behavior (keys, atomicity, corruption, LRU) is tested with
fake payloads — no XLA compile anywhere near those tests; the
executable round-trip tests use one tiny program each (tier-1 budget
discipline: the 870s budget truncates the suite tail if tests get
expensive).
"""
import json
import os
import pickle

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import compile_cache as cc
from mxnet_tpu import deploy, nd, runtime_metrics as rm, serving
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn


@pytest.fixture(autouse=True)
def _metrics_on():
    rm.reset()
    rm.enable()
    yield
    rm.disable()
    rm.reset()


@pytest.fixture()
def cache(tmp_path):
    return cc.CompileCache(str(tmp_path / "cache"), max_bytes=0)


class TestCacheKey:
    def test_deterministic(self):
        a = cc.cache_key("abc", 4, ["float32"], topology="t")
        b = cc.cache_key("abc", 4, ["float32"], topology="t")
        assert a == b and len(a) == 64

    def test_sensitive_to_every_component(self):
        base = cc.cache_key("abc", 4, ["float32"], topology="t")
        assert cc.cache_key("abd", 4, ["float32"], topology="t") != base
        assert cc.cache_key("abc", 8, ["float32"], topology="t") != base
        assert cc.cache_key("abc", 4, ["float16"], topology="t") != base
        assert cc.cache_key("abc", 4, ["float32"], topology="u") != base

    def test_default_topology_carries_versions(self):
        import jax
        fp = cc.topology_fingerprint()
        assert jax.__version__ in fp
        # the default key uses the live topology
        assert cc.cache_key("x", 1, []) == cc.cache_key(
            "x", 1, [], topology=fp)


class TestBytesTier:
    def test_put_get_roundtrip_and_counters(self, cache):
        key = "k" * 64
        assert cache.get(key) is None
        assert cache.misses == 1
        assert cache.put(key, b"payload")
        assert cache.get(key) == b"payload"
        assert cache.hits == 1 and cache.stores == 1
        assert rm.COMPILE_CACHE.value(event="hit") == 1
        assert rm.COMPILE_CACHE.value(event="miss") == 1
        assert rm.COMPILE_CACHE.value(event="store") == 1

    def test_atomic_write_leaves_no_temp_files(self, cache):
        for i in range(4):
            cache.put(f"{i:064d}", b"x" * 100)
        names = os.listdir(cache.cache_dir)
        assert len(names) == 4
        assert all(n.endswith(".bin") for n in names)

    def test_uncreatable_dir_degrades_to_cache_off(self, tmp_path,
                                                   monkeypatch):
        """A mis-set MXNET_COMPILE_CACHE_DIR must never raise on the
        serving path — it disables the cache with a warning (and
        diagnose stays runnable to report it)."""
        blocker = tmp_path / "file"             # a FILE as parent dir
        blocker.write_text("x")
        bad = str(blocker / "cache")
        c = cc.CompileCache(bad)
        assert not c.enabled
        assert c.get("k" * 64) is None          # inert, no error
        monkeypatch.setenv("MXNET_COMPILE_CACHE_DIR", bad)
        d1 = cc.get_default()
        assert not d1.enabled
        assert cc.get_default() is d1           # no rebuild-warn loop

    def test_disabled_cache_is_inert(self, monkeypatch):
        monkeypatch.delenv("MXNET_COMPILE_CACHE_DIR", raising=False)
        c = cc.CompileCache(None)
        assert not c.enabled
        assert not c.put("k" * 64, b"data")
        assert c.get("k" * 64) is None
        assert c.stats()["entries"] == 0

    def test_bitflip_is_a_counted_corrupt_miss(self, cache):
        key = "a" * 64
        cache.put(key, b"hello world payload")
        path = cache._path(key)
        raw = bytearray(open(path, "rb").read())
        raw[-1] ^= 0xFF
        with open(path, "wb") as f:
            f.write(bytes(raw))
        assert cache.get(key) is None           # never an error
        assert cache.corrupt == 1
        assert not os.path.exists(path)         # rot is cleared
        assert rm.COMPILE_CACHE.value(event="corrupt") == 1
        # the slot is reusable afterwards
        cache.put(key, b"fresh")
        assert cache.get(key) == b"fresh"

    def test_truncated_and_foreign_blobs_are_corrupt(self, cache):
        for i, raw in enumerate([b"", b"MXAOT1short", b"not-our-format"]):
            key = f"{i:064d}"
            with open(cache._path(key), "wb") as f:
                f.write(raw)
            assert cache.get(key) is None
        assert cache.corrupt == 3

    def test_lru_eviction_oldest_first(self, tmp_path):
        c = cc.CompileCache(str(tmp_path / "c"), max_bytes=3000)
        body = b"x" * 900                       # ~938B per entry on disk
        now = 1_700_000_000
        for i in range(3):
            c.put(f"{i:064d}", body)
            os.utime(c._path(f"{i:064d}"), (now + i, now + i))
        # a hit refreshes entry 0's recency, so entry 1 is now oldest
        os.utime(c._path("0" * 64), (now + 10, now + 10))
        c.put(f"{3:064d}", body)                # overflows the bound
        assert c.evictions >= 1
        assert c.get(f"{1:064d}") is None       # oldest evicted
        assert c.get("0" * 64) == body          # refreshed one survives

    def test_single_oversized_entry_survives(self, tmp_path):
        c = cc.CompileCache(str(tmp_path / "c"), max_bytes=10)
        c.put("f" * 64, b"y" * 1000)
        assert c.get("f" * 64) is not None      # never evicts itself

    def test_ingest_seeds_from_shipped_file(self, cache, tmp_path):
        shipped = tmp_path / "shipped.bin"
        cc.write_payload_file(str(shipped), b"exported-executable")
        key = "e" * 64
        assert cache.ingest(key, str(shipped))
        assert cache.get(key) == b"exported-executable"
        # corrupt shipped file refuses to seed
        with open(shipped, "wb") as f:
            f.write(b"garbage")
        assert not cache.ingest("d" * 64, str(shipped))

    def test_orphan_tmp_swept_at_construction(self, cache):
        """A writer SIGKILLed between mkstemp and rename leaves *.tmp
        litter; the next cache over the dir sweeps stale ones (age-
        gated, so a concurrent writer's fresh tmp survives)."""
        old = os.path.join(cache.cache_dir, "dead1234.tmp")
        fresh = os.path.join(cache.cache_dir, "live5678.tmp")
        for p in (old, fresh):
            with open(p, "wb") as f:
                f.write(b"partial write")
        os.utime(old, (1, 1))                   # ancient
        cc.CompileCache(cache.cache_dir, max_bytes=0)
        assert not os.path.exists(old)
        assert os.path.exists(fresh)

    def test_stats_shape(self, cache):
        cache.put("a" * 64, b"12345")
        st = cache.stats()
        assert st["enabled"] and st["entries"] == 1
        assert st["bytes"] > 5                  # header + body
        assert st["dir"] == cache.cache_dir


class TestDefaultInstance:
    def test_env_driven_rebuild(self, tmp_path, monkeypatch):
        monkeypatch.delenv("MXNET_COMPILE_CACHE_DIR", raising=False)
        assert not cc.get_default().enabled
        monkeypatch.setenv("MXNET_COMPILE_CACHE_DIR",
                           str(tmp_path / "d1"))
        c1 = cc.get_default()
        assert c1.enabled and c1.cache_dir == str(tmp_path / "d1")
        assert cc.get_default() is c1           # stable while env stable
        monkeypatch.setenv("MXNET_COMPILE_CACHE_DIR",
                           str(tmp_path / "d2"))
        assert cc.get_default() is not c1


class TestExecutableTier:
    def test_fake_executable_roundtrip_no_xla(self, cache, monkeypatch):
        """The executable layer over fake (de)serializers: flags, the
        deserialize histogram, and deserialize-failure => corrupt —
        zero XLA involvement."""
        monkeypatch.setattr(cc, "_serialize_compiled",
                            lambda compiled: pickle.dumps(compiled))
        monkeypatch.setattr(cc, "_deserialize_compiled",
                            lambda body: pickle.loads(body))
        key = "b" * 64
        assert cache.load_executable(key) is None
        assert cache.store_executable(key, {"fake": "executable"})
        prog = cache.load_executable(key)
        assert prog._mx_from_disk_cache is True
        assert rm.COMPILE_CACHE_DESERIALIZE_SECONDS.count() == 1

    def test_undeserializable_blob_degrades_to_miss(self, cache,
                                                    monkeypatch):
        key = "c" * 64
        cache.put(key, b"valid checksum, not an executable")

        def boom(body):
            raise ValueError("stale PJRT blob")
        monkeypatch.setattr(cc, "_deserialize_compiled", boom)
        assert cache.load_executable(key) is None
        # a checksum-valid but unloadable blob is corrupt + MISS, never
        # a hit — the miss counter must equal the compiles that follow
        # (the CI round-trip's zero-recompile assertion rides on it)
        assert cache.corrupt == 1
        assert cache.misses == 1 and cache.hits == 0
        assert not os.path.exists(cache._path(key))

    def test_unserializable_backend_keeps_compile_result(self, cache,
                                                         monkeypatch):
        def boom(compiled):
            raise RuntimeError("backend without serialization")
        monkeypatch.setattr(cc, "_serialize_compiled", boom)
        assert not cache.store_executable("a" * 64, object())
        assert cache.stats()["entries"] == 0

    def test_aot_program_compile_then_disk(self, cache):
        """One real tiny compile: first call compiles + stores, a fresh
        cache instance over the same dir deserializes (source='disk')
        and computes the same answer."""
        import jax

        aval = jax.ShapeDtypeStruct((2, 3), np.float32)
        key = cc.cache_key("prog", 2, ["float32"], topology="t")
        prog1, src1 = cc.aot_program(lambda x: x * 2 + 1, (aval,), key,
                                     cache)
        assert src1 == "compile"
        x = np.ones((2, 3), np.float32)
        np.testing.assert_allclose(np.asarray(prog1(x)), x * 2 + 1)
        fresh = cc.CompileCache(cache.cache_dir, max_bytes=0)
        prog2, src2 = cc.aot_program(
            lambda x: (_ for _ in ()).throw(AssertionError("compiled!")),
            (aval,), key, fresh)
        assert src2 == "disk" and prog2._mx_from_disk_cache
        np.testing.assert_allclose(np.asarray(prog2(x)), x * 2 + 1)


def _mlp(seed=7):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(4, in_units=8))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    return net


class TestManifestV3:
    def _manifest(self, **extra):
        m = {"dynamic_batch": True,
             "inputs": [{"shape": [None, 8], "dtype": "float32"}],
             "outputs": [{"shape": [None, 4], "dtype": "float32"}]}
        m.update(extra)
        return m

    def test_valid_precompiled_accepted(self):
        deploy.validate_manifest(self._manifest(
            manifest_version=3,
            precompiled=[{"bucket": 2, "file": "m.aot/abc.bin",
                          "key": "abc"}]))

    def test_malformed_precompiled_rejected(self):
        for bad in ([{"bucket": 0, "file": "f", "key": "k"}],
                    [{"bucket": 2, "file": "/abs/path", "key": "k"}],
                    [{"bucket": 2, "file": "../escape", "key": "k"}],
                    [{"bucket": 2, "file": "f"}],
                    ["not-a-dict"],
                    "not-a-list"):
            with pytest.raises(MXNetError):
                deploy.validate_manifest(
                    self._manifest(precompiled=bad))

    def test_unsupported_manifest_version_rejected(self):
        with pytest.raises(MXNetError, match="manifest_version"):
            deploy.validate_manifest(self._manifest(manifest_version=9))
        deploy.validate_manifest(self._manifest(manifest_version=2))

    def test_export_ships_loadable_aot_blobs(self, tmp_path):
        """export_stablehlo(precompile=...) writes manifest-v3 entries
        whose files exist and pass the payload checksum."""
        net = _mlp()
        x = nd.random.uniform(shape=(2, 8))
        art = net.export_stablehlo(x, path=str(tmp_path / "m"),
                                   dynamic_batch=True, precompile=(1, 2))
        with open(str(tmp_path / "m.json")) as f:
            man = json.load(f)
        assert man["manifest_version"] == 3
        assert [e["bucket"] for e in man["precompiled"]] == [1, 2]
        for e in man["precompiled"]:
            path = os.path.join(str(tmp_path), e["file"])
            assert cc.load_payload_file(path) is not None
        # and the serving loader consumes them with zero compiles even
        # with NO cache dir configured
        repo = serving.ModelRepository()
        repo.load_artifact("m", art)
        srv = serving.ModelServer(repo, serving.ServingConfig(
            max_batch_size=2, max_latency_us=1000))
        try:
            srv.prewarm("m")
            got = srv.predict("m", x.asnumpy(), timeout=60)
            np.testing.assert_allclose(got, net(x).asnumpy(),
                                       rtol=1e-5, atol=1e-5)
        finally:
            srv.stop()
        stats = srv.stats()
        assert stats["bucket_misses"] == 0
        assert stats["bucket_disk_hits"] == 2

    def test_corrupt_cache_entry_does_not_shadow_shipped_blob(
            self, tmp_path, monkeypatch):
        """A bit-flipped cache entry must not beat a pristine shipped
        executable into a recompile: ingest verifies before trusting,
        and aot_program falls back to the shipped file."""
        monkeypatch.setenv("MXNET_COMPILE_CACHE_DIR",
                           str(tmp_path / "cache"))
        net = _mlp()
        x = nd.random.uniform(shape=(1, 8))
        art = net.export_stablehlo(x, path=str(tmp_path / "m"),
                                   dynamic_batch=True, precompile=(1,))
        model = deploy.load_stablehlo(art)
        assert model.aot_program(rows=1)._mx_from_disk_cache
        cache = cc.get_default()
        for name in os.listdir(cache.cache_dir):    # rot the cache copy
            with open(os.path.join(cache.cache_dir, name), "wb") as f:
                f.write(b"bit-flipped")
        prog = model.aot_program(rows=1)            # re-ingests shipped
        assert prog._mx_from_disk_cache, \
            "shipped blob should have served; a compile happened"

    def test_reexport_sweeps_stale_aot_blobs(self, tmp_path):
        """Re-exporting to the same path (new weights => new keys) must
        not accumulate orphaned executables in path.aot/."""
        x = nd.random.uniform(shape=(1, 8))
        deploy.export_stablehlo(_mlp(1), x, path=str(tmp_path / "m"),
                                dynamic_batch=True, precompile=(1,))
        first = set(os.listdir(str(tmp_path / "m.aot")))
        deploy.export_stablehlo(_mlp(2), x, path=str(tmp_path / "m"),
                                dynamic_batch=True, precompile=(1,))
        second = set(os.listdir(str(tmp_path / "m.aot")))
        assert len(second) == 1
        assert not (first & second)         # old key swept, not kept

    def test_static_export_precompile_bucket_rules(self, tmp_path):
        net = _mlp()
        x = nd.random.uniform(shape=(3, 8))
        with pytest.raises(MXNetError, match="static export"):
            deploy.export_stablehlo(net, x, path=str(tmp_path / "s"),
                                    precompile=(1, 2))
        art = deploy.export_stablehlo(net, x, path=str(tmp_path / "s"),
                                      precompile=True)
        with open(str(tmp_path / "s.json")) as f:
            man = json.load(f)
        assert [e["bucket"] for e in man["precompiled"]] == [3]
        assert deploy.load_stablehlo(art).manifest is not None


class TestWarmRestart:
    def test_server_restart_compiles_zero_new_programs(
            self, tmp_path, monkeypatch):
        """The acceptance criterion, in-process: two fresh
        repository+server generations over one cache dir — the second
        deserializes every bucket (miss counter stays 0) and serves
        bit-correct results."""
        monkeypatch.setenv("MXNET_COMPILE_CACHE_DIR",
                           str(tmp_path / "cache"))
        net = _mlp()
        x = nd.random.uniform(shape=(2, 8))
        art = net.export_stablehlo(x, path=str(tmp_path / "m"),
                                   dynamic_batch=True, version=1)
        want = net(x).asnumpy()
        cfg_kw = dict(max_batch_size=2, max_latency_us=1000)

        def serve_once():
            repo = serving.ModelRepository()
            repo.load_artifact("m", art)
            srv = serving.ModelServer(
                repo, serving.ServingConfig(**cfg_kw))
            try:
                srv.prewarm("m")
                np.testing.assert_allclose(
                    srv.predict("m", x.asnumpy(), timeout=60), want,
                    rtol=1e-5, atol=1e-5)
            finally:
                srv.stop()
            return srv.stats()

        cold = serve_once()
        assert cold["bucket_misses"] == 2       # buckets 1, 2 compiled
        assert cc.get_default().stats()["stores"] == 2
        warm = serve_once()
        assert warm["bucket_misses"] == 0, \
            f"warm restart recompiled: {warm}"
        assert warm["bucket_disk_hits"] == 2
        assert warm["programs"] == 2

    def test_corrupt_cache_entry_falls_back_to_compile(
            self, tmp_path, monkeypatch):
        monkeypatch.setenv("MXNET_COMPILE_CACHE_DIR",
                           str(tmp_path / "cache"))
        net = _mlp()
        x = nd.random.uniform(shape=(2, 8))
        art = net.export_stablehlo(x, path=str(tmp_path / "m"),
                                   dynamic_batch=True, version=1)
        model = deploy.load_stablehlo(art)
        prog = model.aot_program(rows=2)
        assert not prog._mx_from_disk_cache
        # rot every stored entry on disk
        cache = cc.get_default()
        for name in os.listdir(cache.cache_dir):
            with open(os.path.join(cache.cache_dir, name), "wb") as f:
                f.write(b"rotten")
        prog2 = model.aot_program(rows=2)       # corrupt -> fresh compile
        assert not prog2._mx_from_disk_cache
        out = prog2(x.asnumpy())
        out = out[0] if isinstance(out, tuple) else out
        np.testing.assert_allclose(np.asarray(out), net(x).asnumpy(),
                                   rtol=1e-5, atol=1e-5)
        assert cache.corrupt >= 1
