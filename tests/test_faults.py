"""Chaos-hardened serving: deterministic fault injection + the
resilience layer it proves (docs/serving.md §8).

Everything here runs on numpy fakes / function entries — ZERO real XLA
compiles — so deadline propagation, retry/bisection, decode
quarantine, and the circuit-breaker lifecycle are tested at step
granularity with seeded, replayable fault plans.
"""
# mxlint: disable-file=fault-site-soundness (this file unit-tests the
# FaultPlan machinery itself on deliberately synthetic sites ('s.x',
# 'c.b', ...); the real-site specs below assert their own firing, so a
# typo'd real site fails the test rather than silently testing nothing)
import threading
import time

import numpy as np
import pytest

from mxnet_tpu import faults, runtime_metrics as rm, serving
from mxnet_tpu.base import MXNetError
from mxnet_tpu.serving import resilience
from mxnet_tpu.serving.decode import DecodeEngine
from mxnet_tpu.serving.resilience import (CircuitBreaker,
                                          CircuitOpenError, Deadline,
                                          DeadlineExceededError,
                                          retry_call)


@pytest.fixture(autouse=True)
def _clean_slate():
    faults.clear()
    rm.reset()
    rm.enable()
    yield
    faults.clear()
    rm.disable()
    rm.reset()


SIG = [{"shape": [None, 2], "dtype": "float32"}]


def _cfg(**kw):
    kw.setdefault("max_batch_size", 8)
    kw.setdefault("max_latency_us", 1)
    kw.setdefault("retry_backoff_ms", 0)    # fast tests, same policy
    return serving.ServingConfig(**kw)


def _decode_cfg(**kw):
    kw.setdefault("decode_page_size", 4)
    kw.setdefault("decode_pool_pages", 9)   # 8 usable
    kw.setdefault("decode_max_batch", 2)
    kw.setdefault("decode_max_new_tokens", 4)
    kw.setdefault("retry_backoff_ms", 0)
    return serving.ServingConfig(**kw)


class FakeModel:
    """Decode-model protocol in plain numpy: next token = (last + 1)
    mod vocab; prefill proposes the prompt's last token."""

    vocab_size = 16
    max_context = 32

    def __init__(self):
        self.prefills = 0
        self.steps = 0

    def prefill(self, tokens, length, block_table):
        self.prefills += 1
        logits = np.zeros((self.vocab_size,), np.float32)
        logits[int(tokens[0, int(length) - 1]) % self.vocab_size] = 1.0
        return logits

    def decode_step(self, tokens, positions, block_tables):
        self.steps += 1
        logits = np.zeros((tokens.shape[0], self.vocab_size), np.float32)
        logits[np.arange(tokens.shape[0]),
               (tokens + 1) % self.vocab_size] = 1.0
        return logits


def _engine(model=None, draft=None, **cfg_kw):
    eng = DecodeEngine(model or FakeModel(), _decode_cfg(**cfg_kw),
                       model_name="fake", draft=draft)
    eng._started = True                 # manual stepping, no loop thread
    return eng


def _drive(eng, seqs, limit=64):
    n = 0
    while not all(s.event.is_set() for s in seqs):
        eng.step()
        n += 1
        assert n < limit, "scheduler did not converge"
    return n


# --------------------------------------------------------------- the plan
class TestFaultPlan:
    def test_parse_roundtrip_and_defaults(self):
        p = faults.FaultPlan.parse(
            "serving.execute=fail,p=0.25,seed=7;"
            "compile_cache.load=corrupt,times=1;"
            "decode.step=delay,ms=5,after=2")
        r0, r1, r2 = p.rules
        assert (r0.pattern, r0.mode, r0.p, r0.seed) == \
            ("serving.execute", "fail", 0.25, 7)
        assert (r1.mode, r1.times) == ("corrupt", 1)
        assert (r2.mode, r2.ms, r2.after) == ("delay", 5.0, 2)
        assert r0.ms == 0.0 and r1.p == 1.0

    @pytest.mark.parametrize("bad", [
        "", "siteonly", "s=explode", "s=fail,p=2.0", "s=fail,zz=1",
        "s=fail,after=-1", "s=fail,times=0", "s=fail,p=abc"])
    def test_parse_rejects_malformed(self, bad):
        with pytest.raises(MXNetError):
            faults.FaultPlan.parse(bad)

    def test_bad_env_spec_degrades_to_off(self, monkeypatch):
        monkeypatch.setenv("MXNET_FAULTS", "not a spec")
        assert faults._init_from_env() is None
        monkeypatch.setenv("MXNET_FAULTS",
                           "serving.execute=fail,times=1")
        plan = faults._init_from_env()
        assert plan is not None and plan.rules[0].times == 1

    def test_off_path_is_identity(self):
        assert faults.active() is None
        assert faults.inject("anything") is None
        payload = b"bytes"
        assert faults.inject("anything", value=payload) is payload
        assert faults.check("anything") is False
        assert faults.counters() == {}

    def test_fail_after_times_and_counters(self):
        with faults.plan("s.x=fail,after=2,times=2"):
            assert faults.inject("s.x") is None     # call 1: skipped
            assert faults.inject("s.x") is None     # call 2: skipped
            for _ in range(2):                      # calls 3-4: fire
                with pytest.raises(faults.InjectedFault):
                    faults.inject("s.x")
            assert faults.inject("s.x") is None     # times exhausted
            assert faults.counters() == {"s.x:fail": 2}
        assert faults.active() is None              # scope restored

    def test_seeded_probability_is_deterministic(self):
        def firing_pattern():
            plan = faults.FaultPlan.parse("s.p=fail,p=0.5,seed=42")
            with faults.plan(plan):
                out = []
                for _ in range(32):
                    try:
                        faults.inject("s.p")
                        out.append(0)
                    except faults.InjectedFault:
                        out.append(1)
                return out

        a, b = firing_pattern(), firing_pattern()
        assert a == b                       # replayable
        assert 0 < sum(a) < 32              # actually probabilistic

    def test_glob_site_matching(self):
        with faults.plan("serving.*=fail,times=1"):
            with pytest.raises(faults.InjectedFault):
                faults.inject("serving.execute")
        with faults.plan("other.site=fail"):
            assert faults.inject("serving.execute") is None

    def test_corrupt_bytes_and_arrays(self):
        with faults.plan("c.b=corrupt,times=1"):
            out = faults.inject("c.b", value=b"\x00" * 8)
            assert out != b"\x00" * 8 and len(out) == 8
        with faults.plan("c.f=corrupt,times=1"):
            arr = faults.inject("c.f", value=np.ones((4,), np.float32))
            assert np.isnan(arr).sum() == 1
        with faults.plan("c.n=corrupt,times=1"):
            with pytest.raises(faults.InjectedFault):
                faults.inject("c.n")        # nothing to corrupt

    def test_fired_faults_counted_in_metrics(self):
        with faults.plan("m.x=fail,times=1"):
            with pytest.raises(faults.InjectedFault):
                faults.inject("m.x")
        assert rm.SERVING_FAULTS.value(site="m.x", mode="fail") == 1
        assert "serving_faults" in rm.dump_prometheus()

    def test_delay_mode_sleeps(self):
        with faults.plan("d.x=delay,ms=30,times=1"):
            t0 = time.perf_counter()
            faults.inject("d.x")
            assert time.perf_counter() - t0 >= 0.025


# ---------------------------------------------------------- deadline unit
class TestDeadline:
    def test_unbounded(self):
        d = Deadline()
        assert d.unset and not d.expired() and d.remaining() is None

    def test_countdown_and_expiry(self):
        d = Deadline.start(0.05)
        assert not d.unset and d.timeout == 0.05
        assert 0 < d.remaining() <= 0.05
        time.sleep(0.06)
        assert d.expired() and d.remaining() == 0.0


# ------------------------------------------------------------- retry unit
class TestRetryCall:
    def _flaky(self, fail_n, exc_factory):
        state = {"calls": 0}

        def fn():
            state["calls"] += 1
            if state["calls"] <= fail_n:
                raise exc_factory()
            return state["calls"]
        return fn, state

    def test_transient_retries_then_succeeds(self):
        fn, state = self._flaky(2, lambda: faults.InjectedFault("s"))
        notes = []
        assert retry_call(fn, retries=2, backoff_ms=0,
                          on_retry=lambda n, e: notes.append(n)) == 3
        assert state["calls"] == 3 and notes == [1, 2]

    def test_budget_exhausted_reraises(self):
        fn, state = self._flaky(5, lambda: faults.InjectedFault("s"))
        with pytest.raises(faults.InjectedFault):
            retry_call(fn, retries=2, backoff_ms=0)
        assert state["calls"] == 3

    def test_non_transient_fails_immediately(self):
        fn, state = self._flaky(5, lambda: ValueError("poisoned"))
        with pytest.raises(ValueError):
            retry_call(fn, retries=3, backoff_ms=0)
        assert state["calls"] == 1

    def test_deadline_stops_backoff_sleep(self):
        fn, state = self._flaky(5, lambda: faults.InjectedFault("s"))
        with pytest.raises(faults.InjectedFault):
            retry_call(fn, retries=5, backoff_ms=10_000,
                       deadline=Deadline.start(0.01))
        assert state["calls"] == 1      # no 10s sleep against a 10ms budget


# ----------------------------------------------------------- breaker unit
class TestCircuitBreaker:
    def test_open_probe_close_lifecycle(self):
        br = CircuitBreaker(4, 0.5, 40, model="m", version=1)
        for ok in (True, False, False, True):   # 50% errors, window full
            br.record(ok)
        assert br.state == resilience.OPEN
        with pytest.raises(CircuitOpenError) as ei:
            br.admit()
        assert ei.value.retry_after_ms <= 40
        time.sleep(0.05)
        assert br.admit() is True           # the half-open probe
        with pytest.raises(CircuitOpenError):
            br.admit()                      # one probe at a time
        br.record(True)
        assert br.state == resilience.CLOSED
        assert br.admit() is False          # closed admits freely
        st = br.debug_state()
        assert st["stats"]["opened"] == 1 and st["stats"]["closed"] == 1

    def test_failed_probe_reopens(self):
        br = CircuitBreaker(2, 0.5, 10, model="m", version=1)
        br.record(False)
        br.record(False)
        assert br.state == resilience.OPEN
        time.sleep(0.02)
        assert br.admit() is True
        br.record(False)                    # probe fails
        assert br.state == resilience.OPEN

    def test_abandoned_probe_self_heals(self):
        """A probe whose outcome never comes back (shed by the queue
        watermark before execute) must not wedge the breaker: after one
        cooldown the next admission takes over as the probe."""
        br = CircuitBreaker(2, 0.5, 20, model="m", version=1)
        br.record(False)
        br.record(False)
        time.sleep(0.03)
        assert br.admit() is True           # probe admitted...
        with pytest.raises(CircuitOpenError):
            br.admit()                      # ...one probe at a time
        time.sleep(0.03)                    # a cooldown later: abandoned
        assert br.admit() is True           # takeover probe
        br.record(True)
        assert br.state == resilience.CLOSED

    def test_partial_window_cannot_trip(self):
        br = CircuitBreaker(8, 0.5, 10, model="m", version=1)
        for _ in range(7):
            br.record(False)                # 100% errors, window NOT full
        assert br.state == resilience.CLOSED

    def test_window_zero_disables(self):
        br = CircuitBreaker(0, 0.5, 10, model="m", version=1)
        for _ in range(16):
            br.record(False)
        assert br.admit() is False
        assert br.state == resilience.CLOSED

    def test_state_gauge_published(self):
        br = CircuitBreaker(2, 0.5, 10, model="gm", version=3)
        br.record(False)
        br.record(False)
        assert rm.SERVING_CIRCUIT_STATE.value(
            model="gm", version="3") == 2.0


# -------------------------------------------------- predict-path e2e
class TestPredictResilience:
    def _server(self, fn, name="m", **cfg_kw):
        repo = serving.ModelRepository()
        repo.add_function(name, fn, SIG)
        return serving.ModelServer(repo, _cfg(**cfg_kw))

    def test_retry_then_success_parity(self):
        """An injected transient execute fault is absorbed by the retry
        policy: same outputs as a fault-free run, one retry counted."""
        x = np.arange(6, dtype=np.float32).reshape(3, 2)
        with self._server(lambda a: a * 3.0) as srv:
            want = srv.predict("m", x, timeout=60)      # fault-free
            with faults.plan("serving.execute=fail,times=1"):
                got = srv.predict("m", x, timeout=60)
            np.testing.assert_array_equal(got, want)
            st = srv.stats()
        assert st["retries"] == 1 and st["errors"] == 0
        assert rm.SERVING_RETRIES.value(model="m") == 1
        assert rm.SERVING_FAULTS.value(site="serving.execute",
                                       mode="fail") == 1

    def test_retries_exhausted_fail_typed(self):
        with self._server(lambda a: a) as srv:
            with faults.plan("serving.execute=fail"):
                with pytest.raises(faults.InjectedFault):
                    srv.predict("m", np.ones((1, 2), np.float32),
                                timeout=60)
            st = srv.stats()
        assert st["errors"] == 1
        assert st["retries"] == srv.config.retry_max

    def test_bisection_isolates_poisoned_request(self):
        """One poisoned request in a coalesced batch fails ALONE; its
        batchmates are re-dispatched and succeed."""
        def picky(a):
            if np.isnan(a).any():
                raise ValueError("poisoned row")
            return a + 1.0

        repo = serving.ModelRepository()
        repo.add_function("m", picky, SIG)
        srv = serving.ModelServer(repo, _cfg(), autostart=False)
        entry = repo.get("m")
        good = [np.full((1, 2), float(i), np.float32) for i in range(3)]
        poison = np.full((1, 2), np.nan, np.float32)
        reqs = [serving.server._Request(entry, (g,), 1) for g in good]
        bad_req = serving.server._Request(entry, (poison,), 1)
        ok, bad = srv._dispatch_group(entry,
                                      reqs[:1] + [bad_req] + reqs[1:])
        assert [r is bad_req for r, _e in bad] == [True]
        assert isinstance(bad[0][1], ValueError)
        assert set(ok) == set(reqs)
        for r, g in zip(reqs, good):
            np.testing.assert_array_equal(r.result[0], g + 1.0)
        assert srv.stats()["bisected"] >= 1

    def test_deadline_bounds_queue_wait(self):
        """A request stuck behind a gated batch fails with the typed
        deadline error at its timeout — and is withdrawn, not left
        occupying queue depth."""
        gate = threading.Event()
        entered = threading.Event()

        def gated(a):
            entered.set()
            assert gate.wait(30)
            return a

        srv = self._server(gated, num_workers=1)
        try:
            t = threading.Thread(
                target=lambda: srv.predict(
                    "m", np.ones((1, 2), np.float32), timeout=30))
            t.start()
            assert entered.wait(30)         # worker held inside batch 1
            t0 = time.monotonic()
            with pytest.raises(DeadlineExceededError,
                               match="no result within"):
                srv.predict("m", np.ones((1, 2), np.float32),
                            timeout=0.1)
            assert time.monotonic() - t0 < 5
            assert srv.stats()["queue_depth"] == 0
            assert srv.stats()["deadline_exceeded"] == 1
            assert rm.SERVING_DEADLINE_EXCEEDED.value(model="m") == 1
        finally:
            gate.set()
            t.join(30)
            srv.stop()

    def test_expired_request_never_dispatched(self):
        """A request whose deadline passed while queued is failed at
        batch assembly WITHOUT consuming a batch slot or model time."""
        calls = []
        gate = threading.Event()
        entered = threading.Event()

        def gated(a):
            calls.append(a.shape)
            entered.set()
            assert gate.wait(30)
            return a

        srv = self._server(gated, num_workers=1)
        results = []

        def hold():
            results.append(srv.predict(
                "m", np.ones((1, 2), np.float32), timeout=30))

        def doomed():
            try:
                srv.predict("m", np.ones((1, 2), np.float32),
                            timeout=0.05)
            except MXNetError as e:
                results.append(e)

        try:
            t1 = threading.Thread(target=hold)
            t1.start()
            assert entered.wait(30)
            t2 = threading.Thread(target=doomed)
            t2.start()
            t2.join(30)                     # fails via its own wait
            time.sleep(0.05)                # now stale in the queue too
            gate.set()                      # worker pops: must skip it
            t1.join(30)
            srv.stop()
        finally:
            gate.set()
        # only the held request ever reached the model
        assert len(calls) == 1
        assert sum(isinstance(r, DeadlineExceededError)
                   for r in results) == 1

    def test_circuit_opens_sheds_probes_and_recovers(self):
        state = {"fail": True, "calls": 0}

        def flappy(a):
            state["calls"] += 1
            if state["fail"]:
                raise ValueError("version is sick")
            return a * 2.0

        srv = self._server(flappy, circuit_window=4,
                           circuit_threshold=0.5, circuit_cooldown_ms=80)
        x = np.ones((1, 2), np.float32)
        try:
            for _ in range(4):              # fill the window with errors
                with pytest.raises(ValueError):
                    srv.predict("m", x, timeout=30)
            # OPEN: instant typed shed, no model call
            calls_before = state["calls"]
            with pytest.raises(CircuitOpenError, match="circuit open"):
                srv.predict("m", x, timeout=30)
            assert state["calls"] == calls_before
            assert srv.stats()["circuit_open_rejects"] == 1
            dbg = srv.debug_state()
            assert [c["state"] for c in dbg["circuits"].values()] \
                == ["open"]
            # cooldown -> half-open probe -> success -> CLOSED
            state["fail"] = False
            time.sleep(0.1)
            np.testing.assert_array_equal(
                srv.predict("m", x, timeout=30), x * 2.0)
            np.testing.assert_array_equal(
                srv.predict("m", x, timeout=30), x * 2.0)
            dbg = srv.debug_state()
            assert [c["state"] for c in dbg["circuits"].values()] \
                == ["closed"]
        finally:
            srv.stop()

    def test_unloaded_version_breaker_not_resurrected(self):
        """A worker finishing an in-flight batch for an unloaded entry
        must not re-insert the popped breaker (it would leak forever —
        nothing evicts a retired uid twice)."""
        repo = serving.ModelRepository()
        repo.add_function("m", lambda a: a, SIG)
        with serving.ModelServer(repo, _cfg()) as srv:
            entry = repo.get("m")
            assert srv._breaker(entry) is srv._breakers[entry.uid]
            repo.unload("m")                # fires _on_unload
            assert entry.uid not in srv._breakers
            late = srv._breaker(entry)      # in-flight straggler path
            late.record(True)               # usable...
            assert entry.uid not in srv._breakers   # ...never stored

    def test_circuit_shed_tags_admit_span(self):
        """An open-circuit shed gets the same trace attribution every
        other shed gets: an admit span tagged with the reason."""
        from mxnet_tpu import tracing
        tracing.enable(sample=1.0)
        try:
            srv = self._server(lambda a: a, circuit_window=2,
                               circuit_threshold=0.5,
                               circuit_cooldown_ms=60_000)
            x = np.ones((1, 2), np.float32)
            try:
                with faults.plan("serving.execute=fail"):
                    for _ in range(2):
                        with pytest.raises(faults.InjectedFault):
                            srv.predict("m", x, timeout=30)
                with pytest.raises(CircuitOpenError):
                    srv.predict("m", x, timeout=30)
            finally:
                srv.stop()
            t = tracing.TRACER.last(root="serving.predict")
            admits = [s for s in t["spans"]
                      if s["name"] == "serving.admit"]
            assert admits and "circuit open" in str(
                admits[0]["tags"].get("shed")), admits
        finally:
            tracing.disable()
            tracing.TRACER.reset()

    def test_corrupt_artifact_load_under_traffic(self, tmp_path):
        """A failing/corrupt artifact load is a typed operator-path
        error; live traffic on the current version keeps serving."""
        with self._server(lambda a: a + 1.0) as srv:
            x = np.ones((2, 2), np.float32)
            np.testing.assert_array_equal(
                srv.predict("m", x, timeout=60), x + 1.0)
            # injected pull failure (deterministic, no artifact needed)
            with faults.plan("repository.load_artifact=fail"):
                with pytest.raises(faults.InjectedFault):
                    srv.repository.load_artifact(
                        "m2", str(tmp_path / "nope.shlo"))
            # real on-disk rot: garbage bytes under a valid-ish name
            bad = tmp_path / "rotten.shlo"
            bad.write_bytes(b"\x00garbage\xff" * 16)
            (tmp_path / "rotten.json").write_text("{not json")
            with pytest.raises(Exception):
                srv.repository.load_artifact("m3", str(bad))
            # the server never noticed either failed deploy
            np.testing.assert_array_equal(
                srv.predict("m", x, timeout=60), x + 1.0)
            assert srv.repository.models() == ["m"]

    def test_chaos_plan_spec_in_incident_dump(self, tmp_path,
                                              monkeypatch):
        from mxnet_tpu import tracing
        tracing.enable(sample=1.0)
        try:
            with faults.plan("x.y=fail,times=1"):
                with pytest.raises(faults.InjectedFault):
                    faults.inject("x.y")
                path = tracing.record_incident(
                    "test.chaos", {"k": "v"},
                    path=str(tmp_path / "dump.json"), min_interval=0)
                import json
                rec = json.load(open(path))
                assert rec["faults"]["spec"] == "x.y=fail,times=1"
                assert rec["faults"]["fired"] == {"x.y:fail": 1}
        finally:
            tracing.disable()
            tracing.TRACER.reset()


class TestBuildWaitDeadline:
    """ISSUE-15 sweep fix: the bucket-program build wait in
    DynamicBatcher.program_for was the one unbounded blocking call on
    the predict path (flagged by the deadline-soundness lint pass) — a
    wedged builder (the serving.compile stall fault) hung every waiter
    of that key forever.  The wait now drains the request Deadline."""

    def _blocked_entry(self):
        repo = serving.ModelRepository()
        repo.add_function("m", lambda a: a, SIG)
        entry = repo.get("m")
        in_build, release = threading.Event(), threading.Event()
        real = entry.make_program

        def blocking_make_program(rows):
            in_build.set()
            assert release.wait(30)
            return real(rows)
        entry.make_program = blocking_make_program
        return repo, entry, in_build, release

    def test_program_build_wait_honors_deadline(self):
        _repo, entry, in_build, release = self._blocked_entry()
        batcher = serving.DynamicBatcher(_cfg())
        builder = threading.Thread(
            target=lambda: batcher.program_for(entry, 1))
        builder.start()
        try:
            assert in_build.wait(10)        # the build is wedged
            t0 = time.monotonic()
            with pytest.raises(DeadlineExceededError,
                               match="bucket build"):
                batcher.program_for(entry, 1,
                                    deadline=Deadline.start(0.2))
            assert time.monotonic() - t0 < 5    # typed failure, no hang
        finally:
            release.set()
            builder.join(30)
        # the builder completed normally; the key now mem-hits and a
        # deadline-less lookup keeps the legacy unbounded path
        assert batcher.program_for(entry, 1) is not None

    def test_build_wait_deadline_skips_breaker(self):
        """A deadline that expired waiting on another thread's build
        says nothing about the model version's health: it must count
        into serving.deadline_exceeded, never into the circuit window
        (window=1/threshold=1.0 would trip on a single recorded
        failure and shed the NEXT request)."""
        repo, _entry, in_build, release = self._blocked_entry()
        x = np.zeros((1, 2), dtype=np.float32)
        with serving.ModelServer(repo, _cfg(
                num_workers=2, circuit_window=1,
                circuit_threshold=1.0)) as srv:
            done = []
            first = threading.Thread(
                target=lambda: done.append(
                    srv.predict("m", x, timeout=60)))
            first.start()
            try:
                assert in_build.wait(10)    # worker A wedged building
                # worker B pops this one, reaches program_for, and
                # must fail it typed within the 0.3s budget
                with pytest.raises(DeadlineExceededError):
                    srv.predict("m", x, timeout=0.3)
                # poll for the count while the builder is STILL wedged
                # (the worker publishes asynchronously after the
                # caller raised; releasing first would let its wait
                # succeed and legitimately count nothing)
                t0 = time.monotonic()
                while time.monotonic() - t0 < 10 and \
                        rm.SERVING_DEADLINE_EXCEEDED.value(
                            model="m") < 1:
                    time.sleep(0.01)
                assert rm.SERVING_DEADLINE_EXCEEDED.value(
                    model="m") >= 1
            finally:
                release.set()
                first.join(30)
            assert len(done) == 1           # the builder's request won
            assert srv.stats()["deadline_exceeded"] >= 1
            # breaker never saw the deadline expiry: a fresh request
            # is admitted (an open circuit would shed it instantly)
            np.testing.assert_array_equal(
                srv.predict("m", x, timeout=60), x)

    def test_group_deadline_expiry_is_not_bisection(self):
        """Review fix: a group-deadline expiry (wedged bucket build)
        says nothing about a poisoned request — the expired coalesced
        members fail typed WITHOUT the bisection stat, and a member
        with budget left is re-dispatched and completes."""
        repo, entry, in_build, release = self._blocked_entry()
        srv = serving.ModelServer(repo, _cfg(), autostart=False)
        x = np.zeros((1, 2), dtype=np.float32)
        # wedge the 4-row bucket this 3-request group coalesces into
        bucket = srv.batcher.bucket_for(entry, 3)
        builder = threading.Thread(
            target=lambda: srv.batcher.program_for(entry, bucket))
        builder.start()
        try:
            assert in_build.wait(10)
            expired = [serving.server._Request(
                entry, (x,), 1, deadline=Deadline.start(0.0))
                for _ in range(2)]
            alive = serving.server._Request(
                entry, (x,), 1, deadline=Deadline.start(30.0))
            # the alive member's solo re-dispatch builds the 1-row
            # bucket itself and would wedge too — un-wedge it shortly
            threading.Timer(0.3, release.set).start()
            ok, bad = srv._dispatch_group(entry, expired + [alive])
        finally:
            release.set()
            builder.join(30)
        assert ok == [alive]
        np.testing.assert_array_equal(alive.result[0], x)
        assert sorted(id(r) for r, _e in bad) \
            == sorted(id(r) for r in expired)
        assert all(isinstance(e, DeadlineExceededError) for _r, e in bad)
        assert srv.stats()["bisected"] == 0


# ----------------------------------------------------- decode-path chaos
class TestDecodeResilience:
    def test_step_retry_then_success_parity(self):
        ref_eng = _engine()
        ref = ref_eng.submit([3], max_new_tokens=4)
        _drive(ref_eng, [ref])
        eng = _engine()
        with faults.plan("decode.step=fail,times=1"):
            s = eng.submit([3], max_new_tokens=4)
            _drive(eng, [s])
        assert s.finish_reason == "length"
        assert s.tokens == ref.tokens       # byte-identical generation
        assert eng.stats()["retries"] == 1
        assert eng.stats()["quarantined"] == 0
        eng.allocator.check_leaks()

    def test_persistent_step_failure_quarantines_alone(self):
        class Poison(FakeModel):
            """decode_step blows up whenever the poisoned sequence's
            token is active — deterministic, not transient."""

            def decode_step(self, tokens, positions, block_tables):
                if np.any(tokens == 13):
                    raise ValueError("poisoned token in the batch")
                return super().decode_step(tokens, positions,
                                           block_tables)

        eng = _engine(Poison())
        good = eng.submit([3], max_new_tokens=4)
        bad = eng.submit([12], max_new_tokens=4)    # prefill emits 12+1
        _drive(eng, [good, bad])
        assert good.finish_reason == "length"
        assert good.tokens == [3, 4, 5, 6]
        assert bad.finish_reason == "quarantined"
        assert isinstance(bad.error, ValueError)
        assert eng.stats()["quarantined"] == 1
        assert rm.SERVING_DECODE_QUARANTINED.value(model="fake") == 1
        eng.allocator.check_leaks()         # quarantine released pages
        assert eng.allocator.used_pages == 0

    def test_prefill_failure_quarantines_only_that_sequence(self):
        class PoisonPrefill(FakeModel):
            def prefill(self, tokens, length, block_table):
                if int(tokens[0, 0]) == 7:
                    raise ValueError("poisoned prompt")
                return super().prefill(tokens, length, block_table)

        eng = _engine(PoisonPrefill())
        good = eng.submit([1], max_new_tokens=2)
        bad = eng.submit([7], max_new_tokens=2)
        _drive(eng, [good, bad])
        assert good.finish_reason == "length"
        assert bad.finish_reason == "quarantined"
        eng.allocator.check_leaks()
        assert eng.allocator.used_pages == 0

    def test_allocator_exhaustion_admission_refusal(self):
        # a request that can NEVER fit is refused at submit, instantly
        eng = _engine(decode_pool_pages=5)          # 4 usable pages
        with pytest.raises(MXNetError, match="KV pages"):
            eng.submit([1], max_new_tokens=31)      # needs 8 pages
        # injected exhaustion: the pool claims full; a deadlined
        # request fails typed instead of waiting forever
        with faults.plan("kv_cache.allocate=fail"):
            s = eng.submit([1], max_new_tokens=4, timeout=0.05)
            eng.step()                      # cannot admit (exhausted)
            assert not s.event.is_set()
            time.sleep(0.06)
            eng.step()                      # deadline pruned the line
        assert s.finish_reason == "deadline"
        assert isinstance(s.error, DeadlineExceededError)
        assert eng.stats()["waiting"] == 0
        eng.allocator.check_leaks()

    def test_check_only_honors_fail_mode(self):
        """A latency-only plan (delay/stall) must never masquerade as
        allocator exhaustion — check() fires fail rules only."""
        eng = _engine()
        with faults.plan("*=delay,ms=0"):
            s = eng.submit([1], max_new_tokens=2)
            _drive(eng, [s])
        assert s.finish_reason == "length"  # admitted + generated fine
        eng.allocator.check_leaks()

    def test_decode_retry_backoff_respects_deadline(self):
        """A transient step fault with a huge configured backoff must
        not sleep the engine thread past the running sequences'
        deadlines — the retry gives up and quarantine takes over."""
        eng = _engine(retry_backoff_ms=60_000)
        s = eng.submit([1], max_new_tokens=4, timeout=0.25)
        eng.step()                          # prefill (no step fault yet)
        with faults.plan("decode.step=fail"):
            t0 = time.monotonic()
            eng.step()                      # transient fail; no 60s sleep
            assert time.monotonic() - t0 < 5
        assert s.event.is_set()
        assert s.finish_reason == "quarantined"
        eng.allocator.check_leaks()

    def test_deadline_expires_mid_generation(self):
        eng = _engine()
        s = eng.submit([1], max_new_tokens=64 // 4, timeout=0.05)
        eng.step()                          # admitted + prefilled
        assert s.tokens, "prefill should emit the first token"
        time.sleep(0.06)
        eng.step()                          # expiry observed -> evict
        assert s.finish_reason == "deadline"
        assert isinstance(s.error, DeadlineExceededError)
        eng.allocator.check_leaks()
        assert eng.allocator.used_pages == 0

    def test_engine_stop_during_inflight_generate_with_deadline(self):
        eng = DecodeEngine(FakeModel(), _decode_cfg(),
                           model_name="fake", autostart=True)
        with faults.plan("decode.step=delay,ms=20"):
            results = {}

            def gen():
                try:
                    results["out"] = eng.generate(
                        [1], max_new_tokens=4, timeout=30)
                except MXNetError as e:
                    results["err"] = e

            t = threading.Thread(target=gen)
            t.start()
            deadline = time.monotonic() + 30
            while not eng.stats()["running"] \
                    and time.monotonic() < deadline:
                time.sleep(0.002)
            assert eng.stop(timeout=30)     # stop mid-generation
            t.join(30)
        # the caller got a TYPED answer promptly — finished or stopped,
        # never a hang past its deadline
        assert results, "generate() hung through engine stop"
        if "err" in results:
            assert "stopped" in str(results["err"])
        eng.allocator.check_leaks()
        assert eng.allocator.used_pages == 0

    def test_server_records_decode_outcomes_on_breaker(self):
        repo = serving.ModelRepository()
        repo.add_decoder("lm", FakeModel())
        srv = serving.ModelServer(repo, _decode_cfg(
            circuit_window=2, circuit_threshold=0.5,
            circuit_cooldown_ms=50))
        try:
            with faults.plan("decode.prefill=fail"):    # beyond retries
                for _ in range(2):
                    with pytest.raises(faults.InjectedFault):
                        srv.generate("lm", [1], max_new_tokens=2,
                                     timeout=30)
            with pytest.raises(CircuitOpenError):
                srv.generate("lm", [1], max_new_tokens=2, timeout=30)
            time.sleep(0.06)                # cooldown -> probe succeeds
            out = srv.generate("lm", [2], max_new_tokens=2, timeout=30)
            assert out.tolist() == [2, 3]
            dbg = srv.debug_state()
            assert [c["state"] for c in dbg["circuits"].values()] \
                == ["closed"]
        finally:
            srv.stop()


# ---------------------------------------------------- §9 chaos (ISSUE-12)
from test_serving_decode import ChainModel as _SharedChainModel  # noqa: E402


class ChainModel(_SharedChainModel):
    """The shared self-consistent §9 fake (ONE protocol definition,
    tests/test_serving_decode.py), narrowed to this file's vocab so
    the existing mod-16 token chains keep reading literally."""

    vocab_size = 16


class AgreeingDraft(ChainModel):
    pass


class TestPrefixAndSpecChaos:
    """The ISSUE-12 fault-injection satellite: the new decode paths
    under the §8 machinery — a corrupted/failed prefix lookup degrades
    to a plain prefill (never wrong tokens), and a failed speculative
    verify quarantines through the PR-11 path, leak-free."""

    def _fault_free_reference(self, prompts, n):
        eng = _engine(ChainModel(), decode_pool_pages=33)
        outs = []
        for p in prompts:
            s = eng.submit(p, max_new_tokens=n)
            _drive(eng, [s])
            outs.append(list(s.tokens))
        return outs

    @pytest.mark.parametrize("mode", ["fail", "corrupt", "stall"])
    def test_prefix_lookup_fault_degrades_never_corrupts(self, mode):
        """Every lookup fault mode ends in either a served hit or a
        plain prefill with byte-identical tokens; the radix path can
        cost latency, never correctness."""
        prompts = [list(range(1, 9))] * 3 + [list(range(1, 9)) + [2]]
        want = self._fault_free_reference(prompts, 3)
        model = ChainModel()
        eng = _engine(model, prefix_cache=True, decode_pool_pages=33)
        spec = f"decode.prefix_lookup={mode},p=0.5,seed=3" \
            if mode != "stall" else \
            f"decode.prefix_lookup={mode},p=0.5,seed=3,ms=1"
        with faults.plan(spec) as plan_obj:
            for prompt, ref in zip(prompts, want):
                s = eng.submit(prompt, max_new_tokens=3)
                _drive(eng, [s])
                assert list(s.tokens) == ref    # NEVER wrong tokens
            fired = sum(plan_obj.counters().values())
        st = eng.stats()
        if mode in ("fail", "corrupt") and fired:
            # a fired lookup fault is a counted degrade -> plain
            # prefill; hits+misses+degraded covers every admission
            assert st["prefix_degraded"] == fired
        assert st["quarantined"] == 0
        eng.allocator.check_leaks()

    def test_verify_fault_quarantines_via_bisection_path_leak_free(self):
        """A persistent decode.verify failure is a target-model
        failure: the poisoned sequence quarantines alone (pages
        released through the leak-guard path), batchmates finish with
        correct tokens, and the engine keeps serving."""
        model = ChainModel()
        eng = _engine(model, draft=AgreeingDraft(), spec_k=2,
                      decode_max_batch=2, decode_pool_pages=33,
                      retry_max=0)
        a = eng.submit([5], max_new_tokens=4)
        b = eng.submit([9], max_new_tokens=4)
        with faults.plan("decode.verify=fail,times=1"):
            _drive(eng, [a, b])
        reasons = {s.finish_reason for s in (a, b)}
        assert reasons == {"quarantined", "length"}, reasons
        ok = a if a.finish_reason == "length" else b
        bad = b if ok is a else a
        assert list(ok.tokens) == [(int(ok.prompt[0]) + i) % 16
                                   for i in range(1, 5)]
        assert isinstance(bad.error, faults.InjectedFault)
        st = eng.stats()
        assert st["quarantined"] == 1
        assert rm.SERVING_DECODE_QUARANTINED.value(model="fake") == 1
        eng.allocator.check_leaks()
        assert eng.allocator.used_pages == eng.allocator.cached_pages
        # the engine is not poisoned: a fresh request completes
        c = eng.submit([3], max_new_tokens=2)
        _drive(eng, [c])
        assert list(c.tokens) == [4, 5]
        eng.allocator.check_leaks()

    def test_transient_verify_fault_retries_to_success(self):
        """One transient verify fault under retry_max=2 is absorbed:
        same tokens, one retry counted, no quarantine."""
        model = ChainModel()
        eng = _engine(model, draft=AgreeingDraft(), spec_k=2,
                      decode_pool_pages=33, retry_max=2)
        with faults.plan("decode.verify=fail,times=1"):
            s = eng.submit([5], max_new_tokens=4)
            _drive(eng, [s])
        assert list(s.tokens) == [6, 7, 8, 9]
        st = eng.stats()
        assert st["retries"] >= 1 and st["quarantined"] == 0
        eng.allocator.check_leaks()

    def test_injected_pool_exhaustion_with_shared_pages(self):
        """kv_cache.allocate refusal composes with prefix sharing: the
        admission is refused whole (no half-aliased sequence), then
        succeeds once the fault clears."""
        model = ChainModel()
        eng = _engine(model, prefix_cache=True, decode_pool_pages=33)
        a = eng.submit(list(range(1, 9)), max_new_tokens=2)
        _drive(eng, [a])
        with faults.plan("kv_cache.allocate=fail,times=1"):
            b = eng.submit(list(range(1, 9)), max_new_tokens=2)
            eng.step()                  # refused admission this step
            assert eng.stats()["running"] == 0
            eng.allocator.check_leaks()
            _drive(eng, [b])            # fault spent: admitted now
        assert list(b.tokens) == list(a.tokens)
        eng.allocator.check_leaks()


# ------------------------------------------- client-side retry-after honor
class TestHonorRetryAfter:
    """resilience.honor_retry_after — the client twin of the server's
    retry_after_ms hint: jittered sleeps (U[1.0, 1.5) x hint) so a shed
    storm's clients do not come back as one synchronized wave."""

    class _Clock:
        def __init__(self):
            self.sleeps = []

        def __call__(self, s):
            self.sleeps.append(s)

    def _shedding(self, fail_n, retry_after_ms=40):
        calls = []

        def fn():
            calls.append(1)
            if len(calls) <= fail_n:
                raise resilience.ServerOverloadedError(
                    "m", retry_after_ms, "queue full")
            return "served"

        return fn, calls

    def test_honors_hint_with_multiplicative_jitter(self, monkeypatch):
        clock = self._Clock()
        monkeypatch.setattr(resilience.time, "sleep", clock)
        fn, calls = self._shedding(3)

        class SeededRng:
            def __init__(self):
                import random
                self._r = random.Random(7)

            def random(self):
                return self._r.random()

        out = resilience.honor_retry_after(fn, attempts=5,
                                           rng=SeededRng())
        assert out == "served" and len(calls) == 4
        assert len(clock.sleeps) == 3
        for s in clock.sleeps:
            # hint * U[1.0, 1.5): never shorter than the server asked,
            # never more than 1.5x — the desynchronization band
            assert 0.040 <= s < 0.060, clock.sleeps

    def test_attempts_exhausted_reraises_typed(self, monkeypatch):
        monkeypatch.setattr(resilience.time, "sleep", self._Clock())
        fn, calls = self._shedding(100)
        with pytest.raises(resilience.ServerOverloadedError):
            resilience.honor_retry_after(fn, attempts=2)
        assert len(calls) == 3          # initial + 2 retries

    def test_circuit_open_is_honored_too(self, monkeypatch):
        monkeypatch.setattr(resilience.time, "sleep", self._Clock())
        calls = []

        def fn():
            calls.append(1)
            if len(calls) == 1:
                raise CircuitOpenError("m", 10, "circuit open")
            return "ok"

        assert resilience.honor_retry_after(fn) == "ok"
        assert len(calls) == 2

    def test_deadline_bounds_the_sleep(self, monkeypatch):
        clock = self._Clock()
        monkeypatch.setattr(resilience.time, "sleep", clock)
        fn, calls = self._shedding(5, retry_after_ms=10_000)
        # a 10s hint cannot fit in a 50ms budget: raise, don't sleep
        with pytest.raises(resilience.ServerOverloadedError):
            resilience.honor_retry_after(
                fn, attempts=5, deadline=Deadline.start(0.05))
        assert len(calls) == 1 and not clock.sleeps

    def test_other_errors_propagate_immediately(self, monkeypatch):
        monkeypatch.setattr(resilience.time, "sleep", self._Clock())

        def fn():
            raise ValueError("not an overload")

        with pytest.raises(ValueError):
            resilience.honor_retry_after(fn)

    def test_on_backoff_observer(self, monkeypatch):
        monkeypatch.setattr(resilience.time, "sleep", self._Clock())
        fn, _ = self._shedding(2)
        seen = []
        resilience.honor_retry_after(
            fn, attempts=3,
            on_backoff=lambda n, d, e: seen.append((n, d > 0)))
        assert seen == [(1, True), (2, True)]

    def test_end_to_end_against_a_shedding_server(self):
        """A saturated bounded queue sheds; the honoring client backs
        off and lands once capacity frees — zero client-side races."""
        gate = threading.Event()
        entered = threading.Event()

        def gated(a):
            entered.set()
            assert gate.wait(30)
            return a

        repo = serving.ModelRepository()
        repo.add_function("g", gated, SIG)
        cfg = _cfg(max_batch_size=1, queue_depth=2, shed_watermark=1,
                   num_workers=1, retry_after_ms=5)
        x = np.ones((1, 2), np.float32)
        with serving.ModelServer(repo, cfg) as srv:
            t = threading.Thread(
                target=lambda: srv.predict("g", x, timeout=30))
            t.start()
            assert entered.wait(30)
            deadline = time.monotonic() + 30
            while srv.stats()["queue_depth"] > 0:
                assert time.monotonic() < deadline
                time.sleep(0.005)
            t2 = threading.Thread(
                target=lambda: srv.predict("g", x, timeout=30))
            t2.start()
            deadline = time.monotonic() + 30
            while srv.stats()["queue_depth"] < 1:
                assert time.monotonic() < deadline
                time.sleep(0.005)
            # the queue is saturated: a bare call sheds typed;
            # the honoring client retries through the release
            with pytest.raises(resilience.ServerOverloadedError):
                srv.predict("g", x, timeout=30)
            released = threading.Timer(0.05, gate.set)
            released.start()
            out = resilience.honor_retry_after(
                lambda: srv.predict("g", x, timeout=30),
                attempts=20, deadline=Deadline.start(30))
            np.testing.assert_array_equal(out, x)
            t.join(30)
            t2.join(30)
            released.join()
        assert srv.stats()["shed"] >= 1
