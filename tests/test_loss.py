"""Loss tests vs NumPy references (reference: tests/python/unittest/test_loss.py)."""
import numpy as np

from mxnet_tpu import autograd, gluon, nd

L = gluon.loss


def test_l2_loss():
    pred = nd.array(np.array([[1., 2.], [3., 4.]]))
    label = nd.array(np.array([[1.5, 2.5], [2., 5.]]))
    out = L.L2Loss()(pred, label).asnumpy()
    ref = 0.5 * ((pred.asnumpy() - label.asnumpy()) ** 2).mean(axis=1)
    assert np.allclose(out, ref, atol=1e-6)


def test_l1_loss():
    pred = nd.array(np.array([[1., -2.]]))
    label = nd.array(np.array([[0., 0.]]))
    assert np.allclose(L.L1Loss()(pred, label).asnumpy(), [1.5])


def test_softmax_ce_sparse_vs_dense():
    logits = np.random.randn(4, 5).astype(np.float32)
    labels = np.random.randint(0, 5, (4,))
    onehot = np.eye(5, dtype=np.float32)[labels]
    sparse = L.SoftmaxCrossEntropyLoss()(
        nd.array(logits), nd.array(labels)).asnumpy()
    dense = L.SoftmaxCrossEntropyLoss(sparse_label=False)(
        nd.array(logits), nd.array(onehot)).asnumpy()
    # numpy reference
    e = np.exp(logits - logits.max(axis=1, keepdims=True))
    p = e / e.sum(axis=1, keepdims=True)
    ref = -np.log(p[np.arange(4), labels])
    assert np.allclose(sparse, ref, atol=1e-5)
    assert np.allclose(dense, ref, atol=1e-5)


def test_sigmoid_bce():
    x = np.random.randn(6).astype(np.float32)
    z = (np.random.rand(6) > 0.5).astype(np.float32)
    out = L.SigmoidBinaryCrossEntropyLoss()(
        nd.array(x), nd.array(z)).asnumpy()
    p = 1 / (1 + np.exp(-x))
    ref = -(z * np.log(p) + (1 - z) * np.log(1 - p))
    assert np.allclose(out, ref, atol=1e-5)


def test_huber_loss():
    pred = nd.array(np.array([0., 0., 0.]))
    label = nd.array(np.array([0.5, 2.0, -3.0]))
    out = L.HuberLoss(rho=1.0)(pred, label).asnumpy()
    ref = np.mean([0.5 * 0.25, 2.0 - 0.5, 3.0 - 0.5])
    assert np.allclose(out.mean(), ref, atol=1e-6)


def test_kl_div():
    logits = np.random.randn(3, 4).astype(np.float32)
    e = np.exp(logits)
    target = (e / e.sum(1, keepdims=True)).astype(np.float32)
    logp = np.log(target)
    out = L.KLDivLoss()(nd.array(logp), nd.array(target)).asnumpy()
    assert np.allclose(out, 0, atol=1e-5)


def test_hinge_losses():
    pred = nd.array(np.array([[0.3], [-2.0]]))
    label = nd.array(np.array([[1.0], [-1.0]]))
    out = L.HingeLoss()(pred, label).asnumpy()
    assert np.allclose(out.ravel(), [0.7, 0.0], atol=1e-6)
    out2 = L.SquaredHingeLoss()(pred, label).asnumpy()
    assert np.allclose(out2.ravel(), [0.49, 0.0], atol=1e-6)


def test_triplet_loss():
    a = nd.zeros((2, 3))
    p = nd.zeros((2, 3))
    n = nd.ones((2, 3))
    out = L.TripletLoss(margin=1.0)(a, p, n).asnumpy()
    # d(a,p)=0, d(a,n)=3 -> max(0, 0-3+1)=0
    assert np.allclose(out, 0)


def test_cosine_embedding_loss():
    a = nd.array(np.array([[1., 0.]]))
    b = nd.array(np.array([[1., 0.]]))
    y = nd.array(np.array([1.0]))
    out = L.CosineEmbeddingLoss()(a, b, y).asnumpy()
    assert np.allclose(out, 0, atol=1e-6)


def test_ctc_loss_simple():
    # T=3, N=1, C=3 (blank=0); uniform logits -> loss = -log p
    T, N, C = 3, 1, 3
    logits = np.zeros((T, N, C), dtype=np.float32)
    label = np.array([[1, 2]], dtype=np.float32)
    loss = L.CTCLoss(layout="TNC")(nd.array(logits),
                                   nd.array(label)).asnumpy()
    assert loss.shape == (1,)
    assert loss[0] > 0
    # probability of all valid alignments of "1,2" in 3 frames with
    # uniform p=1/3: alignments {1,2,b},{1,b,2},{b,1,2},{1,1,2},{1,2,2},
    # {1,2,b}... enumerate: paths mapping to (1,2): count = 5? verify
    # loosely: loss < T*log(C) (can't exceed total uncertainty)
    assert loss[0] < T * np.log(C) + 1


def test_ctc_loss_grad_flows():
    T, N, C = 4, 2, 5
    x = nd.array(np.random.randn(T, N, C).astype(np.float32))
    x.attach_grad()
    label = nd.array(np.array([[1, 2], [3, 0]], dtype=np.float32))
    with autograd.record():
        loss = L.CTCLoss(layout="TNC")(x, label)
        total = loss.sum()
    total.backward()
    assert np.abs(x.grad.asnumpy()).sum() > 0


def test_loss_hybridize_consistency():
    for loss_fn in [L.L2Loss(), L.SoftmaxCrossEntropyLoss(),
                    L.SigmoidBinaryCrossEntropyLoss()]:
        pred = nd.array(np.random.randn(4, 3).astype(np.float32))
        if isinstance(loss_fn, L.SoftmaxCrossEntropyLoss):
            label = nd.array(np.random.randint(0, 3, (4,)))
        else:
            label = nd.array(np.random.rand(4, 3).astype(np.float32))
        y1 = loss_fn(pred, label).asnumpy()
        loss_fn.hybridize()
        y2 = loss_fn(pred, label).asnumpy()
        assert np.allclose(y1, y2, atol=1e-5), type(loss_fn)
