"""Traffic plane, parts 2+3: SLO-driven autoscaling and tiered
admission (docs/serving.md §11).

The autoscaler is driven tick-by-tick with a fake metrics source and a
fake clock against REAL ReplicaSets of numpy function entries — zero
XLA compiles, zero wall-clock sleeps in the decision logic — so
hysteresis, cooldowns, the prewarm-aware lead, and the chaos path are
asserted at exact tick granularity.  Admission is likewise clocked
through explicit ``now=`` stamps.
"""
import math
import time

import numpy as np
import pytest

from mxnet_tpu import faults, runtime_metrics as rm, serving
from mxnet_tpu.base import MXNetError
from mxnet_tpu.serving.admission import (AdmissionController, TierPolicy,
                                         parse_tier_spec)
from mxnet_tpu.serving.autoscaler import (Autoscaler, AutoscalerConfig,
                                          RuntimeMetricsSource,
                                          SLOTargets,
                                          _quantile_from_counts)
from mxnet_tpu.serving.resilience import (Deadline, ServerOverloadedError,
                                          honor_retry_after)


@pytest.fixture(autouse=True)
def _clean_slate():
    faults.clear()
    rm.reset()
    rm.enable()
    yield
    faults.clear()
    rm.disable()
    rm.reset()


SIG = [{"shape": [None, 2], "dtype": "float32"}]
TIERS = "gold=100,silver=10/50,free=1/5/8"


def _fn(a):
    return a * 2.0 + 1.0


def _server(**cfg_kw):
    repo = serving.ModelRepository()
    repo.add_function("m", _fn, SIG)
    cfg_kw.setdefault("max_batch_size", 4)
    cfg_kw.setdefault("max_latency_us", 1)
    return serving.ModelServer(repo, serving.ServingConfig(**cfg_kw))


# ------------------------------------------------------------ tier specs
class TestTierSpec:
    def test_parse(self):
        tiers = parse_tier_spec(TIERS)
        assert list(tiers) == ["gold", "silver", "free"]
        assert tiers["gold"].quota_rps is None
        assert tiers["silver"].quota_rps == 50 \
            and tiers["silver"].burst == 50     # burst defaults to quota
        assert tiers["free"].burst == 8

    @pytest.mark.parametrize("bad", ["", "gold", "gold=a", "g=1/2/3/4",
                                     "gold=1,gold=2"])
    def test_parse_rejects(self, bad):
        with pytest.raises(MXNetError):
            parse_tier_spec(bad)

    def test_policy_validation(self):
        with pytest.raises(MXNetError):
            TierPolicy("t", 1, quota_rps=0)
        with pytest.raises(MXNetError):
            TierPolicy("t", 1, quota_rps=5, burst=0)


# ------------------------------------------------------------- admission
class TestAdmission:
    def test_default_tier_is_highest_priority(self):
        adm = AdmissionController(TIERS)
        assert adm.default_tier == "gold"
        assert adm.resolve(None) == (None, "gold")
        assert adm.resolve("a") == ("a", "gold")
        assert adm.resolve("a:free") == ("a", "free")
        with pytest.raises(MXNetError):
            adm.resolve("a:platinum")

    def test_register_tenant(self):
        adm = AdmissionController(TIERS)
        adm.register_tenant("bob", "free")
        assert adm.resolve("bob") == ("bob", "free")
        with pytest.raises(MXNetError):
            adm.register_tenant("bob", "nope")

    def test_shed_thresholds_stack_low_tier_first(self):
        adm = AdmissionController(TIERS, shed_start=0.5)
        th = adm.shed_thresholds()
        assert list(th) == ["free", "silver", "gold"]
        assert th["free"] == pytest.approx(0.5 + 0.5 / 3)
        assert th["gold"] == pytest.approx(1.0)

    def test_pressure_sheds_in_tier_order(self):
        adm = AdmissionController(TIERS, shed_start=0.5)
        # free sheds at its threshold while silver and gold pass
        p_free = adm.shed_thresholds()["free"] + 0.01
        with pytest.raises(ServerOverloadedError) as ei:
            adm.check("a:free", model="m", load=p_free, now=0.0)
        assert "priority shedding" in str(ei.value)
        adm.check("b:silver", model="m", load=p_free, now=0.0)
        adm.check("c:gold", model="m", load=p_free, now=0.0)
        # at full pressure even gold sheds
        with pytest.raises(ServerOverloadedError):
            adm.check("c:gold", model="m", load=1.0, now=0.0)
        s = adm.stats()
        assert s["pressure_sheds"] == 2 and s["admitted"] == 2
        assert s["by_tenant"]["a"]["shed"] == 1

    def test_autoscaler_published_pressure_maxes_with_load(self):
        adm = AdmissionController(TIERS, shed_start=0.5,
                                  pressure_ttl_s=5.0)
        adm.update_pressure(0.95, now=10.0)
        # local load says calm, the published SLO pressure says shed
        with pytest.raises(ServerOverloadedError):
            adm.check("a:free", model="m", load=0.0, now=11.0)
        # and the publish decays after its TTL — a dead autoscaler
        # cannot pin the gate shut
        adm.check("a:free", model="m", load=0.0, now=20.0)
        assert adm.pressure(now=20.0) == 0.0

    def test_quota_bucket_meters_and_refills(self):
        adm = AdmissionController("gold=100,free=1/5/2")
        adm.check("a:free", now=0.0)
        adm.check("a:free", now=0.0)     # burst of 2 spent
        with pytest.raises(ServerOverloadedError) as ei:
            adm.check("a:free", now=0.0)
        assert "quota" in str(ei.value)
        # retry-after covers the time until one token accrues (0.2s
        # at 5 rps)
        assert ei.value.retry_after_ms >= 200
        # refill: 0.2s later exactly one token is back
        adm.check("a:free", now=0.2)
        with pytest.raises(ServerOverloadedError):
            adm.check("a:free", now=0.2)
        # quota is per tenant, not per tier
        adm.check("b:free", now=0.2)

    def test_anonymous_and_unquotad_tiers_are_exempt(self):
        adm = AdmissionController("gold=100,free=1/5/2")
        for _ in range(10):
            adm.check(None, now=0.0)         # anonymous: no bucket
            adm.check("g:gold", now=0.0)     # gold has no quota_rps
        assert adm.stats()["quota_sheds"] == 0

    def test_metrics_under_cardinality_guard(self):
        adm = AdmissionController("gold=100,free=1/5/1")
        adm.check("a:free", now=0.0)
        with pytest.raises(ServerOverloadedError):
            adm.check("a:free", now=0.0)
        adm.check(None, now=0.0)
        assert rm.SERVING_TENANT_REQUESTS.value(
            tenant="a", tier="free") == 1
        assert rm.SERVING_TENANT_SHED.value(
            tenant="a", tier="free") == 1
        assert rm.SERVING_TENANT_REQUESTS.value(
            tenant="__anon__", tier="gold") == 1

    def test_typed_contract_retries_cleanly(self):
        # the shed is the SAME typed family every other shed uses, so
        # honor_retry_after backs off and succeeds once quota refills
        adm = AdmissionController("free=1/100/1", retry_after_ms=5)
        t0 = time.monotonic()
        calls = []

        def attempt():
            calls.append(1)
            adm.check("a:free")          # real clock: refills at 100/s
            return "ok"

        out = honor_retry_after(attempt, attempts=6,
                                deadline=Deadline.start(5.0))
        assert out == "ok" and len(calls) >= 1
        assert time.monotonic() - t0 < 5.0

    def test_from_config_gating(self):
        cfg = serving.ServingConfig(tenant_tiers=None)
        assert AdmissionController.from_config(cfg) is None
        cfg = serving.ServingConfig(tenant_tiers=TIERS,
                                    admission_shed_start=0.25)
        adm = AdmissionController.from_config(cfg)
        assert adm is not None and adm.shed_start == 0.25

    def test_env_spec(self, monkeypatch):
        monkeypatch.setenv("MXNET_SERVING_TENANT_TIERS",
                           "vip=9,basic=1/10")
        cfg = serving.ServingConfig()
        adm = AdmissionController.from_config(cfg)
        assert sorted(adm.tiers) == ["basic", "vip"]
        assert adm.default_tier == "vip"

    def test_debug_state_serializes(self):
        import json
        adm = AdmissionController(TIERS)
        adm.check("a:free", now=0.0)
        json.dumps(adm.debug_state())


# --------------------------------------------------- server integration
class TestServerAdmission:
    def test_tenant_gate_ahead_of_watermark(self):
        srv = _server(tenant_tiers="gold=100,free=1/5/1")
        try:
            x = np.ones((1, 2), np.float32)
            out = srv.predict("m", x, tenant="a:gold")
            assert out.shape == (1, 2)
            srv.predict("m", x, tenant="b:free")
            with pytest.raises(ServerOverloadedError) as ei:
                srv.predict("m", x, tenant="b:free")   # burst 1 spent
            assert "quota" in str(ei.value)
            st = srv.stats()
            assert st["tenant_sheds"] == 1
            assert st["shed"] >= 1
            assert st["admission"]["quota_sheds"] == 1
            # the typed shed reached the shared serving.shed metric too
            assert rm.SERVING_SHED.value(model="m") == 1
            assert "admission" in srv.debug_state()
        finally:
            srv.stop()

    def test_generate_path_gated(self):
        # a numpy decode-model fake (the ChainModel protocol of
        # tests/test_serving_decode.py) — the gate must sit ahead of
        # the decode engine, so the engine is never even built
        class ChainLM:
            vocab_size = 8
            max_context = 16

            def _row(self, t):
                row = np.zeros((self.vocab_size,), np.float32)
                row[(int(t) + 1) % self.vocab_size] = 1.0
                return row

            def prefill(self, tokens, length, block_table):
                return self._row(tokens[0, int(length) - 1])

            def decode_step(self, tokens, positions, block_tables):
                return np.stack([self._row(t) for t in tokens])

        repo = serving.ModelRepository()
        repo.add_decoder("lm", ChainLM())
        srv = serving.ModelServer(repo, serving.ServingConfig(
            tenant_tiers="gold=100,free=1/5/1",
            decode_page_size=4, decode_pool_pages=9,
            decode_max_batch=2))
        try:
            srv.admission_controller().update_pressure(1.0)
            with pytest.raises(ServerOverloadedError):
                srv.generate("lm", [1, 2], max_new_tokens=2,
                             tenant="a:gold")
            assert srv.stats()["tenant_sheds"] == 1
            # pressure decays / clears -> the same request admits
            srv.admission_controller().update_pressure(0.0)
            out = srv.generate("lm", [1, 2], max_new_tokens=2,
                               tenant="a:gold")
            assert list(out) == [3, 4]   # next = last + 1
        finally:
            srv.stop()

    def test_no_tiers_means_no_gate(self):
        srv = _server()
        try:
            assert srv.admission_controller() is None
            out = srv.predict("m", np.ones((1, 2), np.float32),
                              tenant="anyone:anything")
            assert out.shape == (1, 2)
        finally:
            srv.stop()


# ------------------------------------------------------------------ SLOs
class TestSLOTargets:
    def test_requires_one_target(self):
        with pytest.raises(MXNetError):
            SLOTargets()

    def test_queue_band_defaults(self):
        slo = SLOTargets(queue_high=8)
        assert slo.queue_low == 2
        with pytest.raises(MXNetError):
            SLOTargets(queue_high=4, queue_low=9)

    def test_env_defaults(self, monkeypatch):
        monkeypatch.setenv("MXNET_SERVING_AUTOSCALE_SLO_TTFT_P99_MS",
                           "250")
        slo = SLOTargets()
        assert slo.ttft_p99_ms == 250.0 and slo.queue_high is None

    def test_config_validation(self):
        with pytest.raises(MXNetError):
            AutoscalerConfig(min_replicas=3, max_replicas=2)
        with pytest.raises(MXNetError):
            AutoscalerConfig(breach_ticks=0)
        cfg = AutoscalerConfig(interval_s=0.25, cooldown_up_s=1.5)
        assert cfg.interval_s == 0.25        # ctor args are seconds
        assert cfg.cooldown_up_s == 1.5

    def test_config_env_is_milliseconds(self, monkeypatch):
        monkeypatch.setenv("MXNET_SERVING_AUTOSCALE_INTERVAL_MS", "500")
        monkeypatch.setenv("MXNET_SERVING_AUTOSCALE_COOLDOWN_UP_MS",
                           "2500")
        cfg = AutoscalerConfig()
        assert cfg.interval_s == 0.5 and cfg.cooldown_up_s == 2.5


class TestWindowedQuantile:
    def test_interpolated(self):
        buckets = [0.1, 1.0, 10.0]
        assert _quantile_from_counts(buckets, [100, 0, 0, 0], 0.99) \
            <= 0.1
        assert math.isnan(_quantile_from_counts(buckets, [0, 0, 0, 0],
                                                0.99))
        hi = _quantile_from_counts(buckets, [0, 0, 0, 5], 0.99)
        assert hi == 10.0                   # overflow pins to top edge

    def test_runtime_source_windows_the_histogram(self):
        src = RuntimeMetricsSource("srvX", "m")
        rm.SERVING_REQUEST_SECONDS.observe(9.0, model="m")
        s1 = src.sample()                   # window 1 sees the 9s burst
        assert s1["latency_p99_s"] > 1.0
        rm.SERVING_REQUEST_SECONDS.observe(0.001, model="m")
        s2 = src.sample()                   # window 2 must NOT
        assert s2["latency_p99_s"] < 1.0    # remember the old burst
        s3 = src.sample()                   # empty window -> NaN
        assert math.isnan(s3["latency_p99_s"])
        rm.SERVING_QUEUE_DEPTH.set(7, server="srvX")
        assert src.sample()["queue_depth"] == 7

    def test_runtime_source_aggregates_replica_series(self):
        # replica-path decode engines observe TTFT under
        # model="name/rid" (replica.py) — the sensor must sum those
        # series, or a replicated fleet's breach is invisible
        src = RuntimeMetricsSource("srvY", "lm")
        rm.SERVING_DECODE_TTFT_SECONDS.observe(4.0, model="lm/r0")
        rm.SERVING_DECODE_TTFT_SECONDS.observe(4.0, model="lm/r1")
        rm.SERVING_DECODE_TTFT_SECONDS.observe(4.0, model="lm2")  # other
        s = src.sample()
        assert s["ttft_p99_s"] > 1.0
        # windowing still applies across the aggregate
        assert math.isnan(src.sample()["ttft_p99_s"])

    def test_histogram_label_values(self):
        rm.SERVING_DECODE_TTFT_SECONDS.observe(0.1, model="a")
        rm.SERVING_DECODE_TTFT_SECONDS.observe(0.2, model="b")
        assert rm.SERVING_DECODE_TTFT_SECONDS.label_values("model") \
            == ["a", "b"]
        with pytest.raises(MXNetError):
            rm.SERVING_DECODE_TTFT_SECONDS.label_values("nope")


# ------------------------------------------------------------ autoscaler
class _FakeSource:
    def __init__(self, queue=0.0, ttft=None, latency=None):
        self.queue, self.ttft, self.latency = queue, ttft, latency

    def sample(self):
        return {"queue_depth": self.queue, "ttft_p99_s": self.ttft,
                "latency_p99_s": self.latency}


class _Harness:
    """Real server + ReplicaSet, fake clock + sensor, manual ticks."""

    def __init__(self, replicas=2, slo=None, admission=None, **cfg_kw):
        self.srv = _server(replicas=replicas)
        self.rset = self.srv.replica_set("m")
        self.src = _FakeSource()
        self.now = 0.0
        cfg_kw.setdefault("min_replicas", 1)
        cfg_kw.setdefault("max_replicas", 4)
        cfg_kw.setdefault("interval_s", 0.1)
        cfg_kw.setdefault("breach_ticks", 2)
        cfg_kw.setdefault("idle_ticks", 3)
        cfg_kw.setdefault("cooldown_up_s", 0.0)
        cfg_kw.setdefault("cooldown_down_s", 0.0)
        self.asc = Autoscaler(
            self.rset, slo or SLOTargets(queue_high=8),
            AutoscalerConfig(**cfg_kw), source=self.src,
            admission=admission, clock=lambda: self.now)

    def tick(self):
        self.now += 0.1
        return self.asc.tick()

    def replicas(self):
        return len(self.rset.replicas())

    def close(self):
        self.asc.stop()
        self.srv.stop()


@pytest.fixture
def h():
    hs = []

    def make(**kw):
        hs.append(_Harness(**kw))
        return hs[-1]

    yield make
    for x in hs:
        x.close()


class TestAutoscaler:
    def test_scale_up_needs_hysteresis(self, h):
        hx = h()
        hx.src.queue = 20.0
        assert hx.tick()["action"] == "hold"     # streak 1 < 2
        d = hx.tick()
        assert d["action"] == "up" and hx.replicas() == 3
        assert "queue depth" in d["reason"]

    def test_one_breach_tick_is_noise(self, h):
        hx = h()
        hx.src.queue = 20.0
        hx.tick()
        hx.src.queue = 0.0                       # breach clears
        assert hx.tick()["action"] == "hold"
        hx.src.queue = 20.0
        assert hx.tick()["action"] == "hold"     # streak restarted
        assert hx.replicas() == 2

    def test_up_cooldown_blocks_staircase(self, h):
        hx = h(cooldown_up_s=0.5)
        hx.src.queue = 20.0
        hx.tick()
        assert hx.tick()["action"] == "up"
        for _ in range(4):                       # 0.4s < cooldown
            d = hx.tick()
        assert d["action"] == "blocked" and "cooldown" in d["reason"]
        assert hx.replicas() == 3
        for _ in range(2):                       # past the cooldown
            d = hx.tick()
        assert d["action"] == "up" and hx.replicas() == 4

    def test_blocked_at_max_budget(self, h):
        hx = h(max_replicas=2)
        hx.src.queue = 20.0
        hx.tick()
        d = hx.tick()
        assert d["action"] == "blocked"
        assert "max-replica budget" in d["reason"]
        assert hx.replicas() == 2

    def test_scale_down_on_idle_not_below_min(self, h):
        hx = h(replicas=3, min_replicas=2, idle_ticks=3)
        hx.src.queue = 0.0
        acts = [hx.tick()["action"] for _ in range(4)]
        assert acts == ["hold", "hold", "down", "hold"]
        assert hx.replicas() == 2
        for _ in range(5):
            assert hx.tick()["action"] == "hold"     # at the floor
        assert hx.replicas() == 2

    def test_down_cooldown(self, h):
        hx = h(replicas=3, idle_ticks=1, cooldown_down_s=10.0)
        hx.src.queue = 0.0
        assert hx.tick()["action"] == "down"
        d = hx.tick()
        assert d["action"] == "blocked" and "cooldown" in d["reason"]
        assert hx.replicas() == 2

    def test_prewarm_lead_shrinks_the_window(self, h):
        # prewarm estimate of 2 ticks against breach_ticks=3 means the
        # controller cannot afford to wait: it must act after 1 tick
        hx = h(breach_ticks=3, prewarm_lead_s=0.2)
        hx.src.queue = 20.0
        assert hx.tick()["action"] == "up"
        assert hx.replicas() == 3
        # and the estimate is refreshed by the measured add
        assert hx.asc.stats()["prewarm_estimate_s"] > 0

    def test_latency_slo_breach(self, h):
        hx = h(slo=SLOTargets(latency_p99_ms=100.0))
        hx.src.latency = 0.5                     # 500ms > 100ms target
        hx.tick()
        assert hx.tick()["action"] == "up"
        hx.src.latency = float("nan")            # no data = no breach
        assert hx.tick()["action"] == "hold"

    def test_decisions_and_metrics(self, h):
        hx = h()
        hx.src.queue = 20.0
        hx.tick()
        hx.tick()
        assert rm.SERVING_AUTOSCALE_DECISIONS.value(
            model="m", action="hold") == 1
        assert rm.SERVING_AUTOSCALE_DECISIONS.value(
            model="m", action="up") == 1
        assert rm.SERVING_AUTOSCALE_REPLICAS_TARGET.value(
            model="m") == 3
        last = hx.asc.last_decisions(2)
        assert [d["action"] for d in last] == ["hold", "up"]
        assert hx.asc.target() == 3
        st = hx.asc.stats()
        assert st["ticks"] == 2 and st["up"] == 1

    def test_publishes_pressure_to_admission(self, h):
        adm = AdmissionController(TIERS, shed_start=0.5)
        hx = h(admission=adm)
        hx.src.queue = 6.0                       # 75% of queue_high 8
        hx.tick()
        assert adm.pressure(now=hx.now) == pytest.approx(0.75)
        # free's threshold is 2/3 — the SLO sensors now shed it even
        # though the caller's own load reading is calm
        with pytest.raises(ServerOverloadedError):
            adm.check("a:free", load=0.0, now=hx.now)

    def test_chaos_prewarm_failure_keeps_loop_alive(self, h):
        # the ISSUE's chaos clause: a scale-up whose prewarm dies must
        # leave the controller alive, counted, and backing off
        hx = h(cooldown_up_s=0.5)
        hx.src.queue = 20.0
        with faults.plan("autoscale.decide=fail,times=1"):
            hx.tick()
            d = hx.tick()
            assert d["action"] == "error"
            assert "scale-up failed" in d["reason"]
            assert hx.replicas() == 2            # nothing half-added
            # the failure resets the streak AND stamps the up-cooldown:
            # the rebuilt streak meets a live cooldown, no hot-loop
            assert hx.tick()["action"] == "hold"
            assert hx.tick()["action"] == "blocked"
            for _ in range(6):                   # past the cooldown
                d = hx.tick()
                if d["action"] == "up":
                    break
            assert d["action"] == "up"           # recovered
            assert hx.replicas() == 3
        st = hx.asc.stats()
        assert st["error"] == 1 and st["up"] == 1
        assert rm.SERVING_AUTOSCALE_DECISIONS.value(
            model="m", action="error") == 1

    def test_victim_is_least_loaded_newest(self, h):
        hx = h(replicas=3, idle_ticks=1)
        hx.src.queue = 0.0
        d = hx.tick()
        assert d["action"] == "down"
        # all idle -> the newest rid (r2) drains first
        assert "r2" in d["reason"]
        assert sorted(hx.rset.replicas()) == ["r0", "r1"]

    def test_loop_thread_start_stop(self, h):
        hx = h(interval_s=0.01)
        hx.asc.clock = time.monotonic
        with hx.asc:
            deadline = time.monotonic() + 5.0
            while hx.asc.stats()["ticks"] == 0:
                assert time.monotonic() < deadline
                time.sleep(0.005)
        assert hx.asc.stats()["ticks"] >= 1

    def test_debug_state_serializes(self, h):
        import json
        hx = h(admission=AdmissionController(TIERS))
        hx.src.queue = 20.0
        hx.tick()
        hx.tick()
        json.dumps(hx.asc.debug_state())
