"""Model-family tests (BERT, NMT transformer, model_zoo vision)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd, models


def _tiny_bert(**kw):
    cfg = dict(vocab_size=64, units=32, hidden_size=64, num_layers=2,
               num_heads=4, max_length=32, dropout=0.0)
    cfg.update(kw)
    return models.get_bert_model("bert_12_768_12", **cfg)


def test_bert_forward_shapes():
    bert = _tiny_bert()
    bert.initialize()
    B, L = 2, 16
    inp = nd.array(np.random.randint(0, 64, (B, L)), dtype="int32")
    tt = nd.zeros((B, L), dtype="int32")
    vl = nd.array(np.array([16, 9], dtype=np.float32))
    seq, pooled = bert(inp, tt, vl)
    assert seq.shape == (B, L, 32)
    assert pooled.shape == (B, 32)


def test_bert_valid_length_masks_attention():
    """Tokens past valid_length must not influence earlier positions."""
    bert = _tiny_bert()
    bert.initialize()
    B, L = 1, 8
    base = np.random.randint(1, 64, (B, L)).astype(np.int32)
    vl = nd.array(np.array([4], dtype=np.float32))
    tt = nd.zeros((B, L), dtype="int32")
    seq1, _ = bert(nd.array(base, dtype="int32"), tt, vl)
    changed = base.copy()
    changed[0, 5] = (changed[0, 5] + 7) % 64   # mutate a masked-out token
    seq2, _ = bert(nd.array(changed, dtype="int32"), tt, vl)
    a = seq1.asnumpy()[0, :4]
    b = seq2.asnumpy()[0, :4]
    assert np.allclose(a, b, atol=1e-5), np.abs(a - b).max()


def test_bert_pretrain_heads():
    bert = _tiny_bert()
    bert.initialize()
    head = models.BERTForPretrain(bert, vocab_size=64)
    head.initialize()
    B, L, M = 2, 16, 3
    inp = nd.array(np.random.randint(0, 64, (B, L)), dtype="int32")
    tt = nd.zeros((B, L), dtype="int32")
    vl = nd.array(np.full((B,), L, np.float32))
    mpos = nd.array(np.random.randint(0, L, (B, M)), dtype="int32")
    with autograd.record():
        mlm, nsp = head(inp, tt, vl, mpos)
        loss = mlm.sum() + nsp.sum()
    loss.backward()
    assert mlm.shape == (B, M, 64)
    assert nsp.shape == (B, 2)
    g = bert.word_embed.weight.grad().asnumpy()
    assert np.abs(g).sum() > 0


def test_bert_qa_head():
    bert = _tiny_bert()
    bert.initialize()
    qa = models.BERTForQA(bert)
    qa.initialize()
    inp = nd.array(np.random.randint(0, 64, (2, 16)), dtype="int32")
    tt = nd.zeros((2, 16), dtype="int32")
    out = qa(inp, tt, nd.array(np.full((2,), 16, np.float32)))
    assert out.shape == (2, 16, 2)


def _tiny_nmt():
    return models.transformer_base(32, 40, units=16, hidden_size=32,
                                   num_layers=2, num_heads=2,
                                   max_length=64, dropout=0.0)


def test_transformer_train_and_decode():
    tr = _tiny_nmt()
    tr.initialize()
    src = nd.array(np.random.randint(4, 32, (2, 10)), dtype="int32")
    tgt = nd.array(np.random.randint(4, 40, (2, 8)), dtype="int32")
    sv = nd.array(np.array([10, 7], dtype=np.float32))
    logits = tr(src, tgt, sv)
    assert logits.shape == (2, 8, 40)
    loss_fn = models.SmoothedSoftmaxCELoss(smoothing=0.1)
    lab = nd.array(np.random.randint(0, 40, (2, 8)))
    with autograd.record():
        lg = tr(src, tgt, sv)
        loss = loss_fn(lg, lab,
                       nd.array(np.array([8, 6], dtype=np.float32)))
    loss.backward()
    assert np.isfinite(loss.asnumpy()).all()
    out = tr.greedy_decode(src, sv, max_decode_len=4)
    assert out.shape[0] == 2 and out.shape[1] <= 5
    beam = tr.beam_search(src.slice_axis(axis=0, begin=0, end=1),
                          sv.slice_axis(axis=0, begin=0, end=1),
                          beam_size=2, max_decode_len=3)
    assert beam.asnumpy()[0, 0] == 2  # starts with BOS


def test_transformer_causal_mask():
    """Changing a later target token must not change earlier logits."""
    tr = _tiny_nmt()
    tr.initialize()
    src = nd.array(np.random.randint(4, 32, (1, 6)), dtype="int32")
    tgt1 = np.random.randint(4, 40, (1, 6)).astype(np.int32)
    tgt2 = tgt1.copy()
    tgt2[0, 4] = (tgt2[0, 4] + 3) % 36 + 4
    l1 = tr(src, nd.array(tgt1, dtype="int32")).asnumpy()
    l2 = tr(src, nd.array(tgt2, dtype="int32")).asnumpy()
    assert np.allclose(l1[0, :4], l2[0, :4], atol=1e-5)
    assert not np.allclose(l1[0, 4:], l2[0, 4:], atol=1e-5)


def test_label_smoothing_loss_value():
    logits = np.log(np.full((1, 1, 4), 0.25, dtype=np.float32))
    lab = nd.array(np.array([[1]], dtype=np.float32))
    loss = models.SmoothedSoftmaxCELoss(smoothing=0.1)(
        nd.array(logits), lab).asnumpy()
    # uniform logits: nll == smooth == log(4)
    assert np.allclose(loss, np.log(4), atol=1e-5)


@pytest.mark.parametrize("name", ["resnet18_v1", "resnet18_v2",
                                  "mobilenet_v2_1.0".replace("_v2_", "v2_"),
                                  "squeezenet1.0", "densenet121"])
def test_model_zoo_forward(name):
    net = gluon.model_zoo.get_model(name, classes=10)
    net.initialize()
    out = net(nd.random.uniform(shape=(1, 3, 64, 64)))
    assert out.shape == (1, 10)


def test_model_zoo_inception_forward():
    net = gluon.model_zoo.get_model("inceptionv3", classes=7)
    net.initialize()
    out = net(nd.random.uniform(shape=(1, 3, 299, 299)))
    assert out.shape == (1, 7)


# ISSUE-15 tier-1 relief: training the deepest zoo model costs ~60s;
# the slow tier keeps it, tier-1 keeps densenet121's forward test plus
# the cheaper zoo train coverage below.
@pytest.mark.slow
def test_model_zoo_densenet_trains():
    net = gluon.model_zoo.get_model("densenet121", classes=4)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    x = nd.random.uniform(shape=(2, 3, 64, 64))
    y = nd.array([0, 3], dtype="int32")
    lf = gluon.loss.SoftmaxCrossEntropyLoss()
    from mxnet_tpu import autograd
    with autograd.record():
        l = lf(net(x), y)
    l.backward()
    tr.step(2)
    assert np.isfinite(float(l.mean().asscalar()))
