"""Training step attribution, runtime MFU, bottleneck verdicts
(mxnet_tpu.perf_account — ISSUE-16).

Covers: the promoted MFU/FLOPs math, peak detection, the thread-local
data-wait channel, the fake-trainer span chain (tiling, verdicts,
breakdown histograms, exemplar link — zero compiles), the NaN-safe
cost-analysis fallback, the off-path inert contract, a real traced
ShardedTrainer step with the jit cache unchanged, and the Speedometer
log line.  Everything except the one real-trainer test is numpy/sleep
only, so the suite stays cheap under the tier-1 budget.
"""
import logging
import threading
import time
import types

import numpy as np
import pytest

from mxnet_tpu import perf_account as pa
from mxnet_tpu import runtime_metrics as rm
from mxnet_tpu import tracing as tr


@pytest.fixture(autouse=True)
def _clean():
    """Fresh tracer + attribution state per test; off defaults after."""
    tr.reset()
    tr.enable(sample=1.0)
    pa.reset()
    yield
    tr.disable()
    tr.reset()
    tr.TRACER.set_sample(1.0)
    pa.reset()


@pytest.fixture
def metrics():
    rm.reset()
    rm.enable()
    yield rm
    rm.disable()
    rm.reset()


def _assert_links(trace):
    ids = {s["span_id"] for s in trace["spans"]}
    for s in trace["spans"]:
        assert s["trace_id"] == trace["trace_id"], s
        assert s["parent_id"] is None or s["parent_id"] in ids, s


TRAIN_CHAIN = {"train.step", "train.data.wait", "train.h2d",
               "train.compute", "train.collective", "train.optimizer"}


def _fake_steps(att, n=4, data_wait=0.012, h2d=0.002, compute=0.006):
    """Drive the handle API the way ShardedTrainer does, with sleeps
    standing in for the real phases (default shape: the resnet50
    input-bound case — data wait dominates)."""
    for _ in range(n):
        t0 = time.perf_counter()
        if data_wait:
            time.sleep(data_wait)
        pa.note_data_wait(t0, time.perf_counter())
        h = att.step_start()
        with h:
            with h.phase("h2d"):
                time.sleep(h2d)
            with h.phase("compute"):
                time.sleep(compute)
            h.mark("collective", fused=True)
            h.mark("optimizer", fused=True)


# ------------------------------------------------------------- math
def test_mfu_formula():
    # 6NBL over dt * peak: 6 * 1e9 * 32 * 128 / 1.0 / (100e12)
    assert pa.mfu(1e9, 32, 128, 1.0, 100.0) == pytest.approx(
        6e9 * 32 * 128 / 100e12)


def test_detect_peak_env_override(monkeypatch):
    monkeypatch.setenv("MXNET_PEAK_TFLOPS", "123.5")
    assert pa.detect_peak_tflops() == 123.5
    monkeypatch.delenv("MXNET_PEAK_TFLOPS")
    fake_cpu = [types.SimpleNamespace(platform="cpu", device_kind="cpu")]
    assert pa.detect_peak_tflops(fake_cpu) == 0.15
    v5e = [types.SimpleNamespace(platform="tpu",
                                 device_kind="TPU v5 lite")]
    assert pa.detect_peak_tflops(v5e) == 197.0
    v5p = [types.SimpleNamespace(platform="tpu", device_kind="TPU v5p")]
    assert pa.detect_peak_tflops(v5p) == 459.0


def test_step_flops_unavailable_returns_none():
    class Broken:
        compression = None

        def shard_batch(self, *a):
            raise RuntimeError("no backend")

    assert pa.step_flops(Broken(), (np.ones((2, 2)),)) is None


# ------------------------------------------------- data-wait channel
def test_data_wait_channel_consumed_once():
    pa.note_data_wait(1.0, 2.0)
    assert pa.take_data_wait() == (1.0, 2.0)
    assert pa.take_data_wait() is None


def test_data_wait_channel_is_thread_local():
    seen = {}

    def other():
        pa.note_data_wait(5.0, 6.0)
        seen["own"] = pa.take_data_wait()

    t = threading.Thread(target=other)
    t.start()
    t.join()
    assert seen["own"] == (5.0, 6.0)
    assert pa.take_data_wait() is None      # never crossed threads


# ------------------------------------------- fake-trainer span chain
def test_fake_trainer_chain_tiles_and_is_input_bound(metrics):
    att = pa.StepAttribution(peak_tflops=1.0)
    att.note_flops(1e9)
    _fake_steps(att)

    trace = tr.TRACER.last(root="train.step")
    assert trace is not None, tr.TRACER.stats()
    names = {s["name"] for s in trace["spans"]}
    assert TRAIN_CHAIN <= names, sorted(names)
    _assert_links(trace)
    root = next(s for s in trace["spans"] if s["name"] == "train.step")
    for s in trace["spans"]:
        if s["name"] != "train.step":
            assert s["parent_id"] == root["span_id"], s

    # acceptance: phase spans sum to within 10% of the root interval
    dur = root["t1"] - root["t0"]
    span_sum = sum(s["t1"] - s["t0"] for s in trace["spans"]
                   if s["name"] != "train.step")
    assert abs(span_sum - dur) <= 0.10 * dur, (span_sum, dur)

    # resnet50-shaped case (data wait dominates) -> input_bound
    assert att.verdict() == "input_bound"
    assert pa.current_verdict() == "input_bound"
    assert rm.TRAIN_BOTTLENECK.value() == 1.0
    # every phase observed every step, fused markers at 0
    for phase in pa.PHASES:
        assert rm.TRAIN_STEP_BREAKDOWN_SECONDS.count(phase=phase) == 4
    assert rm.TRAIN_STEP_BREAKDOWN_SECONDS.quantile(
        0.5, phase="collective") < 1e-4
    assert att.mfu_value() > 0
    assert rm.TRAIN_MFU.value() == pytest.approx(att.mfu_value())


def test_root_backdated_to_cover_data_wait():
    att = pa.StepAttribution(peak_tflops=1.0)
    _fake_steps(att, n=1)
    trace = tr.TRACER.last(root="train.step")
    root = next(s for s in trace["spans"] if s["name"] == "train.step")
    dw = next(s for s in trace["spans"]
              if s["name"] == "train.data.wait")
    assert root["t0"] <= dw["t0"]
    assert root["t1"] >= dw["t1"]


def test_comm_and_compute_bound_verdicts(metrics):
    att = pa.StepAttribution(peak_tflops=1.0)
    # collective recorded as a real interval (the explicit-pushpull
    # shape) dominating the step -> comm_bound
    h = att.step_start()
    with h:
        t = time.perf_counter()
        h.record("compute", t, t + 0.001)
        h.record("collective", t, t + 0.009)
        time.sleep(0.01)
    assert att.verdict() == "comm_bound"
    assert rm.TRAIN_BOTTLENECK.value() == 2.0

    att2 = pa.StepAttribution(peak_tflops=1.0)
    h = att2.step_start()
    with h:
        with h.phase("compute"):
            time.sleep(0.008)
        h.mark("collective", fused=True)
        h.mark("optimizer", fused=True)
    assert att2.verdict() == "compute_bound"
    assert rm.TRAIN_BOTTLENECK.value() == 0.0


def test_exemplar_links_p99_to_trace(metrics):
    att = pa.StepAttribution(peak_tflops=1.0)
    _fake_steps(att, n=3)
    tid = rm.TRAINER_STEP_SECONDS.exemplar_for_quantile(0.99)
    assert tid is not None
    assert tr.TRACER.find(tid) is not None


def test_mfu_nan_safe_with_one_warning(metrics, caplog):
    att = pa.StepAttribution(peak_tflops=1.0)
    with caplog.at_level(logging.WARNING, logger="mxnet_tpu"):
        att.note_flops(None)          # cost_analysis unavailable
        att.note_flops(0)             # repeated: no second warning
    warnings = [r for r in caplog.records
                if "cost_analysis" in r.getMessage()]
    assert len(warnings) == 1
    _fake_steps(att, n=2)
    assert att.mfu_value() == 0.0
    assert rm.TRAIN_MFU.value() == 0.0
    assert not np.isnan(rm.TRAIN_MFU.value())


def test_metrics_only_mode_publishes_without_tracing(metrics):
    tr.disable()
    att = pa.StepAttribution(peak_tflops=1.0)
    assert att.active          # metrics alone keep attribution on
    _fake_steps(att, n=2)
    assert rm.TRAIN_STEP_BREAKDOWN_SECONDS.count(phase="compute") == 2
    assert pa.current_verdict() is not None
    assert tr.TRACER.stats()["completed"] == 0


# --------------------------------------------------------- off path
def test_off_path_is_inert():
    tr.disable()
    assert not rm.enabled()
    att = pa.StepAttribution(peak_tflops=1.0)
    assert not att.active
    h = att.step_start()
    assert h is pa._INERT                  # shared no-op handle
    with h:
        with h.phase("compute"):
            pass
        h.mark("collective", fused=True)
    assert att.verdict() is None
    assert pa.current_verdict() is None
    assert len(att._window) == 0
    assert tr.TRACER.stats()["completed"] == 0


def test_summary_shape():
    att = pa.StepAttribution(peak_tflops=1.0)
    att.note_flops(1e6)
    _fake_steps(att, n=2)
    s = att.summary()
    assert s["steps"] == 2
    assert set(s["phase_seconds_mean"]) == set(pa.PHASES)
    assert set(s["phase_fraction"]) == set(pa.PHASES)
    assert s["verdict"] == "input_bound"
    # tiled phases: fractions of the step add up to ~1
    assert sum(s["phase_fraction"].values()) == pytest.approx(1.0,
                                                              abs=0.1)
    d = att.debug_state()
    assert d["flops_per_step"] == 1e6
    assert d["peak_tflops"] == 1.0


# ------------------------------------------------- real ShardedTrainer
def test_real_trainer_traced_step_adds_no_programs(metrics):
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import io, nd, parallel
    from mxnet_tpu.gluon import nn

    tr.disable()                 # warmup compiles untraced
    mx.random.seed(0)
    net = nn.Dense(1, in_units=8, prefix="pa_net_")
    net.initialize(mx.init.Xavier())
    rs = np.random.RandomState(7)
    x = rs.randn(32, 8).astype(np.float32)
    y = (x @ rs.randn(8).astype(np.float32))[:, None]
    it = io.NDArrayIter(x, y, batch_size=8, shuffle=False)
    mesh = parallel.make_mesh(dp=1, tp=1, sp=1,
                              devices=jax.devices()[:1])
    trainer = parallel.ShardedTrainer(
        net, lambda out, lab: ((out - lab) ** 2).mean(), mesh,
        optimizer="sgd", optimizer_params={"learning_rate": 1e-2},
        example_inputs=(nd.array(x[:8]),), n_labels=1)
    b = it.next()
    float(jax.device_get(trainer.step(*b.data, *b.label)))
    baseline = trainer._step._cache_size()
    rm.reset()          # drop the warmup step's metrics-only publish
    rm.enable()

    tr.enable(sample=1.0)
    for _ in range(3):
        b = it.next()
        trainer.step(*b.data, *b.label)
    assert trainer._step._cache_size() == baseline

    trace = tr.TRACER.last(root="train.step")
    assert trace is not None
    names = {s["name"] for s in trace["spans"]}
    assert TRAIN_CHAIN <= names, sorted(names)
    _assert_links(trace)
    coll = next(s for s in trace["spans"]
                if s["name"] == "train.collective")
    assert coll["tags"].get("fused") is True
    assert coll["t0"] == coll["t1"]            # zero-length marker
    assert pa.current_verdict() in pa.VERDICTS
    for phase in pa.PHASES:
        assert rm.TRAIN_STEP_BREAKDOWN_SECONDS.count(phase=phase) == 3

    # off path byte-identical contract: with both switches off the
    # trainer takes the original async-dispatch branch again
    tr.disable()
    rm.disable()
    try:
        assert not trainer.perf.active
        it.reset()
        b = it.next()
        float(jax.device_get(trainer.step(*b.data, *b.label)))
        assert trainer._step._cache_size() == baseline
    finally:
        rm.enable()


# ------------------------------------------------------- Speedometer
def test_speedometer_surfaces_mfu_and_verdict(metrics, caplog):
    from mxnet_tpu.callback import Speedometer

    att = pa.StepAttribution(peak_tflops=1.0)
    att.note_flops(1e9)
    _fake_steps(att, n=2)
    assert pa.current_verdict() == "input_bound"

    sm = Speedometer(batch_size=4, frequent=1)
    param = types.SimpleNamespace(nbatch=0, epoch=0, eval_metric=None)
    with caplog.at_level(logging.INFO):
        sm(param)                       # arms the timer
        param = types.SimpleNamespace(nbatch=1, epoch=0,
                                      eval_metric=None)
        sm(param)                       # logs
    msg = "\n".join(r.getMessage() for r in caplog.records)
    assert "verdict=input_bound" in msg
    assert "mfu=" in msg
