"""FPN / RPN / Faster R-CNN building blocks (gluon.contrib.detection):
shape contracts, box-math correctness vs numpy oracles, static NMS
behavior, and an RPN convergence smoke on synthetic boxes."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.contrib import detection as det


def _backbone():
    """Three-stage toy feature extractor: strides 8/16/32 at 64ch."""
    class Feats(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.s1 = nn.HybridSequential()
                for _ in range(3):                 # 8x total
                    self.s1.add(nn.Conv2D(32, 3, strides=2, padding=1,
                                          activation="relu"))
                self.s2 = nn.Conv2D(48, 3, strides=2, padding=1,
                                    activation="relu")
                self.s3 = nn.Conv2D(64, 3, strides=2, padding=1,
                                    activation="relu")

        def hybrid_forward(self, F, x):
            c3 = self.s1(x)
            c4 = self.s2(c3)
            c5 = self.s3(c4)
            return c3, c4, c5
    return Feats(), (32, 48, 64)


def test_fpn_shapes():
    mx.random.seed(0)
    feats, chans = _backbone()
    fpn = det.FPN(chans, channels=32)
    feats.initialize(mx.init.Xavier())
    fpn.initialize(mx.init.Xavier())
    x = nd.array(np.random.RandomState(0).randn(2, 3, 128, 128)
                 .astype(np.float32))
    levels = fpn(*feats(x))
    assert len(levels) == 4                         # P3..P5 + P6
    assert [tuple(l.shape) for l in levels] == [
        (2, 32, 16, 16), (2, 32, 8, 8), (2, 32, 4, 4), (2, 32, 2, 2)]


def test_anchor_generator_oracle():
    gen = det.AnchorGenerator(strides=(8,), sizes=(32,), ratios=(1.0,))
    a = gen.level(0, 2, 2)
    assert a.shape == (4, 4)
    # first anchor: center (4, 4), 32x32 square
    np.testing.assert_allclose(a[0], [4 - 16, 4 - 16, 4 + 16, 4 + 16])
    # second cell along x: center (12, 4)
    np.testing.assert_allclose(a[1], [12 - 16, 4 - 16, 12 + 16, 4 + 16])


def test_box_iou_and_delta_roundtrip():
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    xy = rng.rand(6, 2) * 50
    wh = rng.rand(6, 2) * 30 + 2
    boxes = np.concatenate([xy, xy + wh], axis=1).astype(np.float32)
    iou = np.asarray(det.box_iou(jnp.asarray(boxes), jnp.asarray(boxes)))
    np.testing.assert_allclose(np.diag(iou), 1.0, rtol=1e-5)
    assert (iou >= 0).all() and (iou <= 1 + 1e-6).all()
    # encode/decode round trip
    anchors = boxes
    gt = boxes[::-1].copy()
    deltas = det.encode_deltas(jnp.asarray(anchors), jnp.asarray(gt))
    back = np.asarray(det.decode_deltas(jnp.asarray(anchors), deltas))
    np.testing.assert_allclose(back, gt, rtol=1e-4, atol=1e-3)


def test_nms_static_suppresses_overlaps():
    import jax.numpy as jnp
    boxes = jnp.asarray(np.array([
        [0, 0, 10, 10], [1, 1, 11, 11],        # heavy overlap pair
        [50, 50, 60, 60], [100, 100, 110, 110]], np.float32))
    scores = jnp.asarray(np.array([0.9, 0.95, 0.5, 0.8], np.float32))
    out_boxes, out_scores, keep = det.nms_static(boxes, scores, topk=4,
                                                 iou_thr=0.5)
    kept = np.asarray(out_scores)[np.asarray(keep)]
    # the 0.9 box is suppressed by its 0.95 twin: 3 survivors
    assert np.asarray(keep).sum() == 3
    np.testing.assert_allclose(sorted(kept, reverse=True),
                               [0.95, 0.8, 0.5], rtol=1e-6)


@pytest.fixture(scope="module")
def frcnn():
    mx.random.seed(0)
    feats, chans = _backbone()
    net = det.FasterRCNN(feats, chans, num_classes=3,
                         image_size=(128, 128), channels=32,
                         rpn_pre_topk=64, rpn_post_topk=16)
    net.initialize(mx.init.Xavier())
    return net


def test_faster_rcnn_inference_shapes(frcnn):
    x = nd.array(np.random.RandomState(1).randn(2, 3, 128, 128)
                 .astype(np.float32))
    cls, boxes, rscores = frcnn(x)
    assert tuple(cls.shape) == (2, 16, 4)           # nc + background
    assert tuple(boxes.shape) == (2, 16, 3, 4)
    assert tuple(rscores.shape) == (2, 16)
    assert np.isfinite(cls.asnumpy()).all()
    assert np.isfinite(boxes.asnumpy()).all()


def test_rpn_targets_match_obvious_gt(frcnn):
    import jax.numpy as jnp
    x = nd.array(np.random.RandomState(2).randn(1, 3, 128, 128)
                 .astype(np.float32))
    levels, anchors, obj, reg = frcnn.rpn_forward(x)
    gt = jnp.asarray(np.array([[16, 16, 48, 48]], np.float32))
    obj_t, obj_m, delta_t, pos = frcnn.rpn_targets(anchors, gt)
    assert float(pos.sum()) >= 1                    # someone matched
    # every positive anchor decodes back onto the gt box
    back = np.asarray(det.decode_deltas(jnp.asarray(anchors), delta_t))
    pos_np = np.asarray(pos) > 0
    np.testing.assert_allclose(back[pos_np],
                               np.tile(np.asarray(gt), (pos_np.sum(), 1)),
                               rtol=1e-4, atol=1e-2)


def test_rpn_trains_on_synthetic_boxes(frcnn_steps=60):
    """RPN loss decreases and positives win on a fixed scene."""
    mx.random.seed(3)
    feats, chans = _backbone()
    net = det.FasterRCNN(feats, chans, num_classes=2,
                         image_size=(128, 128), channels=32,
                         rpn_pre_topk=64, rpn_post_topk=16)
    net.initialize(mx.init.Xavier())
    rng = np.random.RandomState(3)
    x = nd.array(rng.randn(2, 3, 128, 128).astype(np.float32))
    gt = nd.array(np.array([[[20, 20, 60, 60]], [[60, 60, 100, 100]]],
                           np.float32))
    params = {k: p for k, p in net.collect_params().items()
              if p.grad_req != "null"}
    tr = gluon.Trainer(params, "adam", {"learning_rate": 3e-3})
    losses = []
    for _ in range(frcnn_steps):
        with autograd.record():
            _lv, anchors, obj, reg = net.rpn_forward(x)
            loss = net.rpn_loss(anchors, obj, reg, gt)
        loss.backward()
        tr.step(2)
        losses.append(float(loss.asnumpy()))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])


def test_fpn_level_routing():
    """Small ROIs pool from fine levels, large from coarse — guards the
    absolute-level vs list-index off-by-base bug."""
    import jax.numpy as jnp
    w = jnp.asarray(np.array([32.0, 112.0, 224.0, 500.0], np.float32))
    h = w
    lvl = np.asarray(det.fpn_level_index(w, h, n_levels=4))
    # 32px -> k = floor(4 + log2(32/224)) = 1 -> clipped index 0 (P3)
    # 112px -> k=3 -> index 0; 224px -> k=4 -> index 1 (P4)
    # 500px -> k=5 -> index 2 (P5)
    assert list(lvl) == [0, 0, 1, 2], list(lvl)


# ISSUE-15 tier-1 relief: the two-stage convergence run costs ~38s;
# tier-1 keeps the RPN training test and the shape/target assertions,
# and examples/faster_rcnn.py carries the full convergence gate.
@pytest.mark.slow
def test_rcnn_targets_and_second_stage_trains():
    """Second-stage targets assign the right class, and the full
    two-stage loss (RPN + ROI head) decreases on a fixed scene."""
    import jax.numpy as jnp
    mx.random.seed(4)
    feats, chans = _backbone()
    net = det.FasterRCNN(feats, chans, num_classes=2,
                         image_size=(128, 128), channels=32,
                         rpn_pre_topk=64, rpn_post_topk=16)
    net.initialize(mx.init.Xavier())

    # targets: a roi sitting on gt box 1 (class 2) gets class 2
    rois = jnp.asarray(np.array([[20, 20, 60, 60], [90, 90, 120, 120],
                                 [0, 0, 8, 8]], np.float32))
    gt = jnp.asarray(np.array([[22, 22, 58, 58], [88, 88, 118, 118]],
                              np.float32))
    gtc = jnp.asarray(np.array([1, 2], np.int32))
    cls_t, delta_t, fg = net.rcnn_targets(rois, gt, gtc)
    assert list(np.asarray(cls_t)) == [1, 2, 0]
    assert list(np.asarray(fg)) == [1.0, 1.0, 0.0]

    # end-to-end two-stage training step decreases the joint loss
    from mxnet_tpu import autograd, gluon, nd
    rng = np.random.RandomState(4)
    x = nd.array(rng.randn(2, 3, 128, 128).astype(np.float32))
    gt_b = nd.array(np.array([[[20, 20, 60, 60]], [[60, 60, 100, 100]]],
                             np.float32))
    gtc_b = nd.array(np.array([[1], [2]], np.int32), dtype="int32")
    params = {k: p for k, p in net.collect_params().items()
              if p.grad_req != "null"}
    # lr matters: the ROI head chases moving proposals while the RPN
    # trains; 2e-3 oscillates, 5e-4 converges cleanly (measured)
    tr = gluon.Trainer(params, "adam", {"learning_rate": 5e-4})
    losses = []
    for _ in range(60):
        with autograd.record():
            levels, anchors, obj, reg = net.rpn_forward(x)
            rloss = net.rpn_loss(anchors, obj, reg, gt_b)
            rois_b, _sc, keep_b = net.proposals(anchors, obj, reg)
            closs = net.rcnn_loss(levels, rois_b, gt_b, gtc_b,
                                  keep=keep_b)
            loss = rloss + closs
        loss.backward()
        tr.step(2)
        losses.append(float(loss.asnumpy()))
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.3, \
        (np.mean(losses[:5]), np.mean(losses[-5:]))
