"""Compiled batched beam search (models/decoding.py) vs the host-side
oracle and greedy decode."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, models


@pytest.fixture(scope="module")
def tiny_model():
    mx.random.seed(0)
    m = models.transformer_base(src_vocab_size=32, units=32,
                                hidden_size=64, num_layers=2,
                                num_heads=4, dropout=0.0, max_length=64)
    m.initialize(mx.init.Xavier())
    return m


def test_compiled_matches_host_oracle(tiny_model):
    m = tiny_model
    rng = np.random.RandomState(0)
    src = nd.array(rng.randint(4, 32, (3, 7)).astype(np.int32),
                   dtype="int32")
    sv = nd.array(np.array([7, 5, 7], np.float32))
    out_c = m.beam_search(src, sv, beam_size=4, max_decode_len=10) \
        .asnumpy()
    out_h = m.beam_search_host(src, sv, beam_size=4,
                               max_decode_len=10).asnumpy()
    for b in range(3):
        n = out_h[b].shape[0]
        assert list(out_c[b][:n]) == list(out_h[b][:n]), b


def test_beam1_matches_greedy(tiny_model):
    m = tiny_model
    rng = np.random.RandomState(1)
    src = nd.array(rng.randint(4, 32, (2, 6)).astype(np.int32),
                   dtype="int32")
    sv = nd.array(np.array([6, 6], np.float32))
    g = m.greedy_decode(src, sv, max_decode_len=8).asnumpy()
    b1 = m.beam_search(src, sv, beam_size=1, max_decode_len=8).asnumpy()
    for b in range(2):
        n = g[b].shape[0]
        assert list(b1[b][:n]) == list(g[b][:n]), b


def test_program_cache_and_refresh(tiny_model):
    m = tiny_model
    rng = np.random.RandomState(2)
    src = nd.array(rng.randint(4, 32, (2, 5)).astype(np.int32),
                   dtype="int32")
    m.beam_search(src, beam_size=2, max_decode_len=6)
    dec = m._beam_decoder
    n_progs = len(dec._progs)
    m.beam_search(src, beam_size=2, max_decode_len=6)
    assert len(dec._progs) == n_progs          # same signature: cache hit
    # weight update must change results without recompiling
    before = m.beam_search(src, beam_size=2, max_decode_len=6).asnumpy()
    for _name, p in m.collect_params().items():
        if p.grad_req != "null":
            p.set_data(p.data() * 1.5)
            break
    dec.refresh()
    assert len(dec._progs) == n_progs          # programs survive refresh
    after = m.beam_search(src, beam_size=2, max_decode_len=6).asnumpy()
    assert before.shape == after.shape


def test_max_decode_len_beyond_pos_table_raises(tiny_model):
    """The positional table has max_length rows; a longer decode would
    silently clamp dynamic_slice and reuse the last embedding (ADVICE
    r3) — must raise instead.  Decoding at EXACTLY the table size is
    safe (the loop reads pos[t] for t < max_decode_len) and must work."""
    from mxnet_tpu.base import MXNetError
    m = tiny_model
    src = nd.array(np.array([[5, 6, 7]], np.int32), dtype="int32")
    out = m.beam_search(src, beam_size=2, max_decode_len=64)   # == table
    assert out.shape == (1, 65)
    with pytest.raises(MXNetError, match="positional"):
        m.beam_search(src, beam_size=2, max_decode_len=65)     # > table
