"""contrib.text tests (reference: tests/python/unittest/test_contrib_text.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.contrib import text


def test_count_tokens_and_vocab():
    c = text.count_tokens_from_str("a b b c c c\nd d d d", to_lower=True)
    assert c["c"] == 3 and c["b"] == 2
    v = text.Vocabulary(c, min_freq=2, reserved_tokens=["<pad>"])
    assert v.unknown_token == "<unk>"
    assert v.idx_to_token[0] == "<unk>" and v.idx_to_token[1] == "<pad>"
    # freq order: d(4), c(3), b(2); 'a'(1) dropped by min_freq
    assert v.idx_to_token[2:] == ["d", "c", "b"]
    assert v.to_indices(["d", "zzz"]) == [2, 0]
    assert v.to_tokens([2, 0]) == ["d", "<unk>"]
    assert len(v) == 5


def test_custom_embedding(tmp_path):
    p = tmp_path / "vecs.txt"
    p.write_text("hello 1.0 2.0 3.0\nworld 4.0 5.0 6.0\n")
    emb = text.CustomEmbedding(str(p))
    assert emb.vec_len == 3
    got = emb.get_vecs_by_tokens(["world", "hello", "missing"])
    assert np.allclose(got.asnumpy(),
                       [[4, 5, 6], [1, 2, 3], [0, 0, 0]])
    single = emb.get_vecs_by_tokens("hello")
    assert np.allclose(single.asnumpy(), [1, 2, 3])
    table = emb.idx_to_vec
    assert table.shape == (len(emb), 3)


def test_pretrained_names_raise():
    with pytest.raises(mx.MXNetError, match="egress"):
        text.get_pretrained_file_names("glove")


def test_count_tokens_metachar_delims():
    c = text.count_tokens_from_str("a^b^^c", token_delim="^", seq_delim="|")
    assert c == {"a": 1, "b": 1, "c": 1}
