"""mxshard tests: the SPMD partition model and the three passes it
powers (sharding-soundness, replication-soundness, donation-soundness),
plus the ISSUE-19 satellites (linter-source cache key glob,
--profile-passes).

Pure-AST + stdlib: no jax import, so the whole file costs a few
seconds (tier-1 budget discipline — ROADMAP.md).
"""
import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.mxlint import PASSES, lint_paths, lint_sources  # noqa: E402
from tools.mxlint.cache import cache_key                   # noqa: E402

SPMD_PASSES = ["sharding-soundness", "replication-soundness",
               "donation-soundness"]

HDR = """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from jax.experimental.shard_map import shard_map
"""


def run(src, select=None, path="mxnet_tpu/fixture.py", extra=None):
    sources = {path: textwrap.dedent(HDR) + textwrap.dedent(src)}
    for p, s in (extra or {}).items():
        sources[p] = textwrap.dedent(s)
    return lint_sources(sources, select=select)


def ids(issues):
    return [i.pass_id for i in issues]


def test_catalogue_has_twentytwo_passes():
    assert len(PASSES) == 22
    for pid in SPMD_PASSES:
        assert pid in PASSES


# ========================================================= pass 17: specs
def test_unknown_axis_on_resolved_mesh_fires():
    issues = run("""
        def f(devs, body):
            mesh = Mesh(np.array(devs).reshape(1, 8),
                        axis_names=("dp", "tp"))
            g = shard_map(body, mesh, in_specs=(P("model"),),
                          out_specs=P("model"))
    """, select=["sharding-soundness"])
    assert ids(issues) == ["sharding-soundness"]
    assert "'model'" in issues[0].message
    assert "['dp', 'tp']" in issues[0].message


def test_known_axes_stay_quiet():
    issues = run("""
        def f(devs, body):
            mesh = Mesh(np.array(devs).reshape(1, 8),
                        axis_names=("dp", "tp"))
            g = shard_map(body, mesh, in_specs=(P("dp"), P("tp")),
                          out_specs=P(("dp", "tp")))
    """, select=["sharding-soundness"])
    assert issues == []


def test_duplicate_axis_in_one_spec_fires():
    issues = run("""
        def f(devs, body):
            mesh = Mesh(np.array(devs).reshape(1, 8),
                        axis_names=("dp", "tp"))
            s = NamedSharding(mesh, P("tp", "tp"))
    """, select=["sharding-soundness"])
    assert ids(issues) == ["sharding-soundness"]
    assert "more than one dim" in issues[0].message


def test_unresolved_mesh_checks_against_axis_universe():
    # the mesh is a runtime parameter, but SOME mesh in the project
    # names its axes — a spec axis outside every literal axis set flags
    issues = run("""
        MESH = Mesh(np.array([0]).reshape(1, 1), axis_names=("dp", "tp"))

        def f(mesh, body):
            g = shard_map(body, mesh, in_specs=(P("bogus"),),
                          out_specs=P("bogus"))
    """, select=["sharding-soundness"])
    assert ids(issues) == ["sharding-soundness"]
    assert "any mesh constructed in this project" in issues[0].message


def test_replica_mesh_helper_resolves_axis_names():
    # placement.replica_mesh-style maker: axis_names=("dp", axis_name)
    # resolves through the helper param default — strict checking
    issues = run("""
        def replica_mesh(group, axis_name="tp"):
            return Mesh(np.array(group, dtype=object)
                        .reshape(1, len(group)),
                        axis_names=("dp", axis_name))

        def f(group, body):
            mesh = replica_mesh(group)
            s = NamedSharding(mesh, P("model"))
    """, select=["sharding-soundness"])
    assert ids(issues) == ["sharding-soundness"]
    assert "['dp', 'tp']" in issues[0].message


def test_replica_mesh_call_site_axis_name_override():
    # a literal call-site kwarg beats the helper default
    issues = run("""
        def replica_mesh(group, axis_name="tp"):
            return Mesh(np.array(group, dtype=object)
                        .reshape(1, len(group)),
                        axis_names=("dp", axis_name))

        def f(group, body):
            mesh = replica_mesh(group, axis_name="model")
            s = NamedSharding(mesh, P("model"))
    """, select=["sharding-soundness"])
    assert issues == []


def test_divisibility_fires_on_concrete_mismatch():
    # dim 12 sharded over extent-8 tp: 12/8 is a symbol-free fraction
    issues = run("""
        def body(x):
            return x

        def f(devs):
            mesh = Mesh(np.array(devs).reshape(1, 8),
                        axis_names=("dp", "tp"))
            g = shard_map(body, mesh, in_specs=(P("tp", None),),
                          out_specs=P("tp", None))
            y = jnp.ones((12, 4))
            return g(y)
    """, select=["sharding-soundness"])
    assert ids(issues) == ["sharding-soundness"]
    assert "not divisible" in issues[0].message
    assert "extent 8" in issues[0].message


def test_divisibility_quiet_when_divisible_or_symbolic():
    issues = run("""
        def body(x):
            return x

        def f(devs, z):
            mesh = Mesh(np.array(devs).reshape(1, 8),
                        axis_names=("dp", "tp"))
            g = shard_map(body, mesh, in_specs=(P("tp", None),),
                          out_specs=P("tp", None))
            ok = jnp.ones((16, 4))          # 16 % 8 == 0: provable
            g(ok)
            B, D = z.shape                  # symbolic: undecidable
            g(z)
    """, select=["sharding-soundness"])
    assert issues == []


def test_rank_overflow_fires():
    issues = run("""
        def f(devs):
            mesh = Mesh(np.array(devs).reshape(1, 8),
                        axis_names=("dp", "tp"))
            x = jnp.ones((4, 4))
            y = jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, P("dp", None, None)))
    """, select=["sharding-soundness"])
    assert ids(issues) == ["sharding-soundness"]
    assert "rank 2" in issues[0].message


def test_spec_built_in_helper_carries_witness_chain():
    issues = run("""
        def make_specs():
            return (P("bogus"),)

        def f(devs, body):
            mesh = Mesh(np.array(devs).reshape(1, 8),
                        axis_names=("dp", "tp"))
            g = shard_map(body, mesh, in_specs=make_specs(),
                          out_specs=P())
    """, select=["sharding-soundness"])
    assert ids(issues) == ["sharding-soundness"]
    assert "via make_specs (mxnet_tpu/fixture.py:" in issues[0].message


def test_sharding_suppression_is_honored():
    issues = run("""
        def f(devs, body):
            mesh = Mesh(np.array(devs).reshape(1, 8),
                        axis_names=("dp", "tp"))
            # mxlint: disable=sharding-soundness (transition mesh)
            g = shard_map(body, mesh, in_specs=(P("model"),),
                          out_specs=P("model"))
    """, select=["sharding-soundness"])
    assert issues == []


# ================================================== pass 18: replication
def test_p_out_spec_on_raw_shard_fires():
    issues = run("""
        def body(x):
            return x

        def f(mesh, x):
            g = shard_map(body, mesh, in_specs=(P("dp"),),
                          out_specs=P())
            return g(x)
    """, select=["replication-soundness"])
    assert ids(issues) == ["replication-soundness"]
    assert "per-device shard" in issues[0].message


def test_reduced_output_is_quiet():
    issues = run("""
        def body(x):
            return lax.psum(x, "dp")

        def f(mesh, x):
            g = shard_map(body, mesh, in_specs=(P("dp"),),
                          out_specs=P())
            return g(x)
    """, select=["replication-soundness"])
    assert issues == []


def test_tuple_alignment_flags_only_the_shard_element():
    issues = run("""
        def body(x):
            s = lax.pmean(x, "dp")
            return s, x

        def f(mesh, x):
            g = shard_map(body, mesh, in_specs=(P("dp"),),
                          out_specs=(P(), P()))
            return g(x)
    """, select=["replication-soundness"])
    assert ids(issues) == ["replication-soundness"]
    assert "out_specs[1]" in issues[0].message


def test_sharded_out_spec_accepts_the_shard():
    issues = run("""
        def body(x):
            s = lax.pmean(x, "dp")
            return s, x

        def f(mesh, x):
            g = shard_map(body, mesh, in_specs=(P("dp"),),
                          out_specs=(P(), P("dp")))
            return g(x)
    """, select=["replication-soundness"])
    assert issues == []


def test_interprocedural_helper_states_per_element():
    # the quantize.allreduce shape: a helper returning
    # (uniform, per-device) — only the per-device element flags
    issues = run("""
        def allreduce(x):
            g = lax.all_gather(x, "dp")
            total = jnp.sum(g, axis=0)
            return total, x

        def body(x):
            out, res = allreduce(x)
            return out, res

        def f(mesh, x):
            g = shard_map(body, mesh, in_specs=(P("dp"),),
                          out_specs=(P(), P()))
            return g(x)
    """, select=["replication-soundness"])
    assert ids(issues) == ["replication-soundness"]
    assert "out_specs[1]" in issues[0].message


def test_shuffling_collective_does_not_wash():
    # ppermute results still differ per device — P() stays wrong
    issues = run("""
        def body(x):
            y = lax.ppermute(x, "dp", perm=[(0, 1), (1, 0)])
            return y

        def f(mesh, x):
            g = shard_map(body, mesh, in_specs=(P("dp"),),
                          out_specs=P())
            return g(x)
    """, select=["replication-soundness"])
    assert ids(issues) == ["replication-soundness"]


def test_lambda_body_and_unchecked_variant():
    issues = run("""
        from mxnet_tpu._jax_compat import shard_map_unchecked

        def f(mesh, x):
            g = shard_map_unchecked(lambda v: v, mesh,
                                    in_specs=(P("dp"),),
                                    out_specs=P())
            h = shard_map_unchecked(lambda v: lax.psum(v, "dp"), mesh,
                                    in_specs=(P("dp"),),
                                    out_specs=P())
            return g(x), h(x)
    """, select=["replication-soundness"])
    assert ids(issues) == ["replication-soundness"]


def test_replication_suppression_is_honored():
    issues = run("""
        def body(x):
            return x

        def f(mesh, x):
            # mxlint: disable=replication-soundness (host dedups later)
            g = shard_map(body, mesh, in_specs=(P("dp"),),
                          out_specs=P())
            return g(x)
    """, select=["replication-soundness"])
    assert issues == []


# ===================================================== pass 19: donation
def test_out_of_range_donation_fires():
    issues = run("""
        def body(x):
            return x

        def f():
            step = jax.jit(body, donate_argnums=(1,))
            return step
    """, select=["donation-soundness"])
    assert ids(issues) == ["donation-soundness"]
    assert "only 1 positional" in issues[0].message


def test_unknown_donate_argname_fires():
    issues = run("""
        def body(x):
            return x

        def f():
            step = jax.jit(body, donate_argnames=("params",))
            return step
    """, select=["donation-soundness"])
    assert ids(issues) == ["donation-soundness"]
    assert "'params'" in issues[0].message


def test_dropped_donation_provable_shape_mismatch_fires():
    issues = run("""
        def body(x):
            B, D = x.shape
            return jnp.zeros((B,))

        def f():
            step = jax.jit(body, donate_argnums=(0,))
            return step
    """, select=["donation-soundness"])
    assert ids(issues) == ["donation-soundness"]
    assert "silently dropped" in issues[0].message


def test_matching_output_keeps_donation_quiet():
    issues = run("""
        def body(x):
            B, D = x.shape
            return x * 2.0, jnp.zeros((B,))

        def f():
            step = jax.jit(body, donate_argnums=(0,))
            return step
    """, select=["donation-soundness"])
    assert issues == []


def test_unknown_output_shape_stays_quiet():
    # an opaque output could alias anything — no provable mismatch
    issues = run("""
        def helper(x):
            return x

        def body(x, f):
            B, D = x.shape
            return f(x)

        def g():
            step = jax.jit(body, donate_argnums=(0,))
            return step
    """, select=["donation-soundness"])
    assert issues == []


def test_use_after_donate_fires():
    issues = run("""
        def body(x):
            return x * 2.0

        def f(x):
            step = jax.jit(body, donate_argnums=(0,))
            y = step(x)
            z = x + 1.0
            return y, z
    """, select=["donation-soundness"])
    assert ids(issues) == ["donation-soundness"]
    assert "deleted or donated" in issues[0].message


def test_rebind_washes_use_after_donate():
    issues = run("""
        def body(x):
            return x * 2.0

        def f(x):
            step = jax.jit(body, donate_argnums=(0,))
            x = step(x)
            z = x + 1.0
            return z
    """, select=["donation-soundness"])
    assert issues == []


def test_self_attribute_use_after_donate_fires():
    issues = run("""
        class T:
            def go(self):
                step = jax.jit(lambda p: p, donate_argnums=(0,))
                out = step(self.params)
                norm = jnp.sum(self.params["w"])
                return out, norm
    """, select=["donation-soundness"])
    assert ids(issues) == ["donation-soundness"]
    assert "'self.params'" in issues[0].message


def test_decorator_donation_checked():
    issues = run("""
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def body(x):
            B, D = x.shape
            return jnp.zeros((B,))
    """, select=["donation-soundness"])
    assert ids(issues) == ["donation-soundness"]


def test_donation_suppression_is_honored():
    issues = run("""
        def body(x):
            return x * 2.0

        def f(x):
            step = jax.jit(body, donate_argnums=(0,))
            y = step(x)
            # mxlint: disable=donation-soundness (x is a host copy)
            z = x + 1.0
            return y, z
    """, select=["donation-soundness"])
    assert issues == []


# ================================================== the real tree gates
def test_repo_tree_is_clean_under_spmd_passes():
    """ISSUE-19 acceptance: the swept tree carries no SPMD findings."""
    issues = lint_paths([os.path.join(REPO, "mxnet_tpu"),
                         os.path.join(REPO, "tools")],
                        select=SPMD_PASSES)
    assert issues == [], "\n".join(str(i) for i in issues)


# =============================================== satellite: cache key glob
def test_new_pass_source_busts_cache_key(tmp_path):
    """Adding or editing ANY file under tools/mxlint/ must miss the
    warm cache — the side-input hash globs the package instead of a
    hard-coded module list."""
    root = tmp_path
    (root / "tools" / "mxlint" / "passes").mkdir(parents=True)
    target = root / "x.py"
    target.write_text("x = 1\n")
    k1 = cache_key([str(target)], None, None, root=str(root))
    newpass = root / "tools" / "mxlint" / "passes" / "shiny.py"
    newpass.write_text("# a new pass module\n")
    k2 = cache_key([str(target)], None, None, root=str(root))
    assert k1 != k2, "adding a pass module must change the key"
    newpass.write_text("# the pass module, edited\n")
    k3 = cache_key([str(target)], None, None, root=str(root))
    assert k3 != k2, "editing a pass module must change the key"


# ============================================ satellite: --profile-passes
def test_profile_passes_prints_timing_table(tmp_path):
    f = tmp_path / "m.py"
    f.write_text("def g(x):\n    return x\n")
    proc = subprocess.run(
        [sys.executable, "-m", "tools.mxlint", "--no-cache",
         "--profile-passes", "--select", "donation-soundness", str(f)],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stderr
    assert "pass timings" in proc.stderr
    assert "donation-soundness" in proc.stderr
    assert "(parse+harvest)" in proc.stderr
