"""Ragged paged-attention kernel parity (CPU interpreter mode; same
code compiles on TPU).  Oracle chain: Pallas kernel == pure-jax
reference == dense softmax over the gathered per-sequence context.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from mxnet_tpu.ndarray import op as opmod
from mxnet_tpu.ops.pallas_kernels import (
    ragged_paged_attention, ragged_paged_attention_reference,
    ragged_paged_verify, ragged_paged_verify_reference)


def _pool(seed, n_pages, page_size, H, D):
    rs = np.random.RandomState(seed)
    k = jnp.asarray(rs.randn(n_pages, page_size, H, D), jnp.float32)
    v = jnp.asarray(rs.randn(n_pages, page_size, H, D), jnp.float32)
    return k, v


def _dense_oracle(q, k_pages, v_pages, bt, lens):
    """Per-sequence gather + masked softmax in numpy."""
    q, k_pages, v_pages = map(np.asarray, (q, k_pages, v_pages))
    bt, lens = np.asarray(bt), np.asarray(lens)
    B, H, D = q.shape
    ps = k_pages.shape[1]
    out = np.zeros_like(q)
    for b in range(B):
        L = int(lens[b])
        if L == 0:
            continue                        # inactive slot: zeros
        k = k_pages[bt[b]].reshape(-1, H, D)[:L]    # (L, H, D)
        v = v_pages[bt[b]].reshape(-1, H, D)[:L]
        s = np.einsum("hd,thd->ht", q[b], k) / np.sqrt(D)
        p = np.exp(s - s.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        out[b] = np.einsum("ht,thd->hd", p, v)
    return out


@pytest.mark.parametrize("lens,pages_per_seq,page_size", [
    ([12, 5, 0], 3, 4),        # full table / partial last page / inactive
    ([8, 8], 2, 4),            # exact page boundary
    ([1, 3], 4, 4),            # single token / partial first page
    ([7], 1, 8),               # one sequence, one partially-filled page
])
def test_kernel_matches_reference_and_dense(lens, pages_per_seq,
                                            page_size):
    rs = np.random.RandomState(42)
    B, H, D, n_pool = len(lens), 2, 8, 11
    q = jnp.asarray(rs.randn(B, H, D), jnp.float32)
    k_pages, v_pages = _pool(1, n_pool, page_size, H, D)
    bt = jnp.asarray(rs.randint(1, n_pool, (B, pages_per_seq)),
                     jnp.int32)
    lens_a = jnp.asarray(lens, jnp.int32)
    out_k = ragged_paged_attention(q, k_pages, v_pages, bt, lens_a,
                                   interpret=True)
    out_r = ragged_paged_attention_reference(q, k_pages, v_pages, bt,
                                             lens_a)
    oracle = _dense_oracle(q, k_pages, v_pages, bt, lens)
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(out_r),
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(out_k), oracle, atol=1e-5)


def test_inactive_slot_outputs_zero():
    q = jnp.ones((2, 1, 4), jnp.float32)
    k_pages, v_pages = _pool(2, 4, 2, 1, 4)
    bt = jnp.asarray([[1, 2], [0, 0]], jnp.int32)
    lens = jnp.asarray([3, 0], jnp.int32)
    out = ragged_paged_attention(q, k_pages, v_pages, bt, lens,
                                 interpret=True)
    assert np.all(np.asarray(out)[1] == 0.0)
    assert np.all(np.isfinite(np.asarray(out)))


def test_block_table_indirection_is_honored():
    """Two sequences sharing identical context through DIFFERENT page
    orderings must attend identically — the indirection, not the page
    ids, defines the context."""
    rs = np.random.RandomState(7)
    H, D, ps = 2, 4, 4
    k_pages, v_pages = _pool(3, 6, ps, H, D)
    # seq 0 reads pages [1, 2]; seq 1 reads [3, 4] holding the SAME data
    k_pages = k_pages.at[3].set(k_pages[1]).at[4].set(k_pages[2])
    v_pages = v_pages.at[3].set(v_pages[1]).at[4].set(v_pages[2])
    q1 = rs.randn(1, H, D).astype(np.float32)
    q = jnp.asarray(np.concatenate([q1, q1], 0))
    bt = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
    lens = jnp.asarray([7, 7], jnp.int32)
    out = np.asarray(ragged_paged_attention(
        q, k_pages, v_pages, bt, lens, interpret=True))
    np.testing.assert_allclose(out[0], out[1], atol=1e-6)


# ------------------------------------------------- multi-token verify
def _dense_verify_oracle(q, k_pages, v_pages, bt, starts, lens):
    """Per-(sequence, window-row) gather + causal masked softmax in
    numpy: row w of sequence b attends over positions 0..starts[b]+w."""
    q, k_pages, v_pages = map(np.asarray, (q, k_pages, v_pages))
    bt, starts, lens = map(np.asarray, (bt, starts, lens))
    B, W, H, D = q.shape
    out = np.zeros_like(q)
    for b in range(B):
        k = k_pages[bt[b]].reshape(-1, H, D)
        v = v_pages[bt[b]].reshape(-1, H, D)
        for w in range(int(lens[b])):
            L = int(starts[b]) + w + 1
            s = np.einsum("hd,thd->ht", q[b, w], k[:L]) / np.sqrt(D)
            p = np.exp(s - s.max(-1, keepdims=True))
            p = p / p.sum(-1, keepdims=True)
            out[b, w] = np.einsum("ht,thd->hd", p, v[:L])
    return out


@pytest.mark.parametrize("starts,lens,W,pages_per_seq,page_size", [
    ([4, 0, 9], [3, 4, 0], 4, 4, 4),   # spec window / prefill / inactive
    ([0, 3], [8, 1], 8, 2, 4),         # whole-context window / width 1
    ([6, 5], [2, 3], 4, 3, 4),         # mid-page starts
    ([7], [1], 1, 1, 8),               # degenerate W=1 recovery shape
])
def test_verify_kernel_matches_reference_and_dense(starts, lens, W,
                                                   pages_per_seq,
                                                   page_size):
    rs = np.random.RandomState(17)
    B, H, D, n_pool = len(lens), 2, 8, 11
    q = jnp.asarray(rs.randn(B, W, H, D), jnp.float32)
    k_pages, v_pages = _pool(5, n_pool, page_size, H, D)
    bt = jnp.asarray(rs.randint(1, n_pool, (B, pages_per_seq)),
                     jnp.int32)
    st = jnp.asarray(starts, jnp.int32)
    ln = jnp.asarray(lens, jnp.int32)
    out_k = ragged_paged_verify(q, k_pages, v_pages, bt, st, ln,
                                interpret=True)
    out_r = ragged_paged_verify_reference(q, k_pages, v_pages, bt, st,
                                          ln)
    oracle = _dense_verify_oracle(q, k_pages, v_pages, bt, starts, lens)
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(out_r),
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(out_k), oracle, atol=1e-5)
    # rows past lens are defined zeros (kernel wrapper and reference)
    for b, L in enumerate(lens):
        assert np.all(np.asarray(out_k)[b, L:] == 0.0)
        assert np.all(np.asarray(out_r)[b, L:] == 0.0)


def test_verify_width1_equals_decode_attention():
    """The W=1 verify window IS decode attention: start = ctx - 1,
    length 1 reproduces ragged_paged_attention for the same query."""
    rs = np.random.RandomState(23)
    B, H, D, ps = 3, 2, 4, 4
    q = jnp.asarray(rs.randn(B, H, D), jnp.float32)
    k_pages, v_pages = _pool(9, 9, ps, H, D)
    bt = jnp.asarray(rs.randint(1, 9, (B, 3)), jnp.int32)
    ctx = jnp.asarray([5, 12, 1], jnp.int32)
    dec = ragged_paged_attention(q, k_pages, v_pages, bt, ctx,
                                 interpret=True)
    ver = ragged_paged_verify(q[:, None], k_pages, v_pages, bt,
                              ctx - 1, jnp.ones((B,), jnp.int32),
                              interpret=True)
    np.testing.assert_allclose(np.asarray(ver)[:, 0], np.asarray(dec),
                               atol=1e-6)


def test_verify_shape_guard():
    from mxnet_tpu.base import MXNetError
    q = jnp.ones((1, 2, 2, 4), jnp.float32)
    k_pages, v_pages = _pool(11, 4, 2, 1, 4)    # heads mismatch
    with pytest.raises(MXNetError, match="inconsistent"):
        ragged_paged_verify(q, k_pages, v_pages,
                            jnp.zeros((1, 2), jnp.int32),
                            jnp.zeros((1,), jnp.int32),
                            jnp.ones((1,), jnp.int32), interpret=True)


def test_registry_frontend_dispatches_reference_on_cpu():
    """The registered op picks the jax reference off-TPU and matches
    the kernel (one ragged batch, mixed lengths)."""
    rs = np.random.RandomState(3)
    B, H, D, ps, n_pool, P = 2, 1, 4, 2, 5, 3
    q = rs.randn(B, H, D).astype(np.float32)
    kp = rs.randn(n_pool, ps, H, D).astype(np.float32)
    vp = rs.randn(n_pool, ps, H, D).astype(np.float32)
    bt = rs.randint(1, n_pool, (B, P)).astype(np.float32)  # casts inside
    lens = np.array([5, 2], np.float32)
    from mxnet_tpu import nd
    got = opmod._contrib_ragged_paged_attention(
        nd.array(q), nd.array(kp), nd.array(vp), nd.array(bt),
        nd.array(lens)).asnumpy()
    want = np.asarray(ragged_paged_attention(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(bt, jnp.int32), jnp.asarray(lens, jnp.int32),
        interpret=True))
    np.testing.assert_allclose(got, want, atol=1e-5)
