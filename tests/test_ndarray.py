"""NDArray basics (reference: tests/python/unittest/test_ndarray.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_creation():
    a = nd.array([[1, 2], [3, 4]])
    assert a.shape == (2, 2)
    assert a.dtype == np.float32
    assert np.allclose(a.asnumpy(), [[1, 2], [3, 4]])

    z = nd.zeros((3, 4))
    assert z.shape == (3, 4)
    assert z.asnumpy().sum() == 0

    o = nd.ones((2,), dtype="int32")
    assert o.dtype == np.int32

    f = nd.full((2, 2), 7.0)
    assert (f.asnumpy() == 7).all()

    r = nd.arange(0, 10, 2)
    assert np.allclose(r.asnumpy(), [0, 2, 4, 6, 8])


def test_arithmetic():
    a = nd.array([1.0, 2.0, 3.0])
    b = nd.array([4.0, 5.0, 6.0])
    assert np.allclose((a + b).asnumpy(), [5, 7, 9])
    assert np.allclose((a - b).asnumpy(), [-3, -3, -3])
    assert np.allclose((a * b).asnumpy(), [4, 10, 18])
    assert np.allclose((b / a).asnumpy(), [4, 2.5, 2])
    assert np.allclose((a + 1).asnumpy(), [2, 3, 4])
    assert np.allclose((1 + a).asnumpy(), [2, 3, 4])
    assert np.allclose((10 - a).asnumpy(), [9, 8, 7])
    assert np.allclose((a ** 2).asnumpy(), [1, 4, 9])
    assert np.allclose((2 / a).asnumpy(), [2, 1, 2 / 3])
    assert np.allclose((-a).asnumpy(), [-1, -2, -3])


def test_inplace_arithmetic():
    a = nd.array([1.0, 2.0])
    a += 1
    assert np.allclose(a.asnumpy(), [2, 3])
    a *= 2
    assert np.allclose(a.asnumpy(), [4, 6])


def test_comparison():
    a = nd.array([1.0, 2.0, 3.0])
    b = nd.array([2.0, 2.0, 2.0])
    assert np.allclose((a > b).asnumpy(), [0, 0, 1])
    assert np.allclose((a >= b).asnumpy(), [0, 1, 1])
    assert np.allclose((a == b).asnumpy(), [0, 1, 0])
    assert np.allclose((a < 2).asnumpy(), [1, 0, 0])


def test_indexing():
    a = nd.array(np.arange(24).reshape(2, 3, 4))
    assert a[0].shape == (3, 4)
    assert a[0, 1, 2].asscalar() == 6
    assert a[:, 1].shape == (2, 4)
    assert a[0, :, 1:3].shape == (3, 2)
    a[0, 0, 0] = 100
    assert a[0, 0, 0].asscalar() == 100
    # boolean/fancy
    idx = nd.array([0, 1], dtype="int32")
    assert a[idx].shape == (2, 3, 4)


def test_shape_methods():
    a = nd.array(np.arange(24).reshape(2, 3, 4))
    assert a.reshape(6, 4).shape == (6, 4)
    assert a.reshape((-1,)).shape == (24,)
    assert a.reshape(0, -1).shape == (2, 12)
    assert a.transpose().shape == (4, 3, 2)
    assert a.transpose((1, 0, 2)).shape == (3, 2, 4)
    assert a.flatten().shape == (2, 12)
    assert a.expand_dims(0).shape == (1, 2, 3, 4)
    assert a.expand_dims(0).squeeze(0).shape == (2, 3, 4)
    assert a.T.shape == (4, 3, 2)


def test_reductions():
    a = nd.array([[1.0, 2.0], [3.0, 4.0]])
    assert a.sum().asscalar() == 10
    assert np.allclose(a.sum(axis=0).asnumpy(), [4, 6])
    assert np.allclose(a.mean(axis=1).asnumpy(), [1.5, 3.5])
    assert a.max().asscalar() == 4
    assert a.min().asscalar() == 1
    assert np.allclose(a.argmax(axis=1).asnumpy(), [1, 1])
    assert abs(a.norm().asscalar() - np.sqrt(30)) < 1e-5


def test_dot():
    a = nd.array(np.random.rand(3, 4))
    b = nd.array(np.random.rand(4, 5))
    c = nd.dot(a, b)
    assert c.shape == (3, 5)
    assert np.allclose(c.asnumpy(), a.asnumpy() @ b.asnumpy(), atol=1e-5)
    # transpose flags
    c2 = nd.dot(a, b.T, transpose_b=True)
    assert np.allclose(c2.asnumpy(), c.asnumpy(), atol=1e-5)


def test_batch_dot():
    a = nd.array(np.random.rand(2, 3, 4))
    b = nd.array(np.random.rand(2, 4, 5))
    c = nd.batch_dot(a, b)
    assert c.shape == (2, 3, 5)
    assert np.allclose(c.asnumpy(), a.asnumpy() @ b.asnumpy(), atol=1e-5)


def test_concat_split_stack():
    a = nd.ones((2, 3))
    b = nd.zeros((2, 3))
    c = nd.concat(a, b, dim=0)
    assert c.shape == (4, 3)
    c2 = nd.concat(a, b, dim=1)
    assert c2.shape == (2, 6)
    s = nd.stack(a, b, axis=0)
    assert s.shape == (2, 2, 3)
    parts = nd.split(c, num_outputs=2, axis=0)
    assert len(parts) == 2 and parts[0].shape == (2, 3)


def test_broadcast_ops():
    a = nd.array([[1.0], [2.0]])
    b = nd.array([[10.0, 20.0]])
    c = nd.broadcast_add(a, b)
    assert c.shape == (2, 2)
    assert np.allclose(c.asnumpy(), [[11, 21], [12, 22]])
    d = nd.broadcast_to(a, shape=(2, 3))
    assert d.shape == (2, 3)


def test_take_pick_onehot():
    w = nd.array(np.arange(12).reshape(4, 3))
    idx = nd.array([0, 2], dtype="int32")
    t = nd.take(w, idx)
    assert t.shape == (2, 3)
    assert np.allclose(t.asnumpy(), [[0, 1, 2], [6, 7, 8]])
    data = nd.array([[0.1, 0.9], [0.8, 0.2]])
    p = nd.pick(data, nd.array([1, 0]))
    assert np.allclose(p.asnumpy(), [0.9, 0.8])
    oh = nd.one_hot(nd.array([0, 2]), depth=3)
    assert np.allclose(oh.asnumpy(), [[1, 0, 0], [0, 0, 1]])


def test_topk_sort():
    a = nd.array([3.0, 1.0, 2.0])
    v = nd.topk(a, k=2, ret_typ="value")
    assert np.allclose(v.asnumpy(), [3, 2])
    s = nd.sort(a)
    assert np.allclose(s.asnumpy(), [1, 2, 3])
    i = nd.argsort(a)
    assert np.allclose(i.asnumpy(), [1, 2, 0])


def test_astype_cast():
    a = nd.array([1.5, 2.5])
    b = a.astype("int32")
    assert b.dtype == np.int32
    c = nd.cast(a, dtype="float16")
    assert c.dtype == np.float16


def test_save_load(tmp_path):
    fname = str(tmp_path / "arrays.npz")
    data = {"w": nd.array([1.0, 2.0]), "b": nd.ones((2, 2))}
    nd.save(fname, data)
    loaded = nd.load(fname)
    assert set(loaded) == {"w", "b"}
    assert np.allclose(loaded["w"].asnumpy(), [1, 2])

    nd.save(fname, [nd.array([3.0])])
    lst = nd.load(fname)
    assert isinstance(lst, list) and np.allclose(lst[0].asnumpy(), [3])


def test_context_placement():
    a = nd.ones((2, 2), ctx=mx.cpu(0))
    assert a.context.device_type in ("cpu",)
    b = a.as_in_context(mx.cpu(0))
    assert b is a
    c = a.copyto(mx.cpu(0))
    assert c is not a


def test_waitall_and_wait_to_read():
    a = nd.random.uniform(shape=(100, 100))
    b = nd.dot(a, a)
    b.wait_to_read()
    mx.waitall()


def test_numpy_interop():
    a = nd.array([1.0, 2.0])
    arr = np.asarray(a)
    assert isinstance(arr, np.ndarray)
    assert float(a.sum()) == 3.0
    assert a.tolist() == [1.0, 2.0]


def test_random_ops():
    mx.random.seed(0)
    u = nd.random.uniform(0, 1, shape=(1000,))
    assert 0.4 < u.asnumpy().mean() < 0.6
    n = nd.random.normal(0, 1, shape=(1000,))
    assert abs(n.asnumpy().mean()) < 0.2
    r = nd.random.randint(0, 10, shape=(100,))
    assert r.asnumpy().min() >= 0 and r.asnumpy().max() < 10
    # seed determinism
    mx.random.seed(7)
    x1 = nd.random.uniform(shape=(5,)).asnumpy()
    mx.random.seed(7)
    x2 = nd.random.uniform(shape=(5,)).asnumpy()
    assert np.allclose(x1, x2)


def _correlation_oracle(d1, d2, kernel_size, max_displacement,
                        stride1, stride2, pad_size, is_multiply):
    """Naive numpy reference for the Correlation cost volume."""
    N, C, H, W = d1.shape
    kr = (kernel_size - 1) // 2
    border = max_displacement + kr
    pH, pW = H + 2 * pad_size, W + 2 * pad_size
    top_h = max(1, -(-(pH - 2 * border) // stride1))
    top_w = max(1, -(-(pW - 2 * border) // stride1))
    gr = max_displacement // stride2
    gw = 2 * gr + 1
    p1 = np.zeros((N, C, pH, pW), d1.dtype)
    p2 = np.zeros((N, C, pH, pW), d1.dtype)
    p1[:, :, pad_size:pad_size + H, pad_size:pad_size + W] = d1
    p2[:, :, pad_size:pad_size + H, pad_size:pad_size + W] = d2
    out = np.zeros((N, gw * gw, top_h, top_w), np.float32)
    sumelems = kernel_size * kernel_size * C
    for oy in range(gw):
        for ox in range(gw):
            dy, dx = (oy - gr) * stride2, (ox - gr) * stride2
            for y in range(top_h):
                for x in range(top_w):
                    y1, x1 = y * stride1 + border, x * stride1 + border
                    a = p1[:, :, y1 - kr:y1 + kr + 1, x1 - kr:x1 + kr + 1]
                    b = p2[:, :, y1 + dy - kr:y1 + dy + kr + 1,
                           x1 + dx - kr:x1 + dx + kr + 1]
                    v = a * b if is_multiply else np.abs(a - b)
                    out[:, oy * gw + ox, y, x] = v.sum((1, 2, 3)) / sumelems
    return out


def test_correlation_vs_oracle():
    rng = np.random.RandomState(0)
    for kwargs in [
        dict(kernel_size=1, max_displacement=2, stride1=1, stride2=1,
             pad_size=2, is_multiply=True),
        dict(kernel_size=3, max_displacement=2, stride1=2, stride2=2,
             pad_size=3, is_multiply=True),
        dict(kernel_size=1, max_displacement=1, stride1=1, stride2=1,
             pad_size=1, is_multiply=False),
    ]:
        d1 = rng.randn(2, 3, 8, 8).astype(np.float32)
        d2 = rng.randn(2, 3, 8, 8).astype(np.float32)
        got = nd.Correlation(nd.array(d1), nd.array(d2), **kwargs).asnumpy()
        want = _correlation_oracle(d1, d2, **kwargs)
        assert got.shape == want.shape, (got.shape, want.shape, kwargs)
        assert np.allclose(got, want, rtol=1e-4, atol=1e-5), kwargs


def test_legacy_params_format_roundtrip(tmp_path):
    from mxnet_tpu import compat
    arrays = {"fc1_weight": nd.random.uniform(shape=(8, 4)),
              "fc1_bias": nd.array(np.arange(8, dtype=np.float32)),
              "count": nd.array(np.array([3], dtype=np.int32)
                                ).astype("int32"),
              "scalar": nd.array(np.float32(7.5).reshape(()))}
    path = str(tmp_path / "model-0000.params")
    compat.save_params_dmlc(path, arrays)
    # magic detected and routed by plain nd.load
    back = nd.load(path)
    assert set(back) == set(arrays)
    for k in arrays:
        assert str(back[k].dtype) == str(arrays[k].dtype), k
        assert np.allclose(back[k].asnumpy(), arrays[k].asnumpy()), k
    # header is the documented dmlc list magic
    import struct
    with open(path, "rb") as f:
        assert struct.unpack("<Q", f.read(8))[0] == 0x112
