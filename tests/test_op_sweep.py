"""Registry-wide operator correctness sweep (VERDICT r4 item 4).

Reference pattern: ``tests/python/unittest/test_operator.py`` — the
biggest single test file upstream, where (nearly) every registered op is
forward-checked against a NumPy oracle and numeric-gradient-checked
(SURVEY.md §4 row 1, ``check_numeric_gradient``).  Here the whole
``list_ops()`` registry is enumerated so a newly registered op is swept
automatically; an op may opt out only via the explicit skip tables below,
each entry with a one-line reason.

Three layers per op:
  1. forward smoke — the generated frontend runs on canonical small
     inputs; outputs are finite (float) and well-formed;
  2. NumPy oracle — where a clean numpy equivalent exists, outputs match;
  3. finite-difference gradient — every differentiable op's autograd
     gradient (the tape path) matches central differences, with
     integer/index inputs held fixed (``wrt``).
"""
import math
import zlib

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
import mxnet_tpu.ndarray.op as opmod
from mxnet_tpu.ops.registry import OP_REGISTRY, list_ops

# --------------------------------------------------------------- enumeration
_seen = {}
for _n in list_ops():
    _od = OP_REGISTRY[_n]
    _seen.setdefault(id(_od), _n)          # first registration = primary name
CANONICAL = sorted(_seen.values())


def _rng(name):
    # crc32, not hash(): str hashes are salted per interpreter run and
    # would make per-op inputs (and any failure) non-reproducible
    return np.random.RandomState(zlib.crc32(name.encode()) % (2 ** 31))


def _f32(rng, *shape):
    return rng.randn(*shape).astype(np.float32)


def _pos(rng, *shape):
    return (np.abs(rng.randn(*shape)) + 0.3).astype(np.float32)


def _idx(rng, n, *shape):
    """index-like float input: x.5 values so ±eps FD perturbation never
    crosses an integer boundary (the op casts to int internally)."""
    return (rng.randint(0, n, shape) + 0.5).astype(np.float32)


def _spd(rng, n, batch=()):
    m = rng.randn(*batch, n, n)
    a = m @ np.swapaxes(m, -1, -2) + n * np.eye(n)
    return a.astype(np.float32)


# --------------------------------------------------------------------- skips
# Ops the sweep does not run AT ALL (each covered elsewhere or not
# meaningfully invokable standalone).  Budget: < 10% of the registry.
FWD_SKIP = {
    "Custom": "python CustomOp trampoline; needs a registered user op "
              "(covered by tests/test_operator_custom.py)",
}

# Differentiable ops whose FD gradient check is skipped (forward still
# swept).  Each reason is a property of the op, not a TODO.
GRAD_SKIP = {
    "BlockGrad": "gradient is zero BY CONTRACT (identity forward); FD "
                 "sees the identity — asserted separately below",
    "Softmax": "SoftmaxOutput's training gradient is (p - one_hot) by "
               "contract, not d(forward)/dx (covered by test_loss)",
    "MakeLoss": "custom grad_scale gradient by contract, not "
                "d(forward)/dx (reference MakeLoss semantics)",
    "_linalg_syevd": "eigenvector gradient is ill-conditioned under FD "
                     "(sign/ordering flips at crossings)",
    "_linalg_gelqf": "LQ factor gradients are sign-ambiguous under FD",
    "RNN": "fused multi-layer kernel; 100+-element parameter vector "
           "makes FD impractical (gradients covered by test_gluon_rnn "
           "training-convergence tests)",
    "Dropout": "rng op: each FD evaluation draws a fresh mask "
               "(p=0 forward identity is asserted in the oracle)",
    "ceil": "piecewise-constant: gradient is zero a.e. and FD at a step "
            "is undefined",
    "floor": "piecewise-constant (as ceil)",
    "rint": "piecewise-constant (as ceil)",
    "round": "piecewise-constant (as ceil)",
    "trunc": "piecewise-constant (as ceil)",
    "sign": "piecewise-constant (as ceil)",
    "_shuffle": "rng op: each FD evaluation permutes differently",
    "_sample_multinomial": "rng sampler (forward distribution checked "
                           "in test_ndarray random tests)",
}

# ------------------------------------------------------------------- domains
# unary float ops needing a restricted input domain for a well-defined,
# smooth forward (name -> generator(rng) for the single input)
_DOMAIN = {
    "arccos": lambda r: (r.uniform(-0.8, 0.8, (2, 3))).astype(np.float32),
    "arcsin": lambda r: (r.uniform(-0.8, 0.8, (2, 3))).astype(np.float32),
    "arctanh": lambda r: (r.uniform(-0.8, 0.8, (2, 3))).astype(np.float32),
    "erfinv": lambda r: (r.uniform(-0.8, 0.8, (2, 3))).astype(np.float32),
    "arccosh": lambda r: (1.5 + np.abs(r.randn(2, 3))).astype(np.float32),
    "log": lambda r: _pos(r, 2, 3),
    "log2": lambda r: _pos(r, 2, 3),
    "log10": lambda r: _pos(r, 2, 3),
    "log1p": lambda r: _pos(r, 2, 3),
    "sqrt": lambda r: _pos(r, 2, 3),
    "rsqrt": lambda r: _pos(r, 2, 3),
    "cbrt": lambda r: _pos(r, 2, 3),
    "rcbrt": lambda r: _pos(r, 2, 3),
    "reciprocal": lambda r: _pos(r, 2, 3),
    "gamma": lambda r: _pos(r, 2, 3),
    "gammaln": lambda r: _pos(r, 2, 3),
    "digamma": lambda r: (1.0 + _pos(r, 2, 3)).astype(np.float32),
    # keep FD away from the |x|=1 kink / integer steps
    "abs": lambda r: (np.sign(r.randn(2, 3)) *
                      (0.3 + np.abs(r.randn(2, 3)))).astype(np.float32),
}

# ------------------------------------------------------------------- oracles
_ERF = np.vectorize(math.erf)
_GAMMA = np.vectorize(math.gamma)
_LGAMMA = np.vectorize(math.lgamma)


def _np_softmax(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


# name -> callable(*np_inputs, **kwargs) returning the expected FIRST
# output as a numpy array.  Only ops with a clean numpy equivalent.
ORACLES = {
    # elementwise unary
    "abs": np.abs, "arccos": np.arccos, "arccosh": np.arccosh,
    "arcsin": np.arcsin, "arcsinh": np.arcsinh, "arctan": np.arctan,
    "arctanh": np.arctanh, "cbrt": np.cbrt, "ceil": np.ceil,
    "cos": np.cos, "cosh": np.cosh, "degrees": np.degrees,
    "erf": _ERF, "erfc": lambda x: 1.0 - _ERF(x),
    "exp": np.exp, "expm1": np.expm1, "floor": np.floor,
    "gamma": _GAMMA, "gammaln": _LGAMMA,
    "log": np.log, "log10": np.log10, "log1p": np.log1p, "log2": np.log2,
    "logical_not": lambda x: (x == 0).astype(np.float32),
    "negative": np.negative, "radians": np.radians,
    "rcbrt": lambda x: 1.0 / np.cbrt(x),
    "reciprocal": lambda x: 1.0 / x,
    "relu": lambda x: np.maximum(x, 0),
    "rint": np.rint,
    "rsqrt": lambda x: 1.0 / np.sqrt(x),
    "sigmoid": lambda x: 1.0 / (1.0 + np.exp(-x)),
    "sign": np.sign, "sin": np.sin, "sinh": np.sinh,
    "softsign": lambda x: x / (1.0 + np.abs(x)),
    "sqrt": np.sqrt, "square": np.square, "tan": np.tan, "tanh": np.tanh,
    "trunc": np.trunc,
    "hard_sigmoid": lambda x, alpha=0.2, beta=0.5:
        np.clip(alpha * x + beta, 0, 1),
    "smooth_l1": lambda x, scalar=1.0: np.where(
        np.abs(x) < 1.0 / scalar ** 2, 0.5 * (scalar * x) ** 2,
        np.abs(x) - 0.5 / scalar ** 2),
    "_copy": lambda x: x, "BlockGrad": lambda x: x, "Flatten":
        lambda x: x.reshape(x.shape[0], -1),
    "_contrib_div_sqrt_dim": lambda x: x / np.sqrt(x.shape[-1]),
    "_contrib_gelu_erf": lambda x: 0.5 * x * (1 + _ERF(x / np.sqrt(2))),
    "zeros_like": np.zeros_like, "ones_like": np.ones_like,
    "full_like": lambda x, fill_value=0.0: np.full_like(x, fill_value),
    "shape_array": lambda x: np.array(x.shape, np.int64),
    "size_array": lambda x: np.array([x.size], np.int64),
    # binary / broadcast
    "_add": np.add, "_minus": np.subtract, "_mul": np.multiply,
    "_div": np.divide, "_power": np.power,
    "broadcast_add": np.add, "broadcast_minus": np.subtract,
    "broadcast_mul": np.multiply, "broadcast_div": np.divide,
    "broadcast_maximum": np.maximum, "broadcast_minimum": np.minimum,
    "broadcast_hypot": np.hypot, "broadcast_arctan2": np.arctan2,
    "broadcast_mod": np.mod,
    "broadcast_equal": lambda a, b: (a == b).astype(np.float32),
    "broadcast_not_equal": lambda a, b: (a != b).astype(np.float32),
    "broadcast_greater": lambda a, b: (a > b).astype(np.float32),
    "broadcast_greater_equal": lambda a, b: (a >= b).astype(np.float32),
    "broadcast_lesser": lambda a, b: (a < b).astype(np.float32),
    "broadcast_lesser_equal": lambda a, b: (a <= b).astype(np.float32),
    "broadcast_logical_and": lambda a, b:
        np.logical_and(a, b).astype(np.float32),
    "broadcast_logical_or": lambda a, b:
        np.logical_or(a, b).astype(np.float32),
    "broadcast_logical_xor": lambda a, b:
        np.logical_xor(a, b).astype(np.float32),
    # scalar ops
    "_plus_scalar": lambda x, scalar=0.0: x + scalar,
    "_minus_scalar": lambda x, scalar=0.0: x - scalar,
    "_rminus_scalar": lambda x, scalar=0.0: scalar - x,
    "_mul_scalar": lambda x, scalar=1.0: x * scalar,
    "_div_scalar": lambda x, scalar=1.0: x / scalar,
    "_rdiv_scalar": lambda x, scalar=1.0: scalar / x,
    "_mod_scalar": lambda x, scalar=1.0: np.mod(x, scalar),
    "_rmod_scalar": lambda x, scalar=1.0: np.mod(scalar, x),
    "_power_scalar": lambda x, scalar=1.0: np.power(x, scalar),
    "_rpower_scalar": lambda x, scalar=1.0: np.power(scalar, x),
    "_hypot_scalar": lambda x, scalar=0.0: np.hypot(x, scalar),
    "_maximum_scalar": lambda x, scalar=0.0: np.maximum(x, scalar),
    "_minimum_scalar": lambda x, scalar=0.0: np.minimum(x, scalar),
    "_equal_scalar": lambda x, scalar=0.0: (x == scalar).astype(np.float32),
    "_not_equal_scalar": lambda x, scalar=0.0:
        (x != scalar).astype(np.float32),
    "_greater_scalar": lambda x, scalar=0.0:
        (x > scalar).astype(np.float32),
    "_greater_equal_scalar": lambda x, scalar=0.0:
        (x >= scalar).astype(np.float32),
    "_greater_scalar_rev": lambda x, scalar=0.0:
        (scalar > x).astype(np.float32),
    "_lesser_scalar": lambda x, scalar=0.0:
        (x < scalar).astype(np.float32),
    "_lesser_equal_scalar": lambda x, scalar=0.0:
        (x <= scalar).astype(np.float32),
    # reductions
    "sum": lambda x, **k: np.sum(x, axis=k.get("axis")),
    "mean": lambda x, **k: np.mean(x, axis=k.get("axis")),
    "max": lambda x, **k: np.max(x, axis=k.get("axis")),
    "min": lambda x, **k: np.min(x, axis=k.get("axis")),
    "prod": lambda x, **k: np.prod(x, axis=k.get("axis")),
    "nansum": lambda x, **k: np.nansum(x, axis=k.get("axis")),
    "nanprod": lambda x, **k: np.nanprod(x, axis=k.get("axis")),
    "norm": lambda x, **k: np.sqrt(np.sum(np.square(x))),
    "_np_cumsum": lambda x, axis=None, dtype=None: np.cumsum(x, axis=axis),
    "cumprod": lambda x, axis=None, dtype=None: np.cumprod(x, axis=axis),
    "argmax": lambda x, axis=None, keepdims=False:
        np.argmax(x, axis=axis).astype(np.float32),
    "argmin": lambda x, axis=None, keepdims=False:
        np.argmin(x, axis=axis).astype(np.float32),
    "argmax_channel": lambda x: np.argmax(x, axis=1).astype(np.float32),
    # shape / indexing
    "transpose": lambda x, axes=(): np.transpose(
        x, axes if axes else None),
    "expand_dims": lambda x, axis=0: np.expand_dims(x, axis),
    "squeeze": lambda x, axis=None: np.squeeze(x, axis),
    "flip": lambda x, axis=0: np.flip(x, axis),
    "tile": lambda x, reps=(): np.tile(x, reps),
    "repeat": lambda x, repeats=1, axis=None: np.repeat(x, repeats, axis),
    "SwapAxis": lambda x, dim1=0, dim2=0: np.swapaxes(x, dim1, dim2),
    "Reshape": lambda x, shape=(), reverse=False: x.reshape(shape),
    "broadcast_to": lambda x, shape=(): np.broadcast_to(x, shape),
    "clip": lambda x, a_min=None, a_max=None: np.clip(x, a_min, a_max),
    "diag": lambda x, k=0, axis1=0, axis2=1: np.diag(x, k),
    "sort": lambda x, axis=-1, is_ascend=True: np.sort(x, axis),
    "argsort": lambda x, axis=-1, is_ascend=True, dtype=None:
        np.argsort(x, axis, kind="stable").astype(np.float32),
    "one_hot": lambda i, depth=0, on_value=1.0, off_value=0.0, dtype=None:
        np.where(np.eye(depth)[i.astype(np.int64)] > 0, on_value,
                 off_value).astype(np.float32),
    "where": lambda c, x, y: np.where(c != 0, x, y),
    "slice_axis": lambda x, axis=0, begin=0, end=None:
        np.take(x, np.arange(begin, end if end is not None
                             else x.shape[axis]), axis=axis),
    "space_to_depth": lambda x, block_size=1: x.reshape(
        x.shape[0], x.shape[1], x.shape[2] // block_size, block_size,
        x.shape[3] // block_size, block_size).transpose(
            0, 3, 5, 1, 2, 4).reshape(
            x.shape[0], x.shape[1] * block_size ** 2,
            x.shape[2] // block_size, x.shape[3] // block_size),
    # linear algebra
    "dot": lambda a, b, transpose_a=False, transpose_b=False: np.dot(
        a.T if transpose_a else a, b.T if transpose_b else b),
    "batch_dot": lambda a, b, transpose_a=False, transpose_b=False:
        np.matmul(np.swapaxes(a, -1, -2) if transpose_a else a,
                  np.swapaxes(b, -1, -2) if transpose_b else b),
    "FullyConnected": lambda x, w, b, num_hidden=0, no_bias=False,
        flatten=True: x.reshape(x.shape[0], -1) @ w.T + b,
    "_linalg_det": lambda a: np.linalg.det(a).astype(np.float32),
    "_linalg_inverse": np.linalg.inv,
    "_linalg_potrf": np.linalg.cholesky,
    "_linalg_sumlogdiag": lambda a: np.log(np.diagonal(
        a, axis1=-2, axis2=-1)).sum(-1).astype(np.float32),
    "_linalg_extractdiag": lambda a, offset=0: np.diagonal(
        a, offset, -2, -1),
    "_linalg_makediag": lambda a, offset=0: np.apply_along_axis(
        lambda v: np.diag(v, offset), -1, a),
    "khatri_rao": lambda a, b: np.vstack(
        [np.kron(a[:, j], b[:, j]).reshape(-1, 1)
         for j in range(a.shape[1])]).reshape(a.shape[1], -1).T,
    # softmax family
    "softmax": lambda x, axis=-1, **k: _np_softmax(x, axis),
    "softmin": lambda x, axis=-1, **k: _np_softmax(-x, axis),
    "log_softmax": lambda x, axis=-1, **k: np.log(_np_softmax(x, axis)),
    "SoftmaxActivation": lambda x, mode="instance": _np_softmax(x, -1),
    "L2Normalization": lambda x, eps=1e-10, mode="instance":
        x / np.sqrt((x.reshape(x.shape[0], -1) ** 2).sum(-1)
                    + eps).reshape(-1, *([1] * (x.ndim - 1))),
    "_contrib_gelu_tanh": lambda x: 0.5 * x * (1 + np.tanh(
        np.sqrt(2 / np.pi) * (x + 0.044715 * x ** 3))),
    # fills
    "_zeros": lambda shape=(), dtype=None, ctx=None: np.zeros(shape),
    "_ones": lambda shape=(), dtype=None, ctx=None: np.ones(shape),
    "_full": lambda shape=(), value=0.0, dtype=None, ctx=None:
        np.full(shape, value),
    "_eye": lambda N=0, M=0, k=0, dtype=None, ctx=None:
        np.eye(N, M or None, k),
    "_arange": lambda start=0, stop=None, step=1.0, repeat=1, dtype=None,
        ctx=None, infer_range=False: np.arange(start, stop, step),
    "_linspace": lambda start=0, stop=1, num=50, endpoint=True,
        dtype=None, ctx=None: np.linspace(start, stop, num, endpoint),
    "Concat": lambda a, b, dim=1, num_args=0: np.concatenate([a, b], dim),
    "stack": lambda a, b, axis=0: np.stack([a, b], axis),
    "Pad": lambda x, mode="constant", pad_width=(), constant_value=0.0:
        np.pad(x, [(pad_width[2 * i], pad_width[2 * i + 1])
                   for i in range(x.ndim)], mode="constant",
               constant_values=constant_value),
    "Cast": lambda x, dtype="float32": x.astype(dtype),
    "amp_cast": lambda x, dtype="float32": x.astype(dtype),
    "Dropout": lambda x, p=0.5, **k: x,              # spec pins p=0.0
    "take": lambda a, i, axis=0, mode="clip": np.take(
        a, i.astype(np.int64), axis=axis),
    "pick": lambda x, i, axis=-1, keepdims=False, mode="clip":
        np.take_along_axis(x, i.astype(np.int64)[..., None],
                           axis=-1).squeeze(-1),
    "gather_nd": lambda d, i: d[tuple(i.astype(np.int64))],
    "unravel_index": lambda x, shape=(): np.stack(
        np.unravel_index(x.astype(np.int64), shape)),
    "_contrib_arange_like": lambda x, start=0.0, step=1.0, repeat=1,
        axis=None: np.arange(start, start + x.size * step,
                             step).reshape(x.shape),
}


# ------------------------------------------------- r5: NN-core oracles
# Independent NumPy forward implementations of the reference semantics
# (VERDICT r4 item 6: FD checks prove gradient/forward CONSISTENCY, not
# forward correctness — a conv with flipped padding passes FD).  These
# are written from the reference op contracts (src/operator/nn/*.cc),
# not transcribed from the jnp bodies.
def _np_sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def _np_conv2d(x, w, b=None, kernel=(), stride=(), dilate=(), pad=(),
               num_filter=0, num_group=1, no_bias=False, **_):
    sh, sw = tuple(stride) or (1, 1)
    ph, pw = tuple(pad) or (0, 0)
    dh, dw = tuple(dilate) or (1, 1)
    n, c, H, W = x.shape
    o, cg, kh, kw = w.shape
    xp = np.pad(x.astype(np.float64),
                ((0, 0), (0, 0), (ph, ph), (pw, pw)))
    eh, ew = (kh - 1) * dh + 1, (kw - 1) * dw + 1
    oh = (H + 2 * ph - eh) // sh + 1
    ow = (W + 2 * pw - ew) // sw + 1
    out = np.zeros((n, o, oh, ow), np.float64)
    og = o // num_group
    for g in range(num_group):
        xs = xp[:, g * cg:(g + 1) * cg]
        ws = w[g * og:(g + 1) * og].astype(np.float64)
        for i in range(oh):
            for j in range(ow):
                patch = xs[:, :, i * sh:i * sh + eh:dh,
                           j * sw:j * sw + ew:dw]
                out[:, g * og:(g + 1) * og, i, j] = np.einsum(
                    "nchw,ochw->no", patch, ws)
    if b is not None and not no_bias:
        out = out + b.reshape(1, -1, 1, 1)
    return out


def _np_deconv2d(x, w, b=None, kernel=(), stride=(), dilate=(), pad=(),
                 adj=(), num_filter=0, num_group=1, no_bias=True,
                 target_shape=(), **_):
    sh, sw = tuple(stride) or (1, 1)
    ph, pw = tuple(pad) or (0, 0)
    ah, aw = tuple(adj) or (0, 0)
    n, ci, H, W = x.shape
    _, og, kh, kw = w.shape
    OH, OW = (H - 1) * sh + kh, (W - 1) * sw + kw
    out = np.zeros((n, og, OH, OW), np.float64)
    for i in range(H):
        for j in range(W):
            out[:, :, i * sh:i * sh + kh, j * sw:j * sw + kw] += np.einsum(
                "nc,cokl->nokl", x[:, :, i, j].astype(np.float64),
                w.astype(np.float64))
    out = out[:, :, ph:OH - ph + ah, pw:OW - pw + aw]
    if b is not None and not no_bias:
        out = out + b.reshape(1, -1, 1, 1)
    return out


def _np_pool2d(x, kernel=(), pool_type="max", stride=(), pad=(),
               global_pool=False, count_include_pad=True,
               pooling_convention="valid", **_):
    if global_pool:
        red = tuple(range(2, x.ndim))
        f = {"max": np.max, "avg": np.mean, "sum": np.sum}[pool_type]
        return f(x, axis=red, keepdims=True)
    kh, kw = kernel
    sh, sw = tuple(stride) or (1, 1)
    ph, pw = tuple(pad) or (0, 0)
    n, c, H, W = x.shape
    fill = -np.inf if pool_type == "max" else 0.0
    xp = np.pad(x.astype(np.float64),
                ((0, 0), (0, 0), (ph, ph), (pw, pw)),
                constant_values=fill)
    oh = (H + 2 * ph - kh) // sh + 1
    ow = (W + 2 * pw - kw) // sw + 1
    out = np.zeros((n, c, oh, ow), np.float64)
    for i in range(oh):
        for j in range(ow):
            win = xp[:, :, i * sh:i * sh + kh, j * sw:j * sw + kw]
            if pool_type == "max":
                out[:, :, i, j] = win.max((2, 3))
            elif pool_type == "sum":
                out[:, :, i, j] = win.sum((2, 3))
            elif count_include_pad:
                out[:, :, i, j] = win.mean((2, 3))
            else:
                iy = max(i * sh, ph), min(i * sh + kh, H + ph)
                ix = max(j * sw, pw), min(j * sw + kw, W + pw)
                cnt = (iy[1] - iy[0]) * (ix[1] - ix[0])
                out[:, :, i, j] = win.sum((2, 3)) / cnt
    return out


def _np_im2col(x, kernel=(), stride=(1, 1), dilate=(1, 1), pad=(0, 0),
               **_):
    kh, kw = kernel
    sh, sw = tuple(stride) or (1, 1)
    dh, dw = tuple(dilate) or (1, 1)
    ph, pw = tuple(pad) or (0, 0)
    n, c, H, W = x.shape
    xp = np.pad(x, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
    eh, ew = (kh - 1) * dh + 1, (kw - 1) * dw + 1
    oh = (H + 2 * ph - eh) // sh + 1
    ow = (W + 2 * pw - ew) // sw + 1
    cols = np.zeros((n, c * kh * kw, oh * ow), x.dtype)
    L = 0
    for i in range(oh):
        for j in range(ow):
            cols[:, :, L] = xp[:, :, i * sh:i * sh + eh:dh,
                               j * sw:j * sw + ew:dw].reshape(n, -1)
            L += 1
    return cols


def _np_col2im(cols, output_size=(), kernel=(), stride=(1, 1),
               dilate=(1, 1), pad=(0, 0), **_):
    H, W = output_size
    kh, kw = kernel
    sh, sw = tuple(stride) or (1, 1)
    dh, dw = tuple(dilate) or (1, 1)
    ph, pw = tuple(pad) or (0, 0)
    n, ckk, _L = cols.shape
    c = ckk // (kh * kw)
    eh, ew = (kh - 1) * dh + 1, (kw - 1) * dw + 1
    oh = (H + 2 * ph - eh) // sh + 1
    ow = (W + 2 * pw - ew) // sw + 1
    img = np.zeros((n, c, H + 2 * ph, W + 2 * pw), np.float64)
    c6 = cols.reshape(n, c, kh, kw, oh, ow)
    for i in range(oh):
        for j in range(ow):
            img[:, :, i * sh:i * sh + eh:dh,
                j * sw:j * sw + ew:dw] += c6[:, :, :, :, i, j]
    return img[:, :, ph:H + ph, pw:W + pw]


def _np_lstm(data, params, state, state_cell, state_size=0, num_layers=1,
             mode="lstm", **_):
    """Single-layer LSTM with the cudnn packed layout (all weights, then
    all biases) and i,f,g,o gate order — reference rnn-inl.h."""
    T, N, I = data.shape
    H = state_size
    o = 0
    Wx = params[o:o + 4 * H * I].reshape(4 * H, I); o += 4 * H * I
    Wh = params[o:o + 4 * H * H].reshape(4 * H, H); o += 4 * H * H
    bx = params[o:o + 4 * H]; o += 4 * H
    bh = params[o:o + 4 * H]
    h, c = state[0].astype(np.float64), state_cell[0].astype(np.float64)
    outs = []
    for t in range(T):
        g = data[t] @ Wx.T + bx + h @ Wh.T + bh
        i_g, f_g, g_g, o_g = np.split(g, 4, axis=-1)
        c = _np_sigmoid(f_g) * c + _np_sigmoid(i_g) * np.tanh(g_g)
        h = _np_sigmoid(o_g) * np.tanh(c)
        outs.append(h)
    return np.stack(outs)


def _np_bilinear_resize(x, height=1, width=1, scale_height=None,
                        scale_width=None, mode="size",
                        align_corners=True, **_):
    n, c, h, w = x.shape
    if scale_height is not None:
        height, width = int(h * scale_height), int(w * scale_width)
    ys = (np.linspace(0, h - 1, height) if align_corners and height > 1
          else (np.arange(height) + 0.5) * h / height - 0.5)
    xs = (np.linspace(0, w - 1, width) if align_corners and width > 1
          else (np.arange(width) + 0.5) * w / width - 0.5)
    ys, xs = np.clip(ys, 0, h - 1), np.clip(xs, 0, w - 1)
    y0 = np.clip(np.floor(ys).astype(int), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, w - 1)
    y1, x1 = np.minimum(y0 + 1, h - 1), np.minimum(x0 + 1, w - 1)
    wy, wx = ys - y0, xs - x0
    rows = (x[:, :, y0, :] * (1 - wy)[None, None, :, None]
            + x[:, :, y1, :] * wy[None, None, :, None])
    return (rows[:, :, :, x0] * (1 - wx) + rows[:, :, :, x1] * wx)


def _np_groupnorm(x, gamma, beta, num_groups=1, eps=1e-5, **_):
    n, c = x.shape[:2]
    xg = x.reshape((n, num_groups, c // num_groups) + x.shape[2:])
    red = tuple(range(2, xg.ndim))
    mean = xg.mean(red, keepdims=True)
    var = xg.var(red, keepdims=True)
    out = ((xg - mean) / np.sqrt(var + eps)).reshape(x.shape)
    shp = (1, -1) + (1,) * (x.ndim - 2)
    return out * gamma.reshape(shp) + beta.reshape(shp)


def _np_lrn(x, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5, **_):
    sq = np.square(x)
    half = nsize // 2
    p = np.pad(sq, [(0, 0), (half, half)] + [(0, 0)] * (x.ndim - 2))
    windows = sum(p[:, i:i + x.shape[1]] for i in range(nsize))
    return x / np.power(knorm + alpha * windows / nsize, beta)


_SCIPY = __import__("scipy.special", fromlist=["special"])

ORACLES.update({
    # activations / softmax family
    "Activation": lambda x, act_type="relu": {
        "relu": lambda v: np.maximum(v, 0),
        "sigmoid": _np_sigmoid, "tanh": np.tanh,
        "softrelu": lambda v: np.log1p(np.exp(v)),
        "softsign": lambda v: v / (1 + np.abs(v))}[act_type](x),
    "LeakyReLU": lambda x, act_type="leaky", slope=0.25, **k:
        np.where(x >= 0, x, slope * x),
    "Softmax": lambda x, label, **k: _np_softmax(x, -1),
    "MakeLoss": lambda x, **k: x,
    "softmax_cross_entropy": lambda x, label: np.array(
        -np.take_along_axis(
            np.log(_np_softmax(x, -1)),
            label.astype(np.int64)[:, None], 1).sum(), np.float32),
    # normalization (test_forward runs OUTSIDE train_mode: BatchNorm is
    # inference-mode, fix_gamma=True means gamma is forced to 1)
    "BatchNorm": lambda x, gamma, beta, mm, mv, eps=1e-3, axis=1, **k:
        (x - mm.reshape(1, -1, 1, 1)) / np.sqrt(
            mv.reshape(1, -1, 1, 1) + eps) + beta.reshape(1, -1, 1, 1),
    "LayerNorm": lambda x, gamma, beta, axis=-1, eps=1e-5, **k:
        (x - x.mean(axis, keepdims=True)) / np.sqrt(
            x.var(axis, keepdims=True) + eps) * gamma + beta,
    "InstanceNorm": lambda x, gamma, beta, eps=1e-3, **k:
        (x - x.mean((2, 3), keepdims=True)) / np.sqrt(
            x.var((2, 3), keepdims=True) + eps)
        * gamma.reshape(1, -1, 1, 1) + beta.reshape(1, -1, 1, 1),
    "GroupNorm": _np_groupnorm,
    "LRN": _np_lrn,
    # NN layers
    "Convolution": _np_conv2d,
    "Deconvolution": _np_deconv2d,
    "Pooling": _np_pool2d,
    "im2col": _np_im2col,
    "col2im": _np_col2im,
    "RNN": _np_lstm,
    "Embedding": lambda idx, w, **k: w[np.clip(
        idx.astype(np.int64), 0, w.shape[0] - 1)],
    "UpSampling": lambda x, scale=1, sample_type="nearest", **k:
        np.repeat(np.repeat(x, scale, 2), scale, 3),
    "AdaptiveAvgPooling2D": lambda x, output_size=(): x.reshape(
        x.shape[0], x.shape[1], output_size[0],
        x.shape[2] // output_size[0], output_size[-1],
        x.shape[3] // output_size[-1]).mean((3, 5)),
    "BilinearResize2D": _np_bilinear_resize,
    "Crop": lambda x, offset=(0, 0), h_w=(0, 0), center_crop=False,
        num_args=1: x[:, :, offset[0]:offset[0] + h_w[0],
                      offset[1]:offset[1] + h_w[1]],
    # sequence ops (time-major; the 2-input frontends consume the
    # lengths — use_sequence_length defaults True here)
    "SequenceLast": lambda x, lens, **k: np.stack(
        [x[int(lens[b]) - 1, b] for b in range(x.shape[1])]),
    "SequenceMask": lambda x, lens, value=0.0, **k: np.where(
        (np.arange(x.shape[0])[:, None]
         < lens.astype(np.int64)[None, :]).reshape(
            (x.shape[0], x.shape[1]) + (1,) * (x.ndim - 2)), x, value),
    "SequenceReverse": lambda x, lens, **k: np.stack(
        [np.concatenate([x[:int(lens[b]), b][::-1], x[int(lens[b]):, b]])
         for b in range(x.shape[1])], axis=1),
    "SliceChannel": lambda x, num_outputs=1, axis=1, **k:
        np.split(x, num_outputs, axis)[0],
    # shape / indexing
    "topk": lambda x, axis=-1, k=1, ret_typ="indices", is_ascend=False,
        dtype="float32": np.argsort(
            x if is_ascend else -x, axis=-1, kind="stable")
        .take(range(k), -1).astype(np.float32),
    "split_v2": lambda x, indices_or_sections=1, axis=0, squeeze_axis=False:
        np.split(x, indices_or_sections, axis)[0],
    "crop": lambda x, begin=(), end=(), step=():
        x[tuple(slice(b, e) for b, e in zip(begin, end))],
    "depth_to_space": lambda x, block_size=1: x.reshape(
        x.shape[0], block_size, block_size,
        x.shape[1] // block_size ** 2, x.shape[2], x.shape[3]).transpose(
        0, 3, 4, 1, 5, 2).reshape(
        x.shape[0], x.shape[1] // block_size ** 2,
        x.shape[2] * block_size, x.shape[3] * block_size),
    "slice_like": lambda a, b, axes=(): a[tuple(
        slice(0, b.shape[i]) if (not axes or i in tuple(axes)) else
        slice(None) for i in range(a.ndim))],
    "broadcast_like": lambda a, b, **k: np.broadcast_to(a, b.shape),
    "broadcast_axes": lambda x, axis=(), size=(): np.broadcast_to(
        x, tuple(size[list(axis).index(i)] if i in tuple(axis) else s
                 for i, s in enumerate(x.shape))),
    "scatter_nd": lambda data, idx, shape=(): (
        lambda out: (np.add.at(out, tuple(idx.astype(np.int64)), data),
                     out)[1])(np.zeros(shape, data.dtype)),
    "all_finite": lambda *data, **k: np.array(
        [float(all(np.isfinite(d).all() for d in data))], np.float32),
    "amp_multicast": lambda *data, **k: data[0],
    "round": np.round,
    "digamma": lambda x: _SCIPY.digamma(x),
    "erfinv": lambda x: _SCIPY.erfinv(x),
    # linalg (spec feeds SPD or tril matrices)
    "_linalg_gemm": lambda A, B, C, transpose_a=False, transpose_b=False,
        alpha=1.0, beta=1.0, axis=-2: alpha * (
            (A.T if transpose_a else A) @ (B.T if transpose_b else B))
        + beta * C,
    "_linalg_gemm2": lambda A, B, transpose_a=False, transpose_b=False,
        alpha=1.0, axis=-2: alpha * (
            (A.T if transpose_a else A) @ (B.T if transpose_b else B)),
    "_linalg_potri": lambda A: np.linalg.inv(np.tril(A) @ np.tril(A).T),
    "_linalg_trmm": lambda A, B, transpose=False, rightside=False,
        lower=True, alpha=1.0: alpha * (np.tril(A) @ B),
    "_linalg_trsm": lambda A, B, transpose=False, rightside=False,
        lower=True, alpha=1.0: np.linalg.solve(np.tril(A), alpha * B),
    "_linalg_syrk": lambda A, transpose=False, alpha=1.0:
        alpha * (A.T @ A if transpose else A @ A.T),
    "_linalg_slogdet": lambda A: np.linalg.slogdet(A)[0],
    # optimizer update ops (reference: optimizer_op.cc formulas; first
    # output = new weight; spec passes no kwargs so defaults apply)
    "sgd_update": lambda w, g, lr=0.01, wd=0.0, **k:
        w - lr * (g + wd * w),
    "sgd_mom_update": lambda w, g, m, lr=0.01, momentum=0.0, wd=0.0, **k:
        w + momentum * m - lr * (g + wd * w),
    "nag_mom_update": lambda w, g, m, lr=0.01, momentum=0.0, wd=0.0, **k:
        w - lr * ((g + wd * w) + momentum
                  * (momentum * m + (g + wd * w))),
    "signsgd_update": lambda w, g, lr=0.01, wd=0.0, **k:
        w - lr * np.sign(g + wd * w),
    "signum_update": lambda w, g, m, lr=0.01, momentum=0.0, wd=0.0,
        wd_lh=0.0, **k: (1 - lr * wd_lh) * w + lr * np.sign(
            momentum * m - (1 - momentum) * (g + wd * w)),
    "rmsprop_update": lambda w, g, n, lr=0.001, gamma1=0.95,
        epsilon=1e-8, wd=0.0, **k: w - lr * (g + wd * w) / np.sqrt(
            gamma1 * n + (1 - gamma1) * np.square(g + wd * w) + epsilon),
    "adam_update": lambda w, g, m, v, lr=0.001, beta1=0.9, beta2=0.999,
        epsilon=1e-8, wd=0.0, **k: w - lr * (
            beta1 * m + (1 - beta1) * (g + wd * w)) / (np.sqrt(
                beta2 * v + (1 - beta2) * np.square(g + wd * w))
                + epsilon),
    "_adamw_update": lambda w, g, m, v, rescale, lr=0.001, beta1=0.9,
        beta2=0.999, epsilon=1e-8, wd=0.0, eta=1.0, **k: w - eta * (
            lr * (beta1 * m + (1 - beta1) * g * rescale) / (np.sqrt(
                beta2 * v + (1 - beta2) * np.square(g * rescale))
                + epsilon) + wd * w),
    "mp_sgd_update": lambda w, g, w32, lr=0.01, wd=0.0, **k:
        w32 - lr * (g + wd * w32),
    "mp_sgd_mom_update": lambda w, g, m, w32, lr=0.01, momentum=0.0,
        wd=0.0, **k: w32 + momentum * m - lr * (g + wd * w32),
    "ftrl_update": lambda w, g, z, n, lr=0.1, lamda1=0.01, beta=1.0,
        wd=0.0, **k: (lambda nn, zn: np.where(
            np.abs(zn) > lamda1,
            -(zn - np.sign(zn) * lamda1)
            / ((beta + np.sqrt(nn)) / lr + wd), 0.0))(
            n + g * g, z + g - (np.sqrt(n + g * g) - np.sqrt(n)) / lr * w),
    "rmspropalex_update": lambda w, g, n, ga, d, lr=0.001, gamma1=0.95,
        gamma2=0.9, epsilon=1e-8, wd=0.0, **k: (lambda nn, gn:
            w + gamma2 * d - lr * (g + wd * w) / np.sqrt(
                np.maximum(nn - gn * gn, 0.0) + epsilon))(
            gamma1 * n + (1 - gamma1) * (g + wd * w) ** 2,
            gamma1 * ga + (1 - gamma1) * (g + wd * w)),
    "lamb_update_phase1": lambda w, g, m, v, beta1=0.9, beta2=0.999,
        epsilon=1e-6, t=1, bias_correction=True, wd=0.0, **k:
        (beta1 * m + (1 - beta1) * g) / (1 - beta1 ** t)
        / (np.sqrt((beta2 * v + (1 - beta2) * g * g)
                   / (1 - beta2 ** t)) + epsilon) + wd * w,
    "lamb_update_phase2": lambda w, g, r1, r2, lr=0.01, lower_bound=-1.0,
        upper_bound=-1.0: w - lr * np.where(
            (r1 > 0) & (r2 > 0), r1 / r2, 1.0) * g,
    "lamb_update_states": lambda w, g, m, v, beta1=0.9, beta2=0.999,
        **k: beta1 * m + (1 - beta1) * g,
    # interleaved-matmul MHA family (reference transformer.cc layout:
    # self-att qkv (L, B, H*3*D) with per-head [q|k|v]; maps (B*H, Lq, Lk))
    "_contrib_interleaved_matmul_selfatt_qk": lambda qkv, heads=1:
        (lambda q, k: np.einsum("bqd,bkd->bqk",
                                q / np.sqrt(q.shape[-1]), k))(
            *_np_split_ileaved(qkv, heads, 3)[:2]),
    "_contrib_interleaved_matmul_selfatt_valatt": lambda qkv, att,
        heads=1: _np_heads_merge(np.einsum(
            "bqk,bkd->bqd", att, _np_split_ileaved(qkv, heads, 3)[2]),
            qkv.shape[1], heads),
    "_contrib_interleaved_matmul_encdec_qk": lambda q, kv, heads=1:
        (lambda qh, kh: np.einsum("bqd,bkd->bqk",
                                  qh / np.sqrt(qh.shape[-1]), kh))(
            _np_q_heads(q, heads), _np_split_ileaved(kv, heads, 2)[0]),
    "_contrib_interleaved_matmul_encdec_valatt": lambda kv, att, heads=1:
        _np_heads_merge(np.einsum(
            "bqk,bkd->bqd", att, _np_split_ileaved(kv, heads, 2)[1]),
            kv.shape[1], heads),
    # misc contrib
    "_contrib_boolean_mask": lambda data, index, axis=0:
        data[np.asarray(index) != 0],
    "_contrib_index_copy": lambda old, idx, new:
        (lambda o: (o.__setitem__(idx.astype(np.int64), new), o)[1])(
            old.copy()),
    "_contrib_index_array": lambda data, axes=None: np.stack(
        np.meshgrid(*[np.arange(s) for s in data.shape], indexing="ij"),
        axis=-1).astype(np.int64),
    "GridGenerator": lambda theta, transform_type="affine",
        target_shape=(): (lambda h, w: np.einsum(
            "nij,jk->nik", theta.reshape(-1, 2, 3), np.stack(
                [np.tile(np.linspace(-1, 1, w), h),
                 np.repeat(np.linspace(-1, 1, h), w),
                 np.ones(h * w)])).reshape(-1, 2, h, w))(*target_shape),
    "MultiBoxPrior": lambda *a, **k: _np_multibox_prior(*a, **k),
    # flash attention vs a dense numpy oracle — the strongest check in
    # the sweep: the Pallas online-softmax kernel against materialized
    # softmax(QK^T)V with the key-padding mask
    "_contrib_flash_selfatt": lambda qkv, vlen, heads=1, **k:
        _np_dense_selfatt(qkv, heads, vlen),
    "_contrib_flash_selfatt_nomask": lambda qkv, heads=1, **k:
        _np_dense_selfatt(qkv, heads, None),
    # decode-path paged attention vs a per-sequence gather + dense
    # softmax (block-table indirection materialized in numpy)
    "_contrib_ragged_paged_attention": lambda q, kp, vp, bt, lens:
        _np_paged_attention(q, kp, vp, bt, lens),
    # int8 quantization formulas (reference quantize.cc symmetric scale)
    "_contrib_quantize": lambda x, mn, mx, out_type="int8":
        np.clip(np.round(x / (max(abs(mn[0]), abs(mx[0])) / 127.0)),
                -127, 127).astype(np.int8),
    "_contrib_quantize_v2": lambda x, **k: np.clip(
        np.round(x / (max(abs(x.min()), abs(x.max())) / 127.0)),
        -127, 127).astype(np.int8),
    "_contrib_dequantize": lambda q, mn, mx, out_type="float32":
        q.astype(np.float32) * (max(abs(mn[0]), abs(mx[0])) / 127.0),
    "BilinearSampler": lambda data, grid, **k:
        _np_bilinear_sampler(data, grid),
    "SpatialTransformer": lambda data, loc, target_shape=(),
        transform_type="affine", sampler_type="bilinear", **k:
        _np_bilinear_sampler(data, ORACLES["GridGenerator"](
            loc, target_shape=target_shape)),
    "CTCLoss": lambda data, label, *a, **k: _np_ctc(data, label),
    "ROIPooling": lambda data, rois, pooled_size=(), spatial_scale=1.0:
        _np_roipool(data, rois, pooled_size, spatial_scale),
    "ROIAlign": lambda data, rois, pooled_size=(), spatial_scale=1.0,
        sample_ratio=-1, **k: _np_roialign(
            data, rois, pooled_size, spatial_scale,
            sample_ratio if sample_ratio > 0 else 2),
    "_contrib_multi_lars": lambda lrs, wss, gss, wds, eta=0.001,
        eps=1e-8, rescale_grad=1.0: lrs * np.where(
            (np.sqrt(wss) > 0) & (np.sqrt(gss) * rescale_grad > 0),
            eta * np.sqrt(wss)
            / (np.sqrt(gss) * rescale_grad + wds * np.sqrt(wss) + eps),
            1.0),
    "_contrib_requantize": lambda q, mn, mx, **k: (lambda real:
        np.clip(np.round(real / (max(abs(real.min()), abs(real.max()))
                                 / 127.0)), -127, 127).astype(np.int8))(
        q.astype(np.float64) * (max(abs(mn[0]), abs(mx[0]))
                                / float(2 ** 31 - 1))),
    "_contrib_quantized_flatten": lambda x, mn, mx:
        x.reshape(x.shape[0], -1),
})


def _np_ctc(data, label):
    """Log-space alpha recursion (Graves 2006), blank = channel 0."""
    T, N, _C = data.shape
    x = data - data.max(-1, keepdims=True)
    logp = x - np.log(np.exp(x).sum(-1, keepdims=True))
    out = np.zeros(N, np.float32)
    for n in range(N):
        ext = [0]
        for v in label[n]:
            if v > 0:
                ext += [int(v), 0]
        S = len(ext)
        alpha = np.full(S, -1e30)
        alpha[0] = logp[0, n, 0]
        if S > 1:
            alpha[1] = logp[0, n, ext[1]]
        for t in range(1, T):
            new = np.full(S, -1e30)
            for s in range(S):
                best = alpha[s]
                if s >= 1:
                    best = np.logaddexp(best, alpha[s - 1])
                if s >= 2 and ext[s] != 0 and ext[s] != ext[s - 2]:
                    best = np.logaddexp(best, alpha[s - 2])
                new[s] = best + logp[t, n, ext[s]]
            alpha = new
        tot = np.logaddexp(alpha[-1], alpha[-2]) if S > 1 else alpha[-1]
        out[n] = -tot
    return out


def _np_roipool(data, rois, pooled_size, spatial_scale):
    """Reference roi_pooling.cc semantics: integer-quantized corners,
    floor/ceil bin boundaries, max over the exact pixels."""
    ph, pw = pooled_size
    _n, c, h, w = data.shape
    out = np.zeros((rois.shape[0], c, ph, pw), np.float32)
    for r, roi in enumerate(rois):
        b = int(roi[0])
        x1, y1 = round(roi[1] * spatial_scale), round(roi[2] * spatial_scale)
        x2, y2 = round(roi[3] * spatial_scale), round(roi[4] * spatial_scale)
        bh = max(y2 - y1 + 1, 1) / ph
        bw = max(x2 - x1 + 1, 1) / pw
        for i in range(ph):
            hs = min(max(int(np.floor(i * bh)) + int(y1), 0), h)
            he = min(max(int(np.ceil((i + 1) * bh)) + int(y1), 0), h)
            for j in range(pw):
                ws = min(max(int(np.floor(j * bw)) + int(x1), 0), w)
                we = min(max(int(np.ceil((j + 1) * bw)) + int(x1), 0), w)
                if he > hs and we > ws:
                    out[r, :, i, j] = data[b, :, hs:he, ws:we].max((1, 2))
    return out


def _np_roialign(data, rois, pooled_size, spatial_scale, s):
    """Bilinear sample grid of (ph*s, pw*s), mean per bin (reference:
    contrib/roi_align.cc, edge-clamped sampling)."""
    ph, pw = pooled_size
    _n, c, h, w = data.shape
    out = np.zeros((rois.shape[0], c, ph, pw), np.float64)
    for r, roi in enumerate(rois):
        b = int(roi[0])
        x1, y1 = roi[1] * spatial_scale, roi[2] * spatial_scale
        x2, y2 = roi[3] * spatial_scale, roi[4] * spatial_scale
        rw, rh = max(x2 - x1, 1.0), max(y2 - y1, 1.0)
        ys = y1 + rh * (np.arange(ph * s) + 0.5) / (ph * s)
        xs = x1 + rw * (np.arange(pw * s) + 0.5) / (pw * s)
        y0 = np.clip(np.floor(ys).astype(int), 0, h - 1)
        x0 = np.clip(np.floor(xs).astype(int), 0, w - 1)
        y1i, x1i = np.clip(y0 + 1, 0, h - 1), np.clip(x0 + 1, 0, w - 1)
        wy, wx = ys - y0, xs - x0
        img = data[b].astype(np.float64)
        v = (img[:, y0][:, :, x0] * ((1 - wy)[:, None] * (1 - wx)[None, :])
             + img[:, y0][:, :, x1i] * ((1 - wy)[:, None] * wx[None, :])
             + img[:, y1i][:, :, x0] * (wy[:, None] * (1 - wx)[None, :])
             + img[:, y1i][:, :, x1i] * (wy[:, None] * wx[None, :]))
        out[r] = v.reshape(c, ph, s, pw, s).mean((2, 4))
    return out


def _np_dense_selfatt(qkv, heads, vlen):
    L, B, H3D = qkv.shape
    D = H3D // (heads * 3)
    x = qkv.reshape(L, B, heads, 3, D)
    q, k, v = (x[:, :, :, i, :].transpose(1, 2, 0, 3)
               .reshape(B * heads, L, D) for i in range(3))
    s = np.einsum("bqd,bkd->bqk", q, k) / np.sqrt(D)
    if vlen is not None:
        lens = np.repeat(vlen.astype(np.int64), heads)
        mask = np.arange(L)[None, None, :] >= lens[:, None, None]
        s = np.where(mask, -np.inf, s)
    s = s - s.max(-1, keepdims=True)
    p = np.exp(s)
    p = p / p.sum(-1, keepdims=True)
    out = np.einsum("bqk,bkd->bqd", p, v)
    return out.reshape(B, heads, L, D).transpose(2, 0, 1, 3).reshape(
        L, B, heads * D)


def _np_paged_attention(q, k_pages, v_pages, block_tables, lens):
    """Gather each sequence's pages through its block table, then dense
    masked softmax attention (the ragged-paged-attention contract:
    context_lens == 0 slots yield zeros)."""
    B, H, D = q.shape
    bt = block_tables.astype(np.int64)
    out = np.zeros_like(q)
    for b in range(B):
        L = int(lens[b])
        if L == 0:
            continue
        k = k_pages[bt[b]].reshape(-1, H, D)[:L]
        v = v_pages[bt[b]].reshape(-1, H, D)[:L]
        s = np.einsum("hd,thd->ht", q[b], k) / np.sqrt(D)
        p = np.exp(s - s.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        out[b] = np.einsum("ht,thd->hd", p, v)
    return out


def _np_bilinear_sampler(data, grid):
    """grid in [-1,1], (B, 2, Ho, Wo) [x; y] -> gather-lerp from
    (B, C, H, W) with edge clamp (reference bilinear_sampler.cc)."""
    n, c, h, w = data.shape
    gx = (grid[:, 0] + 1) * (w - 1) / 2.0
    gy = (grid[:, 1] + 1) * (h - 1) / 2.0
    x0 = np.floor(gx).astype(int)
    y0 = np.floor(gy).astype(int)
    wx, wy = gx - x0, gy - y0
    out = np.zeros((n, c) + gx.shape[1:], np.float64)
    for (dy, dx, wgt) in ((0, 0, (1 - wx) * (1 - wy)),
                          (0, 1, wx * (1 - wy)),
                          (1, 0, (1 - wx) * wy), (1, 1, wx * wy)):
        yy = np.clip(y0 + dy, 0, h - 1)
        xx = np.clip(x0 + dx, 0, w - 1)
        for b in range(n):
            out[b] += data[b][:, yy[b], xx[b]] * wgt[b][None]
    return out


def _np_split_ileaved(x, heads, n):
    """(L, B, H*n*D) -> n arrays of (B*H, L, D) (transformer.cc
    interleaved layout)."""
    L, B, HnD = x.shape
    D = HnD // (heads * n)
    parts = x.reshape(L, B, heads, n, D)
    return [parts[:, :, :, i, :].transpose(1, 2, 0, 3)
            .reshape(B * heads, L, D) for i in range(n)]


def _np_q_heads(q, heads):
    Lq, B, HD = q.shape
    D = HD // heads
    return q.reshape(Lq, B, heads, D).transpose(1, 2, 0, 3).reshape(
        B * heads, Lq, D)


def _np_heads_merge(out, B, heads):
    BH, Lq, D = out.shape
    return out.reshape(B, heads, Lq, D).transpose(2, 0, 1, 3).reshape(
        Lq, B, heads * D)


def _np_multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                       steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """Reference multibox_prior.cc enumeration: per cell, one box per
    size plus one per extra ratio at sizes[0]; w carries the in_h/in_w
    aspect factor so ratio-1 boxes are square in image space."""
    _b, _c, H, W = data.shape
    out = []
    for i in range(H):
        cy = (i + offsets[1]) / H
        for j in range(W):
            cx = (j + offsets[0]) / W
            for s in sizes:
                w = s * H / W / 2
                h = s / 2
                out.append([cx - w, cy - h, cx + w, cy + h])
            for r in ratios[1:]:
                w = sizes[0] * np.sqrt(r) * H / W / 2
                h = sizes[0] / np.sqrt(r) / 2
                out.append([cx - w, cy - h, cx + w, cy + h])
    arr = np.array(out, np.float32)
    if clip:
        arr = np.clip(arr, 0.0, 1.0)
    return arr[None]


# -------------------------------------------------------------------- specs
# Per-op canonical inputs.  An entry is dict(inputs=callable(rng) ->
# [np arrays], kwargs={}, wrt=[indices FD-checked]); ops absent from
# SPECS get arity-default float inputs (with _DOMAIN overrides).
def _i8(rng, *shape):
    return np.clip(rng.randn(*shape) * 50, -127, 127).astype(np.int8)


_MINMAX = lambda: [np.array([-1.0], np.float32), np.array([1.0], np.float32)]

SPECS = {
    # ---------------- NN layers
    "Activation": dict(inputs=lambda r: [_f32(r, 2, 3)]),
    "AdaptiveAvgPooling2D": dict(inputs=lambda r: [_f32(r, 1, 2, 6, 6)],
                                 kwargs=dict(output_size=(2, 2))),
    "BatchNorm": dict(
        inputs=lambda r: [_f32(r, 2, 3, 4, 4), _pos(r, 3), _f32(r, 3),
                          _f32(r, 3), _pos(r, 3)],
        wrt=[0, 2]),   # batch stats: moving_* unused in train fwd
    "BilinearResize2D": dict(inputs=lambda r: [_f32(r, 1, 2, 4, 4)],
                             kwargs=dict(height=6, width=6)),
    "BilinearSampler": dict(
        inputs=lambda r: [_f32(r, 1, 2, 4, 4),
                          np.clip(r.randn(1, 2, 3, 3), -0.9,
                                  0.9).astype(np.float32)]),
    "CTCLoss": dict(
        inputs=lambda r: [_f32(r, 4, 2, 5),
                          np.array([[1, 2], [2, 1]], np.float32)],
        wrt=[0]),
    "Concat": dict(inputs=lambda r: [_f32(r, 2, 3), _f32(r, 2, 3)],
                   kwargs=dict(dim=1, num_args=2)),
    "Convolution": dict(
        inputs=lambda r: [_f32(r, 1, 2, 5, 5), _f32(r, 3, 2, 3, 3),
                          _f32(r, 3)],
        kwargs=dict(kernel=(3, 3), num_filter=3)),
    "Correlation": dict(
        inputs=lambda r: [_f32(r, 1, 2, 4, 4), _f32(r, 1, 2, 4, 4)]),
    "Crop": dict(inputs=lambda r: [_f32(r, 1, 2, 6, 6)],
                 kwargs=dict(h_w=(4, 4), num_args=1)),
    "Deconvolution": dict(
        inputs=lambda r: [_f32(r, 1, 3, 4, 4), _f32(r, 3, 2, 3, 3)],
        kwargs=dict(kernel=(3, 3), num_filter=2)),
    "Dropout": dict(inputs=lambda r: [_f32(r, 2, 3)],
                    kwargs=dict(p=0.0)),
    "Embedding": dict(
        inputs=lambda r: [_idx(r, 5, 2, 3), _f32(r, 5, 4)],
        kwargs=dict(input_dim=5, output_dim=4), wrt=[1]),
    "FullyConnected": dict(
        inputs=lambda r: [_f32(r, 2, 3), _f32(r, 4, 3), _f32(r, 4)],
        kwargs=dict(num_hidden=4)),
    "GridGenerator": dict(inputs=lambda r: [_f32(r, 1, 6)],
                          kwargs=dict(target_shape=(3, 3))),
    "GroupNorm": dict(
        inputs=lambda r: [_f32(r, 2, 4, 3, 3), _pos(r, 4), _f32(r, 4)],
        kwargs=dict(num_groups=2)),
    "InstanceNorm": dict(
        inputs=lambda r: [_f32(r, 2, 3, 4, 4), _pos(r, 3), _f32(r, 3)]),
    "L2Normalization": dict(inputs=lambda r: [_f32(r, 2, 3, 4)]),
    "LRN": dict(inputs=lambda r: [_f32(r, 1, 3, 4, 4)],
                kwargs=dict(nsize=3)),
    "LayerNorm": dict(
        inputs=lambda r: [_f32(r, 2, 3, 4), _pos(r, 4), _f32(r, 4)]),
    "LeakyReLU": dict(inputs=lambda r: [
        (np.sign(r.randn(2, 3)) * (0.3 + np.abs(r.randn(2, 3))))
        .astype(np.float32)]),
    "Pad": dict(inputs=lambda r: [_f32(r, 1, 2, 3, 3)],
                kwargs=dict(mode="constant",
                            pad_width=(0, 0, 0, 0, 1, 1, 1, 1))),
    "Pooling": dict(inputs=lambda r: [_f32(r, 1, 2, 4, 4)],
                    kwargs=dict(kernel=(2, 2), pool_type="avg")),
    "RNN": dict(
        inputs=lambda r: [_f32(r, 3, 2, 4), _f32(r, 108) * 0.1,
                          _f32(r, 1, 2, 3), _f32(r, 1, 2, 3)],
        kwargs=dict(state_size=3, num_layers=1, mode="lstm")),
    "ROIAlign": dict(
        inputs=lambda r: [_f32(r, 1, 2, 6, 6),
                          np.array([[0, 0.5, 0.5, 3.5, 3.5],
                                    [0, 1.0, 1.0, 4.0, 4.0]],
                                   np.float32)],
        kwargs=dict(pooled_size=(2, 2)), wrt=[0]),
    "ROIPooling": dict(
        inputs=lambda r: [_f32(r, 1, 2, 6, 6),
                          np.array([[0, 0, 0, 3, 3]], np.float32)],
        kwargs=dict(pooled_size=(2, 2)), wrt=[0]),
    "Reshape": dict(inputs=lambda r: [_f32(r, 2, 3)],
                    kwargs=dict(shape=(3, 2))),
    "SequenceLast": dict(
        inputs=lambda r: [_f32(r, 3, 2, 4),
                          np.array([1.5, 2.5], np.float32)], wrt=[0]),
    "SequenceMask": dict(
        inputs=lambda r: [_f32(r, 3, 2, 4),
                          np.array([1.5, 2.5], np.float32)], wrt=[0]),
    "SequenceReverse": dict(
        inputs=lambda r: [_f32(r, 3, 2, 4),
                          np.array([1.5, 2.5], np.float32)], wrt=[0]),
    "SliceChannel": dict(inputs=lambda r: [_f32(r, 2, 4, 3)],
                         kwargs=dict(num_outputs=2, axis=1)),
    "Softmax": dict(
        inputs=lambda r: [_f32(r, 4, 5), _idx(r, 5, 4)], wrt=[0]),
    "SoftmaxActivation": dict(inputs=lambda r: [_f32(r, 2, 3)]),
    "SpatialTransformer": dict(
        inputs=lambda r: [_f32(r, 1, 2, 5, 5),
                          np.array([[1.0, 0.1, 0.0, -0.1, 1.0, 0.0]],
                                   np.float32)],
        kwargs=dict(target_shape=(4, 4))),
    "SwapAxis": dict(inputs=lambda r: [_f32(r, 2, 3)],
                     kwargs=dict(dim1=0, dim2=1)),
    "UpSampling": dict(inputs=lambda r: [_f32(r, 1, 2, 3, 3)],
                       kwargs=dict(scale=2, sample_type="nearest",
                                   num_args=1)),
    # ---------------- detection (forward-only; diff=False)
    "MultiBoxPrior": dict(inputs=lambda r: [_f32(r, 1, 3, 4, 4)],
                          kwargs=dict(sizes=(0.5,), ratios=(1.0, 2.0))),
    "MultiBoxDetection": dict(
        inputs=lambda r: [np.abs(r.rand(1, 2, 4)).astype(np.float32),
                          _f32(r, 1, 16),
                          np.abs(r.rand(1, 4, 4)).astype(np.float32)]),
    "MultiBoxTarget": dict(
        inputs=lambda r: [np.abs(r.rand(1, 4, 4)).astype(np.float32),
                          np.array([[[1, 0.1, 0.1, 0.4, 0.4, 0]]],
                                   np.float32),
                          np.abs(r.rand(1, 2, 4)).astype(np.float32)]),
    # ---------------- contrib
    "_contrib_boolean_mask": dict(
        inputs=lambda r: [_f32(r, 4, 3),
                          np.array([1, 0, 1, 1], np.float32)]),
    "_contrib_index_array": dict(inputs=lambda r: [_f32(r, 2, 3)]),
    "_contrib_index_copy": dict(
        inputs=lambda r: [_f32(r, 4, 3), np.array([1.5, 2.5], np.float32),
                          _f32(r, 2, 3)], wrt=[0, 2]),
    "_contrib_flash_selfatt": dict(
        inputs=lambda r: [_f32(r, 4, 2, 12),
                          np.array([3.5, 4.0], np.float32)],
        kwargs=dict(heads=2), wrt=[0], rtol=3e-2, atol=3e-3),
    # q (B,H,D); K/V page pools (pages, page_size, H, D); block tables
    # (B, pages_per_seq) and context lens as x.5 floats (cast to int32
    # inside); forward-only (decode-path op, differentiable=False)
    "_contrib_ragged_paged_attention": dict(
        inputs=lambda r: [_f32(r, 2, 2, 4), _f32(r, 5, 2, 2, 4),
                          _f32(r, 5, 2, 2, 4), _idx(r, 5, 2, 3),
                          np.array([4.5, 1.5], np.float32)]),
    "_contrib_flash_selfatt_nomask": dict(
        inputs=lambda r: [_f32(r, 4, 2, 12)], kwargs=dict(heads=2),
        rtol=3e-2, atol=3e-3),
    "_contrib_interleaved_matmul_selfatt_qk": dict(
        inputs=lambda r: [_f32(r, 4, 2, 12)], kwargs=dict(heads=2)),
    "_contrib_interleaved_matmul_selfatt_valatt": dict(
        inputs=lambda r: [_f32(r, 4, 2, 12), _pos(r, 4, 4, 4)],
        kwargs=dict(heads=2)),
    "_contrib_interleaved_matmul_encdec_qk": dict(
        inputs=lambda r: [_f32(r, 3, 2, 8), _f32(r, 4, 2, 16)],
        kwargs=dict(heads=2)),
    "_contrib_interleaved_matmul_encdec_valatt": dict(
        inputs=lambda r: [_f32(r, 4, 2, 16), _pos(r, 4, 3, 4)],
        kwargs=dict(heads=2)),
    "_contrib_moe_ffn": dict(
        inputs=lambda r: [_f32(r, 4, 3), _f32(r, 3, 2), _f32(r, 2, 3, 5),
                          _f32(r, 2, 5), _f32(r, 2, 5, 3), _f32(r, 2, 3)],
        rtol=3e-2, atol=3e-3),
    "_contrib_moe_top1_dispatch": dict(inputs=lambda r: [_f32(r, 4, 2)],
                                       kwargs=dict(capacity=2)),
    "_contrib_multi_lars": dict(
        inputs=lambda r: [_pos(r, 3), _pos(r, 3), _pos(r, 3),
                          _pos(r, 3)]),
    "_contrib_arange_like": dict(inputs=lambda r: [_f32(r, 2, 3)]),
    # ---------------- quantization (int8; forward-only, diff=False)
    "_contrib_quantize": dict(
        inputs=lambda r: [_f32(r, 2, 3)] + _MINMAX()),
    "_contrib_quantize_v2": dict(inputs=lambda r: [_f32(r, 2, 3)]),
    "_contrib_dequantize": dict(
        inputs=lambda r: [_i8(r, 2, 3)] + _MINMAX()),
    "_contrib_requantize": dict(
        inputs=lambda r: [(r.randn(2, 3) * 1000).astype(np.int32)]
        + _MINMAX()),
    "_contrib_quantized_flatten": dict(
        inputs=lambda r: [_i8(r, 1, 2, 3)] + _MINMAX()),
    "_contrib_quantized_act": dict(
        inputs=lambda r: [_i8(r, 2, 3)] + _MINMAX()),
    "_contrib_quantized_pooling": dict(
        inputs=lambda r: [_i8(r, 1, 2, 4, 4)] + _MINMAX(),
        kwargs=dict(kernel=(2, 2))),
    "_contrib_quantized_conv": dict(
        inputs=lambda r: [_i8(r, 1, 2, 4, 4), _i8(r, 3, 2, 3, 3),
                          (r.randn(3) * 10).astype(np.int32)]
        + _MINMAX() * 3,
        kwargs=dict(kernel=(3, 3), num_filter=3)),
    "_contrib_quantized_fully_connected": dict(
        inputs=lambda r: [_i8(r, 2, 6), _i8(r, 4, 6),
                          (r.randn(4) * 10).astype(np.int32)]
        + _MINMAX() * 3,
        kwargs=dict(num_hidden=4)),
    # ---------------- linalg
    "_linalg_det": dict(inputs=lambda r: [_spd(r, 3)]),
    "_linalg_slogdet": dict(inputs=lambda r: [_spd(r, 3)]),
    "_linalg_inverse": dict(inputs=lambda r: [_spd(r, 3)]),
    "_linalg_potrf": dict(inputs=lambda r: [_spd(r, 3)]),
    "_linalg_potri": dict(inputs=lambda r: [_spd(r, 3)]),
    "_linalg_sumlogdiag": dict(inputs=lambda r: [_spd(r, 3)]),
    "_linalg_syevd": dict(inputs=lambda r: [_spd(r, 3)]),
    "_linalg_gelqf": dict(inputs=lambda r: [_f32(r, 2, 3)]),
    "_linalg_syrk": dict(inputs=lambda r: [_f32(r, 2, 3)]),
    "_linalg_extractdiag": dict(inputs=lambda r: [_f32(r, 3, 3)]),
    "_linalg_makediag": dict(inputs=lambda r: [_f32(r, 3)]),
    "_linalg_gemm": dict(
        inputs=lambda r: [_f32(r, 2, 3), _f32(r, 3, 4), _f32(r, 2, 4)]),
    "_linalg_gemm2": dict(inputs=lambda r: [_f32(r, 2, 3), _f32(r, 3, 4)]),
    "_linalg_trmm": dict(
        inputs=lambda r: [np.tril(_spd(r, 3)), _f32(r, 3, 3)]),
    "_linalg_trsm": dict(
        inputs=lambda r: [np.tril(_spd(r, 3)), _f32(r, 3, 3)]),
    # ---------------- optimizer update ops (first output = new weight)
    "sgd_update": dict(inputs=lambda r: [_f32(r, 4), _f32(r, 4)]),
    "signsgd_update": dict(inputs=lambda r: [_f32(r, 4), _f32(r, 4)],
                           grad=False,
                           grad_reason="sign() of grad: piecewise-const"),
    "sgd_mom_update": dict(
        inputs=lambda r: [_f32(r, 4), _f32(r, 4), _f32(r, 4)]),
    "mp_sgd_update": dict(
        inputs=lambda r: [_f32(r, 4), _f32(r, 4), _f32(r, 4)]),
    "mp_sgd_mom_update": dict(
        inputs=lambda r: [_f32(r, 4), _f32(r, 4), _f32(r, 4),
                          _f32(r, 4)]),
    "nag_mom_update": dict(
        inputs=lambda r: [_f32(r, 4), _f32(r, 4), _f32(r, 4)]),
    "signum_update": dict(
        inputs=lambda r: [_f32(r, 4), _f32(r, 4), _f32(r, 4)],
        grad=False, grad_reason="sign() of momentum: piecewise-const"),
    "adam_update": dict(
        inputs=lambda r: [_f32(r, 4), _f32(r, 4), _f32(r, 4), _pos(r, 4)]),
    "_adamw_update": dict(
        inputs=lambda r: [_f32(r, 4), _f32(r, 4), _f32(r, 4), _pos(r, 4),
                          np.array([1.0], np.float32)]),
    "ftrl_update": dict(
        inputs=lambda r: [_f32(r, 4), _f32(r, 4), _f32(r, 4), _pos(r, 4)]),
    "rmsprop_update": dict(
        inputs=lambda r: [_f32(r, 4), _f32(r, 4), _pos(r, 4)]),
    "rmspropalex_update": dict(
        # consistent running stats: n >= g_acc^2 (true for states evolved
        # from zero; keeps the Graves-RMSProp radicand positive so the
        # FD check probes the smooth region)
        inputs=lambda r: (lambda ga: [_f32(r, 4), _f32(r, 4),
                                      ga ** 2 + _pos(r, 4), ga,
                                      _f32(r, 4)])(_f32(r, 4))),
    "lamb_update_phase1": dict(
        inputs=lambda r: [_f32(r, 4), _f32(r, 4), _f32(r, 4), _pos(r, 4)]),
    "lamb_update_phase2": dict(
        inputs=lambda r: [_f32(r, 4), _f32(r, 4),
                          np.array([1.3], np.float32),
                          np.array([0.7], np.float32)]),
    "lamb_update_states": dict(
        inputs=lambda r: [_f32(r, 4), _f32(r, 4), _f32(r, 4), _pos(r, 4)]),
    # ---------------- indexing / misc
    "dot": dict(inputs=lambda r: [_f32(r, 2, 3), _f32(r, 3, 4)]),
    "batch_dot": dict(inputs=lambda r: [_f32(r, 2, 2, 3),
                                        _f32(r, 2, 3, 4)]),
    "_power": dict(inputs=lambda r: [_pos(r, 2, 3), _f32(r, 2, 3)]),
    "_rmod_scalar": dict(
        inputs=lambda r: [(1.2 + np.abs(r.randn(2, 3)) % 1.5)
                          .astype(np.float32)]),
    "broadcast_mod": dict(
        inputs=lambda r: [_f32(r, 2, 3) * 2.0,
                          (1.5 + np.abs(r.randn(1, 3)) % 1.4)
                          .astype(np.float32)]),
    "take": dict(inputs=lambda r: [_f32(r, 4, 3), _idx(r, 4, 5)],
                 wrt=[0]),
    "pick": dict(inputs=lambda r: [_f32(r, 3, 4), _idx(r, 4, 3)],
                 wrt=[0]),
    "gather_nd": dict(
        inputs=lambda r: [_f32(r, 3, 4),
                          np.array([[0.5, 1.5], [1.5, 2.5]], np.float32)],
        wrt=[0]),
    "scatter_nd": dict(
        inputs=lambda r: [_f32(r, 2, 3),
                          np.array([[0.5, 1.5]], np.float32)],
        kwargs=dict(shape=(2, 3)), wrt=[0]),
    "_contrib_index_array_2": None,      # placeholder never hit
    "one_hot": dict(inputs=lambda r: [_idx(r, 4, 3)],
                    kwargs=dict(depth=4)),
    "where": dict(
        inputs=lambda r: [(r.rand(2, 3) > 0.5).astype(np.float32),
                          _f32(r, 2, 3), _f32(r, 2, 3)], wrt=[1, 2]),
    "softmax_cross_entropy": dict(
        inputs=lambda r: [_f32(r, 3, 4), _idx(r, 4, 3)], wrt=[0]),
    "broadcast_like": dict(inputs=lambda r: [_f32(r, 1, 3), _f32(r, 2, 3)],
                           wrt=[0]),
    "slice_like": dict(inputs=lambda r: [_f32(r, 4, 5), _f32(r, 2, 3)],
                       wrt=[0]),
    "broadcast_axes": dict(inputs=lambda r: [_f32(r, 1, 3)],
                           kwargs=dict(axis=(0,), size=(4,))),
    "broadcast_to": dict(inputs=lambda r: [_f32(r, 1, 3)],
                         kwargs=dict(shape=(2, 3))),
    "crop": dict(inputs=lambda r: [_f32(r, 4, 5)],
                 kwargs=dict(begin=(1, 1), end=(3, 4))),
    "clip": dict(inputs=lambda r: [_f32(r, 2, 3)],
                 kwargs=dict(a_min=-0.4, a_max=0.4)),
    "depth_to_space": dict(inputs=lambda r: [_f32(r, 1, 4, 2, 2)],
                           kwargs=dict(block_size=2)),
    "space_to_depth": dict(inputs=lambda r: [_f32(r, 1, 1, 4, 4)],
                           kwargs=dict(block_size=2)),
    "im2col": dict(inputs=lambda r: [_f32(r, 1, 2, 4, 4)],
                   kwargs=dict(kernel=(2, 2))),
    "col2im": dict(inputs=lambda r: [_f32(r, 1, 8, 4)],
                   kwargs=dict(output_size=(3, 3), kernel=(2, 2))),
    "unravel_index": dict(
        inputs=lambda r: [np.array([1, 3, 5], np.float32)],
        kwargs=dict(shape=(2, 3))),
    "khatri_rao": dict(inputs=lambda r: [_f32(r, 2, 3), _f32(r, 4, 3)]),
    "stack": dict(inputs=lambda r: [_f32(r, 2, 3), _f32(r, 2, 3)]),
    "all_finite": dict(inputs=lambda r: [_f32(r, 2, 3), _f32(r, 3)]),
    "amp_multicast": dict(inputs=lambda r: [_f32(r, 2, 3), _f32(r, 3)],
                          kwargs=dict(num_outputs=2)),
    "topk": dict(inputs=lambda r: [_f32(r, 3, 5)], kwargs=dict(k=2)),
    "split_v2": dict(inputs=lambda r: [_f32(r, 4, 3)],
                     kwargs=dict(indices_or_sections=2)),
    "diag": dict(inputs=lambda r: [_f32(r, 3, 3)]),
    "tile": dict(inputs=lambda r: [_f32(r, 2, 3)], kwargs=dict(reps=(2, 1))),
    "repeat": dict(inputs=lambda r: [_f32(r, 2, 3)],
                   kwargs=dict(repeats=2, axis=1)),
    "slice_axis": dict(inputs=lambda r: [_f32(r, 4, 5)],
                       kwargs=dict(axis=1, begin=1, end=4)),
    "norm": dict(inputs=lambda r: [_f32(r, 2, 3)]),
    "squeeze": dict(inputs=lambda r: [_f32(r, 2, 1, 3)]),
    "flip": dict(inputs=lambda r: [_f32(r, 2, 3)], kwargs=dict(axis=1)),
    "transpose": dict(inputs=lambda r: [_f32(r, 2, 3)]),
    "expand_dims": dict(inputs=lambda r: [_f32(r, 2, 3)],
                        kwargs=dict(axis=1)),
    "sort": dict(inputs=lambda r: [_f32(r, 2, 5)]),
    "argsort": dict(inputs=lambda r: [_f32(r, 2, 5)]),
    "smooth_l1": dict(inputs=lambda r: [
        (np.sign(r.randn(2, 3)) * (0.3 + np.abs(r.randn(2, 3)) % 0.5))
        .astype(np.float32)]),
    "_sample_multinomial": dict(
        inputs=lambda r: [np.abs(r.rand(2, 4)).astype(np.float32) + 0.1]),
    "sample_normal": dict(
        inputs=lambda r: [_f32(r, 3), _pos(r, 3)]),
    "sample_uniform": dict(
        inputs=lambda r: [_f32(r, 3), _f32(r, 3) ** 2 + 1.0]),
    "_shuffle": dict(inputs=lambda r: [_f32(r, 6)]),
    "_sample_unique_zipfian": dict(inputs=lambda r: [],
                                   kwargs=dict(range_max=20, shape=(2, 5))),
    # fills: no inputs, kwargs drive
    "_zeros": dict(inputs=lambda r: [], kwargs=dict(shape=(2, 3))),
    "_ones": dict(inputs=lambda r: [], kwargs=dict(shape=(2, 3))),
    "_full": dict(inputs=lambda r: [], kwargs=dict(shape=(2, 2),
                                                   value=1.5)),
    "_eye": dict(inputs=lambda r: [], kwargs=dict(N=3)),
    "_arange": dict(inputs=lambda r: [], kwargs=dict(start=0, stop=5)),
    "_linspace": dict(inputs=lambda r: [], kwargs=dict(num=7)),
    "_random_exponential": dict(inputs=lambda r: [],
                                kwargs=dict(shape=(2, 3))),
    "_random_gamma": dict(inputs=lambda r: [], kwargs=dict(shape=(2, 3))),
    "_random_negative_binomial": dict(inputs=lambda r: [],
                                      kwargs=dict(k=3, p=0.5,
                                                  shape=(2, 3))),
    "_random_normal": dict(inputs=lambda r: [], kwargs=dict(shape=(2, 3))),
    "_random_poisson": dict(inputs=lambda r: [], kwargs=dict(shape=(2, 3))),
    "_random_randint": dict(inputs=lambda r: [],
                            kwargs=dict(low=0, high=10, shape=(2, 3))),
    "_random_uniform": dict(inputs=lambda r: [], kwargs=dict(shape=(2, 3))),
}


def _default_inputs(name, od, rng):
    if name in _DOMAIN:
        return [_DOMAIN[name](rng)]
    ni = od.num_inputs
    if ni is None:                      # variadic without a spec: 2 inputs
        return [_f32(rng, 2, 3), _f32(rng, 2, 3)]
    if callable(ni):
        raise AssertionError(
            f"op {name} has callable num_inputs and no SPECS entry — "
            f"add one")
    return [_f32(rng, 2, 3) for _ in range(ni)]


def _get_spec(name, od):
    spec = SPECS.get(name)
    rng = _rng(name)
    if spec is None:
        return _default_inputs(name, od, rng), {}, None, None, 1e-2, 1e-3
    return (spec["inputs"](rng), dict(spec.get("kwargs", {})),
            spec.get("wrt"), spec.get("grad_reason"),
            spec.get("rtol", 1e-2), spec.get("atol", 1e-3))


def _to_nd(x):
    return nd.array(x, dtype=str(x.dtype))


def _first(outs):
    return outs[0] if isinstance(outs, (list, tuple)) else outs


def _run(name, np_inputs, kwargs):
    frontend = getattr(opmod, name)
    return frontend(*[_to_nd(x) for x in np_inputs], **kwargs)


# --------------------------------------------------------------------- tests
@pytest.mark.parametrize("name", CANONICAL)
def test_forward(name):
    if name in FWD_SKIP:
        pytest.skip(FWD_SKIP[name])
    od = OP_REGISTRY[name]
    np_inputs, kwargs, _wrt, _gr, rtol, atol = _get_spec(name, od)
    outs = _run(name, np_inputs, kwargs)
    for o in (outs if isinstance(outs, (list, tuple)) else [outs]):
        a = o.asnumpy()
        assert a.size > 0 or name in ("_contrib_boolean_mask",), name
        if np.issubdtype(a.dtype, np.floating):
            assert np.isfinite(a).all(), f"{name}: non-finite output"
    oracle = ORACLES.get(name)
    if oracle is not None:
        got = _first(outs).asnumpy()
        want = np.asarray(oracle(*np_inputs, **kwargs))
        assert got.shape == tuple(want.shape), \
            f"{name}: shape {got.shape} vs oracle {want.shape}"
        np.testing.assert_allclose(got.astype(np.float64),
                                   want.astype(np.float64),
                                   rtol=1e-4, atol=1e-5, err_msg=name)


# FD gradient checks whose cost dominates the whole sweep (ISSUE-15
# tier-1 relief: the two flash kernels finite-difference a fused
# attention at ~65s each, CTCLoss ~12s — together 2/3 of this file's
# runtime).  They run in the slow tier; tier-1 keeps their forward
# sweep here plus the cheap analytic gradient parity in
# tests/test_pallas.py::test_flash_grads_match_dense.
SLOW_GRAD = {"_contrib_flash_selfatt", "_contrib_flash_selfatt_nomask",
             "CTCLoss"}

DIFF = [pytest.param(n, marks=pytest.mark.slow) if n in SLOW_GRAD else n
        for n in CANONICAL
        if OP_REGISTRY[n].differentiable and n not in FWD_SKIP]


@pytest.mark.parametrize("name", DIFF)
def test_gradient(name):
    od = OP_REGISTRY[name]
    np_inputs, kwargs, wrt, grad_reason, rtol, atol = _get_spec(name, od)
    spec = SPECS.get(name, {})
    if name in GRAD_SKIP:
        pytest.skip(GRAD_SKIP[name])
    if spec and spec.get("grad") is False:
        pytest.skip(spec["grad_reason"])
    if not np_inputs:
        pytest.skip("no array inputs (fill op)")
    if wrt is None:
        wrt = [i for i, x in enumerate(np_inputs)
               if np.issubdtype(x.dtype, np.floating)]
    if not wrt:
        pytest.skip("no float inputs to differentiate")

    from mxnet_tpu import autograd
    from mxnet_tpu.test_utils import numeric_grad, assert_almost_equal

    # fixed random projection of the first output: a plain .sum() is
    # structurally zero-gradient for normalization ops (the normalized
    # values sum to a constant) and would only compare FD noise
    with autograd.train_mode():
        out0 = _first(_run(name, np_inputs, kwargs)).asnumpy()
    proj = np.asarray(_rng(name + "/proj").randn(*out0.shape),
                      np.float32)

    def scalar_f(wrt_vals):
        full = list(np_inputs)
        for i, v in zip(wrt, wrt_vals):
            full[i] = v.astype(np.float32)
        # train_mode: mode-dependent ops (BatchNorm) must linearize the
        # same branch the recorded forward below uses
        with autograd.train_mode():
            out = _first(_run(name, full, kwargs))
        return float((out.asnumpy().astype(np.float64) * proj).sum())

    expected = numeric_grad(
        scalar_f, [np_inputs[i].astype(np.float64) for i in wrt],
        eps=1e-3)

    nd_inputs = [_to_nd(x) for x in np_inputs]
    for i in wrt:
        nd_inputs[i].attach_grad()
    with autograd.record():
        out = _first(getattr(opmod, name)(*nd_inputs, **kwargs))
        loss = (out * _to_nd(proj)).sum()
    loss.backward()
    for i, exp in zip(wrt, expected):
        assert_almost_equal(
            nd_inputs[i].grad.asnumpy(), exp.astype(np.float32),
            rtol=rtol, atol=atol,
            names=(f"{name}.grad[{i}]", f"{name}.fd[{i}]"))


def test_blockgrad_gradient_is_zero():
    """BlockGrad: identity forward, zero gradient BY CONTRACT (why it is
    excluded from the FD sweep)."""
    from mxnet_tpu import autograd
    x = _to_nd(np.ones((2, 3), np.float32))
    x.attach_grad()
    with autograd.record():
        y = (opmod.BlockGrad(x) * 3.0).sum()
    y.backward()
    assert float(np.abs(x.grad.asnumpy()).sum()) == 0.0


def test_sweep_budget():
    """The skip lists stay small and every skipped name really is a
    registered op (a rename must not silently disable its coverage)."""
    for k in list(FWD_SKIP) + list(GRAD_SKIP):
        assert k in CANONICAL, f"skip-list entry {k} not in registry"
    assert len(FWD_SKIP) <= 0.02 * len(CANONICAL)
    n_grad_skips = len(GRAD_SKIP) + sum(
        1 for s in SPECS.values()
        if isinstance(s, dict) and s.get("grad") is False)
    assert n_grad_skips <= 0.1 * len(CANONICAL), n_grad_skips
    # tier-2 oracle-coverage floor (r5): most of the registry must have
    # an independent NumPy forward reference, not just smoke+FD — and
    # the floor is asserted so coverage can only ratchet up
    n_oracle = sum(1 for n in CANONICAL if n in ORACLES)
    assert n_oracle >= 240, n_oracle
    assert n_oracle >= 0.9 * len(CANONICAL), (n_oracle, len(CANONICAL))
    # every oracle-less canonical op is one of the legitimate classes:
    # rng samplers (distribution tests live in test_ndarray/test_text),
    # sign-ambiguous decompositions, or complex ops with dedicated
    # oracle tests elsewhere (quantized conv/fc, MultiBox target/
    # detection, MoE) — list pinned so a new op can't silently join it
    allowed_no_oracle = {
        "BilinearResize2D", "Correlation", "MultiBoxDetection",
        "MultiBoxTarget", "_contrib_moe_ffn",
        "_contrib_moe_top1_dispatch", "_contrib_quantized_act",
        "_contrib_quantized_conv", "_contrib_quantized_fully_connected",
        "_contrib_quantized_pooling", "_linalg_gelqf", "_linalg_syevd",
        "_random_exponential", "_random_gamma",
        "_random_negative_binomial", "_random_normal",
        "_random_poisson", "_random_randint", "_random_uniform",
        "_sample_multinomial", "_sample_unique_zipfian", "_shuffle",
        "sample_normal", "sample_uniform", "Custom"}
    missing = {n for n in CANONICAL if n not in ORACLES}
    assert missing <= allowed_no_oracle, missing - allowed_no_oracle


# ------------------------------------------------- declarative shape rules
# ISSUE-5: ops with a rule in ops/shape_rules.py answer "what comes
# out?" without tracing (OpDef.infer_signature) — the same algebra the
# mxlint abstract interpreter and deploy manifest checks consume.  The
# sweep holds every rule to the real forward pass: a concrete predicted
# dim must match the actual output.
RULED = [n for n in CANONICAL
         if OP_REGISTRY[n].shape_rule is not None and n not in FWD_SKIP]


def test_shape_rules_cover_the_juggling_core():
    # the reshape/transpose/reduce/matmul family the serving and lint
    # layers reason about must stay covered as the registry grows
    assert {"Reshape", "transpose", "expand_dims", "dot", "batch_dot",
            "sum", "Concat"} <= set(RULED)


@pytest.mark.parametrize("name", RULED)
def test_infer_signature_agrees_with_forward(name):
    od = OP_REGISTRY[name]
    np_inputs, kwargs, _wrt, _gr, _rtol, _atol = _get_spec(name, od)
    out = _first(_run(name, np_inputs, kwargs))
    sig = od.infer_signature(
        [(x.shape, str(x.dtype)) for x in np_inputs], kwargs)
    assert sig is not None
    shape, dtype = sig
    actual = out.asnumpy()
    if shape is not None:
        assert len(shape) == actual.ndim, \
            f"{name}: predicted rank {len(shape)} vs {actual.ndim}"
        for i, d in enumerate(shape):
            if d is not None and d.concrete is not None:
                assert d.concrete == actual.shape[i], \
                    f"{name}: axis {i} predicted {d.concrete}, " \
                    f"got {actual.shape[i]}"
    if dtype is not None:
        assert dtype == str(actual.dtype), \
            f"{name}: predicted dtype {dtype}, got {actual.dtype}"


def test_infer_signature_symbolic_and_infeasible():
    """The registry rule answers symbolic queries (serving's dynamic
    batch dim) and raises MXNetError on provable infeasibility before
    any tracing happens."""
    from mxnet_tpu.base import MXNetError
    from mxnet_tpu.ops import shape_rules as SR

    od = OP_REGISTRY["reshape"]
    B = SR.sym("B")
    shape, dtype = od.infer_signature([((B, 8), "float32")],
                                      {"shape": (-1, 4)})
    assert SR.dim_eq(shape[0], SR.dim_mul(SR.lit(2), B)) is True
    assert SR.dim_eq(shape[1], SR.lit(4)) is True
    assert dtype == "float32"
    with pytest.raises(MXNetError, match="infeasible"):
        od.infer_signature([((3, 4), "float32")], {"shape": (5, 2)})
    # int dims in the query are lifted to Dim literals
    shape, _ = od.infer_signature([((6, 4), "float32")],
                                  {"shape": (3, -1)})
    assert shape == (SR.lit(3), SR.lit(8))
    # an op without a rule degrades to None, never to a guess
    no_rule = next(n for n in CANONICAL
                   if OP_REGISTRY[n].shape_rule is None)
    assert OP_REGISTRY[no_rule].infer_signature(
        [((2, 2), "float32")], {}) is None
