"""Registry-wide operator correctness sweep (VERDICT r4 item 4).

Reference pattern: ``tests/python/unittest/test_operator.py`` — the
biggest single test file upstream, where (nearly) every registered op is
forward-checked against a NumPy oracle and numeric-gradient-checked
(SURVEY.md §4 row 1, ``check_numeric_gradient``).  Here the whole
``list_ops()`` registry is enumerated so a newly registered op is swept
automatically; an op may opt out only via the explicit skip tables below,
each entry with a one-line reason.

Three layers per op:
  1. forward smoke — the generated frontend runs on canonical small
     inputs; outputs are finite (float) and well-formed;
  2. NumPy oracle — where a clean numpy equivalent exists, outputs match;
  3. finite-difference gradient — every differentiable op's autograd
     gradient (the tape path) matches central differences, with
     integer/index inputs held fixed (``wrt``).
"""
import math
import zlib

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
import mxnet_tpu.ndarray.op as opmod
from mxnet_tpu.ops.registry import OP_REGISTRY, list_ops

# --------------------------------------------------------------- enumeration
_seen = {}
for _n in list_ops():
    _od = OP_REGISTRY[_n]
    _seen.setdefault(id(_od), _n)          # first registration = primary name
CANONICAL = sorted(_seen.values())


def _rng(name):
    # crc32, not hash(): str hashes are salted per interpreter run and
    # would make per-op inputs (and any failure) non-reproducible
    return np.random.RandomState(zlib.crc32(name.encode()) % (2 ** 31))


def _f32(rng, *shape):
    return rng.randn(*shape).astype(np.float32)


def _pos(rng, *shape):
    return (np.abs(rng.randn(*shape)) + 0.3).astype(np.float32)


def _idx(rng, n, *shape):
    """index-like float input: x.5 values so ±eps FD perturbation never
    crosses an integer boundary (the op casts to int internally)."""
    return (rng.randint(0, n, shape) + 0.5).astype(np.float32)


def _spd(rng, n, batch=()):
    m = rng.randn(*batch, n, n)
    a = m @ np.swapaxes(m, -1, -2) + n * np.eye(n)
    return a.astype(np.float32)


# --------------------------------------------------------------------- skips
# Ops the sweep does not run AT ALL (each covered elsewhere or not
# meaningfully invokable standalone).  Budget: < 10% of the registry.
FWD_SKIP = {
    "Custom": "python CustomOp trampoline; needs a registered user op "
              "(covered by tests/test_operator_custom.py)",
}

# Differentiable ops whose FD gradient check is skipped (forward still
# swept).  Each reason is a property of the op, not a TODO.
GRAD_SKIP = {
    "BlockGrad": "gradient is zero BY CONTRACT (identity forward); FD "
                 "sees the identity — asserted separately below",
    "Softmax": "SoftmaxOutput's training gradient is (p - one_hot) by "
               "contract, not d(forward)/dx (covered by test_loss)",
    "MakeLoss": "custom grad_scale gradient by contract, not "
                "d(forward)/dx (reference MakeLoss semantics)",
    "_linalg_syevd": "eigenvector gradient is ill-conditioned under FD "
                     "(sign/ordering flips at crossings)",
    "_linalg_gelqf": "LQ factor gradients are sign-ambiguous under FD",
    "RNN": "fused multi-layer kernel; 100+-element parameter vector "
           "makes FD impractical (gradients covered by test_gluon_rnn "
           "training-convergence tests)",
    "Dropout": "rng op: each FD evaluation draws a fresh mask "
               "(p=0 forward identity is asserted in the oracle)",
    "ceil": "piecewise-constant: gradient is zero a.e. and FD at a step "
            "is undefined",
    "floor": "piecewise-constant (as ceil)",
    "rint": "piecewise-constant (as ceil)",
    "round": "piecewise-constant (as ceil)",
    "trunc": "piecewise-constant (as ceil)",
    "sign": "piecewise-constant (as ceil)",
    "_shuffle": "rng op: each FD evaluation permutes differently",
    "_sample_multinomial": "rng sampler (forward distribution checked "
                           "in test_ndarray random tests)",
}

# ------------------------------------------------------------------- domains
# unary float ops needing a restricted input domain for a well-defined,
# smooth forward (name -> generator(rng) for the single input)
_DOMAIN = {
    "arccos": lambda r: (r.uniform(-0.8, 0.8, (2, 3))).astype(np.float32),
    "arcsin": lambda r: (r.uniform(-0.8, 0.8, (2, 3))).astype(np.float32),
    "arctanh": lambda r: (r.uniform(-0.8, 0.8, (2, 3))).astype(np.float32),
    "erfinv": lambda r: (r.uniform(-0.8, 0.8, (2, 3))).astype(np.float32),
    "arccosh": lambda r: (1.5 + np.abs(r.randn(2, 3))).astype(np.float32),
    "log": lambda r: _pos(r, 2, 3),
    "log2": lambda r: _pos(r, 2, 3),
    "log10": lambda r: _pos(r, 2, 3),
    "log1p": lambda r: _pos(r, 2, 3),
    "sqrt": lambda r: _pos(r, 2, 3),
    "rsqrt": lambda r: _pos(r, 2, 3),
    "cbrt": lambda r: _pos(r, 2, 3),
    "rcbrt": lambda r: _pos(r, 2, 3),
    "reciprocal": lambda r: _pos(r, 2, 3),
    "gamma": lambda r: _pos(r, 2, 3),
    "gammaln": lambda r: _pos(r, 2, 3),
    "digamma": lambda r: (1.0 + _pos(r, 2, 3)).astype(np.float32),
    # keep FD away from the |x|=1 kink / integer steps
    "abs": lambda r: (np.sign(r.randn(2, 3)) *
                      (0.3 + np.abs(r.randn(2, 3)))).astype(np.float32),
}

# ------------------------------------------------------------------- oracles
_ERF = np.vectorize(math.erf)
_GAMMA = np.vectorize(math.gamma)
_LGAMMA = np.vectorize(math.lgamma)


def _np_softmax(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


# name -> callable(*np_inputs, **kwargs) returning the expected FIRST
# output as a numpy array.  Only ops with a clean numpy equivalent.
ORACLES = {
    # elementwise unary
    "abs": np.abs, "arccos": np.arccos, "arccosh": np.arccosh,
    "arcsin": np.arcsin, "arcsinh": np.arcsinh, "arctan": np.arctan,
    "arctanh": np.arctanh, "cbrt": np.cbrt, "ceil": np.ceil,
    "cos": np.cos, "cosh": np.cosh, "degrees": np.degrees,
    "erf": _ERF, "erfc": lambda x: 1.0 - _ERF(x),
    "exp": np.exp, "expm1": np.expm1, "floor": np.floor,
    "gamma": _GAMMA, "gammaln": _LGAMMA,
    "log": np.log, "log10": np.log10, "log1p": np.log1p, "log2": np.log2,
    "logical_not": lambda x: (x == 0).astype(np.float32),
    "negative": np.negative, "radians": np.radians,
    "rcbrt": lambda x: 1.0 / np.cbrt(x),
    "reciprocal": lambda x: 1.0 / x,
    "relu": lambda x: np.maximum(x, 0),
    "rint": np.rint,
    "rsqrt": lambda x: 1.0 / np.sqrt(x),
    "sigmoid": lambda x: 1.0 / (1.0 + np.exp(-x)),
    "sign": np.sign, "sin": np.sin, "sinh": np.sinh,
    "softsign": lambda x: x / (1.0 + np.abs(x)),
    "sqrt": np.sqrt, "square": np.square, "tan": np.tan, "tanh": np.tanh,
    "trunc": np.trunc,
    "hard_sigmoid": lambda x, alpha=0.2, beta=0.5:
        np.clip(alpha * x + beta, 0, 1),
    "smooth_l1": lambda x, scalar=1.0: np.where(
        np.abs(x) < 1.0 / scalar ** 2, 0.5 * (scalar * x) ** 2,
        np.abs(x) - 0.5 / scalar ** 2),
    "_copy": lambda x: x, "BlockGrad": lambda x: x, "Flatten":
        lambda x: x.reshape(x.shape[0], -1),
    "_contrib_div_sqrt_dim": lambda x: x / np.sqrt(x.shape[-1]),
    "_contrib_gelu_erf": lambda x: 0.5 * x * (1 + _ERF(x / np.sqrt(2))),
    "zeros_like": np.zeros_like, "ones_like": np.ones_like,
    "full_like": lambda x, fill_value=0.0: np.full_like(x, fill_value),
    "shape_array": lambda x: np.array(x.shape, np.int64),
    "size_array": lambda x: np.array([x.size], np.int64),
    # binary / broadcast
    "_add": np.add, "_minus": np.subtract, "_mul": np.multiply,
    "_div": np.divide, "_power": np.power,
    "broadcast_add": np.add, "broadcast_minus": np.subtract,
    "broadcast_mul": np.multiply, "broadcast_div": np.divide,
    "broadcast_maximum": np.maximum, "broadcast_minimum": np.minimum,
    "broadcast_hypot": np.hypot, "broadcast_arctan2": np.arctan2,
    "broadcast_mod": np.mod,
    "broadcast_equal": lambda a, b: (a == b).astype(np.float32),
    "broadcast_not_equal": lambda a, b: (a != b).astype(np.float32),
    "broadcast_greater": lambda a, b: (a > b).astype(np.float32),
    "broadcast_greater_equal": lambda a, b: (a >= b).astype(np.float32),
    "broadcast_lesser": lambda a, b: (a < b).astype(np.float32),
    "broadcast_lesser_equal": lambda a, b: (a <= b).astype(np.float32),
    "broadcast_logical_and": lambda a, b:
        np.logical_and(a, b).astype(np.float32),
    "broadcast_logical_or": lambda a, b:
        np.logical_or(a, b).astype(np.float32),
    "broadcast_logical_xor": lambda a, b:
        np.logical_xor(a, b).astype(np.float32),
    # scalar ops
    "_plus_scalar": lambda x, scalar=0.0: x + scalar,
    "_minus_scalar": lambda x, scalar=0.0: x - scalar,
    "_rminus_scalar": lambda x, scalar=0.0: scalar - x,
    "_mul_scalar": lambda x, scalar=1.0: x * scalar,
    "_div_scalar": lambda x, scalar=1.0: x / scalar,
    "_rdiv_scalar": lambda x, scalar=1.0: scalar / x,
    "_mod_scalar": lambda x, scalar=1.0: np.mod(x, scalar),
    "_rmod_scalar": lambda x, scalar=1.0: np.mod(scalar, x),
    "_power_scalar": lambda x, scalar=1.0: np.power(x, scalar),
    "_rpower_scalar": lambda x, scalar=1.0: np.power(scalar, x),
    "_hypot_scalar": lambda x, scalar=0.0: np.hypot(x, scalar),
    "_maximum_scalar": lambda x, scalar=0.0: np.maximum(x, scalar),
    "_minimum_scalar": lambda x, scalar=0.0: np.minimum(x, scalar),
    "_equal_scalar": lambda x, scalar=0.0: (x == scalar).astype(np.float32),
    "_not_equal_scalar": lambda x, scalar=0.0:
        (x != scalar).astype(np.float32),
    "_greater_scalar": lambda x, scalar=0.0:
        (x > scalar).astype(np.float32),
    "_greater_equal_scalar": lambda x, scalar=0.0:
        (x >= scalar).astype(np.float32),
    "_greater_scalar_rev": lambda x, scalar=0.0:
        (scalar > x).astype(np.float32),
    "_lesser_scalar": lambda x, scalar=0.0:
        (x < scalar).astype(np.float32),
    "_lesser_equal_scalar": lambda x, scalar=0.0:
        (x <= scalar).astype(np.float32),
    # reductions
    "sum": lambda x, **k: np.sum(x, axis=k.get("axis")),
    "mean": lambda x, **k: np.mean(x, axis=k.get("axis")),
    "max": lambda x, **k: np.max(x, axis=k.get("axis")),
    "min": lambda x, **k: np.min(x, axis=k.get("axis")),
    "prod": lambda x, **k: np.prod(x, axis=k.get("axis")),
    "nansum": lambda x, **k: np.nansum(x, axis=k.get("axis")),
    "nanprod": lambda x, **k: np.nanprod(x, axis=k.get("axis")),
    "norm": lambda x, **k: np.sqrt(np.sum(np.square(x))),
    "_np_cumsum": lambda x, axis=None, dtype=None: np.cumsum(x, axis=axis),
    "cumprod": lambda x, axis=None, dtype=None: np.cumprod(x, axis=axis),
    "argmax": lambda x, axis=None, keepdims=False:
        np.argmax(x, axis=axis).astype(np.float32),
    "argmin": lambda x, axis=None, keepdims=False:
        np.argmin(x, axis=axis).astype(np.float32),
    "argmax_channel": lambda x: np.argmax(x, axis=1).astype(np.float32),
    # shape / indexing
    "transpose": lambda x, axes=(): np.transpose(
        x, axes if axes else None),
    "expand_dims": lambda x, axis=0: np.expand_dims(x, axis),
    "squeeze": lambda x, axis=None: np.squeeze(x, axis),
    "flip": lambda x, axis=0: np.flip(x, axis),
    "tile": lambda x, reps=(): np.tile(x, reps),
    "repeat": lambda x, repeats=1, axis=None: np.repeat(x, repeats, axis),
    "SwapAxis": lambda x, dim1=0, dim2=0: np.swapaxes(x, dim1, dim2),
    "Reshape": lambda x, shape=(), reverse=False: x.reshape(shape),
    "broadcast_to": lambda x, shape=(): np.broadcast_to(x, shape),
    "clip": lambda x, a_min=None, a_max=None: np.clip(x, a_min, a_max),
    "diag": lambda x, k=0, axis1=0, axis2=1: np.diag(x, k),
    "sort": lambda x, axis=-1, is_ascend=True: np.sort(x, axis),
    "argsort": lambda x, axis=-1, is_ascend=True, dtype=None:
        np.argsort(x, axis, kind="stable").astype(np.float32),
    "one_hot": lambda i, depth=0, on_value=1.0, off_value=0.0, dtype=None:
        np.where(np.eye(depth)[i.astype(np.int64)] > 0, on_value,
                 off_value).astype(np.float32),
    "where": lambda c, x, y: np.where(c != 0, x, y),
    "slice_axis": lambda x, axis=0, begin=0, end=None:
        np.take(x, np.arange(begin, end if end is not None
                             else x.shape[axis]), axis=axis),
    "space_to_depth": lambda x, block_size=1: x.reshape(
        x.shape[0], x.shape[1], x.shape[2] // block_size, block_size,
        x.shape[3] // block_size, block_size).transpose(
            0, 3, 5, 1, 2, 4).reshape(
            x.shape[0], x.shape[1] * block_size ** 2,
            x.shape[2] // block_size, x.shape[3] // block_size),
    # linear algebra
    "dot": lambda a, b, transpose_a=False, transpose_b=False: np.dot(
        a.T if transpose_a else a, b.T if transpose_b else b),
    "batch_dot": lambda a, b, transpose_a=False, transpose_b=False:
        np.matmul(np.swapaxes(a, -1, -2) if transpose_a else a,
                  np.swapaxes(b, -1, -2) if transpose_b else b),
    "FullyConnected": lambda x, w, b, num_hidden=0, no_bias=False,
        flatten=True: x.reshape(x.shape[0], -1) @ w.T + b,
    "_linalg_det": lambda a: np.linalg.det(a).astype(np.float32),
    "_linalg_inverse": np.linalg.inv,
    "_linalg_potrf": np.linalg.cholesky,
    "_linalg_sumlogdiag": lambda a: np.log(np.diagonal(
        a, axis1=-2, axis2=-1)).sum(-1).astype(np.float32),
    "_linalg_extractdiag": lambda a, offset=0: np.diagonal(
        a, offset, -2, -1),
    "_linalg_makediag": lambda a, offset=0: np.apply_along_axis(
        lambda v: np.diag(v, offset), -1, a),
    "khatri_rao": lambda a, b: np.vstack(
        [np.kron(a[:, j], b[:, j]).reshape(-1, 1)
         for j in range(a.shape[1])]).reshape(a.shape[1], -1).T,
    # softmax family
    "softmax": lambda x, axis=-1, **k: _np_softmax(x, axis),
    "softmin": lambda x, axis=-1, **k: _np_softmax(-x, axis),
    "log_softmax": lambda x, axis=-1, **k: np.log(_np_softmax(x, axis)),
    "SoftmaxActivation": lambda x, mode="instance": _np_softmax(x, -1),
    "L2Normalization": lambda x, eps=1e-10, mode="instance":
        x / np.sqrt((x.reshape(x.shape[0], -1) ** 2).sum(-1)
                    + eps).reshape(-1, *([1] * (x.ndim - 1))),
    "_contrib_gelu_tanh": lambda x: 0.5 * x * (1 + np.tanh(
        np.sqrt(2 / np.pi) * (x + 0.044715 * x ** 3))),
    # fills
    "_zeros": lambda shape=(), dtype=None, ctx=None: np.zeros(shape),
    "_ones": lambda shape=(), dtype=None, ctx=None: np.ones(shape),
    "_full": lambda shape=(), value=0.0, dtype=None, ctx=None:
        np.full(shape, value),
    "_eye": lambda N=0, M=0, k=0, dtype=None, ctx=None:
        np.eye(N, M or None, k),
    "_arange": lambda start=0, stop=None, step=1.0, repeat=1, dtype=None,
        ctx=None, infer_range=False: np.arange(start, stop, step),
    "_linspace": lambda start=0, stop=1, num=50, endpoint=True,
        dtype=None, ctx=None: np.linspace(start, stop, num, endpoint),
    "Concat": lambda a, b, dim=1, num_args=0: np.concatenate([a, b], dim),
    "stack": lambda a, b, axis=0: np.stack([a, b], axis),
    "Pad": lambda x, mode="constant", pad_width=(), constant_value=0.0:
        np.pad(x, [(pad_width[2 * i], pad_width[2 * i + 1])
                   for i in range(x.ndim)], mode="constant",
               constant_values=constant_value),
    "Cast": lambda x, dtype="float32": x.astype(dtype),
    "amp_cast": lambda x, dtype="float32": x.astype(dtype),
    "Dropout": lambda x, p=0.5, **k: x,              # spec pins p=0.0
    "take": lambda a, i, axis=0, mode="clip": np.take(
        a, i.astype(np.int64), axis=axis),
    "pick": lambda x, i, axis=-1, keepdims=False, mode="clip":
        np.take_along_axis(x, i.astype(np.int64)[..., None],
                           axis=-1).squeeze(-1),
    "gather_nd": lambda d, i: d[tuple(i.astype(np.int64))],
    "unravel_index": lambda x, shape=(): np.stack(
        np.unravel_index(x.astype(np.int64), shape)),
    "_contrib_arange_like": lambda x, start=0.0, step=1.0, repeat=1,
        axis=None: np.arange(start, start + x.size * step,
                             step).reshape(x.shape),
}


# -------------------------------------------------------------------- specs
# Per-op canonical inputs.  An entry is dict(inputs=callable(rng) ->
# [np arrays], kwargs={}, wrt=[indices FD-checked]); ops absent from
# SPECS get arity-default float inputs (with _DOMAIN overrides).
def _i8(rng, *shape):
    return np.clip(rng.randn(*shape) * 50, -127, 127).astype(np.int8)


_MINMAX = lambda: [np.array([-1.0], np.float32), np.array([1.0], np.float32)]

SPECS = {
    # ---------------- NN layers
    "Activation": dict(inputs=lambda r: [_f32(r, 2, 3)]),
    "AdaptiveAvgPooling2D": dict(inputs=lambda r: [_f32(r, 1, 2, 6, 6)],
                                 kwargs=dict(output_size=(2, 2))),
    "BatchNorm": dict(
        inputs=lambda r: [_f32(r, 2, 3, 4, 4), _pos(r, 3), _f32(r, 3),
                          _f32(r, 3), _pos(r, 3)],
        wrt=[0, 2]),   # batch stats: moving_* unused in train fwd
    "BilinearResize2D": dict(inputs=lambda r: [_f32(r, 1, 2, 4, 4)],
                             kwargs=dict(height=6, width=6)),
    "BilinearSampler": dict(
        inputs=lambda r: [_f32(r, 1, 2, 4, 4),
                          np.clip(r.randn(1, 2, 3, 3), -0.9,
                                  0.9).astype(np.float32)]),
    "CTCLoss": dict(
        inputs=lambda r: [_f32(r, 4, 2, 5),
                          np.array([[1, 2], [2, 1]], np.float32)],
        wrt=[0]),
    "Concat": dict(inputs=lambda r: [_f32(r, 2, 3), _f32(r, 2, 3)],
                   kwargs=dict(dim=1, num_args=2)),
    "Convolution": dict(
        inputs=lambda r: [_f32(r, 1, 2, 5, 5), _f32(r, 3, 2, 3, 3),
                          _f32(r, 3)],
        kwargs=dict(kernel=(3, 3), num_filter=3)),
    "Correlation": dict(
        inputs=lambda r: [_f32(r, 1, 2, 4, 4), _f32(r, 1, 2, 4, 4)]),
    "Crop": dict(inputs=lambda r: [_f32(r, 1, 2, 6, 6)],
                 kwargs=dict(h_w=(4, 4), num_args=1)),
    "Deconvolution": dict(
        inputs=lambda r: [_f32(r, 1, 3, 4, 4), _f32(r, 3, 2, 3, 3)],
        kwargs=dict(kernel=(3, 3), num_filter=2)),
    "Dropout": dict(inputs=lambda r: [_f32(r, 2, 3)],
                    kwargs=dict(p=0.0)),
    "Embedding": dict(
        inputs=lambda r: [_idx(r, 5, 2, 3), _f32(r, 5, 4)],
        kwargs=dict(input_dim=5, output_dim=4), wrt=[1]),
    "FullyConnected": dict(
        inputs=lambda r: [_f32(r, 2, 3), _f32(r, 4, 3), _f32(r, 4)],
        kwargs=dict(num_hidden=4)),
    "GridGenerator": dict(inputs=lambda r: [_f32(r, 1, 6)],
                          kwargs=dict(target_shape=(3, 3))),
    "GroupNorm": dict(
        inputs=lambda r: [_f32(r, 2, 4, 3, 3), _pos(r, 4), _f32(r, 4)],
        kwargs=dict(num_groups=2)),
    "InstanceNorm": dict(
        inputs=lambda r: [_f32(r, 2, 3, 4, 4), _pos(r, 3), _f32(r, 3)]),
    "L2Normalization": dict(inputs=lambda r: [_f32(r, 2, 3, 4)]),
    "LRN": dict(inputs=lambda r: [_f32(r, 1, 3, 4, 4)],
                kwargs=dict(nsize=3)),
    "LayerNorm": dict(
        inputs=lambda r: [_f32(r, 2, 3, 4), _pos(r, 4), _f32(r, 4)]),
    "LeakyReLU": dict(inputs=lambda r: [
        (np.sign(r.randn(2, 3)) * (0.3 + np.abs(r.randn(2, 3))))
        .astype(np.float32)]),
    "Pad": dict(inputs=lambda r: [_f32(r, 1, 2, 3, 3)],
                kwargs=dict(mode="constant",
                            pad_width=(0, 0, 0, 0, 1, 1, 1, 1))),
    "Pooling": dict(inputs=lambda r: [_f32(r, 1, 2, 4, 4)],
                    kwargs=dict(kernel=(2, 2), pool_type="avg")),
    "RNN": dict(
        inputs=lambda r: [_f32(r, 3, 2, 4), _f32(r, 108) * 0.1,
                          _f32(r, 1, 2, 3), _f32(r, 1, 2, 3)],
        kwargs=dict(state_size=3, num_layers=1, mode="lstm")),
    "ROIAlign": dict(
        inputs=lambda r: [_f32(r, 1, 2, 6, 6),
                          np.array([[0, 0.5, 0.5, 3.5, 3.5],
                                    [0, 1.0, 1.0, 4.0, 4.0]],
                                   np.float32)],
        kwargs=dict(pooled_size=(2, 2)), wrt=[0]),
    "ROIPooling": dict(
        inputs=lambda r: [_f32(r, 1, 2, 6, 6),
                          np.array([[0, 0, 0, 3, 3]], np.float32)],
        kwargs=dict(pooled_size=(2, 2)), wrt=[0]),
    "Reshape": dict(inputs=lambda r: [_f32(r, 2, 3)],
                    kwargs=dict(shape=(3, 2))),
    "SequenceLast": dict(
        inputs=lambda r: [_f32(r, 3, 2, 4),
                          np.array([1.5, 2.5], np.float32)], wrt=[0]),
    "SequenceMask": dict(
        inputs=lambda r: [_f32(r, 3, 2, 4),
                          np.array([1.5, 2.5], np.float32)], wrt=[0]),
    "SequenceReverse": dict(
        inputs=lambda r: [_f32(r, 3, 2, 4),
                          np.array([1.5, 2.5], np.float32)], wrt=[0]),
    "SliceChannel": dict(inputs=lambda r: [_f32(r, 2, 4, 3)],
                         kwargs=dict(num_outputs=2, axis=1)),
    "Softmax": dict(
        inputs=lambda r: [_f32(r, 4, 5), _idx(r, 5, 4)], wrt=[0]),
    "SoftmaxActivation": dict(inputs=lambda r: [_f32(r, 2, 3)]),
    "SpatialTransformer": dict(
        inputs=lambda r: [_f32(r, 1, 2, 5, 5),
                          np.array([[1.0, 0.1, 0.0, -0.1, 1.0, 0.0]],
                                   np.float32)],
        kwargs=dict(target_shape=(4, 4))),
    "SwapAxis": dict(inputs=lambda r: [_f32(r, 2, 3)],
                     kwargs=dict(dim1=0, dim2=1)),
    "UpSampling": dict(inputs=lambda r: [_f32(r, 1, 2, 3, 3)],
                       kwargs=dict(scale=2, sample_type="nearest",
                                   num_args=1)),
    # ---------------- detection (forward-only; diff=False)
    "MultiBoxPrior": dict(inputs=lambda r: [_f32(r, 1, 3, 4, 4)],
                          kwargs=dict(sizes=(0.5,), ratios=(1.0, 2.0))),
    "MultiBoxDetection": dict(
        inputs=lambda r: [np.abs(r.rand(1, 2, 4)).astype(np.float32),
                          _f32(r, 1, 16),
                          np.abs(r.rand(1, 4, 4)).astype(np.float32)]),
    "MultiBoxTarget": dict(
        inputs=lambda r: [np.abs(r.rand(1, 4, 4)).astype(np.float32),
                          np.array([[[1, 0.1, 0.1, 0.4, 0.4, 0]]],
                                   np.float32),
                          np.abs(r.rand(1, 2, 4)).astype(np.float32)]),
    # ---------------- contrib
    "_contrib_boolean_mask": dict(
        inputs=lambda r: [_f32(r, 4, 3),
                          np.array([1, 0, 1, 1], np.float32)]),
    "_contrib_index_array": dict(inputs=lambda r: [_f32(r, 2, 3)]),
    "_contrib_index_copy": dict(
        inputs=lambda r: [_f32(r, 4, 3), np.array([1.5, 2.5], np.float32),
                          _f32(r, 2, 3)], wrt=[0, 2]),
    "_contrib_flash_selfatt": dict(
        inputs=lambda r: [_f32(r, 4, 2, 12),
                          np.array([3.5, 4.0], np.float32)],
        kwargs=dict(heads=2), wrt=[0], rtol=3e-2, atol=3e-3),
    "_contrib_flash_selfatt_nomask": dict(
        inputs=lambda r: [_f32(r, 4, 2, 12)], kwargs=dict(heads=2),
        rtol=3e-2, atol=3e-3),
    "_contrib_interleaved_matmul_selfatt_qk": dict(
        inputs=lambda r: [_f32(r, 4, 2, 12)], kwargs=dict(heads=2)),
    "_contrib_interleaved_matmul_selfatt_valatt": dict(
        inputs=lambda r: [_f32(r, 4, 2, 12), _pos(r, 4, 4, 4)],
        kwargs=dict(heads=2)),
    "_contrib_interleaved_matmul_encdec_qk": dict(
        inputs=lambda r: [_f32(r, 3, 2, 8), _f32(r, 4, 2, 16)],
        kwargs=dict(heads=2)),
    "_contrib_interleaved_matmul_encdec_valatt": dict(
        inputs=lambda r: [_f32(r, 4, 2, 16), _pos(r, 4, 3, 4)],
        kwargs=dict(heads=2)),
    "_contrib_moe_ffn": dict(
        inputs=lambda r: [_f32(r, 4, 3), _f32(r, 3, 2), _f32(r, 2, 3, 5),
                          _f32(r, 2, 5), _f32(r, 2, 5, 3), _f32(r, 2, 3)],
        rtol=3e-2, atol=3e-3),
    "_contrib_moe_top1_dispatch": dict(inputs=lambda r: [_f32(r, 4, 2)],
                                       kwargs=dict(capacity=2)),
    "_contrib_multi_lars": dict(
        inputs=lambda r: [_pos(r, 3), _pos(r, 3), _pos(r, 3),
                          _pos(r, 3)]),
    "_contrib_arange_like": dict(inputs=lambda r: [_f32(r, 2, 3)]),
    # ---------------- quantization (int8; forward-only, diff=False)
    "_contrib_quantize": dict(
        inputs=lambda r: [_f32(r, 2, 3)] + _MINMAX()),
    "_contrib_quantize_v2": dict(inputs=lambda r: [_f32(r, 2, 3)]),
    "_contrib_dequantize": dict(
        inputs=lambda r: [_i8(r, 2, 3)] + _MINMAX()),
    "_contrib_requantize": dict(
        inputs=lambda r: [(r.randn(2, 3) * 1000).astype(np.int32)]
        + _MINMAX()),
    "_contrib_quantized_flatten": dict(
        inputs=lambda r: [_i8(r, 1, 2, 3)] + _MINMAX()),
    "_contrib_quantized_act": dict(
        inputs=lambda r: [_i8(r, 2, 3)] + _MINMAX()),
    "_contrib_quantized_pooling": dict(
        inputs=lambda r: [_i8(r, 1, 2, 4, 4)] + _MINMAX(),
        kwargs=dict(kernel=(2, 2))),
    "_contrib_quantized_conv": dict(
        inputs=lambda r: [_i8(r, 1, 2, 4, 4), _i8(r, 3, 2, 3, 3),
                          (r.randn(3) * 10).astype(np.int32)]
        + _MINMAX() * 3,
        kwargs=dict(kernel=(3, 3), num_filter=3)),
    "_contrib_quantized_fully_connected": dict(
        inputs=lambda r: [_i8(r, 2, 6), _i8(r, 4, 6),
                          (r.randn(4) * 10).astype(np.int32)]
        + _MINMAX() * 3,
        kwargs=dict(num_hidden=4)),
    # ---------------- linalg
    "_linalg_det": dict(inputs=lambda r: [_spd(r, 3)]),
    "_linalg_slogdet": dict(inputs=lambda r: [_spd(r, 3)]),
    "_linalg_inverse": dict(inputs=lambda r: [_spd(r, 3)]),
    "_linalg_potrf": dict(inputs=lambda r: [_spd(r, 3)]),
    "_linalg_potri": dict(inputs=lambda r: [_spd(r, 3)]),
    "_linalg_sumlogdiag": dict(inputs=lambda r: [_spd(r, 3)]),
    "_linalg_syevd": dict(inputs=lambda r: [_spd(r, 3)]),
    "_linalg_gelqf": dict(inputs=lambda r: [_f32(r, 2, 3)]),
    "_linalg_syrk": dict(inputs=lambda r: [_f32(r, 2, 3)]),
    "_linalg_extractdiag": dict(inputs=lambda r: [_f32(r, 3, 3)]),
    "_linalg_makediag": dict(inputs=lambda r: [_f32(r, 3)]),
    "_linalg_gemm": dict(
        inputs=lambda r: [_f32(r, 2, 3), _f32(r, 3, 4), _f32(r, 2, 4)]),
    "_linalg_gemm2": dict(inputs=lambda r: [_f32(r, 2, 3), _f32(r, 3, 4)]),
    "_linalg_trmm": dict(
        inputs=lambda r: [np.tril(_spd(r, 3)), _f32(r, 3, 3)]),
    "_linalg_trsm": dict(
        inputs=lambda r: [np.tril(_spd(r, 3)), _f32(r, 3, 3)]),
    # ---------------- optimizer update ops (first output = new weight)
    "sgd_update": dict(inputs=lambda r: [_f32(r, 4), _f32(r, 4)]),
    "signsgd_update": dict(inputs=lambda r: [_f32(r, 4), _f32(r, 4)],
                           grad=False,
                           grad_reason="sign() of grad: piecewise-const"),
    "sgd_mom_update": dict(
        inputs=lambda r: [_f32(r, 4), _f32(r, 4), _f32(r, 4)]),
    "mp_sgd_update": dict(
        inputs=lambda r: [_f32(r, 4), _f32(r, 4), _f32(r, 4)]),
    "mp_sgd_mom_update": dict(
        inputs=lambda r: [_f32(r, 4), _f32(r, 4), _f32(r, 4),
                          _f32(r, 4)]),
    "nag_mom_update": dict(
        inputs=lambda r: [_f32(r, 4), _f32(r, 4), _f32(r, 4)]),
    "signum_update": dict(
        inputs=lambda r: [_f32(r, 4), _f32(r, 4), _f32(r, 4)],
        grad=False, grad_reason="sign() of momentum: piecewise-const"),
    "adam_update": dict(
        inputs=lambda r: [_f32(r, 4), _f32(r, 4), _f32(r, 4), _pos(r, 4)]),
    "_adamw_update": dict(
        inputs=lambda r: [_f32(r, 4), _f32(r, 4), _f32(r, 4), _pos(r, 4),
                          np.array([1.0], np.float32)]),
    "ftrl_update": dict(
        inputs=lambda r: [_f32(r, 4), _f32(r, 4), _f32(r, 4), _pos(r, 4)]),
    "rmsprop_update": dict(
        inputs=lambda r: [_f32(r, 4), _f32(r, 4), _pos(r, 4)]),
    "rmspropalex_update": dict(
        # consistent running stats: n >= g_acc^2 (true for states evolved
        # from zero; keeps the Graves-RMSProp radicand positive so the
        # FD check probes the smooth region)
        inputs=lambda r: (lambda ga: [_f32(r, 4), _f32(r, 4),
                                      ga ** 2 + _pos(r, 4), ga,
                                      _f32(r, 4)])(_f32(r, 4))),
    "lamb_update_phase1": dict(
        inputs=lambda r: [_f32(r, 4), _f32(r, 4), _f32(r, 4), _pos(r, 4)]),
    "lamb_update_phase2": dict(
        inputs=lambda r: [_f32(r, 4), _f32(r, 4),
                          np.array([1.3], np.float32),
                          np.array([0.7], np.float32)]),
    "lamb_update_states": dict(
        inputs=lambda r: [_f32(r, 4), _f32(r, 4), _f32(r, 4), _pos(r, 4)]),
    # ---------------- indexing / misc
    "dot": dict(inputs=lambda r: [_f32(r, 2, 3), _f32(r, 3, 4)]),
    "batch_dot": dict(inputs=lambda r: [_f32(r, 2, 2, 3),
                                        _f32(r, 2, 3, 4)]),
    "_power": dict(inputs=lambda r: [_pos(r, 2, 3), _f32(r, 2, 3)]),
    "_rmod_scalar": dict(
        inputs=lambda r: [(1.2 + np.abs(r.randn(2, 3)) % 1.5)
                          .astype(np.float32)]),
    "broadcast_mod": dict(
        inputs=lambda r: [_f32(r, 2, 3) * 2.0,
                          (1.5 + np.abs(r.randn(1, 3)) % 1.4)
                          .astype(np.float32)]),
    "take": dict(inputs=lambda r: [_f32(r, 4, 3), _idx(r, 4, 5)],
                 wrt=[0]),
    "pick": dict(inputs=lambda r: [_f32(r, 3, 4), _idx(r, 4, 3)],
                 wrt=[0]),
    "gather_nd": dict(
        inputs=lambda r: [_f32(r, 3, 4),
                          np.array([[0.5, 1.5], [1.5, 2.5]], np.float32)],
        wrt=[0]),
    "scatter_nd": dict(
        inputs=lambda r: [_f32(r, 2, 3),
                          np.array([[0.5, 1.5]], np.float32)],
        kwargs=dict(shape=(2, 3)), wrt=[0]),
    "_contrib_index_array_2": None,      # placeholder never hit
    "one_hot": dict(inputs=lambda r: [_idx(r, 4, 3)],
                    kwargs=dict(depth=4)),
    "where": dict(
        inputs=lambda r: [(r.rand(2, 3) > 0.5).astype(np.float32),
                          _f32(r, 2, 3), _f32(r, 2, 3)], wrt=[1, 2]),
    "softmax_cross_entropy": dict(
        inputs=lambda r: [_f32(r, 3, 4), _idx(r, 4, 3)], wrt=[0]),
    "broadcast_like": dict(inputs=lambda r: [_f32(r, 1, 3), _f32(r, 2, 3)],
                           wrt=[0]),
    "slice_like": dict(inputs=lambda r: [_f32(r, 4, 5), _f32(r, 2, 3)],
                       wrt=[0]),
    "broadcast_axes": dict(inputs=lambda r: [_f32(r, 1, 3)],
                           kwargs=dict(axis=(0,), size=(4,))),
    "broadcast_to": dict(inputs=lambda r: [_f32(r, 1, 3)],
                         kwargs=dict(shape=(2, 3))),
    "crop": dict(inputs=lambda r: [_f32(r, 4, 5)],
                 kwargs=dict(begin=(1, 1), end=(3, 4))),
    "clip": dict(inputs=lambda r: [_f32(r, 2, 3)],
                 kwargs=dict(a_min=-0.4, a_max=0.4)),
    "depth_to_space": dict(inputs=lambda r: [_f32(r, 1, 4, 2, 2)],
                           kwargs=dict(block_size=2)),
    "space_to_depth": dict(inputs=lambda r: [_f32(r, 1, 1, 4, 4)],
                           kwargs=dict(block_size=2)),
    "im2col": dict(inputs=lambda r: [_f32(r, 1, 2, 4, 4)],
                   kwargs=dict(kernel=(2, 2))),
    "col2im": dict(inputs=lambda r: [_f32(r, 1, 8, 4)],
                   kwargs=dict(output_size=(3, 3), kernel=(2, 2))),
    "unravel_index": dict(
        inputs=lambda r: [np.array([1, 3, 5], np.float32)],
        kwargs=dict(shape=(2, 3))),
    "khatri_rao": dict(inputs=lambda r: [_f32(r, 2, 3), _f32(r, 4, 3)]),
    "stack": dict(inputs=lambda r: [_f32(r, 2, 3), _f32(r, 2, 3)]),
    "all_finite": dict(inputs=lambda r: [_f32(r, 2, 3), _f32(r, 3)]),
    "amp_multicast": dict(inputs=lambda r: [_f32(r, 2, 3), _f32(r, 3)],
                          kwargs=dict(num_outputs=2)),
    "topk": dict(inputs=lambda r: [_f32(r, 3, 5)], kwargs=dict(k=2)),
    "split_v2": dict(inputs=lambda r: [_f32(r, 4, 3)],
                     kwargs=dict(indices_or_sections=2)),
    "diag": dict(inputs=lambda r: [_f32(r, 3, 3)]),
    "tile": dict(inputs=lambda r: [_f32(r, 2, 3)], kwargs=dict(reps=(2, 1))),
    "repeat": dict(inputs=lambda r: [_f32(r, 2, 3)],
                   kwargs=dict(repeats=2, axis=1)),
    "slice_axis": dict(inputs=lambda r: [_f32(r, 4, 5)],
                       kwargs=dict(axis=1, begin=1, end=4)),
    "norm": dict(inputs=lambda r: [_f32(r, 2, 3)]),
    "squeeze": dict(inputs=lambda r: [_f32(r, 2, 1, 3)]),
    "flip": dict(inputs=lambda r: [_f32(r, 2, 3)], kwargs=dict(axis=1)),
    "transpose": dict(inputs=lambda r: [_f32(r, 2, 3)]),
    "expand_dims": dict(inputs=lambda r: [_f32(r, 2, 3)],
                        kwargs=dict(axis=1)),
    "sort": dict(inputs=lambda r: [_f32(r, 2, 5)]),
    "argsort": dict(inputs=lambda r: [_f32(r, 2, 5)]),
    "smooth_l1": dict(inputs=lambda r: [
        (np.sign(r.randn(2, 3)) * (0.3 + np.abs(r.randn(2, 3)) % 0.5))
        .astype(np.float32)]),
    "_sample_multinomial": dict(
        inputs=lambda r: [np.abs(r.rand(2, 4)).astype(np.float32) + 0.1]),
    "sample_normal": dict(
        inputs=lambda r: [_f32(r, 3), _pos(r, 3)]),
    "sample_uniform": dict(
        inputs=lambda r: [_f32(r, 3), _f32(r, 3) ** 2 + 1.0]),
    "_shuffle": dict(inputs=lambda r: [_f32(r, 6)]),
    "_sample_unique_zipfian": dict(inputs=lambda r: [],
                                   kwargs=dict(range_max=20, shape=(2, 5))),
    # fills: no inputs, kwargs drive
    "_zeros": dict(inputs=lambda r: [], kwargs=dict(shape=(2, 3))),
    "_ones": dict(inputs=lambda r: [], kwargs=dict(shape=(2, 3))),
    "_full": dict(inputs=lambda r: [], kwargs=dict(shape=(2, 2),
                                                   value=1.5)),
    "_eye": dict(inputs=lambda r: [], kwargs=dict(N=3)),
    "_arange": dict(inputs=lambda r: [], kwargs=dict(start=0, stop=5)),
    "_linspace": dict(inputs=lambda r: [], kwargs=dict(num=7)),
    "_random_exponential": dict(inputs=lambda r: [],
                                kwargs=dict(shape=(2, 3))),
    "_random_gamma": dict(inputs=lambda r: [], kwargs=dict(shape=(2, 3))),
    "_random_negative_binomial": dict(inputs=lambda r: [],
                                      kwargs=dict(k=3, p=0.5,
                                                  shape=(2, 3))),
    "_random_normal": dict(inputs=lambda r: [], kwargs=dict(shape=(2, 3))),
    "_random_poisson": dict(inputs=lambda r: [], kwargs=dict(shape=(2, 3))),
    "_random_randint": dict(inputs=lambda r: [],
                            kwargs=dict(low=0, high=10, shape=(2, 3))),
    "_random_uniform": dict(inputs=lambda r: [], kwargs=dict(shape=(2, 3))),
}


def _default_inputs(name, od, rng):
    if name in _DOMAIN:
        return [_DOMAIN[name](rng)]
    ni = od.num_inputs
    if ni is None:                      # variadic without a spec: 2 inputs
        return [_f32(rng, 2, 3), _f32(rng, 2, 3)]
    if callable(ni):
        raise AssertionError(
            f"op {name} has callable num_inputs and no SPECS entry — "
            f"add one")
    return [_f32(rng, 2, 3) for _ in range(ni)]


def _get_spec(name, od):
    spec = SPECS.get(name)
    rng = _rng(name)
    if spec is None:
        return _default_inputs(name, od, rng), {}, None, None, 1e-2, 1e-3
    return (spec["inputs"](rng), dict(spec.get("kwargs", {})),
            spec.get("wrt"), spec.get("grad_reason"),
            spec.get("rtol", 1e-2), spec.get("atol", 1e-3))


def _to_nd(x):
    return nd.array(x, dtype=str(x.dtype))


def _first(outs):
    return outs[0] if isinstance(outs, (list, tuple)) else outs


def _run(name, np_inputs, kwargs):
    frontend = getattr(opmod, name)
    return frontend(*[_to_nd(x) for x in np_inputs], **kwargs)


# --------------------------------------------------------------------- tests
@pytest.mark.parametrize("name", CANONICAL)
def test_forward(name):
    if name in FWD_SKIP:
        pytest.skip(FWD_SKIP[name])
    od = OP_REGISTRY[name]
    np_inputs, kwargs, _wrt, _gr, rtol, atol = _get_spec(name, od)
    outs = _run(name, np_inputs, kwargs)
    for o in (outs if isinstance(outs, (list, tuple)) else [outs]):
        a = o.asnumpy()
        assert a.size > 0 or name in ("_contrib_boolean_mask",), name
        if np.issubdtype(a.dtype, np.floating):
            assert np.isfinite(a).all(), f"{name}: non-finite output"
    oracle = ORACLES.get(name)
    if oracle is not None:
        got = _first(outs).asnumpy()
        want = np.asarray(oracle(*np_inputs, **kwargs))
        assert got.shape == tuple(want.shape), \
            f"{name}: shape {got.shape} vs oracle {want.shape}"
        np.testing.assert_allclose(got.astype(np.float64),
                                   want.astype(np.float64),
                                   rtol=1e-4, atol=1e-5, err_msg=name)


DIFF = [n for n in CANONICAL
        if OP_REGISTRY[n].differentiable and n not in FWD_SKIP]


@pytest.mark.parametrize("name", DIFF)
def test_gradient(name):
    od = OP_REGISTRY[name]
    np_inputs, kwargs, wrt, grad_reason, rtol, atol = _get_spec(name, od)
    spec = SPECS.get(name, {})
    if name in GRAD_SKIP:
        pytest.skip(GRAD_SKIP[name])
    if spec and spec.get("grad") is False:
        pytest.skip(spec["grad_reason"])
    if not np_inputs:
        pytest.skip("no array inputs (fill op)")
    if wrt is None:
        wrt = [i for i, x in enumerate(np_inputs)
               if np.issubdtype(x.dtype, np.floating)]
    if not wrt:
        pytest.skip("no float inputs to differentiate")

    from mxnet_tpu import autograd
    from mxnet_tpu.test_utils import numeric_grad, assert_almost_equal

    # fixed random projection of the first output: a plain .sum() is
    # structurally zero-gradient for normalization ops (the normalized
    # values sum to a constant) and would only compare FD noise
    with autograd.train_mode():
        out0 = _first(_run(name, np_inputs, kwargs)).asnumpy()
    proj = np.asarray(_rng(name + "/proj").randn(*out0.shape),
                      np.float32)

    def scalar_f(wrt_vals):
        full = list(np_inputs)
        for i, v in zip(wrt, wrt_vals):
            full[i] = v.astype(np.float32)
        # train_mode: mode-dependent ops (BatchNorm) must linearize the
        # same branch the recorded forward below uses
        with autograd.train_mode():
            out = _first(_run(name, full, kwargs))
        return float((out.asnumpy().astype(np.float64) * proj).sum())

    expected = numeric_grad(
        scalar_f, [np_inputs[i].astype(np.float64) for i in wrt],
        eps=1e-3)

    nd_inputs = [_to_nd(x) for x in np_inputs]
    for i in wrt:
        nd_inputs[i].attach_grad()
    with autograd.record():
        out = _first(getattr(opmod, name)(*nd_inputs, **kwargs))
        loss = (out * _to_nd(proj)).sum()
    loss.backward()
    for i, exp in zip(wrt, expected):
        assert_almost_equal(
            nd_inputs[i].grad.asnumpy(), exp.astype(np.float32),
            rtol=rtol, atol=atol,
            names=(f"{name}.grad[{i}]", f"{name}.fd[{i}]"))


def test_blockgrad_gradient_is_zero():
    """BlockGrad: identity forward, zero gradient BY CONTRACT (why it is
    excluded from the FD sweep)."""
    from mxnet_tpu import autograd
    x = _to_nd(np.ones((2, 3), np.float32))
    x.attach_grad()
    with autograd.record():
        y = (opmod.BlockGrad(x) * 3.0).sum()
    y.backward()
    assert float(np.abs(x.grad.asnumpy()).sum()) == 0.0


def test_sweep_budget():
    """The skip lists stay small and every skipped name really is a
    registered op (a rename must not silently disable its coverage)."""
    for k in list(FWD_SKIP) + list(GRAD_SKIP):
        assert k in CANONICAL, f"skip-list entry {k} not in registry"
    assert len(FWD_SKIP) <= 0.02 * len(CANONICAL)
    n_grad_skips = len(GRAD_SKIP) + sum(
        1 for s in SPECS.values()
        if isinstance(s, dict) and s.get("grad") is False)
    assert n_grad_skips <= 0.1 * len(CANONICAL), n_grad_skips
