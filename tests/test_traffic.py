"""Traffic plane, part 1: the workload simulator (docs/serving.md §11).

Trace generation must be seed-deterministic (same config -> byte-equal
JSONL), record/replay must round-trip bit-exactly, and the replay
harness must uphold its zero-hung-requests contract and map server
outcomes onto the typed status taxonomy.  Everything here runs without
a server or any XLA compile — ``replay_trace`` is driven with plain
callables.
"""
import os
import threading
import time

import numpy as np
import pytest

from mxnet_tpu.base import MXNetError
from mxnet_tpu.serving import traffic
from mxnet_tpu.serving.resilience import (DeadlineExceededError,
                                          ServerOverloadedError)
from mxnet_tpu.serving.traffic import (Trace, TraceConfig, TraceRequest,
                                       exponential_gap, generate_trace,
                                       predict_payload, prompt_tokens,
                                       replay_trace, summarize)


# ------------------------------------------------------------ generation
class TestGeneration:
    def test_deterministic_by_seed(self):
        cfg = dict(seed=11, duration_s=4.0, base_rate=25.0)
        a = generate_trace(TraceConfig(**cfg))
        b = generate_trace(TraceConfig(**cfg))
        assert a.to_jsonl() == b.to_jsonl()
        c = generate_trace(TraceConfig(seed=12, duration_s=4.0,
                                       base_rate=25.0))
        assert a.to_jsonl() != c.to_jsonl()

    def test_timeline_sorted_and_bounded(self):
        tr = generate_trace(TraceConfig(seed=2, duration_s=3.0))
        ts = [r.t for r in tr.requests]
        assert ts == sorted(ts)
        assert all(0.0 <= t < 3.0 + 1e-9 for t in ts)
        assert len(tr) == len(tr.requests) > 0

    def test_rate_roughly_honored(self):
        tr = generate_trace(TraceConfig(seed=3, duration_s=10.0,
                                        base_rate=40.0,
                                        diurnal_amplitude=0.0))
        # Poisson(400) — a 4-sigma band is ±80
        assert 300 <= len(tr) <= 500

    def test_burst_window_is_hotter(self):
        tr = generate_trace(TraceConfig(
            seed=4, duration_s=8.0, base_rate=20.0, burst_at=0.5,
            burst_x=10.0, burst_duration_s=2.0, diurnal_amplitude=0.0))
        burst = sum(1 for r in tr.requests if 4.0 <= r.t < 6.0)
        before = sum(1 for r in tr.requests if 0.0 <= r.t < 4.0)
        # 10x the rate over half the baseline span -> ~5x the count
        assert burst > 2 * before

    def test_tenant_skew_and_tiers(self):
        cfg = TraceConfig(seed=5, duration_s=10.0, base_rate=50.0,
                          tenants=8, tenant_skew=1.5)
        tr = generate_trace(cfg)
        counts = {}
        for r in tr.requests:
            counts[r.tenant] = counts.get(r.tenant, 0) + 1
            assert r.tier in cfg.tiers
        top = max(counts.values())
        # zipf(1.5) over 8 tenants concentrates far beyond uniform
        assert top > 2 * (len(tr) / 8)

    def test_mixed_ops_and_lengths(self):
        tr = generate_trace(TraceConfig(seed=6, duration_s=10.0,
                                        base_rate=40.0,
                                        generate_fraction=0.5))
        ops = {r.op for r in tr.requests}
        assert ops == {"predict", "generate"}
        for r in tr.requests:
            if r.op == "predict":
                assert 1 <= r.rows
            else:
                assert r.prompt_len >= 1 and r.max_new_tokens >= 1

    def test_prefix_clusters_mark_generate_rows(self):
        cfg = TraceConfig(seed=7, duration_s=10.0, base_rate=40.0,
                          generate_fraction=1.0, prefix_clusters=3,
                          prefix_share=0.6)
        tr = generate_trace(cfg)
        gen = [r for r in tr.requests if r.op == "generate"]
        clustered = [r for r in gen if r.prefix_group is not None]
        assert gen and 0.3 <= len(clustered) / len(gen) <= 0.9
        assert {r.prefix_group for r in clustered} \
            <= set(range(cfg.prefix_clusters))

    def test_shared_prefix_tokens_actually_shared(self):
        a = TraceRequest(0.0, "t0", "gold", "m", "generate",
                         prompt_len=32, max_new_tokens=4,
                         prefix_group=1, seed=10)
        b = TraceRequest(1.0, "t1", "free", "m", "generate",
                         prompt_len=40, max_new_tokens=4,
                         prefix_group=1, seed=11)
        c = TraceRequest(2.0, "t2", "free", "m", "generate",
                         prompt_len=40, max_new_tokens=4,
                         prefix_group=2, seed=12)
        ta, tb, tc = (prompt_tokens(r, prefix_len=16) for r in (a, b, c))
        assert ta[:16] == tb[:16]        # same cluster, same prefix
        assert ta[:16] != tc[:16]        # different cluster differs
        assert ta[16:] != tb[16:]        # suffixes are per-request

    def test_payload_deterministic(self):
        r = TraceRequest(0.0, "t", "gold", "m", "predict", rows=3,
                         seed=99)
        x, y = predict_payload(r), predict_payload(r)
        assert x.shape == (3, 2) and x.dtype == np.float32
        np.testing.assert_array_equal(x, y)

    def test_heavy_tail_processes(self):
        for proc in ("poisson", "lognormal", "pareto"):
            tr = generate_trace(TraceConfig(seed=8, duration_s=5.0,
                                            base_rate=30.0,
                                            process=proc))
            assert len(tr) > 10, proc
        with pytest.raises(MXNetError):
            TraceConfig(process="weibull")

    def test_env_seed_and_rate(self, monkeypatch):
        monkeypatch.setenv("MXNET_SERVING_TRACE_SEED", "77")
        monkeypatch.setenv("MXNET_SERVING_TRACE_RATE", "12.5")
        cfg = TraceConfig()
        assert cfg.seed == 77 and cfg.base_rate == 12.5


# -------------------------------------------------------------- arrivals
class TestExponentialGap:
    def test_is_the_one_poisson_primitive(self):
        # the dedupe contract with benchmark/bench_serving.py: same rng
        # call, so a seeded schedule is unchanged by the refactor
        r1, r2 = np.random.RandomState(0), np.random.RandomState(0)
        a = [float(r1.exponential(1.0 / 25.0)) for _ in range(64)]
        b = [exponential_gap(r2, 25.0) for _ in range(64)]
        assert a == b

    def test_positive_and_mean(self):
        rng = np.random.RandomState(123)
        gaps = [exponential_gap(rng, 50.0) for _ in range(4000)]
        assert min(gaps) > 0
        assert abs(np.mean(gaps) - 1.0 / 50.0) < 0.002


# ------------------------------------------------------------ record/replay
class TestRoundTrip:
    def test_save_load_bit_exact(self, tmp_path):
        tr = generate_trace(TraceConfig(seed=21, duration_s=5.0))
        p = os.path.join(str(tmp_path), "trace.jsonl")
        tr.save(p)
        back = Trace.load(p)
        assert back == tr
        assert back.to_jsonl() == tr.to_jsonl()
        # and a second save of the loaded trace is byte-identical
        p2 = os.path.join(str(tmp_path), "again.jsonl")
        back.save(p2)
        with open(p, "rb") as f1, open(p2, "rb") as f2:
            assert f1.read() == f2.read()

    def test_header_carries_config(self):
        cfg = TraceConfig(seed=5, duration_s=2.0, base_rate=9.0,
                          burst_x=3.0)
        tr = generate_trace(cfg)
        assert tr.header["seed"] == 5
        assert tr.header["base_rate"] == 9.0
        assert tr.header["burst_x"] == 3.0

    def test_load_rejects_garbage(self, tmp_path):
        p = os.path.join(str(tmp_path), "bad.jsonl")
        with open(p, "w") as f:
            f.write('{"kind": "not-a-header"}\n')
        with pytest.raises(MXNetError):
            Trace.load(p)


class TestReplay:
    def _trace(self, n=12, gap=0.01):
        reqs = [TraceRequest(i * gap, f"t{i % 3}",
                             ("gold", "silver", "free")[i % 3], "m",
                             "predict", rows=1, seed=i)
                for i in range(n)]
        return Trace({"duration_s": n * gap}, reqs)

    def test_all_ok_and_ordered(self):
        tr = self._trace()
        calls = []
        lock = threading.Lock()

        def call(req):
            with lock:
                calls.append(req.tenant)
            return {"echo": req.seed}

        recs, wall = replay_trace(tr, call, clients=3, speed=4.0,
                                  timeout_s=5.0)
        assert len(recs) == len(tr)
        assert all(r["status"] == "ok" for r in recs)
        assert [r["index"] for r in recs] == list(range(len(tr)))
        assert recs[0]["echo"] == 0      # call() extras merge in
        assert len(calls) == len(tr)
        assert wall > 0

    def test_statuses_are_typed(self):
        tr = self._trace(n=3, gap=0.0)

        def call(req):
            if req.seed == 0:
                raise ServerOverloadedError("m", 1, "full")
            if req.seed == 1:
                raise DeadlineExceededError("op", 0.1, "q")
            raise MXNetError("boom")

        recs, _ = replay_trace(tr, call, clients=1, speed=100.0,
                               attempts=2, timeout_s=2.0)
        assert [r["status"] for r in recs] == ["shed", "deadline",
                                               "error"]
        assert recs[0]["error"] == "ServerOverloadedError"

    def test_retry_after_is_honored(self):
        tr = self._trace(n=1)
        state = {"n": 0}

        def call(req):
            state["n"] += 1
            if state["n"] < 3:
                raise ServerOverloadedError("m", 1, "warming")
            return None

        recs, _ = replay_trace(tr, call, clients=1, speed=100.0,
                               attempts=4, timeout_s=5.0)
        assert recs[0]["status"] == "ok"
        assert state["n"] == 3           # two sheds, then success

    def test_speed_compresses_wall_time(self):
        tr = self._trace(n=10, gap=0.05)    # 0.5s of timeline
        t0 = time.monotonic()
        replay_trace(tr, lambda r: None, clients=2, speed=10.0,
                     timeout_s=5.0)
        assert time.monotonic() - t0 < 0.45

    def test_rejects_bad_speed(self):
        with pytest.raises(MXNetError):
            replay_trace(self._trace(1), lambda r: None, speed=0.0)


# -------------------------------------------------------------- summarize
class TestSummarize:
    def _rec(self, status="ok", tier="gold", latency=0.01, ttft=None):
        r = {"status": status, "tier": tier, "latency_s": latency}
        if ttft is not None:
            r["ttft_s"] = ttft
        return r

    def test_sheds_count_against_attainment(self):
        recs = [self._rec() for _ in range(8)] \
            + [self._rec(status="shed", tier="free") for _ in range(2)]
        s = summarize(recs, wall_s=2.0, latency_slo_s=0.1)
        assert s["requests"] == 10 and s["ok"] == 8 and s["shed"] == 2
        assert s["attainment"] == pytest.approx(0.8)
        assert s["goodput_rps"] == pytest.approx(4.0)
        assert s["by_tier"]["free"]["shed"] == 2

    def test_slo_miss_is_not_goodput(self):
        recs = [self._rec(latency=0.01), self._rec(latency=5.0)]
        s = summarize(recs, wall_s=1.0, latency_slo_s=0.1)
        assert s["ok"] == 2 and s["slo_ok"] == 1

    def test_ttft_target_applies_to_generate(self):
        recs = [self._rec(ttft=0.01), self._rec(ttft=2.0)]
        s = summarize(recs, wall_s=1.0, ttft_slo_s=0.1)
        assert s["slo_ok"] == 1
        assert s["ttft_p50_s"] > 0

    def test_smoke_against_generated_trace(self):
        # the whole loop: generate -> replay (trivial server) -> score
        tr = generate_trace(TraceConfig(seed=31, duration_s=1.0,
                                        base_rate=30.0))
        recs, wall = replay_trace(tr, lambda r: None, clients=4,
                                  speed=20.0, timeout_s=5.0)
        s = summarize(recs, wall_s=wall, latency_slo_s=1.0)
        assert s["attainment"] == pytest.approx(1.0)
        assert set(s["by_tier"]) <= {"gold", "silver", "free"}


# ------------------------------------------------- replay determinism
class TestReplayDeterminism:
    """ISSUE-18: the BENCH_r07 burst trace replayed twice against
    identical deterministic twins must score byte-identically —
    the regression that keeps ambient entropy out of the
    generate -> replay -> summarize chain (mxlint
    determinism-soundness is the static twin of this test)."""

    def _bench_r07_config(self):
        # mirror benchmark/bench_traffic.py run(): the r07 burst shape
        # at 1/6 duration so the test stays inside the tier-1 budget
        duration = 1.0
        return TraceConfig(
            seed=0, duration_s=duration, base_rate=14.0,
            process="lognormal", models=("lm",), generate_fraction=1.0,
            tenants=6, burst_at=0.45, burst_x=10.0,
            burst_duration_s=duration * 0.25, prompt_max=16,
            output_max=10, output_mean=5.0)

    @staticmethod
    def _twin_call(req):
        # a deterministic server twin: outcome and every measured field
        # are pure functions of the request, overriding the wall-clock
        # measurements via the rec.update(info) contract
        lat = 0.001 + (req.prompt_len + req.max_new_tokens) * 1e-4
        return {"latency_s": lat, "ttft_s": lat * 0.25,
                "start_s": req.t}

    def _replay_summary(self, trace):
        import json
        recs, _ = replay_trace(trace, self._twin_call, clients=6,
                               speed=50.0, timeout_s=10.0)
        s = summarize(recs, wall_s=trace.duration_s,
                      latency_slo_s=0.05, ttft_slo_s=0.02)
        return json.dumps(s, sort_keys=True)

    def test_bench_r07_replay_is_byte_identical(self):
        cfg = self._bench_r07_config()
        tr_a = generate_trace(cfg)
        tr_b = generate_trace(cfg)
        assert tr_a.to_jsonl() == tr_b.to_jsonl()
        assert self._replay_summary(tr_a) == self._replay_summary(tr_b)
