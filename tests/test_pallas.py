"""Flash-attention Pallas kernel tests (CPU interpreter mode; same code
compiles on TPU).  Oracle = dense softmax attention, the reference's
_contrib_interleaved_matmul_* chain semantics."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.ops.pallas_kernels import flash_attention


def _dense_ref(q, k, v, lens=None, causal=False):
    D = q.shape[-1]
    s = jnp.einsum("bqd,bkd->bqk", q, k) / np.sqrt(D)
    Lq, Lk = q.shape[1], k.shape[1]
    mask = jnp.ones((q.shape[0], Lq, Lk), bool)
    if lens is not None:
        mask &= (jnp.arange(Lk)[None, None, :] < lens[:, None, None])
    if causal:
        mask &= (jnp.arange(Lk)[None, None, :]
                 <= jnp.arange(Lq)[None, :, None])
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v)


def _rand_qkv(BH=4, L=48, D=16, seed=0):
    rs = np.random.RandomState(seed)
    return tuple(jnp.asarray(rs.randn(BH, L, D), jnp.float32)
                 for _ in range(3))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("with_lens", [False, True])
def test_flash_forward_matches_dense(causal, with_lens):
    q, k, v = _rand_qkv()
    lens = jnp.asarray([48, 17, 32, 5], jnp.int32) if with_lens else None
    out = flash_attention(q, k, v, lengths=lens, causal=causal,
                          block_q=16, block_k=16)
    ref = _dense_ref(q, k, v, lens, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5)


def test_flash_nondivisible_seq_padding():
    """Lq=37 not a multiple of any block size: wrapper pads + slices."""
    q, k, v = _rand_qkv(BH=2, L=37, D=8, seed=3)
    out = flash_attention(q, k, v, block_q=16, block_k=16)
    ref = _dense_ref(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5)


def test_flash_grads_match_dense():
    q, k, v = _rand_qkv(seed=7)
    lens = jnp.asarray([48, 20, 48, 9], jnp.int32)
    cot = jnp.asarray(np.random.RandomState(8).randn(*q.shape),
                      jnp.float32)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, lengths=lens, causal=True,
                                block_q=16, block_k=16) * cot).sum()

    def loss_dense(q, k, v):
        return (_dense_ref(q, k, v, lens, True) * cot).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4)


def test_flash_selfatt_op_matches_interleaved_chain():
    """F.flash_selfatt == interleaved qk -> masked softmax -> valatt."""
    L, B, H, D = 24, 3, 2, 8
    rs = np.random.RandomState(0)
    qkv = nd.array(rs.randn(L, B, H * 3 * D).astype(np.float32))
    valid = nd.array(np.array([24, 10, 17], np.float32))

    flash = nd.flash_selfatt(qkv, valid, heads=H)

    scores = nd.interleaved_matmul_selfatt_qk(qkv, heads=H)  # (B*H, L, L)
    neg = np.full((B, 1, 1, L), 0.0, np.float32)
    steps = np.arange(L)
    for b in range(B):
        neg[b, 0, 0, steps >= int(valid.asnumpy()[b])] = -1e30
    mask = nd.array(np.broadcast_to(neg, (B, H, L, L))
                    .reshape(B * H, L, L).copy())
    att = nd.softmax(scores + mask, axis=-1)
    dense = nd.interleaved_matmul_selfatt_valatt(qkv, att, heads=H)
    np.testing.assert_allclose(flash.asnumpy(), dense.asnumpy(),
                               atol=1e-4)


def test_bert_use_flash_matches_dense():
    """BERT with use_flash=True == dense-mask BERT, same params."""
    from mxnet_tpu import models
    kwargs = dict(vocab_size=64, units=32, hidden_size=64, num_layers=2,
                  num_heads=4, max_length=32, dropout=0.0)
    mx.random.seed(0)
    dense_model = models.get_bert_model("bert_12_768_12", **kwargs)
    dense_model.initialize()
    flash_model = models.get_bert_model("bert_12_768_12", use_flash=True,
                                        **kwargs)
    flash_model.initialize()
    # copy params dense -> flash (names differ only by block prefix)
    src = {k.split("bertmodel", 1)[-1].split("_", 1)[-1]: v
           for k, v in dense_model.collect_params().items()}
    for name, p in flash_model.collect_params().items():
        key = name.split("bertmodel", 1)[-1].split("_", 1)[-1]
        p.set_data(src[key].data())

    rs = np.random.RandomState(1)
    B, L = 2, 24
    inputs = nd.array(rs.randint(0, 64, (B, L)), dtype="int32")
    tok = nd.zeros((B, L), dtype="int32")
    valid = nd.array(np.array([24, 11], np.float32))
    seq_d, pool_d = dense_model(inputs, tok, valid)
    seq_f, pool_f = flash_model(inputs, tok, valid)
    # padded positions attend to garbage by design; compare valid rows
    for b, vl in enumerate([24, 11]):
        np.testing.assert_allclose(seq_f.asnumpy()[b, :vl],
                                   seq_d.asnumpy()[b, :vl], atol=2e-4)
    np.testing.assert_allclose(pool_f.asnumpy(), pool_d.asnumpy(),
                               atol=2e-4)


def test_runtime_reports_pallas_honestly():
    feats = mx.runtime.Features()
    assert feats.is_enabled("PALLAS")  # interpret mode counts as available

def test_flash_bf16_inputs_close_to_fp32_dense():
    """The r3 kernel keeps q/k/v in bf16 for the MXU dots (fp32 softmax
    stats): outputs must stay within bf16-grade tolerance of the fp32
    dense oracle."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.pallas_kernels import flash_attention
    rng = np.random.RandomState(0)
    BH, L, D = 4, 64, 16
    qf = rng.randn(BH, L, D).astype(np.float32)
    kf = rng.randn(BH, L, D).astype(np.float32)
    vf = rng.randn(BH, L, D).astype(np.float32)
    out = np.asarray(flash_attention(
        jnp.asarray(qf, jnp.bfloat16), jnp.asarray(kf, jnp.bfloat16),
        jnp.asarray(vf, jnp.bfloat16), causal=True)).astype(np.float32)
    s = np.einsum("bqd,bkd->bqk", qf, kf) / np.sqrt(D)
    s[:, np.triu(np.ones((L, L), bool), k=1)] = -1e30
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = np.einsum("bqk,bkd->bqd", p, vf)
    assert np.abs(out - ref).max() < 0.06, np.abs(out - ref).max()


def test_flash_block_defaults_table():
    from mxnet_tpu.ops.pallas_kernels import _default_blocks
    assert _default_blocks(128, 128, 64) == (128, 128)
    assert _default_blocks(512, 512, 64) == (512, 512)
    assert _default_blocks(2048, 2048, 64) == (1024, 1024)
    import os
    os.environ["MXNET_FLASH_BLOCK_Q"] = "64"
    os.environ["MXNET_FLASH_BLOCK_K"] = "32"
    try:
        assert _default_blocks(512, 512, 64) == (64, 32)
    finally:
        del os.environ["MXNET_FLASH_BLOCK_Q"]
        del os.environ["MXNET_FLASH_BLOCK_K"]


def test_flash_sliding_window_matches_dense():
    """Causal sliding-window attention (window w: keys in [q-w+1, q])
    matches the dense masked oracle, forward and grads."""
    q, k, v = _rand_qkv(BH=2, L=48, D=8, seed=11)
    w = 12

    def dense_win(q, k, v):
        D = q.shape[-1]
        s = jnp.einsum("bqd,bkd->bqk", q, k) / np.sqrt(D)
        qi = jnp.arange(48)[:, None]
        ki = jnp.arange(48)[None, :]
        mask = (ki <= qi) & (ki >= qi - (w - 1))
        s = jnp.where(mask[None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bqk,bkd->bqd", p, v)

    out = flash_attention(q, k, v, causal=True, window=w,
                          block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(dense_win(q, k, v)), atol=1e-5)

    cot = jnp.asarray(np.random.RandomState(12).randn(*q.shape),
                      jnp.float32)
    gf = jax.grad(lambda q, k, v: (flash_attention(
        q, k, v, causal=True, window=w, block_q=16, block_k=16)
        * cot).sum(), argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(lambda q, k, v: (dense_win(q, k, v) * cot).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4)


def test_flash_window_requires_causal():
    import pytest
    from mxnet_tpu.base import MXNetError
    q, k, v = _rand_qkv(BH=1, L=16, D=8)
    with pytest.raises(MXNetError, match="causal"):
        flash_attention(q, k, v, causal=False, window=4)
