"""mxshape tests: the symbolic shape/dtype lattice, the three passes it
powers (shape-soundness, dtype-promotion, recompile-churn), the
interprocedural witness chains, and the baseline/--changed CLI modes
(ISSUE-5).

Pure-AST + stdlib: no jax import, so the whole file costs a few seconds
(tier-1 budget discipline — ROADMAP.md; the <15s satellite bound).
"""
import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.mxlint import lint_sources                        # noqa: E402
from tools.mxlint.baseline import (                          # noqa: E402
    apply_baseline, key_of, load_baseline, record, save_baseline)
from tools.mxlint.shapes import rules, _join, Arr, DimV, ShapeV  # noqa: E402

R = rules()


def run(src, select=None, path="mxnet_tpu/fixture.py", extra=None):
    sources = {path: textwrap.dedent(src)}
    for p, s in (extra or {}).items():
        sources[p] = textwrap.dedent(s)
    return lint_sources(sources, select=select)


def ids(issues):
    return [i.pass_id for i in issues]


# ================================================== the dim lattice itself
def test_dim_literals_and_symbols():
    assert R.lit(4).concrete == 4
    assert R.sym("B").concrete is None
    assert R.lit(3) == R.lit(3)
    assert R.lit(3) != R.sym("B")
    assert R.fmt_dim(R.sym("B")) == "B"
    assert R.fmt_dim(None) == "?"


def test_dim_mul_div_cancellation():
    B, H = R.sym("B"), R.sym("H")
    prod = R.dim_mul(B, H)
    assert R.fmt_dim(prod) == "B*H"
    # (B*H) / H == B — exact symbolic division
    assert R.dim_eq(R.dim_div(prod, H), B) is True
    # (4*B) / 2 == 2*B
    four_b = R.dim_mul(R.lit(4), B)
    assert R.dim_eq(R.dim_div(four_b, R.lit(2)),
                    R.dim_mul(R.lit(2), B)) is True
    assert R.dim_mul(None, B) is None


def test_dim_eq_is_three_valued():
    B, L = R.sym("B"), R.sym("L")
    assert R.dim_eq(B, B) is True
    # symbols are >= 1, so 2*B == 3*B is PROVABLY false…
    assert R.dim_eq(R.dim_mul(R.lit(2), B),
                    R.dim_mul(R.lit(3), B)) is False
    # …but B vs L is simply unknown
    assert R.dim_eq(B, L) is None
    assert R.dim_eq(B, None) is None
    assert R.dim_eq(R.lit(0), R.lit(0)) is True
    assert R.dim_eq(R.lit(0), B) is False


def test_dim_add_only_concrete():
    assert R.dim_add(R.lit(2), R.lit(3)).concrete == 5
    assert R.dim_add(R.sym("B"), R.lit(1)) is None


def test_product_and_fmt_shape():
    B = R.sym("B")
    p = R.product((R.lit(2), B, R.lit(3)))
    assert R.dim_eq(p, R.dim_mul(R.lit(6), B)) is True
    assert R.product((B, None)) is None
    assert R.fmt_shape((R.lit(2), B, None)) == "(2, B, ?)"
    assert R.fmt_shape(None) == "(?)"


def test_abstract_value_join():
    """The interpreter's join (control-flow merge): equal dims survive,
    differing dims widen to ?, dtype mismatches widen to unknown."""
    B = R.sym("B")
    a = _join(Arr((B, R.lit(4)), "float32"), Arr((B, R.lit(8)), "float32"))
    assert a.shape == (B, None) and a.dtype == "float32"
    a = _join(Arr((B,), "float32"), Arr((B,), "bfloat16"))
    assert a.dtype is None
    d = _join(DimV(R.lit(3)), DimV(R.lit(3)))
    assert d.dim.concrete == 3
    d = _join(DimV(R.lit(3)), DimV(R.lit(4)))
    assert d.dim is None
    s = _join(ShapeV((B, R.lit(2))), ShapeV((B, R.lit(3))))
    assert s.dims == (B, None)
    # rank mismatch / unrelated kinds go to top
    assert _join(Arr((B,), "float32"), Arr((B, B), "float32")).shape is None


# ===================================================== the shape checkers
def test_check_reshape_symbolic_feasible_and_infeasible():
    B, L = R.sym("B"), R.sym("L")
    HnD = R.dim_mul(R.lit(8), B)
    # (L, 8*B) -> (L, B, 8): products cancel, feasible
    out = R.check_reshape((L, HnD), [L, B, R.lit(8)])
    assert out == (L, B, R.lit(8))
    # (L, B) -> (L, B, 2): ratio is 2, provably infeasible
    with pytest.raises(R.ShapeError):
        R.check_reshape((L, B), [L, B, R.lit(2)])
    with pytest.raises(R.ShapeError):
        R.check_reshape((R.lit(3), R.lit(4)), [R.lit(5), R.lit(2)])
    # unknown operand stays quiet
    assert R.check_reshape(None, [R.lit(5), R.lit(2)]) == (R.lit(5),
                                                          R.lit(2))


def test_check_reshape_minus_one_inference():
    out = R.check_reshape((R.lit(6), R.lit(4)), [R.lit(3), -1])
    assert out == (R.lit(3), R.lit(8))
    # -1 binds a clean symbolic factor too
    B = R.sym("B")
    out = R.check_reshape((B, R.lit(4)), [-1, R.lit(2)])
    assert R.dim_eq(out[0], R.dim_mul(R.lit(2), B)) is True
    with pytest.raises(R.ShapeError):     # 12 / 5 is not an integer
        R.check_reshape((R.lit(3), R.lit(4)), [R.lit(5), -1])
    with pytest.raises(R.ShapeError):     # two -1s
        R.check_reshape((R.lit(8),), [-1, -1])


def test_check_transpose():
    B = R.sym("B")
    assert R.check_transpose((B, R.lit(4)), None) == (R.lit(4), B)
    assert R.check_transpose((B, R.lit(4), R.lit(2)), (2, 0, 1)) == \
        (R.lit(2), B, R.lit(4))
    with pytest.raises(R.ShapeError):
        R.check_transpose((B, R.lit(4)), (0, 0))
    with pytest.raises(R.ShapeError):
        R.check_transpose((B, R.lit(4)), (0, 1, 2))
    with pytest.raises(R.ShapeError):
        R.check_transpose((B, R.lit(4)), (0, 5))


def test_broadcast_join():
    B = R.sym("B")
    assert R.broadcast((B, R.lit(1)), (B, R.lit(4))) == (B, R.lit(4))
    assert R.broadcast((R.lit(4),), (B, R.lit(4))) == (B, R.lit(4))
    with pytest.raises(R.ShapeError):
        R.broadcast((R.lit(3),), (R.lit(5),))
    # a symbol could still be 1: unknown, not an error
    out = R.broadcast((B,), (R.lit(5),))
    assert out == (None,)


def test_check_matmul_and_einsum():
    B, K = R.sym("B"), R.sym("K")
    out = R.check_matmul((B, R.lit(3), K), (K, R.lit(7)))
    assert out == (B, R.lit(3), R.lit(7))
    with pytest.raises(R.ShapeError):
        R.check_matmul((R.lit(3), R.lit(5)), (R.lit(4), R.lit(2)))
    out = R.check_einsum("bij,bjk->bik",
                         [(B, R.lit(2), K), (B, K, R.lit(5))])
    assert out == (B, R.lit(2), R.lit(5))
    with pytest.raises(R.ShapeError):
        R.check_einsum("ij,jk->ik",
                       [(R.lit(2), R.lit(3)), (R.lit(4), R.lit(5))])
    with pytest.raises(R.ShapeError):     # rank mismatch
        R.check_einsum("ijk->ik", [(R.lit(2), R.lit(3))])
    assert R.check_einsum("b...->b", [(B, R.lit(2))]) is None  # quiet


def test_reduce_and_concat_shapes():
    B = R.sym("B")
    assert R.reduce_shape((B, R.lit(4)), 1) == (B,)
    assert R.reduce_shape((B, R.lit(4)), 1, keepdims=True) == \
        (B, R.lit(1))
    with pytest.raises(R.ShapeError):
        R.reduce_shape((B, R.lit(4)), 5)
    out = R.concat_shapes([(B, R.lit(2)), (B, R.lit(3))], 1)
    assert out == (B, R.lit(5))
    with pytest.raises(R.ShapeError):
        R.concat_shapes([(R.lit(2), R.lit(2)), (R.lit(3), R.lit(2))], 1)


# ==================================================== the dtype lattice
def test_promote_follows_jax_lattice():
    assert R.promote("float32", "float32") == "float32"
    assert R.promote("float32", "float64") == "float64"
    assert R.promote("bfloat16", "float16") == "float32"
    assert R.promote("int32", "int64") == "int64"
    assert R.promote("bool", "int32") == "int32"
    # weak python scalars stay weak against arrays
    assert R.promote("float", "float32") == "float32"
    assert R.promote("float", "bfloat16") == "bfloat16"
    assert R.promote("int", "uint8") == "uint8"
    assert R.promote("int64", "float") == "float"
    assert R.promote(None, "float32") is None
    assert R.promote("float32", "not_a_dtype") is None


# ============================================== shape-soundness fixtures
def test_shape_soundness_infeasible_reshape_in_jit():
    issues = run("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            a = jnp.zeros((3, 4))
            return a.reshape(5, 2)
    """, select=["shape-soundness"])
    assert ids(issues) == ["shape-soundness"]
    assert "cannot tile the input" in issues[0].message


def test_shape_soundness_seeding_trick_in_hybrid_forward():
    """`L, B, HnD = x.shape` refines an unknown-rank input to named
    symbols; the infeasible extra factor is then provable."""
    issues = run("""
        class Net:
            def hybrid_forward(self, F, x):
                L, B, HnD = x.shape
                return x.reshape(L, B, 4, HnD)
    """, select=["shape-soundness"])
    assert ids(issues) == ["shape-soundness"]
    assert "4*B*HnD*L" in issues[0].message


def test_shape_soundness_feasible_symbolic_juggling_is_quiet():
    """The ops/contrib.py interleaved-attention pattern: symbolic
    factors cancel, so nothing fires."""
    issues = run("""
        import jax

        @jax.jit
        def attn(x, heads=4):
            L, B, HnD = x.shape
            D = HnD // (heads * 2)
            y = x.reshape(L, B, heads, 2, D)
            return y.transpose(1, 2, 0, 3, 4)
    """, select=["shape-soundness"])
    assert issues == []


def test_shape_soundness_transpose_matmul_einsum_broadcast_unpack():
    issues = run("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            y = x.reshape(4, 8)
            t = y.transpose(0, 0)
            m = jnp.ones((3, 5)) @ jnp.ones((4, 2))
            e = jnp.einsum("ij,jk->ik", jnp.ones((2, 3)), jnp.ones((4, 5)))
            b = jnp.ones((3, 4)) + jnp.ones((3, 5))
            a, bb, c = y.shape
            return t, m, e, b, a
    """, select=["shape-soundness"])
    assert ids(issues) == ["shape-soundness"] * 5
    msgs = " | ".join(i.message for i in issues)
    assert "not a permutation" in msgs
    assert "matmul contraction mismatch" in msgs
    assert "einsum axis 'j'" in msgs
    assert "broadcast-compatible" in msgs
    assert "unpacking the rank-2 shape" in msgs


def test_shape_soundness_registry_op_body_is_a_surface():
    issues = run("""
        from .registry import register

        @register("bad_op", num_inputs=1)
        def bad_op(x):
            L, B = x.shape
            return x.reshape(L, B, 2)
    """, select=["shape-soundness"], path="mxnet_tpu/ops/fixture.py")
    assert ids(issues) == ["shape-soundness"]


def test_shape_soundness_suppression():
    issues = run("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            a = jnp.zeros((3, 4))
            return a.reshape(5, 2)  # mxlint: disable=shape-soundness (demo)
    """, select=["shape-soundness"])
    assert issues == []


def test_shape_soundness_interprocedural_witness_chain():
    """A reshape broken only by the caller's facts anchors at the
    traced call site with a `via helper (...)` chain."""
    issues = run("""
        import jax

        def _merge(y, h):
            return y.reshape(y.shape[0], h * 2)

        @jax.jit
        def f(x):
            a, b = x.shape
            return _merge(x, b)
    """, select=["shape-soundness"])
    assert ids(issues) == ["shape-soundness"]
    assert issues[0].message.startswith("via _merge (mxnet_tpu/fixture.py:")
    assert issues[0].line == 10      # the call site, not the helper body


def test_shape_soundness_checked_helper_owns_its_own_finding():
    """A helper that is itself a traced surface keeps its direct
    finding; the caller does not duplicate it (one bug = one issue)."""
    issues = run("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def broken():
            return jnp.zeros((3, 4)).reshape(5, 2)

        @jax.jit
        def f(x):
            return broken() + x
    """, select=["shape-soundness"])
    assert len(issues) == 1
    assert issues[0].line == 7


# ============================================== dtype-promotion fixtures
def test_dtype_promotion_silent_float64():
    issues = run("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            y = x.astype(jnp.float32)
            scale = jnp.ones((3,), dtype=jnp.float64)
            return y * scale
    """, select=["dtype-promotion"])
    assert ids(issues) == ["dtype-promotion"]
    assert "silent float64 promotion" in issues[0].message


def test_dtype_promotion_weak_python_scalar_is_quiet():
    issues = run("""
        import jax

        @jax.jit
        def f(x):
            y = x.astype("float32")
            return y * 2.0 + 1.0
    """, select=["dtype-promotion"])
    assert issues == []


def test_dtype_promotion_int64_upcast():
    issues = run("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            idx = x.astype(jnp.int32)
            big = jnp.ones((3,), dtype=jnp.int64)
            return idx + big
    """, select=["dtype-promotion"])
    assert ids(issues) == ["dtype-promotion"]
    assert "silent int64 upcast" in issues[0].message


def test_dtype_promotion_bf16_accumulation():
    issues = run("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            y = x.astype(jnp.bfloat16)
            return jnp.sum(y, axis=0)
    """, select=["dtype-promotion"])
    assert ids(issues) == ["dtype-promotion"]
    assert "accumulates in bfloat16" in issues[0].message


def test_dtype_promotion_quant_core_scoped_exemption():
    """ISSUE-10 satellite: narrow-accumulation findings anchored in
    mxnet_tpu/quantize.py are intentional-by-contract (the quant ->
    accumulate-in-f32 -> dequant core widens before every accumulate;
    a deliberate 16-bit accumulate there is part of the quant
    codebook, not a bug) — while the SAME code at any other path still
    flags, and non-accumulation dtype findings still flag even in the
    quant core."""
    accum_src = """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            y = x.astype(jnp.bfloat16)
            return jnp.sum(y, axis=0)
    """
    # the same source: exempt under the quant-core path ...
    assert run(accum_src, select=["dtype-promotion"],
               path="mxnet_tpu/quantize.py") == []
    # ... still a finding anywhere else
    assert ids(run(accum_src, select=["dtype-promotion"],
                   path="mxnet_tpu/other.py")) == ["dtype-promotion"]
    # silent-f64 widening is NOT covered by the exemption
    f64_src = """
        import jax
        import numpy as np

        @jax.jit
        def g(x):
            y = x.astype("float32")
            return y * np.float64(2.0)
    """
    issues = run(f64_src, select=["dtype-promotion"],
                 path="mxnet_tpu/quantize.py")
    assert ids(issues) == ["dtype-promotion"]


def test_dtype_promotion_explicit_accum_dtype_is_quiet():
    issues = run("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            y = x.astype(jnp.bfloat16)
            wide = jnp.sum(y, axis=0, dtype=jnp.float32)
            dot = y @ y.T                 # MXU accumulates dots in f32
            mx = jnp.max(y, axis=0)       # compare, not accumulate
            return wide, dot, mx
    """, select=["dtype-promotion"])
    assert issues == []


def test_dtype_promotion_witness_chain_and_suppression():
    issues = run("""
        import jax
        import jax.numpy as jnp

        def _scale(y):
            return y * jnp.ones((3,), dtype=jnp.float64)

        @jax.jit
        def f(x):
            return _scale(x.astype(jnp.float32))

        @jax.jit
        def g(x):
            # mxlint: disable=dtype-promotion (f64 demanded by checkpoint)
            return _scale(x.astype(jnp.float32))
    """, select=["dtype-promotion"])
    assert ids(issues) == ["dtype-promotion"]
    assert issues[0].message.startswith("via _scale (")


# ============================================== recompile-churn fixtures
def test_recompile_churn_static_arg_from_request():
    issues = run("""
        import jax
        from functools import partial

        @partial(jax.jit, static_argnums=(1,))
        def kernel(x, n):
            return x[:n]

        def handle(request, x):
            n = int(request)
            return kernel(x, n)
    """, select=["recompile-churn"])
    assert ids(issues) == ["recompile-churn"]
    assert "static argument 'n'" in issues[0].message
    assert "request-scoped parameter 'request'" in issues[0].message


def test_recompile_churn_data_dependent_dimension():
    issues = run("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def kernel(x):
            return x * 2

        def handle(request):
            n = len(request)
            pad = jnp.zeros((n, 4))
            return kernel(pad)
    """, select=["recompile-churn"])
    assert ids(issues) == ["recompile-churn"]
    assert "new trace signature" in issues[0].message


def test_recompile_churn_bucketed_dimension_is_washed():
    issues = run("""
        import jax
        import jax.numpy as jnp
        from mxnet_tpu.serving.batcher import next_bucket

        @jax.jit
        def kernel(x):
            return x * 2

        def handle(request):
            n = next_bucket(len(request))
            pad = jnp.zeros((n, 4))
            return kernel(pad)
    """, select=["recompile-churn"])
    assert issues == []


def test_recompile_churn_self_config_is_bounded():
    issues = run("""
        import jax
        from functools import partial

        @partial(jax.jit, static_argnums=(1,))
        def kernel(x, n):
            return x[:n]

        class Model:
            def predict(self, x):
                return kernel(x, self.max_len)
    """, select=["recompile-churn"])
    assert issues == []


def test_recompile_churn_witness_chain_through_helper():
    issues = run("""
        import jax
        from functools import partial

        @partial(jax.jit, static_argnums=(1,))
        def kernel(x, n):
            return x[:n]

        def _prep(req):
            return int(req) + 1

        def handle(request, x):
            n = _prep(request)
            return kernel(x, n)
    """, select=["recompile-churn"])
    assert ids(issues) == ["recompile-churn"]
    assert "via _prep (mxnet_tpu/fixture.py:13)" in issues[0].message


def test_recompile_churn_suppression_and_nonliteral_statics():
    issues = run("""
        import jax
        from functools import partial

        _NUMS = (1,)

        @partial(jax.jit, static_argnums=_NUMS)
        def kernel(x, n):
            return x[:n]

        @partial(jax.jit, static_argnums=(1,))
        def kernel2(x, n):
            return x[:n]

        def handle(request, x):
            a = kernel(x, int(request))   # statics unknown: stay quiet
            # mxlint: disable=recompile-churn (request len is an enum of 2)
            b = kernel2(x, int(request))
            return a, b
    """, select=["recompile-churn"])
    assert issues == []


# ============================================ the ISSUE-5 acceptance gate
def test_acceptance_fixture_one_finding_each_with_witness():
    """One fixture with an infeasible reshape, a silent dtype promotion
    and an unbounded-signature jit call site: exactly one finding per
    pass, each carrying a witness chain."""
    issues = run("""
        import jax
        import jax.numpy as jnp
        from functools import partial

        def _reshape_helper(y, b):
            return y.reshape(y.shape[0], 2 * b)

        def _widen_helper(y):
            return y + jnp.ones((4,), dtype=jnp.float64)

        def _count_helper(request):
            return len(request)

        @jax.jit
        def traced(x):
            a, b = x.shape
            bad_shape = _reshape_helper(x, b)
            bad_dtype = _widen_helper(x.astype(jnp.float32))
            return bad_shape, bad_dtype

        @partial(jax.jit, static_argnums=(1,))
        def kernel(x, n):
            return x[:n]

        def serve(request, x):
            return kernel(x, _count_helper(request))
    """)
    by_pass = {i.pass_id: i for i in issues}
    assert sorted(by_pass) == ["dtype-promotion", "recompile-churn",
                               "shape-soundness"]
    assert len(issues) == 3
    assert "via _reshape_helper (" in by_pass["shape-soundness"].message
    assert "via _widen_helper (" in by_pass["dtype-promotion"].message
    assert "via _count_helper (" in by_pass["recompile-churn"].message


# ======================================================= baseline ratchet
def _mkissues(*keys):
    """Fabricate sorted issues from (pass, path, msg) triples."""
    from tools.mxlint.core import Issue
    out = [Issue(p, f, i + 1, 0, m)
           for i, (p, f, m) in enumerate(keys)]
    out.sort(key=lambda i: i.sort_key())
    return out


def test_baseline_record_and_apply():
    issues = _mkissues(("p", "a.py", "msg1"), ("p", "a.py", "msg1"),
                       ("q", "b.py", "msg2"))
    counts = record(issues)
    assert counts == {"p|a.py|msg1": 2, "q|b.py|msg2": 1}
    new, baselined, stale = apply_baseline(issues, counts)
    assert new == [] and baselined == 3 and stale == []
    # one extra occurrence of a baselined key IS a new finding
    extra = _mkissues(("p", "a.py", "msg1"), ("p", "a.py", "msg1"),
                      ("p", "a.py", "msg1"), ("q", "b.py", "msg2"))
    new, baselined, stale = apply_baseline(extra, counts)
    assert len(new) == 1 and key_of(new[0]) == "p|a.py|msg1"
    # a fixed finding leaves a stale key
    new, baselined, stale = apply_baseline(
        _mkissues(("p", "a.py", "msg1"), ("p", "a.py", "msg1")), counts)
    assert new == [] and stale == ["q|b.py|mssg2".replace("ss", "s")]


def test_baseline_roundtrip_is_byte_stable(tmp_path):
    """Re-recording an unchanged tree must be byte-identical — the CI
    drift check diffs the file."""
    path = str(tmp_path / "base.json")
    issues = _mkissues(("q", "b.py", "m2"), ("p", "a.py", "m1"))
    save_baseline(path, issues)
    first = open(path).read()
    assert load_baseline(path) == record(issues)
    save_baseline(path, issues)
    assert open(path).read() == first


def test_baseline_malformed_is_hard_error(tmp_path):
    path = tmp_path / "base.json"
    with pytest.raises(FileNotFoundError):
        load_baseline(str(path))
    path.write_text('{"version": 99, "findings": {}}')
    with pytest.raises(ValueError):
        load_baseline(str(path))
    path.write_text('{"version": 1, "findings": {"k": 0}}')
    with pytest.raises(ValueError):
        load_baseline(str(path))


# ===================================================== CLI: ratchet mode
BAD_FIXTURE = """\
import jax
import jax.numpy as jnp

@jax.jit
def f(x):
    a = jnp.zeros((3, 4))
    return a.reshape(5, 2)
"""


def mxlint(*argv, cwd=REPO):
    env = dict(os.environ, PYTHONPATH=REPO)
    return subprocess.run(
        [sys.executable, "-m", "tools.mxlint"] + list(argv),
        cwd=cwd, capture_output=True, text=True, env=env)


def test_cli_baseline_ratchet(tmp_path):
    bad = tmp_path / "fix" / "bad.py"
    bad.parent.mkdir()
    bad.write_text(BAD_FIXTURE)
    base = str(tmp_path / "base.json")
    # without a baseline: the finding fails the run
    proc = mxlint(str(bad.parent))
    assert proc.returncode == 1 and "shape-soundness" in proc.stdout
    # record, then the same tree is clean
    proc = mxlint("--baseline", base, "--update-baseline",
                  str(bad.parent))
    assert proc.returncode == 0, proc.stderr
    proc = mxlint("--baseline", base, str(bad.parent))
    assert proc.returncode == 0
    assert "baselined" in proc.stdout
    # a NEW finding still fails, and only it is printed
    bad.write_text(BAD_FIXTURE +
                   "\n@jax.jit\ndef g(x):\n"
                   "    return jnp.ones((2, 2)).reshape(3, 3)\n")
    proc = mxlint("--baseline", base, "--format", "json",
                  str(bad.parent))
    assert proc.returncode == 1
    lines = [json.loads(l) for l in proc.stdout.splitlines()]
    assert len(lines) == 1 and lines[0]["line"] == 11
    # fixing everything leaves stale keys -> warning, still rc 0
    bad.write_text("x = 1\n")
    proc = mxlint("--baseline", base, str(bad.parent))
    assert proc.returncode == 0
    assert "stale baseline" in proc.stderr


def test_cli_update_baseline_requires_file_and_full_run(tmp_path):
    proc = mxlint("--update-baseline", "tools/mxlint/baseline.py")
    assert proc.returncode == 2
    assert "--baseline" in proc.stderr
    proc = mxlint("--baseline", str(tmp_path / "b.json"),
                  "--update-baseline", "--changed",
                  "tools/mxlint/baseline.py")
    assert proc.returncode == 2
    assert "partial" in proc.stderr
    # --select is just as partial: recording it would wipe every
    # baselined finding of the unselected passes
    proc = mxlint("--baseline", str(tmp_path / "b.json"),
                  "--update-baseline", "--select", "env-registry",
                  "tools/mxlint/baseline.py")
    assert proc.returncode == 2
    assert "partial" in proc.stderr


# ===================================================== CLI: changed mode
def _git(cwd, *argv):
    proc = subprocess.run(
        ["git"] + list(argv), cwd=cwd, capture_output=True, text=True,
        env=dict(os.environ, GIT_AUTHOR_NAME="t", GIT_AUTHOR_EMAIL="t@t",
                 GIT_COMMITTER_NAME="t", GIT_COMMITTER_EMAIL="t@t",
                 HOME=str(cwd)))
    assert proc.returncode == 0, proc.stderr
    return proc.stdout


HELPER_SRC = """\
import jax
from functools import partial

@partial(jax.jit, static_argnums=(1,))
def hkernel(x, n):
    return x[:n]

def helper_bug(x, request):
    return hkernel(x, int(request))

def prep(req):
    return int(req) + 1
"""

CALLER_V1 = """\
def handle(x, request):
    return None
"""

CALLER_V2 = """\
import jax
from functools import partial

from .helper import prep

@partial(jax.jit, static_argnums=(1,))
def ckernel(x, n):
    return x[:n]

def handle(x, request):
    n = prep(request)
    return ckernel(x, n)
"""


def test_cli_changed_filters_reporting_but_not_the_callgraph(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "helper.py").write_text(HELPER_SRC)
    (pkg / "caller.py").write_text(CALLER_V1)
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "add", ".")
    _git(tmp_path, "commit", "-qm", "seed")
    # nothing changed: clean no-op (paths before the bare flag — an
    # nargs="?" REF would otherwise swallow the path)
    proc = mxlint("pkg", "--changed", cwd=tmp_path)
    assert proc.returncode == 0
    assert "no linted files changed" in proc.stdout
    # a full run sees BOTH bugs (helper's own + nothing in caller yet)
    proc = mxlint("pkg", cwd=tmp_path)
    assert proc.returncode == 1 and "helper.py" in proc.stdout
    # modify only caller.py: its cross-file finding (through the
    # UNCHANGED helper) is reported, helper's own bug is not
    (pkg / "caller.py").write_text(CALLER_V2)
    proc = mxlint("pkg", "--changed", "--format", "json", cwd=tmp_path)
    assert proc.returncode == 1, proc.stderr
    findings = [json.loads(l) for l in proc.stdout.splitlines()]
    assert [f["file"] for f in findings] == [os.path.join("pkg",
                                                          "caller.py")]
    assert findings[0]["pass"] == "recompile-churn"
    assert "via prep" in findings[0]["message"]
    # explicit REF works too
    proc = mxlint("--changed", "HEAD", "pkg", cwd=tmp_path)
    assert proc.returncode == 1 and "caller.py" in proc.stdout
    # an UNTRACKED file counts as changed even when mxlint runs from a
    # subdirectory (ls-files is cwd-scoped; mxlint pins it to the root)
    (tmp_path / "pkg" / "caller.py").write_text(CALLER_V1)   # revert
    (tmp_path / "pkg" / "fresh.py").write_text(BAD_FIXTURE)
    proc = mxlint(".", "--changed", cwd=tmp_path / "pkg")
    assert proc.returncode == 1, proc.stderr
    assert "fresh.py" in proc.stdout and "helper.py" not in proc.stdout
    # a path mistakenly consumed as the REF is a hard error, never a
    # silent "nothing changed"
    proc = mxlint("--changed", "pkg", cwd=tmp_path)
    assert proc.returncode == 2
    assert "clean" not in proc.stdout


def test_cli_changed_bad_ref_is_a_hard_error(tmp_path):
    pkg = tmp_path / "p"
    pkg.mkdir()
    (pkg / "a.py").write_text("x = 1\n")
    _git(tmp_path, "init", "-q")
    proc = mxlint("--changed", "no_such_ref", "p", cwd=tmp_path)
    assert proc.returncode == 2
    assert "git" in proc.stderr
