"""Serving subsystem: shape-bucketed dynamic batching, versioned
repository hot-swap, bounded-queue backpressure (docs/serving.md).
"""
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, runtime_metrics as rm, serving
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn
from mxnet_tpu.serving import (ModelRepository, ModelServer,
                               ServerOverloadedError, ServingConfig,
                               next_bucket, pad_batch, unpad_outputs)


@pytest.fixture(autouse=True)
def _metrics_on():
    rm.reset()
    rm.enable()
    yield
    rm.disable()
    rm.reset()


def _mlp(seed=7, in_units=8, out_units=4):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu", in_units=in_units))
        net.add(nn.Dense(out_units, in_units=16))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    return net


def _export(net, tmp_path, name="m", dynamic_batch=True, batch=5,
            version=None):
    x = nd.random.uniform(shape=(batch, 8))
    return net.export_stablehlo(x, path=str(tmp_path / name),
                                dynamic_batch=dynamic_batch,
                                version=version)


def _cfg(**kw):
    kw.setdefault("max_batch_size", 8)
    kw.setdefault("max_latency_us", 20_000)
    return ServingConfig(**kw)


class TestBucketMath:
    def test_next_bucket_powers_of_two(self):
        assert [next_bucket(n, 8) for n in (1, 2, 3, 4, 5, 7, 8)] == \
            [1, 2, 4, 4, 8, 8, 8]

    def test_next_bucket_non_pow2_cap(self):
        # the cap itself is the last bucket even when not a power of two
        assert next_bucket(5, 6) == 6
        assert next_bucket(6, 6) == 6
        assert next_bucket(9, 6) == 6

    def test_next_bucket_rejects_zero(self):
        with pytest.raises(MXNetError):
            next_bucket(0, 8)

    def test_bucket_set_size_bound(self):
        # any request mix reaches at most ceil(log2(max))+1 shapes
        import math
        for max_batch in (1, 2, 6, 8, 16):
            buckets = {next_bucket(n, max_batch)
                       for n in range(1, 3 * max_batch)}
            assert len(buckets) <= math.ceil(math.log2(max_batch)) + 1

    def test_pad_unpad_roundtrip_ragged(self):
        reqs = [(np.arange(2 * 3, dtype=np.float32).reshape(2, 3),),
                (np.ones((1, 3), np.float32),),
                (np.full((2, 3), 7, np.float32),)]
        padded, offsets = pad_batch(reqs, 8)        # 5 real + 3 pad rows
        assert padded[0].shape == (8, 3)
        assert offsets == [0, 2, 3, 5]
        assert np.all(padded[0][5:] == 0)
        outs = (padded[0] * 2,)                     # batch-major op
        back = unpad_outputs(outs, offsets)
        for req, out in zip(reqs, back):
            np.testing.assert_allclose(out[0], req[0] * 2)

    def test_pad_batch_overflow_raises(self):
        with pytest.raises(MXNetError, match="exceed bucket"):
            pad_batch([(np.ones((4, 2), np.float32),)], 2)

    def test_unpad_rejects_non_batch_major(self):
        with pytest.raises(MXNetError, match="batch-major"):
            unpad_outputs((np.float32(3.0),), [0, 2, 4])


class TestRepository:
    def test_block_roundtrip_and_versioning(self):
        repo = ModelRepository()
        net = _mlp(1)
        x = nd.random.uniform(shape=(4, 8))
        e1 = repo.add_block("net", net, x)
        assert repo.current_version("net") == e1.version == 1
        e2 = repo.add_block("net", net, x)          # auto-increments
        assert e2.version == 2
        assert repo.current_version("net") == 2     # activate=True
        assert repo.versions("net") == [1, 2]
        assert repo.swap("net", 1) == 2
        assert repo.get("net") is e1

    def test_register_without_activate_keeps_current(self):
        repo = ModelRepository()
        net = _mlp(2)
        x = nd.random.uniform(shape=(4, 8))
        repo.add_block("net", net, x)
        repo.add_block("net", net, x, activate=False)
        assert repo.current_version("net") == 1

    def test_first_version_staged_with_activate_false(self):
        """activate=False stages even the first version of a new name:
        nothing serves until an explicit swap() activates it."""
        repo = ModelRepository()
        net = _mlp(2)
        x = nd.random.uniform(shape=(4, 8))
        repo.add_block("net", net, x, activate=False)
        assert repo.current_version("net") is None
        with pytest.raises(MXNetError, match="no active version"):
            repo.get("net")
        repo.swap("net", 1)
        assert repo.get("net").version == 1

    def test_duplicate_version_rejected(self):
        repo = ModelRepository()
        net = _mlp(3)
        x = nd.random.uniform(shape=(4, 8))
        repo.add_block("net", net, x, version=5)
        with pytest.raises(MXNetError, match="already registered"):
            repo.add_block("net", net, x, version=5)

    def test_unload_rules(self):
        repo = ModelRepository()
        net = _mlp(4)
        x = nd.random.uniform(shape=(4, 8))
        repo.add_block("net", net, x)
        repo.add_block("net", net, x)
        with pytest.raises(MXNetError, match="is current"):
            repo.unload("net", 2)
        repo.swap("net", 1)
        repo.unload("net", 2)
        assert repo.versions("net") == [1]
        repo.unload("net")
        with pytest.raises(MXNetError, match="no model"):
            repo.get("net")

    def test_missing_model_message_lists_known(self):
        repo = ModelRepository()
        with pytest.raises(MXNetError, match="no model 'ghost'"):
            repo.get("ghost")

    def test_block_weights_snapshot_at_registration(self, tmp_path):
        """Training after add_block must not mutate the served version —
        publish new weights by registering + swapping."""
        repo = ModelRepository()
        net = _mlp(5)
        x = nd.random.uniform(shape=(3, 8))
        want_v1 = net(x).asnumpy()
        repo.add_block("net", net, x)
        for p in net.collect_params().values():     # "training"
            p.set_data(p.data() * 0.5)
        want_v2 = net(x).asnumpy()
        assert not np.allclose(want_v1, want_v2)
        repo.add_block("net", net, x, activate=False)
        with ModelServer(repo, _cfg()) as srv:
            np.testing.assert_allclose(srv.predict("net", x.asnumpy()),
                                       want_v1, rtol=1e-5, atol=1e-5)
            repo.swap("net", 2)
            np.testing.assert_allclose(srv.predict("net", x.asnumpy()),
                                       want_v2, rtol=1e-5, atol=1e-5)

    def test_concurrent_auto_versioning_never_collides(self):
        """version=None registrations assign under one lock hold: two
        racing add_block calls must get distinct versions, not a
        spurious 'already registered' error."""
        repo = ModelRepository()
        net = _mlp(30)
        x = nd.random.uniform(shape=(2, 8))
        errors = []
        barrier = threading.Barrier(4)

        def register():
            try:
                barrier.wait(10)
                repo.add_block("net", net, x)
            except Exception as e:      # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=register) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert not errors, errors[:2]
        assert sorted(repo.versions("net")) == [1, 2, 3, 4]

    def test_unload_evicts_cached_programs(self):
        """Retired versions must not pin compiled programs (hot-swap
        deploy loops would otherwise grow memory without bound)."""
        repo = ModelRepository()
        net = _mlp(31)
        x = nd.random.uniform(shape=(2, 8))
        repo.add_block("net", net, x)
        repo.add_block("net", net, x, activate=False)
        with ModelServer(repo, _cfg()) as srv:
            e1 = repo.get("net")
            srv.predict("net", x.asnumpy(), timeout=60)
            assert srv.batcher.programs(e1) == 1
            repo.swap("net", 2)
            repo.unload("net", 1)
            assert srv.batcher.programs(e1) == 0
            srv.predict("net", x.asnumpy(), timeout=60)  # v2 serves on
            assert srv.batcher.programs() == 1
            # a batch admitted pre-unload may still dispatch once, but
            # must NOT re-cache under the retired uid
            srv.batcher.run_batch(e1, [(x.asnumpy(),)])
            assert srv.batcher.programs(e1) == 0

    def test_load_artifact_auto_versions_default_exports(self, tmp_path):
        """Exports without an explicit version (manifest version null)
        auto-increment in the repository — the documented export ->
        load_artifact -> swap loop must not collide on the second
        default export."""
        net = _mlp(32)
        a1 = _export(net, tmp_path, name="a1")
        a2 = _export(net, tmp_path, name="a2")
        repo = ModelRepository()
        repo.load_artifact("net", a1)
        repo.load_artifact("net", a2)
        assert repo.versions("net") == [1, 2]
        assert repo.current_version("net") == 2

    def test_stopped_server_unsubscribes_from_repository(self):
        repo = ModelRepository()
        srv = ModelServer(repo, _cfg())
        assert len(repo._unload_listeners) == 1
        srv.stop()
        assert repo._unload_listeners == []
        srv.start()                         # re-subscribes
        assert len(repo._unload_listeners) == 1
        srv.stop()

    def test_load_artifact_requires_manifest(self, tmp_path):
        net = _mlp(6)
        art = _export(net, tmp_path)
        (tmp_path / "m.json").unlink()
        with pytest.raises(MXNetError, match="no manifest"):
            ModelRepository().load_artifact("net", art)


class TestValidation:
    def test_predict_validates_dtype_and_shape(self, tmp_path):
        net = _mlp(7)
        repo = ModelRepository()
        repo.load_artifact("net", _export(net, tmp_path))
        with ModelServer(repo, _cfg()) as srv:
            with pytest.raises(MXNetError, match="dtype mismatch"):
                srv.predict("net", np.ones((2, 8), np.float64))
            with pytest.raises(MXNetError, match="rank mismatch"):
                srv.predict("net", np.ones((8,), np.float32))
            with pytest.raises(MXNetError, match="axis 1"):
                srv.predict("net", np.ones((2, 9), np.float32))
            with pytest.raises(MXNetError, match="expected 1 input"):
                srv.predict("net", np.ones((2, 8), np.float32),
                            np.ones((2, 8), np.float32))

    def test_request_rows_bounded_by_policy(self, tmp_path):
        net = _mlp(8)
        repo = ModelRepository()
        repo.load_artifact("net", _export(net, tmp_path))
        with ModelServer(repo, _cfg(max_batch_size=4)) as srv:
            with pytest.raises(MXNetError, match="outside"):
                srv.predict("net", np.ones((5, 8), np.float32))


class TestDynamicBatching:
    def test_concurrent_requests_coalesce_into_buckets(self, tmp_path):
        """32 concurrent predict()s of 3 distinct batch sizes: results
        exact, programs bounded by ceil(log2(max_batch))+1, cache-hit
        counter moves, padded rows never leak (acceptance criteria)."""
        net = _mlp(9)
        repo = ModelRepository()
        repo.load_artifact("net", _export(net, tmp_path))
        cfg = _cfg(max_batch_size=8, max_latency_us=50_000)
        refs = {}
        for n in (1, 2, 3):
            x = np.random.RandomState(n).randn(n, 8).astype(np.float32)
            refs[n] = (x, net(nd.NDArray(x)).asnumpy())

        errors = []
        start = threading.Barrier(32 + 1)

        with ModelServer(repo, cfg) as srv:
            def one(i):
                n = 1 + i % 3
                try:
                    start.wait(10)
                    x, want = refs[n]
                    got = srv.predict("net", x, timeout=60)
                    np.testing.assert_allclose(got, want, rtol=1e-5,
                                               atol=1e-5)
                except Exception as e:      # noqa: BLE001
                    errors.append(e)

            threads = [threading.Thread(target=one, args=(i,))
                       for i in range(32)]
            for t in threads:
                t.start()
            start.wait(10)
            for t in threads:
                t.join(60)
            stats = srv.stats()
        assert not errors, errors[:3]
        assert stats["completed"] == stats["requests"] == 32
        # coalescing really happened, and cannot exceed one batch per
        # request
        assert stats["batches"] < 32
        assert stats["batches"] >= 1
        # O(log N) compiled programs: buckets are {1,2,4,8} at most
        assert stats["programs"] <= 4
        # the documented invariant under the mem_hit|disk_hit|miss
        # split: misses == freshly compiled programs, and in-memory
        # programs == misses + disk hits (no disk cache here, so the
        # disk_hit series stays zero)
        assert stats["bucket_misses"] == stats["programs"]
        assert stats["programs"] == \
            stats["bucket_misses"] + stats["bucket_disk_hits"]
        assert stats["bucket_disk_hits"] == 0
        assert rm.SERVING_BUCKET_CACHE.value(event="disk_hit") == 0
        assert stats["bucket_hits"] == \
            rm.SERVING_BUCKET_CACHE.value(event="mem_hit")
        assert stats["bucket_misses"] == \
            rm.SERVING_BUCKET_CACHE.value(event="miss")
        assert stats["bucket_hits"] + stats["bucket_misses"] == \
            stats["batches"]
        assert stats["queue_depth"] == 0
        # per-model latency histogram carries every request; p99 reads
        p99 = rm.SERVING_REQUEST_SECONDS.quantile(0.99, model="net")
        assert rm.SERVING_REQUEST_SECONDS.count(model="net") == 32
        assert np.isfinite(p99) and p99 >= 0
        # the bounded sync point around batch dispatch was exercised
        assert rm.ENGINE_SYNC_SECONDS.count(site="serving") == \
            stats["batches"]
        # prometheus exporter carries the serving metrics
        prom = rm.dump_prometheus()
        assert 'serving_request_seconds_count{model="net"} 32' in prom
        assert "serving_queue_depth" in prom
        assert "serving_batch_occupancy_bucket" in prom

    def test_single_request_no_server_needed(self, tmp_path):
        """The batcher is usable standalone (no worker pool)."""
        net = _mlp(10)
        repo = ModelRepository()
        entry = repo.load_artifact("net", _export(net, tmp_path))
        b = serving.DynamicBatcher(_cfg())
        x = np.random.RandomState(0).randn(3, 8).astype(np.float32)
        [(out,)] = b.run_batch(entry, [(x,)])
        np.testing.assert_allclose(out, net(nd.NDArray(x)).asnumpy(),
                                   rtol=1e-5, atol=1e-5)
        assert b.bucket_misses == 1
        [(out2,)] = b.run_batch(entry, [(x,)])      # same bucket: hit
        assert b.bucket_hits == 1
        np.testing.assert_allclose(out, out2, rtol=1e-6)

    def test_static_artifact_pads_to_exported_batch(self, tmp_path):
        net = _mlp(11)
        repo = ModelRepository()
        repo.load_artifact(
            "net", _export(net, tmp_path, dynamic_batch=False, batch=4))
        entry = repo.get("net")
        assert not entry.dynamic_batch and entry.fixed_batch == 4
        with ModelServer(repo, _cfg()) as srv:
            for n in (1, 2, 4):
                x = np.random.RandomState(n).randn(n, 8) \
                    .astype(np.float32)
                got = srv.predict("net", x, timeout=60)
                np.testing.assert_allclose(
                    got, net(nd.NDArray(x)).asnumpy(),
                    rtol=1e-5, atol=1e-5)
            with pytest.raises(MXNetError, match="outside"):
                srv.predict("net", np.ones((5, 8), np.float32))
        # one program: every dispatch pads to the exported batch of 4
        assert srv.stats()["programs"] == 1

    def test_static_function_entry_pads_to_declared_batch(self):
        """dynamic_batch=False function entries derive fixed_batch from
        the signature and serve via padding, like static artifacts."""
        repo = ModelRepository()
        repo.add_function("f", lambda x: x * 2.0,
                          [{"shape": [4, 2], "dtype": "float32"}],
                          dynamic_batch=False)
        assert repo.get("f").fixed_batch == 4
        with ModelServer(repo, _cfg()) as srv:
            x = np.arange(4, dtype=np.float32).reshape(2, 2)
            np.testing.assert_allclose(
                srv.predict("f", x, timeout=60), x * 2)
            with pytest.raises(MXNetError, match="outside"):
                srv.predict("f", np.ones((5, 2), np.float32))

    def test_multi_output_model_returns_tuple(self):
        repo = ModelRepository()
        sig = [{"shape": [None, 3], "dtype": "float32"}]
        repo.add_function("twin", lambda x: (x * 2.0, x + 1.0), sig)
        with ModelServer(repo, _cfg()) as srv:
            x = np.ones((2, 3), np.float32)
            a, b = srv.predict("twin", x, timeout=60)
            np.testing.assert_allclose(a, x * 2)
            np.testing.assert_allclose(b, x + 1)


class _GatedModel:
    """Function entry whose batches block until released — makes queue
    buildup deterministic for backpressure tests."""

    def __init__(self):
        self.release = threading.Event()
        self.entered = threading.Event()

    def __call__(self, x):
        self.entered.set()
        assert self.release.wait(30), "test never released the gate"
        return x * 2.0


class TestBackpressure:
    SIG = [{"shape": [None, 2], "dtype": "float32"}]

    def _spawn_predicts(self, srv, n, results):
        threads = []
        for i in range(n):
            def one():
                try:
                    results.append(srv.predict(
                        "gated", np.ones((1, 2), np.float32),
                        timeout=60))
                except Exception as e:  # noqa: BLE001
                    results.append(e)
            t = threading.Thread(target=one)
            t.start()
            threads.append(t)
        return threads

    def test_load_shedding_at_watermark(self):
        repo = ModelRepository()
        gate = _GatedModel()
        repo.add_function("gated", gate, self.SIG)
        cfg = _cfg(max_batch_size=1, max_latency_us=1, queue_depth=4,
                   shed_watermark=2, num_workers=1, retry_after_ms=17)
        srv = ModelServer(repo, cfg)
        try:
            results = []
            t1 = self._spawn_predicts(srv, 1, results)
            # worker picks up request 1 and blocks inside the model
            assert gate.entered.wait(30)
            deadline = time.monotonic() + 30
            while srv.stats()["queue_depth"] > 0:   # popped from queue
                assert time.monotonic() < deadline
                time.sleep(0.005)
            t2 = self._spawn_predicts(srv, 2, results)  # fill to the mark
            deadline = time.monotonic() + 30
            while srv.stats()["queue_depth"] < 2:
                assert time.monotonic() < deadline
                time.sleep(0.005)
            # depth == watermark: next request must shed immediately
            with pytest.raises(ServerOverloadedError) as ei:
                srv.predict("gated", np.ones((1, 2), np.float32))
            assert ei.value.retry_after_ms == 17
            assert "retry after 17ms" in str(ei.value)
            assert srv.stats()["shed"] == 1
            assert rm.SERVING_SHED.value(model="gated") == 1
            gate.release.set()
            for t in t1 + t2:
                t.join(60)
            assert all(isinstance(r, np.ndarray) for r in results), \
                results
        finally:
            gate.release.set()
            srv.stop()
        assert srv.stats()["completed"] == 3

    def test_inflight_counts_against_queue_depth(self):
        """queue_depth bounds total outstanding work: with the waiting
        queue below the watermark, dispatched-but-unfinished requests
        still push admission into the shed path."""
        repo = ModelRepository()
        gate = _GatedModel()
        repo.add_function("gated", gate, self.SIG)
        cfg = _cfg(max_batch_size=1, max_latency_us=1, queue_depth=2,
                   shed_watermark=2, num_workers=1)
        srv = ModelServer(repo, cfg)
        try:
            results = []
            t1 = self._spawn_predicts(srv, 1, results)
            assert gate.entered.wait(30)        # in-flight, queue empty
            t2 = self._spawn_predicts(srv, 1, results)  # queued: depth 1
            deadline = time.monotonic() + 30
            while srv.stats()["queue_depth"] < 1:
                assert time.monotonic() < deadline
                time.sleep(0.005)
            # depth(1) < watermark(2), but depth + inflight == 2 ==
            # queue_depth: total-outstanding bound sheds
            with pytest.raises(ServerOverloadedError):
                srv.predict("gated", np.ones((1, 2), np.float32))
            gate.release.set()
            for t in t1 + t2:
                t.join(60)
            assert all(isinstance(r, np.ndarray) for r in results)
        finally:
            gate.release.set()
            srv.stop()

    def test_graceful_drain_completes_queued_requests(self):
        repo = ModelRepository()
        gate = _GatedModel()
        repo.add_function("gated", gate, self.SIG)
        cfg = _cfg(max_batch_size=1, max_latency_us=1, queue_depth=8,
                   num_workers=1)
        srv = ModelServer(repo, cfg)
        results = []
        threads = self._spawn_predicts(srv, 4, results)
        assert gate.entered.wait(30)
        # gate.entered only proves request 1 is executing; the other
        # three must actually be queued before admission closes, or
        # stop() races the predict threads and sheds a straggler
        deadline = time.monotonic() + 30
        while srv.stats()["queue_depth"] < 3:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        gate.release.set()
        srv.stop(drain=True)                # waits for every request
        for t in threads:
            t.join(60)
        assert len(results) == 4
        assert all(isinstance(r, np.ndarray) for r in results), results
        with pytest.raises(MXNetError, match="not accepting"):
            srv.predict("gated", np.ones((1, 2), np.float32))

    def test_hard_stop_fails_queued_requests(self):
        repo = ModelRepository()
        gate = _GatedModel()
        repo.add_function("gated", gate, self.SIG)
        cfg = _cfg(max_batch_size=1, max_latency_us=1, queue_depth=8,
                   num_workers=1)
        srv = ModelServer(repo, cfg)
        results = []
        threads = self._spawn_predicts(srv, 3, results)
        assert gate.entered.wait(30)
        deadline = time.monotonic() + 30
        while srv.stats()["queue_depth"] < 2:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        gate.release.set()
        srv.stop(drain=False)
        for t in threads:
            t.join(60)
        assert len(results) == 3
        stopped = [r for r in results if isinstance(r, MXNetError)]
        served = [r for r in results if isinstance(r, np.ndarray)]
        assert len(stopped) == 2 and len(served) == 1, results

    def test_timed_out_request_is_withdrawn(self):
        """An abandoned predict() must not occupy queue depth (pushing
        later admissions into the shed watermark) nor be dispatched."""
        repo = ModelRepository()
        gate = _GatedModel()
        repo.add_function("gated", gate, self.SIG)
        cfg = _cfg(max_batch_size=1, max_latency_us=1, queue_depth=8,
                   shed_watermark=2, num_workers=1)
        srv = ModelServer(repo, cfg)
        try:
            results = []
            t1 = self._spawn_predicts(srv, 1, results)
            assert gate.entered.wait(30)        # worker holds request 1
            with pytest.raises(MXNetError, match="no result within"):
                srv.predict("gated", np.ones((1, 2), np.float32),
                            timeout=0.05)
            assert srv.stats()["queue_depth"] == 0      # withdrawn
            # depth is back below the watermark: a fresh request admits
            t2 = self._spawn_predicts(srv, 1, results)
            gate.release.set()
            for t in t1 + t2:
                t.join(60)
            assert all(isinstance(r, np.ndarray) for r in results)
        finally:
            gate.release.set()
            srv.stop()
        # the timed-out request was never dispatched
        assert srv.stats()["completed"] == 2

    def test_stop_timeout_keeps_stopping_state(self):
        """A join timeout with a stuck worker must NOT mark the server
        stopped — start() would spawn a second pool next to the orphan.
        """
        repo = ModelRepository()
        gate = _GatedModel()
        repo.add_function("gated", gate, self.SIG)
        srv = ModelServer(repo, _cfg(max_batch_size=1, max_latency_us=1,
                                     num_workers=1))
        results = []
        threads = self._spawn_predicts(srv, 1, results)
        assert gate.entered.wait(30)            # worker stuck in model
        assert srv.stop(drain=True, timeout=0.05) is False
        assert srv.started                      # still owns the orphan
        srv.start()                             # must be a no-op
        assert len(srv._workers) == 1
        gate.release.set()
        assert srv.stop(drain=True) is True
        for t in threads:
            t.join(60)
        assert all(isinstance(r, np.ndarray) for r in results)

    def test_full_batch_not_blocked_by_other_models_hold_window(self):
        """A ripe (full) batch for one model dispatches immediately even
        while another model's forming batch sits in a long hold window.
        """
        repo = ModelRepository()
        repo.add_function("slow_form", lambda x: x, self.SIG)
        repo.add_function("fast", lambda x: x + 1.0, self.SIG)
        cfg = _cfg(max_batch_size=2, max_latency_us=10_000_000,
                   num_workers=1)
        srv = ModelServer(repo, cfg)
        try:
            holder_out = []
            holder = threading.Thread(
                target=lambda: holder_out.append(srv.predict(
                    "slow_form", np.ones((1, 2), np.float32),
                    timeout=60)))
            holder.start()                      # forms for 10s
            done = []

            def full_batch(results=done):
                results.append(srv.predict(
                    "fast", np.ones((1, 2), np.float32), timeout=60))
            t0 = time.monotonic()
            fast_threads = [threading.Thread(target=full_batch)
                            for _ in range(2)]         # 2 rows == cap
            for t in fast_threads:
                t.start()
            for t in fast_threads:
                t.join(60)
            elapsed = time.monotonic() - t0
            assert len(done) == 2
            # far below the 10s hold window of the forming model
            assert elapsed < 5, elapsed
        finally:
            srv.stop(drain=True)                # drains the forming req
        holder.join(60)
        assert srv.stats()["completed"] == 3

    def test_model_error_propagates_to_caller(self):
        repo = ModelRepository()

        def boom(x):
            raise ValueError("synthetic model failure")

        repo.add_function("boom", boom, self.SIG)
        with ModelServer(repo, _cfg(max_latency_us=1)) as srv:
            with pytest.raises(ValueError, match="synthetic"):
                srv.predict("boom", np.ones((1, 2), np.float32),
                            timeout=60)
        assert srv.stats()["errors"] == 1


class TestHotSwap:
    def test_swap_under_concurrent_load_is_atomic(self, tmp_path):
        """Every response matches exactly v1 or v2 — never a mix."""
        net1, net2 = _mlp(20), _mlp(21)
        x = np.random.RandomState(0).randn(2, 8).astype(np.float32)
        want1 = net1(nd.NDArray(x)).asnumpy()
        want2 = net2(nd.NDArray(x)).asnumpy()
        assert not np.allclose(want1, want2)

        repo = ModelRepository()
        repo.add_block("net", net1, nd.NDArray(x), version=1)
        repo.add_block("net", net2, nd.NDArray(x), version=2,
                       activate=False)
        errors, seen_v2 = [], threading.Event()

        with ModelServer(repo, _cfg(max_latency_us=1000)) as srv:
            def caller():
                for _ in range(20):
                    try:
                        got = srv.predict("net", x, timeout=60)
                    except Exception as e:  # noqa: BLE001
                        errors.append(e)
                        return
                    if np.allclose(got, want2, rtol=1e-5, atol=1e-5):
                        seen_v2.set()
                    elif not np.allclose(got, want1, rtol=1e-5,
                                         atol=1e-5):
                        errors.append(AssertionError(
                            "response matches neither version"))
                        return
            threads = [threading.Thread(target=caller)
                       for _ in range(4)]
            for t in threads:
                t.start()
            time.sleep(0.02)
            assert repo.swap("net", 2) == 1
            for t in threads:
                t.join(60)
        assert not errors, errors[:3]
        assert seen_v2.is_set()             # swap became visible


class _CountingModel:
    """Function entry that counts executions — the fake-compile
    fixture: make_program constructions show up as bucket misses, and
    prewarm's forced first call shows up as an execution, with no real
    XLA compile anywhere."""

    def __init__(self):
        self.calls = 0
        self.lock = threading.Lock()

    def __call__(self, x):
        with self.lock:
            self.calls += 1
        return x * 2.0


class TestPrewarm:
    SIG = [{"shape": [None, 2], "dtype": "float32"}]

    def test_prewarm_builds_and_executes_every_bucket(self):
        """Cold start: prewarm() must construct AND run one program per
        shape bucket, so no later request ever meets a first
        (compiling) call."""
        repo = ModelRepository()
        model = _CountingModel()
        repo.add_function("m", model, self.SIG)
        with ModelServer(repo, _cfg(max_batch_size=8)) as srv:
            out = srv.prewarm("m")
            assert out["buckets"] == [1, 2, 4, 8]
            assert out["compiled"] == 4 and out["disk_hits"] == 0
            entry = repo.get("m")
            assert srv.batcher.programs(entry) == 4
            assert model.calls == 4             # each program forced once
            misses = srv.batcher.bucket_misses
            got = srv.predict("m", np.ones((3, 2), np.float32),
                              timeout=60)
            np.testing.assert_allclose(got, np.full((3, 2), 2.0))
            # the request path saw only mem hits
            assert srv.batcher.bucket_misses == misses

    def test_prewarm_non_pow2_cap_and_static_entry(self):
        repo = ModelRepository()
        repo.add_function("dyn", _CountingModel(), self.SIG)
        repo.add_function("static", _CountingModel(),
                          [{"shape": [4, 2], "dtype": "float32"}],
                          dynamic_batch=False)
        with ModelServer(repo, _cfg(max_batch_size=6)) as srv:
            assert srv.prewarm("dyn")["buckets"] == [1, 2, 4, 6]
            # static artifacts have exactly one bucket: the exported batch
            assert srv.prewarm("static")["buckets"] == [4]

    def test_prewarm_staged_version_then_swap_serves_without_compile(
            self):
        """The zero-compile hot-swap loop: stage v2, prewarm it, swap —
        post-swap traffic must never construct a program."""
        repo = ModelRepository()
        m1, m2 = _CountingModel(), _CountingModel()
        repo.add_function("m", m1, self.SIG, version=1)
        repo.add_function("m", m2, self.SIG, version=2, activate=False)
        with ModelServer(repo, _cfg(max_batch_size=4)) as srv:
            srv.predict("m", np.ones((1, 2), np.float32), timeout=60)
            assert srv.prewarm("m", version=2)["buckets"] == [1, 2, 4]
            misses = srv.batcher.bucket_misses      # 1 (v1) + 3 (v2)
            assert repo.swap("m", 2) == 1
            for n in (1, 2, 3, 4):
                srv.predict("m", np.ones((n, 2), np.float32),
                            timeout=60)
            # no compile on the request path after the swap
            assert srv.batcher.bucket_misses == misses
            assert m2.calls == 3 + 4            # prewarm + 4 requests

    def test_prewarm_swap_under_concurrent_load(self):
        """Swap to a prewarmed staged version while callers hammer the
        model: every response is valid and no post-swap request
        constructs a program."""
        repo = ModelRepository()
        repo.add_function("m", lambda x: x * 2.0, self.SIG, version=1)
        repo.add_function("m", lambda x: x * 3.0, self.SIG, version=2,
                          activate=False)
        errors = []
        stop = threading.Event()

        with ModelServer(repo, _cfg(max_batch_size=4,
                                    max_latency_us=500)) as srv:
            def caller():
                x = np.ones((1, 2), np.float32)
                while not stop.is_set():
                    try:
                        got = srv.predict("m", x, timeout=60)
                    except Exception as e:      # noqa: BLE001
                        errors.append(e)
                        return
                    if not (np.allclose(got, 2.0)
                            or np.allclose(got, 3.0)):
                        errors.append(AssertionError(repr(got)))
                        return
            threads = [threading.Thread(target=caller)
                       for _ in range(4)]
            for t in threads:
                t.start()
            try:
                srv.prewarm("m", version=2)
                v2 = repo._resolve("m", 2)
                progs_at_swap = srv.batcher.programs(v2)
                repo.swap("m", 2)
                # post-swap traffic runs on the prewarmed programs
                deadline = time.monotonic() + 30
                while not np.allclose(
                        srv.predict("m", np.ones((1, 2), np.float32),
                                    timeout=60), 3.0):
                    assert time.monotonic() < deadline
            finally:
                stop.set()
                for t in threads:
                    t.join(60)
            assert not errors, errors[:3]
            # every v2 bucket predates the swap (prewarm built them all)
            # — post-swap traffic never constructed a v2 program, i.e.
            # the hot-swap served zero compiles on the request path
            assert progs_at_swap == 3
            assert srv.batcher.programs(v2) == 3
            misses_settled = srv.batcher.bucket_misses
            for n in (1, 2, 3, 4):
                srv.predict("m", np.ones((n, 2), np.float32),
                            timeout=60)
            assert srv.batcher.bucket_misses == misses_settled

    def test_prewarm_summary_ignores_concurrent_other_entry_builds(
            self):
        """prewarm()'s compiled/disk_hits are per-entry: builds for
        OTHER models racing the prewarm (the swap-under-load flow) must
        not be misattributed."""
        repo = ModelRepository()
        repo.add_function("other", lambda x: x, self.SIG)
        other = repo.get("other")
        target = _CountingModel()
        repo.add_function("m", target, self.SIG)
        entry = repo.get("m")
        batcher = serving.DynamicBatcher(_cfg(max_batch_size=4))
        real = entry.make_program
        side = {"bucket": 16}

        def make_program_with_traffic(rows):
            # deterministic stand-in for concurrent traffic: every
            # build of "m" also builds a fresh bucket of "other"
            side["bucket"] += 1
            batcher.program_for(other, side["bucket"])
            return real(rows)
        entry.make_program = make_program_with_traffic
        out = repo.prewarm("m", batcher=batcher)
        assert out["buckets"] == [1, 2, 4]
        assert out["compiled"] == 3 and out["disk_hits"] == 0
        # the global counter did move for both entries
        assert batcher.bucket_misses == 6

    def test_prewarm_staged_needs_explicit_version(self):
        repo = ModelRepository()
        repo.add_function("m", _CountingModel(), self.SIG,
                          activate=False)
        with ModelServer(repo, _cfg()) as srv:
            with pytest.raises(MXNetError, match="no active version"):
                srv.prewarm("m")
            srv.prewarm("m", version=1)

    def test_prewarm_unknown_model_and_version(self):
        repo = ModelRepository()
        repo.add_function("m", _CountingModel(), self.SIG)
        with ModelServer(repo, _cfg()) as srv:
            with pytest.raises(MXNetError, match="no model"):
                srv.prewarm("ghost")
            with pytest.raises(MXNetError, match="no version"):
                srv.prewarm("m", version=9)

    def test_program_build_runs_outside_the_batcher_lock(self):
        """An XLA compile can take seconds; it must not stall other
        keys' mem-hit lookups, and concurrent lookups of the SAME key
        must build once (misses stay == compiled programs)."""
        repo = ModelRepository()
        repo.add_function("slow", lambda x: x, self.SIG)
        repo.add_function("fast", lambda x: x + 1.0, self.SIG)
        slow, fast = repo.get("slow"), repo.get("fast")
        batcher = serving.DynamicBatcher(_cfg(max_batch_size=4))
        batcher.program_for(fast, 1)            # warm the fast key
        in_build = threading.Event()
        release = threading.Event()
        builds = []
        real = slow.make_program

        def blocking_make_program(rows):
            builds.append(rows)
            in_build.set()
            assert release.wait(30)
            return real(rows)
        slow.make_program = blocking_make_program
        results = []
        builders = [threading.Thread(
            target=lambda: results.append(batcher.program_for(slow, 1)))
            for _ in range(3)]
        for t in builders:
            t.start()
        assert in_build.wait(30)                # a build is in flight
        # ... and a DIFFERENT key's mem hit does not block behind it
        t0 = time.monotonic()
        assert batcher.program_for(fast, 1) is not None
        assert time.monotonic() - t0 < 5
        release.set()
        for t in builders:
            t.join(30)
        # same key built exactly once; the other callers waited for it
        assert builds == [1]
        assert len(results) == 3
        assert all(r is results[0] for r in results)
        assert batcher.programs(slow) == 1

    def test_failed_build_wakes_waiters_and_retries(self):
        repo = ModelRepository()
        repo.add_function("m", lambda x: x, self.SIG)
        entry = repo.get("m")
        real = entry.make_program
        state = {"calls": 0}

        def flaky(rows):
            state["calls"] += 1
            if state["calls"] == 1:
                raise RuntimeError("transient compile failure")
            return real(rows)
        entry.make_program = flaky
        batcher = serving.DynamicBatcher(_cfg(max_batch_size=4))
        with pytest.raises(RuntimeError, match="transient"):
            batcher.program_for(entry, 1)
        # the in-flight marker was cleared: the next lookup rebuilds
        assert batcher.program_for(entry, 1) is not None
        assert state["calls"] == 2

    def test_disk_loaded_programs_counted_as_disk_hits(self):
        """A program whose make_program marks _mx_from_disk_cache (the
        compile-cache deserialization path) must count as disk_hit, not
        miss — misses stay == compiled programs."""
        repo = ModelRepository()
        repo.add_function("m", lambda x: x + 1.0, self.SIG)
        entry = repo.get("m")
        real = entry.make_program

        def disk_make_program(rows):
            prog = real(rows)
            prog._mx_from_disk_cache = True
            return prog
        entry.make_program = disk_make_program
        with ModelServer(repo, _cfg(max_batch_size=4)) as srv:
            out = srv.prewarm("m")
            assert out == {"model": "m", "version": 1,
                           "buckets": [1, 2, 4], "compiled": 0,
                           "disk_hits": 3}
            stats = srv.stats()
            assert stats["bucket_disk_hits"] == 3
            assert stats["bucket_misses"] == 0
            assert stats["programs"] == \
                stats["bucket_misses"] + stats["bucket_disk_hits"]
            assert rm.SERVING_BUCKET_CACHE.value(event="disk_hit") == 3
            assert rm.SERVING_BUCKET_CACHE.value(event="miss") == 0
            # second lookup of a disk-loaded program is a plain mem hit
            srv.predict("m", np.ones((1, 2), np.float32), timeout=60)
            assert rm.SERVING_BUCKET_CACHE.value(event="mem_hit") >= 1


class TestConfig:
    def test_env_defaults(self, monkeypatch):
        monkeypatch.setenv("MXNET_SERVING_MAX_BATCH", "16")
        monkeypatch.setenv("MXNET_SERVING_SHED_WATERMARK", "9")
        cfg = ServingConfig()
        assert cfg.max_batch_size == 16
        assert cfg.shed_watermark == 9
        assert cfg.queue_depth == 128

    def test_validation(self):
        with pytest.raises(MXNetError, match="max_batch_size"):
            ServingConfig(max_batch_size=0)
        with pytest.raises(MXNetError, match="shed_watermark"):
            ServingConfig(queue_depth=4, shed_watermark=9)
        with pytest.raises(MXNetError, match="max_latency_us"):
            ServingConfig(max_latency_us=-1)
        with pytest.raises(MXNetError, match="retry_after_ms"):
            ServingConfig(retry_after_ms=-1)
