"""Detection augmenters + ImageDetIter (mx.image.detection parity)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.image import (CreateDetAugmenter, DetBorrowAug,
                             DetHorizontalFlipAug, DetRandomCropAug,
                             DetRandomPadAug, DetRandomSelectAug,
                             ImageDetIter, CastAug)


def _img(h=32, w=48):
    rng = np.random.RandomState(0)
    return nd.array(rng.randint(0, 255, (h, w, 3)).astype(np.uint8),
                    dtype="uint8")


def _label():
    # one object: class 1 in the left half
    return np.array([[1.0, 0.1, 0.2, 0.4, 0.8]], np.float32)


def test_flip_mirrors_boxes():
    import random as pyrandom
    pyrandom.seed(0)
    aug = DetHorizontalFlipAug(p=1.0)
    src, lab = aug(_img(), _label())
    np.testing.assert_allclose(lab[0, 1], 1.0 - 0.4, atol=1e-6)
    np.testing.assert_allclose(lab[0, 3], 1.0 - 0.1, atol=1e-6)
    # y coords untouched
    np.testing.assert_allclose(lab[0, [2, 4]], [0.2, 0.8])
    # flipping twice restores the original boxes
    _, lab2 = aug(src, lab)
    np.testing.assert_allclose(lab2, _label(), atol=1e-6)


def test_random_crop_keeps_or_drops_objects():
    import random as pyrandom
    pyrandom.seed(1)
    aug = DetRandomCropAug(min_object_covered=0.5,
                           area_range=(0.5, 0.9))
    src, lab = aug(_img(), _label())
    kept = lab[lab[:, 0] >= 0]
    for row in kept:
        assert 0.0 <= row[1] <= row[3] <= 1.0
        assert 0.0 <= row[2] <= row[4] <= 1.0


def test_random_pad_shrinks_boxes():
    import random as pyrandom
    pyrandom.seed(2)
    aug = DetRandomPadAug(area_range=(2.0, 2.0))
    src, lab = aug(_img(), _label())
    w_before = 0.4 - 0.1
    w_after = lab[0, 3] - lab[0, 1]
    assert w_after < w_before            # zoom-out shrinks the box
    assert src.shape[0] > 32 and src.shape[1] > 48


def test_borrow_aug_keeps_labels():
    aug = DetBorrowAug(CastAug("float32"))
    src, lab = aug(_img(), _label())
    assert str(src.dtype) == "float32"
    np.testing.assert_allclose(lab, _label())


def test_create_det_augmenter_pipeline():
    import random as pyrandom
    pyrandom.seed(3)
    augs = CreateDetAugmenter((3, 64, 64), rand_crop=0.5, rand_pad=0.5,
                              rand_mirror=True, mean=True, std=True)
    src, lab = _img(), _label()
    for a in augs:
        src, lab = a(src, lab)
    assert src.shape[:2] == (64, 64)
    assert str(src.dtype) == "float32"


def test_image_det_iter_batches():
    rng = np.random.RandomState(4)
    samples = []
    for i in range(5):
        img = nd.array(rng.randint(0, 255, (24, 24, 3))
                       .astype(np.uint8), dtype="uint8")
        samples.append((img, [[float(i % 2), 0.1, 0.1, 0.6, 0.6]]))
    it = ImageDetIter(batch_size=2, data_shape=(3, 32, 32),
                      imglist=samples, max_objects=4)
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].data[0].shape == (2, 3, 32, 32)
    assert batches[0].label[0].shape == (2, 4, 5)
    assert batches[-1].pad == 1
    lab = batches[0].label[0].asnumpy()
    assert (lab[0, 0, 0] >= 0) and (lab[0, 1:, 0] == -1).all()
    # reset re-iterates
    it.reset()
    assert len(list(it)) == 3


def test_image_det_iter_recordio_roundtrip(tmp_path):
    from mxnet_tpu import recordio
    from mxnet_tpu.image import imencode
    rng = np.random.RandomState(5)
    rec_path = str(tmp_path / "det.rec")
    rec = recordio.MXRecordIO(rec_path, "w")
    for i in range(3):
        img = rng.randint(0, 255, (20, 20, 3)).astype(np.uint8)
        # upstream det-record layout: flat[0] = header WIDTH (objects
        # start at flat[int(flat[0])]), flat[1] = object row width.
        if i % 2 == 0:
            # minimal 2-field header: [2, 5, cls, x0, y0, x1, y1]
            label = np.array([2.0, 5.0, float(i), 0.2, 0.2, 0.8, 0.8],
                             np.float32)
        else:
            # 4-field header with extra fields:
            # [4, 5, extra, extra, cls, x0, y0, x1, y1]
            label = np.array([4.0, 5.0, -1.0, -1.0,
                              float(i), 0.2, 0.2, 0.8, 0.8], np.float32)
        header = recordio.IRHeader(0, label, i, 0)
        rec.write(recordio.pack(header, imencode(img, ".png")))
    rec.close()
    it = ImageDetIter(batch_size=3, data_shape=(3, 20, 20),
                      path_imgrec=rec_path, aug_list=[], max_objects=2)
    batch = next(iter(it))
    lab = batch.label[0].asnumpy()
    np.testing.assert_allclose(lab[:, 0, 0], [0.0, 1.0, 2.0])
    np.testing.assert_allclose(lab[:, 0, 1:], [[0.2, 0.2, 0.8, 0.8]] * 3,
                               atol=1e-6)
