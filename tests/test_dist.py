"""Multi-process runtime tests: launcher + dist bootstrap + dist_sync
kvstore + failure detection (reference strategy: SURVEY.md §4 — the dmlc
tracker's local mode exercised as a real multi-process job).

These spawn REAL subprocesses over gloo CPU collectives; PYTHONPATH is
pinned to the repo so workers import mxnet_tpu (and, on the test host,
drop any site-injected accelerator backend).
"""
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
import launch as launch_mod  # noqa: E402


def _worker_env():
    return {"PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "", "JAX_TRACEBACK_FILTERING": "off"}


def _write(tmp_path, name, body):
    path = str(tmp_path / name)
    with open(path, "w") as f:
        f.write(textwrap.dedent(body))
    return path


class TestLauncher:
    def test_two_process_allreduce(self, tmp_path):
        script = _write(tmp_path, "w.py", """
            import numpy as np
            from mxnet_tpu.parallel import dist
            from mxnet_tpu import nd
            dist.initialize()
            assert dist.size() == 2, dist.size()
            total = dist.allreduce_host(nd.array(
                np.array([dist.rank() + 1.0], np.float32)))
            assert total.asnumpy().tolist() == [3.0], total.asnumpy()
            b = dist.broadcast_host(nd.array(
                np.array([float(dist.rank())], np.float32)), root=1)
            assert b.asnumpy().tolist() == [1.0]
            dist.barrier()
            print("WORKER_OK", dist.rank())
        """)
        rc = launch_mod.launch(2, [sys.executable, script],
                               env_extra=_worker_env(), timeout=240)
        assert rc == 0

    def test_dist_sync_kvstore(self, tmp_path):
        script = _write(tmp_path, "w.py", """
            import numpy as np
            import mxnet_tpu as mx
            from mxnet_tpu import nd
            from mxnet_tpu.parallel import dist
            dist.initialize()
            kv = mx.kv.create("dist_sync")
            assert kv.num_workers == 2
            # rank-dependent init: rank 0's value must win on every rank
            kv.init("w", nd.array(np.full((2,), 10.0 * (kv.rank + 1),
                                          np.float32)))
            w0 = nd.zeros((2,))
            kv.pull("w", out=w0)
            np.testing.assert_allclose(w0.asnumpy(), 10.0)
            kv.init("3", nd.zeros((2, 2)))
            # each worker pushes rank+1; sum across group = 3
            kv.push("3", nd.array(np.full((2, 2), kv.rank + 1.0,
                                          np.float32)))
            out = nd.zeros((2, 2))
            kv.pull("3", out=out)
            np.testing.assert_allclose(out.asnumpy(), 3.0)
            print("KV_OK", kv.rank)
        """)
        rc = launch_mod.launch(2, [sys.executable, script],
                               env_extra=_worker_env(), timeout=240)
        assert rc == 0

    # ISSUE-15 tier-1 relief: two spawned processes + detection window
    # cost ~28s; tier-1 keeps the in-process watchdog-abort test, the
    # slow tier keeps this full two-process ladder.
    @pytest.mark.slow
    def test_failure_detection_aborts_job(self, tmp_path):
        """§5.3: one dead worker must take the job down, not hang it."""
        script = _write(tmp_path, "w.py", """
            import os, sys, time
            from mxnet_tpu.parallel import dist
            dist.initialize()
            if dist.rank() == 1:
                sys.exit(7)       # simulated worker crash
            time.sleep(600)       # would hang forever without detection
        """)
        import time
        t0 = time.monotonic()
        rc = launch_mod.launch(2, [sys.executable, script],
                               env_extra=_worker_env(), timeout=240)
        elapsed = time.monotonic() - t0
        # the job must die promptly and non-zero — never hang out the
        # sleeping worker (which exit code wins is a race between the
        # crashed rank and the peer's coordination-failure abort)
        assert rc != 0
        assert elapsed < 120, elapsed

    def test_launcher_timeout(self, tmp_path):
        script = _write(tmp_path, "w.py", "import time; time.sleep(600)")
        rc = launch_mod.launch(1, [sys.executable, script],
                               env_extra=_worker_env(), timeout=5)
        assert rc == 124


class TestWatchdog:
    def test_watchdog_aborts_hung_step(self, tmp_path):
        script = _write(tmp_path, "w.py", """
            import time
            from mxnet_tpu.parallel import dist
            wd = dist.Watchdog(timeout_s=2, name="step").start()
            wd.kick()
            time.sleep(600)   # hang: watchdog must abort with code 42
        """)
        proc = subprocess.run(
            [sys.executable, script],
            env={**os.environ, **_worker_env()}, timeout=120,
            capture_output=True)
        assert proc.returncode == 42

    def test_watchdog_quiet_when_kicked(self):
        import time
        from mxnet_tpu.parallel import dist
        with dist.Watchdog(timeout_s=2, name="ok") as wd:
            for _ in range(3):
                time.sleep(0.5)
                wd.kick()
        # still alive — no abort

    def test_standalone_initialize_noop(self):
        from mxnet_tpu.parallel import dist
        for var in ("MXNET_TPU_COORDINATOR", "MXNET_TPU_NUM_PROCS",
                    "MXNET_TPU_PROC_ID", "DMLC_PS_ROOT_URI",
                    "DMLC_NUM_WORKER", "DMLC_WORKER_ID"):
            assert var not in os.environ or True
        dist.initialize()      # no env, no args: standalone no-op
        assert not dist.is_initialized()

    def test_initialize_is_noop_while_finalizing(self, monkeypatch):
        """A concurrent initialize() during teardown must not re-create
        the jax distributed client while shutdown is in flight."""
        import jax
        from mxnet_tpu.parallel import dist

        def boom(*a, **k):      # pragma: no cover
            raise AssertionError(
                "jax.distributed.initialize called mid-teardown")

        monkeypatch.setattr(jax.distributed, "initialize", boom)
        monkeypatch.setitem(dist._state, "finalizing", True)
        dist.initialize(coordinator_address="127.0.0.1:1",
                        num_processes=1, process_id=0)
        assert not dist.is_initialized()
        # and a concurrent finalize() returns immediately too
        dist.finalize()


class TestMultiHostSPMD:
    """The DCN-spanning codepath a v5p multi-slice job will actually
    use: 2 PROCESSES x 4 virtual CPU devices each, one GLOBAL 8-device
    mesh, a full ShardedTrainer step compiled over it (dp grads cross
    the process boundary through XLA collectives over gloo), verified
    against a single-device oracle.  Every other multi-device proof in
    the suite is single-process; mesh construction, device_put to
    non-addressable shardings, and collective bootstrap all break
    differently across process boundaries (SURVEY §4 multi-node,
    §5.8)."""

    # Root cause of the long-standing failure (fixed): plain
    # jax.device_put of host values onto shardings spanning
    # NON-ADDRESSABLE devices lowers to cross-host transfer
    # collectives, and the gloo TCP transport aborts on them with
    # `gloo::EnforceNotMet: op.preamble.length <= op.nbytes` (worker-0
    # SIGABRT -> the peer then burned the launch timeout in the
    # coordination barrier — the "hang" was the symptom, the abort the
    # disease).  parallel.sharding.global_device_put now assembles
    # global arrays from locally-sliced host shards
    # (make_array_from_callback) instead, which needs no wire traffic;
    # ShardedTrainer uses it for params/opt-state/residuals/batches.
    def test_two_process_global_mesh_trainer_step(self, tmp_path):
        script = _write(tmp_path, "w.py", """
            import numpy as np
            import jax
            import jax.numpy as jnp
            from jax.sharding import NamedSharding, PartitionSpec as P

            from mxnet_tpu.parallel import dist
            dist.initialize()
            assert jax.process_count() == 2, jax.process_count()
            assert jax.device_count() == 8, jax.device_count()
            assert len(jax.local_devices()) == 4

            import mxnet_tpu as mx
            from mxnet_tpu import nd, models, parallel

            mx.random.seed(0)
            bert = models.get_bert_model(
                "bert_12_768_12", vocab_size=96, units=64,
                hidden_size=128, num_layers=2, num_heads=4,
                max_length=32, dropout=0.0)
            bert.initialize()
            head = models.BERTClassifier(bert, num_classes=2, dropout=0.0)
            head.initialize()
            B, L = 8, 16
            rng = np.random.RandomState(0)
            inp = nd.array(rng.randint(0, 96, (B, L)), dtype="int32")
            tt = nd.zeros((B, L), dtype="int32")
            vl = nd.array(np.full((B,), L, np.float32))
            lab = nd.array(rng.randint(0, 2, (B,)), dtype="int32")

            def loss_fn(logits, labels):
                logp = jax.nn.log_softmax(logits, axis=-1)
                return -jnp.take_along_axis(
                    logp, labels[:, None], axis=1).mean()

            def checksums(tr, mesh):
                names = sorted(tr.params)
                fn = jax.jit(
                    lambda ps: jnp.stack(
                        [jnp.sum(ps[n].astype(jnp.float32))
                         for n in names]),
                    out_shardings=NamedSharding(mesh, P()))
                return names, np.asarray(jax.device_get(fn(tr.params)))

            # single-device oracle (each process computes it identically
            # from the same seed; only local devices involved)
            mesh1 = parallel.make_mesh(
                dp=1, tp=1, sp=1, devices=jax.local_devices()[:1])
            tr1 = parallel.ShardedTrainer(
                head, loss_fn, mesh1, optimizer="adamw",
                optimizer_params={"learning_rate": 1e-3},
                example_inputs=(inp, tt, vl), n_labels=1)
            o_l0 = float(jax.device_get(tr1.step(inp, tt, vl, lab)))
            o_l1 = float(jax.device_get(tr1.step(inp, tt, vl, lab)))
            _names, o_ck = checksums(tr1, mesh1)

            # global dp=2 x tp=2 x sp=2 mesh spanning BOTH processes
            mesh = parallel.make_mesh(dp=2, tp=2, sp=2)
            assert len(set(d.process_index for d in
                           mesh.devices.flat)) == 2
            tr = parallel.ShardedTrainer(
                head, loss_fn, mesh, optimizer="adamw",
                optimizer_params={"learning_rate": 1e-3},
                example_inputs=(inp, tt, vl), n_labels=1)
            # tp really sharded across the process boundary
            qkv = [n for n in tr.params if n.endswith("qkv_weight")][0]
            assert tr.params[qkv].sharding.spec[0] == "tp"
            d_l0 = float(jax.device_get(tr.step(inp, tt, vl, lab)))
            d_l1 = float(jax.device_get(tr.step(inp, tt, vl, lab)))
            names, d_ck = checksums(tr, mesh)

            np.testing.assert_allclose(d_l0, o_l0, rtol=2e-4, atol=2e-5)
            np.testing.assert_allclose(d_l1, o_l1, rtol=2e-3, atol=2e-4)
            bad = [(n, a, b) for n, a, b in zip(names, d_ck, o_ck)
                   if not np.isclose(a, b, rtol=2e-3, atol=2e-3)]
            assert not bad, bad[:5]
            dist.barrier()
            print("SPMD_OK", dist.rank())
        """)
        env = _worker_env()
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        rc = launch_mod.launch(2, [sys.executable, script],
                               env_extra=env, timeout=420)
        assert rc == 0
