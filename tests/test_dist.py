"""Multi-process runtime tests: launcher + dist bootstrap + dist_sync
kvstore + failure detection (reference strategy: SURVEY.md §4 — the dmlc
tracker's local mode exercised as a real multi-process job).

These spawn REAL subprocesses over gloo CPU collectives; PYTHONPATH is
pinned to the repo so workers import mxnet_tpu (and, on the test host,
drop any site-injected accelerator backend).
"""
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
import launch as launch_mod  # noqa: E402


def _worker_env():
    return {"PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "", "JAX_TRACEBACK_FILTERING": "off"}


def _write(tmp_path, name, body):
    path = str(tmp_path / name)
    with open(path, "w") as f:
        f.write(textwrap.dedent(body))
    return path


class TestLauncher:
    def test_two_process_allreduce(self, tmp_path):
        script = _write(tmp_path, "w.py", """
            import numpy as np
            from mxnet_tpu.parallel import dist
            from mxnet_tpu import nd
            dist.initialize()
            assert dist.size() == 2, dist.size()
            total = dist.allreduce_host(nd.array(
                np.array([dist.rank() + 1.0], np.float32)))
            assert total.asnumpy().tolist() == [3.0], total.asnumpy()
            b = dist.broadcast_host(nd.array(
                np.array([float(dist.rank())], np.float32)), root=1)
            assert b.asnumpy().tolist() == [1.0]
            dist.barrier()
            print("WORKER_OK", dist.rank())
        """)
        rc = launch_mod.launch(2, [sys.executable, script],
                               env_extra=_worker_env(), timeout=240)
        assert rc == 0

    def test_dist_sync_kvstore(self, tmp_path):
        script = _write(tmp_path, "w.py", """
            import numpy as np
            import mxnet_tpu as mx
            from mxnet_tpu import nd
            from mxnet_tpu.parallel import dist
            dist.initialize()
            kv = mx.kv.create("dist_sync")
            assert kv.num_workers == 2
            # rank-dependent init: rank 0's value must win on every rank
            kv.init("w", nd.array(np.full((2,), 10.0 * (kv.rank + 1),
                                          np.float32)))
            w0 = nd.zeros((2,))
            kv.pull("w", out=w0)
            np.testing.assert_allclose(w0.asnumpy(), 10.0)
            kv.init("3", nd.zeros((2, 2)))
            # each worker pushes rank+1; sum across group = 3
            kv.push("3", nd.array(np.full((2, 2), kv.rank + 1.0,
                                          np.float32)))
            out = nd.zeros((2, 2))
            kv.pull("3", out=out)
            np.testing.assert_allclose(out.asnumpy(), 3.0)
            print("KV_OK", kv.rank)
        """)
        rc = launch_mod.launch(2, [sys.executable, script],
                               env_extra=_worker_env(), timeout=240)
        assert rc == 0

    def test_failure_detection_aborts_job(self, tmp_path):
        """§5.3: one dead worker must take the job down, not hang it."""
        script = _write(tmp_path, "w.py", """
            import os, sys, time
            from mxnet_tpu.parallel import dist
            dist.initialize()
            if dist.rank() == 1:
                sys.exit(7)       # simulated worker crash
            time.sleep(600)       # would hang forever without detection
        """)
        import time
        t0 = time.monotonic()
        rc = launch_mod.launch(2, [sys.executable, script],
                               env_extra=_worker_env(), timeout=240)
        elapsed = time.monotonic() - t0
        # the job must die promptly and non-zero — never hang out the
        # sleeping worker (which exit code wins is a race between the
        # crashed rank and the peer's coordination-failure abort)
        assert rc != 0
        assert elapsed < 120, elapsed

    def test_launcher_timeout(self, tmp_path):
        script = _write(tmp_path, "w.py", "import time; time.sleep(600)")
        rc = launch_mod.launch(1, [sys.executable, script],
                               env_extra=_worker_env(), timeout=5)
        assert rc == 124


class TestWatchdog:
    def test_watchdog_aborts_hung_step(self, tmp_path):
        script = _write(tmp_path, "w.py", """
            import time
            from mxnet_tpu.parallel import dist
            wd = dist.Watchdog(timeout_s=2, name="step").start()
            wd.kick()
            time.sleep(600)   # hang: watchdog must abort with code 42
        """)
        proc = subprocess.run(
            [sys.executable, script],
            env={**os.environ, **_worker_env()}, timeout=120,
            capture_output=True)
        assert proc.returncode == 42

    def test_watchdog_quiet_when_kicked(self):
        import time
        from mxnet_tpu.parallel import dist
        with dist.Watchdog(timeout_s=2, name="ok") as wd:
            for _ in range(3):
                time.sleep(0.5)
                wd.kick()
        # still alive — no abort

    def test_standalone_initialize_noop(self):
        from mxnet_tpu.parallel import dist
        for var in ("MXNET_TPU_COORDINATOR", "MXNET_TPU_NUM_PROCS",
                    "MXNET_TPU_PROC_ID", "DMLC_PS_ROOT_URI",
                    "DMLC_NUM_WORKER", "DMLC_WORKER_ID"):
            assert var not in os.environ or True
        dist.initialize()      # no env, no args: standalone no-op
        assert not dist.is_initialized()
