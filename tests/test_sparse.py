"""Sparse NDArray tests vs dense oracles
(reference strategy: tests/python/unittest/test_sparse_ndarray.py,
test_sparse_operator.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.ndarray import sparse


def _rand_csr_dense(m=8, n=6, density=0.3, seed=0):
    rng = np.random.RandomState(seed)
    dense = rng.randn(m, n).astype(np.float32)
    dense[rng.rand(m, n) > density] = 0.0
    return dense


class TestCSR:
    def test_from_dense_roundtrip(self):
        dense = _rand_csr_dense()
        csr = sparse.csr_matrix(dense)
        assert csr.stype == "csr"
        np.testing.assert_allclose(csr.asnumpy(), dense)
        back = csr.tostype("default")
        assert back.stype == "default"
        np.testing.assert_allclose(back.asnumpy(), dense)

    def test_from_components(self):
        # [[1,0,2],[0,0,3]]
        csr = sparse.csr_matrix(([1., 2., 3.], [0, 2, 2], [0, 2, 3]),
                                shape=(2, 3))
        np.testing.assert_allclose(csr.asnumpy(),
                                   [[1, 0, 2], [0, 0, 3]])
        np.testing.assert_array_equal(csr.indptr.asnumpy(), [0, 2, 3])

    def test_dot_vs_dense(self):
        a = _rand_csr_dense(10, 7, seed=1)
        b = np.random.RandomState(2).randn(7, 4).astype(np.float32)
        csr = sparse.csr_matrix(a)
        out = sparse.dot(csr, nd.array(b))
        np.testing.assert_allclose(out.asnumpy(), a @ b, rtol=1e-5,
                                   atol=1e-5)

    def test_dot_transpose_a(self):
        a = _rand_csr_dense(10, 7, seed=3)
        b = np.random.RandomState(4).randn(10, 5).astype(np.float32)
        out = sparse.dot(sparse.csr_matrix(a), nd.array(b),
                         transpose_a=True)
        np.testing.assert_allclose(out.asnumpy(), a.T @ b, rtol=1e-5,
                                   atol=1e-5)

    def test_row_slice(self):
        dense = _rand_csr_dense(8, 5, seed=5)
        csr = sparse.csr_matrix(dense)
        sl = csr[2:6]
        assert sl.stype == "csr"
        np.testing.assert_allclose(sl.asnumpy(), dense[2:6])
        np.testing.assert_allclose(csr[-1].asnumpy(), dense[-1:])
        with pytest.raises(mx.MXNetError):
            csr[8]

    def test_dense_op_fallback(self):
        """Ops without sparse kernels densify transparently."""
        dense = _rand_csr_dense()
        csr = sparse.csr_matrix(dense)
        out = nd.relu(csr)
        np.testing.assert_allclose(out.asnumpy(), np.maximum(dense, 0))

    def test_zeros(self):
        z = sparse.zeros("csr", (3, 4))
        np.testing.assert_array_equal(z.asnumpy(), np.zeros((3, 4)))


class TestRowSparse:
    def test_roundtrip_and_retain(self):
        dense = np.zeros((6, 3), np.float32)
        dense[1] = 1.0
        dense[4] = 2.0
        rsp = sparse.row_sparse_array(dense)
        assert rsp.stype == "row_sparse"
        np.testing.assert_array_equal(rsp.indices.asnumpy(), [1, 4])
        np.testing.assert_allclose(rsp.asnumpy(), dense)
        kept = sparse.retain(rsp, nd.array([4.0]))
        np.testing.assert_array_equal(kept.indices.asnumpy(), [4])
        np.testing.assert_allclose(kept.asnumpy()[4], dense[4])
        np.testing.assert_allclose(kept.asnumpy()[1], 0.0)

    def test_from_components(self):
        rsp = sparse.row_sparse_array(
            (np.ones((2, 3), np.float32), [0, 5]), shape=(7, 3))
        out = rsp.asnumpy()
        np.testing.assert_allclose(out[0], 1.0)
        np.testing.assert_allclose(out[5], 1.0)
        assert out.sum() == 6.0

    def test_dense_tostype(self):
        dense = nd.array(np.eye(4, dtype=np.float32))
        rsp = dense.tostype("row_sparse")
        assert rsp.stype == "row_sparse"
        csr = dense.tostype("csr")
        assert csr.stype == "csr"
        np.testing.assert_allclose(rsp.asnumpy(), np.eye(4))
        np.testing.assert_allclose(csr.asnumpy(), np.eye(4))


class TestSparseOptimizer:
    def _grad(self, shape, rows, seed=0):
        g = np.zeros(shape, np.float32)
        g[rows] = np.random.RandomState(seed).randn(
            len(rows), shape[1]).astype(np.float32)
        return g

    def test_sgd_lazy_matches_dense_on_touched_rows(self):
        shape, rows = (10, 4), [2, 7]
        w0 = np.random.RandomState(1).randn(*shape).astype(np.float32)
        gd = self._grad(shape, rows)
        # dense reference update
        opt_d = mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9)
        wd_ = nd.array(w0)
        sd = opt_d.create_state(0, wd_)
        opt_d.update(0, wd_, nd.array(gd), sd)
        # lazy sparse update
        opt_s = mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9)
        ws = nd.array(w0)
        ss = opt_s.create_state(0, ws)
        opt_s.update(0, ws, sparse.row_sparse_array(gd), ss)
        np.testing.assert_allclose(ws.asnumpy(), wd_.asnumpy(), rtol=1e-5,
                                   atol=1e-6)

    def test_sgd_lazy_untouched_rows_frozen(self):
        shape, rows = (10, 4), [0, 3]
        w0 = np.random.RandomState(2).randn(*shape).astype(np.float32)
        opt = mx.optimizer.create("sgd", learning_rate=0.5, momentum=0.9,
                                  wd=0.1)
        w = nd.array(w0)
        s = opt.create_state(0, w)
        opt.update(0, w, sparse.row_sparse_array(self._grad(shape, rows)),
                   s)
        out = w.asnumpy()
        untouched = [i for i in range(10) if i not in rows]
        # untouched rows see NO update (not even weight decay) — the lazy
        # contract
        np.testing.assert_array_equal(out[untouched], w0[untouched])
        assert np.abs(out[rows] - w0[rows]).max() > 0

    def test_adam_lazy_converges(self):
        """Sparse embedding-style regression with lazy adam."""
        vocab, dim = 50, 8
        rng = np.random.RandomState(0)
        true_emb = rng.randn(vocab, dim).astype(np.float32)
        opt = mx.optimizer.create("adam", learning_rate=0.05)
        w = nd.array(np.zeros((vocab, dim), np.float32))
        state = opt.create_state(0, w)
        for step in range(800):
            idx = rng.randint(0, vocab, size=8)
            uniq = np.unique(idx)
            grad_rows = w.asnumpy()[uniq] - true_emb[uniq]
            rsp = sparse.row_sparse_array((grad_rows, uniq),
                                          shape=(vocab, dim))
            opt.update(0, w, rsp, state)
        err = np.abs(w.asnumpy() - true_emb).mean()
        assert err < 0.03, err


class TestSparseEmbeddingTraining:
    def test_gluon_embedding_sparse_grad(self):
        from mxnet_tpu import gluon, autograd
        mx.random.seed(0)
        net = gluon.nn.Embedding(20, 4, sparse_grad=True)
        net.initialize(mx.init.Normal(0.1))
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 1.0, "momentum": 0.0})
        w_before = None
        x = nd.array(np.array([1, 5, 5], np.float32))
        with autograd.record():
            out = net(x)
            loss = out.sum()
        loss.backward()
        w_before = net.weight.data().asnumpy().copy()
        trainer.step(1)
        w_after = net.weight.data().asnumpy()
        changed = np.abs(w_after - w_before).sum(axis=1) > 0
        assert changed[1] and changed[5]
        assert not changed[0] and not changed[19]
