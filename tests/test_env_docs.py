"""The env-knob registry, its documentation, and the actual getenv
call-sites must agree (SURVEY.md §5.6: ONE documented registry, not
scattered getenv — VERDICT r3 flagged the doc drifting)."""
import os
import re
import subprocess
import sys

import mxnet_tpu as mx

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOC = os.path.join(REPO, "docs", "env_vars.md")

# env reads through these call forms define a knob (bare mentions in
# comments/docstrings citing the reference do not)
_READ = re.compile(
    r"(?:get_env|env_truthy|environ\.get|environ\[|getenv|_env)\(\s*"
    r"[\"'](MXNET_[A-Z0-9_]+)[\"']")


def _code_knobs():
    found = {}
    for dirpath, _dirs, files in os.walk(os.path.join(REPO, "mxnet_tpu")):
        if "__pycache__" in dirpath:
            continue
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path) as f:
                src = f.read()
            for m in _READ.finditer(src):
                found.setdefault(m.group(1), path)
    return found


def test_every_read_knob_is_documented():
    with open(DOC) as f:
        doc = f.read()
    undocumented = {k: v for k, v in _code_knobs().items() if k not in doc}
    assert not undocumented, (
        f"env knobs read in code but absent from docs/env_vars.md: "
        f"{undocumented} — declare them via mx.base.declare_env and run "
        f"tools/gen_env_docs.py")


def test_registry_matches_doc_table():
    with open(DOC) as f:
        doc = f.read()
    rows = set(re.findall(r"^\| `(MXNET_[A-Z0-9_]+)` \|", doc, re.M))
    reg = set(mx.base.list_env_vars())
    assert rows == reg, (
        f"doc table vs declare_env registry: only in doc {rows - reg}, "
        f"only in registry {reg - rows} — run tools/gen_env_docs.py")


def test_generator_check_mode_green():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "gen_env_docs.py"),
         "--check"], env=env, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
