"""Quantized collectives (ISSUE-10): the mxnet_tpu.quantize core, the
kvstore int8/fp8 compressed allreduce (quant/dequant INSIDE the jitted
collective), the kvstore.wire.bytes accounting, and the ShardedTrainer
quantized data-parallel gradient sync with error feedback."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import gluon, kvstore, nd, parallel
from mxnet_tpu import quantize as qz
from mxnet_tpu import runtime_metrics as rm
from mxnet_tpu.base import MXNetError

CTXS = [mx.cpu(0), mx.cpu(1)]


def _rand(shape, seed=0, scale=1.0):
    return (np.random.RandomState(seed).uniform(-1, 1, shape)
            * scale).astype("float32")


# ---------------------------------------------------------------- spec
class TestCompressionSpec:
    def test_parse_string_and_options(self):
        spec = qz.CompressionSpec.parse("int8:block=64,stochastic=1")
        assert (spec.kind, spec.block, spec.stochastic) \
            == ("int8", 64, True)
        assert spec.error_feedback is True
        spec = qz.CompressionSpec.parse("fp8:error_feedback=0")
        assert spec.kind == "fp8" and spec.error_feedback is False

    def test_parse_dict_none_and_passthrough(self):
        assert qz.CompressionSpec.parse(None) is None
        assert qz.CompressionSpec.parse("none") is None
        spec = qz.CompressionSpec.parse({"type": "int8", "block": 32})
        assert spec.block == 32
        assert qz.CompressionSpec.parse(spec) is spec

    def test_parse_rejects_unknown(self):
        with pytest.raises(MXNetError, match="unknown kind"):
            qz.CompressionSpec.parse("int4")
        with pytest.raises(MXNetError, match="unknown params"):
            qz.CompressionSpec.parse({"type": "int8", "threshold": 1})
        with pytest.raises(MXNetError, match="malformed option"):
            qz.CompressionSpec.parse("int8:block")

    def test_fp8_stochastic_rejected_not_ignored(self):
        # fp8 rounds in the e4m3 cast; silently ignoring stochastic=1
        # would hand back biased rounding where unbiased was asked for
        with pytest.raises(MXNetError, match="int8-only"):
            qz.CompressionSpec.parse("fp8:stochastic=1")

    def test_from_env(self, monkeypatch):
        monkeypatch.setenv("MXNET_KVSTORE_GRAD_COMPRESSION",
                           "int8:block=16")
        spec = qz.CompressionSpec.from_env()
        assert spec.kind == "int8" and spec.block == 16
        monkeypatch.delenv("MXNET_KVSTORE_GRAD_COMPRESSION")
        assert qz.CompressionSpec.from_env() is None

    def test_immutable_hashable(self):
        spec = qz.CompressionSpec("int8")
        with pytest.raises(AttributeError):
            spec.block = 7
        assert spec == qz.CompressionSpec("int8") \
            and hash(spec) == hash(qz.CompressionSpec("int8"))


# ----------------------------------------------------------- quant core
class TestQuantCore:
    @pytest.mark.parametrize("kind", ["int8", "fp8"])
    def test_roundtrip_error_bounded_by_block_scale(self, kind):
        spec = qz.CompressionSpec(kind, block=32)
        x = jnp.asarray(_rand((40, 13), 3))
        payload, scales = qz.quantize(x, spec)
        assert payload.dtype == spec.wire_dtype
        assert scales.shape == (qz._nblocks(x.size, spec),)
        back = qz.dequantize(payload, scales, x.shape, x.dtype)
        # per-element error <= half a quantization step of its block
        # (fp8's mantissa step at the block max is coarser than int8's)
        step = np.repeat(np.asarray(scales), spec.block)[:x.size]
        err = np.abs(np.asarray(back - x)).ravel()
        slack = 0.51 if kind == "int8" else 16.1
        assert (err <= step * slack + 1e-7).all()

    def test_blockwise_scales_track_local_magnitude(self):
        # one huge block would drown the small half in quant noise;
        # blockwise scales keep each half's error proportional to ITS
        # own magnitude
        spec = qz.CompressionSpec("int8", block=64)
        x = jnp.concatenate([jnp.full((64,), 100.0),
                             jnp.full((64,), 1e-3)])
        _, scales = qz.quantize(x, spec)
        assert float(scales[0]) > 0.5 and float(scales[1]) < 1e-4

    def test_zero_block_survives(self):
        spec = qz.CompressionSpec("int8", block=8)
        x = jnp.zeros((16,))
        payload, scales = qz.quantize(x, spec)
        assert np.asarray(qz.dequantize(payload, scales, x.shape,
                                        x.dtype)).sum() == 0.0

    def test_stochastic_rounding_unbiased(self):
        spec = qz.CompressionSpec("int8", block=8, stochastic=True)
        # 0.3 quantization steps above a representable point: determin-
        # istic rounding always lands below; stochastic averages to it
        x = jnp.full((8,), 10.3 / 127.0 * 1.0)
        got = []
        for i in range(200):
            p, s = qz.quantize(x, spec, key=jax.random.PRNGKey(i))
            got.append(float(np.asarray(
                qz.dequantize(p, s, x.shape, x.dtype))[0]))
        assert abs(np.mean(got) - float(x[0])) < 0.1 * float(s[0])
        with pytest.raises(MXNetError, match="PRNG key"):
            qz.quantize(x, spec)

    def test_error_feedback_residual(self):
        spec = qz.CompressionSpec("int8", block=8)
        g = jnp.asarray(_rand((8,), 1))
        res = jnp.zeros((8,))
        payload, scales, new_res = qz.quantize_with_feedback(
            g, res, spec)
        deq = qz.dequantize(payload, scales, g.shape, jnp.float32)
        np.testing.assert_allclose(np.asarray(new_res),
                                   np.asarray(g - deq), rtol=1e-6)
        no_ef = qz.CompressionSpec("int8", block=8,
                                   error_feedback=False)
        _, _, r2 = qz.quantize_with_feedback(g, res, no_ef)
        assert np.asarray(r2).sum() == 0.0

    def test_wire_bytes_math(self):
        spec = qz.CompressionSpec("int8", block=128)
        # 300 elems -> 3 blocks: 384 payload bytes + 12 scale bytes
        assert qz.wire_bytes(300, spec) == 3 * 128 + 3 * 4
        assert qz.logical_bytes(300, "float32") == 1200
        assert qz.logical_bytes(300, "bfloat16") == 600

    def test_tensor_quant_roundtrip(self):
        spec = qz.CompressionSpec("int8")
        w = _rand((32, 16), 5)
        scale = qz.tensor_scale(w, spec)
        q = qz.quantize_tensor(w, scale, spec)
        back = np.asarray(qz.dequantize_tensor(q, scale, jnp.float32))
        assert np.abs(back - w).max() <= scale * 0.51 + 1e-7


# ------------------------------------------------------------- kvstore
class TestKVStoreQuantized:
    @pytest.mark.parametrize("kind", ["int8", "fp8"])
    def test_xla_compressed_pushpull_parity(self, kind):
        kv = kvstore.create("xla")
        kv.set_gradient_compression({"type": kind, "block": 128})
        shape = (128, 40)
        kv.init("w", nd.zeros(shape))
        a, b = _rand(shape, 1, 0.1), _rand(shape, 2, 0.1)
        vals = [nd.array(a, ctx=CTXS[0]), nd.array(b, ctx=CTXS[1])]
        outs = [nd.zeros(shape, ctx=c) for c in CTXS]
        kv.pushpull("w", vals, out=outs)
        want = a + b
        err = np.abs(outs[0].asnumpy() - want).max()
        # one step's quantization error is bounded by ~a block step
        # per device contribution
        assert err < 0.02, err
        np.testing.assert_array_equal(outs[0].asnumpy(),
                                      outs[1].asnumpy())

    def test_xla_wire_bytes_ratio(self):
        rm.enable()
        rm.reset()
        try:
            kv = kvstore.create("xla")
            kv.set_gradient_compression({"type": "int8"})
            shape = (256, 64)          # 16384 elems = 128 full blocks
            kv.init("w", nd.zeros(shape))
            vals = [nd.array(_rand(shape, i, 0.1), ctx=c)
                    for i, c in enumerate(CTXS)]
            outs = [nd.zeros(shape, ctx=c) for c in CTXS]
            kv.pushpull("w", vals, out=outs)
            push = rm.KV_PUSH_BYTES.value()
            wire = rm.KV_WIRE_BYTES.value()
            assert push / wire >= 3.5, (push, wire)
            # the ISSUE CI criterion spelling
            assert wire < push / 3, (push, wire)
        finally:
            rm.disable()
            rm.reset()

    def test_xla_uncompressed_wire_equals_logical(self):
        rm.enable()
        rm.reset()
        try:
            kv = kvstore.create("xla")
            shape = (64, 8)
            kv.init("w", nd.zeros(shape))
            vals = [nd.array(_rand(shape, i), ctx=c)
                    for i, c in enumerate(CTXS)]
            outs = [nd.zeros(shape, ctx=c) for c in CTXS]
            kv.pushpull("w", vals, out=outs)
            assert rm.KV_WIRE_BYTES.value() \
                == rm.KV_PUSH_BYTES.value() > 0
        finally:
            rm.disable()
            rm.reset()

    def test_xla_error_feedback_converges(self):
        """Repeated pushes of the SAME grads: the running mean of the
        quantized allreduce approaches the exact sum (EF cancels the
        rounding error across steps)."""
        kv = kvstore.create("xla")
        kv.set_gradient_compression({"type": "int8", "block": 64})
        shape = (64, 9)
        kv.init("w", nd.zeros(shape))
        a, b = _rand(shape, 1, 0.1), _rand(shape, 2, 0.1)
        vals = [nd.array(a, ctx=CTXS[0]), nd.array(b, ctx=CTXS[1])]
        outs = [nd.zeros(shape, ctx=c) for c in CTXS]
        want = a + b
        kv.pushpull("w", vals, out=outs)
        one_step = np.abs(outs[0].asnumpy() - want).max()
        acc = np.zeros(shape, np.float32)
        steps = 16
        for _ in range(steps):
            kv.pushpull("w", vals, out=outs)
            acc += outs[0].asnumpy()
        averaged = np.abs(acc / steps - want).max()
        assert averaged < one_step / 3, (averaged, one_step)

    def test_xla_compressed_multi_key_bucket_fusion(self):
        kv = kvstore.create("xla")
        kv.set_gradient_compression({"type": "int8", "block": 64})
        kv.bigarray_bound = 256     # force shared + solo buckets
        shapes = [(7,), (130,), (300,)]
        keys = [str(i) for i in range(len(shapes))]
        kv.init(keys, [nd.zeros(s) for s in shapes])
        per_key, want = [], []
        for i, s in enumerate(shapes):
            a, b = _rand(s, i, 0.1), _rand(s, 100 + i, 0.1)
            per_key.append([nd.array(a, ctx=CTXS[0]),
                            nd.array(b, ctx=CTXS[1])])
            want.append(a + b)
        outs = [[nd.zeros(s, ctx=c) for c in CTXS] for s in shapes]
        kv.pushpull(keys, per_key, out=outs)
        for i in range(len(shapes)):
            assert np.abs(outs[i][0].asnumpy() - want[i]).max() < 0.02

    def test_local_tier_quant_compressor(self):
        kv = kvstore.create("device")
        kv.set_gradient_compression("int8:block=32")
        shape = (64,)
        kv.init("0", nd.zeros(shape))
        g = _rand(shape, 3, 0.1)
        vals = [nd.array(g, ctx=c) for c in CTXS]
        outs = [nd.zeros(shape, ctx=CTXS[0])]
        kv.pushpull("0", vals, out=outs)
        assert np.abs(outs[0].asnumpy() - 2 * g).max() < 0.01

    def test_env_knob_compresses_created_stores(self, monkeypatch):
        monkeypatch.setenv("MXNET_KVSTORE_GRAD_COMPRESSION", "int8")
        kv = kvstore.create("xla")
        from mxnet_tpu.kvstore.kvstore import _QuantCompressor
        assert isinstance(kv._compressor, _QuantCompressor)
        assert kv._compressor.spec.kind == "int8"
        # per-store override back to uncompressed must work (the env
        # default would otherwise be sticky for the whole process)
        kv.set_gradient_compression(None)
        assert kv._compressor is None

    def test_xla_classic_push_path_still_compresses(self):
        """push() (not the fused pushpull) also routes through the
        in-collective quantizer — wire bytes shrink and the stored
        value is the quantized sum (no silent f32 fallback)."""
        rm.enable()
        rm.reset()
        try:
            kv = kvstore.create("xla")
            kv.set_gradient_compression({"type": "int8"})
            shape = (256, 16)
            kv.init("w", nd.zeros(shape))
            a, b = _rand(shape, 1, 0.1), _rand(shape, 2, 0.1)
            kv.push("w", [nd.array(a, ctx=CTXS[0]),
                          nd.array(b, ctx=CTXS[1])])
            outs = [nd.zeros(shape, ctx=CTXS[0])]
            kv.pull("w", out=outs)
            assert np.abs(outs[0].asnumpy() - (a + b)).max() < 0.02
            push = rm.KV_PUSH_BYTES.value()
            wire = rm.KV_WIRE_BYTES.value()
            assert wire < push / 3, (push, wire)
        finally:
            rm.disable()
            rm.reset()

    def test_int8_int_dtype_keys_stay_exact(self):
        """Non-float keys bypass quantization (exact psum)."""
        kv = kvstore.create("xla")
        kv.set_gradient_compression({"type": "int8"})
        kv.init("i", nd.array(np.zeros((8,), "int32")))
        vals = [nd.array(np.arange(8, dtype="int32"), ctx=c)
                for c in CTXS]
        outs = [nd.array(np.zeros((8,), "int32"), ctx=CTXS[0])]
        kv.pushpull("i", vals, out=outs)
        np.testing.assert_array_equal(
            outs[0].asnumpy(), 2 * np.arange(8, dtype="int32"))


# -------------------------------------------------------- ShardedTrainer
def _mlp():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, activation="relu"), gluon.nn.Dense(1))
    net.initialize(mx.init.Xavier())
    return net


def _mse(out, y):
    return ((out - y) ** 2).mean()


class TestShardedTrainerCompression:
    def test_requires_pure_dp_mesh(self):
        mesh = parallel.make_mesh(dp=4, tp=2)
        net = _mlp()
        x = nd.array(_rand((8, 8), 1))
        with pytest.raises(MXNetError, match="pure data-parallel"):
            parallel.ShardedTrainer(net, _mse, mesh,
                                    example_inputs=(x,),
                                    compression="int8")

    def test_compressed_step_matches_f32(self):
        mesh = parallel.make_mesh(dp=8)
        X = _rand((16, 8), 7)
        Y = (X @ _rand((8, 1), 8) + 0.1).astype("float32")
        xs, ys = nd.array(X), nd.array(Y)

        def run(compression):
            mx.random.seed(0)
            tr = parallel.ShardedTrainer(
                _mlp(), _mse, mesh, optimizer="adamw",
                optimizer_params={"learning_rate": 1e-2},
                example_inputs=(xs,), n_labels=1,
                compression=compression)
            return [float(jax.device_get(tr.step(xs, ys)))
                    for _ in range(8)], tr

        f32, _ = run(None)
        int8, tr = run("int8")
        # forward loss on identical params must match exactly-ish; the
        # trajectory stays within tight tolerance thanks to EF
        assert abs(f32[0] - int8[0]) < 1e-4
        assert abs(f32[-1] - int8[-1]) < 0.05 * abs(f32[0])
        assert int8[-1] < int8[0] * 0.5, "compressed run not learning"
        assert tr.wire_bytes_per_step < tr.logical_bytes_per_step
        assert len(tr.residuals) > 0

    def test_stochastic_rounding_variant_learns(self):
        mesh = parallel.make_mesh(dp=8)
        X = _rand((16, 8), 3)
        Y = (X @ _rand((8, 1), 4)).astype("float32")
        xs, ys = nd.array(X), nd.array(Y)
        mx.random.seed(0)
        tr = parallel.ShardedTrainer(
            _mlp(), _mse, mesh, optimizer="adamw",
            optimizer_params={"learning_rate": 1e-2},
            example_inputs=(xs,), n_labels=1,
            compression="int8:stochastic=1")
        losses = [float(jax.device_get(tr.step(xs, ys)))
                  for _ in range(6)]
        assert losses[-1] < losses[0]

    def test_wire_counter_increments(self):
        rm.enable()
        rm.reset()
        try:
            mesh = parallel.make_mesh(dp=8)
            xs = nd.array(_rand((8, 8), 1))
            ys = nd.array(_rand((8, 1), 2))
            tr = parallel.ShardedTrainer(
                _mlp(), _mse, mesh, example_inputs=(xs,), n_labels=1,
                compression="int8")
            tr.step(xs, ys)
            tr.step(xs, ys)
            assert rm.KV_WIRE_BYTES.value() \
                == 2 * tr.wire_bytes_per_step > 0
        finally:
            rm.disable()
            rm.reset()


class TestConvergenceParity:
    """ISSUE-10 satellite: BERT-tiny trained N steps with int8
    error-feedback compression matches the f32 run's loss within
    tolerance, on the fake-multidevice harness (tier-1 cheap: 1-layer
    tiny config, 6 steps)."""

    def test_bert_tiny_int8_matches_f32(self):
        from mxnet_tpu import models
        devices = jax.devices()[:4]
        mesh = parallel.make_mesh(dp=4, devices=devices)
        rng = np.random.RandomState(0)
        B, L, V = 8, 8, 64
        inputs = nd.array(rng.randint(0, V, (B, L)), dtype="int32")
        token_types = nd.zeros((B, L), dtype="int32")
        valid_length = nd.array(np.full((B,), L, np.float32))
        labels = nd.array(rng.randint(0, 2, (B,)), dtype="int32")

        def loss_fn(logits, labels):
            logp = jax.nn.log_softmax(logits, axis=-1)
            return -jnp.take_along_axis(
                logp, labels[:, None], axis=1).mean()

        def run(compression, steps=6):
            mx.random.seed(0)
            bert = models.get_bert_model(
                "bert_12_768_12", vocab_size=V, units=32,
                hidden_size=64, num_layers=1, num_heads=2,
                max_length=16, dropout=0.0)
            bert.initialize()
            head = models.BERTClassifier(bert, num_classes=2,
                                         dropout=0.0)
            head.initialize()
            tr = parallel.ShardedTrainer(
                head, loss_fn, mesh, optimizer="adamw",
                optimizer_params={"learning_rate": 5e-3},
                example_inputs=(inputs, token_types, valid_length),
                n_labels=1, compression=compression)
            return [float(jax.device_get(
                tr.step(inputs, token_types, valid_length, labels)))
                for _ in range(steps)]

        f32 = run(None)
        int8 = run("int8")
        assert np.isfinite(int8).all()
        # identical initial forward; per-step drift bounded; final loss
        # within 3% (absolute floor for near-zero losses)
        assert abs(f32[0] - int8[0]) < 1e-4, (f32[0], int8[0])
        tol = max(0.03 * abs(f32[-1]), 0.03)
        assert abs(f32[-1] - int8[-1]) < tol, (f32, int8)
        assert int8[-1] < int8[0], "int8 run did not descend"
