"""mx.image tests: codecs (incl. the pure-numpy PNG fallback), augmenters,
ImageIter, and the ImageFolderDataset path that VERDICT r1 flagged as a
dangling import."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import image as img
from mxnet_tpu import nd


def _rand_img(h=24, w=32, c=3, seed=0):
    return np.random.RandomState(seed).randint(
        0, 255, size=(h, w, c)).astype(np.uint8)


class TestCodecs:
    def test_png_roundtrip_builtin_codec(self, tmp_path):
        """The pure-numpy codec is exercised directly: encode->decode is
        lossless regardless of the backend cv2/PIL chain."""
        arr = _rand_img()
        data = img.image._png_encode(arr)
        out = img.image._png_decode(data)
        np.testing.assert_array_equal(out, arr)

    def test_png_roundtrip_gray(self):
        arr = _rand_img(c=1)
        out = img.image._png_decode(img.image._png_encode(arr))
        np.testing.assert_array_equal(out, arr)

    def test_imwrite_imread_roundtrip(self, tmp_path):
        arr = _rand_img()
        path = str(tmp_path / "x.png")
        img.imwrite(path, arr)
        back = img.imread(path)
        assert back.shape == arr.shape
        np.testing.assert_array_equal(back.asnumpy(), arr)

    def test_imread_grayscale_flag(self, tmp_path):
        arr = _rand_img()
        path = str(tmp_path / "x.png")
        img.imwrite(path, arr)
        gray = img.imread(path, flag=0)
        assert gray.shape == (24, 32, 1)

    def test_imread_missing_raises(self):
        with pytest.raises(mx.MXNetError):
            img.imread("/nonexistent/zzz.png")


class TestTransforms:
    def test_imresize(self):
        out = img.imresize(_rand_img(), 16, 12)
        assert out.shape == (12, 16, 3)

    def test_resize_short(self):
        out = img.resize_short(_rand_img(h=24, w=48), 12)
        assert out.shape == (12, 24, 3)

    def test_center_and_random_crop(self):
        arr = _rand_img(h=30, w=40)
        out, (x0, y0, w, h) = img.center_crop(arr, (20, 16))
        assert out.shape == (16, 20, 3) and (w, h) == (20, 16)
        out2, _ = img.random_crop(arr, (20, 16))
        assert out2.shape == (16, 20, 3)

    def test_color_normalize(self):
        arr = np.full((4, 4, 3), 100, np.uint8)
        out = img.color_normalize(arr, mean=(100, 100, 100), std=(2, 2, 2))
        np.testing.assert_allclose(out.asnumpy(), 0.0)

    def test_create_augmenter_pipeline(self):
        augs = img.CreateAugmenter((3, 16, 16), resize=20, rand_crop=True,
                                   rand_mirror=True, brightness=0.1,
                                   mean=True, std=True)
        out = _rand_img(h=40, w=50)
        x = nd.array(out, dtype="uint8")
        for a in augs:
            x = a(x)
        assert x.shape == (16, 16, 3)
        assert str(x.dtype) == "float32"

    def test_hue_and_gray_augs(self):
        x = nd.array(_rand_img(), dtype="uint8")
        h = img.HueJitterAug(0.5)(x)
        assert h.shape == x.shape
        g = img.RandomGrayAug(1.0)(x)
        a = g.asnumpy()
        np.testing.assert_allclose(a[..., 0], a[..., 1], rtol=1e-5)


class TestImageIter:
    def _write_folder(self, root, n_per_class=4):
        for cls in ("cat", "dog"):
            os.makedirs(os.path.join(root, cls), exist_ok=True)
            for i in range(n_per_class):
                img.imwrite(os.path.join(root, cls, f"{i}.png"),
                            _rand_img(seed=hash((cls, i)) % 1000))

    def test_imageiter_from_imglist(self, tmp_path):
        root = str(tmp_path)
        self._write_folder(root)
        imglist = [[0.0, os.path.join("cat", f"{i}.png")] for i in range(4)]
        imglist += [[1.0, os.path.join("dog", f"{i}.png")] for i in range(4)]
        it = img.ImageIter(batch_size=4, data_shape=(3, 16, 16),
                           imglist=imglist, path_root=root, shuffle=True)
        batch = next(it)
        assert batch.data[0].shape == (4, 3, 16, 16)
        assert batch.label[0].shape == (4,)
        n = 1 + sum(1 for _ in it)
        assert n == 2
        it.reset()
        assert next(it) is not None

    def test_imageiter_from_recordio(self, tmp_path):
        from mxnet_tpu import recordio
        rec_path = str(tmp_path / "data.rec")
        idx_path = str(tmp_path / "data.idx")
        rec = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
        for i in range(6):
            arr = _rand_img(seed=i)
            payload = img.imencode(arr, ext=".png")
            header = recordio.IRHeader(0, float(i % 2), i, 0)
            rec.write_idx(i, recordio.pack(header, payload))
        rec.close()
        it = img.ImageIter(batch_size=3, data_shape=(3, 16, 16),
                           path_imgrec=rec_path)
        batch = next(it)
        assert batch.data[0].shape == (3, 3, 16, 16)
        labels = batch.label[0].asnumpy()
        assert set(labels) <= {0.0, 1.0}


class TestImageFolderDataset:
    def test_folder_dataset_reads_real_pngs(self, tmp_path):
        """VERDICT r1: gluon ImageFolderDataset crashed on a dangling
        `image` import; now it must read real files."""
        from mxnet_tpu.gluon.data.vision import ImageFolderDataset
        root = str(tmp_path)
        TestImageIter()._write_folder(root, n_per_class=3)
        ds = ImageFolderDataset(root)
        assert len(ds) == 6
        assert sorted(ds.synsets) == ["cat", "dog"]
        x, y = ds[0]
        assert x.shape == (24, 32, 3)
        assert y in (0, 1)
