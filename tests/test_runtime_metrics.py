"""Runtime metrics registry: primitives, exporters, and the
instrumented hot layers (op dispatch, engine, io, kvstore, trainer)."""
import json
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, profiler, runtime_metrics as rm


@pytest.fixture(autouse=True)
def _metrics_on():
    """Enable + zero the registry per test, restore the off default."""
    rm.reset()
    rm.enable()
    yield
    rm.disable()
    rm.reset()


class TestPrimitives:
    def test_disabled_path_is_noop(self):
        rm.disable()
        c = rm.counter("t.disabled.counter")
        g = rm.gauge("t.disabled.gauge")
        h = rm.histogram("t.disabled.hist")
        c.inc(5)
        g.set(3.0)
        h.observe(0.1)
        assert c.value() == 0
        assert g.value() == 0
        assert h.count() == 0

    def test_counter_concurrent_increments(self):
        c = rm.counter("t.concurrent", labelnames=("who",))
        n_threads, n_incs = 8, 500

        def worker(i):
            for _ in range(n_incs):
                c.inc(who=str(i % 2))

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.total() == n_threads * n_incs
        assert c.value(who="0") == n_threads * n_incs / 2

    def test_counter_rejects_negative(self):
        c = rm.counter("t.neg")
        with pytest.raises(mx.MXNetError):
            c.inc(-1)
        # validation is independent of the registry switch — a bad call
        # site must not run clean in metrics-off environments
        rm.disable()
        with pytest.raises(mx.MXNetError):
            c.inc(-1)

    def test_histogram_bucket_conflict_rejected(self):
        rm.histogram("t.bucket.conflict", buckets=(1.0, 2.0))
        with pytest.raises(mx.MXNetError, match="buckets"):
            rm.histogram("t.bucket.conflict", buckets=(5.0,))
        # same buckets (any order) re-resolve fine
        rm.histogram("t.bucket.conflict", buckets=(2.0, 1.0))
        # omitting buckets returns the existing metric unchecked
        assert rm.histogram("t.bucket.conflict").buckets == (1.0, 2.0)

    def test_gauge_set_max_and_incdec(self):
        g = rm.gauge("t.gauge")
        g.set(5)
        g.set_max(3)
        assert g.value() == 5
        g.set_max(9)
        assert g.value() == 9
        g.inc(1)
        g.dec(4)
        assert g.value() == 6

    def test_histogram_quantiles(self):
        h = rm.histogram("t.hist", buckets=(1, 2, 4, 8))
        for v in (0.5, 1.5, 1.5, 3, 6):
            h.observe(v)
        assert h.count() == 5
        assert h.sum() == pytest.approx(12.5)
        q50 = h.quantile(0.5)
        assert 1.0 <= q50 <= 2.0       # median lands in the (1, 2] bucket
        assert h.quantile(1.0) <= 8.0
        assert h.quantile(0.0) <= 1.0
        # overflow values clamp to the last finite bound
        h.observe(100.0)
        assert h.quantile(1.0) == 8.0

    def test_registry_type_and_label_conflicts(self):
        rm.counter("t.conflict")
        with pytest.raises(mx.MXNetError):
            rm.gauge("t.conflict")
        rm.counter("t.labeled", labelnames=("a",))
        with pytest.raises(mx.MXNetError):
            rm.counter("t.labeled", labelnames=("b",))
        # get-or-create returns the same object
        assert rm.counter("t.conflict") is rm.counter("t.conflict")


class TestExporters:
    def test_prometheus_text_format(self):
        c = rm.counter("t.prom.ops", "op calls", labelnames=("op",))
        c.inc(3, op="dot")
        g = rm.gauge("t.prom.depth")
        g.set(2)
        h = rm.histogram("t.prom.lat", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5)
        txt = rm.dump_prometheus()
        assert 't_prom_ops_total{op="dot"} 3' in txt
        assert "# TYPE t_prom_ops_total counter" in txt
        assert "t_prom_depth 2" in txt
        assert 't_prom_lat_bucket{le="0.1"} 1' in txt
        assert 't_prom_lat_bucket{le="+Inf"} 2' in txt
        assert "t_prom_lat_count 2" in txt

    def test_chrome_counter_events_merge_into_profiler_dump(self):
        profiler.set_config(filename="/tmp/_rm_merge.json")
        profiler.start()
        (nd.ones((4, 4)) * 2).wait_to_read()
        profiler.stop()
        trace = json.loads(profiler.dumps())
        counters = [e for e in trace["traceEvents"] if e["ph"] == "C"]
        names = {e["name"] for e in counters}
        assert "op.invoke" in names
        ev = next(e for e in counters if e["name"] == "op.invoke")
        assert sum(ev["args"].values()) >= 1

    def test_tensorboard_export_roundtrip(self, tmp_path):
        from mxnet_tpu.contrib.tensorboard import read_events
        rm.counter("t.tb.c").inc(7)
        rm.gauge("t.tb.g").set(1.5)
        rm.histogram("t.tb.h").observe(2.0)
        rm.dump_tensorboard(logdir=str(tmp_path), step=3)
        files = list(tmp_path.iterdir())
        assert len(files) == 1
        tags = {}
        for ev in read_events(str(files[0])):
            tags.update(ev["values"])
        assert tags["t.tb.c"] == pytest.approx(7.0)
        assert tags["t.tb.g"] == pytest.approx(1.5)
        assert tags["t.tb.h.count"] == pytest.approx(1.0)
        assert tags["t.tb.h.mean"] == pytest.approx(2.0)

    def test_snapshot_plain_dict(self):
        rm.counter("t.snap").inc(2)
        snap = rm.snapshot()
        assert snap["t.snap"]["type"] == "counter"
        assert snap["t.snap"]["values"][""] == 2


    def test_prometheus_label_value_escaping(self):
        """Label values are arbitrary user strings (model names): quote,
        backslash, and newline must be escaped per the exposition format
        or a scraper rejects the whole scrape."""
        c = rm.counter("t.prom.esc", labelnames=("model",))
        c.inc(model='net"v2\\x\n')
        txt = rm.dump_prometheus()
        assert 't_prom_esc_total{model="net\\"v2\\\\x\\n"} 1' in txt

    def test_tracked_gauge_resampled_at_export(self):
        """engine.tracked_arrays re-samples the weak dict at scrape time
        — after arrays die it must not keep reporting the stale high
        value set at the last track()."""
        import gc
        arrays = [nd.ones((2,)) for _ in range(50)]
        mx.waitall()
        assert rm.ENGINE_TRACKED.value() >= 50
        del arrays
        gc.collect()
        rm.dump_prometheus()                # runs collect hooks
        from mxnet_tpu.engine import Engine
        assert rm.ENGINE_TRACKED.value() == len(Engine.get()._live)
        assert rm.ENGINE_TRACKED.value() < 50


class TestInstrumentation:
    def test_op_invoke_counter_and_latency(self):
        a = nd.ones((8, 8))
        b = nd.ones((8, 8))
        nd.dot(a, b).wait_to_read()
        assert rm.OP_INVOKE.value(op="dot") >= 1
        assert rm.OP_DISPATCH_SECONDS.count(op="dot") >= 1
        assert "op_invoke_total" in rm.dump_prometheus()

    def test_engine_waitall_and_watermark(self):
        nd.ones((4,))
        mx.waitall()
        assert rm.ENGINE_WAITALL.value() >= 1
        assert rm.ENGINE_WAITALL_SECONDS.count() >= 1
        assert rm.ENGINE_TRACKED_PEAK.value() >= 1

    def test_io_batches_counter(self):
        data = np.random.rand(10, 3).astype(np.float32)
        it = mx.io.NDArrayIter(data, np.zeros(10, np.float32),
                               batch_size=5)
        n = sum(1 for _ in it)
        assert n == 2
        assert rm.IO_BATCHES.value() == 2
        assert "io_batches_total 2" in rm.dump_prometheus()

    def test_kvstore_push_pull_bytes(self):
        kv = mx.kv.create("local")
        v = nd.ones((16,))          # 64 bytes float32
        kv.init("w", v)
        kv.push("w", nd.ones((16,)))
        out = nd.zeros((16,))
        kv.pull("w", out=out)
        assert rm.KV_PUSH.value() == 1
        assert rm.KV_PUSH_BYTES.value() == 64
        assert rm.KV_PULL.value() == 1
        assert rm.KV_PULL_BYTES.value() == 64
        assert "kvstore_push_bytes_total 64" in rm.dump_prometheus()

    def test_trainer_step_histogram(self):
        from mxnet_tpu import autograd, gluon
        net = gluon.nn.Dense(2)
        net.initialize()
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1})
        x = nd.ones((4, 3))
        with autograd.record():
            loss = net(x).sum()
        loss.backward()
        trainer.step(4)
        assert rm.TRAINER_STEP_SECONDS.count() == 1
        assert "trainer_step_seconds_bucket" in rm.dump_prometheus()

    def test_trainer_grad_norm_gauge_gated(self, monkeypatch):
        from mxnet_tpu import autograd, gluon
        monkeypatch.setattr(rm, "_GRAD_NORM", True)
        net = gluon.nn.Dense(2)
        net.initialize()
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1})
        x = nd.ones((4, 3))
        with autograd.record():
            loss = net(x).sum()
        loss.backward()
        trainer.step(4)
        assert rm.TRAINER_GRAD_NORM.value() > 0

    def test_speedometer_publishes_samples_per_sec(self):
        import time as _time
        from mxnet_tpu.callback import Speedometer

        class _Param:
            epoch, nbatch, eval_metric = 0, 0, None

        sp = Speedometer(batch_size=32, frequent=1)
        p = _Param()
        sp(p)                       # initializes the timer
        _time.sleep(0.01)
        p.nbatch = 1
        sp(p)                       # publishes the gauge
        assert rm.TRAINER_SAMPLES_PER_SEC.value() > 0
        assert "trainer_samples_per_sec" in rm.dump_prometheus()

    def test_after_train_step_all_acceptance_metrics_present(self):
        """ISSUE acceptance: one train step + one io batch yields
        non-zero op_invoke_total, io_batches_total and
        trainer_step_seconds lines in the Prometheus dump."""
        from mxnet_tpu import autograd, gluon
        data = np.random.rand(8, 3).astype(np.float32)
        it = mx.io.NDArrayIter(data, np.zeros(8, np.float32),
                               batch_size=8)
        batch = next(it)
        net = gluon.nn.Dense(2)
        net.initialize()
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1})
        with autograd.record():
            loss = net(batch.data[0]).sum()
        loss.backward()
        trainer.step(8)
        txt = rm.dump_prometheus()
        assert rm.OP_INVOKE.total() > 0 and "op_invoke_total" in txt
        assert "io_batches_total 1" in txt
        assert "trainer_step_seconds_count 1" in txt
