"""gluon.contrib.FusedTrainStep: one-program training step must match the
record/backward/step recipe numerically, keep aux states updating, and
respect LR changes mid-training."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd, gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.contrib import FusedTrainStep


def _make_pair(seed, with_bn=False, optimizer="adam",
               opt_args=None):
    """Two identical (net, trainer) pairs with shared init."""
    opt_args = dict(opt_args or {"learning_rate": 1e-2})
    nets = []
    for _ in range(2):
        mx.random.seed(seed)
        net = nn.HybridSequential()
        # explicit in_units: init draws happen eagerly under the seed, so
        # both copies start from identical weights
        net.add(nn.Dense(16, activation="relu", in_units=4))
        if with_bn:
            net.add(nn.BatchNorm(in_channels=16))
        net.add(nn.Dense(1, in_units=16))
        net.initialize(mx.init.Xavier())
        tr = gluon.Trainer(net.collect_params(), optimizer, dict(opt_args))
        nets.append((net, tr))
    return nets


class LossBlock(gluon.HybridBlock):
    def __init__(self, net, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.net = net

    def hybrid_forward(self, F, x, y):
        return ((self.net(x) - y) ** 2).mean()


def test_matches_three_call_recipe():
    (net_a, tr_a), (net_b, tr_b) = _make_pair(0)
    rng = np.random.RandomState(0)
    X = rng.randn(64, 4).astype(np.float32)
    Y = rng.randn(64, 1).astype(np.float32)

    blk_a = LossBlock(net_a)
    blk_b = LossBlock(net_b)
    blk_a.hybridize(static_alloc=True)
    fused = FusedTrainStep(blk_b, tr_b)

    for step in range(5):
        x, y = nd.array(X), nd.array(Y)
        with autograd.record():
            la = blk_a(x, y)
        la.backward()
        tr_a.step(64)
        lb = fused(x, y, batch_size=64)
        np.testing.assert_allclose(float(la.asscalar()),
                                   float(lb.asscalar()), rtol=1e-5)
    # parameters identical after 5 steps
    for (na, pa), (nb, pb) in zip(net_a.collect_params().items(),
                                  net_b.collect_params().items()):
        np.testing.assert_allclose(pa.data().asnumpy(),
                                   pb.data().asnumpy(), rtol=1e-4,
                                   atol=1e-6)


def test_lr_change_applies():
    (net_a, tr_a), (net_b, tr_b) = _make_pair(1, optimizer="sgd")
    rng = np.random.RandomState(1)
    X = rng.randn(32, 4).astype(np.float32)
    Y = rng.randn(32, 1).astype(np.float32)
    blk_a, blk_b = LossBlock(net_a), LossBlock(net_b)
    fused = FusedTrainStep(blk_b, tr_b)
    for step in range(4):
        if step == 2:
            tr_a.set_learning_rate(1e-3)
            tr_b.set_learning_rate(1e-3)
        x, y = nd.array(X), nd.array(Y)
        with autograd.record():
            la = blk_a(x, y)
        la.backward()
        tr_a.step(32)
        fused(x, y, batch_size=32)
    for (_, pa), (_, pb) in zip(net_a.collect_params().items(),
                                net_b.collect_params().items()):
        np.testing.assert_allclose(pa.data().asnumpy(),
                                   pb.data().asnumpy(), rtol=1e-4,
                                   atol=1e-6)


def test_batchnorm_aux_states_update():
    (net, tr), _ = _make_pair(2, with_bn=True)
    blk = LossBlock(net)
    fused = FusedTrainStep(blk, tr)
    bn = [b for b in net._children.values()
          if isinstance(b, nn.BatchNorm)][0]
    before = bn.running_mean.data().asnumpy().copy()
    rng = np.random.RandomState(2)
    for _ in range(3):
        x = nd.array(rng.randn(32, 4).astype(np.float32) + 5.0)
        y = nd.zeros((32, 1))
        fused(x, y)
    after = bn.running_mean.data().asnumpy()
    assert np.abs(after - before).max() > 1e-3


def test_convergence():
    (net, tr), _ = _make_pair(3)
    blk = LossBlock(net)
    fused = FusedTrainStep(blk, tr)
    rng = np.random.RandomState(3)
    X = rng.randn(128, 4).astype(np.float32)
    Y = (X.sum(1, keepdims=True) * 0.5).astype(np.float32)
    first = last = None
    for i in range(150):
        loss = fused(nd.array(X), nd.array(Y))
        if i == 0:
            first = float(loss.asscalar())
    last = float(loss.asscalar())
    assert last < 0.1 * first, (first, last)


def test_sparse_grad_rejected():
    (net, tr), _ = _make_pair(7)
    p = next(iter(net.collect_params().values()))
    p._grad_stype = "row_sparse"
    with pytest.raises(mx.MXNetError):
        FusedTrainStep(LossBlock(net), tr)


def test_grad_add_rejected():
    (net, tr), _ = _make_pair(4)
    for p in net.collect_params().values():
        p.grad_req = "add"
    with pytest.raises(mx.MXNetError):
        FusedTrainStep(LossBlock(net), tr)


def test_save_load_still_works(tmp_path):
    (net, tr), _ = _make_pair(5)
    blk = LossBlock(net)
    fused = FusedTrainStep(blk, tr)
    rng = np.random.RandomState(5)
    fused(nd.array(rng.randn(8, 4).astype(np.float32)),
          nd.array(rng.randn(8, 1).astype(np.float32)))
    f = str(tmp_path / "net.params")
    net.save_parameters(f)
    (net2, _), _ = _make_pair(6)
    net2(nd.ones((1, 4)))          # shape init
    net2.load_parameters(f)
    for (_, pa), (_, pb) in zip(net.collect_params().items(),
                                net2.collect_params().items()):
        np.testing.assert_allclose(pa.data().asnumpy(),
                                   pb.data().asnumpy())


def test_failure_recovery_poison_and_reset(tmp_path):
    """A step failing after dispatch consumes donated buffers: the guard
    poisons the instance, rolls back update counts, and reset() (after a
    reload) makes training work again."""
    import jax
    import mxnet_tpu.base as base
    (net, tr), _ = _make_pair(3)
    rng = np.random.RandomState(3)
    x = nd.array(rng.randn(8, 4).astype(np.float32))
    y = nd.array(rng.randn(8, 1).astype(np.float32))
    step = FusedTrainStep(LossBlock(net), tr)
    step(x, y)  # build + one good step
    net.save_parameters(str(tmp_path / "fused_recover.params"))
    o = tr._optimizer
    counts_before = dict(o._index_update_count)
    num_update_before = o.num_update

    sig, entry = next(iter(step._cache.items()))
    real_prog = entry["prog"]

    def failing_prog(key, ts, lrs, wds, rescale, inputs, weights,
                     frozen, states):
        # emulate a post-dispatch failure: donated buffers consumed
        for a in jax.tree_util.tree_leaves((ts, weights, states)):
            a.delete()
        raise RuntimeError("synthetic post-dispatch failure")

    entry["prog"] = failing_prog
    with pytest.raises(base.MXNetError, match="donated"):
        step(x, y)
    # counts rolled back: the failed step must not advance schedules
    assert dict(o._index_update_count) == counts_before
    assert o.num_update == num_update_before
    # subsequent calls raise the poisoned guidance without touching counts
    with pytest.raises(base.MXNetError, match="reset"):
        step(x, y)
    assert dict(o._index_update_count) == counts_before

    entry["prog"] = real_prog
    net.load_parameters(str(tmp_path / "fused_recover.params"))
    step.reset()
    l1 = float(step(x, y).asnumpy())
    l2 = float(step(x, y).asnumpy())
    assert np.isfinite(l1) and np.isfinite(l2)


def test_failure_before_donation_does_not_poison():
    """Trace/compile failures happen before donation: weights stay
    intact and the instance is NOT poisoned."""
    (net, tr), _ = _make_pair(4)
    rng = np.random.RandomState(4)
    x = nd.array(rng.randn(8, 4).astype(np.float32))
    y = nd.array(rng.randn(8, 1).astype(np.float32))
    step = FusedTrainStep(LossBlock(net), tr)
    step(x, y)
    sig, entry = next(iter(step._cache.items()))
    real_prog = entry["prog"]

    def pre_dispatch_fail(*a, **k):
        raise ValueError("synthetic compile failure")

    entry["prog"] = pre_dispatch_fail
    with pytest.raises(ValueError, match="synthetic compile"):
        step(x, y)
    assert step._poisoned is None
    entry["prog"] = real_prog
    # weights intact, training continues without reset
    assert np.isfinite(float(step(x, y).asnumpy()))


def test_reset_keeps_reloaded_optimizer_states():
    """reset() must not wipe optimizer states the user restored — only
    states still pointing at deleted buffers are dropped."""
    (net, tr), _ = _make_pair(5)
    rng = np.random.RandomState(5)
    x = nd.array(rng.randn(8, 4).astype(np.float32))
    y = nd.array(rng.randn(8, 1).astype(np.float32))
    step = FusedTrainStep(LossBlock(net), tr)
    step(x, y)
    upd = tr._updater
    live_states = dict(upd.states)
    step._poisoned = RuntimeError("synthetic")
    step.reset()
    assert upd.states == live_states  # live states preserved
