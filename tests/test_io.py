"""Data I/O tests (reference patterns: tests/python/unittest/test_io.py,
test_recordio.py)."""
import gzip
import os
import struct

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import io, nd, recordio, gluon
from mxnet_tpu.base import MXNetError


# ----------------------------------------------------------------- recordio
def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "t.rec")
    payloads = [b"hello", b"", b"x" * 1000, os.urandom(37)]
    w = recordio.MXRecordIO(path, "w")
    for p in payloads:
        w.write(p)
    w.close()
    r = recordio.MXRecordIO(path, "r")
    got = []
    while True:
        rec = r.read()
        if rec is None:
            break
        got.append(rec)
    assert got == payloads


def test_recordio_payload_containing_magic(tmp_path):
    """dmlc multipart framing: payloads embedding the magic word survive."""
    path = str(tmp_path / "m.rec")
    magic = struct.pack("<I", 0xced7230a)
    payloads = [magic, b"a" + magic + b"b", magic * 3, b"pre" + magic]
    w = recordio.MXRecordIO(path, "w")
    for p in payloads:
        w.write(p)
    w.close()
    r = recordio.MXRecordIO(path, "r")
    for want in payloads:
        assert r.read() == want
    assert r.read() is None


def test_indexed_recordio(tmp_path):
    path = str(tmp_path / "i.rec")
    idx_path = str(tmp_path / "i.idx")
    w = recordio.MXIndexedRecordIO(idx_path, path, "w")
    for i in range(10):
        w.write_idx(i, f"record-{i}".encode())
    w.close()
    r = recordio.MXIndexedRecordIO(idx_path, path, "r")
    assert r.keys == list(range(10))
    for i in (3, 0, 9, 5):  # random access
        assert r.read_idx(i) == f"record-{i}".encode()


def test_pack_unpack_header():
    h = recordio.IRHeader(0, 3.5, 7, 0)
    packed = recordio.pack(h, b"payload")
    h2, payload = recordio.unpack(packed)
    assert payload == b"payload"
    assert h2.label == pytest.approx(3.5) and h2.id == 7
    # multi-label via flag
    h = recordio.IRHeader(0, [1.0, 2.0, 3.0], 1, 0)
    h2, payload = recordio.unpack(recordio.pack(h, b"x"))
    np.testing.assert_allclose(h2.label, [1.0, 2.0, 3.0])
    assert payload == b"x"


def test_pack_img_unpack_img():
    img = np.random.RandomState(0).randint(0, 255, (32, 24, 3), np.uint8)
    s = recordio.pack_img(recordio.IRHeader(0, 1.0, 0, 0), img,
                          img_fmt=".png")
    h, img2 = recordio.unpack_img(s)
    assert h.label == pytest.approx(1.0)
    np.testing.assert_array_equal(img2, img)  # png is lossless


# -------------------------------------------------------------- NDArrayIter
def test_ndarray_iter_basic():
    data = np.arange(40, dtype=np.float32).reshape(10, 4)
    label = np.arange(10, dtype=np.float32)
    it = io.NDArrayIter(data, label, batch_size=3, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 4
    assert batches[-1].pad == 2
    np.testing.assert_array_equal(batches[0].data[0].asnumpy(), data[:3])
    np.testing.assert_array_equal(batches[0].label[0].asnumpy(), label[:3])
    # second epoch after reset
    it.reset()
    assert len(list(it)) == 4


def test_ndarray_iter_discard():
    it = io.NDArrayIter(np.zeros((10, 2), np.float32), batch_size=3,
                        last_batch_handle="discard")
    assert len(list(it)) == 3


def test_ndarray_iter_roll_over():
    """Leftover samples must lead the NEXT epoch, never duplicate within
    one epoch (reference last_batch_handle='roll_over' semantics)."""
    data = np.arange(10, dtype=np.float32).reshape(10, 1)
    it = io.NDArrayIter(data, batch_size=4,
                        last_batch_handle="roll_over")
    epoch1 = [b.data[0].asnumpy().ravel() for b in it]
    assert len(epoch1) == 2  # only complete batches
    np.testing.assert_array_equal(np.concatenate(epoch1),
                                  np.arange(8, dtype=np.float32))
    it.reset()
    epoch2 = [b.data[0].asnumpy().ravel() for b in it]
    assert len(epoch2) == 3  # 2 carried + 10 = 12 -> 3 full batches
    np.testing.assert_array_equal(epoch2[0][:2], [8.0, 9.0])
    seen = np.concatenate(epoch2)
    assert len(seen) == len(set(seen.tolist())) + 2  # only the carry repeats


def test_ndarray_iter_dict_input():
    it = io.NDArrayIter({"a": np.zeros((4, 2), np.float32),
                         "b": np.ones((4, 3), np.float32)},
                        batch_size=2)
    names = [d.name for d in it.provide_data]
    assert sorted(names) == ["a", "b"]
    batch = next(it)
    assert len(batch.data) == 2


def test_ndarray_iter_provide_data_desc():
    it = io.NDArrayIter(np.zeros((8, 3, 2), np.float32), batch_size=4)
    desc = it.provide_data[0]
    assert desc.shape == (4, 3, 2)
    assert io.DataDesc.get_batch_axis("NCHW") == 0


# ------------------------------------------------------------------ CSVIter
def test_csv_iter(tmp_path):
    data = np.random.RandomState(0).randn(12, 3).astype(np.float32)
    label = np.arange(12, dtype=np.float32)
    dpath, lpath = str(tmp_path / "d.csv"), str(tmp_path / "l.csv")
    np.savetxt(dpath, data, delimiter=",")
    np.savetxt(lpath, label, delimiter=",")
    it = io.CSVIter(data_csv=dpath, data_shape=(3,), label_csv=lpath,
                    batch_size=4)
    b = next(it)
    np.testing.assert_allclose(b.data[0].asnumpy(), data[:4], rtol=1e-5)
    np.testing.assert_allclose(b.label[0].asnumpy(), label[:4])


def test_csv_iter_sharded(tmp_path):
    data = np.arange(20, dtype=np.float32).reshape(10, 2)
    dpath = str(tmp_path / "d.csv")
    np.savetxt(dpath, data, delimiter=",")
    part0 = io.CSVIter(data_csv=dpath, data_shape=(2,), batch_size=5,
                       num_parts=2, part_index=0)
    part1 = io.CSVIter(data_csv=dpath, data_shape=(2,), batch_size=5,
                       num_parts=2, part_index=1)
    d0 = next(part0).data[0].asnumpy()
    d1 = next(part1).data[0].asnumpy()
    np.testing.assert_array_equal(np.vstack([d0, d1]), data)


# ---------------------------------------------------------------- MNISTIter
def _write_mnist_fixture(tmp_path, n=32, gz=True):
    rng = np.random.RandomState(0)
    images = rng.randint(0, 255, (n, 28, 28), np.uint8)
    labels = rng.randint(0, 10, (n,)).astype(np.uint8)
    ipath = str(tmp_path / ("img.idx3.gz" if gz else "img.idx3"))
    lpath = str(tmp_path / ("lbl.idx1.gz" if gz else "lbl.idx1"))
    opener = gzip.open if gz else open
    with opener(ipath, "wb") as f:
        f.write(struct.pack(">IIII", 0x803, n, 28, 28))
        f.write(images.tobytes())
    with opener(lpath, "wb") as f:
        f.write(struct.pack(">II", 0x801, n))
        f.write(labels.tobytes())
    return ipath, lpath, images, labels


def test_mnist_iter_real_files(tmp_path):
    ipath, lpath, images, labels = _write_mnist_fixture(tmp_path)
    it = io.MNISTIter(image=ipath, label=lpath, batch_size=8,
                      shuffle=False)
    b = next(it)
    assert b.data[0].shape == (8, 1, 28, 28)
    np.testing.assert_allclose(b.data[0].asnumpy()[:, 0] * 255.0,
                               images[:8], atol=1e-4)
    np.testing.assert_array_equal(b.label[0].asnumpy(), labels[:8])


def test_mnist_iter_sharded(tmp_path):
    ipath, lpath, images, labels = _write_mnist_fixture(tmp_path)
    parts = [io.MNISTIter(image=ipath, label=lpath, batch_size=16,
                          shuffle=False, num_parts=2, part_index=i)
             for i in range(2)]
    got = np.concatenate([next(p).label[0].asnumpy() for p in parts])
    np.testing.assert_array_equal(got, labels)


def test_mnist_dataset_real_file_branch(tmp_path):
    """VERDICT weak #7: exercise gluon MNIST's real-file parsing path."""
    rng = np.random.RandomState(1)
    n = 16
    images = rng.randint(0, 255, (n, 28, 28), np.uint8)
    labels = rng.randint(0, 10, (n,)).astype(np.uint8)
    root = tmp_path / "mnist"
    root.mkdir()
    with gzip.open(root / "train-images-idx3-ubyte.gz", "wb") as f:
        f.write(struct.pack(">IIII", 0x803, n, 28, 28))
        f.write(images.tobytes())
    with gzip.open(root / "train-labels-idx1-ubyte.gz", "wb") as f:
        f.write(struct.pack(">II", 0x801, n))
        f.write(labels.tobytes())
    ds = gluon.data.vision.MNIST(root=str(root), train=True)
    assert not ds.synthetic
    assert len(ds) == n
    img, lab = ds[3]
    assert img.shape == (28, 28, 1)
    np.testing.assert_array_equal(img.asnumpy()[:, :, 0], images[3])
    assert int(lab) == int(labels[3])


# ----------------------------------------------------------- ImageRecordIter
def _write_image_rec(tmp_path, n=12, hw=(40, 36)):
    import cv2  # noqa: F401
    rng = np.random.RandomState(0)
    prefix = str(tmp_path / "data")
    writer = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec",
                                        "w")
    labels = []
    for i in range(n):
        img = rng.randint(0, 255, hw + (3,), np.uint8)
        label = float(i % 3)
        labels.append(label)
        writer.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, label, i, 0), img, img_fmt=".png"))
    writer.close()
    return prefix, labels


def test_image_record_iter(tmp_path):
    prefix, labels = _write_image_rec(tmp_path)
    it = io.ImageRecordIter(path_imgrec=prefix + ".rec",
                            path_imgidx=prefix + ".idx",
                            data_shape=(3, 32, 32), batch_size=4,
                            shuffle=False)
    b = next(it)
    assert b.data[0].shape == (4, 3, 32, 32)
    np.testing.assert_array_equal(b.label[0].asnumpy(), labels[:4])
    n_batches = 1 + sum(1 for _ in it)
    assert n_batches == 3
    it.reset()
    assert next(it).data[0].shape == (4, 3, 32, 32)


def test_image_record_iter_sharded(tmp_path):
    prefix, labels = _write_image_rec(tmp_path)
    got = []
    for part in range(3):
        it = io.ImageRecordIter(path_imgrec=prefix + ".rec",
                                path_imgidx=prefix + ".idx",
                                data_shape=(3, 32, 32), batch_size=4,
                                shuffle=False, num_parts=3,
                                part_index=part)
        got.extend(next(it).label[0].asnumpy().tolist())
    assert got == labels


def test_im2rec_tool_end_to_end(tmp_path):
    """Folder of PNGs -> .lst -> .rec -> ImageRecordIter feeds training."""
    import cv2
    import subprocess, sys
    root = tmp_path / "imgs"
    for cls in ("cat", "dog"):
        (root / cls).mkdir(parents=True)
        rng = np.random.RandomState(hash(cls) % 2**31)
        for i in range(4):
            cv2.imwrite(str(root / cls / f"{i}.png"),
                        rng.randint(0, 255, (34, 30, 3), np.uint8))
    prefix = str(tmp_path / "ds")
    tool = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "im2rec.py")
    subprocess.check_call([sys.executable, tool, "--list", prefix,
                           str(root)])
    subprocess.check_call([sys.executable, tool, prefix, str(root)])
    it = io.ImageRecordIter(path_imgrec=prefix + ".rec",
                            path_imgidx=prefix + ".idx",
                            data_shape=(3, 28, 28), batch_size=4,
                            shuffle=True)
    b = next(it)
    assert b.data[0].shape == (4, 3, 28, 28)
    assert set(b.label[0].asnumpy()) <= {0.0, 1.0}


def test_image_record_iter_batch_larger_than_twice_shard(tmp_path):
    prefix, labels = _write_image_rec(tmp_path, n=3)
    it = io.ImageRecordIter(path_imgrec=prefix + ".rec",
                            path_imgidx=prefix + ".idx",
                            data_shape=(3, 32, 32), batch_size=8,
                            shuffle=False, round_batch=True)
    b = next(it)  # wraps the 3 records multiple times
    assert b.data[0].shape == (8, 3, 32, 32)
    np.testing.assert_array_equal(b.label[0].asnumpy(),
                                  [labels[i % 3] for i in range(8)])


def test_image_record_iter_label_width_mismatch(tmp_path):
    prefix, _ = _write_image_rec(tmp_path, n=2)
    it = io.ImageRecordIter(path_imgrec=prefix + ".rec",
                            path_imgidx=prefix + ".idx",
                            data_shape=(3, 32, 32), batch_size=2,
                            label_width=3)
    with pytest.raises(MXNetError, match="label"):
        next(it)


# ------------------------------------------------------------- prefetch etc
def test_prefetching_iter():
    data = np.arange(24, dtype=np.float32).reshape(12, 2)
    base = io.NDArrayIter(data, np.zeros(12, np.float32), batch_size=4)
    it = io.PrefetchingIter(base)
    batches = []
    try:
        while True:
            batches.append(it.next())
    except StopIteration:
        pass
    assert len(batches) == 3
    np.testing.assert_array_equal(batches[0].data[0].asnumpy(), data[:4])
    # probing past exhaustion must keep raising, not deadlock
    with pytest.raises(StopIteration):
        it.next()
    it.reset()
    assert it.next().data[0].shape == (4, 2)


def test_resize_iter():
    base = io.NDArrayIter(np.zeros((10, 2), np.float32), batch_size=5)
    it = io.ResizeIter(base, size=7)  # loops the 2-batch inner iter
    assert sum(1 for _ in it) == 7


def test_pipeline_feeds_training(tmp_path):
    """Input-pipeline-fed training (VERDICT item #3 'done' criterion):
    RecordIO images -> ImageRecordIter -> Gluon train step, no synthetic
    fallback anywhere."""
    from mxnet_tpu import autograd
    prefix, _ = _write_image_rec(tmp_path, n=16)
    it = io.ImageRecordIter(path_imgrec=prefix + ".rec",
                            path_imgidx=prefix + ".idx",
                            data_shape=(3, 32, 32), batch_size=8,
                            shuffle=True)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(4, 3, activation="relu"),
            gluon.nn.GlobalAvgPool2D(), gluon.nn.Dense(3))
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    losses = []
    for _ in range(2):
        it.reset()
        for batch in it:
            with autograd.record():
                l = loss_fn(net(batch.data[0]), batch.label[0]).mean()
            l.backward()
            trainer.step(batch.data[0].shape[0])
            losses.append(float(l.asscalar()))
    assert all(np.isfinite(losses))


def test_jpeg_dims_header_scan():
    """_jpeg_dims reads SOF dimensions without decoding; non-JPEG
    payloads return None (decode then falls back to full IMREAD_COLOR)."""
    import cv2
    from mxnet_tpu.io.io import _jpeg_dims
    rng = np.random.RandomState(0)
    for hw in ((540, 720), (37, 61), (256, 256)):
        img = rng.randint(0, 255, hw + (3,), np.uint8)
        ok, enc = cv2.imencode(".jpg", img,
                               [cv2.IMWRITE_JPEG_QUALITY, 90])
        assert ok
        assert _jpeg_dims(enc.tobytes()) == hw
    ok, enc = cv2.imencode(".png", rng.randint(0, 255, (8, 9, 3),
                                               np.uint8))
    assert _jpeg_dims(enc.tobytes()) is None


def test_reduced_decode_matches_full_decode(tmp_path):
    """The DCT-reduced decode fast path (source >= 2x resize target)
    must produce images close to the full-decode + resize reference."""
    import cv2
    rng = np.random.RandomState(1)
    prefix = str(tmp_path / "big")
    writer = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec",
                                        "w")
    raws = []
    for i in range(4):
        # smooth natural-ish image (pure noise is the DCT worst case)
        base = rng.randint(0, 255, (68, 90, 3), np.uint8)
        img = cv2.resize(base, (720, 540),
                         interpolation=cv2.INTER_CUBIC)
        raws.append(img)
        writer.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i), i, 0), img, quality=95,
            img_fmt=".jpg"))
    writer.close()
    it = io.ImageRecordIter(path_imgrec=prefix + ".rec",
                            path_imgidx=prefix + ".idx",
                            data_shape=(3, 224, 224), batch_size=4,
                            resize=256, shuffle=False)
    got = next(it).data[0].asnumpy()
    assert got.shape == (4, 3, 224, 224)
    for i, raw in enumerate(raws):
        # reference: full-resolution source, resize short side to 256,
        # center crop (q95 encode noise is within the tolerance)
        h, w = raw.shape[:2]
        ref = cv2.resize(raw, (int(w * 256 / h), 256))
        y, x = (256 - 224) // 2, (ref.shape[1] - 224) // 2
        ref = ref[y:y + 224, x:x + 224, ::-1]        # BGR->RGB
        ref = np.transpose(ref, (2, 0, 1)).astype(np.float32)
        diff = np.abs(got[i] - ref).mean()
        assert diff < 8.0, (i, diff)
