"""Parallelism tests on the virtual 8-device CPU mesh (SURVEY.md §4:
"real runtime, fake scale")."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import nd, models, parallel


def test_make_mesh():
    mesh = parallel.make_mesh(dp=2, tp=2, sp=2)
    assert mesh.shape["dp"] == 2
    assert mesh.shape["tp"] == 2
    assert mesh.shape["sp"] == 2
    with pytest.raises(Exception):
        parallel.make_mesh(dp=3, tp=3, sp=1)


def test_ring_attention_matches_dense():
    mesh = parallel.make_mesh(dp=1, tp=1, sp=8)
    B, H, L, D = 2, 4, 32, 8
    rng = np.random.RandomState(0)
    q = rng.randn(B, H, L, D).astype(np.float32)
    k = rng.randn(B, H, L, D).astype(np.float32)
    v = rng.randn(B, H, L, D).astype(np.float32)

    for causal in (False, True):
        out = np.asarray(parallel.ring_attention(
            jnp.array(q), jnp.array(k), jnp.array(v), mesh, "sp",
            causal=causal))
        s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
        if causal:
            s[:, :, np.triu(np.ones((L, L), bool), k=1)] = -1e30
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        ref = np.einsum("bhqk,bhkd->bhqd", p, v)
        assert np.abs(out - ref).max() < 1e-4, f"causal={causal}"


def test_ring_self_attention_runs():
    mesh = parallel.make_mesh(dp=1, tp=1, sp=4)
    B, L, C, H = 2, 16, 8, 2
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(B, L, C), jnp.float32)
    w_qkv = jnp.asarray(rng.randn(3 * C, C) * 0.1, jnp.float32)
    w_out = jnp.asarray(rng.randn(C, C) * 0.1, jnp.float32)
    out = parallel.ring_self_attention(x, w_qkv, w_out, H, mesh, "sp")
    assert out.shape == (B, L, C)
    assert np.isfinite(np.asarray(out)).all()


def test_sharding_rules():
    from jax.sharding import PartitionSpec as P
    rules = parallel.MEGATRON_RULES
    assert rules.spec_for("enc_qkv_weight") == P("tp", None)
    assert rules.spec_for("enc_ffn_2_weight") == P(None, "tp")
    assert rules.spec_for("bn_gamma") == P()


def test_sharded_trainer_bert_converges():
    mesh = parallel.make_mesh(dp=4, tp=2, sp=1)
    bert = models.get_bert_model(
        "bert_12_768_12", vocab_size=96, units=64, hidden_size=128,
        num_layers=2, num_heads=4, max_length=32, dropout=0.0)
    bert.initialize()
    head = models.BERTClassifier(bert, num_classes=2, dropout=0.0)
    head.initialize()
    B, L = 8, 16
    rng = np.random.RandomState(0)
    inp = nd.array(rng.randint(0, 96, (B, L)), dtype="int32")
    tt = nd.zeros((B, L), dtype="int32")
    vl = nd.array(np.full((B,), L, np.float32))
    lab = nd.array(rng.randint(0, 2, (B,)), dtype="int32")

    def loss_fn(logits, labels):
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.take_along_axis(logp, labels[:, None], axis=1).mean()

    tr = parallel.ShardedTrainer(
        head, loss_fn, mesh, optimizer="adamw",
        optimizer_params={"learning_rate": 1e-3},
        example_inputs=(inp, tt, vl), n_labels=1)
    losses = [float(jax.device_get(tr.step(inp, tt, vl, lab)))
              for _ in range(6)]
    assert losses[-1] < losses[0], losses
    # tensor-parallel sharding took effect on attention weights
    name = [n for n in tr.params if n.endswith("qkv_weight")][0]
    assert tr.params[name].sharding.spec[0] == "tp"
    # params stay consistent across steps (pure-fn update path)
    assert all(not isinstance(v, tuple) for v in tr.params.values())


def test_functionalize_matches_imperative():
    from mxnet_tpu.gluon import nn
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu", in_units=8))
        net.add(nn.Dense(4, in_units=16))
    net.initialize()
    x = nd.random.uniform(shape=(2, 8))
    ref = net(x).asnumpy()
    apply_fn, params = parallel.functionalize(net, x)
    out, aux = apply_fn(params, x._data)
    assert np.allclose(np.asarray(out), ref, atol=1e-6)
    assert aux == {}


def test_pure_optimizers_step():
    params = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
    grads = {"w": jnp.full((4, 4), 0.1), "b": jnp.full((4,), 0.1)}
    state = parallel.adamw_init(params)
    new_p, new_s = parallel.adamw_update(params, grads, state, lr=0.1)
    assert new_p["w"].shape == (4, 4)          # no tuple-nesting
    assert not isinstance(new_p["w"], tuple)
    assert float(new_s["step"]) == 1
    assert np.all(np.asarray(new_p["w"]) < 1.0)  # moved against grad

    state = parallel.sgd_init(params)
    new_p, new_s = parallel.sgd_update(params, grads, state, lr=0.1,
                                       momentum=0.9)
    assert np.allclose(np.asarray(new_p["w"]), 1.0 - 0.01, atol=1e-6)


# ISSUE-15 tier-1 relief: the full multichip dryrun costs ~40s and has
# its own dedicated CI job (ci/runtime_functions.sh multichip_dryrun).
@pytest.mark.slow
def test_graft_entry_dryrun():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_graft", "/root/repo/__graft_entry__.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.dryrun_multichip(8)


def test_sharded_trainer_dtype_noop_does_not_alias():
    """ADVICE r2: with dtype set to the params' existing dtype, astype is
    a no-op alias; the donated step must not delete the Block's live
    buffers (p.data() stays readable after step())."""
    from mxnet_tpu.gluon import nn
    mesh = parallel.make_mesh(dp=2, tp=1, sp=1)
    for dt in (jnp.float32, None):
        net = nn.Dense(4, in_units=8)
        net.initialize()
        x = nd.random.uniform(shape=(4, 8))
        y = nd.random.uniform(shape=(4, 4))
        tr = parallel.ShardedTrainer(
            net, lambda o, t: ((o - t) ** 2).mean(), mesh,
            optimizer="sgd", optimizer_params={"learning_rate": 0.1},
            example_inputs=(x,), n_labels=1, dtype=dt)
        tr.step(x, y)
        for name, p in net.collect_params().items():
            p.data().asnumpy()  # must not raise "Array has been deleted"


def test_sharded_trainer_updates_batchnorm_stats_preserves_frozen():
    """Aux states (BatchNorm moving stats) must update through the
    sharded step; frozen (grad_req='null') params must pass through
    untouched (weight decay with zero grads would erode them)."""
    from mxnet_tpu.gluon import nn
    mesh = parallel.make_mesh(dp=2, tp=1, sp=1)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=4))
    net.add(nn.BatchNorm(in_channels=8))
    net.add(nn.Dense(2, in_units=8))
    net.initialize()
    x = nd.random.uniform(shape=(8, 4)) + 3.0       # nonzero mean input
    y = nd.random.uniform(shape=(8, 2))
    tr = parallel.ShardedTrainer(
        net, lambda o, t: ((o - t) ** 2).mean(), mesh,
        optimizer="adamw",
        optimizer_params={"learning_rate": 1e-3, "weight_decay": 0.1},
        example_inputs=(x,), n_labels=1)
    rm_name = [n for n in tr.params if n.endswith("running_mean")][0]
    before = np.asarray(jax.device_get(tr.params[rm_name])).copy()
    for _ in range(5):
        tr.step(x, y)
    after = np.asarray(jax.device_get(tr.params[rm_name]))
    assert np.abs(after - before).max() > 1e-4, \
        "running_mean did not update through the sharded step"
    assert np.isfinite(after).all()
    # frozen param: freeze a weight and check wd does not decay it
    net2 = nn.Dense(4, in_units=4)
    net2.initialize()
    net2.weight.grad_req = "null"
    w0 = net2.weight.data().asnumpy().copy()
    tr2 = parallel.ShardedTrainer(
        net2, lambda o, t: ((o - t) ** 2).mean(), mesh,
        optimizer="adamw",
        optimizer_params={"learning_rate": 1e-2, "weight_decay": 0.5},
        example_inputs=(x,), n_labels=1)
    for _ in range(5):
        tr2.step(x, nd.random.uniform(shape=(8, 4)))
    wname = [n for n in tr2.params if n.endswith("weight")][0]
    np.testing.assert_allclose(
        np.asarray(jax.device_get(tr2.params[wname])), w0, rtol=1e-6,
        err_msg="frozen param was eroded by the sharded optimizer")


def test_ring_attention_windowed_matches_dense():
    """Sliding-window ring attention (out-of-band hops skip compute)
    matches the dense windowed oracle; window >= L degenerates to
    plain causal."""
    mesh = parallel.make_mesh(dp=1, tp=1, sp=8)
    B, H, L, D = 2, 2, 32, 8
    rng = np.random.RandomState(1)
    q = rng.randn(B, H, L, D).astype(np.float32)
    k = rng.randn(B, H, L, D).astype(np.float32)
    v = rng.randn(B, H, L, D).astype(np.float32)

    def dense_ref(window):
        s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
        qi = np.arange(L)[:, None]
        ki = np.arange(L)[None, :]
        dead = (ki > qi) | (ki <= qi - window)
        s[:, :, dead] = -1e30
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        return np.einsum("bhqk,bhkd->bhqd", p, v)

    for window in (4, 7, 16, 64):
        out = np.asarray(parallel.ring_attention(
            jnp.array(q), jnp.array(k), jnp.array(v), mesh, "sp",
            causal=True, window=window))
        assert np.abs(out - dense_ref(window)).max() < 1e-4, window

    import pytest as _pytest
    from mxnet_tpu.base import MXNetError
    with _pytest.raises(MXNetError, match="causal"):
        parallel.ring_attention(jnp.array(q), jnp.array(k),
                                jnp.array(v), mesh, "sp", causal=False,
                                window=4)
    with _pytest.raises(MXNetError, match=">= 1"):
        parallel.ring_attention(jnp.array(q), jnp.array(k),
                                jnp.array(v), mesh, "sp", causal=True,
                                window=0)
