"""Multi-process data-parallel training via the launcher (reference:
example/distributed_training + tools/launch.py local mode).

  python tools/launch.py -n 2 python examples/distributed_train.py

Each process computes gradients on its shard of the batch; the 'dist'
kvstore (jax.distributed + XLA collectives) averages them.
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import mxnet_tpu as mx                                    # noqa: E402
from mxnet_tpu import autograd, gluon, nd                 # noqa: E402
from mxnet_tpu.gluon import nn                            # noqa: E402
from mxnet_tpu.parallel import dist                       # noqa: E402


def main():
    dist.initialize()                  # reads the launcher's env handshake
    rank, world = dist.rank(), dist.size()
    print(f"[{rank}/{world}] up")

    mx.random.seed(7)                  # same init on every worker
    net = nn.Sequential()
    net.add(nn.Dense(64, activation="relu", in_units=32),
            nn.Dense(8, in_units=64))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05}, kvstore="dist")

    rng = np.random.RandomState(0)     # same data stream, sharded by rank
    X = rng.randn(256, 32).astype(np.float32)
    Y = X @ rng.randn(32, 8).astype(np.float32)
    shard = slice(rank * 256 // world, (rank + 1) * 256 // world)
    xs, ys = nd.array(X[shard]), nd.array(Y[shard])

    for epoch in range(20):
        with autograd.record():
            loss = ((net(xs) - ys) ** 2).mean()
        loss.backward()
        # grads are already per-sample means; the dist kvstore SUMS across
        # workers, so rescale by world size to average
        trainer.step(world)
        if rank == 0 and epoch % 5 == 0:
            print(f"epoch {epoch}: loss {float(loss.asscalar()):.5f}")
    print(f"[{rank}] final loss {float(loss.asscalar()):.5f}")


if __name__ == "__main__":
    main()
