"""Word-level LSTM language model (reference: example/rnn/word_lm).

No egress in this environment, so the corpus is synthetic but structured:
sentences drawn from a tiny probabilistic grammar, which a 2-layer LSTM
can learn far below the unigram entropy — perplexity dropping well under
the unigram baseline is the training signal.

  python examples/word_language_model.py --epochs 3
"""
import argparse
import math
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import mxnet_tpu as mx                                    # noqa: E402
from mxnet_tpu import autograd, gluon, nd                 # noqa: E402
from mxnet_tpu.gluon import nn, rnn                       # noqa: E402


def make_corpus(n_sentences=2000, seed=0):
    """Subject-verb-object sentences from a tiny grammar."""
    rng = np.random.RandomState(seed)
    subjects = ["the cat", "a dog", "the bird", "my friend"]
    verbs = ["sees", "likes", "chases", "finds"]
    objects = ["the ball", "a fish", "the tree", "some food"]
    words = ["<eos>"]
    sentences = []
    for _ in range(n_sentences):
        s = (subjects[rng.randint(4)].split() +
             [verbs[rng.randint(4)]] +
             objects[rng.randint(4)].split() + ["<eos>"])
        sentences.append(s)
    vocab = sorted({w for s in sentences for w in s} | set(words))
    w2i = {w: i for i, w in enumerate(vocab)}
    ids = np.array([w2i[w] for s in sentences for w in s], np.int32)
    return ids, vocab


def batchify(ids, batch_size, seq_len):
    n = (len(ids) - 1) // (batch_size * seq_len)
    usable = n * batch_size * seq_len
    x = ids[:usable].reshape(batch_size, -1)
    y = ids[1:usable + 1].reshape(batch_size, -1)
    for i in range(0, x.shape[1] - seq_len + 1, seq_len):
        yield (nd.array(x[:, i:i + seq_len], dtype="int32"),
               nd.array(y[:, i:i + seq_len], dtype="int32"))


class RNNModel(gluon.HybridBlock):
    def __init__(self, vocab_size, embed=64, hidden=128, layers=2, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.embedding = nn.Embedding(vocab_size, embed)
            self.rnn = rnn.LSTM(hidden, num_layers=layers,
                                layout="NTC")
            self.decoder = nn.Dense(vocab_size, flatten=False)

    def hybrid_forward(self, F, x):
        h = self.embedding(x)
        h = self.rnn(h)
        return self.decoder(h)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--seq-len", type=int, default=20)
    ap.add_argument("--lr", type=float, default=3e-3)
    args = ap.parse_args()

    mx.random.seed(1)
    ids, vocab = make_corpus()
    # unigram entropy — the "model learned nothing" perplexity ceiling
    counts = np.bincount(ids, minlength=len(vocab)) / len(ids)
    unigram_ppl = math.exp(-(counts[counts > 0] *
                             np.log(counts[counts > 0])).sum())
    print(f"vocab {len(vocab)}, tokens {len(ids)}, "
          f"unigram ppl {unigram_ppl:.1f}")

    model = RNNModel(len(vocab))
    model.initialize(mx.init.Xavier())
    model.hybridize(static_alloc=True)
    trainer = gluon.Trainer(model.collect_params(), "adam",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    for epoch in range(args.epochs):
        total, n_batches = 0.0, 0
        t0 = time.time()
        for x, y in batchify(ids, args.batch_size, args.seq_len):
            with autograd.record():
                logits = model(x)
                loss = loss_fn(logits.reshape((-1, len(vocab))),
                               y.reshape((-1,))).mean()
            loss.backward()
            trainer.step(args.batch_size)
            total += float(loss.asscalar())
            n_batches += 1
        ppl = math.exp(total / n_batches)
        print(f"epoch {epoch}: ppl {ppl:.2f} "
              f"({time.time() - t0:.1f}s, {n_batches} batches)")
    assert ppl < unigram_ppl, "model did not beat the unigram baseline"
    print("done: perplexity beat the unigram baseline "
          f"({ppl:.2f} < {unigram_ppl:.1f})")


if __name__ == "__main__":
    main()
