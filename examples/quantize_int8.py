"""Post-training INT8 quantization of a Gluon network (reference:
example/quantization/imagenet_gen_qsym_onedn.py — here the int8 compute
runs on the MXU's 8-bit multiply / 32-bit accumulate path).

  python examples/quantize_int8.py
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax                                                # noqa: E402
import mxnet_tpu as mx                                    # noqa: E402
from mxnet_tpu import nd                                  # noqa: E402
from mxnet_tpu.contrib import quantization as qt          # noqa: E402
from mxnet_tpu.gluon import nn                            # noqa: E402


def main():
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Conv2D(32, 3, padding=1, activation="relu"),
            nn.MaxPool2D(2),
            nn.Conv2D(64, 3, padding=1, activation="relu"),
            nn.MaxPool2D(2),
            nn.Dense(128, activation="relu"),
            nn.Dense(10))
    net.initialize(mx.init.Xavier())

    x = nd.random.uniform(-1, 1, shape=(8, 3, 32, 32))
    ref = net(x)

    # KL-divergence ("entropy") calibration over representative batches
    calib = [nd.random.uniform(-1, 1, shape=(8, 3, 32, 32))
             for _ in range(4)]
    qnet = qt.quantize_net(net, calib_mode="entropy", calib_data=calib)
    qnet.hybridize(static_alloc=True)

    out = qnet(x)
    err = np.abs(out.asnumpy() - ref.asnumpy()).max()
    corr = np.corrcoef(out.asnumpy().ravel(), ref.asnumpy().ravel())[0, 1]
    print(f"int8 vs fp32: max abs err {err:.4f}, correlation {corr:.5f}")

    for tag, m in (("fp32", net), ("int8", qnet)):
        jax.device_get(m(x)[0]._data)
        t0 = time.perf_counter()
        for _ in range(10):
            out = m(x)
        jax.device_get(out[0]._data)
        print(f"{tag}: {(time.perf_counter() - t0) / 10 * 1e3:.2f} ms/batch")


if __name__ == "__main__":
    main()
