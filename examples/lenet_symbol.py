"""Train a symbolic-API MLP with the legacy Module interface (reference:
example/image-classification/train_mnist.py symbolic path).

  python examples/lenet_symbol.py
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import mxnet_tpu as mx                                    # noqa: E402
import mxnet_tpu.symbol as sym                            # noqa: E402
from mxnet_tpu import io, nd                              # noqa: E402
from mxnet_tpu.module import Module                       # noqa: E402


def build_symbol():
    data = sym.var("data")
    h = sym.FullyConnected(data, sym.var("fc1_weight"), sym.var("fc1_bias"),
                           num_hidden=128, name="fc1")
    h = sym.Activation(h, act_type="relu")
    h = sym.FullyConnected(h, sym.var("fc2_weight"), sym.var("fc2_bias"),
                           num_hidden=10, name="fc2")
    return sym.SoftmaxOutput(h, sym.var("softmax_label"), name="softmax")


def main():
    mx.random.seed(0)
    rng = np.random.RandomState(0)
    # synthetic 10-class problem: linearly separable clusters
    n = 2048
    centers = rng.randn(10, 64).astype(np.float32) * 3
    labels = rng.randint(0, 10, n)
    data = centers[labels] + rng.randn(n, 64).astype(np.float32)

    train_iter = io.NDArrayIter(data={"data": nd.array(data)},
                                label={"softmax_label": nd.array(
                                    labels.astype(np.float32))},
                                batch_size=128, shuffle=True)

    mod = Module(build_symbol(), data_names=("data",),
                 label_names=("softmax_label",))
    mod.fit(train_iter, num_epoch=5, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1},
            eval_metric="acc")
    score = mod.score(train_iter, mx.metric.Accuracy())
    print("final accuracy:", score)


if __name__ == "__main__":
    main()
