"""BERT pretraining with tensor+sequence parallel sharding over a device
mesh (reference: the gluon-nlp BERT pretraining recipe; here expressed
TPU-natively with jax.sharding + the fused ShardedTrainer step).

On a machine without multiple accelerators, run on the virtual CPU mesh:

  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/bert_pretrain.py --dp 2 --tp 2 --sp 2 --tiny
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax                                                # noqa: E402
import mxnet_tpu as mx                                    # noqa: E402
from mxnet_tpu import models, nd, parallel                # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--sp", type=int, default=1)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--seqlen", type=int, default=128)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--tiny", action="store_true",
                    help="2-layer toy config (CI/CPU)")
    args = ap.parse_args()

    mx.random.seed(0)
    rng = np.random.RandomState(0)
    B, L = args.batch_size, args.seqlen
    if args.tiny:
        cfg = dict(model_name="bert_12_768_12", vocab_size=1024, units=128,
                   hidden_size=512, num_layers=2, num_heads=8, max_length=L)
    else:
        cfg = dict(model_name="bert_24_1024_16", vocab_size=30522,
                   max_length=L)

    model = models.get_bert_model(dropout=0.0, **cfg)
    model.initialize()
    head = models.BERTForPretrain(model, vocab_size=cfg["vocab_size"])
    head.initialize()

    n_mask = max(1, int(0.15 * L))
    inputs = nd.array(rng.randint(0, cfg["vocab_size"], (B, L)),
                      dtype="int32")
    token_types = nd.zeros((B, L), dtype="int32")
    valid_length = nd.array(np.full((B,), L, np.float32))
    masked_pos = nd.array(rng.randint(0, L, (B, n_mask)), dtype="int32")
    mlm_y = nd.array(rng.randint(0, cfg["vocab_size"], (B, n_mask)),
                     dtype="int32")
    nsp_y = nd.array(rng.randint(0, 2, (B,)), dtype="int32")

    def loss_fn(outputs, mlm_labels, nsp_labels):
        import jax.numpy as jnp
        mlm_scores, nsp_scores = outputs
        mlm_lp = jax.nn.log_softmax(mlm_scores.astype(jnp.float32), -1)
        nsp_lp = jax.nn.log_softmax(nsp_scores.astype(jnp.float32), -1)
        return (-jnp.take_along_axis(
                    mlm_lp, mlm_labels[..., None], axis=-1).mean()
                - jnp.take_along_axis(
                    nsp_lp, nsp_labels[:, None], axis=-1).mean())

    mesh = parallel.make_mesh(dp=args.dp, tp=args.tp, sp=args.sp)
    print("mesh:", dict(zip(mesh.axis_names, mesh.devices.shape)))
    trainer = parallel.ShardedTrainer(
        head, loss_fn, mesh, optimizer="adamw",
        optimizer_params={"learning_rate": 1e-4},
        example_inputs=(inputs, token_types, valid_length, masked_pos),
        n_labels=2)

    batch = (inputs, token_types, valid_length, masked_pos, mlm_y, nsp_y)
    loss = trainer.step(*batch)
    jax.device_get(loss)                      # compile + first step
    tic = time.time()
    for step in range(args.steps):
        loss = trainer.step(*batch)
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step}: loss {float(jax.device_get(loss)):.4f}")
    dt = (time.time() - tic) / args.steps
    print(f"{B / dt:.1f} samples/s ({dt * 1e3:.1f} ms/step)")


if __name__ == "__main__":
    main()
