"""WGAN with gradient penalty on a 2-D toy distribution (reference:
example/gan/ — upstream ships DCGAN; the GP variant additionally
exercises ``autograd.grad(create_graph=True)`` higher-order gradients,
which upstream could not express on its tape).

The generator learns to map N(0,I) noise onto a ring of 8 Gaussians;
success criterion: generated samples land near the ring radius.

  python examples/wgan_gp.py --iters 300
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import mxnet_tpu as mx                                    # noqa: E402
from mxnet_tpu import autograd, gluon, nd                 # noqa: E402
from mxnet_tpu.gluon import nn                            # noqa: E402

RADIUS = 2.0


def real_batch(rng, n):
    """8 Gaussians on a radius-2 ring."""
    angles = rng.randint(0, 8, n) * (2 * np.pi / 8)
    centers = np.stack([RADIUS * np.cos(angles),
                        RADIUS * np.sin(angles)], 1)
    return (centers + 0.05 * rng.randn(n, 2)).astype(np.float32)


def mlp(sizes, out):
    net = nn.HybridSequential()
    for s in sizes:
        net.add(nn.Dense(s, activation="relu"))
    net.add(nn.Dense(out))
    return net


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=250)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--gp-weight", type=float, default=1.0)
    ap.add_argument("--n-critic", type=int, default=3)
    args = ap.parse_args()

    mx.random.seed(3)
    rng = np.random.RandomState(3)

    gen = mlp([64, 64], 2)
    critic = mlp([64, 64], 1)
    for net in (gen, critic):
        net.initialize(mx.init.Xavier())
    g_tr = gluon.Trainer(gen.collect_params(), "adam",
                         {"learning_rate": 1e-3, "beta1": 0.5})
    c_tr = gluon.Trainer(critic.collect_params(), "adam",
                         {"learning_rate": 1e-3, "beta1": 0.5})

    B = args.batch_size
    for it in range(args.iters):
        # ---- critic steps with gradient penalty
        for _ in range(args.n_critic):
            x_real = nd.array(real_batch(rng, B))
            z = nd.array(rng.randn(B, 2).astype(np.float32))
            eps = nd.array(rng.rand(B, 1).astype(np.float32))
            x_fake = gen(z)
            # the interpolate is the differentiation leaf of the penalty
            x_hat_leaf = (eps * x_real + (1.0 - eps) * x_fake).copy()
            with autograd.record():
                c_real = critic(x_real).mean()
                c_fake = critic(gen(z)).mean()
                c_hat = critic(x_hat_leaf).sum()
                ghat = autograd.grad(c_hat, [x_hat_leaf],
                                     create_graph=True)[0]
                gnorm = ((ghat * ghat).sum(axis=1) + 1e-12).sqrt()
                gp = ((gnorm - 1.0) ** 2).mean()
                c_loss = c_fake - c_real + args.gp_weight * gp
            c_loss.backward()
            c_tr.step(B)

        # ---- generator step
        z = nd.array(rng.randn(B, 2).astype(np.float32))
        with autograd.record():
            g_loss = -critic(gen(z)).mean()
        g_loss.backward()
        g_tr.step(B)

        if it % 50 == 0 or it == args.iters - 1:
            print(f"iter {it}: critic {float(c_loss.asscalar()):+.3f} "
                  f"gp {float(gp.asscalar()):.3f} "
                  f"gen {float(g_loss.asscalar()):+.3f}")

    samples = gen(nd.array(rng.randn(512, 2).astype(np.float32))).asnumpy()
    radii = np.linalg.norm(samples, axis=1)
    print(f"sample radius mean {radii.mean():.2f} (target {RADIUS}); "
          f"std {radii.std():.2f}")
    assert abs(radii.mean() - RADIUS) < 0.8, "generator missed the ring"
    print("done: generator reached the target ring")


if __name__ == "__main__":
    main()
