"""Transformer NMT training + compiled beam-search decoding
(BASELINE.md config 4: Sockeye-style WMT seq2seq with bucketed lengths).

No egress in this environment, so the corpus is a synthetic
sequence-transduction task with real structure: the "translation" of a
source sentence is its REVERSE with a vocabulary shift — forcing the
decoder to use cross-attention over the whole source (a copy task would
let it cheat with a trivial monotonic alignment).

Training uses bucketed target lengths through the Gluon compile cache
(hybridize(bucket_shapes=...)) — the MXNet BucketingModule pattern — and
decoding uses the COMPILED batched beam search (models/decoding.py: the
whole search is one jitted lax.while_loop program with KV caches).

Success criterion printed at the end: exact-match rate of beam-decoded
reversals on held-out sentences (>= 0.9 after 14 epochs at the default
tiny scale; a BLEU-like proxy for the synthetic corpus).

  python examples/nmt_transformer.py
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import mxnet_tpu as mx                                    # noqa: E402
from mxnet_tpu import autograd, gluon, nd, models         # noqa: E402

PAD, UNK, BOS, EOS = 0, 1, 2, 3
N_SPECIAL = 4


def make_pairs(n, vocab_size, min_len, max_len, rng):
    """(src, tgt) pairs: tgt = reversed(src) with a +1 vocab rotation."""
    pairs = []
    for _ in range(n):
        L = rng.randint(min_len, max_len + 1)
        src = rng.randint(N_SPECIAL, vocab_size, (L,)).astype(np.int32)
        tgt = ((src[::-1] - N_SPECIAL + 1)
               % (vocab_size - N_SPECIAL)) + N_SPECIAL
        pairs.append((src, tgt.astype(np.int32)))
    return pairs


def buckets_for(max_len):
    """Length buckets covering src (max_len) and tgt (max_len+1)."""
    top = max_len + 4
    return tuple(b for b in range(4, top + 4, 4))


def batches(pairs, batch_size, max_len, rng):
    """Padded batches; lengths stay ragged so bucketing does the work."""
    bks = buckets_for(max_len)
    order = rng.permutation(len(pairs))
    # Sockeye-style length bucketing: sort a window by length so batch
    # padding is tight, then batch
    window = 8 * batch_size
    for w0 in range(0, len(order), window):
        idx = sorted(order[w0:w0 + window],
                     key=lambda i: len(pairs[i][0]))
        for b0 in range(0, len(idx), batch_size):
            chunk = [pairs[i] for i in idx[b0:b0 + batch_size]]
            if len(chunk) < batch_size:
                continue
            def bucket(L):
                return min(b for b in bks if b >= L)
            Ls = bucket(max(len(s) for s, _ in chunk))
            Lt = bucket(max(len(t) for _, t in chunk) + 1)  # BOS prefix
            src = np.full((batch_size, Ls), PAD, np.int32)
            tgt_in = np.full((batch_size, Lt), PAD, np.int32)
            tgt_out = np.full((batch_size, Lt), PAD, np.int32)
            sv = np.zeros((batch_size,), np.float32)
            tv = np.zeros((batch_size,), np.float32)
            for i, (s, t) in enumerate(chunk):
                src[i, :len(s)] = s
                tgt_in[i, 0] = BOS
                tgt_in[i, 1:len(t) + 1] = t
                tgt_out[i, :len(t)] = t
                tgt_out[i, len(t)] = EOS
                sv[i], tv[i] = len(s), len(t) + 1
            yield (nd.array(src, dtype="int32"),
                   nd.array(tgt_in, dtype="int32"),
                   nd.array(tgt_out, dtype="int32"),
                   nd.array(sv), nd.array(tv))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=14)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--vocab", type=int, default=24)
    p.add_argument("--min-len", type=int, default=3)
    p.add_argument("--max-len", type=int, default=10)
    p.add_argument("--units", type=int, default=64)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--beam", type=int, default=4)
    p.add_argument("--min-match", type=float, default=0.9,
                   help="fail below this exact-match rate (0 disables)")
    args = p.parse_args()

    mx.random.seed(0)
    rng = np.random.RandomState(0)
    train = make_pairs(3000, args.vocab, args.min_len, args.max_len, rng)
    test = make_pairs(64, args.vocab, args.min_len, args.max_len, rng)

    model = models.transformer_base(
        src_vocab_size=args.vocab, units=args.units,
        hidden_size=4 * args.units, num_layers=args.layers, num_heads=4,
        dropout=0.0, max_length=args.max_len + 4)
    model.initialize(mx.init.Xavier())
    # bucket ragged (src, tgt) lengths onto a fixed set: bounded compile
    # cache instead of one program per length pair
    model.hybridize(
        bucket_shapes={1: list(buckets_for(args.max_len))})
    loss_fn = models.SmoothedSoftmaxCELoss(smoothing=0.1)
    trainer = gluon.Trainer(model.collect_params(), "adam",
                            {"learning_rate": 3e-3})

    for epoch in range(args.epochs):
        # inverse-sqrt-ish decay (Sockeye schedule at toy scale)
        trainer.set_learning_rate(3e-3 / (1.0 + 0.35 * epoch) ** 0.5)
        t0 = time.time()
        total, n = 0.0, 0
        for src, tgt_in, tgt_out, sv, tv in batches(
                train, args.batch_size, args.max_len, rng):
            with autograd.record():
                logits = model(src, tgt_in, sv, tv)
                loss = loss_fn(logits, tgt_out, tv).mean()
            loss.backward()
            trainer.step(args.batch_size)
            total += float(loss.asnumpy())
            n += 1
        print(f"epoch {epoch}: loss={total / n:.4f} "
              f"({time.time() - t0:.1f}s)")

    # --------------------------- compiled beam-search decode, exactness
    correct = 0
    t0 = time.time()
    n_tok = 0
    group = {}
    for s, t in test:
        group.setdefault(len(s), []).append((s, t))
    for L, items in sorted(group.items()):
        src = nd.array(np.stack([s for s, _ in items]), dtype="int32")
        sv = nd.array(np.full((len(items),), L, np.float32))
        out = model.beam_search(src, sv, bos=BOS, eos=EOS,
                                beam_size=args.beam,
                                max_decode_len=args.max_len + 2).asnumpy()
        n_tok += out.size
        for row, (_s, t) in zip(out, items):
            hyp = []
            for tok in row[1:]:
                if tok == EOS:
                    break
                hyp.append(int(tok))
            correct += hyp == list(t)
    rate = correct / len(test)
    print(f"beam-decode exact-match: {rate:.3f} "
          f"({time.time() - t0:.1f}s incl. compile)")
    if rate < args.min_match:
        print(f"WARNING: exact-match below {args.min_match}",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
