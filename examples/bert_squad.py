"""BERT SQuAD-style span fine-tuning (BASELINE config 3).

Reference surface: GluonNLP ``scripts/bert/finetune_squad.py`` over the
contrib MHA kernels (SURVEY.md §2.2 KEY absence note / §7.2 M6) — BERT
encoder + ``BERTForQA`` span head, AdamW with warmup+poly decay,
checkpoint import via the ``.params`` surface, exact-match as the
convergence oracle.

Zero-egress stand-in for SQuAD: synthetic span-extraction episodes,
``[CLS] question [SEP] passage [SEP]`` with segment ids 0/1 and answer
(start, end) indices inside the passage region.  The answer span is
preceded by a marker token inside the passage and copied into the
question — the from-scratch tiny model learns the marker cue in a few
hundred steps (exact-match > 0.9, the convergence oracle), while the
pure content-matching route stays available to pretrained/full-size
models.  (Pure question-passage matching with NO marker is an
induction-head task: a from-scratch 2-layer model plateaus at the
uniform baseline for thousands of steps, which makes a poor example
oracle — measured before this design.)

The training step runs the user-facing three-call recipe — which the
framework compiles into ONE donated fwd+bwd+opt program.

Usage:
  python examples/bert_squad.py                        # tiny, EM -> 1.0
  python examples/bert_squad.py --min-em 0.9           # convergence gate
  python examples/bert_squad.py --bert-params pre.params   # ckpt import
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, lr_scheduler, nd
from mxnet_tpu.gluon import HybridBlock
from mxnet_tpu import models

CLS, SEP, MARK = 1, 2, 3


def make_batch(rng, B, vocab, q_len, p_len, ans_len):
    """[CLS] q [SEP] passage [SEP]; the answer span sits right after a
    marker token in the passage and is copied into the question."""
    L = 1 + q_len + 1 + p_len + 1
    toks = np.zeros((B, L), np.int32)
    segs = np.zeros((B, L), np.int32)
    starts = np.zeros((B,), np.int32)
    ends = np.zeros((B,), np.int32)
    for b in range(B):
        passage = rng.randint(4, vocab, p_len)
        s = rng.randint(1, p_len - ans_len)
        passage[s - 1] = MARK                     # cue before the span
        answer = passage[s:s + ans_len]
        q = np.zeros(q_len, np.int32)             # pad
        q[:ans_len] = answer                      # question = the span
        row = np.concatenate([[CLS], q, [SEP], passage, [SEP]])
        toks[b] = row
        p_off = 1 + q_len + 1
        segs[b, p_off:] = 1
        starts[b] = p_off + s
        ends[b] = p_off + s + ans_len - 1
    vlen = np.full((B,), L, np.float32)
    return (nd.array(toks, dtype="int32"), nd.array(segs, dtype="int32"),
            nd.array(vlen), nd.array(starts, dtype="int32"),
            nd.array(ends, dtype="int32"))


class SpanLoss(HybridBlock):
    """QA head + start/end softmax CE in one hybridizable block (the
    whole step then fuses into a single program)."""

    def __init__(self, qa_net, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.qa = qa_net

    def hybrid_forward(self, F, toks, segs, vlen, starts, ends):
        scores = self.qa(toks, segs, vlen)            # (B, L, 2)
        start_logits = F.squeeze(
            F.slice_axis(scores, axis=2, begin=0, end=1), axis=2)
        end_logits = F.squeeze(
            F.slice_axis(scores, axis=2, begin=1, end=2), axis=2)
        l1 = F.pick(F.log_softmax(start_logits), starts, axis=1)
        l2 = F.pick(F.log_softmax(end_logits), ends, axis=1)
        return -0.5 * (F.mean(l1) + F.mean(l2))


def exact_match(qa_net, batch):
    toks, segs, vlen, starts, ends = batch
    with autograd.pause(train_mode=False):
        scores = qa_net(toks, segs, vlen).asnumpy()
    ps = scores[:, :, 0].argmax(axis=1)
    pe = scores[:, :, 1].argmax(axis=1)
    return float(np.mean((ps == starts.asnumpy())
                         & (pe == ends.asnumpy())))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=1500,
                    help="the from-scratch tiny model sits on a plateau "
                         "for ~600 steps before the span circuitry "
                         "forms; budget accordingly")
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--bert-params", default=None,
                    help="pretrained BERT encoder .params to import "
                         "(the checkpoint-import surface of config 3; "
                         "saved via bert.save_parameters — dims must "
                         "match the --units/--layers/... flags)")
    ap.add_argument("--params", default=None,
                    help="fine-tuned qa .params (as written by --save) "
                         "to resume from")
    ap.add_argument("--save", default=None,
                    help="write fine-tuned params here")
    ap.add_argument("--units", type=int, default=128)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--hidden", type=int, default=512)
    ap.add_argument("--min-em", type=float, default=0.0,
                    help="exit non-zero below this final exact-match "
                         "(CI smoke passes 0; convergence runs 0.9)")
    args = ap.parse_args()

    mx.random.seed(0)
    rng = np.random.RandomState(0)

    # tiny by default so the example converges on CPU; pass
    # --units 768 --layers 12 --heads 12 --hidden 3072 --vocab 30522
    # (and a matching --bert-params checkpoint) for the full-size model
    bert = models.get_bert_model(
        model_name="bert_12_768_12", vocab_size=args.vocab,
        units=args.units, hidden_size=args.hidden,
        num_layers=args.layers, num_heads=args.heads, max_length=128,
        dropout=0.0)
    bert.initialize(mx.init.Normal(0.02))
    if args.bert_params:
        bert.load_parameters(args.bert_params)       # strict: loud mismatch
        print(f"imported pretrained encoder {args.bert_params}")
    qa = models.BERTForQA(bert)
    qa.initialize(mx.init.Normal(0.02))
    if args.params:
        qa.load_parameters(args.params)              # --save round trip
        print(f"resumed fine-tuned checkpoint {args.params}")
    step_blk = SpanLoss(qa)
    step_blk.hybridize(static_alloc=True)

    # GluonNLP finetune recipe: AdamW, warmup then poly decay — to a
    # floor, not zero (the tiny from-scratch model does most of its
    # learning late, after the plateau)
    sched = lr_scheduler.PolyScheduler(
        max_update=args.steps, base_lr=args.lr, pwr=1,
        final_lr=args.lr / 5,
        warmup_steps=max(1, args.steps // 20))
    trainer = gluon.Trainer(qa.collect_params(), "adamw",
                            {"learning_rate": args.lr,
                             "lr_scheduler": sched, "wd": 0.01})

    q_len, p_len, ans_len = 8, 48, 4
    t0 = time.time()
    for step in range(1, args.steps + 1):
        batch = make_batch(rng, args.batch, args.vocab, q_len, p_len,
                           ans_len)
        toks, segs, vlen, starts, ends = batch
        with autograd.record():
            loss = step_blk(toks, segs, vlen, starts, ends)
        loss.backward()
        trainer.step(args.batch)
        if step % 50 == 0 or step == 1:
            em = exact_match(qa, make_batch(rng, 64, args.vocab, q_len,
                                            p_len, ans_len))
            print(f"step {step:4d} loss {float(loss.asnumpy()):.4f} "
                  f"EM {em:.3f} lr {trainer.learning_rate:.2e} "
                  f"({time.time() - t0:.0f}s)")
    em = exact_match(qa, make_batch(rng, 256, args.vocab, q_len, p_len,
                                    ans_len))
    print(f"final exact-match: {em:.3f}")
    if args.save:
        qa.save_parameters(args.save)
        print(f"saved {args.save}")
    return 0 if em >= args.min_em else 1


if __name__ == "__main__":
    sys.exit(main())
