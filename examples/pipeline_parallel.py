"""Pipeline-parallel training with the GPipe microbatch schedule
(new TPU-first capability; the closest upstream artifact is
example/model-parallel — manual per-layer device placement, which GSPMD
and this schedule supersede).

Stages of a deep residual MLP live on different devices of a ``pp``
mesh; microbatches stream through `parallel.pipeline_apply` (one
differentiable compiled program, ppermute hand-offs on ICI).

  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/pipeline_parallel.py
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax                                                # noqa: E402
import jax.numpy as jnp                                   # noqa: E402

from mxnet_tpu import parallel                            # noqa: E402


def stage_fn(params, x):
    """One pipeline stage: residual 2-layer MLP block."""
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    return x + h @ params["w2"]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--stages", type=int, default=4)
    ap.add_argument("--micro", type=int, default=8)
    ap.add_argument("--micro-batch", type=int, default=16)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument("--iters", type=int, default=400)
    ap.add_argument("--lr", type=float, default=0.1)
    args = ap.parse_args()

    n_dev = len(jax.devices())
    stages = min(args.stages, n_dev)
    mesh = parallel.make_pipeline_mesh(stages)
    print(f"pipeline: {stages} stages over {stages} devices, "
          f"{args.micro} microbatches x {args.micro_batch}")

    rng = np.random.RandomState(0)
    D, H = args.dim, args.hidden
    params = {
        "w1": jnp.asarray(rng.randn(stages, D, H), jnp.float32) * 0.1,
        "b1": jnp.zeros((stages, H), jnp.float32),
        "w2": jnp.asarray(rng.randn(stages, H, D), jnp.float32) * 0.1,
    }
    # teacher-student: targets from a fixed random pipeline
    teacher = {
        "w1": jnp.asarray(rng.randn(stages, D, H), jnp.float32) * 0.1,
        "b1": jnp.asarray(rng.randn(stages, H), jnp.float32) * 0.1,
        "w2": jnp.asarray(rng.randn(stages, H, D), jnp.float32) * 0.1,
    }
    xs = jnp.asarray(rng.randn(args.micro, args.micro_batch, D),
                     jnp.float32)
    ys = parallel.pipeline_apply(stage_fn, teacher, xs, mesh)

    @jax.jit
    def step(params):
        def loss_fn(p):
            out = parallel.pipeline_apply(stage_fn, p, xs, mesh)
            return ((out - ys) ** 2).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        return jax.tree_util.tree_map(
            lambda p, g: p - args.lr * g, params, grads), loss

    t0 = time.time()
    first = None
    for it in range(args.iters):
        params, loss = step(params)
        if it == 0:
            first = float(loss)
        if it % 50 == 0 or it == args.iters - 1:
            print(f"iter {it}: loss {float(loss):.6f}")
    print(f"{args.iters} iters in {time.time() - t0:.1f}s; "
          f"loss {first:.4f} -> {float(loss):.6f}")
    assert float(loss) < 0.05 * first
    print("done: pipeline-parallel training converged")


if __name__ == "__main__":
    main()
