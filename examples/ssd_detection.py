"""Single-shot detection (SSD) on a synthetic shapes dataset
(reference: example/ssd — upstream trains VGG-SSD on VOC; no egress
here, so the data is generated: one bright axis-aligned square per
image, class = which half of the brightness range).

Exercises the MultiBox op family end to end: MultiBoxPrior anchors →
conv class/box predictors → MultiBoxTarget matching + offset encoding →
SmoothL1 + softmax losses → MultiBoxDetection decode + NMS at eval.

  python examples/ssd_detection.py --iters 150
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import mxnet_tpu as mx                                    # noqa: E402
from mxnet_tpu import autograd, gluon, nd                 # noqa: E402
from mxnet_tpu.gluon import nn                            # noqa: E402

IMG = 32
N_CLS = 2          # two foreground classes


def synth_batch(rng, n):
    """Images with one square; label rows [cls, xmin, ymin, xmax, ymax]."""
    imgs = np.zeros((n, 1, IMG, IMG), np.float32)
    labels = np.zeros((n, 1, 5), np.float32)
    for i in range(n):
        size = rng.randint(8, 16)
        x0 = rng.randint(0, IMG - size)
        y0 = rng.randint(0, IMG - size)
        cls = rng.randint(0, N_CLS)
        val = 0.4 if cls == 0 else 0.9
        imgs[i, 0, y0:y0 + size, x0:x0 + size] = val
        labels[i, 0] = [cls, x0 / IMG, y0 / IMG,
                        (x0 + size) / IMG, (y0 + size) / IMG]
    return nd.array(imgs), nd.array(labels)


class TinySSD(gluon.HybridBlock):
    """One backbone + one 8x8 prediction scale (K anchors per cell)."""

    SIZES = (0.3, 0.45)
    RATIOS = (1.0, 2.0, 0.5)
    K = len(SIZES) + len(RATIOS) - 1

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.backbone = nn.HybridSequential()
            self.backbone.add(
                nn.Conv2D(16, 3, padding=1, activation="relu"),
                nn.MaxPool2D(2, 2),                       # 16x16
                nn.Conv2D(32, 3, padding=1, activation="relu"),
                nn.MaxPool2D(2, 2),                       # 8x8
                nn.Conv2D(64, 3, padding=1, activation="relu"))
            self.cls_head = nn.Conv2D(self.K * (N_CLS + 1), 3, padding=1)
            self.box_head = nn.Conv2D(self.K * 4, 3, padding=1)

    def hybrid_forward(self, F, x):
        feat = self.backbone(x)                           # (B, 64, 8, 8)
        anchors = F.MultiBoxPrior(feat, sizes=self.SIZES,
                                  ratios=self.RATIOS)
        cls = self.cls_head(feat)                         # (B, K*(C+1), 8, 8)
        box = self.box_head(feat)
        B = cls.shape[0]
        # (B, C+1, N) layout expected by MultiBoxTarget/Detection
        cls = cls.transpose((0, 2, 3, 1)).reshape(
            (B, -1, N_CLS + 1)).transpose((0, 2, 1))
        box = box.transpose((0, 2, 3, 1)).reshape((B, -1))
        return anchors, cls, box


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=150)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--lr", type=float, default=5e-3)
    args = ap.parse_args()

    mx.random.seed(0)
    rng = np.random.RandomState(0)
    net = TinySSD()
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    ce = gluon.loss.SoftmaxCrossEntropyLoss()

    for it in range(args.iters):
        imgs, labels = synth_batch(rng, args.batch_size)
        with autograd.record():
            anchors, cls_pred, box_pred = net(imgs)
            with autograd.pause():
                box_t, box_m, cls_t = nd.MultiBoxTarget(
                    anchors, labels, cls_pred,
                    negative_mining_ratio=3.0)
            cls_l = ce(cls_pred.transpose((0, 2, 1)).reshape(
                (-1, N_CLS + 1)), cls_t.reshape((-1,)))
            # ignore_label -1 rows get zero weight
            w = (cls_t.reshape((-1,)) >= 0)
            cls_l = (cls_l * w).sum() / w.sum()
            box_l = (nd.smooth_l1(box_pred - box_t) * box_m).sum() \
                / box_m.sum().clip(1.0, None)
            loss = cls_l + box_l
        loss.backward()
        trainer.step(args.batch_size)
        if it % 25 == 0 or it == args.iters - 1:
            print(f"iter {it}: loss {float(loss.asscalar()):.4f} "
                  f"(cls {float(cls_l.asscalar()):.4f} "
                  f"box {float(box_l.asscalar()):.4f})")

    # ---- evaluate: mean IoU of the top detection vs ground truth
    imgs, labels = synth_batch(rng, 64)
    anchors, cls_pred, box_pred = net(imgs)
    cls_prob = nd.softmax(cls_pred, axis=1)
    dets = nd.MultiBoxDetection(cls_prob, box_pred, anchors,
                                nms_threshold=0.45).asnumpy()
    gts = labels.asnumpy()
    ious, cls_hits = [], []
    for i in range(dets.shape[0]):
        top = dets[i, 0]                                  # best-scoring box
        gt = gts[i, 0]
        bx = top[2:]
        gx = gt[1:]
        ix = max(0.0, min(bx[2], gx[2]) - max(bx[0], gx[0]))
        iy = max(0.0, min(bx[3], gx[3]) - max(bx[1], gx[1]))
        inter = ix * iy
        union = ((bx[2] - bx[0]) * (bx[3] - bx[1]) +
                 (gx[2] - gx[0]) * (gx[3] - gx[1]) - inter)
        ious.append(inter / max(union, 1e-9))
        cls_hits.append(float(top[0] == gt[0]))
    miou = float(np.mean(ious))
    acc = float(np.mean(cls_hits))
    print(f"eval: mean IoU {miou:.3f}, class accuracy {acc:.2f}")
    assert miou > 0.4, f"detector did not localize (mIoU {miou:.3f})"
    print("done: detector localizes the synthetic objects")


if __name__ == "__main__":
    main()
