"""Faster R-CNN two-stage detection, end to end (reference:
GluonCV ``faster_rcnn`` + upstream example/rcnn; SURVEY.md §2.3).

No egress here, so the data is a synthetic detection shard packed in the
im2rec RecordIO layout (JPEG images + 5-wide labels
``[cls, x0, y0, x1, y1]`` in pixels) and read back through
``ImageRecordIter`` — the same pipeline real VOC/COCO shards use.  Each
image holds one colored rectangle; the class is the color, so the ROI
head must use appearance (not just geometry) to classify.

Training is the full two-stage path per step, all static-shape compiled:
RPN forward over FPN levels → RPN target matching + loss → static
top-k + NMS proposals → level-assigned ROIAlign → ROI-head class/box
loss, with gradients flowing through the ROIAlign into the FPN and
backbone (one joint backward).

Success criterion printed at the end: fraction of held-out images whose
top detection has IoU >= 0.5 with the ground-truth box AND the right
class (exits 1 below ``--min-recall``).

  python examples/faster_rcnn.py --iters 120
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import mxnet_tpu as mx                                    # noqa: E402
from mxnet_tpu import autograd, gluon, nd, recordio       # noqa: E402
from mxnet_tpu.gluon import nn                            # noqa: E402
from mxnet_tpu.gluon.contrib import detection as det      # noqa: E402
from mxnet_tpu.io import ImageRecordIter                  # noqa: E402

IMG = 128
# class -> rectangle fill color (RGB); ids 1..2, 0 is background
COLORS = {1: (200, 60, 40), 2: (40, 200, 60)}


def synth_rec(path, n, seed=0):
    """Pack one-rectangle-per-image JPEG detection shards."""
    import cv2
    rng = np.random.RandomState(seed)
    rec = recordio.MXIndexedRecordIO(path + ".idx", path + ".rec", "w")
    for i in range(n):
        cls = rng.randint(1, 3)
        w = rng.randint(28, 72)
        h = rng.randint(28, 72)
        x0 = rng.randint(4, IMG - w - 4)
        y0 = rng.randint(4, IMG - h - 4)
        img = rng.randint(0, 60, (IMG, IMG, 3)).astype(np.uint8)
        img[y0:y0 + h, x0:x0 + w] = np.array(
            COLORS[cls], np.uint8) + rng.randint(-20, 20, 3).astype(
                np.int16).astype(np.uint8)
        header = recordio.IRHeader(
            0, np.array([cls, x0, y0, x0 + w, y0 + h], np.float32), i, 0)
        rec.write_idx(i, recordio.pack(
            header, cv2.imencode(".jpg", img[:, :, ::-1],
                                 [1, 92])[1].tobytes()))
    rec.close()


def backbone():
    """Three-stage feature extractor: strides 8/16/32."""
    class Feats(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.s1 = nn.HybridSequential()
                for _ in range(3):
                    self.s1.add(nn.Conv2D(32, 3, strides=2, padding=1,
                                          activation="relu"))
                self.s2 = nn.Conv2D(48, 3, strides=2, padding=1,
                                    activation="relu")
                self.s3 = nn.Conv2D(64, 3, strides=2, padding=1,
                                    activation="relu")

        def hybrid_forward(self, F, x):
            c3 = self.s1(x)
            c4 = self.s2(c3)
            c5 = self.s3(c4)
            return c3, c4, c5
    return Feats(), (32, 48, 64)


def box_iou_np(a, b):
    x0 = max(a[0], b[0]); y0 = max(a[1], b[1])
    x1 = min(a[2], b[2]); y1 = min(a[3], b[3])
    inter = max(0.0, x1 - x0) * max(0.0, y1 - y0)
    ua = ((a[2] - a[0]) * (a[3] - a[1])
          + (b[2] - b[0]) * (b[3] - b[1]) - inter)
    return inter / max(ua, 1e-9)


def evaluate(net, it, n_batches):
    """Top-detection recall: IoU >= 0.5 with gt AND correct class."""
    hits = total = 0
    it.reset()
    for _ in range(n_batches):
        batch = it.next()
        x = batch.data[0]
        lab = batch.label[0].asnumpy()
        cls, boxes, rscores = net(x)
        prob = nd.softmax(cls, axis=-1).asnumpy()       # (B, R, nc+1)
        boxes = boxes.asnumpy()
        rs = rscores.asnumpy()
        for b in range(x.shape[0]):
            fg = prob[b, :, 1:]                          # (R, nc)
            fg = np.where(np.isfinite(rs[b])[:, None], fg, 0.0)
            r, c = np.unravel_index(np.argmax(fg), fg.shape)
            pred_cls = c + 1
            pred_box = boxes[b, r, c]
            gt_cls = int(lab[b, 0])
            gt_box = lab[b, 1:5]
            ok = (pred_cls == gt_cls
                  and box_iou_np(pred_box, gt_box) >= 0.5)
            hits += ok
            total += 1
    return hits / max(total, 1)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--iters", type=int, default=120)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--lr", type=float, default=5e-4)
    p.add_argument("--min-recall", type=float, default=0.5,
                   help="fail below this top-detection recall "
                        "(0 disables)")
    p.add_argument("--rec", default=None)
    args = p.parse_args()

    mx.random.seed(0)
    rec_path = args.rec
    if rec_path is None:
        rec_path = "/tmp/synth_frcnn"
        if not os.path.exists(rec_path + ".rec"):
            synth_rec(rec_path, 256)
    else:
        rec_path = rec_path[:-4] if rec_path.endswith(".rec") else rec_path

    it = ImageRecordIter(
        path_imgrec=rec_path + ".rec", data_shape=(3, IMG, IMG),
        batch_size=args.batch_size, shuffle=True, label_width=5,
        scale=1.0 / 255, preprocess_threads=2, round_batch=True)

    feats, chans = backbone()
    net = det.FasterRCNN(feats, chans, num_classes=2,
                         image_size=(IMG, IMG), channels=32,
                         rpn_pre_topk=64, rpn_post_topk=16)
    net.initialize(mx.init.Xavier())
    params = {k: p_ for k, p_ in net.collect_params().items()
              if p_.grad_req != "null"}
    trainer = gluon.Trainer(params, "adam", {"learning_rate": args.lr})

    step = 0
    while step < args.iters:
        it.reset()
        while step < args.iters:
            try:
                batch = it.next()
            except StopIteration:
                break
            x = batch.data[0]
            lab = batch.label[0].asnumpy()
            gt_b = nd.array(lab[:, None, 1:5])
            gtc_b = nd.array(lab[:, None, 0].astype(np.int32),
                             dtype="int32")
            with autograd.record():
                levels, anchors, obj, reg = net.rpn_forward(x)
                rloss = net.rpn_loss(anchors, obj, reg, gt_b)
                rois_b, _sc, keep_b = net.proposals(anchors, obj, reg)
                closs = net.rcnn_loss(levels, rois_b, gt_b, gtc_b,
                                      keep=keep_b)
                loss = rloss + closs
            loss.backward()
            trainer.step(x.shape[0])
            if step % 20 == 0 or step == args.iters - 1:
                print(f"iter {step}: loss {float(loss.asnumpy()):.4f} "
                      f"(rpn {float(rloss.asnumpy()):.4f} "
                      f"roi {float(closs.asnumpy()):.4f})")
            step += 1

    # held-out evaluation: a FRESH shard from a different seed — the
    # gate must measure generalization, not training-set memorization
    eval_path = "/tmp/synth_frcnn_eval"
    if not os.path.exists(eval_path + ".rec"):
        synth_rec(eval_path, 64, seed=1)
    eval_it = ImageRecordIter(
        path_imgrec=eval_path + ".rec", data_shape=(3, IMG, IMG),
        batch_size=args.batch_size, shuffle=False, label_width=5,
        scale=1.0 / 255, round_batch=True)
    recall = evaluate(net, eval_it,
                      n_batches=max(1, 64 // args.batch_size))
    print(f"top-detection recall (IoU>=0.5 + class, held out): "
          f"{recall:.3f}")
    if args.min_recall > 0 and recall < args.min_recall:
        print(f"FAIL: recall below {args.min_recall}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
