"""Train LeNet on MNIST with the Gluon API (reference:
example/gluon/mnist/mnist.py).

Runs anywhere; on a machine without the MNIST files the dataset serves a
synthetic fallback (gluon.data.vision.MNIST(...).synthetic is True).

  python examples/mnist_gluon.py --epochs 2
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import mxnet_tpu as mx                                    # noqa: E402
from mxnet_tpu import autograd, gluon, nd                 # noqa: E402
from mxnet_tpu.gluon import nn                            # noqa: E402


def build_net():
    net = nn.HybridSequential()
    net.add(nn.Conv2D(20, 5, activation="relu"), nn.MaxPool2D(2, 2),
            nn.Conv2D(50, 5, activation="relu"), nn.MaxPool2D(2, 2),
            nn.Dense(500, activation="relu"), nn.Dense(10))
    return net


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=1e-3)
    args = ap.parse_args()

    mx.random.seed(42)
    train = gluon.data.vision.MNIST(train=True)
    loader = gluon.data.DataLoader(train, batch_size=args.batch_size,
                                   shuffle=True)
    if train.synthetic:
        print("note: no local MNIST files; training on the synthetic set")

    net = build_net()
    net.initialize(mx.init.Xavier())
    net.hybridize(static_alloc=True)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = mx.metric.Accuracy()

    for epoch in range(args.epochs):
        metric.reset()
        tic = time.time()
        for i, (x, y) in enumerate(loader):
            x = x.astype("float32").transpose((0, 3, 1, 2)) / 255.0
            with autograd.record():
                out = net(x)
                loss = loss_fn(out, y)
            loss.backward()
            trainer.step(x.shape[0])
            metric.update(y, out)
        name, acc = metric.get()
        print(f"epoch {epoch}: {name}={acc:.4f} "
              f"({time.time() - tic:.1f}s)")

    net.save_parameters("lenet.params")
    print("saved lenet.params")


if __name__ == "__main__":
    main()
