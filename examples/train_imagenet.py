"""ResNet-50 image-classification training (BASELINE.md config 2:
GluonCV image_classification — conv/BN, the RecordIO input pipeline, AMP,
and the fused ShardedTrainer step).

No egress in this environment, so by default the script synthesizes an
ImageNet-shaped RecordIO shard (tools/im2rec.py packing format: JPEG/PNG
images + class labels) and trains on it — same code path as real
ImageNet shards built with ``python tools/im2rec.py``.  Point
``--rec`` at a real shard to train on actual data.

Pipeline: ImageRecordIter (threaded decode/augment, rand-crop+mirror)
→ model_zoo ResNet → AMP bfloat16 cast → ShardedTrainer (whole step as
one donated XLA program over the dp mesh).

  python examples/train_imagenet.py --model resnet18_v1 --iters 30
  python examples/train_imagenet.py --model resnet50_v1 --shape 224
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax                                                # noqa: E402
import jax.numpy as jnp                                   # noqa: E402

import mxnet_tpu as mx                                    # noqa: E402
from mxnet_tpu import nd, gluon, parallel, recordio       # noqa: E402
from mxnet_tpu.gluon.model_zoo import vision              # noqa: E402
from mxnet_tpu.image import imencode                      # noqa: E402
from mxnet_tpu.io import ImageRecordIter                  # noqa: E402


def synth_rec(path, n, shape, n_classes, seed=0):
    """Pack a synthetic class-colored image shard (im2rec layout)."""
    rng = np.random.RandomState(seed)
    rec = recordio.MXIndexedRecordIO(path + ".idx", path + ".rec", "w")
    for i in range(n):
        cls = rng.randint(n_classes)
        # class-dependent mean color + noise: learnable but not trivial
        base = np.zeros((shape, shape, 3), np.float32)
        base[..., cls % 3] = 80 + 40 * (cls // 3)
        img = np.clip(base + rng.randn(shape, shape, 3) * 25, 0,
                      255).astype(np.uint8)
        header = recordio.IRHeader(0, float(cls), i, 0)
        rec.write_idx(i, recordio.pack(header, imencode(img, ".png")))
    rec.close()


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet18_v1")
    p.add_argument("--rec", default=None,
                   help="existing .rec shard (default: synthesize)")
    p.add_argument("--classes", type=int, default=6)
    p.add_argument("--shape", type=int, default=32,
                   help="image side (224 for real ImageNet shapes)")
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--iters", type=int, default=60)
    p.add_argument("--lr", type=float, default=0.02)
    p.add_argument("--no-amp", action="store_true")
    args = p.parse_args()

    mx.random.seed(0)
    rec_path = args.rec
    if rec_path is None:
        rec_path = "/tmp/synth_imagenet"
        if not os.path.exists(rec_path + ".rec"):
            synth_rec(rec_path, 512, args.shape, args.classes)
        rec_path += ".rec"

    it = ImageRecordIter(
        path_imgrec=rec_path, data_shape=(3, args.shape, args.shape),
        batch_size=args.batch_size, shuffle=True, rand_mirror=True,
        scale=1.0 / 255, preprocess_threads=2)

    net = vision.get_model(args.model, classes=args.classes)
    net.initialize(mx.init.Xavier())
    on_tpu = any(d.platform != "cpu" for d in jax.devices())
    use_amp = on_tpu and not args.no_amp
    if use_amp:
        net.cast("bfloat16")            # bf16 weights; fp32 master in opt

    # dp mesh over every local device; the whole train step (fwd+bwd+
    # allreduce+sgd) is ONE donated XLA program
    n_dev = len(jax.devices())
    dp = n_dev if args.batch_size % n_dev == 0 else 1
    mesh = parallel.make_mesh(dp=dp, tp=1, sp=1,
                              devices=jax.devices()[:dp])

    def loss_fn(logits, labels):
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        return -jnp.take_along_axis(
            logp, labels[:, None], axis=1).mean()

    example = nd.zeros((args.batch_size, 3, args.shape, args.shape))
    trainer = parallel.ShardedTrainer(
        net, loss_fn, mesh, optimizer="sgd",
        optimizer_params={"learning_rate": args.lr, "momentum": 0.9},
        example_inputs=(example,), n_labels=1,
        dtype=jnp.bfloat16 if use_amp else None)

    seen, correct, t0 = 0, 0, time.time()
    i = 0
    losses = []
    while i < args.iters:
        for batch in it:
            if i >= args.iters:
                break
            x = batch.data[0]
            y = nd.array(batch.label[0].asnumpy().astype(np.int32)
                         .reshape(-1), dtype="int32")
            loss = trainer.step(x, y)
            losses.append(float(jax.device_get(loss)))
            i += 1
        it.reset()
    dt = time.time() - t0
    ips = args.iters * args.batch_size / dt
    print(f"{args.model}: {args.iters} iters, "
          f"loss {losses[0]:.3f} -> {losses[-1]:.3f}, "
          f"{ips:.1f} img/s (incl. compile)")

    # eval accuracy with the trained weights (write_back -> gluon path)
    trainer.write_back()
    it.reset()
    metric = mx.metric.Accuracy()
    for batch in it:
        out = net(batch.data[0])
        metric.update([nd.array(batch.label[0].asnumpy().reshape(-1))],
                      [out])
    name, acc = metric.get()
    print(f"train-set {name}: {acc:.3f}")
    if args.rec is None and (losses[-1] > losses[0] * 0.9 or acc < 0.5):
        print("WARNING: did not learn the synthetic classes",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
