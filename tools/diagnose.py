"""Diagnose the runtime environment (reference parity:
``tools/diagnose.py`` upstream, which prints platform/pip/hardware
info for bug reports).

Prints: platform + Python, jax/jaxlib/numpy versions, the JAX backend
and device list, every ``MXNET_*`` env knob (registry defaults plus
anything set in the environment), native-library availability, the
persistent compile-cache state (dir, entry count, bytes, hit ratio —
so a mis-set MXNET_COMPILE_CACHE_DIR is diagnosable in one command),
and a runtime-metrics snapshot.  With ``--metrics-smoke`` it also enables the
metrics registry, dispatches one op, and verifies the pipeline end to
end (used as a CI smoke step by ci/runtime_functions.sh).

Usage: python tools/diagnose.py [--metrics-smoke]
"""
import os
import platform
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _section(title):
    print(f"\n----------{title}----------")


def diagnose(metrics_smoke=False):
    _section("Platform Info")
    print(f"Platform     : {platform.platform()}")
    print(f"system       : {platform.system()}")
    print(f"node         : {platform.node()}")
    print(f"release      : {platform.release()}")
    print(f"version      : {platform.version()}")

    _section("Python Info")
    print(f"version      : {platform.python_version()}")
    print(f"compiler     : {platform.python_compiler()}")
    print(f"implementation: {platform.python_implementation()}")

    _section("Framework Info")
    import numpy as np
    import jax
    import mxnet_tpu as mx
    print(f"mxnet_tpu    : {mx.__version__}")
    print(f"numpy        : {np.__version__}")
    print(f"jax          : {jax.__version__}")
    try:
        import jaxlib
        print(f"jaxlib       : {jaxlib.__version__}")
    except Exception:                       # noqa: BLE001
        pass
    print(f"backend      : {jax.default_backend()}")
    print(f"device_count : {jax.device_count()}")
    for d in jax.devices():
        print(f"  device     : {d} ({d.device_kind})")
    from mxnet_tpu.lib import nativelib
    print(f"native io lib: {'available' if nativelib.available() else 'absent'}")

    _section("Environment")
    for name, (default, _doc) in sorted(mx.base.list_env_vars().items()):
        cur = os.environ.get(name)
        mark = f"{cur}  (set)" if cur is not None else f"{default}  (default)"
        print(f"{name}={mark}")
    extra = sorted(k for k in os.environ
                   if k.startswith(("MXNET_", "DMLC_", "JAX_", "XLA_"))
                   and k not in mx.base.list_env_vars())
    for k in extra:
        print(f"{k}={os.environ[k]}  (set, unregistered)")

    _section("Compile Cache")
    from mxnet_tpu import compile_cache
    st = compile_cache.get_default().stats()
    if not st["enabled"]:
        print("dir          : (disabled — set MXNET_COMPILE_CACHE_DIR "
              "for zero-cold-start serving; docs/serving.md §5)")
    else:
        total = st["hits"] + st["misses"]
        ratio = f"{st['hits'] / total:.2f}" if total else "n/a"
        print(f"dir          : {st['dir']}")
        print(f"entries      : {st['entries']}")
        print(f"bytes        : {st['bytes']} "
              f"(bound {st['max_bytes'] or 'unbounded'})")
        print(f"hit ratio    : {ratio}  (this process: {st['hits']} hit / "
              f"{st['misses']} miss / {st['corrupt']} corrupt / "
              f"{st['evictions']} evicted)")
        print(f"topology key : {compile_cache.topology_fingerprint()}")

    _section("Concurrency Sanitizer")
    from mxnet_tpu import engine
    print(f"active       : {engine.sanitizer_active()}  "
          f"(MXNET_ENGINE_SANITIZE=1 to enable lock-order recording + "
          f"tracked-array assertions; docs/static_analysis.md)")

    _section("Runtime Metrics")
    from mxnet_tpu import runtime_metrics as rm
    print(f"enabled      : {rm.enabled()}")
    if metrics_smoke:
        rm.enable()
        a = mx.nd.ones((8, 8))
        mx.nd.dot(a, a).wait_to_read()
        mx.waitall()
        assert rm.OP_INVOKE.value(op="dot") >= 1, "metrics pipeline broken"
        mem = rm.sample_memory()
        print(f"memory sample: {mem}")
    snap = rm.snapshot()
    if not snap:
        print("(no metrics recorded)")
    for name, m in sorted(snap.items()):
        if not m["values"]:
            continue
        print(f"{name} [{m['type']}]: {m['values']}")
    if metrics_smoke:
        print("\nmetrics smoke: OK")


def main(argv):
    diagnose(metrics_smoke="--metrics-smoke" in argv)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
