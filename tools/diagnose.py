"""Diagnose the runtime environment (reference parity:
``tools/diagnose.py`` upstream, which prints platform/pip/hardware
info for bug reports).

Prints: platform + Python, jax/jaxlib/numpy versions, the JAX backend
and device list, every ``MXNET_*`` env knob (registry defaults plus
anything set in the environment), native-library availability, the
persistent compile-cache state (dir, entry count, bytes, hit ratio —
so a mis-set MXNET_COMPILE_CACHE_DIR is diagnosable in one command),
the request-tracer / flight-recorder state, and a runtime-metrics
snapshot.  With ``--metrics-smoke`` it also enables the metrics
registry, dispatches one op, and verifies the pipeline end to end
(used as a CI smoke step by ci/runtime_functions.sh).

``--trace-smoke`` runs a traced serving round trip IN PROCESS — one
``predict()`` and one ``generate()`` through a ModelServer over
fake (numpy, zero-compile) models with ``MXNET_TRACE`` forced on —
then asserts the span chains (admission -> queue wait -> batch/execute
on the predict side; admission -> queue wait -> prefill -> decode
steps -> evict on the generate side), the p99 exemplar link, and that
the flight-recorder dump is non-empty and parses as chrome trace.
It then drives the TRAINING chain the same way: a resnet50-shaped
fake trainer (input-bound: data wait dominates) through
``perf_account.StepAttribution``, asserting the ``train.step`` span
tree resolves, the phase spans tile the root to within 10%, the
bottleneck verdict comes out ``input_bound``, and the
``trainer.step.seconds`` p99 exemplar resolves to a trace.  This is
the CI gate for docs/observability.md's tracing section
(ci/runtime_functions.sh serving_smoke).

``--flight-dump [PATH]`` writes the in-process flight-recorder
snapshot (tracer stats + completed-trace ring) as JSON to PATH
(default ./flight_record.json) — the on-demand half of the flight
recorder.

Usage: python tools/diagnose.py [--metrics-smoke] [--trace-smoke]
                                [--flight-dump [PATH]]
"""
import json
import os
import platform
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _section(title):
    print(f"\n----------{title}----------")


def diagnose(metrics_smoke=False):
    _section("Platform Info")
    print(f"Platform     : {platform.platform()}")
    print(f"system       : {platform.system()}")
    print(f"node         : {platform.node()}")
    print(f"release      : {platform.release()}")
    print(f"version      : {platform.version()}")

    _section("Python Info")
    print(f"version      : {platform.python_version()}")
    print(f"compiler     : {platform.python_compiler()}")
    print(f"implementation: {platform.python_implementation()}")

    _section("Framework Info")
    import numpy as np
    import jax
    import mxnet_tpu as mx
    print(f"mxnet_tpu    : {mx.__version__}")
    print(f"numpy        : {np.__version__}")
    print(f"jax          : {jax.__version__}")
    try:
        import jaxlib
        print(f"jaxlib       : {jaxlib.__version__}")
    except Exception:                       # noqa: BLE001
        pass
    print(f"backend      : {jax.default_backend()}")
    print(f"device_count : {jax.device_count()}")
    for d in jax.devices():
        print(f"  device     : {d} ({d.device_kind})")
    from mxnet_tpu.lib import nativelib
    print(f"native io lib: {'available' if nativelib.available() else 'absent'}")

    _section("Environment")
    for name, (default, _doc) in sorted(mx.base.list_env_vars().items()):
        cur = os.environ.get(name)
        mark = f"{cur}  (set)" if cur is not None else f"{default}  (default)"
        print(f"{name}={mark}")
    extra = sorted(k for k in os.environ
                   if k.startswith(("MXNET_", "DMLC_", "JAX_", "XLA_"))
                   and k not in mx.base.list_env_vars())
    for k in extra:
        print(f"{k}={os.environ[k]}  (set, unregistered)")

    _section("Compile Cache")
    from mxnet_tpu import compile_cache
    st = compile_cache.get_default().stats()
    if not st["enabled"]:
        print("dir          : (disabled — set MXNET_COMPILE_CACHE_DIR "
              "for zero-cold-start serving; docs/serving.md §5)")
    else:
        total = st["hits"] + st["misses"]
        ratio = f"{st['hits'] / total:.2f}" if total else "n/a"
        print(f"dir          : {st['dir']}")
        print(f"entries      : {st['entries']}")
        print(f"bytes        : {st['bytes']} "
              f"(bound {st['max_bytes'] or 'unbounded'})")
        print(f"hit ratio    : {ratio}  (this process: {st['hits']} hit / "
              f"{st['misses']} miss / {st['corrupt']} corrupt / "
              f"{st['evictions']} evicted)")
        print(f"topology key : {compile_cache.topology_fingerprint()}")

    _section("Concurrency Sanitizer")
    from mxnet_tpu import engine
    print(f"active       : {engine.sanitizer_active()}  "
          f"(MXNET_ENGINE_SANITIZE=1 to enable lock-order recording + "
          f"tracked-array assertions; docs/static_analysis.md)")

    _section("Threads")
    from mxnet_tpu import base as _base
    rows = engine.thread_registry()
    if not engine.sanitizer_active():
        print("registry     : (off — MXNET_ENGINE_SANITIZE=1 records "
              "every engine.make_thread with owner + spawn site, and "
              "check_thread_leaks() fails tests whose threads outlive "
              "their owner's stop)")
    elif not rows:
        print("registry     : 0 framework thread(s) registered")
    else:
        print(f"registry     : {len(rows)} framework thread(s)")
        for r in rows:
            flags = ["daemon" if r["daemon"] else "non-daemon"]
            if r["abandoned"]:
                flags.append(f"abandoned: {r['abandoned']}")
            print(f"  {r['name']:<28s} owner={r['owner']} "
                  f"site={r['site']} age={r['age_s']:.1f}s "
                  f"({', '.join(flags)})")
    print(f"deterministic: {len(_base.list_deterministic())} declared "
          f"surface(s) (base.declare_deterministic; ambient entropy on "
          f"them is a lint error — mxlint determinism-soundness)")

    _section("Fault Injection")
    from mxnet_tpu import faults
    sites = faults.declared_sites()
    print(f"declared     : {len(sites)} sites "
          f"(faults.declared_sites(); tables in docs/serving.md §8 + "
          f"docs/training_resilience.md §2)")
    plan = faults.active()
    if plan is None:
        print("plan         : (off — set MXNET_FAULTS to chaos-test "
              "the serving resilience layer; docs/serving.md §8)")
    else:
        print(f"plan         : {plan.spec}")
        for rule in plan.rules:
            if not faults.pattern_matches_declared(rule.pattern):
                print(f"  DEAD RULE  : {rule.spec()} matches no "
                      f"declared site — it can never fire")
            elif not faults.pattern_matches_declared(rule.pattern,
                                                     mode=rule.mode):
                print(f"  DEAD RULE  : {rule.spec()}: no site matching "
                      f"{rule.pattern!r} honors mode {rule.mode!r} — "
                      f"it can never fire")
        for key, fired in sorted(plan.counters().items()):
            print(f"  fired      : {key} x{fired}")

    _section("Training Resilience")
    from mxnet_tpu.base import get_env
    timeout_ms = get_env("MXNET_TRAIN_STEP_TIMEOUT_MS", typ=float)
    slow = get_env("MXNET_TRAIN_SLOW_STEP_FACTOR", typ=float)
    print(f"step deadline: "
          + (f"{timeout_ms:g}ms (TrainStepTimeoutError past it)"
             if timeout_ms else
             "(off — set MXNET_TRAIN_STEP_TIMEOUT_MS to bound a "
             "wedged collective; docs/training_resilience.md §3)"))
    print(f"straggler    : "
          + (f"step > {slow:g}x rolling median -> train.slow_steps + "
             f"incident dump" if slow else
             "(off — set MXNET_TRAIN_SLOW_STEP_FACTOR)"))
    print(f"supervisor   : crash-loop breaker after "
          f"{get_env('MXNET_TRAIN_MAX_RESTARTS', typ=int)} consecutive "
          f"restarts; backoff "
          f"{get_env('MXNET_TRAIN_RESTART_BACKOFF_MS', typ=float):g}ms "
          f"doubling, cap "
          f"{get_env('MXNET_TRAIN_RESTART_BACKOFF_MAX_MS', typ=float):g}"
          f"ms (jitter U[0.5, 1.0))")
    from mxnet_tpu import runtime_metrics as _trm
    if _trm.enabled():
        print(f"restarts     : {_trm.TRAIN_RESTARTS.value():g} "
              f"(+ {_trm.TRAIN_STEP_TIMEOUTS.value():g} step "
              f"timeout(s), {_trm.TRAIN_SLOW_STEPS.value():g} slow "
              f"step(s) this process)")

    _section("Training Performance")
    from mxnet_tpu import perf_account as _perf
    print(f"peak tflops  : {_perf.detect_peak_tflops():g}  "
          f"(MXNET_PEAK_TFLOPS or the device-kind table; the "
          f"train.mfu denominator)")
    verdict = _perf.current_verdict()
    if verdict is None:
        print("attribution  : (no attributed steps this process — with "
              "MXNET_TRACE/MXNET_RUNTIME_METRICS on, ShardedTrainer "
              "steps publish train.step.breakdown.seconds + the "
              "train.bottleneck verdict; docs/perf_playbook.md)")
    else:
        print(f"verdict      : {verdict}  (train.bottleneck, rolling "
              f"window)")
        print(f"mfu          : {_perf.current_mfu():.4f}  (train.mfu)")

    _section("Replica Serving")
    n_rep = get_env("MXNET_SERVING_REPLICAS", typ=int)
    print(f"replicas     : {n_rep}  (MXNET_SERVING_REPLICAS; > 1 "
          f"serves every model through a health-checked ReplicaSet; "
          f"docs/serving.md §10)")
    print(f"heartbeat    : every "
          f"{get_env('MXNET_SERVING_REPLICA_HEARTBEAT_MS', typ=float)}"
          f"ms, stale past "
          f"{get_env('MXNET_SERVING_REPLICA_HEARTBEAT_WINDOW_MS', typ=float)}"
          f"ms -> UNHEALTHY")
    print(f"failure trip : "
          f"{get_env('MXNET_SERVING_REPLICA_FAILURE_THRESHOLD', typ=int)}"
          f" consecutive typed failures -> UNHEALTHY (probe after "
          f"cooldown)")
    try:
        import jax
        n_dev = len(jax.devices())
        from mxnet_tpu.parallel.placement import replica_groups
        groups = replica_groups(max(1, n_rep), oversubscribe=None)
        print(f"placement    : {n_dev} device(s) -> "
              f"{len(groups)} group(s)"
              + ("  (oversubscribed: logical replicas)"
                 if n_dev < max(1, n_rep) else ""))
    except Exception as e:      # noqa: BLE001 — diagnostics best-effort
        print(f"placement    : unavailable ({e})")

    _section("Traffic / Autoscaling / Admission")
    tiers = get_env("MXNET_SERVING_TENANT_TIERS", typ=str)
    if tiers:
        from mxnet_tpu.serving.admission import parse_tier_spec
        try:
            parsed = parse_tier_spec(tiers)
            print(f"tiers        : {len(parsed)} "
                  f"({', '.join(parsed)})  "
                  f"(MXNET_SERVING_TENANT_TIERS; docs/serving.md §11)")
            print(f"shed start   : pressure >= "
                  f"{get_env('MXNET_SERVING_ADMISSION_SHED_START', typ=float):g}"
                  f" sheds the lowest tier first (gold-class tiers "
                  f"hold to 1.0)")
        except Exception as e:  # noqa: BLE001 — diagnostics best-effort
            print(f"tiers        : INVALID spec ({e})")
    else:
        print("tiers        : (off — set MXNET_SERVING_TENANT_TIERS "
              "for per-tenant quota buckets + priority shedding; "
              "docs/serving.md §11)")
    slo_ttft = get_env("MXNET_SERVING_AUTOSCALE_SLO_TTFT_P99_MS",
                       typ=float)
    slo_lat = get_env("MXNET_SERVING_AUTOSCALE_SLO_LATENCY_P99_MS",
                      typ=float)
    q_high = get_env("MXNET_SERVING_AUTOSCALE_QUEUE_HIGH", typ=int)
    targets = [s for s in (
        f"ttft p99 {slo_ttft:g}ms" if slo_ttft else None,
        f"latency p99 {slo_lat:g}ms" if slo_lat else None,
        f"queue >= {q_high}" if q_high else None) if s]
    print(f"autoscaler   : "
          f"{get_env('MXNET_SERVING_AUTOSCALE_MIN', typ=int)}"
          f"-{get_env('MXNET_SERVING_AUTOSCALE_MAX', typ=int)} "
          f"replicas, tick "
          f"{get_env('MXNET_SERVING_AUTOSCALE_INTERVAL_MS', typ=float):g}"
          f"ms, up after "
          f"{get_env('MXNET_SERVING_AUTOSCALE_BREACH_TICKS', typ=int)} "
          f"breach tick(s), down after "
          f"{get_env('MXNET_SERVING_AUTOSCALE_IDLE_TICKS', typ=int)} "
          f"idle tick(s)")
    print(f"slo targets  : "
          + (", ".join(targets) if targets else
             "(none — pass SLOTargets(...) or set "
             "MXNET_SERVING_AUTOSCALE_SLO_*)"))
    if _trm.enabled():
        dec = _trm.SERVING_AUTOSCALE_DECISIONS
        models = dec.label_values("model")
        acts = {a: int(sum(dec.value(model=m, action=a)
                           for m in models))
                for a in ("up", "down", "blocked", "error")}
        if any(acts.values()):
            print(f"decisions    : " + ", ".join(
                f"{v} {k}" for k, v in acts.items() if v)
                + "  (serving.autoscale.decisions this process)")
        sheds = _trm.SERVING_TENANT_SHED.total()
        if sheds:
            print(f"tenant sheds : {sheds:g}  (serving.tenant.shed "
                  f"this process)")

    _section("Tracing / Flight Recorder")
    from mxnet_tpu import tracing
    st = tracing.TRACER.stats()
    if not st["enabled"]:
        print("enabled      : False  (MXNET_TRACE=1 for per-request "
              "span timelines + the flight recorder; "
              "docs/observability.md)")
    else:
        print(f"enabled      : True  (sample={st['sample']}, "
              f"ring={st['ring']})")
        print(f"traces       : {st['completed']} completed in ring / "
              f"{st['active']} active / {st['traces_started']} started "
              f"/ {st['traces_unsampled']} sampled out / "
              f"{st['traces_evicted']} evicted")
        print(f"spans        : {st['spans']} recorded / "
              f"{st['spans_dropped']} dropped")
    incidents = tracing.incident_paths()
    print(f"incidents    : {len(incidents)}"
          + ("".join(f"\n  dump       : {p}" for p in incidents)))

    _section("Runtime Metrics")
    from mxnet_tpu import runtime_metrics as rm
    print(f"enabled      : {rm.enabled()}")
    if metrics_smoke:
        rm.enable()
        a = mx.nd.ones((8, 8))
        mx.nd.dot(a, a).wait_to_read()
        mx.waitall()
        assert rm.OP_INVOKE.value(op="dot") >= 1, "metrics pipeline broken"
        mem = rm.sample_memory()
        print(f"memory sample: {mem}")
    snap = rm.snapshot()
    if not snap:
        print("(no metrics recorded)")
    for name, m in sorted(snap.items()):
        if not m["values"]:
            continue
        print(f"{name} [{m['type']}]: {m['values']}")
    if metrics_smoke:
        print("\nmetrics smoke: OK")


class _FakeLM:
    """Zero-compile decode model (numpy only) so the trace smoke runs
    in CI time: deterministic logits, no jax programs."""

    vocab_size = 8
    max_context = 16

    def prefill(self, tokens, length, block_table):
        import numpy as np
        return np.eye(self.vocab_size,
                      dtype=np.float32)[int(length) % self.vocab_size]

    def decode_step(self, tokens, positions, block_tables):
        import numpy as np
        out = np.zeros((tokens.shape[0], self.vocab_size), np.float32)
        out[np.arange(tokens.shape[0]),
            (tokens + 1) % self.vocab_size] = 1.0
        return out


def trace_smoke():
    """Traced predict + generate round trip; asserts the span chains,
    the exemplar link, and a non-empty, parsable flight-recorder dump.
    The serving_smoke CI job runs this."""
    import tempfile

    import numpy as np

    from mxnet_tpu import runtime_metrics as rm, serving, tracing
    tracing.enable(sample=1.0)
    tracing.reset()
    rm.enable()

    repo = serving.ModelRepository()
    repo.add_function("echo", lambda x: x * 2.0,
                      [{"shape": [None, 3], "dtype": "float32"}])
    repo.add_decoder("lm", _FakeLM())
    cfg = serving.ServingConfig(decode_page_size=4, decode_pool_pages=16,
                                decode_max_batch=2,
                                decode_max_new_tokens=4)
    srv = serving.ModelServer(repo, cfg)
    try:
        out = srv.predict("echo", np.ones((2, 3), np.float32),
                          timeout=120)
        np.testing.assert_allclose(out, 2.0)
        toks = srv.generate("lm", [1, 2, 3], max_new_tokens=3,
                            timeout=120)
        assert len(toks) == 3, toks
        state = srv.debug_state()
    finally:
        srv.stop()

    # span chains: one trace each, every span parent-linked inside it
    pt = tracing.TRACER.last(root="serving.predict")
    gt = tracing.TRACER.last(root="serving.generate")
    assert pt is not None and gt is not None, tracing.TRACER.stats()
    for tr, need in (
            (pt, {"serving.predict", "serving.admit",
                  "serving.queue_wait", "serving.batch",
                  "serving.execute"}),
            (gt, {"serving.generate", "decode.admission",
                  "decode.queue_wait", "decode.prefill", "decode.step",
                  "decode.evict"})):
        names = {s["name"] for s in tr["spans"]}
        assert need <= names, (sorted(need - names), sorted(names))
        ids = {s["span_id"] for s in tr["spans"]}
        for s in tr["spans"]:
            assert s["trace_id"] == tr["trace_id"], s
            assert s["parent_id"] is None or s["parent_id"] in ids, s

    # exemplar link: the p99 resolves to the predict trace
    ex = rm.SERVING_REQUEST_SECONDS.exemplar_for_quantile(
        0.99, model="echo")
    assert ex == pt["trace_id"], (ex, pt["trace_id"])

    # training chain: the resnet50-shaped input-bound case (data wait
    # >> compute) through the same StepAttribution the ShardedTrainer
    # uses — fake phases, zero compiles
    import time as _time

    from mxnet_tpu import perf_account as perf
    att = perf.StepAttribution(peak_tflops=1.0)
    att.note_flops(1e9)
    for _ in range(4):
        t0 = _time.perf_counter()
        _time.sleep(0.012)              # starved input pipeline
        perf.note_data_wait(t0, _time.perf_counter())
        h = att.step_start()
        with h:
            with h.phase("h2d"):
                _time.sleep(0.002)
            with h.phase("compute"):
                _time.sleep(0.006)
            h.mark("collective", fused=True)
            h.mark("optimizer", fused=True)
    tt = tracing.TRACER.last(root="train.step")
    assert tt is not None, tracing.TRACER.stats()
    need = {"train.step", "train.data.wait", "train.h2d",
            "train.compute", "train.collective", "train.optimizer"}
    names = {s["name"] for s in tt["spans"]}
    assert need <= names, (sorted(need - names), sorted(names))
    ids = {s["span_id"] for s in tt["spans"]}
    for s in tt["spans"]:
        assert s["trace_id"] == tt["trace_id"], s
        assert s["parent_id"] is None or s["parent_id"] in ids, s
    root = next(s for s in tt["spans"] if s["name"] == "train.step")
    span_sum = sum(s["t1"] - s["t0"] for s in tt["spans"]
                   if s["name"] != "train.step")
    dur = root["t1"] - root["t0"]
    assert abs(span_sum - dur) <= 0.10 * dur, (span_sum, dur)
    assert att.verdict() == "input_bound", att.summary()
    assert rm.TRAIN_BOTTLENECK.value() == 1.0, rm.TRAIN_BOTTLENECK
    tex = rm.TRAINER_STEP_SECONDS.exemplar_for_quantile(0.99)
    assert tracing.TRACER.find(tex) is not None, tex

    # flight-recorder dump: non-empty and parsable (the CI criterion)
    with tempfile.TemporaryDirectory() as tmp:
        fpath = os.path.join(tmp, "flight.json")
        with open(fpath, "w") as f:
            json.dump(tracing.flight_record(state=state), f,
                      default=str)
        with open(fpath) as f:
            rec = json.load(f)
        assert rec["traces"], "flight-recorder dump is empty"
        assert rec["state"]["repository"]["lm"]["current"] == 1
        cpath = tracing.dump_chrome_trace(
            os.path.join(tmp, "trace.json"), [pt, gt, tt])
        with open(cpath) as f:
            events = json.load(f)["traceEvents"]
        assert len(events) > 8, "chrome-trace dump is empty"

    print(f"trace smoke: OK ({len(pt['spans'])} predict span(s), "
          f"{len(gt['spans'])} generate span(s), {len(tt['spans'])} "
          f"train span(s), verdict={att.verdict()}, flight recorder "
          f"parsed)")


def main(argv):
    if "--trace-smoke" in argv:
        trace_smoke()
        return 0
    if "--flight-dump" in argv:
        from mxnet_tpu import tracing
        i = argv.index("--flight-dump")
        path = argv[i + 1] if i + 1 < len(argv) \
            and not argv[i + 1].startswith("-") else "flight_record.json"
        with open(path, "w") as f:
            json.dump(tracing.flight_record(), f, default=str)
        print(f"flight record written to {path}")
        return 0
    diagnose(metrics_smoke="--metrics-smoke" in argv)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
