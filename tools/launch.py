#!/usr/bin/env python
"""Job launcher: spawn N framework processes with the dist env protocol.

Reference: ``tools/launch.py`` + ``dmlc_tracker/local.py`` (SURVEY.md §2.3
Tools row, §2.4 P3) — the local-mode tracker that starts workers with
DMLC_* env vars and supervises them.

TPU-native redesign: there is no server role to schedule — every process
is a worker; process 0 doubles as the JAX coordination-service host.  The
launcher's remaining jobs are (a) the env handshake, (b) output fan-in,
and (c) **failure detection with clean abort** (SURVEY.md §5.3): the first
worker to die takes the whole job down (SIGTERM, then SIGKILL) instead of
leaving the others hung in a collective.

Usage::

    python tools/launch.py -n 4 [--coordinator 127.0.0.1:9876] \
        python train.py --epochs 10

Workers read the handshake via ``mxnet_tpu.parallel.dist.initialize()``
(no arguments).
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import threading
import time


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _pump(proc, rank, stream_name):
    stream = getattr(proc, stream_name)
    prefix = f"[worker-{rank}] ".encode()
    out = getattr(sys, stream_name).buffer
    for line in iter(stream.readline, b""):
        out.write(prefix + line)
        out.flush()


def launch(n: int, cmd, coordinator: str = None, env_extra=None,
           timeout: float = None) -> int:
    """Spawn n workers; returns the job's exit code (0 iff all succeed)."""
    coordinator = coordinator or f"127.0.0.1:{_free_port()}"
    procs = []
    pumps = []
    for rank in range(n):
        env = dict(os.environ)
        env.update(env_extra or {})
        env.update({
            "MXNET_TPU_COORDINATOR": coordinator,
            "MXNET_TPU_NUM_PROCS": str(n),
            "MXNET_TPU_PROC_ID": str(rank),
            # reference-compatible names for ported scripts
            "DMLC_NUM_WORKER": str(n),
            "DMLC_WORKER_ID": str(rank),
        })
        p = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT)
        procs.append(p)
        t = threading.Thread(target=_pump, args=(p, rank, "stdout"),
                             daemon=True)
        t.start()
        pumps.append(t)

    # failure detection: first non-zero exit (or timeout) aborts the job
    deadline = time.monotonic() + timeout if timeout else None
    failed_rank = None
    rc = 0
    try:
        while True:
            alive = False
            for rank, p in enumerate(procs):
                code = p.poll()
                if code is None:
                    alive = True
                elif code != 0 and failed_rank is None:
                    failed_rank = rank
                    rc = code
            if failed_rank is not None or not alive:
                break
            if deadline and time.monotonic() > deadline:
                failed_rank = -1
                rc = 124
                break
            time.sleep(0.1)
    finally:
        if failed_rank is not None:
            sys.stderr.write(
                f"launch: {'timeout' if failed_rank == -1 else f'worker-{failed_rank} exited rc={rc}'}"
                f" — aborting remaining workers\n")
            for p in procs:
                if p.poll() is None:
                    p.send_signal(signal.SIGTERM)
            t_end = time.monotonic() + 10
            for p in procs:
                try:
                    p.wait(timeout=max(0.1, t_end - time.monotonic()))
                except subprocess.TimeoutExpired:
                    p.kill()
        for t in pumps:
            t.join(timeout=2)
    return rc


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Launch an N-process mxnet_tpu job (local mode)")
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("--coordinator", default=None,
                    help="host:port of process 0's coordination service "
                         "(default: a free local port)")
    ap.add_argument("--timeout", type=float, default=None,
                    help="kill the job after this many seconds")
    ap.add_argument("--env", action="append", default=[],
                    help="extra KEY=VALUE for workers (repeatable)")
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)
    if not args.command:
        ap.error("no worker command given")
    extra = dict(kv.split("=", 1) for kv in args.env)
    return launch(args.num_workers, args.command,
                  coordinator=args.coordinator, env_extra=extra,
                  timeout=args.timeout)


if __name__ == "__main__":
    sys.exit(main())
